// CPU baseline for the game-of-life benchmark: the reference's hello-world
// workload (examples/game_of_life.cpp — 2-D board, length-1 vertex
// neighborhood, live-neighbor count then 2/3 rule) with the reference's
// compute pattern: AoS cells holding {is_alive, live_neighbor_count}
// (examples/simple_game_of_life.cpp:36-44) and neighbor access through an
// index indirection list (the neighbors_of iteration), multi-threaded over
// all host cores.
//
// The actual reference (dccrg + MPI + Zoltan) cannot be built in this image
// (no MPI/boost/Zoltan); this program re-creates its compute pattern as the
// honest MPI-CPU denominator for BASELINE.md's protocol, exactly like
// tools/cpu_baseline.cpp does for advection.
//
// Usage: cpu_gol_baseline NX NY TURNS  -> prints cell-updates/sec
#include <cstdio>
#include <cstdlib>
#include <cstdint>
#include <chrono>
#include <vector>
#ifdef _OPENMP
#include <omp.h>
#endif

struct Cell {
    uint64_t data[2]; // is_alive, live_neighbor_count
};

int main(int argc, char** argv) {
    const int64_t nx = argc > 1 ? atoll(argv[1]) : 500;
    const int64_t ny = argc > 2 ? atoll(argv[2]) : 500;
    const int64_t turns = argc > 3 ? atoll(argv[3]) : 100;
    const int64_t n = nx * ny;

    std::vector<Cell> cells(n);
    // 8-neighbor indirection (open boundaries: -1 = missing neighbor,
    // the reference's error_cell skip)
    std::vector<int64_t> nbr(n * 8);
    uint64_t seed = 0x9e3779b97f4a7c15ull;
    for (int64_t y = 0; y < ny; y++)
    for (int64_t x = 0; x < nx; x++) {
        const int64_t i = x + nx * y;
        // xorshift: ~30% initial fill, deterministic
        seed ^= seed << 13; seed ^= seed >> 7; seed ^= seed << 17;
        cells[i].data[0] = (seed % 100) < 30 ? 1 : 0;
        cells[i].data[1] = 0;
        int k = 0;
        for (int dy = -1; dy <= 1; dy++)
        for (int dx = -1; dx <= 1; dx++) {
            if (!dx && !dy) continue;
            const int64_t xx = x + dx, yy = y + dy;
            nbr[i * 8 + k++] =
                (xx < 0 || xx >= nx || yy < 0 || yy >= ny)
                    ? -1 : xx + nx * yy;
        }
    }

    const auto t0 = std::chrono::high_resolution_clock::now();
    for (int64_t t = 0; t < turns; t++) {
#pragma omp parallel for schedule(static)
        for (int64_t i = 0; i < n; i++) {
            uint64_t cnt = 0;
            for (int k = 0; k < 8; k++) {
                const int64_t j = nbr[i * 8 + k];
                if (j >= 0) cnt += cells[j].data[0];
            }
            cells[i].data[1] = cnt;
        }
#pragma omp parallel for schedule(static)
        for (int64_t i = 0; i < n; i++) {
            const uint64_t cnt = cells[i].data[1];
            if (cnt == 3) cells[i].data[0] = 1;
            else if (cnt != 2) cells[i].data[0] = 0;
        }
    }
    const auto t1 = std::chrono::high_resolution_clock::now();
    const double secs = std::chrono::duration<double>(t1 - t0).count();
    volatile uint64_t sink = cells[n / 2].data[0];
    (void)sink;
    printf("%.6e\n", double(n) * turns / secs);
    return 0;
}
