#!/usr/bin/env python
"""Differential soak driver: randomized cross-checks of every fast path
and subsystem against its oracle (the general gather path, the invariant
checker, or lockstep round trips).  This is the reference's DEBUG-build
discipline applied as fuzzing — run it after substantial changes:

    python tools/soak.py all --seeds 0 25
    python tools/soak.py paths --seeds 0 100
    python tools/soak.py crash --seeds 0 5

Subsystems: paths (boxed/flat advection vs general), three_level,
amr (commit pipeline + verify + mass), checkpoint (round trips across
device counts), particles, gol (all four variants), hoods (user
neighborhoods), vlasov (conservation + fused-kernel bit-identity),
poisson (flat/gather solve differential under the restart driver +
fused whole-solve kernel), crash (SIGKILL/resume convergence through
the checkpoint lineage: the child runs GoL + advection with periodic
lineage commits while being killed — by injected SIGKILLs at commit
boundaries AND by the parent at random wall-clock times — and every
resume, possibly at a different device count, must converge to the
uninterrupted run's final state: GoL exactly, advection within the
cross-layout tolerance).  Per-seed crash/resume outcomes stream into
the telemetry JSONL (``obs/stream.py``), so a hung crash-soak leaves
evidence of which generation each attempt was resuming from.

The ``elastic`` subsystem (ISSUE 8) is the supervised-rescale proof:
a child runs GoL + advection under AMR churn while performing seeded
in-process grow/shrink rescales (``resilience/elastic.py``), streaming
a heartbeat the parent's ``Supervisor`` tails; injected ``step.hang``
faults wedge the step loop (the watchdog must detect the stall and
escalate to a degraded rescale-down) and injected ``device.lost``
faults kill the worker (the supervisor relaunches it at fewer devices
from ``latest_valid()``).  The completed run must converge to a
fixed-mesh reference bit-identically (GoL exact, advection 1e-11),
and a fork-a-fresh-process warm-start proof must then resume from the
lineage with ``epoch.recompiles == 0`` on the held ShapeSignature
(the persistent compilation cache, ``DCCRG_COMPILE_CACHE_DIR``).

Black box (ISSUE 10): crash and elastic children arm the flight
recorder (``obs/flightrec.py``) at their workdir — the ring checkpoints
to ``flightrec_<pid>.json`` every 0.5 s and each step marks its unit in
flight first — and the drivers assert that every killed attempt left a
schema-valid postmortem naming the step it was serving when it died
(:func:`check_flightrec_dump`).
"""
import argparse
import pathlib
import subprocess
import sys
import textwrap

ROOT = pathlib.Path(__file__).resolve().parents[1]

BODIES = {}

BODIES["paths"] = r"""'''Differential fuzz: boxed and flat AMR paths vs the general gather
path on random refined grids (random periodicity, device counts,
velocities, refinement patterns).  Any mismatch is a bug.'''
import jax
jax.config.update('jax_platforms', 'cpu')
jax.config.update('jax_num_cpu_devices', 8)
import numpy as np, sys
import jax.numpy as jnp
sys.path.insert(0, '/root/repo')
from dccrg_tpu import CartesianGeometry, Grid, make_mesh
from dccrg_tpu.models import Advection

def one_case(seed):
    rng = np.random.default_rng(seed)
    n = int(rng.choice([4, 6, 8]))
    n_dev = int(rng.choice([1, 2, 4]))
    periodic = tuple(bool(b) for b in rng.integers(0, 2, 3))
    g = (Grid().set_initial_length((n, n, n)).set_neighborhood_length(0)
         .set_periodic(*periodic).set_maximum_refinement_level(1)
         .set_geometry(CartesianGeometry, start=(0.,0.,0.),
                       level_0_cell_length=(1./n,)*3)
         .initialize(mesh=make_mesh(n_devices=n_dev)))
    ids = g.get_cells()
    k = max(1, int(0.3 * len(ids)))
    for cid in rng.choice(ids, size=k, replace=False):
        g.refine_completely(int(cid))
    g.stop_refining()
    ids = g.get_cells()
    lvls = g.mapping.get_refinement_level(ids)
    if lvls.max() == 0:
        return "uniform"
    adv = Advection(g, dtype=np.float32, use_pallas=False)   # boxed or general
    flat = Advection(g, dtype=np.float32,
                     use_pallas="interpret" if n_dev == 1 else True)
    s0 = adv.initialize_state()
    s0 = adv.set_cell_data(s0, 'density', ids,
                           rng.uniform(1, 2, len(ids)).astype(np.float32))
    for f in ('vx', 'vy', 'vz'):
        s0 = adv.set_cell_data(s0, f, ids,
                               rng.uniform(-0.3, 0.3, len(ids)).astype(np.float32))
    s0 = g.update_copies_of_remote_neighbors(s0)
    dt = np.float32(0.3 * adv.max_time_step(s0))
    st = s0
    for _ in range(3):
        st = adv.step(st, dt)
    ref = np.asarray(adv.get_cell_data(st, 'density', ids), np.float64)
    scale = np.abs(ref).max()
    tags = []
    if getattr(adv, '_boxed_run', None) is not None:
        b = adv._boxed_run(s0, jnp.asarray(3, jnp.int32), dt)
        rb = np.asarray(adv.get_cell_data(b, 'density', ids), np.float64)
        err = np.abs(rb - ref).max() / scale
        assert err < 5e-6, (seed, 'BOXED', n, n_dev, periodic, err)
        tags.append('boxed')
    if getattr(flat, '_flat_run', None) is not None:
        a = flat.run(s0, 3, dt)
        ra = np.asarray(flat.get_cell_data(a, 'density', ids), np.float64)
        err = np.abs(ra - ref).max() / scale
        assert err < 5e-6, (seed, 'FLAT', n, n_dev, periodic, err)
        tags.append('flat')
    return '+'.join(tags) or 'general-only'

import collections
stats = collections.Counter()
lo, hi = int(sys.argv[1]), int(sys.argv[2])
for seed in range(lo, hi):
    try:
        stats[one_case(seed)] += 1
    except AssertionError as e:
        print("MISMATCH:", e)
        raise
print("OK", dict(stats))
"""

BODIES["three_level"] = r"""import jax
jax.config.update('jax_platforms', 'cpu')
jax.config.update('jax_num_cpu_devices', 8)
import numpy as np, sys
import jax.numpy as jnp
sys.path.insert(0, '/root/repo')
from dccrg_tpu import CartesianGeometry, Grid, make_mesh
from dccrg_tpu.models import Advection

def one(seed):
    rng = np.random.default_rng(seed)
    n = int(rng.choice([4, 6]))
    n_dev = int(rng.choice([1, 2, 4]))
    periodic = tuple(bool(b) for b in rng.integers(0, 2, 3))
    g = (Grid().set_initial_length((n, n, n)).set_neighborhood_length(0)
         .set_periodic(*periodic).set_maximum_refinement_level(2)
         .set_geometry(CartesianGeometry, start=(0.,0.,0.),
                       level_0_cell_length=(1./n,)*3)
         .initialize(mesh=make_mesh(n_devices=n_dev)))
    for frac in (0.3, 0.2):
        ids = g.get_cells()
        for cid in rng.choice(ids, size=max(1, int(frac*len(ids))), replace=False):
            g.refine_completely(int(cid))
        g.stop_refining()
    ids = g.get_cells()
    lv = g.mapping.get_refinement_level(ids)
    if lv.max() < 2:
        return 'shallow'
    adv = Advection(g, dtype=np.float32, use_pallas=False)
    if getattr(adv, '_boxed_run', None) is None:
        return 'no-boxed'
    s0 = adv.initialize_state()
    s0 = adv.set_cell_data(s0, 'density', ids, rng.uniform(1, 2, len(ids)).astype(np.float32))
    for f in ('vx','vy','vz'):
        s0 = adv.set_cell_data(s0, f, ids, rng.uniform(-0.3, 0.3, len(ids)).astype(np.float32))
    s0 = g.update_copies_of_remote_neighbors(s0)
    dt = np.float32(0.3 * adv.max_time_step(s0))
    st = s0
    for _ in range(3): st = adv.step(st, dt)
    ref = np.asarray(adv.get_cell_data(st, 'density', ids), np.float64)
    b = adv._boxed_run(s0, jnp.asarray(3, jnp.int32), dt)
    rb = np.asarray(adv.get_cell_data(b, 'density', ids), np.float64)
    err = np.abs(rb - ref).max() / np.abs(ref).max()
    assert err < 5e-6, (seed, n, n_dev, periodic, err)
    # multi-level flat path (when the layout qualifies): same state,
    # same oracle
    adv_ml = Advection(g, dtype=np.float32)
    if getattr(adv_ml, '_flat_kind', None) == 'ml':
        m = adv_ml._flat_run(s0, jnp.asarray(3, jnp.int32), dt)
        rm = np.asarray(adv_ml.get_cell_data(m, 'density', ids), np.float64)
        errm = np.abs(rm - ref).max() / np.abs(ref).max()
        assert errm < 5e-6, (seed, 'ml', n, n_dev, periodic, errm)
        return '3lvl-ml-ok'
    return '3lvl-ok'

import collections
stats = collections.Counter()
for seed in range(int(sys.argv[1]), int(sys.argv[2])):
    stats[one(seed)] += 1
print("OK", dict(stats))
"""

BODIES["amr"] = r"""import jax
jax.config.update('jax_platforms', 'cpu')
jax.config.update('jax_num_cpu_devices', 8)
jax.config.update('jax_enable_x64', True)
import numpy as np, sys
sys.path.insert(0, '/root/repo'); sys.path.insert(0, '/root/repo/tests')
from test_stress import make_grid, total_mass, SPEC
from dccrg_tpu.utils.verify import verify_grid, verify_user_data

def one(seed):
    rng = np.random.default_rng(seed)
    method = str(rng.choice(["RCB", "HILBERT", "GRAPH", "MORTON"]))
    g = make_grid(n=int(rng.choice([4, 6, 8])), max_lvl=2,
                  n_dev=int(rng.choice([2, 4, 8])), method=method)
    state = g.new_state(SPEC, fill=0.0)
    ids = g.get_cells()
    state = g.set_cell_data(state, "density", ids, rng.uniform(1, 2, len(ids)))
    m = total_mass(g, state)
    for ri in range(5):
        ids = g.get_cells()
        for cid in rng.choice(ids, size=min(15, len(ids)), replace=False):
            op = rng.integers(4)
            if op == 0: g.refine_completely(int(cid))
            elif op == 1: g.unrefine_completely(int(cid))
            elif op == 2: g.dont_refine(int(cid))
            else: g.dont_unrefine(int(cid))
        g.stop_refining()
        state = g.remap_state(state)
        verify_grid(g)
        verify_user_data(g, state, SPEC)
        mm = total_mass(g, state)
        assert abs(mm - m) / abs(m) < 1e-12, (seed, ri, mm, m)
        if ri % 2 == 1:
            g.balance_load()
            state = g.remap_state(state)
            verify_grid(g)
            mm = total_mass(g, state)
            assert abs(mm - m) / abs(m) < 1e-12, (seed, ri, 'lb', mm, m)
    return method

for seed in range(int(sys.argv[1]), int(sys.argv[2])):
    print(seed, one(seed), flush=True)
print("AMR_FUZZ_OK")
"""

BODIES["checkpoint"] = r"""'''Fuzz checkpoint round-trips: random refined grid + data, save,
reload at a different device count, verify structure + payloads, then
advect both in lockstep.'''
import jax
jax.config.update('jax_platforms', 'cpu')
jax.config.update('jax_num_cpu_devices', 8)
jax.config.update('jax_enable_x64', True)
import numpy as np, sys, tempfile, os
sys.path.insert(0, '/root/repo')
from dccrg_tpu import CartesianGeometry, Grid, make_mesh
from dccrg_tpu.models import Advection

def one(seed):
    rng = np.random.default_rng(seed)
    n = int(rng.choice([4, 6]))
    nd_a = int(rng.choice([1, 2, 4]))
    nd_b = int(rng.choice([1, 3, 8]))
    periodic = tuple(bool(b) for b in rng.integers(0, 2, 3))
    max_lvl = int(rng.choice([1, 2]))
    g = (Grid().set_initial_length((n, n, n)).set_neighborhood_length(0)
         .set_periodic(*periodic).set_maximum_refinement_level(max_lvl)
         .set_geometry(CartesianGeometry, start=(0.,0.,0.),
                       level_0_cell_length=(1./n,)*3)
         .initialize(mesh=make_mesh(n_devices=nd_a)))
    for _ in range(max_lvl):
        ids = g.get_cells()
        for cid in rng.choice(ids, size=max(1, len(ids)//5), replace=False):
            g.refine_completely(int(cid))
        g.stop_refining()
    ids = g.get_cells()
    adv = Advection(g)
    s = adv.initialize_state()
    s = adv.set_cell_data(s, 'density', ids, rng.uniform(1, 2, len(ids)))
    for f in ('vx','vy','vz'):
        s = adv.set_cell_data(s, f, ids, rng.uniform(-0.2, 0.2, len(ids)))
    s = g.update_copies_of_remote_neighbors(s)
    spec = {k: adv.spec[k] for k in ('density','vx','vy','vz')}
    with tempfile.TemporaryDirectory() as tmp:
        path = os.path.join(tmp, 'f.dc')
        g.save_grid_data(s, path, spec)
        g2, s2, _ = Grid.load_grid_data(path, spec, n_devices=nd_b)
    assert np.array_equal(g2.get_cells(), ids), (seed, 'structure')
    for f in spec:
        np.testing.assert_array_equal(
            g2.get_cell_data(s2, f, ids), g.get_cell_data(s, f, ids),
            err_msg=f'{seed} field {f}')
    # lockstep advection
    adv2 = Advection(g2)
    full2 = adv2.initialize_state()
    for f in spec:
        full2 = adv2.set_cell_data(full2, f, ids, g2.get_cell_data(s2, f, ids))
    full2 = g2.update_copies_of_remote_neighbors(full2)
    dt = 0.3 * adv.max_time_step(s)
    a, b = s, full2
    for _ in range(2):
        a = adv.step(a, dt)
        b = adv2.step(b, dt)
    np.testing.assert_allclose(
        np.asarray(adv.get_cell_data(a, 'density', ids)),
        np.asarray(adv2.get_cell_data(b, 'density', ids)),
        rtol=1e-13, atol=0, err_msg=str(seed))
    return (nd_a, nd_b, max_lvl)

for seed in range(int(sys.argv[1]), int(sys.argv[2])):
    info = one(seed)
    print(seed, info, flush=True)
print("CKPT_FUZZ_OK")
"""

BODIES["particles"] = r"""import jax
jax.config.update('jax_platforms', 'cpu')
jax.config.update('jax_num_cpu_devices', 8)
jax.config.update('jax_enable_x64', True)
import numpy as np, sys
sys.path.insert(0, '/root/repo')
from dccrg_tpu import CartesianGeometry, Grid, make_mesh
from dccrg_tpu.models import Particles

def one(seed):
    rng = np.random.default_rng(seed)
    n = int(rng.choice([4, 6, 8]))
    n_dev = int(rng.choice([1, 2, 4, 8]))
    maxref = int(rng.choice([1, 2]))   # up to 3 leaf levels
    g = (Grid().set_initial_length((n, n, n)).set_neighborhood_length(1)
         .set_periodic(True, True, True)
         .set_maximum_refinement_level(maxref)
         .set_geometry(CartesianGeometry, start=(0.,0.,0.),
                       level_0_cell_length=(1./n,)*3)
         .initialize(mesh=make_mesh(n_devices=n_dev)))
    if rng.random() < 0.7:
        for _round in range(maxref):
            ids = g.get_cells()
            for cid in rng.choice(ids, size=len(ids)//6 + 1, replace=False):
                g.refine_completely(int(cid))
            g.stop_refining()
    npart = int(rng.integers(200, 1500))
    m = Particles(g, max_particles_per_cell=256)
    # uniform Cartesian fully-periodic grids — refined or not — must
    # qualify for the generalized device re-bucket
    assert m._dev_rebucket is not None, (seed, 'device path gated off')
    state = m.new_state(rng.random((npart, 3)))
    assert m.count(state) == npart
    vel = m.velocity_field(lambda c: 0.2 * (c - 0.5))
    for turn in range(4):
        state = m.step(state, velocity=vel, dt=0.1)
        assert m.count(state) == npart, (seed, turn)
    # device-vs-host differential on this (possibly refined) grid
    mh = Particles(g, max_particles_per_cell=256)
    mh._dev_rebucket = None
    sh = mh.new_state(m.positions(state))
    state = m.run(state, 2, velocity=(0.03, -0.02, 0.01), dt=0.5)
    for _ in range(2):
        sh = mh.step(sh, velocity=(0.03, -0.02, 0.01), dt=0.5)
    np.testing.assert_array_equal(
        np.sort(m.positions(state), axis=0),
        np.sort(mh.positions(sh), axis=0))
    assert m.count(state) == npart, (seed, 'post-differential')
    # bucket validity: every particle inside its cell
    ids = g.get_cells()
    for cell in rng.choice(ids, size=min(30, len(ids)), replace=False):
        pts = m.particles_of(state, int(cell))
        if len(pts):
            lo = g.geometry.get_min(np.asarray([cell], np.uint64))[0]
            hi = g.geometry.get_max(np.asarray([cell], np.uint64))[0]
            assert ((pts >= lo - 1e-12) & (pts <= hi + 1e-12)).all(), (seed, cell)
    # survive AMR + balance
    for cid in rng.choice(ids, size=3, replace=False):
        g.refine_completely(int(cid))
    g.stop_refining()
    state = m.remap(state)
    assert m.count(state) == npart, (seed, 'remap-amr')
    g.balance_load()
    state = m.remap(state)
    vel = m.velocity_field(lambda c: 0.2 * (c - 0.5))
    state = m.step(state, velocity=vel, dt=0.1)
    assert m.count(state) == npart, (seed, 'post-lb')
    return n_dev

for seed in range(int(sys.argv[1]), int(sys.argv[2])):
    print(seed, one(seed), flush=True)
print("PIC_FUZZ_OK")
"""

BODIES["gol"] = r"""import jax
jax.config.update('jax_platforms', 'cpu')
jax.config.update('jax_num_cpu_devices', 8)
import numpy as np, sys
sys.path.insert(0, '/root/repo')
from dccrg_tpu import Grid, make_mesh
from dccrg_tpu.models import GameOfLife

def one(seed):
    rng = np.random.default_rng(seed)
    nx = int(rng.choice([6, 10, 12, 16]))
    ny = int(rng.choice([6, 10, 12, 16]))
    n_dev = int(rng.choice([1, 2, 4]))
    if ny % n_dev:
        n_dev = 1
    periodic = (bool(rng.integers(0, 2)), bool(rng.integers(0, 2)), False)
    g = (Grid().set_initial_length((nx, ny, 1)).set_maximum_refinement_level(0)
         .set_neighborhood_length(1).set_periodic(*periodic)
         .initialize(mesh=make_mesh(n_devices=n_dev)))
    cells = g.get_cells()
    alive0 = cells[rng.random(len(cells)) < rng.uniform(0.2, 0.5)]
    variants = {}
    for name, kw in (("general", dict(allow_dense=False)),
                     ("dense", dict(use_pallas=False)),
                     ("fused", dict(use_pallas="interpret"))):
        m = GameOfLife(g, **kw)
        if name != "general" and m._dense_run is None:
            continue
        s = m.run(m.new_state(alive_cells=alive0), int(rng.integers(3, 20)))
        variants[name] = (set(m.alive_cells(s).tolist()),
                         tuple(np.asarray(g.get_cell_data(s, "live_neighbor_count", cells)).tolist()))
    # all computed variants agree... (turns differ per variant! FIX: same turns)
    return variants

# redo with fixed turns
def one2(seed):
    rng = np.random.default_rng(seed)
    nx = int(rng.choice([6, 10, 12, 16]))
    ny = int(rng.choice([6, 10, 12, 16]))
    n_dev = int(rng.choice([1, 2, 4]))
    if ny % n_dev:
        n_dev = 1
    periodic = (bool(rng.integers(0, 2)), bool(rng.integers(0, 2)), False)
    turns = int(rng.integers(3, 20))
    g = (Grid().set_initial_length((nx, ny, 1)).set_maximum_refinement_level(0)
         .set_neighborhood_length(1).set_periodic(*periodic)
         .initialize(mesh=make_mesh(n_devices=n_dev)))
    cells = g.get_cells()
    alive0 = cells[rng.random(len(cells)) < rng.uniform(0.2, 0.5)]
    results = {}
    for name, kw in (("general", dict(allow_dense=False)),
                     ("dense", dict(use_pallas=False)),
                     ("fused", dict(use_pallas="interpret")),
                     ("overlap", dict(overlap=True))):
        m = GameOfLife(g, **kw)
        s = m.run(m.new_state(alive_cells=alive0), turns)
        results[name] = set(m.alive_cells(s).tolist())
    ref = results.pop("general")
    for name, got in results.items():
        assert got == ref, (seed, name, len(got ^ ref))
    return (nx, ny, n_dev, periodic, turns)

for seed in range(int(sys.argv[1]), int(sys.argv[2])):
    print(seed, one2(seed), flush=True)
print("GOL_FUZZ_OK")
"""

BODIES["hoods"] = r"""import jax
jax.config.update('jax_platforms', 'cpu')
jax.config.update('jax_num_cpu_devices', 8)
jax.config.update('jax_enable_x64', True)
import numpy as np, sys
sys.path.insert(0, '/root/repo')
from dccrg_tpu import CartesianGeometry, Grid, make_mesh
from dccrg_tpu.utils.verify import verify_grid, verify_user_data

def one(seed):
    rng = np.random.default_rng(seed)
    n = int(rng.choice([4, 6]))
    n_dev = int(rng.choice([1, 2, 4, 8]))
    periodic = tuple(bool(b) for b in rng.integers(0, 2, 3))
    g = (Grid().set_initial_length((n, n, n)).set_neighborhood_length(2)
         .set_periodic(*periodic).set_maximum_refinement_level(1)
         .set_geometry(CartesianGeometry, start=(0.,0.,0.),
                       level_0_cell_length=(1./n,)*3)
         .initialize(mesh=make_mesh(n_devices=n_dev)))
    # random sub-neighborhoods within the default length-2 hood
    all_offs = [(dx, dy, dz) for dx in range(-2, 3) for dy in range(-2, 3)
                for dz in range(-2, 3) if (dx, dy, dz) != (0, 0, 0)]
    hoods = []
    for hid in range(1, 4):
        k = int(rng.integers(1, 10))
        offs = [all_offs[i] for i in rng.choice(len(all_offs), k, replace=False)]
        assert g.add_neighborhood(hid, offs)
        hoods.append(hid)
    # refine and verify all hood state stays consistent
    ids = g.get_cells()
    for cid in rng.choice(ids, size=max(1, len(ids)//4), replace=False):
        g.refine_completely(int(cid))
    g.stop_refining()
    verify_grid(g)
    # per-hood ghost identity
    spec = {"q": ((), np.float64)}
    state = g.new_state(spec)
    ids = g.get_cells()
    state = g.set_cell_data(state, "q", ids, rng.uniform(0, 1, len(ids)))
    for hid in [None] + hoods:
        st = g.update_copies_of_remote_neighbors(state, hid)
        # ghosts of THIS hood must match owners
        ep = g.epoch
        arr = np.asarray(st["q"])
        h = ep.hoods[hid]
        for d in range(g.n_devices):
            gp = ep.ghost_pos[d]
            # only ghosts this hood's schedule covers
            rows = ep.rows_on_device(d, gp)
            scr = ep.R - 1
            covered = np.zeros(len(gp), dtype=bool)
            rr = h.recv_rows[d].reshape(-1)
            covered_rows = set(rr[rr != scr].tolist())
            for i, r in enumerate(rows):
                if int(r) in covered_rows:
                    covered[i] = True
            if covered.any():
                own = arr[ep.leaves.owner[gp[covered]], ep.row_of[gp[covered]]]
                got = arr[d, rows[covered]]
                np.testing.assert_array_equal(got, own, err_msg=f"{seed} hood {hid} dev {d}")
    # removal keeps things consistent
    g.remove_neighborhood(hoods[0])
    verify_grid(g)
    g.balance_load()
    verify_grid(g)
    return n_dev

for seed in range(int(sys.argv[1]), int(sys.argv[2])):
    print(seed, one(seed), flush=True)
print("HOOD_FUZZ_OK")
"""

BODIES["vlasov"] = r"""import jax
jax.config.update('jax_platforms', 'cpu')
jax.config.update('jax_num_cpu_devices', 8)
jax.config.update('jax_enable_x64', True)   # the AMR per-bin oracle is f64
import numpy as np, sys
sys.path.insert(0, '/root/repo')
from dccrg_tpu import CartesianGeometry, Grid, make_mesh
from dccrg_tpu.models import Vlasov

def one(seed):
    rng = np.random.default_rng(seed)
    n = int(rng.choice([8, 16]))
    n_dev = int(rng.choice([1, 2, 4]))
    periodic = (True, True, bool(rng.integers(0, 2)))
    g = (Grid().set_initial_length((n, n, n)).set_neighborhood_length(0)
         .set_periodic(*periodic)
         .set_geometry(CartesianGeometry, start=(0.,0.,0.),
                       level_0_cell_length=(1./n,)*3)
         .initialize(mesh=make_mesh(n_devices=n_dev)))
    v = Vlasov(g, nv=4, dtype=np.float32, use_pallas=False)
    s0 = v.initialize_state()
    m0 = v.total_mass(s0)
    dt = np.float32(0.4 * v.max_time_step())
    state = v.run(s0, 6, dt)
    m1 = v.total_mass(state)
    if all(periodic):
        assert abs(m1 - m0) / m0 < 1e-5, (seed, m0, m1)
    else:
        assert m1 <= m0 * (1 + 1e-5), (seed, m0, m1)  # open z only loses
    assert np.isfinite(np.asarray(state['f'])).all(), seed
    # fused blocked kernel (interpret) must be bit-identical to the XLA
    # three-split body on current jax; the 0.4.x Pallas interpreter
    # rounds a few ULP differently (see tests/test_vlasov.py), so old
    # jax gets the same ULP tolerance there
    vf = Vlasov(g, nv=4, dtype=np.float32, use_pallas="interpret")
    assert vf._fused_block > 0, seed
    sf = vf.run(s0, 6, dt)
    a32 = np.asarray(sf['f'], np.float32)
    b32 = np.asarray(state['f'], np.float32)
    if tuple(int(p) for p in jax.__version__.split('.')[:2]) >= (0, 5):
        assert np.array_equal(a32, b32), seed
    else:
        ulp = np.spacing(np.maximum(np.abs(a32), np.abs(b32)))
        assert (np.abs(a32 - b32) <= 4 * ulp).all(), (
            seed, float(np.abs(a32 - b32).max()))
    # general/AMR path on a randomly refined grid: every bin's unsplit
    # update must equal the advection general step with that bin's
    # constant velocity (the oracle the path is built to match)
    if seed % 2 == 0:
        from dccrg_tpu.models import Advection
        na = 4
        # fully periodic: the advection oracle's open boundaries are
        # zero-flux walls while Vlasov's are outflow, so the per-bin
        # identity only holds away from open boundaries
        ga = (Grid().set_initial_length((na, na, na))
              .set_neighborhood_length(0).set_periodic(True, True, True)
              .set_maximum_refinement_level(1)
              .set_geometry(CartesianGeometry, start=(0.,0.,0.),
                            level_0_cell_length=(1./na,)*3)
              .initialize(mesh=make_mesh(n_devices=n_dev)))
        ids0 = ga.get_cells()
        for cid in rng.choice(ids0, size=max(1, len(ids0)//5),
                              replace=False):
            ga.refine_completely(int(cid))
        ga.stop_refining()
        va = Vlasov(ga, nv=2, dtype=np.float64)
        assert va.info is None, seed
        sa = va.initialize_state()
        dta = 0.4 * va.max_time_step()
        oa = va.run(sa, 3, dta)
        ids = np.sort(ga.leaves.cells)
        f0 = np.asarray(ga.get_cell_data(sa, 'f', ids), np.float64)
        fT = np.asarray(ga.get_cell_data(oa, 'f', ids), np.float64)
        adv = Advection(ga, dtype=np.float64, use_pallas=False,
                        allow_boxed=False)
        b = int(rng.integers(0, va.B))
        st = adv.initialize_state()
        st = adv.set_cell_data(st, 'density', ids, f0[:, b])
        for d3, nm in enumerate(('vx', 'vy', 'vz')):
            st = adv.set_cell_data(st, nm, ids,
                                   np.full(len(ids), va.v_bins[b, d3]))
        st = ga.update_copies_of_remote_neighbors(st)
        for _ in range(3):
            st = adv.step(st, dta)
        want = np.asarray(ga.get_cell_data(st, 'density', ids), np.float64)
        errb = np.abs(fT[:, b] - want).max() / max(np.abs(want).max(), 1e-30)
        assert errb < 1e-11, (seed, b, errb)
    return periodic, n_dev

for seed in range(int(sys.argv[1]), int(sys.argv[2])):
    print(seed, one(seed), flush=True)
print("VLASOV_FUZZ_OK")
"""



BODIES["poisson"] = r"""'''Differential fuzz: the flat dense BiCG path vs the gather-table path
on random (possibly refined) grids with random cell roles — identical
systems must produce matching solutions and iteration trajectories.'''
import jax
jax.config.update('jax_platforms', 'cpu')
jax.config.update('jax_num_cpu_devices', 8)
jax.config.update('jax_enable_x64', True)
import numpy as np, sys
sys.path.insert(0, '/root/repo')
from dccrg_tpu import CartesianGeometry, Grid, make_mesh
from dccrg_tpu.models import Poisson

def one(seed):
    rng = np.random.default_rng(seed)
    n = int(rng.choice([4, 6, 8]))
    n_dev = int(rng.choice([1, 2, 4]))
    periodic = tuple(bool(b) for b in rng.integers(0, 2, 3))
    maxref = int(rng.integers(0, 3))   # 0-2: up to 3 leaf levels
    g = (Grid().set_initial_length((n, n, n)).set_neighborhood_length(0)
         .set_periodic(*periodic).set_maximum_refinement_level(maxref)
         .set_geometry(CartesianGeometry, start=(0.,0.,0.),
                       level_0_cell_length=(1./n,)*3)
         .initialize(mesh=make_mesh(n_devices=n_dev)))
    for _round in range(maxref):
        ids = g.get_cells()
        k = max(1, int(0.2 * len(ids)))
        for cid in rng.choice(ids, size=k, replace=False):
            g.refine_completely(int(cid))
        g.stop_refining()
    cells = g.get_cells()
    rhs = rng.standard_normal(len(cells))
    kw = {}
    mode = rng.integers(0, 3)
    if mode == 1:          # skip a random subset
        kw['skip_cells'] = rng.choice(cells, size=len(cells)//8 + 1,
                                      replace=False)
    elif mode == 2:        # explicit solve set with boundary remainder
        sel = rng.random(len(cells)) < 0.7
        if not sel.any():
            sel[0] = True
        kw['solve_cells'] = cells[sel]
    pf = Poisson(g, **kw)
    pg = Poisson(g, allow_flat=False, allow_rolled=False, **kw)  # raw oracle

    # rolled static-offset decomposition (any device count: per-device
    # roll spaces, union offset set): must be the gather operator
    # entry-for-entry on random vectors over the real rows.  Checked
    # BEFORE the flat early-return: flat-refusing grids are exactly the
    # rolled path's production audience (poisson.py builds it only when
    # _flat is None)
    prl = Poisson(g, allow_flat=False, allow_rolled=True, **kw)
    if prl._rolled is not None:
        mfo, mro = pg._mult_tables()
        local = np.asarray(pg.tables.local_mask)
        vro = rng.standard_normal(len(cells))
        sR = g.new_state(pg.spec)
        xR = g.set_cell_data(sR, 'solution', cells, vro)['solution']
        for mult, rolled in ((mfo, prl._rolled[0]), (mro, prl._rolled[1])):
            a_g = np.asarray(pg._apply(xR, mult)[0])
            a_r = np.asarray(rolled(xR))
            ops = max(1.0, np.abs(a_g).max())
            da = np.abs(np.where(local, a_g - a_r, 0.0)).max()
            assert da < 1e-10 * ops, (seed, 'rolled', da, ops)
    if pf._flat is None:
        return ('rolled-only' if prl._rolled is not None
                else 'gather-only')

    # operator-level oracle: A.v and A^T.v must agree to fp roundoff on
    # a random vector (BiCG trajectories may legitimately diverge on
    # near-singular systems, so the solver output is only compared by
    # solution QUALITY below)
    vr = rng.standard_normal(len(cells))
    sV = g.new_state(pf.spec)
    sV = g.set_cell_data(sV, 'solution', cells, vr)
    mf, mr = pg._mult_tables()
    af, ar, vox, wb, _masks = pf._flat
    for mult, fl in ((mf, af), (mr, ar)):
        a_g, _ = pg._apply(sV['solution'], mult)
        a_f = wb(fl(vox(sV['solution'])))
        ag = np.asarray(g.get_cell_data({'solution': a_g}, 'solution', cells))
        afc = np.asarray(g.get_cell_data({'solution': a_f}, 'solution', cells))
        ops = max(1.0, np.abs(ag).max())
        assert np.abs(ag - afc).max() < 1e-10 * ops, (
            seed, np.abs(ag - afc).max(), ops)

    s0 = g.new_state(pf.spec)
    s0 = g.set_cell_data(s0, 'rhs', cells, rhs - rhs.mean())
    rhs_norm = float(np.linalg.norm(rhs))

    def restarted(p):
        # the reference's usage shape: BiCG on these non-normal systems
        # (random roles + AMR) can break down mid-Krylov-space — the
        # restart driver rebuilds the space from the best solution and
        # recovers (seed 529: 1.4e-5 -> 6.5e-12 in 3 restarts; seed 61's
        # 3-level random-role system needs 8 restarts on the ml-flat
        # path: 4.6e-7 after 4, 7.8e-12 after 8, gather similar).
        # Budgets must be generous in BOTH dimensions: seed 1532's
        # 3-level skip-mode system stagnates at 1.4e-6 on the flat
        # trajectory for ANY number of 60-iteration restart cycles but
        # converges to 9e-12 given 200 iterations in one cycle —
        # fp-association puts the two operator forms on differently
        # shaped Krylov paths.  Compare the PATHS under the same
        # driver, not single trajectories, which legitimately diverge
        # in rounding.
        st, _r, _i = p.solve(s0, max_iterations=200, stop_residual=1e-11,
                             restarts=8)
        return st

    of = restarted(pf)
    og = restarted(pg)
    # solution quality under the GATHER operator (the oracle): the flat
    # solve must be as good as the gather solve up to a modest factor
    rf_chk = pg.residual(of)
    rg_chk = pg.residual(og)
    assert rf_chk <= 10.0 * rg_chk + 1e-9 * rhs_norm, (
        seed, rf_chk, rg_chk)
    if max(rf_chk, rg_chk) < 1e-10 * rhs_norm:
        # both fully converged: solutions must coincide
        sf = np.asarray(g.get_cell_data(of, 'solution', cells))
        sg = np.asarray(g.get_cell_data(og, 'solution', cells))
        scale = max(1.0, np.abs(sg).max())
        assert np.abs(sf - sg).max() < 1e-7 * scale, (
            seed, np.abs(sf - sg).max(), scale)

    # fused whole-solve kernel (interpret) vs the f32 XLA flat path:
    # identical masked-loop semantics -> same iteration count and
    # solver-tolerance-equal solutions
    pk = Poisson(g, dtype=np.float32, use_pallas='interpret', **kw)
    if pk._solve_fast is not None:
        px = Poisson(g, dtype=np.float32, use_pallas=False, **kw)
        s32 = g.new_state(pk.spec)
        s32 = g.set_cell_data(s32, 'rhs', cells,
                              (rhs - rhs.mean()).astype(np.float32))
        ok_, rk, itk = pk.solve(s32, max_iterations=40, stop_residual=1e-4)
        assert pk._solve_fast is not None, (seed, 'kernel fell back')
        ox_, rx, itx = px.solve(s32, max_iterations=40, stop_residual=1e-4)
        assert abs(itk - itx) <= 1, (seed, itk, itx)
        sk = np.asarray(g.get_cell_data(ok_, 'solution', cells))
        sx = np.asarray(g.get_cell_data(ox_, 'solution', cells))
        scale = max(1.0, np.abs(sx).max())
        assert np.abs(sk - sx).max() < 1e-4 * scale, (
            seed, np.abs(sk - sx).max(), scale)
    return 'flat-ok', n_dev, mode

for seed in range(int(sys.argv[1]), int(sys.argv[2])):
    print(seed, one(seed), flush=True)
print("POISSON_FUZZ_OK")
"""


#: the crash-subsystem child: a resume-capable GoL + advection run with
#: periodic checkpoint-lineage commits.  Launched repeatedly by
#: run_crash(); any launch may die (injected SIGKILL at a commit
#: boundary via DCCRG_FAULT, or the parent's random-time SIGKILL) and
#: the next launch must resume from latest_valid() — possibly at a
#: DIFFERENT device count — and still converge to the uninterrupted
#: run's final state.  argv: workdir seed n_devices total_steps every
CRASH_CHILD = r"""import sys
wd, seed, nd, total, every = (sys.argv[1], int(sys.argv[2]),
                              int(sys.argv[3]), int(sys.argv[4]),
                              int(sys.argv[5]))
import jax
jax.config.update('jax_platforms', 'cpu')
try:
    jax.config.update('jax_num_cpu_devices', nd)
except AttributeError:   # old jax: pre-init XLA_FLAGS is the only knob
    import os as _os
    if 'xla_force_host_platform_device_count' not in _os.environ.get('XLA_FLAGS', ''):
        _os.environ['XLA_FLAGS'] = (_os.environ.get('XLA_FLAGS', '')
            + ' --xla_force_host_platform_device_count=%d' % nd).strip()
jax.config.update('jax_enable_x64', True)
import os
import numpy as np
sys.path.insert(0, __DCCRG_ROOT__)
from dccrg_tpu import CartesianGeometry, Grid, make_mesh, obs
from dccrg_tpu.io.checkpoint import CheckpointError
from dccrg_tpu.models import Advection, GameOfLife
from dccrg_tpu.resilience.manager import CheckpointLineage

obs.stream_to(os.path.join(wd, 'child_stream.jsonl'), period=2.0,
              extra={'subsystem': 'crash', 'seed': seed, 'n_devices': nd})
# black box (ISSUE 10): the ring checkpoints itself to
# flightrec_<pid>.json in the workdir, so even a SIGKILL mid-step
# leaves a schema-valid postmortem naming the unit in flight — the
# driver asserts this for every killed attempt
from dccrg_tpu.obs import flightrec as _flightrec
_flightrec.recorder.arm(wd, period=0.5)
# per-child timeline export at exit: carries origin_unix_s, the anchor
# the post-run fleet merge (obs.merge_chrome_traces) unifies children on.
# A SIGKILLed attempt leaves no trace file — the surviving attempts'
# traces still merge (crash evidence lives in the streams, not here).
import atexit as _atexit
_atexit.register(lambda: obs.export_chrome_trace(
    os.path.join(wd, 'child_%d.trace.json' % os.getpid())))


def atomic_save(path, arr):
    tmp = path + '.tmp'
    with open(tmp, 'wb') as f:
        np.save(f, arr)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


# ---- phase 1: Game of Life (exact across device counts) -------------
final = os.path.join(wd, 'gol_final.npy')
if not os.path.exists(final):
    rng = np.random.default_rng(seed)
    g = (Grid().set_initial_length((10, 10, 1)).set_neighborhood_length(1)
         .set_periodic(True, True, False)
         .initialize(mesh=make_mesh(n_devices=nd)))
    cells = g.get_cells()
    alive0 = cells[rng.random(len(cells)) < 0.35]
    lineage = CheckpointLineage(os.path.join(wd, 'gol'), keep=3)
    try:
        g, s, hdr, gen = lineage.latest_valid(GameOfLife.SPEC, n_devices=nd)
        step = int(hdr)
        gol = GameOfLife(g)
        print('RESUMED gol gen=%d step=%d' % (gen, step), flush=True)
    except CheckpointError:
        gol = GameOfLife(g)
        s = gol.new_state(alive_cells=alive0)
        step = 0
        print('FRESH gol', flush=True)
    while step < total:
        _flightrec.recorder.mark_unit('gol/%d' % step, tenant='soak',
                                      phase='gol', step=step)
        s = gol.run(s, 1)
        step += 1
        if step % every == 0:
            lineage.commit(g, s, GameOfLife.SPEC,
                           user_header=str(step).encode())
    atomic_save(final, np.sort(gol.alive_cells(s)))

# ---- phase 2: advection (within documented tolerance) ---------------
final = os.path.join(wd, 'adv_final.npy')
if not os.path.exists(final):
    rng = np.random.default_rng(seed + 1)
    n = 4
    g = (Grid().set_initial_length((n, n, n)).set_neighborhood_length(0)
         .set_periodic(True, True, True).set_maximum_refinement_level(1)
         .set_geometry(CartesianGeometry, start=(0., 0., 0.),
                       level_0_cell_length=(1. / n,) * 3)
         .initialize(mesh=make_mesh(n_devices=nd)))
    ids0 = g.get_cells()
    for cid in rng.choice(ids0, size=max(1, len(ids0) // 5), replace=False):
        g.refine_completely(int(cid))
    g.stop_refining()
    ids = g.get_cells()
    # deterministic initial conditions from the seed — regenerated on
    # every launch, discarded when a lineage resume takes over
    dens0 = rng.uniform(1, 2, len(ids))
    vels0 = {f: rng.uniform(-0.2, 0.2, len(ids)) for f in ('vx', 'vy', 'vz')}
    adv = Advection(g)
    spec = {k: adv.spec[k] for k in ('density', 'vx', 'vy', 'vz')}
    s0 = adv.initialize_state()
    s0 = adv.set_cell_data(s0, 'density', ids, dens0)
    for f in ('vx', 'vy', 'vz'):
        s0 = adv.set_cell_data(s0, f, ids, vels0[f])
    s0 = g.update_copies_of_remote_neighbors(s0)
    dt = 0.3 * adv.max_time_step(s0)
    lineage = CheckpointLineage(os.path.join(wd, 'adv'), keep=3)
    try:
        g2, s2, hdr, gen = lineage.latest_valid(spec, n_devices=nd)
        step = int(hdr)
        adv = Advection(g2)
        s = adv.initialize_state()
        for f in spec:
            s = adv.set_cell_data(s, f, ids, g2.get_cell_data(s2, f, ids))
        s = g2.update_copies_of_remote_neighbors(s)
        g = g2
        print('RESUMED adv gen=%d step=%d' % (gen, step), flush=True)
    except CheckpointError:
        s = s0
        step = 0
        print('FRESH adv', flush=True)
    while step < total:
        _flightrec.recorder.mark_unit('adv/%d' % step, tenant='soak',
                                      phase='adv', step=step)
        s = adv.step(s, dt)
        step += 1
        if step % every == 0:
            lineage.commit(g, s, spec, user_header=str(step).encode())
    atomic_save(final, np.asarray(g.get_cell_data(s, 'density', ids),
                                  np.float64))

print('CRASH_CHILD_DONE', flush=True)
"""


def check_flightrec_dump(workdir: str, context: str,
                         require_inflight: bool = True) -> list:
    """Driver-side black-box assertion (ISSUE 10): a killed child must
    have left a parseable ``flightrec_*.json`` postmortem in its workdir
    naming the unit(s) it had in flight.  Returns failure strings.

    ``require_inflight=False`` relaxes the victim-naming requirement to
    "only if the dump shows stepping ever began" (any ``unit`` event in
    the ring) — the crash harness kills at RANDOM wall-clock times that
    can land in the sliver between arming and the first step."""
    import glob as _glob
    import json
    import os

    if str(ROOT) not in sys.path:
        sys.path.insert(0, str(ROOT))
    from dccrg_tpu.obs.flightrec import validate_flightrec

    files = _glob.glob(os.path.join(workdir, "flightrec_*.json"))
    if not files:
        return [f"{context}: killed child left no flight-recorder dump"]
    newest = max(files, key=os.path.getmtime)
    name = os.path.basename(newest)
    fails = [f"{context}: {name}: {f}" for f in validate_flightrec(newest)]
    if fails:
        return fails
    with open(newest) as f:
        rec = json.load(f)
    stepped = any(ev.get("kind") == "unit"
                  for ev in rec.get("events", []))
    if (require_inflight or stepped) and not rec.get("in_flight"):
        return [f"{context}: postmortem {name} names no in-flight "
                "request"]
    return []


def run_crash(lo: int, hi: int, stream_dir: str | None = None,
              total_steps: int = 24, every: int = 3) -> bool:
    """The crash/resume proof harness (ISSUE 4e).  Per seed:

    1. an uninterrupted reference child runs to completion;
    2. a crash child runs the same workload with lineage checkpoints
       while being killed — even attempts arm an injected SIGKILL at a
       random commit boundary plus occasional torn writes
       (``DCCRG_FAULT``), odd attempts get SIGKILLed by THIS process at
       a random wall-clock moment (which can land mid-write or
       mid-manifest-rewrite — the genuinely torn cases); each relaunch
       resumes from ``latest_valid()`` at a possibly different device
       count;
    3. once a launch completes, the final states must match the
       reference: GoL exactly, advection to the documented 1e-11
       cross-layout tolerance.

    Every attempt's outcome (exit status, kill mode, which generation
    the resume picked up) is appended to the streaming telemetry JSONL.
    """
    import json
    import os
    import re
    import shutil
    import tempfile
    import time

    import numpy as np

    stream = None
    if stream_dir:
        os.makedirs(stream_dir, exist_ok=True)
        if str(ROOT) not in sys.path:
            sys.path.insert(0, str(ROOT))
        from dccrg_tpu.obs.stream import TelemetryStream

        stream = TelemetryStream(
            os.path.join(stream_dir, f"crash_{lo}_{hi}.jsonl"),
            truncate=True, extra={"subsystem": "crash", "seeds": [lo, hi]},
        )

    def record(**kw):
        if stream is not None:
            stream.write_snapshot(**kw)

    def launch(workdir, seed, nd, env_extra=None):
        env = dict(os.environ)
        env.pop("DCCRG_FAULT", None)
        env.update(env_extra or {})
        log = open(os.path.join(workdir, "child.log"), "a")
        p = subprocess.Popen(
            [sys.executable, "-c",
             CRASH_CHILD.replace("__DCCRG_ROOT__", repr(str(ROOT))),
             workdir, str(seed), str(nd), str(total_steps), str(every)],
            cwd=str(ROOT), stdout=log, stderr=subprocess.STDOUT, env=env,
        )
        return p, log

    def resumes_of(workdir):
        try:
            with open(os.path.join(workdir, "child.log")) as f:
                return re.findall(r"(?:RESUMED|FRESH) [^\n]*", f.read())[-4:]
        except OSError:
            return []

    nd_cycle = (2, 1, 4)
    max_attempts = 8
    ok_all = True
    for seed in range(lo, hi):
        rng = np.random.default_rng(10_000 + seed)
        tmp = tempfile.mkdtemp(prefix=f"dccrg_crash_{seed}_")
        try:
            # 1. uninterrupted reference
            ref = os.path.join(tmp, "ref")
            os.makedirs(ref)
            nd_ref = int(rng.choice(nd_cycle))
            p, log = launch(ref, seed, nd_ref)
            rc = p.wait()
            log.close()
            if rc != 0:
                print(f"crash seed {seed}: reference run failed rc={rc}")
                print(open(os.path.join(ref, "child.log")).read()[-2000:])
                record(seed=seed, outcome="reference-failed", exit=rc)
                ok_all = False
                continue

            # 2. crash/resume until a launch completes
            wd = os.path.join(tmp, "crash")
            os.makedirs(wd)
            rc = -1
            for attempt in range(max_attempts):
                nd = nd_cycle[attempt % len(nd_cycle)]
                last = attempt == max_attempts - 1
                env_extra, kill_mode = {}, "none"
                if not last and attempt % 2 == 0:
                    kill_mode = "inject-sigkill"
                    env_extra["DCCRG_FAULT"] = (
                        f"sigkill.post_commit:0.6:{seed * 97 + attempt}:1"
                        f":{int(rng.integers(0, 4))}"
                        f",checkpoint.torn_write:0.07:{seed * 31 + attempt}"
                    )
                elif not last:
                    kill_mode = "parent-kill"
                p, log = launch(wd, seed, nd, env_extra)
                if kill_mode == "parent-kill":
                    try:
                        p.wait(timeout=float(rng.uniform(2.0, 10.0)))
                    except subprocess.TimeoutExpired:
                        p.kill()
                try:
                    # hang guard: a wedged child is killed and recorded
                    # as such; the stream keeps the evidence
                    rc = p.wait(timeout=600)
                except subprocess.TimeoutExpired:
                    p.kill()
                    rc = p.wait()
                    kill_mode += "+hang-guard"
                log.close()
                record(seed=seed, attempt=attempt, n_devices=nd,
                       kill=kill_mode, exit=rc, resumes=resumes_of(wd))
                if rc == 0:
                    break
                # ISSUE 10: every killed attempt that reached the
                # workload must have left its black box (random-time
                # kills can land before arming — resumes_of is the
                # evidence the child got that far)
                if resumes_of(wd):
                    probs = check_flightrec_dump(
                        wd, f"crash seed {seed} attempt {attempt}",
                        require_inflight=False,
                    )
                    for p in probs:
                        print(f"  FLIGHTREC: {p}")
                    if probs:
                        record(seed=seed, attempt=attempt,
                               outcome="flightrec-missing")
                        ok_all = False
            if rc != 0:
                print(f"crash seed {seed}: no attempt completed "
                      f"(last rc={rc})")
                print(open(os.path.join(wd, "child.log")).read()[-2000:])
                record(seed=seed, outcome="never-completed", exit=rc)
                ok_all = False
                continue

            # 3. convergence against the reference
            try:
                gol_ref = np.load(os.path.join(ref, "gol_final.npy"))
                gol_got = np.load(os.path.join(wd, "gol_final.npy"))
                np.testing.assert_array_equal(gol_got, gol_ref)
                adv_ref = np.load(os.path.join(ref, "adv_final.npy"))
                adv_got = np.load(os.path.join(wd, "adv_final.npy"))
                np.testing.assert_allclose(adv_got, adv_ref,
                                           rtol=1e-11, atol=0)
            except AssertionError as e:
                print(f"crash seed {seed}: DIVERGED after resume: "
                      f"{str(e)[:200]}")
                record(seed=seed, outcome="diverged")
                ok_all = False
                continue
            record(seed=seed, outcome="ok", attempts=attempt + 1)
            print(f"crash seed {seed}: OK after {attempt + 1} attempt(s)")
        finally:
            # salvage child timeline exports before the workdir goes:
            # they carry origin_unix_s, the anchor the post-run fleet
            # merge unifies every process on (SIGKILLed attempts left
            # none — the streams keep their evidence)
            if stream_dir:
                import glob as _glob

                for i, t in enumerate(sorted(_glob.glob(
                        os.path.join(tmp, "*", "child_*.trace.json")))):
                    shutil.copy(t, os.path.join(
                        stream_dir, f"crash_{seed}_{i}.trace.json"))
            shutil.rmtree(tmp, ignore_errors=True)
    if stream is not None:
        stream.stop(final=True)
    print(f"{'crash':12s} [{lo},{hi}): {'OK' if ok_all else 'FAIL'}")
    return ok_all


#: the elastic-subsystem child: GoL + advection-under-AMR-churn with
#: periodic lineage commits, seeded in-process grow/shrink rescales
#: (``resilience/elastic.py``), a 0.5 s heartbeat stream the parent's
#: Supervisor tails, and per-step fault hooks (``step.hang`` wedges the
#: loop for the watchdog to catch; ``device.lost`` exits 42 for the
#: supervisor to relaunch degraded).  The churn + rescale schedules are
#: pure functions of (seed, step), so every attempt — and the fixed-mesh
#: reference (do_rescale=0, no faults) — walks the same structural
#: history and must converge to the same final state.
#: argv: workdir seed n_devices total_steps every do_rescale
ELASTIC_CHILD = r"""import sys
wd, seed, nd, total, every, do_rescale = (
    sys.argv[1], int(sys.argv[2]), int(sys.argv[3]), int(sys.argv[4]),
    int(sys.argv[5]), int(sys.argv[6]))
import jax
jax.config.update('jax_platforms', 'cpu')
try:
    jax.config.update('jax_num_cpu_devices', 8)
except AttributeError:   # old jax: pre-init XLA_FLAGS is the only knob
    import os as _os
    if 'xla_force_host_platform_device_count' not in _os.environ.get('XLA_FLAGS', ''):
        _os.environ['XLA_FLAGS'] = (_os.environ.get('XLA_FLAGS', '')
            + ' --xla_force_host_platform_device_count=8').strip()
jax.config.update('jax_enable_x64', True)
import os
import numpy as np
sys.path.insert(0, __DCCRG_ROOT__)
from dccrg_tpu import CartesianGeometry, Grid, make_mesh, obs
from dccrg_tpu.io.checkpoint import CheckpointError
from dccrg_tpu.models import Advection, GameOfLife
from dccrg_tpu.resilience import (CheckpointLineage, DeviceLostError,
                                  rescale)
from dccrg_tpu.resilience import inject

hb = os.environ.get('DCCRG_ELASTIC_HEARTBEAT',
                    os.path.join(wd, 'heartbeat.jsonl'))
stream = obs.stream_to(hb, period=0.5,
                       extra={'subsystem': 'elastic', 'seed': seed})
# black box (ISSUE 10): armed at the workdir so every killed attempt
# (watchdog rescue, device loss, SIGKILL) leaves flightrec_<pid>.json
# naming the step that was in flight — asserted by the driver
from dccrg_tpu.obs import flightrec as _flightrec
_flightrec.recorder.arm(wd, period=0.5)

ADV_SPEC = {k: ((), np.float64) for k in ('density', 'vx', 'vy', 'vz')}


def atomic_save(path, arr):
    tmp = path + '.tmp'
    with open(tmp, 'wb') as f:
        np.save(f, arr)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def schedules(phase):
    '''Seeded (rescale, churn) schedules — pure in (seed, phase), so
    every launch of every attempt agrees on the structural history.'''
    rng = np.random.default_rng(100_000 + seed * 7 + phase)
    n_r = min(3, max(1, total // 6))
    steps = np.sort(rng.choice(np.arange(2, total), size=n_r,
                               replace=False))
    rescales = {int(s): int(rng.choice([1, 2, 4, 8]))
                for s in steps}
    churn = {int(s) for s in rng.choice(np.arange(1, total),
                                        size=min(3, total // 5),
                                        replace=False)}
    return rescales, churn


def step_hooks(phase, step):
    '''Per-step fault seams: a hang wedges the loop (the supervisor's
    heartbeat watchdog must catch it); a device loss aborts to exit 42
    (the supervisor must relaunch degraded).  The unit is marked in the
    flight recorder FIRST, so whichever fault fires, the postmortem
    names this step as the victim.'''
    _flightrec.recorder.mark_unit('%s/%d' % (phase, step), tenant='soak',
                                  phase=phase, step=step)
    stream.write_snapshot(phase=phase, step=step)
    inject.maybe_raise('device.lost', DeviceLostError, where='step')
    inject.maybe_hang('step.hang', seconds=600.0)


def churn_refine(g, s, rng_tag):
    '''Deterministic one-cell refinement churn: the target is chosen
    from the SORTED leaf ids, so every layout/device-count agrees.'''
    ids = np.sort(g.get_cells())
    lvl = g.mapping.get_refinement_level(ids)
    cand = ids[lvl < g.mapping.max_refinement_level]
    if not len(cand):
        return g, s, False
    g.refine_completely(int(cand[rng_tag % len(cand)]))
    g.stop_refining()
    s = g.remap_state(s)
    return g, s, True


def run_phases():
    # ---- phase 1: Game of Life (exact across counts and rescales) --------
    final = os.path.join(wd, 'gol_final.npy')
    if not os.path.exists(final):
        rescales, _churn = schedules(0)
        rng = np.random.default_rng(seed)
        g = (Grid().set_initial_length((10, 10, 1)).set_neighborhood_length(1)
             .set_periodic(True, True, False)
             .initialize(mesh=make_mesh(n_devices=nd)))
        cells = g.get_cells()
        alive0 = cells[rng.random(len(cells)) < 0.35]
        lineage = CheckpointLineage(os.path.join(wd, 'gol'), keep=3)
        try:
            g, s, hdr, gen = lineage.latest_valid(GameOfLife.SPEC,
                                                  n_devices=nd)
            step = int(hdr)
            gol = GameOfLife(g)
            print('RESUMED gol gen=%d step=%d nd=%d' % (gen, step, nd),
                  flush=True)
        except CheckpointError:
            gol = GameOfLife(g)
            s = gol.new_state(alive_cells=alive0)
            step = 0
            print('FRESH gol nd=%d' % nd, flush=True)
        while step < total:
            step_hooks('gol', step)
            if do_rescale and step in rescales and rescales[step] != g.n_devices:
                r = rescale(g, s, GameOfLife.SPEC, rescales[step],
                            lineage=lineage, user_header=str(step).encode())
                g, s = r.grid, r.state
                gol = GameOfLife(g)
                print('RESCALED gol step=%d %d->%d' % (
                    step, r.n_devices_before, r.n_devices_after), flush=True)
            s = gol.run(s, 1)
            step += 1
            if step % every == 0:
                lineage.commit(g, s, GameOfLife.SPEC,
                               user_header=str(step).encode())
        atomic_save(final, np.sort(gol.alive_cells(s)))

    # ---- phase 2: advection under AMR churn (1e-11 across layouts) -------
    final = os.path.join(wd, 'adv_final.npy')
    if not os.path.exists(final):
        rescales, churn = schedules(1)
        rng = np.random.default_rng(seed + 1)
        n = 4
        g = (Grid().set_initial_length((n, n, n)).set_neighborhood_length(0)
             .set_periodic(True, True, True).set_maximum_refinement_level(1)
             .set_geometry(CartesianGeometry, start=(0., 0., 0.),
                           level_0_cell_length=(1. / n,) * 3)
             .initialize(mesh=make_mesh(n_devices=nd)))
        ids0 = np.sort(g.get_cells())
        for cid in rng.choice(ids0, size=max(1, len(ids0) // 5),
                              replace=False):
            g.refine_completely(int(cid))
        g.stop_refining()
        ids = np.sort(g.get_cells())
        dens0 = rng.uniform(1, 2, len(ids))
        vels0 = {f: rng.uniform(-0.2, 0.2, len(ids))
                 for f in ('vx', 'vy', 'vz')}


        def land(g2, s2):
            '''(re)build the model + full state from a loaded/rescaled
            (grid, spec-field state) pair — the shared landing path for
            fresh starts, resumes, rescales, and churn rebuilds.'''
            ids2 = np.sort(g2.get_cells())
            a2 = Advection(g2)
            st = a2.initialize_state()
            for f in ADV_SPEC:
                st = a2.set_cell_data(st, f, ids2,
                                      g2.get_cell_data(s2, f, ids2))
            st = g2.update_copies_of_remote_neighbors(st)
            return a2, st


        adv = Advection(g)
        s0 = adv.initialize_state()
        s0 = adv.set_cell_data(s0, 'density', ids, dens0)
        for f in ('vx', 'vy', 'vz'):
            s0 = adv.set_cell_data(s0, f, ids, vels0[f])
        s0 = g.update_copies_of_remote_neighbors(s0)
        dt = 0.3 * adv.max_time_step(s0)
        lineage = CheckpointLineage(os.path.join(wd, 'adv'), keep=3)
        try:
            g2, s2, hdr, gen = lineage.latest_valid(ADV_SPEC, n_devices=nd)
            step = int(hdr)
            g = g2
            adv, s = land(g, s2)
            print('RESUMED adv gen=%d step=%d nd=%d' % (gen, step, nd),
                  flush=True)
        except CheckpointError:
            s = s0
            step = 0
            print('FRESH adv nd=%d' % nd, flush=True)
        while step < total:
            step_hooks('adv', step)
            if step in churn:
                g, s, did = churn_refine(g, s, 7919 * (step + 1))
                if did:
                    s = g.update_copies_of_remote_neighbors(s)
                    adv = Advection(g)
            if do_rescale and step in rescales and rescales[step] != g.n_devices:
                r = rescale(g, s, ADV_SPEC, rescales[step], lineage=lineage,
                            user_header=str(step).encode())
                g = r.grid
                adv, s = land(g, r.state)
                print('RESCALED adv step=%d %d->%d' % (
                    step, r.n_devices_before, r.n_devices_after), flush=True)
            s = adv.step(s, dt)
            step += 1
            if step % every == 0:
                lineage.commit(g, s, ADV_SPEC, user_header=str(step).encode())
        ids_f = np.sort(g.get_cells())
        atomic_save(final, np.asarray(
            g.get_cell_data(s, 'density', ids_f), np.float64))



try:
    run_phases()
except DeviceLostError as e:
    print('DEVICE_LOST:', e, flush=True)
    sys.exit(42)
print('ELASTIC_CHILD_DONE', flush=True)
"""

#: the zero-cold-start proof child: resume the elastic run's advection
#: lineage on ``nd`` devices, run one deterministic churn cycle, and
#: report the grid's ShapeSignature + the recompile/warm-compile split.
#: Run twice with DCCRG_COMPILE_CACHE_DIR shared: the first populates
#: the persistent compilation cache for the signature, the second — a
#: genuinely fresh process — must record ``epoch.recompiles == 0`` on
#: the SAME signature (every compile served from disk).
#: argv: workdir n_devices out_json
PROOF_CHILD = r"""import sys, json
wd, nd, out = sys.argv[1], int(sys.argv[2]), sys.argv[3]
import jax
jax.config.update('jax_platforms', 'cpu')
try:
    jax.config.update('jax_num_cpu_devices', 8)
except AttributeError:
    import os as _os
    if 'xla_force_host_platform_device_count' not in _os.environ.get('XLA_FLAGS', ''):
        _os.environ['XLA_FLAGS'] = (_os.environ.get('XLA_FLAGS', '')
            + ' --xla_force_host_platform_device_count=8').strip()
jax.config.update('jax_enable_x64', True)
import os
import numpy as np
sys.path.insert(0, __DCCRG_ROOT__)
from dccrg_tpu import Grid, make_mesh, obs
from dccrg_tpu.models import Advection
from dccrg_tpu.parallel.exec_cache import persistent_cache_counts
from dccrg_tpu.resilience import CheckpointLineage

ADV_SPEC = {k: ((), np.float64) for k in ('density', 'vx', 'vy', 'vz')}
lineage = CheckpointLineage(os.path.join(wd, 'adv'), keep=3)
g, s2, hdr, gen = lineage.latest_valid(ADV_SPEC, n_devices=nd)
ids = np.sort(g.get_cells())
adv = Advection(g)
s = adv.initialize_state()
for f in ADV_SPEC:
    s = adv.set_cell_data(s, f, ids, g.get_cell_data(s2, f, ids))
s = g.update_copies_of_remote_neighbors(s)
dt = 0.25 * adv.max_time_step(s)
s = adv.step(s, dt)
# one churn cycle (deterministic target): rebuild + re-land + step —
# the "first churn cycle already warm" claim under proof
lvl = g.mapping.get_refinement_level(ids)
cand = ids[lvl < g.mapping.max_refinement_level]
if len(cand):
    g.refine_completely(int(cand[len(cand) // 2]))
    g.stop_refining()
    s = g.remap_state(s)
    s = g.update_copies_of_remote_neighbors(s)
    adv = Advection(g)
    s = adv.step(s, dt)
jax.block_until_ready(s['density'])
rep = obs.metrics.report()
rec = {
    'signature': repr(g.shape_signature()),
    'generation': gen,
    'recompiles': int(sum(
        rep['counters'].get('epoch.recompiles', {}).values())),
    'warm_compiles': int(sum(
        rep['counters'].get('epoch.warm_compiles', {}).values())),
    'persistent_cache': persistent_cache_counts(),
}
with open(out, 'w') as f:
    json.dump(rec, f)
print('PROOF_CHILD_DONE', json.dumps(rec), flush=True)
"""


def run_elastic(lo: int, hi: int, stream_dir: str | None = None,
                total_steps: int = 18, every: int = 3) -> bool:
    """The elastic-fleet proof harness (ISSUE 8).  Per seed:

    1. a fixed-mesh reference child runs the workload to completion
       (same seeded AMR-churn schedule, no rescales, no faults);
    2. an elastic run: the child performs seeded in-process grow/shrink
       rescales while the parent's :class:`Supervisor` tails its 0.5 s
       heartbeat stream — attempt 0 arms an injected ``step.hang``
       (the watchdog must detect the stall and escalate warn →
       rescale-down: the child is killed and relaunched DEGRADED at
       half the devices), attempt 1 arms ``device.lost`` (the child
       exits 42; the supervisor's dead-child path relaunches it at
       fewer devices from ``latest_valid()``), later attempts run
       clean; every relaunch resumes from the lineage;
    3. the completed run's final states must match the reference —
       GoL exactly, advection to the 1e-11 cross-layout tolerance;
    4. the warm-start proof: two fresh processes resume the final
       lineage under a shared ``DCCRG_COMPILE_CACHE_DIR`` and run one
       churn cycle; the second must land on the first's ShapeSignature
       with ``epoch.recompiles == 0`` (every compile a persistent-cache
       hit).
    """
    import json
    import os
    import shutil
    import tempfile
    import time

    import numpy as np

    if str(ROOT) not in sys.path:
        sys.path.insert(0, str(ROOT))
    from dccrg_tpu.obs.stream import TelemetryStream
    from dccrg_tpu.resilience import HeartbeatMonitor, Supervisor

    stream = None
    if stream_dir:
        os.makedirs(stream_dir, exist_ok=True)
        stream = TelemetryStream(
            os.path.join(stream_dir, f"elastic_{lo}_{hi}.jsonl"),
            truncate=True,
            extra={"subsystem": "elastic", "seeds": [lo, hi]},
        )

    def record(**kw):
        if stream is not None:
            stream.write_snapshot(**kw)

    def launch(body, argv, env_extra=None, log_name="child.log"):
        env = dict(os.environ)
        env.pop("DCCRG_FAULT", None)
        env.update(env_extra or {})
        log = open(os.path.join(argv[0], log_name), "a")
        p = subprocess.Popen(
            [sys.executable, "-c",
             body.replace("__DCCRG_ROOT__", repr(str(ROOT)))]
            + [str(a) for a in argv],
            cwd=str(ROOT), stdout=log, stderr=subprocess.STDOUT, env=env,
        )
        return p, log

    def supervise(p, hb_path, stall_after=25.0, timeout=600.0):
        """Poll the child's heartbeat until it exits or the watchdog
        decides; returns ``(outcome, returncode)`` where outcome is
        ``exited`` | ``rescale_down`` | ``restart`` | ``timeout``."""
        mon = HeartbeatMonitor(hb_path, stall_after_s=stall_after)
        sup = Supervisor(mon, child_alive=lambda: p.poll() is None)
        t0 = time.monotonic()
        while True:
            time.sleep(0.3)
            if p.poll() is not None:
                if p.returncode != 0:
                    # count the dead-child escalation in THIS process's
                    # registry (the child's own counters died with it)
                    sup.poll()
                return "exited", p.returncode
            act = sup.poll()
            if act["action"] == "warn":
                print(f"    watchdog: WARN ({act['reason']})", flush=True)
            elif act["action"] in ("rescale_down", "restart"):
                p.kill()
                p.wait()
                return act["action"], None
            if time.monotonic() - t0 > timeout:
                p.kill()
                p.wait()
                return "timeout", None

    nd_ref = 2
    max_attempts = 8
    ok_all = True
    for seed in range(lo, hi):
        tmp = tempfile.mkdtemp(prefix=f"dccrg_elastic_{seed}_")
        cache_dir = os.path.join(tmp, "compile_cache")
        try:
            # 1. fixed-mesh reference (no rescales, no faults)
            ref = os.path.join(tmp, "ref")
            os.makedirs(ref)
            p, log = launch(
                ELASTIC_CHILD,
                [ref, seed, nd_ref, total_steps, every, 0],
                {"DCCRG_COMPILE_CACHE_DIR": cache_dir},
            )
            rc = p.wait()
            log.close()
            if rc != 0:
                print(f"elastic seed {seed}: reference failed rc={rc}")
                print(open(os.path.join(ref, "child.log")).read()[-2000:])
                record(seed=seed, outcome="reference-failed", exit=rc)
                ok_all = False
                continue

            # 2. supervised elastic run with injected hang + device loss
            wd = os.path.join(tmp, "elastic")
            os.makedirs(wd)
            nd = 4
            rc = -1
            for attempt in range(max_attempts):
                hb = os.path.join(wd, f"heartbeat_{attempt}.jsonl")
                env_extra = {
                    "DCCRG_ELASTIC_HEARTBEAT": hb,
                    "DCCRG_COMPILE_CACHE_DIR": cache_dir,
                }
                fault = "none"
                if attempt == 0:
                    # wedge the step loop a few steps in: only the
                    # heartbeat watchdog can see this failure
                    fault = "step.hang"
                    env_extra["DCCRG_FAULT"] = \
                        f"step.hang:1:{seed}:1:{2 + seed % 3}"
                elif attempt == 1:
                    fault = "device.lost"
                    env_extra["DCCRG_FAULT"] = \
                        f"device.lost:1:{seed}:1:{3 + seed % 4}"
                p, log = launch(
                    ELASTIC_CHILD,
                    [wd, seed, nd, total_steps, every, 1],
                    env_extra,
                )
                outcome, rc = supervise(p, hb)
                log.close()
                record(seed=seed, attempt=attempt, n_devices=nd,
                       fault=fault, outcome=outcome, exit=rc)
                print(f"  attempt {attempt} nd={nd} fault={fault}: "
                      f"{outcome} rc={rc}", flush=True)
                if outcome == "exited" and rc == 0:
                    break
                # ISSUE 10: a killed/faulted attempt must leave its
                # black box naming the step it was serving — the hang
                # wedges AFTER the unit is marked and the checkpoint
                # ticks every 0.5s, so the postmortem is always there
                probs = check_flightrec_dump(
                    wd, f"elastic seed {seed} attempt {attempt}")
                for p in probs:
                    print(f"  FLIGHTREC: {p}")
                if probs:
                    record(seed=seed, attempt=attempt,
                           outcome="flightrec-missing")
                    ok_all = False
                # degraded relaunch at fewer devices after a watchdog
                # rescale-down or a device loss (exit 42); a restart
                # keeps the count
                if outcome == "rescale_down" or rc == 42:
                    nd = max(1, nd // 2)
            if rc != 0:
                print(f"elastic seed {seed}: no attempt completed "
                      f"(last rc={rc})")
                print(open(os.path.join(wd, "child.log")).read()[-2000:])
                record(seed=seed, outcome="never-completed", exit=rc)
                ok_all = False
                continue

            # 3. convergence against the fixed-mesh reference
            try:
                gol_ref = np.load(os.path.join(ref, "gol_final.npy"))
                gol_got = np.load(os.path.join(wd, "gol_final.npy"))
                np.testing.assert_array_equal(gol_got, gol_ref)
                adv_ref = np.load(os.path.join(ref, "adv_final.npy"))
                adv_got = np.load(os.path.join(wd, "adv_final.npy"))
                np.testing.assert_allclose(adv_got, adv_ref,
                                           rtol=1e-11, atol=0)
            except AssertionError as e:
                print(f"elastic seed {seed}: DIVERGED from fixed-mesh "
                      f"reference: {str(e)[:300]}")
                record(seed=seed, outcome="diverged")
                ok_all = False
                continue

            # 4. fresh-process warm-start proof on the held signature
            proofs = []
            proof_ok = True
            for i in range(2):
                out = os.path.join(wd, f"proof_{i}.json")
                p, log = launch(
                    PROOF_CHILD, [wd, nd, out],
                    {"DCCRG_COMPILE_CACHE_DIR": cache_dir},
                    log_name=f"proof_{i}.log",
                )
                prc = p.wait()
                log.close()
                if prc != 0:
                    print(f"elastic seed {seed}: proof child {i} rc={prc}")
                    print(open(os.path.join(
                        wd, f"proof_{i}.log")).read()[-1500:])
                    proof_ok = False
                    break
                with open(out) as f:
                    proofs.append(json.load(f))
            if proof_ok:
                a, b = proofs
                if b["signature"] != a["signature"]:
                    print(f"elastic seed {seed}: warm-start signature "
                          f"drifted: {a['signature']} -> {b['signature']}")
                    proof_ok = False
                elif b["recompiles"] != 0 or b["warm_compiles"] == 0:
                    print(f"elastic seed {seed}: warm start NOT warm: "
                          f"recompiles={b['recompiles']} "
                          f"warm={b['warm_compiles']} "
                          f"cache={b['persistent_cache']}")
                    proof_ok = False
            record(seed=seed,
                   outcome="ok" if proof_ok else "warm-start-failed",
                   attempts=attempt + 1, proofs=proofs)
            if not proof_ok:
                ok_all = False
                continue
            print(f"elastic seed {seed}: OK after {attempt + 1} "
                  f"attempt(s); warm start recompiles=0 "
                  f"(warm_compiles={proofs[1]['warm_compiles']})")
        finally:
            shutil.rmtree(tmp, ignore_errors=True)
    if stream is not None:
        stream.stop(final=True)
    print(f"{'elastic':12s} [{lo},{hi}): {'OK' if ok_all else 'FAIL'}")
    return ok_all


#: the fleet soak's solo-replay oracle: computes every scenario's
#: uninterrupted single-member reference result (the bytes the fleet —
#: kills, redispatches and all — must reproduce), then pre-compiles the
#: cohort widths a 2-worker fleet can reach into the shared persistent
#: cache (redispatch piles members onto survivors, so replacement
#: cohorts are WIDER than the solo pass — warming widths 2 and 4 now is
#: what makes ``epoch.recompiles == 0`` across the whole fleet a
#: deterministic assertion, not a scheduling accident)
FLEET_SOLO_CHILD = r"""import sys
sys.path.insert(0, __DCCRG_ROOT__)
import json
import os

specs_path, refdir, n_devices = sys.argv[2], sys.argv[3], int(sys.argv[4])
import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

from dccrg_tpu.serve.ensemble import Ensemble
from dccrg_tpu.serve.worker import build_scenario, park_state

with open(specs_path) as f:
    specs = json.load(f)
os.makedirs(refdir, exist_ok=True)
ens = Ensemble()
# 1. the oracle: one member at a time, no chunking, no cohort peers
for spec in specs:
    b = build_scenario(spec, n_devices)
    t = ens.submit(b["model"], b["state"], steps=int(spec["steps"]),
                   dt=b["dt"])
    ens.run()
    park_state(b, t.result,
               os.path.join(refdir, "result_%s.npz" % spec["sid"]),
               int(spec["steps"]))
# 2. warm the wider cohort bodies into the shared persistent cache
widths = {"gol": (2, 4), "advection": (2,)}
for kind, ws in widths.items():
    ks = [s for s in specs if s.get("model", "gol") == kind]
    if not ks:
        continue
    for width in ws:
        for i in range(width):
            b = build_scenario(ks[i % len(ks)], n_devices)
            ens.submit(b["model"], b["state"], steps=4, dt=b["dt"])
        ens.run()
print("SOLO REFS OK", len(specs))
"""


#: one killable gateway incarnation: real worker subprocesses, a real
#: journal, seeded mid-run worker SIGKILLs.  The parent SIGKILLs the
#: whole incarnation once real progress is journaled and launches a
#: second one over the SAME journal — durability is proven by the
#: second incarnation replaying the first's watermarks and finishing
#: the fleet to the oracle's bytes
FLEET_GATEWAY_CHILD = r"""import sys
sys.path.insert(0, __DCCRG_ROOT__)
import json
import os
import random
import time

wd, specs_path = sys.argv[1], sys.argv[2]
n_workers, n_devices = int(sys.argv[3]), int(sys.argv[4])
seed, n_kills = int(sys.argv[5]), int(sys.argv[6])
done_path = sys.argv[7]

from dccrg_tpu import obs
from dccrg_tpu.obs.flightrec import recorder as flightrec
from dccrg_tpu.obs.registry import metrics
from dccrg_tpu.serve import Gateway, WorkerHandle

metrics.enabled = True
obs.stream_to(os.path.join(wd, "gateway.stream.jsonl"), period=1.0,
              truncate=True, extra={"role": "gateway"})
flightrec.arm(wd, period=1.0)

workers = [WorkerHandle("w%d" % i, os.path.join(wd, "w%d" % i), n_devices)
           for i in range(n_workers)]
for w in workers:
    w.start()
gw = Gateway(os.path.join(wd, "journal.jsonl"), workers)
with open(specs_path) as f:
    for spec in json.load(f):
        ok, why = gw.submit(spec)   # idempotent across incarnations
        if not ok:
            print("REJECTED", spec["sid"], why, flush=True)

rng = random.Random(seed * 7919 + n_kills)
kills, last_kill_tick, ticks = 0, -10**9, 0
deadline = time.monotonic() + 540.0
while True:
    st = gw.tick(restart_lost=True)
    ticks += 1
    if ticks % 40 == 0:
        gw.journal.checkpoint()
    # kill only after THIS incarnation has seen live watermark progress
    # (gw._last_wm is incarnation-local), and only a victim with > 2
    # chunks of work left — the redispatch must move real work, and the
    # scenario must not retire in the race between kill and detection
    if kills < n_kills and ticks - last_kill_tick > 60 and gw._last_wm:
        def _meaty(w):
            if w.lost or not w.alive():
                return False
            for sid in gw.journal.in_flight(w.wid):
                done = gw.journal.watermark.get(sid, {}).get("step", 0)
                if int(gw.journal.accepted[sid].get("steps", 0)) - done > 8:
                    return True
            return False
        victims = sorted((w for w in workers if _meaty(w)),
                         key=lambda w: w.wid)
        if victims:
            v = rng.choice(victims)
            print("KILLING", v.wid, "generation", v.generation, flush=True)
            v.kill()   # SIGKILL: next tick detects, redispatches, restarts
            kills += 1
            last_kill_tick = ticks
    if st["outstanding"] == 0:
        break
    if time.monotonic() > deadline:
        print("FLEET GATEWAY TIMEOUT", st, flush=True)
        gw.close()
        sys.exit(3)
    time.sleep(0.05)
gw.journal.checkpoint()
rep = metrics.report()["counters"]
state = {
    "accepted": sorted(gw.journal.accepted),
    "retired": sorted(gw.journal.retired),
    "rejected": gw.journal.rejected,
    "kills": kills,
    "generations": {w.wid: w.generation for w in workers},
    "redispatches": gw.redispatches,
    "counters": {k: v for k, v in rep.items() if k.startswith("gateway.")},
}
tmp = done_path + ".tmp"
with open(tmp, "w") as f:
    json.dump(state, f, sort_keys=True, indent=1)
os.replace(tmp, done_path)
gw.drain(timeout_s=30.0)   # SIGTERM drain: final heartbeats flush
gw.close()
print("FLEET DRAINED", len(state["retired"]), "retired", flush=True)
"""


def _fleet_specs(seed: int) -> list:
    """The per-seed fleet workload: mixed signatures so routing
    affinity and redispatch both cross model boundaries."""
    specs = [{"sid": f"g{i}", "model": "gol", "n": 8,
              "seed": seed * 100 + i, "steps": 48, "tenant": "fleet"}
             for i in range(4)]
    specs += [{"sid": f"a{i}", "model": "advection", "n": 4,
               "seed": seed * 100 + 50 + i, "steps": 48,
               "tenant": "fleet"} for i in range(2)]
    return specs


def _fleet_admission_ab(record, n_devices: int = 4) -> bool:
    """The enforced-admission starvation A/B (ISSUE 19): with the
    policy ON a burst tenant whose predicted queue wait blows its
    budget is rejected at the door, so the deadline tenant's miss rate
    stays zero; with ``DCCRG_GATEWAY_ADMISSION=0`` the same burst is
    admitted, the deadline tenant queues behind one enormous chunk
    round, and its deadline verdict flips to a miss.  Runs one real
    worker in each mode; both runs warm the service-rate window (and
    the shared compile cache) first so the prediction prices stepping,
    not compiles."""
    import os
    import shutil
    import tempfile
    import time

    from dccrg_tpu.obs.registry import metrics
    from dccrg_tpu.serve import Gateway, WorkerHandle

    tmp = tempfile.mkdtemp(prefix="dccrg_fleet_ab_")
    chunk = 20000            # one OFF-mode burst round: minutes of steps
    burst_steps = 2 * chunk
    dl_deadline, burst_deadline = 5.0, 2.0
    keys = ("DCCRG_GATEWAY_ADMISSION", "DCCRG_GATEWAY_PARK_EVERY",
            "DCCRG_GATEWAY_STALL_S", "DCCRG_GATEWAY_QUEUE_MAX",
            "DCCRG_SLO_QUEUE_S", "DCCRG_COMPILE_CACHE_DIR")
    saved = {k: os.environ.get(k) for k in keys}
    os.environ["DCCRG_GATEWAY_PARK_EVERY"] = str(chunk)
    os.environ["DCCRG_GATEWAY_STALL_S"] = "600"
    os.environ["DCCRG_GATEWAY_QUEUE_MAX"] = "64"
    os.environ.pop("DCCRG_SLO_QUEUE_S", None)
    os.environ["DCCRG_COMPILE_CACHE_DIR"] = os.path.join(tmp, "cache")
    metrics.enabled = True

    def tenant_count(name, tenant):
        rep = metrics.report()["counters"].get(name, {})
        return sum(v for k, v in rep.items() if k == f"tenant={tenant}")

    def one_run(tag, admission):
        os.environ["DCCRG_GATEWAY_ADMISSION"] = "1" if admission else "0"
        wd = os.path.join(tmp, tag)
        w = WorkerHandle("w0", os.path.join(wd, "w0"), n_devices)
        w.start()
        gw = Gateway(os.path.join(wd, "journal.jsonl"), [w])

        def drive(pending, budget_s):
            deadline = time.monotonic() + budget_s
            while set(pending) - gw.journal.retired:
                gw.tick()
                if time.monotonic() > deadline:
                    return False
                time.sleep(0.05)
            return True

        try:
            # arm both tenants' service rates on real retirements; the
            # dl warmup is long enough that its measured rate reflects
            # stepping throughput, not the one-off compile wall
            gw.submit({"sid": "warm-b", "model": "advection", "n": 4,
                       "seed": 7, "steps": 4000, "tenant": "burst"})
            gw.submit({"sid": "warm-d", "model": "gol", "n": 8,
                       "seed": 7, "steps": 2000, "tenant": "dl"})
            if not drive(["warm-b", "warm-d"], 420.0):
                print("fleet A/B: warmup never retired")
                return None
            rejected = 0
            for i in range(4):
                ok, _ = gw.submit({"sid": f"b{i}", "model": "advection",
                                   "n": 4, "seed": 100 + i,
                                   "steps": burst_steps, "tenant": "burst",
                                   "deadline_s": burst_deadline})
                rejected += 0 if ok else 1
            ok, why = gw.submit({"sid": "dl0", "model": "gol", "n": 8,
                                 "seed": 9, "steps": 8, "tenant": "dl",
                                 "deadline_s": dl_deadline})
            if not ok:
                print(f"fleet A/B ({tag}): deadline tenant rejected "
                      f"({why}) — it must always be admitted")
                return None
            miss0 = tenant_count("gateway.deadline_miss", "dl")
            ok0 = tenant_count("gateway.deadline_ok", "dl")
            if not drive(["dl0"], 420.0):
                print(f"fleet A/B ({tag}): deadline tenant never retired")
                return None
            return {
                "rejected": rejected,
                "miss": tenant_count("gateway.deadline_miss", "dl") - miss0,
                "ok": tenant_count("gateway.deadline_ok", "dl") - ok0,
            }
        finally:
            gw.close()   # abandoned burst members die with the worker

    try:
        on = one_run("on", True)
        off = one_run("off", False)
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        shutil.rmtree(tmp, ignore_errors=True)
    record(phase="admission-ab", on=on, off=off)
    ok_all = True
    if on is None or off is None:
        print("fleet A/B: a mode failed to complete")
        return False
    if on["rejected"] < 1:
        print(f"fleet A/B: policy ON admitted the whole burst "
              f"({on}) — admission is not enforcing")
        ok_all = False
    if on["miss"] != 0 or on["ok"] != 1:
        print(f"fleet A/B: deadline tenant missed under policy ON "
              f"({on}) — the burst starved it despite admission")
        ok_all = False
    if off["rejected"] != 0:
        print(f"fleet A/B: DCCRG_GATEWAY_ADMISSION=0 rejected "
              f"submissions ({off}) — the A/B baseline is not off")
        ok_all = False
    if off["miss"] < 1:
        print(f"fleet A/B: deadline tenant met its deadline under the "
              f"unthrottled burst ({off}) — starvation did not "
              "reproduce; the A/B proves nothing")
        ok_all = False
    print(f"fleet A/B: ON rejected={on['rejected']} dl_miss={on['miss']}"
          f" | OFF rejected={off['rejected']} dl_miss={off['miss']}")
    return ok_all


def run_fleet(lo: int, hi: int, stream_dir: str | None = None,
              n_workers: int = 2, n_devices: int = 4) -> bool:
    """The fault-tolerant fleet gateway proof harness (ISSUE 19).
    Per seed:

    1. a solo-replay oracle child computes every scenario's
       uninterrupted reference bytes and pre-warms the shared compile
       cache across cohort widths;
    2. a gateway child runs N supervised workers over a crash-durable
       journal; it SIGKILLs one worker mid-flight (seeded), and the
       PARENT SIGKILLs the whole gateway once real progress is
       journaled — then relaunches it over the same journal, where a
       second seeded worker kill lands during the replayed run;
    3. every accepted scenario must retire EXACTLY once (journal
       dedupe across kills, zombies and both incarnations), and every
       result — including redispatched members — must match the oracle
       (GoL bit-exact, advection to the 1e-11 cross-layout tolerance);
    4. the loss postmortem: a schema-valid flight-recorder dump naming
       the killed worker; replacements must be WARM
       (``epoch.recompiles == 0`` in every worker's final stream);
    5. the fleet p99 comes from merging the per-worker histogram
       exports (``obs.slo.merge_series`` over the worker streams).

    After the seed loop, one enforced-admission starvation A/B
    (:func:`_fleet_admission_ab`)."""
    import glob as _glob
    import json
    import os
    import shutil
    import tempfile
    import time

    import numpy as np

    if str(ROOT) not in sys.path:
        sys.path.insert(0, str(ROOT))
    from dccrg_tpu.obs import slo as obs_slo
    from dccrg_tpu.obs.flightrec import validate_flightrec
    from dccrg_tpu.obs.stream import TelemetryStream

    stream = None
    if stream_dir:
        os.makedirs(stream_dir, exist_ok=True)
        stream = TelemetryStream(
            os.path.join(stream_dir, f"fleet_{lo}_{hi}.jsonl"),
            truncate=True,
            extra={"subsystem": "fleet", "seeds": [lo, hi]},
        )

    def record(**kw):
        if stream is not None:
            stream.write_snapshot(**kw)

    def launch(body, argv, env_extra=None, log_name="child.log"):
        env = dict(os.environ)
        env.pop("DCCRG_FAULT", None)
        env.update(env_extra or {})
        log = open(os.path.join(argv[0], log_name), "a")
        p = subprocess.Popen(
            [sys.executable, "-c",
             body.replace("__DCCRG_ROOT__", repr(str(ROOT)))]
            + [str(a) for a in argv],
            cwd=str(ROOT), stdout=log, stderr=subprocess.STDOUT, env=env,
        )
        return p, log

    def wait_for(p, timeout):
        t0 = time.monotonic()
        while p.poll() is None:
            if time.monotonic() - t0 > timeout:
                p.kill()
                p.wait()
                return None
            time.sleep(0.25)
        return p.returncode

    ok_all = True
    for seed in range(lo, hi):
        tmp = tempfile.mkdtemp(prefix=f"dccrg_fleet_{seed}_")
        try:
            specs = _fleet_specs(seed)
            sids = [s["sid"] for s in specs]
            specs_path = os.path.join(tmp, "specs.json")
            with open(specs_path, "w") as f:
                json.dump(specs, f)
            env = {
                "DCCRG_COMPILE_CACHE_DIR": os.path.join(tmp, "cache"),
                "XLA_FLAGS":
                    f"--xla_force_host_platform_device_count={n_devices}",
                "JAX_PLATFORMS": "cpu",
                "JAX_ENABLE_X64": "1",
                "DCCRG_GATEWAY_PARK_EVERY": "4",
                "DCCRG_GATEWAY_STALL_S": "120",
                "DCCRG_GATEWAY_QUEUE_MAX": "64",
                "DCCRG_GATEWAY_ADMISSION": "1",
                "DCCRG_SLO_QUEUE_S": "",   # falsy: no ambient budget
            }

            # 1. the solo-replay oracle (+ cohort-width cache warmer)
            refdir = os.path.join(tmp, "ref")
            os.makedirs(refdir)
            p, log = launch(FLEET_SOLO_CHILD,
                            [tmp, specs_path, refdir, n_devices],
                            env, log_name="solo.log")
            rc = wait_for(p, 420.0)
            log.close()
            if rc != 0:
                print(f"fleet seed {seed}: solo oracle failed rc={rc}")
                print(open(os.path.join(tmp, "solo.log")).read()[-2000:])
                record(seed=seed, outcome="oracle-failed", exit=rc)
                ok_all = False
                continue

            # 2a. gateway incarnation 0: one seeded worker SIGKILL; the
            #     parent SIGKILLs the incarnation once a watermark is
            #     journaled (fsync'd appends make the cut byte-exact)
            wd = os.path.join(tmp, "fleet")
            os.makedirs(wd)
            done_path = os.path.join(wd, "done.json")
            p, log = launch(
                FLEET_GATEWAY_CHILD,
                [wd, specs_path, n_workers, n_devices, seed, 1,
                 done_path],
                env, log_name="gateway_0.log")
            journal = os.path.join(wd, "journal.jsonl")
            snap = journal + ".snap.json"
            def journaled_progress():
                """True once a real watermark is durable — in the WAL
                (record form) or compacted into the snapshot state."""
                try:
                    with open(journal, "rb") as f:
                        if b'"ev":"watermark"' in f.read():
                            return True
                except OSError:
                    pass
                try:
                    with open(snap) as f:
                        state = (json.load(f).get("state") or {})
                    return bool(state.get("watermark"))
                except (OSError, ValueError):
                    return False

            killed_gw = False
            t0 = time.monotonic()
            while p.poll() is None and time.monotonic() - t0 < 300.0:
                if journaled_progress():
                    time.sleep(0.2 + (seed % 5) * 0.3)
                    p.kill()
                    p.wait()
                    killed_gw = True
                    break
                time.sleep(0.25)
            log.close()
            record(seed=seed, phase="gateway-sigkill", killed=killed_gw)
            if not killed_gw:
                rc = wait_for(p, 60.0)
                print(f"fleet seed {seed}: no watermark journaled in "
                      f"300s (gateway rc={rc}) — nothing to replay")
                print(open(os.path.join(
                    wd, "gateway_0.log")).read()[-2000:])
                record(seed=seed, outcome="no-progress", exit=rc)
                ok_all = False
                continue

            # 2b. incarnation 1 over the SAME journal: replay, resume,
            #     one more seeded worker kill, drain to completion
            p, log = launch(
                FLEET_GATEWAY_CHILD,
                [wd, specs_path, n_workers, n_devices, seed + 1, 1,
                 done_path],
                env, log_name="gateway_1.log")
            rc = wait_for(p, 600.0)
            log.close()
            if rc != 0:
                print(f"fleet seed {seed}: relaunched gateway failed "
                      f"rc={rc}")
                print(open(os.path.join(
                    wd, "gateway_1.log")).read()[-3000:])
                record(seed=seed, outcome="relaunch-failed", exit=rc)
                ok_all = False
                continue
            with open(done_path) as f:
                done = json.load(f)

            def ctr(name):
                return sum((done["counters"].get(name) or {}).values())

            fails = []
            # 3a. exactly-once retirement across both incarnations
            if set(done["accepted"]) != set(sids):
                fails.append(f"accepted {done['accepted']} != "
                             f"submitted {sids}")
            if set(done["retired"]) != set(sids):
                fails.append(f"retired {done['retired']} != "
                             f"submitted {sids}")
            if ctr("gateway.journal_replays") < 1:
                fails.append("relaunched gateway never replayed the "
                             "journal")
            if ctr("gateway.worker_lost") < 1:
                fails.append("incarnation 1's seeded kill counted no "
                             "gateway.worker_lost")
            if ctr("gateway.redispatched") < 1:
                fails.append("worker loss moved no in-flight work "
                             "(gateway.redispatched == 0)")
            # 3b. every result (original, redispatched, zombie
            #     duplicate) byte-compares against the oracle
            for spec in specs:
                sid = spec["sid"]
                ref = os.path.join(refdir, f"result_{sid}.npz")
                outs = sorted(_glob.glob(os.path.join(
                    wd, "w*", f"result_{sid}.npz")))
                if not outs:
                    fails.append(f"{sid}: retired but no worker holds "
                                 "its result park")
                    continue
                with np.load(ref) as z:
                    want = {k: np.asarray(z[k]) for k in z.files}
                for out in outs:
                    with np.load(out) as z:
                        got = {k: np.asarray(z[k]) for k in z.files}
                    try:
                        if spec["model"] == "gol":
                            np.testing.assert_array_equal(
                                got["alive"], want["alive"])
                        else:
                            for field in ("density", "vx", "vy", "vz"):
                                np.testing.assert_allclose(
                                    got[field], want[field],
                                    rtol=1e-11, atol=0)
                    except AssertionError as e:
                        fails.append(f"{sid}: {os.path.basename(out)} "
                                     f"diverged from the solo oracle: "
                                     f"{str(e)[:200]}")
            # 4a. the loss postmortem names a killed worker
            dumps = _glob.glob(os.path.join(wd, "flightrec_*.json"))
            named = False
            for dump in dumps:
                probs = validate_flightrec(dump)
                if probs:
                    fails.append(f"{os.path.basename(dump)}: {probs[0]}")
                    continue
                with open(dump) as f:
                    rec = json.load(f)
                named = named or any(
                    ev.get("kind") == "worker.lost" and ev.get("worker")
                    for ev in rec.get("events", []))
            if not named:
                fails.append("no flight-recorder dump names a lost "
                             f"worker ({len(dumps)} dumps)")
            # 4b. warm fleet: the oracle pre-warmed every cohort width,
            #     so NO worker incarnation — replacements included —
            #     may recompile; final streams are the evidence
            reports = []
            for wdir in sorted(_glob.glob(os.path.join(wd, "w*"))):
                spath = os.path.join(wdir, "worker.stream.jsonl")
                try:
                    rep = obs_slo.load_report(spath)
                except (OSError, ValueError):
                    continue   # a worker that never snapshotted
                reports.append(rep)
                ctrs = rep.get("counters") or {}
                recompiles = sum(
                    (ctrs.get("epoch.recompiles") or {}).values())
                warm = sum(
                    (ctrs.get("epoch.warm_compiles") or {}).values())
                if recompiles:
                    fails.append(
                        f"{os.path.basename(wdir)}: replacement NOT "
                        f"warm: epoch.recompiles={recompiles} "
                        f"(warm_compiles={warm})")
            if max(done["generations"].values() or [0]) < 2:
                fails.append("no worker was ever replaced (generations "
                             f"{done['generations']})")
            # 5. fleet p99 from the merged per-worker histogram exports
            series = obs_slo.merge_series(reports, "ensemble.e2e_s")
            merged = obs_slo.merge(*series.values())
            p99 = obs_slo.quantile(merged, 0.99)
            if p99 is None:
                fails.append("merged worker streams yield no "
                             "ensemble.e2e_s histogram — no fleet p99")
            for msg in fails:
                print(f"fleet seed {seed}: {msg}")
            outcome = "ok" if not fails else "failed"
            record(seed=seed, outcome=outcome, retired=len(done["retired"]),
                   kills=done["kills"], generations=done["generations"],
                   redispatches=len(done["redispatches"]),
                   fleet_p99_s=p99, failures=fails)
            if fails:
                ok_all = False
                continue
            print(f"fleet seed {seed}: OK — {len(done['retired'])} "
                  f"retired exactly once across a gateway SIGKILL and "
                  f"{done['kills'] + 1} worker kills; fleet p99="
                  f"{p99:.3f}s from {len(reports)} merged worker "
                  "streams")
        finally:
            shutil.rmtree(tmp, ignore_errors=True)

    ab_ok = _fleet_admission_ab(record)
    ok_all = ok_all and ab_ok
    if stream is not None:
        stream.stop(final=True)
    print(f"{'fleet':12s} [{lo},{hi}): {'OK' if ok_all else 'FAIL'}")
    return ok_all


#: prepended to every child body when streaming is on: appends an
#: incremental registry snapshot as JSONL every few seconds (plus a
#: final one at exit), so a hung or killed seed leaves the phase
#: evidence of everything it exercised (epoch builds, halo traffic,
#: AMR commits) behind for post-mortem — schema-gated by
#: ``tools/check_telemetry.py --validate-stream``
STREAM_PRELUDE = """\
import sys as _sys
_sys.path.insert(0, %r)
try:
    from dccrg_tpu import obs as _obs
    _obs.stream_to(%r, period=%r, truncate=True,
                   extra={"subsystem": %r, "seeds": %r})
    # timeline export at exit: the per-process half of the fleet trace
    # (origin_unix_s anchors the post-run merge on a shared epoch-zero)
    import atexit as _atexit
    _atexit.register(lambda: _obs.export_chrome_trace(%r))
except Exception as _e:  # telemetry must never break the fuzz
    print("soak stream unavailable:", _e)
"""


#: every body pins an 8-device virtual CPU mesh via the new-jax config
#: knob; old jax (0.4.x) lacks it — swap in the XLA_FLAGS spelling
#: before the backend initializes (the utils/compat.py bridge, applied
#: at the driver so the bodies stay on the current-jax vocabulary)
_NUM_DEVICES_LINE = "jax.config.update('jax_num_cpu_devices', 8)\n"
_NUM_DEVICES_COMPAT = """\
try:
    jax.config.update('jax_num_cpu_devices', 8)
except AttributeError:   # old jax: pre-init XLA_FLAGS is the only knob
    import os as _os
    if 'xla_force_host_platform_device_count' not in _os.environ.get('XLA_FLAGS', ''):
        _os.environ['XLA_FLAGS'] = (_os.environ.get('XLA_FLAGS', '')
            + ' --xla_force_host_platform_device_count=8').strip()
"""


def run(name: str, lo: int, hi: int, stream_dir: str | None = None) -> bool:
    code = BODIES[name].replace(_NUM_DEVICES_LINE, _NUM_DEVICES_COMPAT)
    if stream_dir:
        import os

        os.makedirs(stream_dir, exist_ok=True)
        spath = os.path.join(stream_dir, f"{name}_{lo}_{hi}.jsonl")
        tpath = os.path.join(stream_dir, f"{name}_{lo}_{hi}.trace.json")
        code = STREAM_PRELUDE % (
            str(ROOT), spath, 5.0, name, [lo, hi], tpath,
        ) + code
    r = subprocess.run(
        [sys.executable, "-c", code, str(lo), str(hi)],
        cwd=str(ROOT),
        text=True,
        capture_output=True,
    )
    ok = r.returncode == 0
    # on success show the body's own stdout marker — stderr may end with
    # benign XLA advisories (slow constant folding etc.) that would make
    # an OK line read like a failure
    src = r.stdout if ok else (r.stdout + r.stderr)
    tail = src.strip().splitlines()[-1:] or [""]
    print(f"{name:12s} [{lo},{hi}): {'OK' if ok else 'FAIL'}  {tail[0][:90]}")
    if not ok:
        print(r.stdout[-2000:])
        print(r.stderr[-2000:])
    return ok


def merge_fleet(stream_dir: str) -> str | None:
    """Post-run step: unify every per-process timeline export under
    ``stream_dir`` (battery runs + salvaged crash children) into ONE
    fleet trace on their shared epoch-zero (``obs.merge_chrome_traces``
    aligns on each trace's ``origin_unix_s``).  Returns the fleet trace
    path, or None when no child exported a timeline."""
    import glob as _glob
    import os

    traces = sorted(_glob.glob(os.path.join(stream_dir, "*.trace.json")))
    if not traces:
        return None
    sys.path.insert(0, str(ROOT))
    try:
        from dccrg_tpu.obs.merge import merge_chrome_traces

        out = os.path.join(stream_dir, "fleet_trace.json")
        fleet = merge_chrome_traces(traces, out_path=out)
        print(f"fleet trace: {len(fleet['traceEvents'])} events from "
              f"{len(traces)} process timelines -> {out}")
        return out
    except Exception as e:  # noqa: BLE001 — telemetry never fails the soak
        print(f"fleet merge unavailable: {e}")
        return None


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("subsystem",
                    choices=list(BODIES) + ["crash", "elastic", "fleet",
                                            "all"])
    ap.add_argument("--seeds", type=int, nargs=2, default=(0, 10))
    ap.add_argument("--crash-seeds", type=int, nargs=2, default=None,
                    help="seed range for the crash subsystem under "
                         "'all' (default: first 3 of --seeds; each "
                         "crash seed launches several child processes)")
    ap.add_argument("--stream-dir",
                    default=str(ROOT / "tools" / "soak_stream"),
                    help="per-subsystem incremental telemetry JSONL "
                         "streams land here (one file per run)")
    ap.add_argument("--no-stream", action="store_true",
                    help="disable the incremental telemetry streams")
    a = ap.parse_args()
    names = list(BODIES) if a.subsystem == "all" else [a.subsystem]
    sdir = None if a.no_stream else a.stream_dir
    results = []
    if a.subsystem == "crash":
        results.append(run_crash(*a.seeds, stream_dir=sdir))
    elif a.subsystem == "elastic":
        results.append(run_elastic(*a.seeds, stream_dir=sdir))
    elif a.subsystem == "fleet":
        results.append(run_fleet(*a.seeds, stream_dir=sdir))
    else:
        results += [run(n, *a.seeds, stream_dir=sdir)
                    for n in names if n != "crash"]
        if a.subsystem == "all":
            lo, hi = a.crash_seeds or (a.seeds[0],
                                       min(a.seeds[0] + 3, a.seeds[1]))
            results.append(run_crash(lo, hi, stream_dir=sdir))
            results.append(run_elastic(lo, hi, stream_dir=sdir))
            results.append(run_fleet(lo, hi, stream_dir=sdir))
    if sdir:
        merge_fleet(sdir)
    sys.exit(0 if all(results) else 1)


if __name__ == "__main__":
    main()
