#!/usr/bin/env python
"""Microbenchmark the whole-run flat-AMR Pallas kernel in isolation.

Times ``ops/flat_amr.make_flat_amr_run`` on synthetic weight tables at a
sweep of voxel-grid shapes, to separate intrinsic kernel throughput from
grid effects — in particular the lane-alignment question: the TPU vector
lane width is 128, so an x extent of 96 forces Mosaic to pad and to lower
the x rolls as unaligned cross-lane shuffles, while 128 is native.

Run on the real chip (no env overrides):  python tools/flat_kernel_bench.py
"""
import pathlib
import sys
import time
import statistics

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

import jax
import jax.numpy as jnp
import numpy as np

from dccrg_tpu.ops.flat_amr import make_flat_amr_run

SHAPES = [
    (96, 96, 96),      # the r02 refined-bench voxel grid (48^3 coarse)
    (96, 96, 96, 128),  # same grid, lane-padded (explicit wrap halos)
    (96, 96, 128),     # x lane-aligned, same order of voxels
    (64, 96, 128),     # x aligned, shallower z
    (64, 128, 128),    # the dense headline kernel's block shape
    (128, 128, 128),   # aligned, 2.1M voxels
]
STEPS = 1000
REPS = 5


def bench(nz1, ny1, nx1, nx_pad=None):
    n_vox = nz1 * ny1 * nx1
    rng = np.random.default_rng(0)
    kern = make_flat_amr_run(nz1, ny1, nx1, nx_pad=nx_pad)
    shape = (nz1, ny1, nx1)
    V = jnp.asarray(rng.random(shape), jnp.float32)
    # synthetic but structurally faithful weights: small CFL-scale values,
    # coarse blocks on one octant
    w = [jnp.asarray(rng.random(shape) * 1e-3, jnp.float32) for _ in range(6)]
    fine = np.zeros(shape, np.bool_)
    fine[: nz1 // 2, : ny1 // 2, : nx1 // 2] = True
    updf = jnp.asarray(fine / 1.0, jnp.float32)
    updc = jnp.asarray((~fine) / 8.0, jnp.float32)
    dt = jnp.float32(1.0)

    out = kern(V, *w, updf, updc, dt, 2)
    jax.block_until_ready(out)
    times = []
    for _ in range(REPS):
        t0 = time.perf_counter()
        out = kern(V, *w, updf, updc, dt, STEPS)
        jax.block_until_ready(out)
        times.append(time.perf_counter() - t0)
    med = statistics.median(times)
    rate = n_vox * STEPS / med
    pad = f" nx_pad={nx_pad}" if nx_pad else ""
    print(
        f"shape=({nz1},{ny1},{nx1}){pad} n_vox={n_vox} "
        f"med={med:.4f}s rate={rate/1e9:.2f} B voxel-updates/s "
        f"times={[round(t, 4) for t in times]}"
    )
    return rate


def main():
    print("platform:", jax.devices()[0].platform, jax.devices()[0].device_kind)
    for shape in SHAPES:
        try:
            bench(*shape)
        except Exception as e:  # noqa: BLE001 - keep sweeping
            print(f"shape={shape} FAILED: {str(e)[-200:]}")


if __name__ == "__main__":
    main()
