#!/usr/bin/env python
"""Telemetry gate: run a tiny advection workload and verify the obs
subsystem end to end.

Checks (exit 1 on any failure):

* every instrumented phase fires — ``halo.exchange``, ``epoch.build``,
  ``loadbalance.migrate``, ``amr.refine``, ``checkpoint.write`` — with
  nonzero counts, and the byte counters carry nonzero values where the
  workload exercises them;
* the report exports to ``telemetry.json`` (path via ``--out``) and the
  file round-trips through ``json.load``;
* the streaming exporter leaves a schema-valid JSONL file next to it
  (``<out>.stream.jsonl``: every line a complete snapshot, ``seq``
  strictly increasing, ``ts`` non-decreasing, counters monotonic) and
  the event timeline exports a valid Chrome trace
  (``<out>.trace.json``: matched begin/end pairs, monotonic in-thread
  timestamps) — :func:`validate_stream` / :func:`validate_chrome_trace`
  are also importable and runnable standalone on any such file
  (``--validate-stream`` / ``--validate-trace``);
* a forced injection round (ISSUE 4): a bit-flipped lineage generation
  must be detected by its payload CRC and skipped back to the clean
  one, and an injected ``p2p.recv`` fault must drive the retry plane —
  the probe fails unless ``resilience.injected``,
  ``checkpoint.crc_failures``, ``lineage.generations_skipped`` and
  ``p2p.retries`` all recorded;
* a profiled round (ISSUE 6): one split-phase drive captured under
  ``jax.profiler`` must produce the measured device-timeline plane —
  ``overlap.fraction{phase=halo}`` in (0, 1], per-device busy gauges,
  kernel attribution intersecting ``epoch.recompiles``, and a
  schema-valid merged trace (``<out>.merged_trace.json``, also checkable
  standalone via ``--validate-merged-trace``); captures with no
  execution lines (deviceless backends, ``DCCRG_XPLANE=0``) are the
  documented no-op;
* a halo-backend round (ISSUE 7): a forced ``DCCRG_HALO_BACKEND=pallas``
  + ``DCCRG_HALO_VERIFY=1`` grid runs blocking and split exchanges
  through the async-DMA ring bodies (interpreted on CPU) and must leave
  ``halo.verify_checks`` with zero ``halo.verify_mismatches``; the
  profiled round additionally drives the fused split-phase advection and
  vlasov steps and requires their per-model
  ``overlap.fraction{model=..., phase=halo}`` gauges;
* an elastic round (ISSUE 8): one forced rescale down AND up through a
  checkpoint lineage (payload bit-identical both ways, the
  ``elastic.rescale`` phase + ``elastic.rescales{direction}`` counters
  required) plus a driven watchdog escalation over a synthetic stalled
  heartbeat (warn → rescale-down → restart in order, leaving
  ``supervisor.warnings`` / ``supervisor.escalations`` /
  ``elastic.degraded``);
* an SLO round (ISSUE 10): a deadline-mixed ensemble round must leave
  the request-latency histograms (``ensemble.queue_wait_s`` /
  ``ensemble.service_s`` / ``ensemble.e2e_s``) with sane quantile
  ordering (p50 <= p95 <= p99 recovered from the exported buckets),
  exact ``ensemble.deadline_miss`` counts and request lifecycle spans;
  a forced supervisor escalation with the flight recorder armed must
  produce exactly ONE schema-valid postmortem dump naming the round's
  requests (``obs.validate_flightrec``); the overhead budget below runs
  with the whole request plane on;
* a fleet round (ISSUE 19): two real worker subprocesses on 4-device
  mesh slices behind an in-process gateway; one worker is SIGKILLed
  after it starts stepping and its in-flight scenarios must redispatch
  to the survivor with every accepted scenario retiring EXACTLY once
  (one redispatched member byte-compared against uninterrupted solo
  stepping), one overflow submission must be rejected at the pinned
  queue bound, the loss must leave exactly ONE schema-valid postmortem
  naming the dead worker, and a journal reopen must replay the retired
  state (``gateway.{accepted,rejected,redispatched,journal_replays}``
  all required nonzero);
* side artifacts (``<out>.stream.jsonl`` / ``.trace.json`` /
  ``.merged_trace.json``) land next to ``--out`` — or under ``tools/``
  when ``--out`` is the repo root's ``telemetry.json``, keeping bench
  byproducts out of the root (``--artifact-dir`` overrides);
* unless ``--skip-overhead``: enabling telemetry must not slow the
  workload's step loop by more than ``--threshold`` (default 1.05 =
  5%) vs the disabled mode — the zero-cost-when-disabled and
  cheap-when-enabled contract.

Runnable standalone (``python tools/check_telemetry.py``) and as a
``not slow`` pytest via ``tests/test_obs.py::test_check_telemetry_tool``.
``bench.py`` runs it per bench round to produce the round's
``telemetry.json``.
"""
from __future__ import annotations

import argparse
import json
import os
import pathlib
import sys
import tempfile
import time

ROOT = pathlib.Path(__file__).resolve().parent.parent

#: the phase set the acceptance criteria require (ISSUE 1; ISSUE 3 adds
#: the incremental rebuild phase; ISSUE 4 the lineage phases)
REQUIRED_PHASES = (
    "halo.exchange",
    "epoch.build",
    "epoch.delta_build",
    "loadbalance.migrate",
    "amr.refine",
    "checkpoint.write",
    "lineage.commit",
    "lineage.scan",
    # ISSUE 5: kernel (re)traces are timed — a probe run always compiles
    # its kernels at least once in a fresh process
    "compile",
    # ISSUE 8: the forced rescale round must time the full commit ->
    # re-land -> verify pipeline
    "elastic.rescale",
    # ISSUE 9: the ensemble probe's admit -> step -> retire round
    "ensemble.admit",
    "ensemble.step",
    # ISSUE 10: the forced escalation must write its black box
    "flightrec.dump",
    # ISSUE 17: every submission with the cost model armed times its
    # admission estimate
    "cost.estimate",
)

#: counters that must be nonzero after the workload
REQUIRED_NONZERO_COUNTERS = (
    "halo.bytes_moved",
    "halo.cells_moved",
    "amr.cells_refined",
    "checkpoint.bytes_written",
    # the probe's small second commit must take the incremental path,
    # not fall back — a silent fallback is a coverage loss
    "epoch.delta_builds",
    # ISSUE 4: the forced injection round must leave the full
    # detection-path evidence — an injected fault that is not counted,
    # or a corrupt generation whose CRC failure is not counted, means
    # the resilience plane silently lost coverage
    "resilience.injected",
    "checkpoint.crc_failures",
    "lineage.generations_skipped",
    "p2p.retries",
    # ISSUE 5: compiled-schedule accounting — every fresh process traces
    # kernels (recompiles), and the churn probe must HIT the executable
    # cache on its second cycle
    "epoch.recompiles",
    "epoch.cache_hits",
    # ISSUE 7: the forced pallas-backend round must leave its oracle
    # evidence — a verify round that silently checked nothing is a
    # coverage loss, exactly like an uncounted injected fault
    "halo.backend_schedules",
    "halo.verify_checks",
    # ISSUE 8: the forced rescale + driven watchdog ladder must leave
    # the full elastic-fleet evidence — a rescale that is not counted,
    # or an escalation rung that never fires, is lost coverage of the
    # supervised-rescale plane
    "elastic.rescales",
    "elastic.degraded",
    "supervisor.warnings",
    "supervisor.escalations",
    # ISSUE 9: the ensemble probe must leave the full serving-lifecycle
    # evidence — an admission, retirement, or served step that is not
    # counted is lost coverage of the multiplexing plane, and a verify
    # round that checked nothing is a silent oracle loss
    "ensemble.admitted",
    "ensemble.retired",
    "ensemble.steps_served",
    "ensemble.verify_checks",
    # ISSUE 10: the deadline-mixed SLO round must count its misses
    # (silent misses are exactly what the request plane exists to end)
    # and the forced escalation must leave its postmortem evidence
    "ensemble.deadline_miss",
    "flightrec.dumps",
    # ISSUE 17: the cost plane's evidence — admission verdicts counted
    # on every submit, and the conservation companion every dispatch
    # bills wall×mesh device-seconds into
    "ensemble.admission_estimates",
    "ensemble.device_s_total",
    # ISSUE 19: the fleet probe's forced failure round must leave the
    # whole gateway evidence trail — an accepted fleet, an enforced
    # rejection at the pinned queue bound, the kill's redispatch, and a
    # journal reopen that counts its replay.  Any of these at zero
    # means the fault-tolerance plane silently lost coverage.
    "gateway.accepted",
    "gateway.rejected",
    "gateway.redispatched",
    "gateway.journal_replays",
)

#: histograms that must carry samples after the probe (ISSUE 10): the
#: per-request latency distributions the SLO report quantiles, and the
#: phase-duration series the registry's observe_duration hook feeds
REQUIRED_HISTOGRAMS = (
    "ensemble.queue_latency",
    "ensemble.queue_wait_s",
    "ensemble.service_s",
    "ensemble.e2e_s",
    "phase.duration_s",
    # ISSUE 17: the per-key step-cost distributions the online model
    # (and its cross-process merges) are built from
    "cost.step_s",
)


#: keys every streaming snapshot line must carry
STREAM_REQUIRED_KEYS = ("seq", "ts", "phases", "counters", "gauges",
                        "histograms")


def validate_stream(path: str, counts: dict | None = None) -> list:
    """Schema-validate a telemetry JSONL stream (``obs.stream_to``
    output); returns failure strings (empty = valid).  A truncated FINAL
    line is tolerated when the file does not end in a newline — that is
    exactly the killed-mid-write case the stream exists to survive — but
    every complete line must parse and the sequence must be coherent.

    Anomalies that are tolerated are no longer silent (ISSUE 16): pass
    a ``counts`` dict and it comes back with ``lines`` (complete
    snapshot lines), ``seq_gaps`` (missing sequence numbers — lines
    lost to a partial copy or a writer restarted without truncate) and
    ``torn_tail`` (1 when the final line was cut mid-write) — the same
    tallies the live tailer (``obs/live.py``) keeps per file."""
    failures: list = []
    if counts is None:
        counts = {}
    counts.update({"lines": 0, "seq_gaps": 0, "torn_tail": 0,
                   "bad_lines": 0})
    try:
        with open(path) as f:
            text = f.read()
    except OSError as e:
        return [f"stream unreadable: {e}"]
    lines = text.split("\n")
    trailing_partial = lines and lines[-1] != ""
    body = [ln for ln in (lines[:-1] if trailing_partial else lines) if ln]
    if trailing_partial:
        try:
            json.loads(lines[-1])
            body.append(lines[-1])  # complete after all, just no newline
        except json.JSONDecodeError:
            # killed mid-write: the complete lines carry the evidence —
            # tolerated, but COUNTED so a consumer can see it happened
            counts["torn_tail"] = 1
    if not body:
        return [f"stream {path} holds no complete snapshot line"]
    prev_seq, prev_ts = None, None
    prev_counters: dict = {}
    for i, ln in enumerate(body):
        try:
            rec = json.loads(ln)
        except json.JSONDecodeError as e:
            counts["bad_lines"] += 1
            failures.append(f"line {i}: not JSON ({e})")
            continue
        if not isinstance(rec, dict):
            counts["bad_lines"] += 1
            failures.append(f"line {i}: not an object")
            continue
        counts["lines"] += 1
        missing = [k for k in STREAM_REQUIRED_KEYS if k not in rec]
        if missing:
            failures.append(f"line {i}: missing keys {missing}")
            continue
        if prev_seq is not None and rec["seq"] <= prev_seq:
            failures.append(
                f"line {i}: seq {rec['seq']} not above {prev_seq}"
            )
        elif prev_seq is not None and rec["seq"] > prev_seq + 1:
            # strictly increasing but not contiguous: lines are MISSING
            # (lost to a partial copy, or a writer reopened an existing
            # file) — coherent enough to consume, counted as gaps
            counts["seq_gaps"] += rec["seq"] - prev_seq - 1
        if prev_ts is not None and rec["ts"] < prev_ts:
            failures.append(
                f"line {i}: ts {rec['ts']} went backwards from {prev_ts}"
            )
        # counters are cumulative monotonic totals — a decrease means a
        # reset mid-stream or a writer bug
        for name, series in rec["counters"].items():
            for label, v in series.items():
                pv = prev_counters.get((name, label))
                if pv is not None and v < pv:
                    failures.append(
                        f"line {i}: counter {name}[{label}] decreased "
                        f"({pv} -> {v})"
                    )
                prev_counters[(name, label)] = v
        prev_seq, prev_ts = rec["seq"], rec["ts"]
    return failures


def validate_chrome_trace(path: str) -> list:
    """Schema-validate a Chrome trace-event export
    (``obs.export_chrome_trace`` output): every ``B`` has a matching
    ``E`` of the same name in stack order per (pid, tid), and in-thread
    timestamps never go backwards.  Returns failure strings."""
    failures: list = []
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        return [f"trace unreadable: {e}"]
    events = data.get("traceEvents") if isinstance(data, dict) else data
    if not isinstance(events, list):
        return ["trace has no traceEvents list"]
    stacks: dict = {}
    last_ts: dict = {}
    for i, ev in enumerate(events):
        if not isinstance(ev, dict) or "ph" not in ev:
            failures.append(f"event {i}: not a trace event")
            continue
        ph = ev["ph"]
        if ph not in ("B", "E"):
            continue  # X/i/M events are legal, just not produced here
        key = (ev.get("pid"), ev.get("tid"))
        ts = ev.get("ts")
        if not isinstance(ts, (int, float)) or ts < 0:
            failures.append(f"event {i}: bad ts {ts!r}")
            continue
        if ts < last_ts.get(key, float("-inf")):
            failures.append(
                f"event {i}: ts {ts} went backwards on tid {key}"
            )
        last_ts[key] = ts
        stack = stacks.setdefault(key, [])
        if ph == "B":
            stack.append((ev.get("name"), ts))
        else:
            if not stack:
                failures.append(
                    f"event {i}: E {ev.get('name')!r} with empty stack "
                    f"on tid {key}"
                )
                continue
            bname, bts = stack.pop()
            if bname != ev.get("name"):
                failures.append(
                    f"event {i}: E {ev.get('name')!r} closes B {bname!r}"
                )
            if ts < bts:
                failures.append(
                    f"event {i}: span {bname!r} ends before it begins"
                )
    for key, stack in stacks.items():
        if stack:
            failures.append(
                f"tid {key}: {len(stack)} unmatched B events "
                f"({[n for n, _ in stack]})"
            )
    return failures


def artifact_path(out_path: str, suffix: str,
                  artifact_dir: str | None = None) -> str:
    """Where a side artifact (``<out basename><suffix>``) lands.

    Default: next to ``out_path`` — EXCEPT when ``out_path`` sits at the
    repo root (the bench's ``telemetry.json``), whose byproducts are
    archived under ``tools/`` alongside ``telemetry_prev.json`` and the
    history instead of littering the root (ISSUE 8).  An explicit
    ``artifact_dir`` (``--artifact-dir``) overrides either way."""
    out = pathlib.Path(out_path)
    if artifact_dir is None:
        parent = out.resolve().parent
        artifact_dir = ROOT / "tools" if parent == ROOT else parent
    return str(pathlib.Path(artifact_dir) / (out.name + suffix))


def _ensure_env() -> None:
    """CPU backend with a small virtual mesh (so halo traffic is real)
    when run standalone; inert when a backend is already configured
    (pytest's conftest sets an 8-device mesh)."""
    if str(ROOT) not in sys.path:
        sys.path.insert(0, str(ROOT))
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=4"
        ).strip()


def build_workload():
    """Tiny refined advection grid: 8^3 level-0 with a refined ball,
    balanced, on the general (host-driven) path."""
    import numpy as np

    from dccrg_tpu import CartesianGeometry, Grid, make_mesh
    from dccrg_tpu.models import Advection

    n = 8
    g = (
        Grid()
        .set_initial_length((n, n, n))
        .set_neighborhood_length(0)
        .set_periodic(True, True, True)
        .set_maximum_refinement_level(1)
        .set_load_balancing_method("RCB")
        .set_geometry(
            CartesianGeometry,
            start=(0.0, 0.0, 0.0),
            level_0_cell_length=(1.0 / n,) * 3,
        )
        .initialize(mesh=make_mesh())
    )
    ids = g.get_cells()
    c = g.geometry.get_center(ids)
    r = np.linalg.norm(c - 0.5, axis=1)
    for cid in ids[r < 0.3]:
        g.refine_completely(int(cid))
    g.stop_refining()
    g.balance_load()
    # one small follow-up commit: its closure is a few percent of the
    # grid, so derived state is delta-patched (epoch.delta_build), not
    # rebuilt — the probe covers BOTH rebuild paths
    g.refine_completely(int(g.get_cells()[0]))
    g.stop_refining()
    adv = Advection(g, dtype=np.float32, allow_dense=False)
    state = adv.initialize_state()
    dt = np.float32(0.4 * adv.max_time_step(state))
    return g, adv, state, dt


def drive(g, adv, state, dt, steps: int):
    """The timed step loop: an explicit host-level ghost refresh (the
    instrumented halo seam) followed by one advection step."""
    import jax

    for _ in range(steps):
        state = {
            **state,
            **g.update_copies_of_remote_neighbors(
                {"density": state["density"]}
            ),
        }
        state = adv.step(state, dt)
    jax.block_until_ready(state["density"])
    return state


def drive_split(g, adv, state, dt, steps: int):
    """The split-phase step loop — the source paper's
    ``start_remote_neighbor_copies`` / compute / ``wait`` pattern: ghost
    payloads go in flight, interior compute dispatches with no data
    dependence on them, then the wait merges.  This is the drive the
    device-timeline probe profiles: the in-flight windows it opens (the
    ``halo.start`` -> ``halo.exchange`` host spans) are the denominator
    of the measured ``overlap.fraction{phase=halo}``."""
    import jax

    for i in range(steps):
        from dccrg_tpu import obs

        with obs.timeline.context(step=i):
            fields = {"density": state["density"]}
            handle = g.start_remote_neighbor_copy_updates(fields)
            interior = adv.step(state, dt)     # overlaps the collective
            fields = g.wait_remote_neighbor_copy_updates(fields, handle)
            state = adv.step({**interior, **fields}, dt)
    jax.block_until_ready(state["density"])
    return state


def drive_fused(step_once, state, steps: int):
    """Drive a FUSED split-phase step (ISSUE 7: advection/vlasov
    ``overlap=True``, GoL's overlap step): the whole start → interior →
    finish → boundary program is ONE dispatch, so the host-visible
    in-flight window is dispatch → completion.  Each step stamps the
    dispatch as a ``halo.start`` span and the completing sync as
    ``halo.exchange`` — the window shape ``obs/merge.py`` pairs — so the
    merged trace measures how much device compute the window hid.  (For
    a fused step this window bounds the true in-flight interval from
    above; the fraction is still a measured floor-gateable overlap
    signal, not an inference.)"""
    import jax

    from dccrg_tpu import obs

    for i in range(steps):
        with obs.timeline.context(step=i):
            t0 = time.perf_counter()
            state = step_once(state)
            obs.metrics.phase_add("halo.start", time.perf_counter() - t0)
            t0 = time.perf_counter()
            jax.block_until_ready(state)
            obs.metrics.phase_add("halo.exchange",
                                  time.perf_counter() - t0)
    return state


def build_fused_model(g, model: str):
    """A fused split-phase stepper for one model on grid ``g``:
    ``(step_once, state)``.  Shared by the device-timeline probe and
    ``tools/trace_report.py --run --model``."""
    import numpy as np

    from dccrg_tpu.models import Advection, GameOfLife, Vlasov

    if model == "advection":
        adv = Advection(g, dtype=np.float32, allow_dense=False,
                        overlap=True)
        state = adv.initialize_state()
        dt = np.float32(0.4 * adv.max_time_step(state))
        return (lambda s: adv.step(s, dt)), state
    if model == "vlasov":
        vl = Vlasov(g, nv=2, dtype=np.float32, overlap=True)
        state = vl.initialize_state()
        dt = np.float32(0.5 * vl.max_time_step())
        return (lambda s: vl.step(s, dt)), state
    if model == "gol":
        gol = GameOfLife(g, overlap=True)
        cells = g.get_cells()
        state = gol.new_state(alive_cells=cells[:: 3])
        return gol.step, state
    raise ValueError(f"unknown model {model!r}")


def _resilience_probe(g, state) -> list:
    """Forced injection round (ISSUE 4): arm a bit flip, commit two
    lineage generations (one corrupt), and require the full detection
    path to fire — the lineage scan must skip the corrupt generation on
    its payload CRC and resume the clean one — plus one injected
    ``p2p.recv`` fault driven through the real transport receive loop
    so the retry/backoff counter records.  Returns failure strings."""
    import socket

    import numpy as np

    failures: list = []
    from dccrg_tpu.io.checkpoint import CheckpointError
    from dccrg_tpu.resilience import CheckpointLineage, plane
    from dccrg_tpu.utils.collectives import _P2PTransport

    spec = {"density": ((), np.float32)}
    with tempfile.TemporaryDirectory() as td:
        lineage = CheckpointLineage(os.path.join(td, "lineage"), keep=3)
        clean_gen = lineage.commit(g, state, spec, user_header=b"clean")
        plane.arm("checkpoint.bit_flip", prob=1.0, seed=0, count=1)
        try:
            corrupt_gen = lineage.commit(g, state, spec,
                                         user_header=b"corrupt")
        finally:
            plane.disarm("checkpoint.bit_flip")
        try:
            _g2, _s2, hdr, gen = lineage.latest_valid(spec, n_devices=1)
            if gen != clean_gen or hdr != b"clean":
                failures.append(
                    f"lineage scan resumed generation {gen} ({hdr!r}) "
                    f"instead of skipping corrupt generation "
                    f"{corrupt_gen} back to {clean_gen}"
                )
        except CheckpointError as e:
            failures.append(f"lineage scan found no valid generation: {e}")

    # injected recv fault through the real _recvn loop: first attempt
    # raises, backoff fires, the retry drains the socket
    a, b = socket.socketpair()
    try:
        b.sendall(b"probe-ok")
        plane.arm("p2p.recv", prob=1.0, seed=0, count=1)
        try:
            got = _P2PTransport._recvn(a, 8, peer=0)
        finally:
            plane.disarm("p2p.recv")
        if got != b"probe-ok":
            failures.append(f"retried recv returned {got!r}")
    finally:
        a.close()
        b.close()
    return failures


def _churn_probe(g, dt) -> list:
    """Forced churn cycle pair (ISSUE 5): cycle one commits a structural
    change, rebuilds the model and steps — warming the executable cache
    for the (possibly new) shape signature; cycle two repeats with an
    unchanged signature and must compile NOTHING (``epoch.recompiles``
    stays flat — the zero-retrace contract of shape-stable epochs)."""
    import jax
    import numpy as np

    from dccrg_tpu import obs
    from dccrg_tpu.models import Advection

    failures: list = []

    def total_recompiles() -> int:
        rep = obs.metrics.report()
        return int(sum(rep["counters"].get("epoch.recompiles", {})
                       .values()))

    def cycle(i: int):
        cells = g.get_cells()
        lvl = g.mapping.get_refinement_level(cells)
        cand = cells[lvl < g.mapping.max_refinement_level]
        g.refine_completely(int(cand[(i * 13) % len(cand)]))
        g.stop_refining()
        adv = Advection(g, dtype=np.float32, allow_dense=False)
        st = adv.initialize_state()
        st = adv.step(st, dt)
        jax.block_until_ready(st["density"])

    cycle(0)
    sig = g.shape_signature()
    before = total_recompiles()
    cycle(1)
    if g.shape_signature() != sig:
        failures.append(
            "churn probe: one-cell commit changed the shape signature "
            f"({sig} -> {g.shape_signature()}) — bucket hysteresis is "
            "not holding shapes"
        )
    elif total_recompiles() != before:
        failures.append(
            f"churn probe: second same-signature cycle recompiled "
            f"{total_recompiles() - before} kernel(s); the executable "
            "cache must make it zero"
        )
    return failures


def _halo_backend_probe() -> list:
    """Forced pallas-backend round (ISSUE 7): build a small multi-ring
    grid with ``DCCRG_HALO_BACKEND=pallas`` + ``DCCRG_HALO_VERIFY=1``,
    run blocking and split-phase exchanges through the async-DMA ring
    bodies (interpreted on CPU), and require the oracle cross-check to
    have fired with ZERO mismatches — the probe fails exactly when the
    DMA transport stops being bit-identical to the collective path."""
    import numpy as np

    from dccrg_tpu import Grid, make_mesh, obs

    failures: list = []
    saved = {k: os.environ.get(k)
             for k in ("DCCRG_HALO_BACKEND", "DCCRG_HALO_VERIFY")}
    os.environ["DCCRG_HALO_BACKEND"] = "pallas"
    os.environ["DCCRG_HALO_VERIFY"] = "1"
    try:
        g = (
            Grid()
            .set_initial_length((8, 8, 1))
            .set_neighborhood_length(1)
            .set_load_balancing_method("RCB")
            .initialize(mesh=make_mesh())
        )
        if g.halo().backend != "pallas":
            return ["halo backend probe: DCCRG_HALO_BACKEND=pallas did "
                    f"not select the pallas transport "
                    f"(got {g.halo().backend!r})"]
        state = g.new_state({"v": ((), np.float64)})
        cells = g.get_cells()
        state = g.set_cell_data(
            state, "v", cells, np.sin(cells.astype(np.float64))
        )
        state = g.update_copies_of_remote_neighbors(state)
        handle = g.start_remote_neighbor_copy_updates(state)
        g.wait_remote_neighbor_copy_updates(state, handle)
        rep = obs.metrics.report()
        checks = sum(rep["counters"].get("halo.verify_checks", {})
                     .values())
        if checks < 2:
            failures.append(
                f"halo backend probe: verify oracle ran {checks} "
                "checks; the blocking + split round must cross-check "
                "both"
            )
        mismatches = sum(rep["counters"]
                         .get("halo.verify_mismatches", {}).values())
        if mismatches:
            failures.append(
                f"halo backend probe: {mismatches} pallas/collective "
                "mismatches — the DMA ring body is no longer "
                "bit-identical to the oracle"
            )
    except Exception as e:  # noqa: BLE001 — probe reports, not dies
        failures.append(f"halo backend probe failed: {e!r}")
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    return failures


def _elastic_probe(g, state) -> list:
    """Forced rescale round + driven watchdog ladder (ISSUE 8).

    Rescale the probe grid down to half its devices and back up through
    a checkpoint lineage (``resilience/elastic.py``) — the payload must
    survive both re-landings bit-identically and both directions must be
    counted under the ``elastic.rescale`` phase.  Then drive the
    supervisor's escalation ladder over a synthetic stalled heartbeat:
    warn → rescale-down (``elastic.degraded``) → restart must fire in
    exactly that order.  Returns failure strings."""
    import numpy as np

    from dccrg_tpu import obs
    from dccrg_tpu.resilience import (
        EscalationLadder,
        HeartbeatMonitor,
        Supervisor,
        rescale,
    )

    failures: list = []
    spec = {"density": ((), np.float32)}
    ids = g.get_cells()
    want = np.asarray(g.get_cell_data(state, "density", ids))
    with tempfile.TemporaryDirectory() as td:
        try:
            down = max(1, g.n_devices // 2)
            r = rescale(g, state, spec, down,
                        directory=os.path.join(td, "lineage"),
                        user_header=b"elastic-probe")
            r2 = rescale(r.grid, r.state, spec, g.n_devices,
                         directory=os.path.join(td, "lineage"),
                         user_header=b"elastic-probe")
            for tag, res, nd in (("down", r, down),
                                 ("up", r2, g.n_devices)):
                if res.n_devices_after != nd:
                    failures.append(
                        f"elastic probe: rescale {tag} landed on "
                        f"{res.n_devices_after} devices, wanted {nd}"
                    )
                got = np.asarray(
                    res.grid.get_cell_data(res.state, "density", ids)
                )
                if not np.array_equal(got, want):
                    failures.append(
                        f"elastic probe: rescale {tag} altered the "
                        "payload"
                    )
        except Exception as e:  # noqa: BLE001 — probe reports, not dies
            failures.append(f"elastic rescale probe failed: {e!r}")

    # watchdog ladder over a synthetic stalled heartbeat (injected
    # clock, so the probe never sleeps)
    with tempfile.TemporaryDirectory() as td:
        try:
            hb = os.path.join(td, "hb.jsonl")
            s = obs.TelemetryStream(hb, period=3600.0, truncate=True)
            s.write_snapshot(step=0)
            mon = HeartbeatMonitor(hb, stall_after_s=1.0, now=0.0)
            sup = Supervisor(mon, ladder=EscalationLadder())
            first = sup.poll(now=0.5)
            if first["status"] != "ok":
                failures.append(
                    f"elastic probe: fresh heartbeat read as "
                    f"{first['status']}"
                )
            acts = [sup.poll(now=10.0 + i)["action"] for i in range(3)]
            if acts != ["warn", "rescale_down", "restart"]:
                failures.append(
                    f"elastic probe: escalation ladder ran {acts}, "
                    "wanted ['warn', 'rescale_down', 'restart']"
                )
        except Exception as e:  # noqa: BLE001
            failures.append(f"elastic watchdog probe failed: {e!r}")
    return failures


def _ensemble_probe() -> list:
    """Ensemble serving round (ISSUE 9): one admit → step → retire
    lifecycle through the cohort front-end with the solo-replay oracle
    armed.  Requirements: a second admission wave at the HELD cohort
    width must trace zero new kernels (``epoch.recompiles`` flat — the
    shape-stable serving contract), the oracle must have checked with
    zero mismatches, a sampled member must retire bit-identical to solo
    stepping, and the peak-occupancy gauge must land in (0, 1] (the
    floor the telemetry gate watches).  Returns failure strings."""
    import numpy as np

    from dccrg_tpu import CartesianGeometry, Grid, make_mesh, obs
    from dccrg_tpu.models import GameOfLife
    from dccrg_tpu.serve import Ensemble

    failures: list = []
    try:
        n = 4
        g = (
            Grid()
            .set_initial_length((n, n, n))
            .set_neighborhood_length(0)
            .set_periodic(True, True, True)
            .set_geometry(
                CartesianGeometry,
                start=(0.0, 0.0, 0.0),
                level_0_cell_length=(1.0 / n,) * 3,
            )
            .initialize(mesh=make_mesh())
        )
        g.stop_refining()
        gol = GameOfLife(g, allow_dense=False)
        cells = g.get_cells()
        rng = np.random.default_rng(0)
        mk = lambda: gol.new_state(
            alive_cells=cells[rng.random(len(cells)) < 0.3]
        )

        def recompiles() -> int:
            rep = obs.metrics.report()
            return int(sum(rep["counters"].get("epoch.recompiles", {})
                           .values()))

        ens = Ensemble(verify=True)
        first = [mk() for _ in range(4)]
        tickets = [ens.submit(gol, s, steps=3, tenant=f"tenant{i % 2}")
                   for i, s in enumerate(first)]
        ens.run()                                # warm the cohort body
        before = recompiles()
        for s in (mk() for _ in range(4)):       # churn at held width
            ens.submit(gol, s, steps=2)
        ens.run()
        if recompiles() != before:
            failures.append(
                f"ensemble probe: admission/retirement at a held "
                f"signature recompiled {recompiles() - before} "
                "kernel(s); the cohort executable must make it zero"
            )
        ref = first[0]
        for _ in range(3):
            ref = gol.step(ref)
        import jax

        same = all(
            np.asarray(a).tobytes() == np.asarray(b).tobytes()
            for a, b in zip(jax.tree_util.tree_leaves(ref),
                            jax.tree_util.tree_leaves(tickets[0].result))
        )
        if not same:
            failures.append(
                "ensemble probe: cohort-stepped member diverged from "
                "solo stepping (bit-identity anchor broken)"
            )
        rep = obs.metrics.report()
        checks = sum(rep["counters"].get("ensemble.verify_checks", {})
                     .values())
        if checks < 2:
            failures.append(
                f"ensemble probe: verify oracle ran {checks} checks; "
                "the armed round must replay sampled members"
            )
        mism = sum(rep["counters"].get("ensemble.verify_mismatches", {})
                   .values())
        if mism:
            failures.append(
                f"ensemble probe: {mism} cohort/solo mismatches — the "
                "stacked cohort body is no longer bit-identical to the "
                "member programs"
            )
        occ = rep["gauges"].get("ensemble.cohort_peak_occupancy", {})
        if not occ:
            failures.append(
                "ensemble probe: ensemble.cohort_peak_occupancy gauge "
                "missing after the serving round"
            )
        elif not all(0.0 < v <= 1.0 for v in occ.values()):
            failures.append(
                f"ensemble probe: peak occupancy out of (0, 1]: {occ}"
            )

        # deep dispatch (ISSUE 11): a k=4 cohort round — the fori_loop
        # body must be bit-identical to 4 solo steps (oracle armed), a
        # second wave at the held (signature, width, k) must recompile
        # NOTHING, and the depth + per-member HBM gauges must land
        ens4 = Ensemble(verify=True, steps_per_dispatch=4)
        deep = [mk() for _ in range(4)]
        deep_tickets = [ens4.submit(gol, s, steps=8) for s in deep]
        ens4.run()                               # warms the k=4 body
        before = recompiles()
        for s in (mk() for _ in range(4)):       # churn at held (W, k)
            ens4.submit(gol, s, steps=8)
        ens4.run()
        if recompiles() != before:
            failures.append(
                f"ensemble probe: k=4 churn at a held (signature, "
                f"width, k) recompiled {recompiles() - before} "
                "kernel(s); deep dispatch must re-dispatch the cached "
                "body"
            )
        ref4 = deep[0]
        for _ in range(8):
            ref4 = gol.step(ref4)
        same4 = all(
            np.asarray(a).tobytes() == np.asarray(b).tobytes()
            for a, b in zip(jax.tree_util.tree_leaves(ref4),
                            jax.tree_util.tree_leaves(
                                deep_tickets[0].result))
        )
        if not same4:
            failures.append(
                "ensemble probe: k=4 deep dispatch diverged from 8 "
                "solo steps (k-step bit-identity anchor broken)"
            )
        rep = obs.metrics.report()
        mism = sum(rep["counters"].get("ensemble.verify_mismatches", {})
                   .values())
        if mism:
            failures.append(
                f"ensemble probe: {mism} cohort/solo mismatches after "
                "the deep-dispatch round — the fori_loop cohort body "
                "is not bit-identical to the member program"
            )
        kgauge = rep["gauges"].get("ensemble.steps_per_dispatch", {})
        if not any(v > 0 for v in kgauge.values()):
            failures.append(
                "ensemble probe: ensemble.steps_per_dispatch gauge "
                f"missing or zero after a k=4 round: {kgauge}"
            )
        hbm_g = rep["gauges"].get("ensemble.hbm_bytes_per_member", {})
        if not any(v > 0 for v in hbm_g.values()):
            failures.append(
                "ensemble probe: ensemble.hbm_bytes_per_member gauge "
                f"missing or zero after the serving rounds: {hbm_g}"
            )
    except Exception as e:  # noqa: BLE001 — probe reports, not dies
        failures.append(f"ensemble probe failed: {e!r}")
    return failures


def _wide_halo_probe() -> list:
    """Exchange-amortized deep dispatch round (ISSUE 14): a k=4 wide
    round on a depth-4 ghost zone must pay ONE exchange per dispatch —
    the ``halo.exchanges_per_step`` gauge (the ceiling-gated headline)
    reads exactly 1/4 — with the solo-replay oracle armed and clean,
    and a second wave at the held (signature, width, k, g) must
    recompile NOTHING.  Returns failure strings."""
    import numpy as np

    from dccrg_tpu import CartesianGeometry, Grid, make_mesh, obs
    from dccrg_tpu.models import GameOfLife
    from dccrg_tpu.parallel import halo
    from dccrg_tpu.serve import Ensemble

    failures: list = []
    try:
        n = 6
        g = (
            Grid()
            .set_initial_length((n, n, n))
            .set_neighborhood_length(4)
            .set_periodic(True, True, True)
            .set_geometry(
                CartesianGeometry,
                start=(0.0, 0.0, 0.0),
                level_0_cell_length=(1.0 / n,) * 3,
            )
            .initialize(mesh=make_mesh())
        )
        g.stop_refining()
        moore = [(i, j, k) for i in (-1, 0, 1) for j in (-1, 0, 1)
                 for k in (-1, 0, 1) if (i, j, k) != (0, 0, 0)]
        g.add_neighborhood(7, moore)
        gol = GameOfLife(g, hood_id=7, allow_dense=False)
        spec = gol.batch_step_spec()
        if spec.wide is None or spec.wide.budget < 4:
            failures.append(
                "wide-halo probe: no engageable wide plan on a depth-4 "
                f"hood (wide={spec.wide!r}); exchange amortization "
                "cannot run"
            )
            return failures
        cells = g.get_cells()
        rng = np.random.default_rng(0)
        mk = lambda: gol.new_state(
            alive_cells=cells[rng.random(len(cells)) < 0.3]
        )

        def recompiles() -> int:
            rep = obs.metrics.report()
            return int(sum(rep["counters"].get("epoch.recompiles", {})
                           .values()))

        halo._amortization.clear()
        ens = Ensemble(verify=True, steps_per_dispatch=4)
        first = [mk() for _ in range(4)]
        tickets = [ens.submit(gol, s, steps=8, tenant="wide")
                   for s in first]
        ens.run()                            # warms the (k=4, g=4) body
        before = recompiles()
        for s in (mk() for _ in range(4)):   # churn at held (W, k, g)
            ens.submit(gol, s, steps=4, tenant="wide")
        ens.run()
        if recompiles() != before:
            failures.append(
                f"wide-halo probe: churn at a held (signature, width, "
                f"k, g) recompiled {recompiles() - before} kernel(s); "
                "the wide cohort body must re-dispatch from cache"
            )
        rep = obs.metrics.report()
        gauge = rep["gauges"].get("halo.exchanges_per_step", {})
        got = gauge.get("model=gol")
        if got != 0.25:
            failures.append(
                f"wide-halo probe: halo.exchanges_per_step = {got!r} "
                "after k=4 wide rounds; one exchange must fund 4 "
                "interior steps (wanted 0.25)"
            )
        checks = sum(rep["counters"].get("ensemble.verify_checks", {})
                     .values())
        if checks < 2:
            failures.append(
                f"wide-halo probe: verify oracle ran {checks} checks; "
                "the armed wide round must replay sampled members"
            )
        mism = sum(rep["counters"].get("ensemble.verify_mismatches", {})
                   .values())
        if mism:
            failures.append(
                f"wide-halo probe: {mism} cohort/solo mismatches — the "
                "amortized body is no longer bit-identical to exchange-"
                "every-step stepping on owned rows"
            )
        # owned-row bit-identity against solo, independent of the oracle
        import jax  # noqa: F401 — tree flatten below

        ref = first[0]
        for _ in range(8):
            ref = gol.step(ref)
        lm = spec.wide.local_mask
        for name in sorted(ref):
            a = np.asarray(ref[name])
            b = np.asarray(tickets[0].result[name])
            if a.shape[:2] == lm.shape:
                a, b = a[lm], b[lm]
            if a.tobytes() != b.tobytes():
                failures.append(
                    f"wide-halo probe: field {name!r} diverged from 8 "
                    "solo steps on owned rows"
                )
    except Exception as e:  # noqa: BLE001 — probe reports, not dies
        failures.append(f"wide-halo probe failed: {e!r}")
    return failures


def _slo_probe() -> list:
    """Request-level SLO round (ISSUE 10).

    Drives a deadline-mixed ensemble round (two tenants; half the
    scenarios submitted with already-passed deadlines, half with far
    ones) and requires the full request plane to materialize: the
    ``ensemble.queue_wait_s`` / ``ensemble.e2e_s`` histograms with sane
    quantile ordering (p50 <= p95 <= p99 from the exported buckets
    alone), exact deadline-miss counts, and request lifecycle spans on
    the timeline.  Then forces a supervisor escalation with the flight
    recorder armed at a scratch directory: the ladder must produce
    EXACTLY ONE schema-valid postmortem dump for the incident, naming
    the round's request activity.  Returns failure strings."""
    import numpy as np

    from dccrg_tpu import CartesianGeometry, Grid, make_mesh, obs
    from dccrg_tpu.models import GameOfLife
    from dccrg_tpu.obs import flight_recorder, slo, validate_flightrec
    from dccrg_tpu.resilience import EscalationLadder
    from dccrg_tpu.serve import Ensemble

    failures: list = []
    prev_dir = flight_recorder.armed_dir
    td = tempfile.mkdtemp(prefix="dccrg_slo_probe_")
    try:
        flight_recorder.arm(td, autodump=False)
        n = 4
        g = (
            Grid()
            .set_initial_length((n, n, n))
            .set_neighborhood_length(0)
            .set_periodic(True, True, True)
            .set_geometry(
                CartesianGeometry,
                start=(0.0, 0.0, 0.0),
                level_0_cell_length=(1.0 / n,) * 3,
            )
            .initialize(mesh=make_mesh())
        )
        g.stop_refining()
        gol = GameOfLife(g, allow_dense=False)
        cells = g.get_cells()
        rng = np.random.default_rng(1)
        mk = lambda: gol.new_state(
            alive_cells=cells[rng.random(len(cells)) < 0.3]
        )
        before_miss = int(sum(
            obs.metrics.report()["counters"]
            .get("ensemble.deadline_miss", {}).values()
        ))
        ens = Ensemble(policy="deadline")
        now = time.perf_counter()
        expect_missed = 0
        for i in range(6):
            # even submissions carry deadlines that already passed —
            # guaranteed misses; odd ones have a generous hour
            past = i % 2 == 0
            ens.submit(gol, mk(), steps=2 + i % 3,
                       tenant=f"tenant{i % 2}",
                       deadline=now - 1.0 if past else now + 3600.0)
            expect_missed += past
        ens.run()

        rep = obs.metrics.report()
        for name in ("ensemble.queue_wait_s", "ensemble.e2e_s",
                     "ensemble.service_s"):
            series = rep["histograms"].get(name)
            if not series:
                failures.append(
                    f"slo probe: histogram {name!r} missing after the "
                    "deadline-mixed round"
                )
                continue
            for label, h in series.items():
                p50, p95, p99 = (slo.quantile(h, q)
                                 for q in (0.5, 0.95, 0.99))
                if p50 is None or not (p50 <= p95 <= p99):
                    failures.append(
                        f"slo probe: {name}{{{label}}} quantiles out of "
                        f"order: p50={p50} p95={p95} p99={p99}"
                    )
        missed = int(sum(
            rep["counters"].get("ensemble.deadline_miss", {}).values()
        )) - before_miss
        if missed != expect_missed:
            failures.append(
                f"slo probe: {missed} deadline misses counted, expected "
                f"exactly {expect_missed} (past-deadline submissions)"
            )
        span_names = {s["name"] for s in obs.timeline.spans()}
        for wanted in ("request.queued", "request.step", "request.e2e"):
            if wanted not in span_names:
                failures.append(
                    f"slo probe: lifecycle span {wanted!r} missing from "
                    "the timeline after the serving round"
                )

        # forced escalation -> exactly one postmortem for the incident
        ladder = EscalationLadder()
        for _ in range(3):
            ladder.escalate("slo-probe-stall")
        dumps = sorted(
            p for p in os.listdir(td)
            if p.startswith("flightrec_") and p.endswith(".json")
        )
        if len(dumps) != 1:
            failures.append(
                f"slo probe: forced escalation left {len(dumps)} "
                f"flight-recorder dumps ({dumps}), wanted exactly one "
                "per incident"
            )
        for p in dumps:
            full = os.path.join(td, p)
            failures += [f"flightrec {p}: {f}"
                         for f in validate_flightrec(full)]
            with open(full) as f:
                rec = json.load(f)
            named = any(
                str(ev.get("kind", "")).startswith("request.")
                for ev in rec.get("events", [])
            ) or any(
                str(sp.get("name", "")).startswith("request.")
                for sp in rec.get("spans", [])
            )
            if not named:
                failures.append(
                    f"slo probe: postmortem {p} names no request "
                    "activity from the serving round"
                )
    except Exception as e:  # noqa: BLE001 — probe reports, not dies
        failures.append(f"slo probe failed: {e!r}")
    finally:
        if prev_dir is not None:
            flight_recorder.arm(prev_dir)
        else:
            flight_recorder.disarm()
        import shutil

        shutil.rmtree(td, ignore_errors=True)
    return failures


def _fleet_probe() -> list:
    """Fleet gateway round (ISSUE 19).

    Launches TWO real worker subprocesses on 4-device mesh slices
    behind an in-process :class:`~dccrg_tpu.serve.Gateway` (in-process
    so the gateway counters land in THIS registry, where the gate's
    required-counter check reads them), submits a small GoL fleet, and
    forces the failure path end to end: one worker is SIGKILLed after
    it reports ``started``, its in-flight scenarios must redispatch to
    the survivor and every accepted scenario must retire EXACTLY once
    — with one redispatched member byte-compared against uninterrupted
    solo stepping.  The queue bound is pinned low enough that one
    overflow submission must be rejected (``gateway.rejected``), the
    worker loss must leave exactly ONE schema-valid flight-recorder
    dump naming the lost worker, and a journal reopen must replay the
    retired set (``gateway.journal_replays``).  Returns failure
    strings."""
    import shutil

    import numpy as np

    from dccrg_tpu import obs
    from dccrg_tpu.obs import flight_recorder, validate_flightrec
    from dccrg_tpu.serve import (
        Ensemble,
        Gateway,
        SubmissionJournal,
        WorkerHandle,
    )
    from dccrg_tpu.serve.worker import build_scenario

    failures: list = []

    def total(name: str) -> int:
        rep = obs.metrics.report()
        return int(sum(rep["counters"].get(name, {}).values()))

    watched = ("gateway.accepted", "gateway.rejected",
               "gateway.redispatched", "gateway.worker_lost",
               "gateway.retired", "gateway.journal_replays")
    before = {n: total(n) for n in watched}
    prev_dir = flight_recorder.armed_dir
    td = tempfile.mkdtemp(prefix="dccrg_fleet_probe_")
    saved_env = {k: os.environ.get(k)
                 for k in ("DCCRG_GATEWAY_QUEUE_MAX",
                           "DCCRG_GATEWAY_STALL_S",
                           "DCCRG_COMPILE_CACHE_DIR")}
    gw = None
    try:
        fr_dir = os.path.join(td, "flightrec")
        os.makedirs(fr_dir)
        flight_recorder.arm(fr_dir, autodump=False)
        # worker cold start (jax import + first compile) exceeds the
        # 10 s default stall budget; the kill below is the ONLY loss
        # this probe scripts, so spurious stall escalations must not
        # race it
        os.environ["DCCRG_GATEWAY_STALL_S"] = "120"
        os.environ["DCCRG_GATEWAY_QUEUE_MAX"] = "4"
        os.environ["DCCRG_COMPILE_CACHE_DIR"] = os.path.join(td, "cache")
        workers = [WorkerHandle(w, os.path.join(td, w), n_devices=4)
                   for w in ("w0", "w1")]
        for w in workers:
            w.start()
        gw = Gateway(os.path.join(td, "journal.jsonl"), workers)
        specs = [{"sid": f"fp{i}", "model": "gol", "n": 8, "seed": i,
                  "steps": 24, "tenant": "fleet"} for i in range(4)]
        for s in specs:
            ok, why = gw.submit(dict(s))
            if not ok:
                failures.append(
                    f"fleet probe: {s['sid']} rejected ({why})")
        ok, why = gw.submit({"sid": "fp-overflow", "model": "gol",
                             "steps": 1, "tenant": "fleet"})
        if ok or why != "queue-full":
            failures.append(
                "fleet probe: overflow submission past the pinned "
                f"queue bound was not rejected (got {(ok, why)!r})")
        gw.tick(restart_lost=False)
        victim = "w0" if gw.journal.in_flight("w0") else "w1"
        survivor = "w1" if victim == "w0" else "w0"
        victim_sids = set(gw.journal.in_flight(victim))
        if not victim_sids:
            failures.append(
                "fleet probe: no in-flight work assigned to the victim")
        # wait until the victim reports 'started' (it is genuinely
        # stepping, not just assigned), then SIGKILL it mid-flight
        deadline = time.monotonic() + 180.0
        while time.monotonic() < deadline:
            gw.tick(restart_lost=False)
            if any(gw.journal.accepted[s].get("sig")
                   for s in victim_sids):
                break
            time.sleep(0.2)
        else:
            failures.append(
                "fleet probe: victim never reported 'started' in 180s")
        victim_sids = set(gw.journal.in_flight(victim))
        gw.workers[victim].kill()
        if not gw.run_until_drained(timeout_s=300.0, restart_lost=False):
            failures.append(
                "fleet probe: fleet failed to drain within 300s after "
                "the forced worker kill")
        # exact retire counts: every accepted scenario exactly once
        accepted = set(gw.journal.accepted)
        if set(gw.journal.retired) != accepted:
            failures.append(
                f"fleet probe: retired {sorted(gw.journal.retired)} != "
                f"accepted {sorted(accepted)}")
        d_retired = total("gateway.retired") - before["gateway.retired"]
        if d_retired != len(specs):
            failures.append(
                f"fleet probe: {d_retired} retirements counted, wanted "
                f"exactly {len(specs)} (at-least-once stepping must "
                "stay exactly-once retirement)")
        if total("gateway.worker_lost") - before["gateway.worker_lost"] \
                != 1:
            failures.append(
                "fleet probe: the one forced kill did not count as "
                "exactly one gateway.worker_lost")
        d_re = (total("gateway.redispatched")
                - before["gateway.redispatched"])
        if d_re != len(victim_sids):
            failures.append(
                f"fleet probe: {d_re} redispatches counted, wanted "
                f"{len(victim_sids)} (the victim's in-flight set)")
        if total("gateway.accepted") - before["gateway.accepted"] \
                != len(specs):
            failures.append(
                "fleet probe: accepted count does not match the "
                "submitted fleet")
        # bit-identity: one redispatched member vs uninterrupted solo
        if victim_sids and not failures:
            sid = sorted(victim_sids)[0]
            res = os.path.join(gw.workers[survivor].workdir,
                               f"result_{sid}.npz")
            spec = next(s for s in specs if s["sid"] == sid)
            bundle = build_scenario(spec, n_devices=4)
            ens = Ensemble()
            t = ens.submit(bundle["model"], bundle["state"],
                           steps=int(spec["steps"]), dt=bundle["dt"])
            ens.run()
            want = np.sort(np.asarray(
                bundle["model"].alive_cells(t.result)))
            try:
                with np.load(res) as z:
                    got = np.asarray(z["alive"])
                if not np.array_equal(want, got):
                    failures.append(
                        f"fleet probe: redispatched member {sid} is not "
                        "bit-identical to uninterrupted solo stepping")
            except OSError as e:
                failures.append(
                    f"fleet probe: result park for {sid} unreadable: {e}")
        # one postmortem per incident, naming the lost worker
        dumps = sorted(p for p in os.listdir(fr_dir)
                       if p.startswith("flightrec_")
                       and p.endswith(".json"))
        if len(dumps) != 1:
            failures.append(
                f"fleet probe: worker loss left {len(dumps)} "
                f"flight-recorder dumps ({dumps}), wanted exactly one")
        for p in dumps:
            full = os.path.join(fr_dir, p)
            failures += [f"fleet flightrec {p}: {f}"
                         for f in validate_flightrec(full)]
            with open(full) as f:
                rec = json.load(f)
            named = any(ev.get("kind") == "worker.lost"
                        and ev.get("worker") == victim
                        for ev in rec.get("events", []))
            if not named:
                failures.append(
                    f"fleet probe: postmortem {p} does not name the "
                    f"lost worker {victim}")
        # crash durability: a journal reopen replays the retired set
        j2 = SubmissionJournal(gw.journal.path)
        if set(j2.retired) != accepted:
            failures.append(
                "fleet probe: journal reopen lost the retired set")
        j2.close()
        if (total("gateway.journal_replays")
                - before["gateway.journal_replays"]) < 1:
            failures.append(
                "fleet probe: journal reopen did not count a replay")
    except Exception as e:  # noqa: BLE001 — probe reports, not dies
        failures.append(f"fleet probe failed: {e!r}")
    finally:
        if gw is not None:
            gw.close()
        for k, v in saved_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        if prev_dir is not None:
            flight_recorder.arm(prev_dir)
        else:
            flight_recorder.disarm()
        shutil.rmtree(td, ignore_errors=True)
    return failures


def _cost_probe() -> list:
    """Cost & capacity round (ISSUE 17).

    Drives a mixed-tenant ensemble round with the cost model armed and
    requires the predictive plane to materialize: every stepped
    compiled-body key must have samples in BOTH the process model and
    the exported ``cost.step_s`` series (the dual store cross-process
    merges depend on), ``predict`` must answer at the exact level for a
    stepped key and walk the fallback chain to ``global`` for a novel
    model kind, and the chargeback conservation invariant must hold
    (per-tenant ``ensemble.device_s`` sums to the recorded
    ``ensemble.device_s_total`` wall×mesh total).  Then the adversarial
    calibration round: a two-tenant burst into a width-capped cohort so
    requests queue, comparing the ``cost.predicted_queue_wait_s``
    gauges read at submit time against the measured per-tenant
    queue-wait p95 — they must agree within one octave bucket
    (``cost.CALIBRATION_BUCKET``, the predictor's documented
    calibration resolution).  No deadlines are used, so the
    ``ensemble.deadline_miss`` count stays exactly the SLO probe's
    (the telemetry_diff gate pins it).  The ≤5% overhead budget is
    re-passed with the model ON by construction: ``_overhead_probe``
    runs in this same process with the default (armed) cost env, which
    this probe asserts.  Returns failure strings."""
    import numpy as np

    from dccrg_tpu import CartesianGeometry, Grid, make_mesh, obs
    from dccrg_tpu.models import Advection
    from dccrg_tpu.obs import cost, slo
    from dccrg_tpu.serve import Ensemble

    failures: list = []
    try:
        if not cost.enabled():
            return ["cost probe: DCCRG_COST_MODEL is off — the probe "
                    "(and the overhead budget) must run with the model "
                    "armed"]
        # The probe serves the paper's advection model on its own tiny
        # grid (NOT the gol the other ensemble probes drive): the
        # ceiling-gated per-model gauges are latest-wins (hbm) and
        # process-cumulative (exchanges_per_step), so this probe's
        # legacy hood-0 k=4 cohorts would otherwise overwrite/dilute
        # the canonical gol series the wide-halo and slo probes leave
        # behind.  Under its own ``model=advection*`` labels the cost
        # rounds get their own gated baseline instead.
        n = 4
        g = (
            Grid()
            .set_initial_length((n, n, n))
            .set_neighborhood_length(0)
            .set_periodic(True, True, True)
            .set_geometry(
                CartesianGeometry,
                start=(0.0, 0.0, 0.0),
                level_0_cell_length=(1.0 / n,) * 3,
            )
            .initialize(mesh=make_mesh())
        )
        g.stop_refining()
        adv = Advection(g, dtype=np.float32, allow_dense=False)
        dt = np.float32(0.4 * adv.max_time_step(adv.initialize_state()))
        mk = adv.initialize_state

        # (1) mixed-tenant round: every stepped key leaves samples
        ens = Ensemble(steps_per_dispatch=4)
        for i in range(4):
            ens.submit(adv, mk(), steps=8, dt=dt, tenant=f"ct{i % 2}")
        ens.run()
        rep = obs.metrics.report()
        series = rep["histograms"].get(cost.COST_HISTOGRAM) or {}
        if not series:
            failures.append(
                "cost probe: no cost.step_s series after the "
                "mixed-tenant round")
        local = cost.model.series()
        for label, h in series.items():
            mine = local.get(label)
            if mine is None or mine["count"] < h["count"]:
                failures.append(
                    f"cost probe: model/registry divergence at "
                    f"{label!r} — the dual store cross-process merges "
                    "depend on is out of sync")
        for label in series:
            kv = cost.parse_label(label)
            est = cost.model.predict(kv["model"], sig=kv["sig"],
                                     k=kv["k"], g=kv["g"], w=kv["w"])
            if est is None or est.level != "exact" or est.n < 1:
                failures.append(
                    f"cost probe: predict({label!r}) did not answer at "
                    f"the exact level: {est}")
        novel = cost.model.predict("no-such-model-kind")
        if novel is None or novel.level != "global":
            failures.append(
                "cost probe: fallback chain broken — a novel model "
                f"kind must answer at the global level, got {novel}")

        # (2) chargeback conservation over everything recorded so far
        cons = cost.conservation(rep)
        if not cons["ok"]:
            failures.append(
                f"cost probe: chargeback conservation violated — "
                f"attributed {cons['attributed']:.6f}s vs wall×mesh "
                f"total {cons['total']:.6f}s (ratio {cons['ratio']})")
        ledger = cost.chargeback(rep)
        if not any(t.startswith("ct") for t in ledger):
            failures.append(
                f"cost probe: mixed-tenant round missing from the "
                f"chargeback ledger: {sorted(ledger)}")

        # (3) adversarial calibration: two-tenant burst, width-capped
        # cohort (16 pending into width 4, so most requests queue),
        # prediction at submit time vs measured wait p95
        burst = Ensemble(steps_per_dispatch=4, max_width=4)
        for _ in range(4):
            burst.submit(adv, mk(), steps=8, dt=dt, tenant="cwarm")
        burst.run()                  # compiles the (W=4, k=4) body
        cost.tracker.reset()         # drop compile-inflated timings
        for _ in range(4):
            burst.submit(adv, mk(), steps=8, dt=dt, tenant="cwarm")
        burst.run()                  # clean wave trains the rate window
        for i in range(16):
            burst.submit(adv, mk(), steps=8, dt=dt,
                         tenant=f"cburst{i % 2}")
        predicted = {
            cost.parse_label(label).get("tenant"): float(v)
            for label, v in (obs.metrics.report()["gauges"]
                             .get("cost.predicted_queue_wait_s") or {})
            .items()
        }
        burst.run()
        rep = obs.metrics.report()
        waits = rep["histograms"].get("ensemble.queue_wait_s") or {}
        for tenant in ("cburst0", "cburst1"):
            pred = predicted.get(tenant)
            if not pred or pred <= 0:
                failures.append(
                    f"cost probe: no predicted queue-wait gauge for "
                    f"burst tenant {tenant!r} at submit time")
                continue
            h = waits.get(f"tenant={tenant}")
            measured = slo.quantile(h, 0.95) if h else None
            if not measured:
                failures.append(
                    f"cost probe: no measured queue-wait for burst "
                    f"tenant {tenant!r}")
                continue
            ratio = pred / measured
            b = cost.CALIBRATION_BUCKET
            if not (1.0 / b <= ratio <= b):
                failures.append(
                    f"cost probe: predicted queue-wait off by more "
                    f"than one calibration bucket for {tenant!r}: "
                    f"predicted {pred:.4f}s vs measured p95 "
                    f"{measured:.4f}s (ratio {ratio:.2f}, "
                    f"envelope [{1.0 / b:.2f}, {b:.2f}])")
    except Exception as e:  # noqa: BLE001 — probe reports, not dies
        failures.append(f"cost probe failed: {e!r}")
    return failures


#: the live-probe stream writer: file-loads the registry (stdlib-only
#: by contract, so the subprocess never pays a jax import), records a
#: DETERMINISTIC sample schedule into the SLO series at the SLO bucket
#: resolution, and hand-writes the stream lines — writer 1 additionally
#: injects a 2-line seq gap and ends on a torn (newline-less) final
#: line, the anomalies the tailer must count without dropping data
_LIVE_WRITER_SRC = r"""
import importlib.util, json, sys, time
reg_path, out_path, wid = sys.argv[1], sys.argv[2], int(sys.argv[3])
spec = importlib.util.spec_from_file_location("dccrg_live_reg", reg_path)
mod = importlib.util.module_from_spec(spec)
spec.loader.exec_module(mod)
assert "jax" not in sys.modules, "registry file-load imported jax"
reg = mod.MetricsRegistry(enabled=True)
reg.set_histogram_resolution("ensemble.e2e_s", 8)
tenant = "t%d" % wid
seq = 0
f = open(out_path, "w")
def snap():
    global seq
    rec = {"seq": seq, "ts": time.time(), **reg.report()}
    f.write(json.dumps(rec, default=float) + "\n")
    f.flush()
    seq += 1
for j in range(30):
    v = 0.001 * (1 + ((7 * j + 3 * wid) % 40))
    reg.observe("ensemble.e2e_s", v, tenant=tenant)
    reg.inc("ensemble.steps_served", 1, tenant=tenant)
    if j % 5 == 0:
        reg.inc("ensemble.deadline_miss", 1, tenant=tenant)
    if j % 3 == 0:
        snap()
    time.sleep(0.005)
if wid == 1:
    seq += 2  # injected seq gap: two line numbers never written
snap()
if wid == 1:
    f.write('{"seq": %d, "ts"' % seq)  # torn final line: cut mid-write
    f.flush()
f.close()
"""


def _live_probe(g, adv, state, dt, steps: int, reps: int = 11,
                threshold: float = 1.05,
                skip_overhead: bool = False) -> list:
    """Live-telemetry round (ISSUE 16).

    Two subprocess writers stream deterministic registry snapshots into
    a scratch directory (one injects a seq gap and a torn final line)
    while the aggregator tails them; then the probe requires:

    * windowed counts EXACT: the full-window fleet counters equal the
      sum of both writers' final cumulative totals — tailing lost
      nothing to the torn tail or the gap;
    * the live windowed p99 equals the post-hoc pooled
      ``obs/slo.py`` quantile on the same files to within one bucket
      (the acceptance criterion: live == post-hoc on pooled exports);
    * seq gaps and torn tails are COUNTED (tailer and
      ``validate_stream`` agree on the tallies);
    * a forced deadline-miss burst fires its alert rule EXACTLY once
      (no flap across repeated polls) and leaves exactly one
      schema-valid flight-recorder dump naming the rule;
    * the <=5% overhead budget re-passes with a live tailer polling the
      probe's own stream in the background (skipped with
      ``--skip-overhead``)."""
    import subprocess
    import threading

    from dccrg_tpu import obs
    from dccrg_tpu.obs import alerts as alerts_mod
    from dccrg_tpu.obs import flight_recorder, live, slo, validate_flightrec

    failures: list = []
    reg_path = str(ROOT / "dccrg_tpu" / "obs" / "registry.py")
    prev_dir = flight_recorder.armed_dir
    td = tempfile.mkdtemp(prefix="dccrg_live_probe_")
    try:
        paths = [os.path.join(td, f"writer{i}.stream.jsonl")
                 for i in (0, 1)]
        procs = [
            subprocess.Popen([sys.executable, "-c", _LIVE_WRITER_SRC,
                              reg_path, paths[i], str(i)])
            for i in (0, 1)
        ]
        agg = live.FleetAggregator(td, window_s=3600.0)
        while any(p.poll() is None for p in procs):
            agg.poll()
            time.sleep(0.02)
        for i, p in enumerate(procs):
            if p.returncode != 0:
                failures.append(
                    f"live probe: writer {i} exited {p.returncode}")
        agg.poll()  # pick up the final lines (and the torn fragment)
        view = agg.view()

        # ---- exact windowed counts vs the writers' cumulative truth
        served = view.counter("ensemble.steps_served")
        missed = view.counter("ensemble.deadline_miss")
        e2e = view.histogram("ensemble.e2e_s")
        if served != 60:
            failures.append(
                f"live probe: windowed ensemble.steps_served {served} "
                "!= 60 (2 writers x 30) — the tailer dropped lines")
        if missed != 12:
            failures.append(
                f"live probe: windowed ensemble.deadline_miss {missed} "
                "!= 12 (2 writers x 6)")
        if int(e2e.get("count") or 0) != 60:
            failures.append(
                f"live probe: windowed e2e histogram count "
                f"{e2e.get('count')} != 60")

        # ---- live windowed p99 == post-hoc pooled within one bucket
        pooled_reports = [slo.load_report(p) for p in paths]
        pooled = slo.merge_series(pooled_reports, "ensemble.e2e_s")
        pooled_all = slo.merge(*pooled.values()) if pooled else {}
        for q in (0.5, 0.95, 0.99):
            live_q = view.quantile("ensemble.e2e_s", q)
            post_q = slo.quantile(pooled_all, q)
            if live_q is None or post_q is None:
                failures.append(
                    f"live probe: q={q} unavailable "
                    f"(live={live_q}, pooled={post_q})")
                continue
            bucket = 2.0 ** (1.0 / slo.SLO_RESOLUTION)
            if not (post_q / bucket <= live_q <= post_q * bucket + 1e-12):
                failures.append(
                    f"live probe: windowed p{round(q * 100)} {live_q} "
                    f"not within one bucket of pooled {post_q}")

        # ---- anomaly counting: tailer and validate_stream agree
        if view.health["seq_gaps"] != 2:
            failures.append(
                f"live probe: tailer counted {view.health['seq_gaps']} "
                "seq gaps, expected exactly 2 (injected)")
        if view.health["torn_tails"] < 1:
            failures.append(
                "live probe: the torn final line was never counted")
        counts: dict = {}
        vs_failures = validate_stream(paths[1], counts)
        failures += [f"live probe writer1 stream: {f}"
                     for f in vs_failures]
        if counts.get("seq_gaps") != 2 or counts.get("torn_tail") != 1:
            failures.append(
                f"live probe: validate_stream counted {counts}, "
                "expected seq_gaps=2 torn_tail=1")

        # ---- forced deadline-miss burst: one fire, no flap, one dump
        flight_recorder.arm(td, autodump=False)
        rule = alerts_mod.AlertRule(
            "burst-miss-rate", "ensemble.deadline_miss",
            source="miss_rate", kind="ceiling",
            threshold=0.01, clear=0.005, for_s=0.0)
        engine = alerts_mod.AlertEngine(
            [rule], registry=obs.metrics, flight_recorder=flight_recorder)
        for _ in range(4):  # the burst persists: must not flap
            engine.poll(view)
        st = engine.state("burst-miss-rate")
        if st["fires"] != 1 or st["clears"] != 0 \
                or st["status"] != "firing":
            failures.append(
                f"live probe: alert fired {st['fires']}x cleared "
                f"{st['clears']}x status={st['status']} — wanted "
                "exactly one fire, still firing (no flap)")
        dumps = sorted(
            p for p in os.listdir(td)
            if p.startswith("flightrec_") and p.endswith(".json"))
        if len(dumps) != 1:
            failures.append(
                f"live probe: alert firing left {len(dumps)} dumps "
                f"({dumps}), wanted exactly one per incident")
        for p in dumps:
            full = os.path.join(td, p)
            failures += [f"live probe flightrec {p}: {f}"
                         for f in validate_flightrec(full)]
            with open(full) as fh:
                rec = json.load(fh)
            named = "burst-miss-rate" in str(rec.get("reason", "")) or any(
                ev.get("rule") == "burst-miss-rate"
                for ev in rec.get("events", [])
                if isinstance(ev, dict))
            if not named:
                failures.append(
                    f"live probe: postmortem {p} does not name the "
                    "firing rule")

        # ---- overhead budget re-passed with a live tailer running
        if not skip_overhead:
            stream_path = os.path.join(td, "probe.stream.jsonl")
            s = obs.TelemetryStream(stream_path, period=0.05,
                                    truncate=True)
            s.start()
            tail_agg = live.FleetAggregator([stream_path],
                                            window_s=60.0)
            stop_evt = threading.Event()

            def _tail_loop():
                while not stop_evt.is_set():
                    tail_agg.poll()
                    stop_evt.wait(0.05)

            t = threading.Thread(target=_tail_loop, daemon=True)
            t.start()
            try:
                over = _overhead_probe(g, adv, state, dt, steps,
                                       reps=reps, threshold=threshold)
                failures += [f"with live tailer: {f}" for f in over]
            finally:
                stop_evt.set()
                t.join(timeout=5.0)
                s.stop(final=False)
    except Exception as e:  # noqa: BLE001 — probe reports, not dies
        failures.append(f"live probe failed: {e!r}")
    finally:
        if prev_dir is not None:
            flight_recorder.arm(prev_dir)
        else:
            flight_recorder.disarm()
        import shutil

        shutil.rmtree(td, ignore_errors=True)
    return failures


def _device_timeline_probe(g, adv, state, dt, out_path: str,
                           merged_path: str | None = None) -> list:
    """Profiled round (ISSUE 6): capture one split-phase drive under
    ``jax.profiler``, merge the xplane capture with the host timeline,
    and require the measured plane to materialize — a schema-valid
    merged trace next to ``telemetry.json``, a nonzero
    ``overlap.fraction{phase=halo}`` gauge, per-device busy gauges, and
    per-kernel device-time attribution intersecting the
    ``epoch.recompiles`` kernel set.  On a backend whose capture holds
    no execution lines at all (no device planes, no XLA runtime
    threads), or under ``DCCRG_XPLANE=0``, the probe is the documented
    no-op: it notes the absence and requires nothing."""
    from dccrg_tpu import obs
    from dccrg_tpu.obs.xplane import xplane_enabled

    failures: list = []
    if not xplane_enabled():
        print("device-timeline probe skipped (DCCRG_XPLANE=0)",
              file=sys.stderr)
        return failures
    if merged_path is None:
        merged_path = artifact_path(out_path, ".merged_trace.json")
    with tempfile.TemporaryDirectory() as td:
        try:
            with obs.profile_trace(td):
                drive_split(g, adv, state, dt, 6)
            # compacted export: the probe trace rides next to
            # telemetry.json in the repo — gauges use the full spans,
            # the artifact keeps the longest per device (truncation
            # noted in otherData.device_spans_dropped)
            _merged, summary = obs.merge_profile(
                td, out_path=merged_path, out_max_spans=250,
            )
        except Exception as e:  # noqa: BLE001 — probe must report, not die
            return [f"device-timeline probe failed: {e!r}"]
    if not summary["device_evidence"]:
        print("device-timeline probe: capture holds no execution lines "
              "(deviceless backend) — overlap/busy gauges not required",
              file=sys.stderr)
        return failures
    # ISSUE 7: fused split-phase rounds — one compiled start → interior
    # → finish → boundary program per model — must measure their own
    # overlap, recorded per model so telemetry_diff's floor gate watches
    # each series (not just the host-split GoL/advection drive above)
    for model in ("advection", "vlasov"):
        try:
            step_once, mstate = build_fused_model(g, model)
            mstate = drive_fused(step_once, mstate, 1)   # warm compiles
            with tempfile.TemporaryDirectory() as td:
                with obs.profile_trace(td):
                    drive_fused(step_once, mstate, 4)
                obs.merge_profile(td, extra_labels={"model": model})
        except Exception as e:  # noqa: BLE001 — probe reports, not dies
            failures.append(
                f"fused split-phase {model} probe failed: {e!r}"
            )
    rep = obs.metrics.report()
    gauges = rep["gauges"]
    frac = gauges.get("overlap.fraction", {}).get("phase=halo")
    if frac is None:
        failures.append("overlap.fraction{phase=halo} gauge missing "
                        "after the profiled round")
    elif not 0.0 < frac <= 1.0:
        failures.append(
            f"overlap.fraction{{phase=halo}} = {frac}: the split-phase "
            "probe must measure nonzero in-(0,1] overlap"
        )
    for model in ("advection", "vlasov"):
        mfrac = gauges.get("overlap.fraction", {}).get(
            f"model={model},phase=halo"
        )
        if mfrac is None:
            failures.append(
                f"overlap.fraction{{model={model},phase=halo}} gauge "
                "missing after the fused split-phase round"
            )
        elif not 0.0 < mfrac <= 1.0:
            failures.append(
                f"overlap.fraction{{model={model},phase=halo}} = "
                f"{mfrac}: the fused round must measure nonzero "
                "in-(0,1] overlap"
            )
    if not gauges.get("device.busy_fraction"):
        failures.append("device.busy_fraction{device=d} gauges missing "
                        "after the profiled round")
    attributed = set(rep["counters"].get("device.kernel_time_us", {}))
    recompiled = set(rep["counters"].get("epoch.recompiles", {}))
    if not attributed & recompiled:
        failures.append(
            "device-time attribution names never intersect the "
            f"epoch.recompiles kernel set (attributed: "
            f"{sorted(attributed)[:6]}; compiled: "
            f"{sorted(recompiled)[:6]}) — the compiled->ran loop is "
            "broken"
        )
    failures += [
        f"merged trace: {f}"
        for f in obs.validate_merged_trace(merged_path)
    ]
    return failures


def run_check(out_path: str, steps: int = 20, skip_overhead: bool = False,
              reps: int = 11, threshold: float = 1.05,
              artifact_dir: str | None = None) -> list:
    """Run the workload + checks; returns a list of failure strings
    (empty = pass) and writes ``telemetry.json`` to ``out_path`` (side
    artifacts — stream/trace/merged-trace — via :func:`artifact_path`)."""
    _ensure_env()
    import numpy as np

    from dccrg_tpu import obs

    failures: list = []
    obs.metrics.reset()
    obs.enable()
    obs.timeline.clear()
    obs.enable_timeline()

    g, adv, state, dt = build_workload()
    state = drive(g, adv, state, dt, steps)

    # checkpoint write + read-back round (the checkpoint.* phases)
    spec = {"density": ((), np.float32)}
    with tempfile.TemporaryDirectory() as td:
        ckpt = os.path.join(td, "telemetry_probe.dc")
        g.save_grid_data(state, ckpt, spec)
        from dccrg_tpu.grid import Grid

        g2, st2, _hdr = Grid.load_grid_data(ckpt, spec)
        same = np.allclose(
            np.asarray(g.get_cell_data(state, "density", g.get_cells())),
            np.asarray(g2.get_cell_data(st2, "density", g.get_cells())),
        )
        if not same:
            failures.append("checkpoint round-trip altered the payload")

    failures += _resilience_probe(g, state)
    failures += _churn_probe(g, dt)
    failures += _halo_backend_probe()
    failures += _ensemble_probe()
    failures += _wide_halo_probe()
    failures += _slo_probe()

    if not skip_overhead:
        # measured BEFORE the profiled round: the xplane ingest/merge
        # allocates MBs of span records whose GC pauses would otherwise
        # land inside the timed reps and flake the 5% budget
        failures += _overhead_probe(g, adv, state, dt, steps,
                                    reps=reps, threshold=threshold)
    failures += _live_probe(g, adv, state, dt, steps,
                            reps=reps, threshold=threshold,
                            skip_overhead=skip_overhead)
    # after the timed overhead reps for the same reason as the xplane
    # round: the cost probe's burst ensembles allocate enough that
    # their GC debt would land inside the 5% budget's timed halves
    # (the budget is still measured with the cost model armed —
    # DCCRG_COST_MODEL defaults on, asserted inside the probe)
    failures += _cost_probe()
    failures += _elastic_probe(g, state)
    failures += _fleet_probe()
    failures += _device_timeline_probe(
        g, adv, state, dt, out_path,
        merged_path=artifact_path(out_path, ".merged_trace.json",
                                  artifact_dir),
    )

    report = g.report()
    for phase in REQUIRED_PHASES:
        rec = report["phases"].get(phase)
        if not rec or rec["count"] < 1:
            failures.append(f"instrumented phase missing from report: "
                            f"{phase!r}")
    for counter in REQUIRED_NONZERO_COUNTERS:
        series = report["counters"].get(counter, {})
        if not any(v > 0 for v in series.values()):
            failures.append(f"counter {counter!r} recorded no value")
    for hist in REQUIRED_HISTOGRAMS:
        series = report["histograms"].get(hist, {})
        if not any(h.get("count", 0) > 0 for h in series.values()):
            failures.append(f"histogram {hist!r} recorded no samples — "
                            "the SLO plane lost its distribution")

    rep = obs.export_json(out_path, extra={
        "workload": f"advection 8^3 refined-ball, {steps} steps, "
                    f"{g.n_devices} devices",
        "n_cells": int(len(g.get_cells())),
    })
    try:
        with open(out_path) as f:
            loaded = json.load(f)
        if loaded["phases"].keys() != rep["phases"].keys():
            failures.append("telemetry.json phase set differs from report")
    except (OSError, ValueError, KeyError) as e:
        failures.append(f"telemetry.json unreadable: {e}")

    # streaming exporter: a few explicit snapshots (no timer sleeps —
    # the probe must stay fast/deterministic) driven through real work
    # between ticks, then schema-validated like any soak/bench stream
    stream_path = artifact_path(out_path, ".stream.jsonl", artifact_dir)
    s = obs.TelemetryStream(stream_path, period=3600.0, truncate=True,
                            extra={"workload": "check_telemetry probe"})
    s.write_snapshot(checkpoint="pre")
    state = drive(g, adv, state, dt, 2)
    s.write_snapshot(checkpoint="mid")
    s.stop(final=True)
    failures += [f"stream: {f}" for f in validate_stream(stream_path)]

    # event timeline: the probe's spans as a Chrome trace, validated for
    # matched begin/end pairs and monotonic in-thread timestamps
    trace_path = artifact_path(out_path, ".trace.json", artifact_dir)
    if not obs.timeline.enabled or len(obs.timeline) == 0:
        failures.append("event timeline recorded no spans during probe")
    obs.export_chrome_trace(trace_path)
    failures += [f"trace: {f}" for f in validate_chrome_trace(trace_path)]

    return failures


def _overhead_probe(g, adv, state, dt, steps: int, reps: int = 11,
                    threshold: float = 1.05) -> list:
    """Enabled-vs-disabled step-loop cost.  The loop is dominated by
    collective rendezvous on an oversubscribed host, so single
    measurements jitter by several percent — alternate the mode order
    each rep (cancels warm-cache ordering bias), collect garbage first
    (a stray GC pause inside one rep skews its half), and compare
    medians.  The true enabled/disabled ratio sits a couple percent
    under the budget (measured ~1.02-1.04x over 25 reps), so a single
    median can still cross the line on a noisy host — a failed
    measurement is confirmed by ONE re-measure, and only failing both
    fails the gate (a real >5% regression fails every measurement; a
    scheduler stall fails one)."""
    import gc
    import statistics

    from dccrg_tpu import obs

    def measure() -> tuple:
        times: dict = {True: [], False: []}
        gc.collect()
        for i in range(reps):
            order = (True, False) if i % 2 == 0 else (False, True)
            for enabled in order:
                obs.metrics.enabled = enabled
                t0 = time.perf_counter()
                drive(g, adv, state, dt, steps)
                times[enabled].append(time.perf_counter() - t0)
        obs.enable()
        return (statistics.median(times[True]),
                statistics.median(times[False]))

    drive(g, adv, state, dt, 2)  # warm every compile
    on, off = measure()
    if on > off * threshold:
        on, off = measure()   # confirm before failing
    if on > off * threshold:
        return [
            f"telemetry overhead {on / off:.3f}x exceeds "
            f"{threshold:.2f}x (enabled median {on:.4f}s vs "
            f"disabled {off:.4f}s over {reps} reps, confirmed twice)"
        ]
    return []


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default=str(ROOT / "telemetry.json"),
                    help="where to write telemetry.json")
    ap.add_argument("--artifact-dir", default=None,
                    help="where the stream/trace/merged-trace side "
                         "artifacts land (default: next to --out, or "
                         "tools/ when --out is at the repo root — the "
                         "root stays free of bench byproducts)")
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--reps", type=int, default=11,
                    help="overhead-probe repetitions per mode (one rep "
                         "is a ~20-step loop, so reps are cheap; the "
                         "median over more reps keeps the 5%% gate from "
                         "flaking on scheduler jitter)")
    ap.add_argument("--threshold", type=float, default=1.05,
                    help="max allowed enabled/disabled step-loop ratio")
    ap.add_argument("--skip-overhead", action="store_true",
                    help="only check phase/counter completeness + export")
    ap.add_argument("--validate-stream", default=None, metavar="FILE",
                    help="only schema-validate an existing telemetry "
                         "JSONL stream and exit")
    ap.add_argument("--validate-trace", default=None, metavar="FILE",
                    help="only schema-validate an existing Chrome "
                         "trace-event export and exit")
    ap.add_argument("--validate-merged-trace", default=None, metavar="FILE",
                    help="only schema-validate an existing merged "
                         "host+device (or fleet) trace and exit")
    args = ap.parse_args(argv)
    if args.validate_stream or args.validate_trace or \
            args.validate_merged_trace:
        failures = []
        if args.validate_stream:
            counts: dict = {}
            failures += [f"stream: {f}"
                         for f in validate_stream(args.validate_stream,
                                                  counts)]
            print(f"stream: {counts['lines']} lines, "
                  f"{counts['seq_gaps']} seq gaps, "
                  f"{counts['torn_tail']} torn tail, "
                  f"{counts['bad_lines']} bad lines", file=sys.stderr)
        if args.validate_trace:
            failures += [f"trace: {f}"
                         for f in validate_chrome_trace(args.validate_trace)]
        if args.validate_merged_trace:
            _ensure_env()
            from dccrg_tpu.obs.merge import validate_merged_trace

            failures += [
                f"merged: {f}"
                for f in validate_merged_trace(args.validate_merged_trace)
            ]
        for f in failures:
            print(f"FAIL: {f}", file=sys.stderr)
        if not failures:
            print("telemetry stream/trace validation passed")
        return 1 if failures else 0
    failures = run_check(args.out, steps=args.steps,
                         skip_overhead=args.skip_overhead,
                         reps=args.reps, threshold=args.threshold,
                         artifact_dir=args.artifact_dir)
    if failures:
        for f in failures:
            print(f"FAIL: {f}", file=sys.stderr)
        return 1
    print(f"telemetry check passed; wrote {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
