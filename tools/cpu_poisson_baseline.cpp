// CPU baseline for the Poisson benchmark: the reference's matrix-free
// BiCG iteration (tests/poisson/poisson_solve.hpp — Numerical Recipes
// 2.7.6 variant: per iteration two matrix applications A.p0 / A^T.p1,
// three global dots, four axpys) on a uniform periodic grid, with the
// reference's compute pattern: AoS cells carrying the solver vectors and
// per-face factors, neighbor access through an index indirection list,
// double precision, multi-threaded over all host cores.
//
// The actual reference (dccrg + MPI + Zoltan) cannot be built in this
// image; this program re-creates its hot loop as the honest MPI-CPU
// denominator for BASELINE.md's protocol, exactly like
// tools/cpu_baseline.cpp does for advection.
//
// Usage: cpu_poisson_baseline NX NY NZ ITERS  -> prints cell-iterations/s
#include <cstdio>
#include <cstdlib>
#include <cstdint>
#include <chrono>
#include <cmath>
#include <vector>
#ifdef _OPENMP
#include <omp.h>
#endif

struct Cell {
    double rhs, x, r0, r1, p0, p1, Ap, ATp;
    double scale;     // diagonal
    double f[6];      // -x +x -y +y -z +z face factors
};

int main(int argc, char** argv) {
    const int64_t nx = argc > 1 ? atoll(argv[1]) : 64;
    const int64_t ny = argc > 2 ? atoll(argv[2]) : 64;
    const int64_t nz = argc > 3 ? atoll(argv[3]) : 64;
    const int64_t iters = argc > 4 ? atoll(argv[4]) : 30;
    const int64_t n = nx * ny * nz;

    std::vector<Cell> cells(n);
    std::vector<int64_t> nbr(n * 6);
    const double dx = 1.0 / nx, dy = 1.0 / ny, dz = 1.0 / nz;
    const double fx = 2.0 / (2.0 * dx * 4.0 * dx);
    const double fy = 2.0 / (2.0 * dy * 4.0 * dy);
    const double fz = 2.0 / (2.0 * dz * 4.0 * dz);
    for (int64_t z = 0; z < nz; z++)
    for (int64_t y = 0; y < ny; y++)
    for (int64_t x = 0; x < nx; x++) {
        const int64_t i = x + nx * (y + ny * z);
        Cell& c = cells[i];
        const double cx = (x + 0.5) * dx, cy = (y + 0.5) * dy;
        c.rhs = sin(2 * M_PI * cx) * cos(2 * M_PI * cy);
        c.x = c.r0 = c.r1 = c.p0 = c.p1 = 0.0;
        c.f[0] = c.f[1] = fx; c.f[2] = c.f[3] = fy; c.f[4] = c.f[5] = fz;
        c.scale = -2.0 * (fx + fy + fz);
        nbr[i * 6 + 0] = ((x + nx - 1) % nx) + nx * (y + ny * z);
        nbr[i * 6 + 1] = ((x + 1) % nx) + nx * (y + ny * z);
        nbr[i * 6 + 2] = x + nx * (((y + ny - 1) % ny) + ny * z);
        nbr[i * 6 + 3] = x + nx * (((y + 1) % ny) + ny * z);
        nbr[i * 6 + 4] = x + nx * (y + ny * ((z + nz - 1) % nz));
        nbr[i * 6 + 5] = x + nx * (y + ny * ((z + 1) % nz));
    }
    // r = rhs - A.x (x = 0), p = r
#pragma omp parallel for schedule(static)
    for (int64_t i = 0; i < n; i++) {
        cells[i].r0 = cells[i].r1 = cells[i].p0 = cells[i].p1 = cells[i].rhs;
    }
    double dot_r = 0;
#pragma omp parallel for schedule(static) reduction(+:dot_r)
    for (int64_t i = 0; i < n; i++) dot_r += cells[i].r0 * cells[i].r1;

    const auto t0 = std::chrono::high_resolution_clock::now();
    for (int64_t it = 0; it < iters; it++) {
        double dot_p = 0;
#pragma omp parallel for schedule(static) reduction(+:dot_p)
        for (int64_t i = 0; i < n; i++) {
            Cell& c = cells[i];
            double ap = c.scale * c.p0, atp = c.scale * c.p1;
            for (int k = 0; k < 6; k++) {
                const Cell& o = cells[nbr[i * 6 + k]];
                ap += c.f[k] * o.p0;
                atp += c.f[k] * o.p1;   // A^T: symmetric factors here,
            }                            // same work shape as reference
            c.Ap = ap; c.ATp = atp;
            dot_p += c.p1 * ap;
        }
        const double alpha = dot_p != 0 ? dot_r / dot_p : 0.0;
        double new_dot_r = 0;
#pragma omp parallel for schedule(static) reduction(+:new_dot_r)
        for (int64_t i = 0; i < n; i++) {
            Cell& c = cells[i];
            c.x += alpha * c.p0;
            c.r0 -= alpha * c.Ap;
            c.r1 -= alpha * c.ATp;
            new_dot_r += c.r0 * c.r1;
        }
        const double beta = dot_r != 0 ? new_dot_r / dot_r : 0.0;
#pragma omp parallel for schedule(static)
        for (int64_t i = 0; i < n; i++) {
            Cell& c = cells[i];
            c.p0 = c.r0 + beta * c.p0;
            c.p1 = c.r1 + beta * c.p1;
        }
        dot_r = new_dot_r;
    }
    const auto t1 = std::chrono::high_resolution_clock::now();
    const double secs = std::chrono::duration<double>(t1 - t0).count();
    volatile double sink = cells[n / 2].x;
    (void)sink;
    printf("%.6e\n", double(n) * iters / secs);
    return 0;
}
