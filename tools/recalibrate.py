#!/usr/bin/env python
"""Turn the on-chip battery's measurements into dispatch recalibrations.

Run after ``tools/onchip_r3.py`` has produced ``tools/onchip_r3.json``:

    python tools/recalibrate.py [--write]

Prints the measured flat-kernel per-voxel rates (padded vs unpadded),
the boxed path's per-voxel rate from the PINNED ``refined_boxed``
measurement (never inferred from whichever path the production dispatch
happened to pick — that inference self-invalidates once a written edge
flips the dispatch), and the recommended flat/boxed edge constant
(``_prefer_boxed``: prefer boxed when ``flat_n_vox > EDGE * boxed_vol``).
The constant is the measured ratio of the flat kernel's voxel-update
rate to the boxed path's, with a 0.8 safety factor so the dispatch only
flips when the win is clear.

``--write`` persists the constant to ``tools/dispatch_calibration.json``,
which ``models/advection.py`` reads at dispatch time; it refuses to
write when the needed measurements are missing or internally
inconsistent.
"""
import json
import pathlib
import sys

ROOT = pathlib.Path(__file__).resolve().parents[1]
BATTERY = ROOT / "tools" / "onchip_r3.json"


def main():
    if not BATTERY.exists():
        sys.exit("no tools/onchip_r3.json yet — run tools/onchip_r3.py "
                 "when the TPU tunnel is up")
    data = json.loads(BATTERY.read_text())

    sweep = data.get("flat_kernel_sweep_Bvox_per_s") or {}
    flat_unpadded = sweep.get("96x96x96")
    flat_padded = sweep.get("96x96x96x128")
    print("flat kernel sweep (B voxel-updates/s):")
    for k, v in sweep.items():
        print(f"  {k}: {v}")
    if isinstance(flat_padded, (int, float)) and \
            isinstance(flat_unpadded, (int, float)) and flat_unpadded:
        print(f"  lane-padding speedup on the refined-bench shape: "
              f"{flat_padded / flat_unpadded:.2f}x")

    disp = data.get("refined_dispatch") or {}
    if disp.get("updates_per_s"):
        print(f"\nrefined dispatch (production choice: "
              f"{disp.get('path', '?')}): "
              f"{disp['updates_per_s']:.3e} cell-updates/s")

    boxed = data.get("refined_boxed") or {}
    rate = boxed.get("updates_per_s")
    ok_to_write = False
    if rate and boxed.get("path") == "boxed" and boxed.get("boxed_vol"):
        n_cells = boxed["n_cells"]
        steps_per_s = rate / n_cells
        boxed_vox_rate = steps_per_s * boxed["boxed_vol"] / 1e9
        print(f"\nrefined boxed (pinned): {rate:.3e} cell-updates/s "
              f"-> {boxed_vox_rate:.2f} B voxel-updates/s")
        if isinstance(flat_padded, (int, float)) and boxed_vox_rate > 0:
            edge = flat_padded / boxed_vox_rate
            rec = round(0.8 * edge, 1)
            print(f"\npadded-flat / boxed per-voxel edge: {edge:.2f}")
            print(f"recommended _prefer_boxed edge constant "
                  f"(default 2.0): {rec}")
            if boxed.get("flat_n_vox"):
                ratio = boxed["flat_n_vox"] / boxed["boxed_vol"]
                print(f"refined-bench voxel ratio is {ratio:.2f} -> "
                      f"dispatch "
                      f"{'FLIPS to flat' if rec > ratio else 'stays boxed'} "
                      f"on that config with that constant")
            ml_key, ml_rec = _ml_edge(data)
            ok_to_write = 0.5 <= rec <= 100.0
            if "--write" in sys.argv:
                if not ok_to_write:
                    sys.exit(f"refusing to write out-of-range edge {rec}")
                record = {
                    "flat_boxed_edge": rec,
                    "source": "tools/recalibrate.py from onchip battery",
                }
                if ml_rec is not None:
                    record[ml_key] = ml_rec
                out = ROOT / "tools" / "dispatch_calibration.json"
                out.write_text(json.dumps(record, indent=1))
                print(f"wrote {out} — models/advection.py reads it at "
                      "dispatch time")
    else:
        print("\nno pinned refined_boxed measurement yet — cannot "
              "compute the edge (and will not infer it from the "
              "production dispatch's path)")
        if "--write" in sys.argv:
            sys.exit("refusing to write without a refined_boxed record")


def _ml_edge(data):
    """(key, edge) for the multi-level dispatch from the PINNED
    refined3_ml / refined3_boxed pair (both measure the identical
    3-level config, so the per-voxel rate ratio is direct).  The key
    names the KIND the battery actually measured — an edge measured on
    the VMEM-resident ml_pallas kernel must not govern the streaming
    XLA 'ml' form, whose per-voxel rate is different.  (None, None)
    when either side is missing, the kind is unrecognized, or the
    result is out of range."""
    ml = data.get("refined3_ml") or {}
    bx = data.get("refined3_boxed") or {}
    key = {"ml_pallas": "ml_pallas_boxed_edge",
           "ml": "ml_boxed_edge"}.get(ml.get("path"))
    if key is None or bx.get("path") != "boxed":
        return None, None
    try:
        ml_vox = ml["updates_per_s"] / ml["n_cells"] * ml["flat_n_vox"]
        bx_vox = bx["updates_per_s"] / bx["n_cells"] * bx["boxed_vol"]
    except (KeyError, TypeError, ZeroDivisionError):
        return None, None
    if not (ml_vox > 0 and bx_vox > 0):
        return None, None
    rec = round(0.8 * ml_vox / bx_vox, 2)
    print(f"\n{ml['path']} / boxed per-voxel edge (refined3 pair): "
          f"{ml_vox / bx_vox:.2f} -> recommended {key} {rec}")
    return (key, rec) if 0.5 <= rec <= 100.0 else (None, None)


if __name__ == "__main__":
    main()
