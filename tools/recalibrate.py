#!/usr/bin/env python
"""Turn the on-chip battery's measurements into dispatch recalibrations.

Run after ``tools/onchip_r3.py`` has produced ``tools/onchip_r3.json``:

    python tools/recalibrate.py

Prints the measured flat-kernel per-voxel rates (padded vs unpadded),
the boxed path's per-voxel rate inferred from the refined dispatch
measurement, and the recommended flat/boxed edge constant for
``models/advection.py`` (``_prefer_boxed``: prefer boxed when
``flat_n_vox > EDGE * boxed_vol``).  The constant is the measured ratio
of the flat kernel's voxel-update rate to the boxed path's — with a
0.8 safety factor so the dispatch only flips when the win is clear.
"""
import json
import pathlib
import sys

ROOT = pathlib.Path(__file__).resolve().parents[1]
BATTERY = ROOT / "tools" / "onchip_r3.json"

#: the refined bench grid's dispatch inputs (48^3 coarse, ball refined;
#: computed from the grid build — see the session notes)
REFINED_N_CELLS = 198008
REFINED_BOXED_VOL = 292480
REFINED_FLAT_VOX = 884736


def main():
    if not BATTERY.exists():
        sys.exit("no tools/onchip_r3.json yet — run tools/onchip_r3.py "
                 "when the TPU tunnel is up")
    data = json.loads(BATTERY.read_text())

    sweep = data.get("flat_kernel_sweep_Bvox_per_s") or {}
    flat_unpadded = sweep.get("96x96x96")
    flat_padded = sweep.get("96x96x96x128")
    print("flat kernel sweep (B voxel-updates/s):")
    for k, v in sweep.items():
        print(f"  {k}: {v}")
    if isinstance(flat_padded, (int, float)) and \
            isinstance(flat_unpadded, (int, float)) and flat_unpadded:
        print(f"  lane-padding speedup on the refined-bench shape: "
              f"{flat_padded / flat_unpadded:.2f}x")

    ref = data.get("refined_dispatch") or {}
    rate = ref.get("updates_per_s")
    if rate:
        n_cells = ref.get("n_cells", REFINED_N_CELLS)
        if n_cells != REFINED_N_CELLS:
            print(f"\nWARNING: measured n_cells {n_cells} != the hardcoded "
                  f"dispatch inputs ({REFINED_N_CELLS}) — the boxed volume "
                  f"and voxel ratio below are stale; recompute them for "
                  f"the current bench config")
        steps_per_s = rate / n_cells
        print(f"\nrefined dispatch: {rate:.3e} cell-updates/s "
              f"({steps_per_s:.0f} steps/s)")
        # whichever path the dispatch picked retires its voxel volume
        # at steps_per_s; infer the boxed per-voxel rate from it when
        # boxed was picked (the current default at edge 2.0)
        boxed_vox_rate = steps_per_s * REFINED_BOXED_VOL / 1e9
        print(f"  implied boxed per-voxel rate (if boxed ran): "
              f"{boxed_vox_rate:.2f} B voxel-updates/s")
        if isinstance(flat_padded, (int, float)):
            edge = flat_padded / boxed_vox_rate
            rec = round(0.8 * edge, 1)
            print(f"\npadded-flat / boxed per-voxel edge: {edge:.2f}")
            print(f"recommended _prefer_boxed constant "
                  f"(models/advection.py, currently 2.0): {rec}")
            ratio = REFINED_FLAT_VOX / REFINED_BOXED_VOL
            print(f"refined-bench voxel ratio is {ratio:.2f} -> dispatch "
                  f"{'FLIPS to flat' if rec > ratio else 'stays boxed'} "
                  f"on that config with that constant")
    else:
        print("\nno refined_dispatch measurement yet")


if __name__ == "__main__":
    main()
