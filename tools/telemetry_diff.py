#!/usr/bin/env python
"""Perf-regression gate: diff two rounds' telemetry phase breakdowns.

Compares the CURRENT round's phase timings (``telemetry.json``, a
``BENCH_DETAIL.json`` record, or a streaming JSONL snapshot — the last
line wins) against a BASELINE of the same shapes and fails (exit 1) when
any gated phase's mean time regresses by more than ``--threshold``
(fractional: 0.35 = +35%).  Phases named via ``--allow`` are reported
but never fail the gate (the allowlist knob for intentional changes).

Baseline discovery (``--baseline`` omitted): first of
``tools/telemetry_prev.json`` (the previous round's probe, archived by
``bench.py`` before it overwrites ``telemetry.json``), then
``BENCH_DETAIL.json``'s embedded phase table.  ``bench.py`` runs this
gate per round and attaches the verdict to the bench record; CI can run
it standalone:

    python tools/telemetry_diff.py                      # auto-discover
    python tools/telemetry_diff.py --current telemetry.json \
        --baseline tools/telemetry_prev.json --threshold 0.5 \
        --allow amr.refine --json verdict.json

Mean per completed span (``total_s / count``) is compared, not totals —
rounds legitimately run different phase counts.  Phases whose baseline
total is below ``--min-total`` are skipped as noise (a 50-microsecond
phase doubling is jitter, not a regression).  A phase present in the
baseline but MISSING from the current round is a coverage loss and
fails the gate (unless allowlisted); new phases only inform.

History + drift: every run appends its phase table to
``tools/telemetry_history.jsonl`` (last ``--history-keep`` rounds
retained) and ALSO gates the current round against the OLDEST retained
round with ``--drift-threshold`` — a phase creeping a few percent per
round never trips the step gate but doubles over the window; the drift
gate catches exactly that.  ``--no-history`` disables both.
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import pathlib
import sys

ROOT = pathlib.Path(__file__).resolve().parent.parent

#: the hot-seam phases the gate watches by default (halo / epoch / the
#: in-loop step seams ISSUE 2 names, plus ISSUE 3's incremental
#: rebuild); --phases overrides
DEFAULT_PHASES = (
    "halo.exchange",
    # ISSUE 6: the split-phase dispatch seam — the in-flight window the
    # overlap gauge measures is opened here, and its dispatch cost is a
    # hot-path regression like the blocking exchange's
    "halo.start",
    "epoch.build",
    "epoch.hood_build",
    "epoch.delta_build",
    "loadbalance.migrate",
    "amr.refine",
    "checkpoint.write",
    "checkpoint.read",
    # ISSUE 5: time spent (re)tracing kernels — a round whose compile
    # mean balloons lost shape stability somewhere
    "compile",
)

#: counters gated round-over-round (total across labels): a probe round
#: that compiles more kernels than the previous round regressed the
#: shape-stable-epoch contract even if each compile stayed cheap
GATED_COUNTERS = (
    "epoch.recompiles",
    # ISSUE 17: the model-driven select_k slack clamp prices dispatch
    # width from pooled step-cost quantiles instead of the cohort EMA —
    # the one regression that pricing change could introduce is MISSING
    # MORE DEADLINES.  The probe workload pins the count (the SLO probe
    # produces exactly its scripted misses; the cost probe submits no
    # deadlines), so any rise here is the clamp mispricing, not noise.
    "ensemble.deadline_miss",
    # ISSUE 19: the fleet probe scripts its gateway workload exactly —
    # 4 accepted scenarios, 1 pinned-queue rejection, one forced worker
    # kill whose in-flight set redispatches, one journal reopen.  Every
    # one of these counts is probe-pinned, so a round-over-round rise
    # is a behavioral regression, not workload noise: extra accepts or
    # rejects mean admission drifted, extra redispatches mean spurious
    # worker losses (a stall-budget or heartbeat regression), extra
    # replays mean journals started reopening when they shouldn't.
    "gateway.accepted",
    "gateway.rejected",
    "gateway.redispatched",
    "gateway.journal_replays",
)

#: counters REPORTED round-over-round but never failed (ISSUE 16): how
#: many alert rules fired is incident evidence the diff should surface
#: next to the perf verdict, but firing count is workload-shaped (a
#: fault-injection round SHOULD fire) — a rise is information, not a
#: regression
INFO_COUNTERS = (
    "alerts.fired",
)


def load_counters(path: str) -> dict | None:
    """Counter table ``{name: {labels: value}}`` from the same shapes
    :func:`load_phases` reads, or None when the source carries none."""
    p = pathlib.Path(path)
    try:
        text = p.read_text()
        if p.suffix == ".jsonl" or "\n{" in text.strip():
            last = None
            for ln in text.splitlines():
                ln = ln.strip()
                if not ln:
                    continue
                try:
                    rec = json.loads(ln)
                except json.JSONDecodeError:
                    continue
                if isinstance(rec, dict) and "counters" in rec:
                    last = rec
            return dict(last["counters"]) if last else None
        data = json.loads(text)
        if "counters" in data:
            return dict(data["counters"])
        tel = (data.get("detail") or {}).get("telemetry") or {}
        if "counters" in tel:
            return dict(tel["counters"])
    except (OSError, ValueError, json.JSONDecodeError):
        pass
    return None


def compare_counters(current: dict | None, baseline: dict | None,
                     threshold: float = 0.35,
                     counters=GATED_COUNTERS,
                     informational=()) -> dict:
    """Round-over-round gate on counter TOTALS (labels summed).  Either
    side missing the table (old rounds, bench records without counters)
    passes vacuously — the gate only engages once both rounds carry
    counter evidence.  ``informational`` counters are tabulated the same
    way but can never fail the gate (status ``info``)."""
    rows = []
    failures = []
    if current is None or baseline is None:
        return {"verdict": "PASS", "rows": rows, "failures": failures}
    info = set(informational)
    for name in tuple(counters) + tuple(informational):
        b = baseline.get(name)
        c = current.get(name)
        if b is None:
            if name in info and c:
                # informational counters surface even without baseline
                # history — new alert activity is evidence, not a fail
                rows.append({"counter": name, "base_total": 0,
                             "cur_total": sum(c.values()),
                             "status": "info"})
            continue
        b_tot = sum(b.values())
        c_tot = sum(c.values()) if c else 0
        row = {"counter": name, "base_total": b_tot, "cur_total": c_tot}
        if name in info:
            row["status"] = "info"
            if b_tot > 0:
                row["ratio"] = round(c_tot / b_tot, 3)
        elif b_tot > 0:
            ratio = c_tot / b_tot
            row["ratio"] = round(ratio, 3)
            if ratio > 1.0 + threshold:
                row["status"] = "REGRESSED"
                failures.append(
                    f"{name}: total {b_tot} -> {c_tot} ({ratio:.2f}x, "
                    f"threshold {1 + threshold:.2f}x)"
                )
            else:
                row["status"] = "ok"
        else:
            row["status"] = "ok" if c_tot == 0 else "new-activity"
        rows.append(row)
    return {
        "verdict": "FAIL" if failures else "PASS",
        "rows": rows,
        "failures": failures,
    }

#: phases reported but never gated (merged with --allow): the ISSUE 4
#: resilience phases time fault-injection rounds and recovery scans,
#: whose cost is dominated by how many faults the round armed and how
#: many generations the scan had to skip — round-over-round variation
#: there is workload-shaped, not a perf regression.  Same for the
#: ISSUE 6 trace-processing phases: ingest/merge cost scales with how
#: many spans the profiled round happened to capture.
DEFAULT_ALLOW = (
    "lineage.commit",
    "lineage.scan",
    "xplane.ingest",
    "trace.merge",
    # ISSUE 7 halo-backend phase: the oracle cross-check replays every
    # exchange on the collective path when DCCRG_HALO_VERIFY=1 — its
    # cost scales with how many exchanges the round chose to verify,
    # which is workload-shaped, not a perf regression
    "halo.verify",
    # ISSUE 8 elastic phases: a rescale is checkpoint-commit + reload +
    # verify, and a supervisor poll is file tailing — both are sized by
    # how many rescales/stalls the round happened to drive (one-off
    # rescale spikes are the MECHANISM working, not a regression)
    "elastic.rescale",
    "supervisor.poll",
    # ISSUE 9 ensemble phases: admit cost scales with how many scenarios
    # the round submitted and step cost with the cohort widths it chose
    # to drive; the verify phase replays solo members on demand — all
    # workload-shaped.  The regression the gate DOES watch is the
    # cohort-occupancy floor (GATED_GAUGES_MIN) and the recompile
    # counter: a serving round that starts retracing or fragmenting its
    # cohorts fails there, not on wall time.
    "ensemble.admit",
    "ensemble.step",
    "ensemble.verify",
    # ISSUE 10 flight-recorder phase: a dump's cost is sized by the ring
    # contents and how many postmortems the round's incidents triggered
    # — workload-shaped, not a perf regression.  The SLO regression the
    # gate DOES watch is the request-latency quantile ceiling
    # (GATED_QUANTILES below).
    "flightrec.dump",
    # ISSUE 16 live-telemetry phases: an aggregator poll is sized by how
    # many stream files grew and by how much, an alert evaluation by how
    # many rules the run configured — both workload-shaped.  The alert
    # OUTCOME is surfaced via the informational alerts.fired counter.
    "live.poll",
    "alerts.evaluate",
    # ISSUE 17 cost plane: an admission estimate runs once per submitted
    # scenario, so its total scales with how many scenarios a probe
    # round submits — workload-shaped.  The OUTCOME the gate watches is
    # ensemble.deadline_miss (GATED_COUNTERS above): the model-driven
    # clamp must not miss more deadlines than the EMA-only baseline.
    "cost.estimate",
)

#: gauges gated round-over-round where a DROP is the regression: the
#: measured halo overlap fraction falling means communication stopped
#: hiding under compute — exactly what the device-timeline plane exists
#: to catch.  Engages only when both rounds carry the gauge (older
#: rounds and deviceless backends pass vacuously).  The floor applies
#: PER LABELED SERIES, so the ISSUE 7 per-model gauges
#: (``overlap.fraction{model=advection|vlasov, phase=halo}`` from the
#: fused split-phase probe rounds) are each gated — and one going
#: missing is a coverage loss — the moment a baseline round carries
#: them.
GATED_GAUGES_MIN = (
    "overlap.fraction",
    # ISSUE 9: highest occupied fraction each cohort reached (labeled by
    # the cross-process-stable signature).  A DROP means admissions
    # stopped packing scenarios into shared executables — cohort
    # fragmentation, exactly the regression ensemble serving exists to
    # prevent.  Monotone per round by construction (a peak), so the
    # floor is meaningful where live occupancy (which legitimately
    # returns to 0 after retirement) would be noise.
    "ensemble.cohort_peak_occupancy",
)

#: gauges gated round-over-round where a RISE is the regression
#: (ISSUE 11): per-member cohort memory (unique table buffers + the
#: in-flight state cost, per ``obs/hbm.py``) is exactly what buffer
#: donation and broadcast-shared tables bought down — a round where it
#: climbs back past the ceiling means stacked table copies or the
#: dispatch-time state double-buffer crept back in, the scenarios-per-
#: chip regression this gate exists to catch.  Engages only when both
#: rounds carry the gauge; per labeled series (one per model kind).
GATED_GAUGES_MAX = (
    "ensemble.hbm_bytes_per_member",
    # ISSUE 14 headline: cumulative exchanges per interior step, ~1/k
    # with wide halos engaged, 1.0 legacy.  A round where it climbs
    # past the ceiling means dispatches stopped amortizing the halo
    # exchange — the regression exchange-amortized deep dispatch
    # exists to prevent.  Per labeled series (one per model kind).
    "halo.exchanges_per_step",
)


#: request-latency histograms whose upper quantile is CEILING-gated
#: round-over-round (ISSUE 10): per labeled series, the current round's
#: p99 may not exceed the baseline's by more than the threshold — the
#: request-level analogue of the phase-mean gate.  Engages only when
#: both rounds carry the series with enough samples; the quantile comes
#: from the exported log buckets (obs/slo.py), so the gate needs no
#: live process.
GATED_QUANTILES = (
    ("ensemble.queue_wait_s", 0.99),
    ("ensemble.e2e_s", 0.99),
    ("ensemble.service_s", 0.99),
)

#: baseline p99s below this many seconds are bucket-resolution noise,
#: not a meaningful ceiling (a 50µs p99 doubling is jitter)
QUANTILE_MIN_BASE_S = 1e-4

_SLO = None


def _slo():
    """Lazy file-load of ``dccrg_tpu/obs/slo.py`` (stdlib-only by
    contract) — the quantile estimator, without importing the package
    (and thus jax) into this gate."""
    global _SLO
    if _SLO is None:
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "dccrg_slo", str(ROOT / "dccrg_tpu" / "obs" / "slo.py"))
        _SLO = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(_SLO)
    return _SLO


def load_histograms(path: str) -> dict | None:
    """Histogram table ``{name: {labels: hist}}`` from the same shapes
    :func:`load_phases` reads, or None when the source carries none."""
    p = pathlib.Path(path)
    try:
        text = p.read_text()
        if p.suffix == ".jsonl" or "\n{" in text.strip():
            last = None
            for ln in text.splitlines():
                ln = ln.strip()
                if not ln:
                    continue
                try:
                    rec = json.loads(ln)
                except json.JSONDecodeError:
                    continue
                if isinstance(rec, dict) and "histograms" in rec:
                    last = rec
            return dict(last["histograms"]) if last else None
        data = json.loads(text)
        if "histograms" in data:
            return dict(data["histograms"])
        tel = (data.get("detail") or {}).get("telemetry") or {}
        if "histograms" in tel:
            return dict(tel["histograms"])
    except (OSError, ValueError, json.JSONDecodeError):
        pass
    return None


def compare_quantiles(current: dict | None, baseline: dict | None,
                      threshold: float = 0.35, gated=GATED_QUANTILES,
                      min_base_s: float = QUANTILE_MIN_BASE_S,
                      min_count: int = 2) -> dict:
    """Ceiling gate on per-label latency quantiles: fails when a gated
    series' quantile exceeds ``baseline * (1 + threshold)``.  Either
    side lacking the table, the series, or enough samples passes
    vacuously — label sets legitimately differ per round (tenants come
    and go), so a missing label only informs."""
    rows = []
    failures = []
    if current is None or baseline is None:
        return {"verdict": "PASS", "rows": rows, "failures": failures}
    slo = _slo()
    for name, q in gated:
        base_series = baseline.get(name)
        if not base_series:
            continue
        cur_series = current.get(name) or {}
        for label, bh in base_series.items():
            ch = cur_series.get(label)
            row = {"histogram": name, "labels": label, "q": q}
            if not isinstance(bh, dict) or bh.get("count", 0) < min_count:
                row["status"] = "below-sample-floor"
                rows.append(row)
                continue
            bq = slo.quantile(bh, q)
            row["base"] = bq
            if ch is None or not isinstance(ch, dict) \
                    or ch.get("count", 0) < min_count:
                row["status"] = "missing-label"
                rows.append(row)
                continue
            cq = slo.quantile(ch, q)
            row["cur"] = cq
            if bq is None or cq is None or bq < min_base_s:
                row["status"] = "below-noise-floor"
            elif cq > bq * (1.0 + threshold):
                row["status"] = "REGRESSED"
                row["ratio"] = round(cq / bq, 3)
                failures.append(
                    f"{name}{{{label}}} p{round(q * 100)}: "
                    f"{bq:.6f}s -> {cq:.6f}s ({cq / bq:.2f}x, ceiling "
                    f"{1 + threshold:.2f}x)"
                )
            else:
                row["status"] = "ok"
                row["ratio"] = round(cq / max(bq, 1e-12), 3)
            rows.append(row)
    return {
        "verdict": "FAIL" if failures else "PASS",
        "rows": rows,
        "failures": failures,
    }


def load_gauges(path: str) -> dict | None:
    """Gauge table ``{name: {labels: value}}`` from the same shapes
    :func:`load_phases` reads, or None when the source carries none."""
    p = pathlib.Path(path)
    try:
        text = p.read_text()
        if p.suffix == ".jsonl" or "\n{" in text.strip():
            last = None
            for ln in text.splitlines():
                ln = ln.strip()
                if not ln:
                    continue
                try:
                    rec = json.loads(ln)
                except json.JSONDecodeError:
                    continue
                if isinstance(rec, dict) and "gauges" in rec:
                    last = rec
            return dict(last["gauges"]) if last else None
        data = json.loads(text)
        if "gauges" in data:
            return dict(data["gauges"])
        tel = (data.get("detail") or {}).get("telemetry") or {}
        if "gauges" in tel:
            return dict(tel["gauges"])
    except (OSError, ValueError, json.JSONDecodeError):
        pass
    return None


def compare_gauges(current: dict | None, baseline: dict | None,
                   threshold: float = 0.35,
                   gauges=GATED_GAUGES_MIN, mode: str = "min") -> dict:
    """Directional gate on per-label gauge values.  ``mode="min"``
    (floor): fails when a gated gauge DROPS below ``baseline * (1 -
    threshold)`` — regression direction is down, these are goodness
    fractions.  ``mode="max"`` (ceiling, ISSUE 11): fails when it
    RISES above ``baseline * (1 + threshold)`` — regression direction
    is up, these are costs (per-member HBM).  A labeled series present
    in the baseline but missing from the current round is a coverage
    loss and fails; either side lacking the whole table passes
    vacuously."""
    rows = []
    failures = []
    if mode not in ("min", "max"):
        raise ValueError(f"unknown gauge-gate mode {mode!r}")
    if current is None or baseline is None:
        return {"verdict": "PASS", "rows": rows, "failures": failures}
    for name in gauges:
        base_series = baseline.get(name)
        if not base_series:
            continue
        cur_series = current.get(name) or {}
        for label, b in base_series.items():
            c = cur_series.get(label)
            row = {"gauge": name, "labels": label, "base": b, "cur": c}
            if c is None:
                row["status"] = "MISSING"
                failures.append(
                    f"{name}{{{label}}}: present in baseline ({b}), "
                    "missing from current round (coverage loss)"
                )
            elif not isinstance(b, (int, float)) or b <= 0:
                row["status"] = "ok"  # nothing to regress from
            elif mode == "min" and c < b * (1.0 - threshold):
                row["status"] = "REGRESSED"
                failures.append(
                    f"{name}{{{label}}}: {b} -> {c} "
                    f"(below {1 - threshold:.2f}x floor)"
                )
            elif mode == "max" and c > b * (1.0 + threshold):
                row["status"] = "REGRESSED"
                failures.append(
                    f"{name}{{{label}}}: {b} -> {c} "
                    f"(above {1 + threshold:.2f}x ceiling)"
                )
            else:
                row["status"] = "ok"
            rows.append(row)
    return {
        "verdict": "FAIL" if failures else "PASS",
        "rows": rows,
        "failures": failures,
    }


def load_phases(path: str) -> dict:
    """Phase table ``{name: {total_s, count, mean_s}}`` from any of the
    telemetry-bearing shapes this repo produces:

    * ``telemetry.json`` — top-level ``phases``;
    * ``BENCH_DETAIL.json`` / ``BENCH_r*.json`` records —
      ``detail.telemetry.phases``;
    * a streaming ``*.jsonl`` — the LAST complete line's ``phases``
      (cumulative, so the last snapshot is the round's final state).
    """
    p = pathlib.Path(path)
    text = p.read_text()
    if p.suffix == ".jsonl" or "\n{" in text.strip():
        last = None
        for ln in text.splitlines():
            ln = ln.strip()
            if not ln:
                continue
            try:
                rec = json.loads(ln)
            except json.JSONDecodeError:
                continue  # killed mid-write: earlier complete lines count
            if isinstance(rec, dict) and "phases" in rec:
                last = rec
        if last is None:
            raise ValueError(f"{path}: no snapshot line carries 'phases'")
        return dict(last["phases"])
    data = json.loads(text)
    if "phases" in data:
        return dict(data["phases"])
    tel = (data.get("detail") or {}).get("telemetry") or {}
    if "phases" in tel:
        return dict(tel["phases"])
    raise ValueError(f"{path}: no phase table found (not telemetry.json, "
                     "a bench record, or a telemetry JSONL stream)")


def discover_baseline() -> str | None:
    """The newest prior-round phase source available in the repo."""
    prev = ROOT / "tools" / "telemetry_prev.json"
    if prev.exists():
        return str(prev)
    detail = ROOT / "BENCH_DETAIL.json"
    if detail.exists():
        try:
            load_phases(str(detail))
            return str(detail)
        except (ValueError, json.JSONDecodeError):
            pass
    for cand in sorted(glob.glob(str(ROOT / "BENCH_r*.json")), reverse=True):
        try:
            load_phases(cand)
            return cand
        except (ValueError, json.JSONDecodeError):
            continue
    return None


def compare(current: dict, baseline: dict, threshold: float = 0.35,
            phases=None, allow=(), min_total: float = 1e-3) -> dict:
    """Pure comparison -> verdict record.  ``current``/``baseline`` are
    phase tables; ``phases`` limits the gate (None = every baseline
    phase); ``allow`` lists phases that may regress without failing."""
    gate = set(phases) if phases else set(baseline)
    allow = set(allow)
    rows = []
    failures = []
    for name in sorted(set(baseline) | set(current)):
        b, c = baseline.get(name), current.get(name)
        row = {"phase": name}
        if b is not None:
            row["base_mean_s"] = round(
                b.get("mean_s", b["total_s"] / max(b.get("count", 1), 1)), 6
            )
            row["base_total_s"] = round(b["total_s"], 6)
        if c is not None:
            row["cur_mean_s"] = round(
                c.get("mean_s", c["total_s"] / max(c.get("count", 1), 1)), 6
            )
        gated = name in gate and name not in allow
        if b is None:
            row["status"] = "new"
        elif name not in gate:
            row["status"] = "ungated"
        elif b["total_s"] < min_total:
            row["status"] = "below-noise-floor"
        elif c is None:
            row["status"] = "allowed-missing" if not gated else "MISSING"
            if gated:
                failures.append(f"{name}: present in baseline, missing "
                                "from current round (coverage loss)")
        else:
            ratio = row["cur_mean_s"] / max(row["base_mean_s"], 1e-12)
            row["ratio"] = round(ratio, 3)
            if ratio > 1.0 + threshold:
                row["status"] = "allowed-regression" if not gated else "REGRESSED"
                if gated:
                    failures.append(
                        f"{name}: mean {row['base_mean_s']:.6f}s -> "
                        f"{row['cur_mean_s']:.6f}s ({ratio:.2f}x, "
                        f"threshold {1 + threshold:.2f}x)"
                    )
            else:
                row["status"] = "ok"
        rows.append(row)
    return {
        "verdict": "FAIL" if failures else "PASS",
        "threshold": threshold,
        "min_total_s": min_total,
        "allow": sorted(allow),
        "failures": failures,
        "rows": rows,
    }


def load_history(path: str) -> list:
    """The retained rounds from a phase-history JSONL, oldest first.
    Unparseable or phase-less lines are skipped (a killed writer leaves
    earlier complete lines intact)."""
    out = []
    try:
        with open(path) as f:
            for ln in f:
                ln = ln.strip()
                if not ln:
                    continue
                try:
                    rec = json.loads(ln)
                except json.JSONDecodeError:
                    continue
                if isinstance(rec, dict) and isinstance(
                    rec.get("phases"), dict
                ):
                    out.append(rec)
    except OSError:
        pass
    return out


def append_history(path: str, phases: dict, keep: int,
                   source: str = "") -> None:
    """Append this round's phase table and trim to the last ``keep``
    rounds (atomic rewrite)."""
    history = load_history(path)
    history.append({"source": source, "phases": phases})
    history = history[-max(keep, 1):]
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        for rec in history:
            f.write(json.dumps(rec) + "\n")
    os.replace(tmp, path)


def check_drift(current: dict, oldest: dict, threshold: float = 0.75,
                phases=None, allow=(), min_total: float = 1e-3) -> dict:
    """Cumulative-drift gate: the same mean-per-span comparison as
    :func:`compare`, but against the OLDEST retained round — a phase
    creeping +10% every round stays inside the step threshold forever
    yet doubles over the window; this catches it.  Coverage loss is the
    step gate's job, so a phase missing from the current round does not
    fail here."""
    v = compare(current, oldest, threshold=threshold, phases=phases,
                allow=allow, min_total=min_total)
    failures = []
    for row in v["rows"]:
        if row["status"] == "REGRESSED":
            row["status"] = "DRIFT"
            failures.append(
                f"{row['phase']}: cumulative drift "
                f"{row['base_mean_s']:.6f}s -> {row['cur_mean_s']:.6f}s "
                f"({row['ratio']:.2f}x over the retained window, "
                f"threshold {1 + threshold:.2f}x)"
            )
        elif row["status"] == "allowed-regression":
            row["status"] = "allowed-drift"
        elif row["status"] == "MISSING":
            row["status"] = "ungated"
    return {
        "verdict": "FAIL" if failures else "PASS",
        "threshold": threshold,
        "failures": failures,
        "rows": v["rows"],
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument("--current", default=str(ROOT / "telemetry.json"),
                    help="this round's telemetry (json or jsonl stream)")
    ap.add_argument("--baseline", default=None,
                    help="previous round (default: auto-discover)")
    ap.add_argument("--threshold", type=float, default=0.35,
                    help="max allowed fractional mean-time regression")
    ap.add_argument("--min-total", type=float, default=1e-3,
                    help="skip phases whose baseline total_s is below this")
    ap.add_argument("--phases", default=",".join(DEFAULT_PHASES),
                    help="comma-separated gated phases ('' = all)")
    ap.add_argument("--allow", action="append", default=[],
                    help="phase allowed to regress (repeatable, or "
                         "comma-separated; the resilience phases "
                         f"{', '.join(DEFAULT_ALLOW)} are always allowed)")
    ap.add_argument("--json", default=None,
                    help="also write the verdict record to this path")
    ap.add_argument("--history",
                    default=str(ROOT / "tools" / "telemetry_history.jsonl"),
                    help="phase-history JSONL: each run appends its "
                         "phase table and drift-checks against the "
                         "oldest retained round")
    ap.add_argument("--no-history", action="store_true",
                    help="neither append to nor drift-check the history")
    ap.add_argument("--history-keep", type=int, default=10,
                    help="rounds retained in the history window")
    ap.add_argument("--drift-threshold", type=float, default=0.75,
                    help="max allowed fractional mean-time drift vs the "
                         "oldest retained round")
    args = ap.parse_args(argv)

    baseline_path = args.baseline or discover_baseline()
    if baseline_path is None:
        print("telemetry_diff: no baseline round found — PASS (vacuous); "
              "run bench.py once to establish one", file=sys.stderr)
        return 0
    try:
        current = load_phases(args.current)
        baseline = load_phases(baseline_path)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"telemetry_diff: cannot load inputs: {e}", file=sys.stderr)
        return 2
    allow = list(DEFAULT_ALLOW) + [
        a for chunk in args.allow for a in chunk.split(",") if a
    ]
    phases = [p for p in args.phases.split(",") if p] or None
    verdict = compare(current, baseline, threshold=args.threshold,
                      phases=phases, allow=allow, min_total=args.min_total)
    verdict["current"] = str(args.current)
    verdict["baseline"] = str(baseline_path)

    # counter gate (epoch.recompiles): engages when both rounds carry
    # counter tables
    cgate = compare_counters(
        load_counters(args.current), load_counters(baseline_path),
        threshold=args.threshold, informational=INFO_COUNTERS,
    )
    verdict["counter_gate"] = cgate
    if cgate["verdict"] == "FAIL":
        verdict["verdict"] = "FAIL"
        verdict["failures"] = list(verdict["failures"]) + cgate["failures"]

    # gauge floor gate (overlap.fraction): engages when both rounds
    # carry the gauge — a drop means compute stopped hiding the halo
    cur_gauges = load_gauges(args.current)
    base_gauges = load_gauges(baseline_path)
    ggate = compare_gauges(cur_gauges, base_gauges,
                           threshold=args.threshold)
    verdict["gauge_gate"] = ggate
    if ggate["verdict"] == "FAIL":
        verdict["verdict"] = "FAIL"
        verdict["failures"] = list(verdict["failures"]) + ggate["failures"]

    # gauge ceiling gate (ISSUE 11): per-member cohort HBM may not rise
    # past the baseline — the donation + shared-table wins are regress-
    # able costs, not one-time events
    cgate_max = compare_gauges(cur_gauges, base_gauges,
                               threshold=args.threshold,
                               gauges=GATED_GAUGES_MAX, mode="max")
    verdict["gauge_ceiling_gate"] = cgate_max
    if cgate_max["verdict"] == "FAIL":
        verdict["verdict"] = "FAIL"
        verdict["failures"] = (list(verdict["failures"])
                               + cgate_max["failures"])

    # quantile ceiling gate (ISSUE 10): the request-latency p99s may
    # not blow past the baseline's — a serving round whose tail latency
    # regressed fails even when every phase MEAN stayed flat (tails
    # hide in means; that is the point of the SLO plane)
    qgate = compare_quantiles(
        load_histograms(args.current), load_histograms(baseline_path),
        threshold=args.threshold,
    )
    verdict["quantile_gate"] = qgate
    if qgate["verdict"] == "FAIL":
        verdict["verdict"] = "FAIL"
        verdict["failures"] = list(verdict["failures"]) + qgate["failures"]

    # cumulative-drift gate over the retained history window (the
    # round-over-round step gate above cannot see slow creep)
    hist_path = None if args.no_history else args.history
    if hist_path:
        history = load_history(hist_path)
        if len(history) >= 2:
            drift = check_drift(
                current, history[0]["phases"],
                threshold=args.drift_threshold, phases=phases,
                allow=allow, min_total=args.min_total,
            )
            drift["baseline_source"] = history[0].get("source", "")
            drift["rounds_spanned"] = len(history)
            verdict["drift"] = drift
            verdict["failures"] = (
                list(verdict["failures"]) + list(drift["failures"])
            )
            if drift["verdict"] == "FAIL":
                verdict["verdict"] = "FAIL"
        append_history(hist_path, current, args.history_keep,
                       source=str(args.current))

    for row in verdict["rows"]:
        parts = [f"{row['phase']:24s} {row['status']:>18s}"]
        if "base_mean_s" in row and "cur_mean_s" in row:
            parts.append(f"{row['base_mean_s']:.6f}s -> "
                         f"{row['cur_mean_s']:.6f}s")
            if "ratio" in row:
                parts.append(f"({row['ratio']:.2f}x)")
        print("  ".join(parts))
    if verdict["quantile_gate"]["rows"]:
        qg = verdict["quantile_gate"]
        gated_n = sum(1 for r in qg["rows"]
                      if r["status"] in ("ok", "REGRESSED"))
        print(f"telemetry_diff: p99 ceiling {qg['verdict']} "
              f"({gated_n} labeled series gated, threshold "
              f"{1 + args.threshold:.2f}x)")
    if "drift" in verdict:
        d = verdict["drift"]
        print(f"telemetry_diff: drift {d['verdict']} vs oldest of "
              f"{d['rounds_spanned']} retained rounds "
              f"(threshold {1 + d['threshold']:.2f}x)")
    print(f"telemetry_diff: {verdict['verdict']} "
          f"({args.current} vs {baseline_path}, "
          f"threshold {1 + args.threshold:.2f}x)")
    for f in verdict["failures"]:
        print(f"  REGRESSION: {f}", file=sys.stderr)
    if args.json:
        tmp = args.json + ".tmp"
        with open(tmp, "w") as f:
            json.dump(verdict, f, indent=1)
        os.replace(tmp, args.json)
    return 1 if verdict["verdict"] == "FAIL" else 0


if __name__ == "__main__":
    sys.exit(main())
