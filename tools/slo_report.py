#!/usr/bin/env python
"""Request-level SLO report: latency quantiles, deadline-miss rates and
slowest-request drill-down from EXPORTED telemetry alone.

No live process is needed: the inputs are the files the serving stack
already leaves behind — ``telemetry.json`` snapshots, streaming
``*.jsonl`` heartbeats (last complete line wins), ``BENCH_DETAIL.json``
records.  Multiple sources merge (``obs/slo.py``: log-bucket histograms
add exactly), so per-tenant p50/p95/p99 aggregate across soak children
or ensemble processes the same way one process would have recorded them:

    python tools/slo_report.py                        # repo telemetry.json
    python tools/slo_report.py run1.json run2.json    # merged fleet view
    python tools/slo_report.py --json slo.json        # machine-readable

Drill-down: ``--trace`` takes a Chrome/merged trace (the
``obs.merge_profile`` output, or any ``export_chrome_trace`` file whose
timeline recorded ``request.e2e`` spans) and prints the N slowest
requests with the kernel/device spans that overlap each one's window —
the "this request was slow BECAUSE that kernel ran long" cross-reference
the merged device timeline exists for:

    python tools/slo_report.py --trace tools/telemetry.json.merged_trace.json

This tool loads ``dccrg_tpu/obs/slo.py`` directly from its file (the
module is stdlib-only by contract), so reporting never imports jax.
"""
from __future__ import annotations

import argparse
import importlib.util
import json
import os
import pathlib
import sys

ROOT = pathlib.Path(__file__).resolve().parent.parent

#: histogram names tabulated by default (--metrics overrides); the
#: phase-duration series is opt-in via --metrics phase.duration_s
DEFAULT_METRICS = (
    "ensemble.queue_wait_s",
    "ensemble.service_s",
    "ensemble.e2e_s",
)


def load_slo():
    """The quantile/merge library, file-loaded so no package (and no
    jax) import happens — ``obs/slo.py`` is stdlib-only by contract."""
    path = ROOT / "dccrg_tpu" / "obs" / "slo.py"
    spec = importlib.util.spec_from_file_location("dccrg_slo", str(path))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def load_live():
    """The live aggregator (``obs/live.py``), file-loaded under the
    same stdlib-only contract — ``--live`` never imports jax either."""
    path = ROOT / "dccrg_tpu" / "obs" / "live.py"
    spec = importlib.util.spec_from_file_location("dccrg_live", str(path))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def combine_reports(slo, reports: list, metrics) -> dict:
    """One merged pseudo-report: histograms merged per (name, label),
    counters summed per (name, label) — each input report is one
    process/round's cumulative state, so summing across inputs is the
    fleet total."""
    hists = {name: slo.merge_series(reports, name) for name in metrics}
    counters: dict = {}
    for rep in reports:
        for name, series in (rep.get("counters") or {}).items():
            dst = counters.setdefault(name, {})
            for label, v in series.items():
                dst[label] = dst.get(label, 0) + v
    return {
        "histograms": {n: s for n, s in hists.items() if s},
        "counters": counters,
    }


def quantile_table(slo, combined: dict, qs) -> list:
    """Rows of ``{metric, labels, count, mean, pXX...}`` (seconds)."""
    rows = []
    for name, series in sorted(combined["histograms"].items()):
        for label, h in sorted(series.items()):
            rows.append({
                "metric": name,
                "labels": label,
                **slo.summarize(h, qs),
            })
    return rows


def print_tables(rows: list, miss_rates: dict, qs) -> None:
    qcols = [f"p{round(q * 100):d}" for q in qs]
    if rows:
        head = (f"{'metric':24s} {'labels':28s} {'count':>7s} "
                + " ".join(f"{c + '(ms)':>10s}" for c in ["mean"] + qcols))
        print(head)
        print("-" * len(head))
        for r in rows:
            cells = [r.get("mean")] + [r.get(c) for c in qcols]
            print(f"{r['metric']:24s} {r['labels']:28s} "
                  f"{r.get('count', 0):>7d} "
                  + " ".join("       n/a" if v is None
                             else f"{v * 1e3:>10.3f}" for v in cells))
    else:
        print("no latency histograms found in the given sources")
    if miss_rates:
        print()
        print(f"{'tenant':16s} {'completed':>9s} {'deadline miss':>13s} "
              f"{'rate':>8s}")
        for tenant, rec in sorted(miss_rates.items()):
            rate = rec["rate"]
            print(f"{tenant:16s} {rec['completed']:>9d} "
                  f"{rec['missed']:>13d} "
                  f"{'n/a' if rate is None else f'{rate:8.2%}'}")


# --------------------------------------------------------- drill-down

def _trace_spans(events: list) -> list:
    """Reconstruct ``{name, pid, tid, ts, dur, args}`` spans (µs) from a
    Chrome trace-event list: X events directly, B/E pairs per thread."""
    spans = []
    stacks: dict = {}
    for ev in events:
        if not isinstance(ev, dict):
            continue
        ph = ev.get("ph")
        key = (ev.get("pid"), ev.get("tid"))
        if ph == "X":
            spans.append({"name": ev.get("name"), "pid": ev.get("pid"),
                          "tid": ev.get("tid"), "ts": ev.get("ts", 0.0),
                          "dur": ev.get("dur", 0.0),
                          "args": ev.get("args") or {}})
        elif ph == "B":
            stacks.setdefault(key, []).append(ev)
        elif ph == "E":
            stack = stacks.get(key)
            if stack:
                b = stack.pop()
                spans.append({
                    "name": b.get("name"), "pid": b.get("pid"),
                    "tid": b.get("tid"), "ts": b.get("ts", 0.0),
                    "dur": max(ev.get("ts", 0.0) - b.get("ts", 0.0), 0.0),
                    "args": b.get("args") or {},
                })
    return spans


def slowest_requests(trace: dict, top: int = 5,
                     kernels_per_request: int = 6) -> list:
    """The ``top`` slowest ``request.e2e`` spans in a (merged) trace,
    each cross-referenced with the longest spans from OTHER pids —
    device kernel tracks in a merged trace — overlapping its window."""
    events = trace.get("traceEvents") if isinstance(trace, dict) else trace
    spans = _trace_spans(events or [])
    requests = sorted(
        (s for s in spans if s["name"] == "request.e2e"),
        key=lambda s: -s["dur"],
    )[:max(top, 0)]
    out = []
    for rq in requests:
        lo, hi = rq["ts"], rq["ts"] + rq["dur"]
        overlapping = [
            s for s in spans
            if s["pid"] != rq["pid"]
            and s["ts"] < hi and s["ts"] + s["dur"] > lo
        ]
        overlapping.sort(key=lambda s: -s["dur"])
        out.append({
            "request": (rq["args"] or {}).get("request"),
            "tenant": (rq["args"] or {}).get("tenant"),
            "e2e_ms": round(rq["dur"] / 1e3, 3),
            "deadline_missed": (rq["args"] or {}).get("deadline_missed"),
            "window_us": [round(lo, 1), round(hi, 1)],
            "kernels": [
                {"name": s["name"], "pid": s["pid"],
                 "dur_ms": round(s["dur"] / 1e3, 3)}
                for s in overlapping[:kernels_per_request]
            ],
        })
    return out


def print_drilldown(slow: list) -> None:
    if not slow:
        print("drill-down: no request.e2e spans in the trace")
        return
    print()
    print("slowest requests (cross-referenced to overlapping "
          "device/kernel spans):")
    for rec in slow:
        missed = " DEADLINE-MISSED" if rec.get("deadline_missed") else ""
        print(f"  request={rec['request']} tenant={rec['tenant']} "
              f"e2e={rec['e2e_ms']:.3f}ms{missed}")
        for k in rec["kernels"]:
            print(f"    {k['dur_ms']:>10.3f}ms  pid={k['pid']:<6} "
                  f"{k['name']}")
        if not rec["kernels"]:
            print("    (no overlapping spans from other tracks)")


def live_report(slo, args, metrics, qs) -> int:
    """``--live``: windowed per-tenant tables from stream dirs via the
    aggregator; ``--follow`` re-polls and reprints every refresh."""
    import time

    live = load_live()
    agg = live.FleetAggregator(args.live, window_s=args.window)
    rounds = 0
    while True:
        agg.poll()
        view = agg.view()
        combined = {
            "histograms": {
                name: series for name, series in
                (view.window_report.get("histograms") or {}).items()
                if name in metrics
            },
            "counters": view.window_report.get("counters") or {},
        }
        if rounds:
            print()
        h = view.health
        print(f"live window={view.window_s:.0f}s  files={h['files']} "
              f"({h['stale_files']} stale)  records={h['records']}  "
              f"seq_gaps={h['seq_gaps']}  torn_tails={h['torn_tails']}")
        rows = quantile_table(slo, combined, qs)
        miss_rates = slo.deadline_miss_rates(combined)
        print_tables(rows, miss_rates, qs)
        if args.json:
            report = {
                "live": args.live,
                "window_s": view.window_s,
                "health": h,
                "quantiles": list(qs),
                "latency": rows,
                "deadline_miss_rates": miss_rates,
            }
            tmp = args.json + ".tmp"
            with open(tmp, "w") as f:
                json.dump(report, f, indent=1, default=float)
            os.replace(tmp, args.json)
        rounds += 1
        if not args.follow:
            break
        try:
            time.sleep(max(args.refresh, 0.1))
        except KeyboardInterrupt:
            break
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument("sources", nargs="*",
                    default=[str(ROOT / "telemetry.json")],
                    help="telemetry.json / *.jsonl stream / bench "
                         "record files; histograms merge across them")
    ap.add_argument("--metrics", default=",".join(DEFAULT_METRICS),
                    help="comma-separated histogram names to tabulate")
    ap.add_argument("--quantiles", default="0.5,0.95,0.99",
                    help="comma-separated quantile fractions")
    ap.add_argument("--trace", default=None,
                    help="Chrome/merged trace for the slowest-request "
                         "kernel drill-down")
    ap.add_argument("--top", type=int, default=5,
                    help="slowest requests to drill into")
    ap.add_argument("--json", default=None,
                    help="also write the full report object to this path")
    ap.add_argument("--live", default=None, metavar="DIR",
                    help="tail *.stream.jsonl files under DIR via the "
                         "live aggregator and report the WINDOWED "
                         "per-tenant view instead of final exports")
    ap.add_argument("--window", type=float, default=None,
                    help="with --live: sliding window seconds "
                         "(default DCCRG_LIVE_WINDOW_S or 60)")
    ap.add_argument("--follow", action="store_true",
                    help="with --live: refresh the tables every "
                         "--refresh seconds until interrupted")
    ap.add_argument("--refresh", type=float, default=2.0,
                    help="refresh period for --follow")
    args = ap.parse_args(argv)

    slo = load_slo()
    qs = tuple(float(x) for x in args.quantiles.split(",") if x)
    metrics = [m for m in args.metrics.split(",") if m]

    if args.live:
        return live_report(slo, args, metrics, qs)

    reports = []
    for src in args.sources:
        try:
            reports.append(slo.load_report(src))
        except (OSError, ValueError) as e:
            print(f"slo_report: skipping {src}: {e}", file=sys.stderr)
    if not reports:
        print("slo_report: no readable telemetry sources", file=sys.stderr)
        return 2
    combined = combine_reports(slo, reports, metrics)
    rows = quantile_table(slo, combined, qs)
    miss_rates = slo.deadline_miss_rates(combined)
    print_tables(rows, miss_rates, qs)

    slow = None
    if args.trace:
        try:
            with open(args.trace) as f:
                trace = json.load(f)
            slow = slowest_requests(trace, top=args.top)
            print_drilldown(slow)
        except (OSError, ValueError) as e:
            print(f"slo_report: trace unreadable: {e}", file=sys.stderr)

    if args.json:
        report = {
            "sources": list(args.sources),
            "quantiles": list(qs),
            "latency": rows,
            "deadline_miss_rates": miss_rates,
            **({"slowest_requests": slow} if slow is not None else {}),
        }
        tmp = args.json + ".tmp"
        with open(tmp, "w") as f:
            json.dump(report, f, indent=1, default=float)
        os.replace(tmp, args.json)
    return 0


if __name__ == "__main__":
    sys.exit(main())
