// CPU baseline for the Vlasov benchmark: the reference's per-cell velocity
// block pattern (Vlasiator payload shape over dccrg, CREDITS:4-6) on a
// uniform periodic 3-D grid — each spatial cell owns a flattened [nv^3]
// f(v) block, and one step is the dimension-split upwind sweep where every
// velocity bin advects with its own constant velocity, per-cell loops with
// 6-face neighbor indirection, double precision, multi-threaded over all
// host cores.
//
// The actual reference (dccrg + MPI + Zoltan + Vlasiator) cannot be built
// in this image; this program re-creates its compute pattern as the honest
// MPI-CPU denominator for BASELINE.md's protocol, exactly like
// cpu_baseline.cpp does for the advection config.
//
// Usage: cpu_vlasov_baseline NX NY NZ NV STEPS -> prints phase-space
// cell-updates/sec (a "step" = all three dimensional sweeps, matching
// dccrg_tpu/models/vlasov.py).

#include <cstdio>
#include <cstdlib>
#include <chrono>
#include <cmath>
#include <cstdint>
#include <vector>
#ifdef _OPENMP
#include <omp.h>
#endif

int main(int argc, char** argv) {
    const int64_t nx = argc > 1 ? atoll(argv[1]) : 32;
    const int64_t ny = argc > 2 ? atoll(argv[2]) : 32;
    const int64_t nz = argc > 3 ? atoll(argv[3]) : 32;
    const int64_t nv = argc > 4 ? atoll(argv[4]) : 8;
    const int64_t steps = argc > 5 ? atoll(argv[5]) : 10;
    const int64_t n = nx * ny * nz;
    const int64_t B = nv * nv * nv;

    // per-axis bin velocity (bin centers in [-vmax, vmax], vmax = 1.0,
    // x-fastest flattening — dccrg_tpu/models/vlasov.py:50-54)
    const double v_max = 1.0;
    std::vector<double> vbin(B * 3);
    for (int64_t bz = 0; bz < nv; bz++)
    for (int64_t by = 0; by < nv; by++)
    for (int64_t bx = 0; bx < nv; bx++) {
        const int64_t b = bx + nv * (by + nv * bz);
        vbin[b * 3 + 0] = (bx + 0.5) / nv * 2 * v_max - v_max;
        vbin[b * 3 + 1] = (by + 0.5) / nv * 2 * v_max - v_max;
        vbin[b * 3 + 2] = (bz + 0.5) / nv * 2 * v_max - v_max;
    }

    // AoS cell blocks + 6-face periodic neighbor indirection, the
    // reference's neighbors_of pattern
    std::vector<double> f(n * B), g(n * B);
    std::vector<int64_t> nbr(n * 6);
    const double dx = 1.0 / nx, dy = 1.0 / ny, dz = 1.0 / nz;
    for (int64_t z = 0; z < nz; z++)
    for (int64_t y = 0; y < ny; y++)
    for (int64_t x = 0; x < nx; x++) {
        const int64_t i = x + nx * (y + ny * z);
        const double cx = (x + 0.5) * dx, cy = (y + 0.5) * dy,
                     cz = (z + 0.5) * dz;
        const double r2 = pow(cx - 0.5, 2) + pow(cy - 0.5, 2)
                        + pow(cz - 0.5, 2);
        for (int64_t b = 0; b < B; b++)
            f[i * B + b] = exp(-20.0 * r2) * (1.0 + 0.1 * (b % 7));
        nbr[i * 6 + 0] = ((x + nx - 1) % nx) + nx * (y + ny * z);
        nbr[i * 6 + 1] = ((x + 1) % nx) + nx * (y + ny * z);
        nbr[i * 6 + 2] = x + nx * (((y + ny - 1) % ny) + ny * z);
        nbr[i * 6 + 3] = x + nx * (((y + 1) % ny) + ny * z);
        nbr[i * 6 + 4] = x + nx * (y + ny * ((z + nz - 1) % nz));
        nbr[i * 6 + 5] = x + nx * (y + ny * ((z + 1) % nz));
    }

    const double inv_d[3] = {1.0 / dx, 1.0 / dy, 1.0 / dz};
    const double dmin = dx < dy ? (dx < dz ? dx : dz) : (dy < dz ? dy : dz);
    const double dt = 0.4 * dmin / v_max;

    const auto t0 = std::chrono::high_resolution_clock::now();
    for (int64_t s = 0; s < steps; s++) {
        for (int axis = 0; axis < 3; axis++) {
#pragma omp parallel for schedule(static)
            for (int64_t i = 0; i < n; i++) {
                const double* fc = &f[i * B];
                const double* fl = &f[nbr[i * 6 + axis * 2] * B];
                const double* fh = &f[nbr[i * 6 + axis * 2 + 1] * B];
                double* out = &g[i * B];
                for (int64_t b = 0; b < B; b++) {
                    const double v = vbin[b * 3 + axis];
                    const double flux_hi = (v >= 0 ? fc[b] : fh[b]) * v;
                    const double flux_lo = (v >= 0 ? fl[b] : fc[b]) * v;
                    out[b] = fc[b] - dt * inv_d[axis] * (flux_hi - flux_lo);
                }
            }
            f.swap(g);
        }
    }
    const auto t1 = std::chrono::high_resolution_clock::now();
    const double secs = std::chrono::duration<double>(t1 - t0).count();
    volatile double sink = f[(n / 2) * B];
    (void)sink;
    printf("%.6e\n", double(n) * double(B) * steps / secs);
    return 0;
}
