#!/usr/bin/env python
"""Fleet cost & capacity console: step-cost model tables, per-tenant
chargeback and predicted queue-waits from EXPORTED telemetry alone.

No live process is needed: the inputs are the files the serving stack
already leaves behind — ``telemetry.json`` snapshots, streaming
``*.jsonl`` heartbeats (last complete line wins), ``BENCH_DETAIL.json``
records.  The cost series merge exactly across sources (``obs/cost.py``
rides the same log-bucket histogram + summed-counter algebra the SLO
plane proved exact), so the printed model IS the model one process
pooling every sample would have learned:

    python tools/cost_report.py                       # repo telemetry.json
    python tools/cost_report.py run1.json run2.json   # merged fleet model
    python tools/cost_report.py --json cost.json      # machine-readable
    python tools/cost_report.py --live run/ --follow  # windowed, from streams

Sections:

* **step-cost model** — one row per ``(model, sig, k, g, w)`` compiled-
  body key: samples, mean ± std, p50/p95 per-interior-step seconds.
* **chargeback** — the per-tenant ledger (device-seconds + share,
  member-steps, attributed halo exchanges and compile time) with the
  conservation check (attributed device-seconds == recorded wall×mesh
  total) printed pass/fail.
* **capacity** — the latest ``cost.predicted_queue_wait_s{tenant}``
  gauges; with ``--live`` also the read-side estimates recomputed from
  the windowed bucket-delta service rates.

This tool file-loads ``dccrg_tpu/obs/cost.py`` (and ``--live`` loads
``obs/live.py`` — both stdlib-only by contract), so billing a fleet
never imports jax.
"""
from __future__ import annotations

import argparse
import importlib.util
import json
import os
import pathlib
import sys

ROOT = pathlib.Path(__file__).resolve().parent.parent


def _load(name: str):
    path = ROOT / "dccrg_tpu" / "obs" / f"{name}.py"
    spec = importlib.util.spec_from_file_location(
        f"dccrg_cost_report_{name}", str(path))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def print_report(summary: dict) -> None:
    rows = summary.get("model") or []
    if rows:
        print(f"{'cost model key':46s} {'n':>6s} {'mean(ms)':>9s} "
              f"{'std(ms)':>9s} {'p50(ms)':>9s} {'p95(ms)':>9s}")
        for r in rows:
            print(f"{r['key']:46s} {r['n']:>6d} "
                  f"{r['mean_s'] * 1e3:>9.3f} {r['std_s'] * 1e3:>9.3f} "
                  f"{r.get('p50_s', 0.0) * 1e3:>9.3f} "
                  f"{r.get('p95_s', 0.0) * 1e3:>9.3f}")
    else:
        print("no cost-model samples found in the given sources")
    ledger = summary.get("chargeback") or {}
    if ledger:
        print()
        print(f"{'tenant':16s} {'device_s':>10s} {'share':>7s} "
              f"{'steps':>9s} {'halo_ex':>9s} {'compile_s':>9s} "
              f"{'recompiles':>10s}")
        for tenant, rec in sorted(ledger.items()):
            print(f"{tenant:16s} {rec['device_s']:>10.3f} "
                  f"{rec['device_share']:>7.2%} "
                  f"{rec['member_steps']:>9d} "
                  f"{rec['halo_exchanges']:>9.0f} "
                  f"{rec['compile_s']:>9.3f} "
                  f"{rec['recompiles']:>10.1f}")
        cons = summary.get("conservation") or {}
        ratio = cons.get("ratio")
        print(f"conservation: attributed="
              f"{cons.get('attributed', 0.0):.3f}s "
              f"total={cons.get('total', 0.0):.3f}s "
              f"ratio={'n/a' if ratio is None else f'{ratio:.4f}'} "
              f"{'OK' if cons.get('ok') else 'VIOLATED'}")
    waits = {**(summary.get("predicted_queue_wait_s") or {}),
             **(summary.get("queue_wait_estimates") or {})}
    if waits:
        print()
        print(f"{'tenant':16s} {'predicted_wait_s':>16s}")
        for tenant, w in sorted(waits.items()):
            print(f"{tenant:16s} {w:>16.3f}")


def _write_json(summary: dict, path: str) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(summary, f, indent=1, default=float)
    os.replace(tmp, path)


def live_report(cost, args) -> int:
    """``--live``: windowed cost & capacity view from stream dirs via
    the fleet aggregator; ``--follow`` re-polls every refresh."""
    import time

    live = _load("live")
    agg = live.FleetAggregator(args.live, window_s=args.window)
    rounds = 0
    while True:
        agg.poll()
        view = agg.view()
        summary = cost.cost_summary(view.cumulative_report)
        summary["queue_wait_estimates"] = cost.queue_wait_estimates(view)
        if rounds:
            print()
        h = view.health
        print(f"cost live window={view.window_s:.0f}s  "
              f"files={h['files']} ({h['stale_files']} stale)  "
              f"records={h['records']}")
        print_report(summary)
        if args.json:
            _write_json(summary, args.json)
        rounds += 1
        if not args.follow:
            break
        try:
            time.sleep(max(args.refresh, 0.1))
        except KeyboardInterrupt:
            break
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument("sources", nargs="*",
                    default=[str(ROOT / "telemetry.json")],
                    help="telemetry.json / *.jsonl stream / bench "
                         "record files; cost series merge across them")
    ap.add_argument("--json", default=None,
                    help="also write the summary object to this path")
    ap.add_argument("--live", default=None, metavar="DIR",
                    help="tail *.stream.jsonl files under DIR via the "
                         "live aggregator: fleet model from the "
                         "cumulative merge plus windowed queue-wait "
                         "estimates")
    ap.add_argument("--window", type=float, default=None,
                    help="with --live: sliding window seconds "
                         "(default DCCRG_LIVE_WINDOW_S or 60)")
    ap.add_argument("--follow", action="store_true",
                    help="with --live: refresh every --refresh seconds")
    ap.add_argument("--refresh", type=float, default=2.0,
                    help="refresh period for --follow")
    args = ap.parse_args(argv)

    cost = _load("cost")
    if args.live:
        return live_report(cost, args)

    slo = _load("slo")
    reports = []
    for src in args.sources:
        try:
            reports.append(slo.load_report(src))
        except (OSError, ValueError) as e:
            print(f"cost_report: skipping {src}: {e}", file=sys.stderr)
    if not reports:
        print("cost_report: no readable telemetry sources",
              file=sys.stderr)
        return 2
    summary = cost.cost_summary(reports)
    print_report(summary)
    if args.json:
        _write_json(summary, args.json)
    return 0


if __name__ == "__main__":
    sys.exit(main())
