// CPU baseline for the advection benchmark: the reference's per-cell upwind
// flux loop (tests/advection/solve.hpp:43-260) on a uniform periodic 3-D
// grid, with the reference's cell layout (9 doubles per cell,
// tests/advection/cell.hpp:36-44), multi-threaded over all host cores.
//
// The actual reference (dccrg + MPI + Zoltan) cannot be built in this image
// (no MPI/boost/Zoltan); this program re-creates its compute pattern --
// AoS cells, neighbor indirection through an index list, double precision --
// as the honest MPI-CPU denominator for BASELINE.md's protocol.
//
// Usage: cpu_baseline NX NY NZ STEPS  -> prints cell-updates/sec

#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <chrono>
#include <cmath>
#include <cstdint>
#include <vector>
#ifdef _OPENMP
#include <omp.h>
#endif

struct Cell {
    double data[9]; // density, vx, vy, vz, flux, max_diff, lx, ly, lz
};

int main(int argc, char** argv) {
    const int64_t nx = argc > 1 ? atoll(argv[1]) : 128;
    const int64_t ny = argc > 2 ? atoll(argv[2]) : 128;
    const int64_t nz = argc > 3 ? atoll(argv[3]) : 64;
    const int64_t steps = argc > 4 ? atoll(argv[4]) : 10;
    const int64_t n = nx * ny * nz;

    std::vector<Cell> cells(n);
    // neighbor index list: 6 face neighbors per cell (periodic), the
    // reference's neighbors_of indirection
    std::vector<int64_t> nbr(n * 6);

    const double dx = 1.0 / nx, dy = 1.0 / ny, dz = 1.0 / nz;
    for (int64_t z = 0; z < nz; z++)
    for (int64_t y = 0; y < ny; y++)
    for (int64_t x = 0; x < nx; x++) {
        const int64_t i = x + nx * (y + ny * z);
        Cell& c = cells[i];
        const double cx = (x + 0.5) * dx, cy = (y + 0.5) * dy;
        c.data[0] = 0.25 * (1 + cos(M_PI * fmin(sqrt(pow(cx - 0.25, 2) + pow(cy - 0.5, 2)), 0.15) / 0.15));
        c.data[1] = -cy + 0.5;
        c.data[2] = cx - 0.5;
        c.data[3] = 0.0;
        c.data[4] = 0.0;
        c.data[6] = dx; c.data[7] = dy; c.data[8] = dz;
        nbr[i * 6 + 0] = ((x + nx - 1) % nx) + nx * (y + ny * z);
        nbr[i * 6 + 1] = ((x + 1) % nx) + nx * (y + ny * z);
        nbr[i * 6 + 2] = x + nx * (((y + ny - 1) % ny) + ny * z);
        nbr[i * 6 + 3] = x + nx * (((y + 1) % ny) + ny * z);
        nbr[i * 6 + 4] = x + nx * (y + ny * ((z + nz - 1) % nz));
        nbr[i * 6 + 5] = x + nx * (y + ny * ((z + 1) % nz));
    }

    const double dt = 0.4 * dx / 0.5;
    const auto t0 = std::chrono::high_resolution_clock::now();
    for (int64_t s = 0; s < steps; s++) {
        // flux sweep (each cell accumulates from all 6 faces; same work
        // shape as the reference's pair-skipping scatter loop)
#pragma omp parallel for schedule(static)
        for (int64_t i = 0; i < n; i++) {
            Cell& c = cells[i];
            const double vol = c.data[6] * c.data[7] * c.data[8];
            double flux = 0;
            for (int k = 0; k < 6; k++) {
                const Cell& o = cells[nbr[i * 6 + k]];
                const int axis = k / 2;
                const int sign = (k % 2) ? 1 : -1;
                const double area = vol / c.data[6 + axis];
                const double v = 0.5 * (c.data[1 + axis] + o.data[1 + axis]);
                const double up = (sign > 0) == (v >= 0) ? ((sign > 0) ? c.data[0] : o.data[0])
                                                         : ((sign > 0) ? o.data[0] : c.data[0]);
                flux -= sign * up * dt * v * area;
            }
            c.data[4] = flux / vol;
        }
#pragma omp parallel for schedule(static)
        for (int64_t i = 0; i < n; i++) {
            cells[i].data[0] += cells[i].data[4];
            cells[i].data[4] = 0;
        }
    }
    const auto t1 = std::chrono::high_resolution_clock::now();
    const double secs = std::chrono::duration<double>(t1 - t0).count();
    // keep the result live
    volatile double sink = cells[n / 2].data[0];
    (void)sink;
    printf("%.6e\n", double(n) * steps / secs);
    return 0;
}
