#!/usr/bin/env python3
"""dccrg-lint — AST-based invariant checker for the dccrg_tpu port.

Every performance property this repo ships rests on hand-maintained
invariants: epoch tables enter kernels as runtime arguments (never
closed over), reductions pin dtypes so x64 promotion can't re-key a
compiled body, ``obs/slo.py`` stays stdlib-only so report tools
file-load without jax, every telemetry series recorded through the
registry is mirrored in the CI gates, and the metrics registry mutates
shared state only under its lock.  This tool enforces those contracts
mechanically, the way the reference dccrg enforces its invariants with
compile-time template machinery.

Stdlib-only by design (ast + json + subprocess): it must run in the
same no-jax contexts it polices.

Rules
-----
DTYPE-PROMOTE      jnp reductions/constructors without an explicit
                   ``dtype=`` in traced code (models/, parallel/,
                   serve/) — the PR 9 uint32→uint64 retrace bug class.
CLOSED-OVER-TABLE  functions handed to jax.jit/vmap/traced_jit whose
                   bodies read device-table bindings (put_table /
                   asarray / device_put products) or ``self.`` state
                   from the enclosing scope instead of taking them as
                   runtime arguments — the PR 5 invariant.  Known
                   boxed/flat offenders live in the baseline, which
                   doubles as the ROADMAP item-4 worklist.
HOST-SYNC          block_until_ready / np.asarray / .item() / float()
                   on device values inside the declared ensemble-step
                   and halo hot paths.
STDLIB-ONLY        module-level non-stdlib imports in declared
                   stdlib-only modules; ``--probe`` additionally
                   file-loads each probe target in a subprocess and
                   asserts sys.modules stays jax-free.
TELEMETRY-DRIFT    recorded counter/gauge/phase/histogram name
                   literals cross-checked against check_telemetry
                   REQUIRED_* and telemetry_diff DEFAULT/GATED sets:
                   gated-but-never-recorded fails always; recorded-
                   but-never-gated fails for phases and histograms
                   (whose gate unions are exhaustive by contract).
LOCK-DISCIPLINE    mutation of a class's shared dict/list/set/deque
                   attributes outside ``with self._lock:`` in any
                   class that owns a threading lock.
ENV-DRIFT          DCCRG_* getenv sites cross-checked against the
                   README env tables: undocumented knobs and dead
                   documented knobs both fail.

Baseline
--------
``tools/lint_baseline.json`` suppresses known findings per site.  A
site key is structural (rule, path, function-qualname detail) — not a
line number — so it survives unrelated edits.  Entries that no longer
match any finding are *stale* and fail the run (the baseline may only
shrink by deleting the entry alongside the fix); ``--update-baseline``
rewrites the file from current findings, preserving reasons.

Exit codes: 0 clean, 1 findings or stale baseline entries, 2 internal
error (unparseable source, missing gate tables).
"""
from __future__ import annotations

import argparse
import ast
import dataclasses
import json
import pathlib
import re
import subprocess
import sys

REPO_ROOT = pathlib.Path(__file__).resolve().parent.parent
BASELINE_REL = "tools/lint_baseline.json"

# --------------------------------------------------------------- config

#: directories never scanned
SKIP_DIRS = {".git", "__pycache__", ".pytest_cache", "build", "dist",
             ".ipynb_checkpoints", "related"}

#: DTYPE-PROMOTE scope: traced model/infra code where an unpinned
#: reduction can re-key a compiled body under x64
TRACED_PREFIXES = ("dccrg_tpu/models/", "dccrg_tpu/parallel/",
                   "dccrg_tpu/serve/")

#: jnp calls that promote to a config-dependent dtype unless pinned
DTYPE_SENSITIVE = {"sum", "prod", "cumsum", "cumprod", "arange"}

#: declared stdlib-only modules (AST import check).  tools/*.py are
#: stdlib-only by contract — report/diff tools must file-load without
#: jax — except the listed exemptions, which are jax benchmarks.
STDLIB_ONLY_EXTRA = ("dccrg_tpu/obs/slo.py", "dccrg_tpu/obs/flightrec.py",
                     "dccrg_tpu/obs/registry.py", "dccrg_tpu/obs/live.py",
                     "dccrg_tpu/obs/alerts.py")
STDLIB_ONLY_TOOL_EXEMPT = {"flat_kernel_bench.py"}

#: subprocess import-probe targets: file-load must leave sys.modules
#: jax-free (flightrec/registry are package-relative, probed via slo's
#: loader contract instead — see tests/test_lint.py)
PROBE_TARGETS = ("dccrg_tpu/obs/slo.py", "dccrg_tpu/obs/live.py",
                 "dccrg_tpu/obs/alerts.py", "tools/slo_report.py",
                 "tools/fleet_top.py", "tools/telemetry_diff.py",
                 "tools/dccrg_lint.py")

#: HOST-SYNC hot paths: per file, the function qualnames that sit on
#: the steady-state dispatch path.  The check is lexical (this body
#: only); oracle/verify helpers are deliberately absent — their host
#: syncs are the point.
HOT_FUNCTIONS = {
    "dccrg_tpu/serve/ensemble.py": {
        "Cohort.step", "Scheduler.step_once", "Scheduler.run",
    },
    "dccrg_tpu/parallel/halo.py": {
        "HaloExchange.__call__", "HaloExchange._dispatch",
        "HaloExchange.start", "HaloExchange._start_dispatch",
        "HaloExchange.finish", "HaloExchange._finish_dispatch",
    },
}

#: calls that force a device→host sync
HOST_SYNC_TAILS = {"block_until_ready", "device_get", "item"}
HOST_SYNC_NP = {"np.asarray", "numpy.asarray", "np.array", "numpy.array"}

#: registry methods that record a named series, by kind
RECORD_KINDS = {
    "inc": "counter", "inc_many": "counter", "inc_batch": "counter",
    "gauge": "gauge", "observe": "histogram",
    "phase": "phase", "phase_add": "phase",
}

#: gate tables parsed out of the CI tools (name -> kind)
CHECK_GATES = {
    "REQUIRED_PHASES": "phase",
    "REQUIRED_NONZERO_COUNTERS": "counter",
    "REQUIRED_HISTOGRAMS": "histogram",
}
DIFF_GATES = {
    "DEFAULT_PHASES": "phase",
    "GATED_COUNTERS": "counter",
    "DEFAULT_ALLOW": "phase",
    "GATED_GAUGES_MIN": "gauge",
    "GATED_GAUGES_MAX": "gauge",
    "GATED_QUANTILES": "histogram",   # tuples of (name, q)
}

#: metric-name grammar: dotted lowercase ("halo.bytes_moved")
METRIC_NAME_RE = re.compile(r"^[a-z][a-z0-9_]*(\.[a-z0-9_]+)+$")

#: mutating methods on dict/list/set/deque
MUTATORS = {"append", "appendleft", "add", "clear", "pop", "popitem",
            "popleft", "update", "setdefault", "extend", "remove",
            "insert", "discard"}

#: calls that materialize a device table; closing over their products
#: inside a jitted body bakes content into the trace
TABLE_CALL_TAILS = {"put_table", "asarray", "device_put"}

ENV_PREFIX = "DCCRG_"


# ------------------------------------------------------------ framework

@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str          # repo-relative posix path
    line: int
    site: str          # structural site id (stable across edits)
    message: str

    @property
    def key(self):
        return (self.rule, self.path, self.site)

    def to_json(self):
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "site": self.site, "message": self.message}


class Mod:
    """One parsed source file with parent links and qualname map."""

    def __init__(self, root: pathlib.Path, path: pathlib.Path):
        self.rel = path.relative_to(root).as_posix()
        self.src = path.read_text()
        self.tree = ast.parse(self.src, filename=str(path))
        self.parent = {}
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                self.parent[child] = node
        self.qualname = {}
        self._name_scopes(self.tree, ())

    def _name_scopes(self, node, stack):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                sub = stack + (child.name,)
                self.qualname[child] = ".".join(sub)
                self._name_scopes(child, sub)
            else:
                self._name_scopes(child, stack)

    def ancestors(self, node):
        while node in self.parent:
            node = self.parent[node]
            yield node

    def enclosing_qualname(self, node) -> str:
        for anc in self.ancestors(node):
            if anc in self.qualname:
                return self.qualname[anc]
        return "<module>"


def dotted(node) -> str | None:
    """'jax.numpy.sum' for an Attribute/Name chain, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


class Ctx:
    def __init__(self, root: pathlib.Path):
        self.root = root
        self.mods: dict[str, Mod] = {}
        self.errors: list[str] = []
        for path in sorted(root.rglob("*.py")):
            rel = path.relative_to(root)
            if any(part in SKIP_DIRS for part in rel.parts):
                continue
            try:
                self.mods[rel.as_posix()] = Mod(root, path)
            except (SyntaxError, UnicodeDecodeError) as e:
                self.errors.append(f"{rel.as_posix()}: unparseable: {e}")

    def under(self, *prefixes):
        for rel, mod in sorted(self.mods.items()):
            if any(rel.startswith(p) for p in prefixes):
                yield rel, mod


class Rule:
    name = "?"
    blurb = "?"

    def run(self, ctx: Ctx):
        raise NotImplementedError


# ------------------------------------------------------- DTYPE-PROMOTE

class DtypePromote(Rule):
    name = "dtype-promote"
    blurb = ("jnp reduction/constructor without dtype= in traced code "
             "(x64 promotion re-keys the compiled body — PR 9 bug class)")

    def run(self, ctx):
        for rel, mod in ctx.under(*TRACED_PREFIXES):
            counts = {}
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.Call):
                    continue
                d = dotted(node.func)
                if d is None:
                    continue
                head, _, tail = d.rpartition(".")
                if tail not in DTYPE_SENSITIVE:
                    continue
                if head not in ("jnp", "jax.numpy"):
                    continue
                if any(kw.arg == "dtype" for kw in node.keywords):
                    continue
                qn = mod.enclosing_qualname(node)
                ordinal = counts.get((qn, tail), 0)
                counts[(qn, tail)] = ordinal + 1
                yield Finding(
                    self.name, rel, node.lineno,
                    f"{qn}:{tail}#{ordinal}",
                    f"{d}(...) without dtype= — under x64 this promotes "
                    f"and re-keys every consumer's trace; pin it like "
                    f"game_of_life.live_neighbor_count (dtype=jnp.uint32)",
                )


# -------------------------------------------------- CLOSED-OVER-TABLE

class ClosedOverTable(Rule):
    name = "closed-over-table"
    blurb = ("jitted function closes over device-table bindings or reads "
             "self state instead of taking them as runtime arguments "
             "(PR 5 invariant; baseline = ROADMAP item-4 worklist)")

    JIT_NAMES = {"jax.jit", "jax.vmap", "jit", "vmap", "traced_jit",
                 "exec_cache.traced_jit"}
    PARTIALS = {"partial", "functools.partial"}

    def run(self, ctx):
        for rel, mod in ctx.under("dccrg_tpu/"):
            entries = self._jit_entries(mod)
            for fn in entries:
                qn = mod.qualname.get(fn, fn.name)
                yield from self._check_entry(mod, rel, fn, qn)

    # ---- entry discovery

    def _jit_entries(self, mod):
        entries = []
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.FunctionDef):
                for dec in node.decorator_list:
                    if self._is_jit_expr(dec):
                        entries.append(node)
                        break
            elif isinstance(node, ast.Call):
                d = dotted(node.func)
                if d in self.JIT_NAMES:
                    for arg in node.args:
                        if isinstance(arg, ast.Name):
                            fd = self._resolve_local_def(mod, node, arg.id)
                            if fd is not None:
                                entries.append(fd)
        seen, out = set(), []
        for fn in entries:
            if id(fn) not in seen:
                seen.add(id(fn))
                out.append(fn)
        return out

    def _resolve_local_def(self, mod, call, name):
        """The FunctionDef `name` refers to at `call`: nearest enclosing
        scope with a directly-nested def of that name (lexical scoping —
        a module-wide name match would conflate every `step`)."""
        scopes = [a for a in mod.ancestors(call)
                  if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef,
                                    ast.Module))]
        for scope in scopes:
            hit = None

            def walk(node):
                nonlocal hit
                for child in ast.iter_child_nodes(node):
                    if isinstance(child, (ast.FunctionDef,
                                          ast.AsyncFunctionDef)):
                        if child.name == name and hit is None:
                            hit = child
                        continue
                    if isinstance(child, ast.Lambda):
                        continue
                    walk(child)

            walk(scope)
            if hit is not None:
                return hit
        return None

    def _is_jit_expr(self, dec):
        d = dotted(dec)
        if d in self.JIT_NAMES:
            return True
        if isinstance(dec, ast.Call):
            fd = dotted(dec.func)
            if fd in self.JIT_NAMES:
                return True
            if fd in self.PARTIALS and dec.args:
                return dotted(dec.args[0]) in self.JIT_NAMES
        return False

    # ---- per-entry closure analysis

    def _check_entry(self, mod, rel, fn, qn):
        bound = self._bound_names(fn)
        free_reads = {}
        self_reads = {}
        for node in ast.walk(fn):
            if (isinstance(node, ast.Name) and
                    isinstance(node.ctx, ast.Load) and
                    node.id not in bound):
                free_reads.setdefault(node.id, node)
            if (isinstance(node, ast.Attribute) and
                    isinstance(node.ctx, ast.Load) and
                    isinstance(node.value, ast.Name) and
                    node.value.id == "self"):
                self_reads.setdefault(node.attr, node)

        scopes = [a for a in mod.ancestors(fn)
                  if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef))]
        lam_tables = self._materializing_lambdas(scopes)

        for name, node in sorted(free_reads.items()):
            binding = self._nearest_binding(scopes, name)
            if binding is None:
                continue
            if self._materializes(binding, lam_tables):
                yield Finding(
                    self.name, rel, fn.lineno, f"{qn}:{name}",
                    f"jitted `{qn}` closes over `{name}` (a put_table/"
                    f"asarray-materialized device table) — content is "
                    f"baked into the trace, so every instance compiles "
                    f"its own body; pass it as a runtime argument",
                )
        table_attrs = self._materialized_self_attrs(mod, fn)
        for attr, node in sorted(self_reads.items()):
            if attr not in table_attrs:
                continue
            yield Finding(
                self.name, rel, node.lineno, f"{qn}:self.{attr}",
                f"jitted `{qn}` reads `self.{attr}` (a device table "
                f"materialized in __init__) — instance state inside a "
                f"traced body re-keys per object; take it as a runtime "
                f"argument",
            )

    def _materialized_self_attrs(self, mod, fn):
        """self attributes bound to put_table/asarray/device_put
        products anywhere in the enclosing class — the array-valued
        instance state a traced body must not read."""
        cls = next((a for a in mod.ancestors(fn)
                    if isinstance(a, ast.ClassDef)), None)
        if cls is None:
            return frozenset()
        out = set()
        for node in ast.walk(cls):
            if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                continue
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            value = node.value
            if value is None:
                continue
            for t in targets:
                if (isinstance(t, ast.Attribute) and
                        isinstance(t.value, ast.Name) and
                        t.value.id == "self" and
                        self._has_table_call(value)):
                    out.add(t.attr)
        return frozenset(out)

    def _bound_names(self, fn):
        bound = set()
        for node in ast.walk(fn):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                bound.add(node.name)
                a = node.args
                for arg in (a.posonlyargs + a.args + a.kwonlyargs):
                    bound.add(arg.arg)
                if a.vararg:
                    bound.add(a.vararg.arg)
                if a.kwarg:
                    bound.add(a.kwarg.arg)
            elif isinstance(node, ast.Lambda):
                a = node.args
                for arg in (a.posonlyargs + a.args + a.kwonlyargs):
                    bound.add(arg.arg)
            elif isinstance(node, ast.Name) and isinstance(
                    node.ctx, (ast.Store, ast.Del)):
                bound.add(node.id)
            elif isinstance(node, (ast.Import, ast.ImportFrom)):
                for alias in node.names:
                    bound.add((alias.asname or alias.name).split(".")[0])
            elif isinstance(node, ast.comprehension):
                for t in ast.walk(node.target):
                    if isinstance(t, ast.Name):
                        bound.add(t.id)
        return bound

    def _scope_bindings(self, scope):
        """name -> value expr assigned directly in `scope` (not inside
        nested function bodies)."""
        out = {}

        def walk(node):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.Lambda)):
                    continue
                if isinstance(child, ast.Assign):
                    for t in child.targets:
                        for n in ast.walk(t):
                            if isinstance(n, ast.Name):
                                out.setdefault(n.id, child.value)
                walk(child)

        walk(scope)
        return out

    def _nearest_binding(self, scopes, name):
        for scope in scopes:
            b = self._scope_bindings(scope)
            if name in b:
                return b[name]
        return None

    def _materializing_lambdas(self, scopes):
        names = set()
        for scope in scopes:
            for n, v in self._scope_bindings(scope).items():
                if isinstance(v, ast.Lambda) and self._has_table_call(v):
                    names.add(n)
        return names

    def _has_table_call(self, expr, lam_tables=frozenset()):
        for node in ast.walk(expr):
            if isinstance(node, ast.Call):
                d = dotted(node.func)
                if d is None:
                    continue
                tail = d.rpartition(".")[2]
                if tail in TABLE_CALL_TAILS or d in lam_tables:
                    return True
        return False

    def _materializes(self, binding, lam_tables):
        return self._has_table_call(binding, lam_tables)


# ------------------------------------------------------------ HOST-SYNC

class HostSync(Rule):
    name = "host-sync"
    blurb = ("device→host sync (block_until_ready/np.asarray/.item()/"
             "float()) inside a declared ensemble/halo hot path")

    def run(self, ctx):
        for rel, wanted in HOT_FUNCTIONS.items():
            mod = ctx.mods.get(rel)
            if mod is None:
                ctx.errors.append(f"host-sync: hot-path file missing: {rel}")
                continue
            found = set()
            for node, qn in mod.qualname.items():
                if qn in wanted and isinstance(node, ast.FunctionDef):
                    found.add(qn)
                    yield from self._scan(mod, rel, node, qn)
            for missing in sorted(wanted - found):
                ctx.errors.append(
                    f"host-sync: declared hot function {rel}:{missing} "
                    f"not found — update HOT_FUNCTIONS")

    def _scan(self, mod, rel, fn, qn):
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            d = dotted(node.func)
            bad = None
            if d in HOST_SYNC_NP:
                bad = d
            elif d == "float" and node.args and not isinstance(
                    node.args[0], ast.Constant):
                bad = "float"
            elif (isinstance(node.func, ast.Attribute) and
                  node.func.attr in HOST_SYNC_TAILS):
                bad = node.func.attr
            if bad:
                yield Finding(
                    self.name, rel, node.lineno, f"{qn}:{bad}",
                    f"`{bad}` in hot path `{qn}` blocks on the device — "
                    f"move it off the steady-state dispatch path (the "
                    f"verify oracles are the sanctioned sync sites)",
                )


# ---------------------------------------------------------- STDLIB-ONLY

class StdlibOnly(Rule):
    name = "stdlib-only"
    blurb = ("module-level non-stdlib import in a declared stdlib-only "
             "module (report tools must file-load without jax)")

    def declared(self, ctx):
        out = list(STDLIB_ONLY_EXTRA)
        for rel in ctx.mods:
            if (rel.startswith("tools/") and "/" not in rel[len("tools/"):]
                    and rel.split("/")[-1] not in STDLIB_ONLY_TOOL_EXEMPT):
                out.append(rel)
        return sorted(set(r for r in out if r in ctx.mods))

    def run(self, ctx):
        declared = set(self.declared(ctx))
        stdlib = set(sys.stdlib_module_names) | {"__future__"}
        for rel in sorted(declared):
            mod = ctx.mods[rel]
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.Import):
                    for alias in node.names:
                        root = alias.name.split(".")[0]
                        if root not in stdlib and not self._nested(mod, node):
                            yield self._finding(rel, node, root, mod)
                elif isinstance(node, ast.ImportFrom):
                    if node.level:
                        target = self._resolve_relative(rel, node)
                        if target not in declared and not self._nested(
                                mod, node):
                            yield Finding(
                                self.name, rel, node.lineno,
                                f"from:{'.' * node.level}{node.module or ''}",
                                f"relative import of `{node.module}` — "
                                f"target is not itself declared "
                                f"stdlib-only",
                            )
                        continue
                    root = (node.module or "").split(".")[0]
                    if root and root not in stdlib and not self._nested(
                            mod, node):
                        yield self._finding(rel, node, root, mod)

    def _nested(self, mod, node):
        """imports inside functions (lazy imports) are the sanctioned
        escape hatch — only module-level imports break file-load."""
        return any(isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef))
                   for a in mod.ancestors(node))

    def _resolve_relative(self, rel, node):
        base = pathlib.PurePosixPath(rel).parent
        for _ in range(node.level - 1):
            base = base.parent
        mod_path = (node.module or "").replace(".", "/")
        return (base / f"{mod_path}.py").as_posix()

    def _finding(self, rel, node, root, mod):
        return Finding(
            self.name, rel, node.lineno, f"import:{root}",
            f"module-level `import {root}` in stdlib-only module — "
            f"move it inside the function that needs it (see "
            f"telemetry_diff._slo() for the file-load pattern)",
        )

    # ---- subprocess probe

    @staticmethod
    def probe(root: pathlib.Path, rel: str) -> str | None:
        """File-load `rel` in a clean subprocess; return an error
        string if jax lands in sys.modules (or the load fails)."""
        code = (
            "import importlib.util, sys\n"
            f"spec = importlib.util.spec_from_file_location('probe', {str(root / rel)!r})\n"
            "m = importlib.util.module_from_spec(spec)\n"
            "sys.modules['probe'] = m\n"
            "spec.loader.exec_module(m)\n"
            "bad = sorted(k for k in sys.modules if k == 'jax' or "
            "k.startswith('jax.') or k.startswith('jaxlib'))\n"
            "assert not bad, f'jax leaked into sys.modules: {bad}'\n"
        )
        r = subprocess.run([sys.executable, "-c", code],
                           capture_output=True, text=True, timeout=120)
        if r.returncode != 0:
            return (r.stderr.strip().splitlines() or ["load failed"])[-1]
        return None


# ------------------------------------------------------ TELEMETRY-DRIFT

class TelemetryDrift(Rule):
    name = "telemetry-drift"
    blurb = ("recorded telemetry series vs CI gate sets: gated-but-"
             "never-recorded, and phases/histograms recorded-but-"
             "never-gated")

    def run(self, ctx):
        recorded, partial, weak = self._recorded(ctx)
        gates = {}
        ok = True
        for rel, table in (("tools/check_telemetry.py", CHECK_GATES),
                           ("tools/telemetry_diff.py", DIFF_GATES)):
            mod = ctx.mods.get(rel)
            if mod is None:
                ctx.errors.append(f"telemetry-drift: missing {rel}")
                ok = False
                continue
            got = self._gate_tables(mod, table)
            for var in table:
                if var not in got:
                    ctx.errors.append(
                        f"telemetry-drift: {rel} has no literal tuple "
                        f"assignment `{var}`")
                    ok = False
            for var, (kind, names) in got.items():
                for n in names:
                    gates.setdefault((kind, n), []).append(f"{rel}:{var}")
        if not ok:
            return

        # (a) gated but never recorded
        for (kind, n), where in sorted(gates.items()):
            strong = recorded.get(kind, set())
            if n in strong or n in weak:
                continue
            if any(n.startswith(p) for p in partial.get(kind, set()) if p):
                continue
            yield Finding(
                self.name, where[0].split(":")[0], 1,
                f"gate:{kind}:{n}",
                f"{kind} `{n}` is gated in {', '.join(where)} but never "
                f"recorded through the registry — dead gate or renamed "
                f"series",
            )

        # (b) recorded but never gated — phases and histograms only:
        # their gate unions are exhaustive by contract; counters/gauges
        # gates are deliberately selective witnesses.
        phase_union = {n for (k, n) in gates if k == "phase"}
        hist_union = {n for (k, n) in gates if k == "histogram"}
        for kind, union in (("phase", phase_union),
                            ("histogram", hist_union)):
            for n, (rel, line) in sorted(recorded.get(
                    kind + "_sites", {}).items()):
                if n in union:
                    continue
                yield Finding(
                    self.name, rel, line, f"recorded:{kind}:{n}",
                    f"{kind} `{n}` is recorded here but appears in no "
                    f"check_telemetry/telemetry_diff gate set — add it "
                    f"to the gates or drop the series",
                )

    def _recorded(self, ctx):
        recorded = {"counter": set(), "gauge": set(), "histogram": set(),
                    "phase": set(), "phase_sites": {},
                    "histogram_sites": {}}
        partial = {"counter": set(), "gauge": set(), "histogram": set(),
                   "phase": set()}
        weak = set()
        for rel, mod in ctx.under("dccrg_tpu/"):
            for node in ast.walk(mod.tree):
                if (isinstance(node, ast.Constant) and
                        isinstance(node.value, str) and
                        METRIC_NAME_RE.match(node.value)):
                    weak.add(node.value)
                if not isinstance(node, ast.Call):
                    continue
                if not isinstance(node.func, ast.Attribute):
                    continue
                kind = RECORD_KINDS.get(node.func.attr)
                if kind is None or not node.args:
                    continue
                first = node.args[0]
                if (isinstance(first, ast.Constant) and
                        isinstance(first.value, str)):
                    recorded[kind].add(first.value)
                    sites = recorded.get(kind + "_sites")
                    if sites is not None and first.value not in sites:
                        sites[first.value] = (rel, node.lineno)
                elif isinstance(first, ast.JoinedStr):
                    head = first.values[0] if first.values else None
                    if (isinstance(head, ast.Constant) and
                            isinstance(head.value, str)):
                        partial[kind].add(head.value)
        return recorded, partial, weak

    def _gate_tables(self, mod, table):
        out = {}
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Assign):
                continue
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id in table:
                    names = []
                    for el in ast.walk(node.value):
                        if (isinstance(el, ast.Constant) and
                                isinstance(el.value, str)):
                            names.append(el.value)
                    out[t.id] = (table[t.id], names)
        return out


# ------------------------------------------------------ LOCK-DISCIPLINE

class LockDiscipline(Rule):
    name = "lock-discipline"
    blurb = ("mutation of lock-guarded shared dict/list/set/deque "
             "attributes outside `with self._lock:`")

    CONTAINER_CALLS = {"dict", "list", "set", "deque",
                       "collections.deque", "collections.defaultdict",
                       "defaultdict", "OrderedDict",
                       "collections.OrderedDict"}
    LOCK_CALLS = {"threading.Lock", "threading.RLock", "Lock", "RLock"}

    def run(self, ctx):
        for rel, mod in ctx.under("dccrg_tpu/"):
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.ClassDef):
                    yield from self._check_class(mod, rel, node)

    def _check_class(self, mod, rel, cls):
        init = next((n for n in cls.body
                     if isinstance(n, ast.FunctionDef)
                     and n.name == "__init__"), None)
        if init is None:
            return
        lock_attrs, guarded = set(), set()
        for node in ast.walk(init):
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                t = node.targets[0]
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                t = node.target
            else:
                continue
            if not (isinstance(t, ast.Attribute) and
                    isinstance(t.value, ast.Name) and t.value.id == "self"):
                continue
            v = node.value
            if isinstance(v, ast.Call) and dotted(v.func) in self.LOCK_CALLS:
                lock_attrs.add(t.attr)
            elif isinstance(v, (ast.Dict, ast.List, ast.Set)):
                guarded.add(t.attr)
            elif (isinstance(v, ast.Call) and
                  dotted(v.func) in self.CONTAINER_CALLS):
                guarded.add(t.attr)
        if not lock_attrs or not guarded:
            return
        qn_cls = mod.qualname[cls]
        for meth in cls.body:
            if (not isinstance(meth, ast.FunctionDef) or
                    meth.name == "__init__"):
                continue
            for node in ast.walk(meth):
                attr = self._mutation(node, guarded)
                if attr and not self._under_lock(mod, node, lock_attrs,
                                                 meth):
                    yield Finding(
                        self.name, rel, node.lineno,
                        f"{qn_cls}.{meth.name}:{attr}",
                        f"`{qn_cls}.{meth.name}` mutates shared "
                        f"`self.{attr}` outside `with self._lock:` — "
                        f"concurrent recorders race (see the registry "
                        f"thread-stress test)",
                    )

    def _self_attr(self, node):
        if (isinstance(node, ast.Attribute) and
                isinstance(node.value, ast.Name) and
                node.value.id == "self"):
            return node.attr
        return None

    def _mutation(self, node, guarded):
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.Delete)):
            targets = (node.targets if isinstance(node, (ast.Assign,
                                                         ast.Delete))
                       else [node.target])
            for t in targets:
                if isinstance(t, ast.Subscript):
                    a = self._self_attr(t.value)
                    if a in guarded:
                        return a
                a = self._self_attr(t)
                if a in guarded:
                    return a
        if isinstance(node, ast.Call) and isinstance(node.func,
                                                     ast.Attribute):
            if node.func.attr in MUTATORS:
                base = node.func.value
                if isinstance(base, ast.Subscript):
                    base = base.value
                a = self._self_attr(base)
                if a in guarded:
                    return a
        return None

    def _under_lock(self, mod, node, lock_attrs, stop):
        for anc in mod.ancestors(node):
            if isinstance(anc, ast.With):
                for item in anc.items:
                    expr = item.context_expr
                    a = self._self_attr(expr)
                    if a is None and isinstance(expr, ast.Call):
                        a = self._self_attr(expr.func)
                    if a in lock_attrs:
                        return True
            if anc is stop:
                return False
        return False


# ------------------------------------------------------------ ENV-DRIFT

class EnvDrift(Rule):
    name = "env-drift"
    blurb = ("DCCRG_* getenv sites vs README env tables: undocumented "
             "knobs and dead documented knobs")

    GETENV = {"os.environ.get", "os.getenv", "environ.get",
              "os.environ.setdefault", "environ.setdefault"}

    def run(self, ctx):
        read_sites = {}
        referenced = set()
        for rel, mod in ctx.under("dccrg_tpu/", "tools/", "bench.py",
                                  "benchmarks/", "examples/"):
            for node in ast.walk(mod.tree):
                if (isinstance(node, ast.Constant) and
                        isinstance(node.value, str) and
                        node.value.startswith(ENV_PREFIX)):
                    referenced.add(node.value)
                name = self._getenv_key(node)
                if name:
                    read_sites.setdefault(name, (rel, node.lineno))

        readme = ctx.root / "README.md"
        if not readme.exists():
            ctx.errors.append("env-drift: README.md not found")
            return
        documented = set(re.findall(r"\bDCCRG_[A-Z0-9_]+\b",
                                    readme.read_text()))

        for name, (rel, line) in sorted(read_sites.items()):
            if name not in documented:
                yield Finding(
                    self.name, rel, line, f"undocumented:{name}",
                    f"env knob `{name}` is read here but has no README "
                    f"row — document it (or run --fix-docs for a "
                    f"paste-ready row)",
                )
        for name in sorted(documented - referenced):
            yield Finding(
                self.name, "README.md", 1, f"dead:{name}",
                f"env knob `{name}` is documented in README but no "
                f"longer referenced anywhere in code — delete the row",
            )

    def _getenv_key(self, node):
        if not (isinstance(node, ast.Call) and node.args):
            # os.environ["DCCRG_X"] loads
            if (isinstance(node, ast.Subscript) and
                    isinstance(node.ctx, ast.Load) and
                    dotted(node.value) in ("os.environ", "environ") and
                    isinstance(node.slice, ast.Constant) and
                    isinstance(node.slice.value, str) and
                    node.slice.value.startswith(ENV_PREFIX)):
                return node.slice.value
            return None
        if dotted(node.func) not in self.GETENV:
            return None
        first = node.args[0]
        if (isinstance(first, ast.Constant) and
                isinstance(first.value, str) and
                first.value.startswith(ENV_PREFIX)):
            return first.value
        return None

    @staticmethod
    def fix_docs(findings):
        rows = []
        for f in findings:
            if f.rule != "env-drift" or not f.site.startswith(
                    "undocumented:"):
                continue
            name = f.site.split(":", 1)[1]
            rows.append(f"| `{name}` | (unset) | TODO: describe — read "
                        f"at {f.path}:{f.line} |")
        return rows


# ------------------------------------------------------------- baseline

def load_baseline(path: pathlib.Path):
    if not path.exists():
        return []
    data = json.loads(path.read_text())
    return data.get("entries", [])


def apply_baseline(findings, entries):
    by_key = {(e["rule"], e["path"], e["site"]): e for e in entries}
    active, suppressed, matched = [], [], set()
    for f in findings:
        if f.key in by_key:
            suppressed.append(f)
            matched.add(f.key)
        else:
            active.append(f)
    stale = [e for e in entries
             if (e["rule"], e["path"], e["site"]) not in matched]
    return active, suppressed, stale


def write_baseline(path, findings, old_entries, carried=()):
    reasons = {(e["rule"], e["path"], e["site"]): e.get("reason", "")
               for e in old_entries}
    entries = [
        {"rule": f.rule, "path": f.path, "site": f.site,
         "reason": reasons.get(f.key, "unreviewed — justify or fix")}
        for f in sorted(findings, key=lambda f: f.key)
    ] + list(carried)
    entries.sort(key=lambda e: (e["rule"], e["path"], e["site"]))
    path.write_text(json.dumps({"entries": entries}, indent=2) + "\n")
    return entries


# ------------------------------------------------------------------ cli

RULES = (DtypePromote, ClosedOverTable, HostSync, StdlibOnly,
         TelemetryDrift, LockDiscipline, EnvDrift)


def run_lint(root: pathlib.Path, rules=None, baseline_entries=None):
    """Programmatic entry: returns (active, suppressed, stale, errors)."""
    ctx = Ctx(root)
    ran = tuple(rules or RULES)
    findings = []
    for cls in ran:
        findings.extend(cls().run(ctx))
    entries = baseline_entries
    if entries is None:
        entries = load_baseline(root / BASELINE_REL)
    # staleness is only decidable for rules that ran: a --rule subset
    # must not declare the other rules' baseline entries fixed
    ran_names = {cls.name for cls in ran}
    entries = [e for e in entries if e["rule"] in ran_names]
    active, suppressed, stale = apply_baseline(findings, entries)
    return active, suppressed, stale, ctx.errors


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="dccrg_lint",
        description="AST invariant checker for the dccrg_tpu port")
    ap.add_argument("--root", default=str(REPO_ROOT),
                    help="repo root to scan (default: this checkout)")
    ap.add_argument("--rule", action="append", default=None,
                    help="run only this rule (repeatable)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable report on stdout")
    ap.add_argument("--fix-docs", action="store_true",
                    help="print paste-ready README rows for "
                         "undocumented env knobs")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline from current findings "
                         "(preserves reasons for surviving entries)")
    ap.add_argument("--probe", action="store_true",
                    help="also run the subprocess stdlib-only import "
                         "probe (slower)")
    args = ap.parse_args(argv)

    root = pathlib.Path(args.root).resolve()
    rules = RULES
    if args.rule:
        by_name = {c.name: c for c in RULES}
        unknown = [r for r in args.rule if r not in by_name]
        if unknown:
            print(f"unknown rule(s): {', '.join(unknown)} "
                  f"(have: {', '.join(by_name)})", file=sys.stderr)
            return 2
        rules = tuple(by_name[r] for r in args.rule)

    active, suppressed, stale, errors = run_lint(root, rules)

    probe_failures = []
    if args.probe:
        for rel in PROBE_TARGETS:
            if not (root / rel).exists():
                continue
            err = StdlibOnly.probe(root, rel)
            if err:
                probe_failures.append({"path": rel, "error": err})

    if args.update_baseline:
        path = root / BASELINE_REL
        old = load_baseline(path)
        # a --rule subset only rewrites its own rules' entries; the
        # rest of the baseline is carried over untouched
        ran_names = {c.name for c in rules}
        carried = [e for e in old if e["rule"] not in ran_names]
        entries = write_baseline(path, active + suppressed, old,
                                 carried=carried)
        print(f"baseline rewritten: {len(entries)} entries")
        return 0

    rc = 1 if (active or stale or errors or probe_failures) else 0

    if args.fix_docs:
        rows = EnvDrift.fix_docs(active)
        if rows:
            print("# paste into the README env table:")
            for r in rows:
                print(r)
        else:
            print("# no undocumented env knobs")

    if args.as_json:
        print(json.dumps({
            "findings": [f.to_json() for f in active],
            "suppressed": len(suppressed),
            "stale_baseline": stale,
            "probe_failures": probe_failures,
            "errors": errors,
            "rc": rc,
        }, indent=2))
        return rc

    for f in active:
        print(f"{f.path}:{f.line}: [{f.rule}] {f.message}")
    for e in stale:
        print(f"{BASELINE_REL}: stale baseline entry "
              f"{e['rule']}:{e['path']}:{e['site']} — the finding is "
              f"gone; delete the entry")
    for p in probe_failures:
        print(f"{p['path']}: [stdlib-only probe] {p['error']}")
    for e in errors:
        print(f"[lint-error] {e}")
    if rc == 0:
        n = len(suppressed)
        print(f"dccrg-lint: clean ({len(rules)} rules, "
              f"{n} baseline-suppressed)")
    else:
        print(f"dccrg-lint: {len(active)} finding(s), {len(stale)} "
              f"stale baseline, {len(errors)} error(s)")
    return rc


if __name__ == "__main__":
    sys.exit(main())
