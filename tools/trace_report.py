#!/usr/bin/env python
"""Device-timeline report: what ran, where the time went, what overlapped.

Consumes the merged host+device timeline (``dccrg_tpu.obs.merge``) and
prints the three answers a perf PR needs:

* **top kernels by device time** — attribution keyed by the same kernel
  labels ``epoch.recompiles{kernel}`` counts (``traced_jit`` names the
  compiled modules), so "what compiled" and "what ran" line up;
* **overlap summary** — the measured ``overlap.fraction{phase=halo}``:
  how much of the collective in-flight window (``halo.start`` dispatch
  -> ``halo.exchange`` wait) coincided with interior device compute;
* **host-gap hunting** — windows where every device sat idle, with the
  host phases that were open (where dispatch overhead hides).

Three input modes:

    python tools/trace_report.py --run             # self-contained probe:
        profile one split-phase round in-process, full merge (host
        timeline + device planes), report + gauges; --model picks the
        drive (host-split advection, or the fused split-phase step of
        gol/advection-fused/vlasov) and --halo-backend pins the halo
        transport (ISSUE 7)
    python tools/trace_report.py LOGDIR            # post-hoc: an existing
        jax.profiler log dir; the host track is rebuilt from the capture's
        own TraceAnnotations (no live timeline needed)
    python tools/trace_report.py --fleet T1 T2 ..  # unify per-process
        merged traces on their shared epoch-zero into one fleet trace

``--json`` prints the full machine-readable record (CI consumes the
``overlap``/``kernels`` keys); ``--merged-out`` exports the merged Chrome
trace for perfetto.  Backends that emit no execution lines (and
``DCCRG_XPLANE=0``) report ``device_evidence: false`` and exit 0 — the
documented no-op — unless ``--require-devices`` makes absence an error.
"""
from __future__ import annotations

import argparse
import json
import os
import pathlib
import sys
import tempfile

ROOT = pathlib.Path(__file__).resolve().parent.parent


def _ensure_env() -> None:
    if str(ROOT) not in sys.path:
        sys.path.insert(0, str(ROOT))
    sys.path.insert(0, str(ROOT / "tools"))
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=4"
        ).strip()


def run_probe(steps: int = 6, model: str = "advection",
              halo_backend: str | None = None):
    """Profile one split-phase round in-process and return
    ``(merged, summary)`` — the full live-host merge, gauges recorded.

    ``model`` picks the drive: ``advection`` profiles the host-split
    start/compute/wait loop (the source paper's pattern), while
    ``advection-fused``, ``vlasov`` and ``gol`` profile the model's
    FUSED split-phase step (one compiled start → interior → finish →
    boundary program, ISSUE 7).  ``halo_backend`` exports
    ``DCCRG_HALO_BACKEND`` before any schedule compiles, so any model's
    overlap can be measured on either transport from the CLI."""
    from dccrg_tpu import obs
    import check_telemetry as ct

    if halo_backend:
        os.environ["DCCRG_HALO_BACKEND"] = halo_backend
    obs.enable()
    obs.enable_timeline()
    g, adv, state, dt = ct.build_workload()
    if model == "advection":
        state = ct.drive(g, adv, state, dt, 2)      # warm the compiles
        state = ct.drive_split(g, adv, state, dt, 1)
        with tempfile.TemporaryDirectory() as td:
            with obs.profile_trace(td):
                ct.drive_split(g, adv, state, dt, steps)
            return obs.merge_profile(td)
    name = "advection" if model == "advection-fused" else model
    step_once, mstate = ct.build_fused_model(g, name)
    mstate = ct.drive_fused(step_once, mstate, 1)   # warm the compiles
    with tempfile.TemporaryDirectory() as td:
        with obs.profile_trace(td):
            ct.drive_fused(step_once, mstate, steps)
        return obs.merge_profile(td, extra_labels={"model": name})


def report_record(merged, summary, top: int = 10,
                  gaps_min_us: float = 100.0) -> dict:
    """The machine-readable report: summary + top kernels + gaps +
    the recompile-key cross-reference (when this process compiled)."""
    from dccrg_tpu import obs

    kernels = list(summary["kernels"].items())[:top]
    recompiles = obs.metrics.report()["counters"].get(
        "epoch.recompiles", {}
    )
    compiled = {k.split("=", 1)[1] for k in recompiles if "=" in k}
    return {
        "window_s": summary["window_s"],
        "aligned": summary["aligned"],
        "alignment": summary["alignment"],
        "device_evidence": summary["device_evidence"],
        "devices": summary["devices"],
        "overlap": summary["overlap"],
        "top_kernels": [
            {"kernel": name, **rec,
             "compiled_this_process": name in compiled}
            for name, rec in kernels
        ],
        "host_gaps": merged.host_gaps(min_us=gaps_min_us, top=top),
    }


def print_report(rec: dict) -> None:
    print(f"window {rec['window_s'] * 1e3:.1f} ms   "
          f"aligned: {rec['aligned']}   "
          f"devices: {len(rec['devices'])}")
    if not rec["device_evidence"]:
        print("no device execution evidence in this capture "
              "(deviceless backend or DCCRG_XPLANE=0) — host-only report")
        return
    for dev, d in sorted(rec["devices"].items(), key=lambda kv: str(kv[0])):
        print(f"  device {dev} ({d['kind']}): busy {d['busy_s'] * 1e3:.2f} ms"
              f" ({d['fraction'] * 100:.1f}%), {d['spans']} spans")
    ov = rec["overlap"]["halo"]
    if ov["fraction"] is not None:
        print(f"overlap[halo]: {ov['fraction'] * 100:.1f}% of "
              f"{ov['inflight_s'] * 1e3:.2f} ms in-flight hidden under "
              f"interior compute "
              f"(compute {ov['device_compute_s'] * 1e3:.2f} ms, "
              f"collectives {ov['device_collective_s'] * 1e3:.2f} ms)")
    else:
        print("overlap[halo]: no halo spans on the host track")
    print(f"top kernels by device time:")
    for k in rec["top_kernels"]:
        mark = "*" if k["compiled_this_process"] else " "
        print(f" {mark} {k['kernel']:32s} {k['time_us'] / 1e3:10.2f} ms  "
              f"{k['count']:8d} calls  ({k['module'] or '-'})")
    if rec["top_kernels"]:
        print("   (* = kernel label also in this process's "
              "epoch.recompiles)")
    if rec["host_gaps"]:
        print("host gaps (all devices idle):")
        for gap in rec["host_gaps"]:
            phases = ", ".join(gap["open_host_phases"]) or "-"
            print(f"   +{gap['start_us'] / 1e3:10.2f} ms  "
                  f"{gap['dur_us'] / 1e3:8.2f} ms   open: {phases}")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument("log_dir", nargs="?", default=None,
                    help="existing jax.profiler log dir to analyze "
                         "post-hoc (host track from its annotations)")
    ap.add_argument("--run", action="store_true",
                    help="profile a built-in split-phase advection round "
                         "in-process and report the live merge")
    ap.add_argument("--steps", type=int, default=6,
                    help="probe steps under --run")
    ap.add_argument("--model",
                    choices=("advection", "advection-fused", "gol",
                             "vlasov"),
                    default="advection",
                    help="drive profiled under --run: 'advection' is "
                         "the host-split loop; the others drive the "
                         "model's fused split-phase step (ISSUE 7)")
    ap.add_argument("--halo-backend", choices=("collective", "pallas",
                                               "auto"),
                    default=None,
                    help="export DCCRG_HALO_BACKEND before the probe "
                         "compiles its halo schedules")
    ap.add_argument("--fleet", nargs="+", default=None, metavar="TRACE",
                    help="merge per-process merged traces onto their "
                         "shared epoch-zero; write with --merged-out")
    ap.add_argument("--top", type=int, default=10,
                    help="kernels/gaps listed")
    ap.add_argument("--gaps-min-us", type=float, default=100.0,
                    help="minimum device-idle gap reported")
    ap.add_argument("--json", action="store_true",
                    help="print the machine-readable record (CI mode)")
    ap.add_argument("--merged-out", default=None, metavar="FILE",
                    help="also export the merged Chrome trace here")
    ap.add_argument("--require-devices", action="store_true",
                    help="exit 1 when the capture holds no device "
                         "execution evidence (CI on device hosts)")
    args = ap.parse_args(argv)
    _ensure_env()

    if args.fleet:
        from dccrg_tpu.obs.merge import (merge_chrome_traces,
                                         validate_merged_trace)

        fleet = merge_chrome_traces(args.fleet, out_path=args.merged_out)
        failures = validate_merged_trace(fleet)
        rec = {
            "sources": fleet["otherData"]["sources"],
            "events": len(fleet["traceEvents"]),
            "origin_unix_s": fleet["otherData"]["origin_unix_s"],
            "valid": not failures,
            "failures": failures,
        }
        if args.json:
            print(json.dumps(rec, indent=1))
        else:
            print(f"fleet trace: {rec['events']} events from "
                  f"{len(rec['sources'])} processes on epoch-zero "
                  f"{rec['origin_unix_s']:.6f}"
                  + (f" -> {args.merged_out}" if args.merged_out else ""))
            for f in failures:
                print(f"FAIL: {f}", file=sys.stderr)
        return 1 if failures else 0

    if args.run or args.log_dir is None:
        merged, summary = run_probe(steps=args.steps, model=args.model,
                                    halo_backend=args.halo_backend)
    else:
        from dccrg_tpu.obs.merge import build_from_capture

        merged = build_from_capture(args.log_dir)
        summary = merged.summary()
    if args.merged_out:
        merged.export(args.merged_out)
    rec = report_record(merged, summary, top=args.top,
                        gaps_min_us=args.gaps_min_us)
    if args.json:
        print(json.dumps(rec, indent=1, default=float))
    else:
        print_report(rec)
    if args.require_devices and not rec["device_evidence"]:
        print("FAIL: no device execution evidence", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
