#!/usr/bin/env python
"""Live fleet console: windowed SLOs, throughput and health from the
per-process ``*.stream.jsonl`` files a running fleet writes.

Point it at the directory the serving processes stream into (or a glob,
or explicit files) and it tails every stream from byte offsets, merges
counters and log-bucket histograms across processes (exact: merging
per-process exports equals pooling the samples), and prints one
windowed snapshot — or refreshes in place with ``--follow``::

    python tools/fleet_top.py /var/run/dccrg/          # one snapshot
    python tools/fleet_top.py run/ --window 30 --follow
    python tools/fleet_top.py run/ --json -            # machine-readable
    python tools/fleet_top.py run/ --prometheus fleet.prom
    python tools/fleet_top.py run/ --alerts            # rule states too
    python tools/fleet_top.py run/ --cost              # cost & capacity
    python tools/fleet_top.py run/ --workers           # gateway fleet view

Every snapshot leads with a per-writer table including each stream's
staleness (``age_s`` — seconds since its last snapshot): a silent dead
writer otherwise just freezes its numbers into every window.  With
``--cost`` the snapshot adds the cost & capacity section (ISSUE 17):
the fleet step-cost model table, the per-tenant chargeback ledger with
its conservation check, and predicted queue-waits.

This tool file-loads ``dccrg_tpu/obs/live.py`` (and ``--cost`` loads
``obs/cost.py`` — both stdlib-only by contract), so watching a fleet
never imports jax.
"""
from __future__ import annotations

import argparse
import importlib.util
import json
import pathlib
import sys
import time

ROOT = pathlib.Path(__file__).resolve().parent.parent

#: latency histograms tabulated per window (--metrics overrides)
DEFAULT_METRICS = (
    "ensemble.queue_wait_s",
    "ensemble.service_s",
    "ensemble.e2e_s",
)

#: windowed counter rates shown in the throughput block
RATE_COUNTERS = (
    "ensemble.steps_served",
    "ensemble.retired",
    "ensemble.deadline_miss",
)


def _load(name: str):
    path = ROOT / "dccrg_tpu" / "obs" / f"{name}.py"
    spec = importlib.util.spec_from_file_location(
        f"dccrg_fleet_{name}", str(path))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def snapshot(view, metrics, qs) -> dict:
    """One JSON-ready fleet snapshot from a view."""
    latency = []
    for name in metrics:
        series = (view.window_report.get("histograms") or {}).get(name) or {}
        for label in sorted(series):
            h = series[label]
            row = {"metric": name, "labels": label,
                   "count": int(h.get("count") or 0),
                   "mean": h.get("mean")}
            for q in qs:
                row[f"p{round(q * 100):d}"] = view.quantile(
                    name, q, labels=_labels_dict(label))
            latency.append(row)
    rates = {}
    for name in RATE_COUNTERS:
        series = (view.window_report.get("counters") or {}).get(name) or {}
        if series:
            rates[name] = {label: v / view.window_s
                           for label, v in sorted(series.items())}
    return {
        "ts": view.now,
        "window_s": view.window_s,
        "health": view.health,
        "files": view.files,
        "latency": latency,
        "rates": rates,
        "deadline_miss_rates": view.miss_rates(),
        "gauges": view.cumulative_report.get("gauges") or {},
    }


def _labels_dict(label_str: str) -> dict:
    return dict(kv.split("=", 1)
                for kv in (label_str or "").split(",") if "=" in kv)


def print_snapshot(snap: dict, qs, alerts=None) -> None:
    h = snap["health"]
    print(f"fleet_top  window={snap['window_s']:.0f}s  "
          f"files={h['files']} ({h['stale_files']} stale)  "
          f"records={h['records']}  seq_gaps={h['seq_gaps']}  "
          f"torn_tails={h['torn_tails']}  bad_lines={h['bad_lines']}")
    files = snap.get("files") or []
    if files:
        print(f"{'writer':36s} {'age_s':>8s} {'seq':>8s} {'gaps':>5s} "
              f"{'torn':>5s}")
        for f in sorted(files, key=lambda f: -f["age_s"]):
            name = pathlib.Path(f["path"]).name
            seq = f.get("seq")
            print(f"{name:36s} {f['age_s']:>8.1f} "
                  f"{'n/a' if seq is None else seq:>8} "
                  f"{f['seq_gaps']:>5d} {f['torn_tails']:>5d}")
    qcols = [f"p{round(q * 100):d}" for q in qs]
    if snap["latency"]:
        head = (f"{'metric':24s} {'labels':28s} {'count':>7s} "
                + " ".join(f"{c + '(ms)':>10s}" for c in ["mean"] + qcols))
        print(head)
        print("-" * len(head))
        for r in snap["latency"]:
            cells = [r.get("mean")] + [r.get(c) for c in qcols]
            print(f"{r['metric']:24s} {r['labels']:28s} {r['count']:>7d} "
                  + " ".join("       n/a" if v is None
                             else f"{v * 1e3:>10.3f}" for v in cells))
    else:
        print("  (no latency samples in the window)")
    if snap["rates"]:
        print()
        print(f"{'counter':28s} {'labels':24s} {'rate/s':>10s}")
        for name, series in sorted(snap["rates"].items()):
            for label, r in series.items():
                print(f"{name:28s} {label:24s} {r:>10.3f}")
    miss = snap["deadline_miss_rates"]
    if miss:
        print()
        print(f"{'tenant':16s} {'completed':>9s} {'missed':>7s} {'rate':>8s}")
        for tenant, rec in sorted(miss.items()):
            rate = rec["rate"]
            print(f"{tenant:16s} {rec['completed']:>9d} "
                  f"{rec['missed']:>7d} "
                  f"{'n/a' if rate is None else f'{rate:8.2%}'}")
    if alerts is not None:
        print()
        print(f"{'alert rule':28s} {'status':8s} {'value':>12s} "
              f"{'fires':>6s}")
        for name, st in sorted(alerts.items()):
            v = st.get("value")
            print(f"{name:28s} {st['status']:8s} "
                  f"{'n/a' if v is None else f'{v:12.4g}'} "
                  f"{st['fires']:>6d}")
    if snap.get("workers") is not None:
        print_workers(snap["workers"])
    if snap.get("cost") is not None:
        print_cost(snap["cost"])


def workers_section(view) -> dict:
    """The ``--workers`` snapshot section (ISSUE 19): per-worker
    liveness from each ``worker.stream.jsonl`` heartbeat's staleness
    (the same ``stream.age_s`` signal the shipped ``worker-lost``
    alert rule fires on), assigned/in-flight counts from the gateway's
    ``gateway.assigned{worker}`` gauges, and redispatch events from
    the ``gateway.redispatched{worker}`` counter."""
    import os

    try:
        stall = float(os.environ.get("DCCRG_GATEWAY_STALL_S", "10"))
    except ValueError:
        stall = 10.0
    cum = view.cumulative_report
    gauges = cum.get("gauges") or {}
    counters = cum.get("counters") or {}
    workers: dict = {}

    def row(wid: str) -> dict:
        return workers.setdefault(wid, {
            "age_s": None, "alive": None, "seq": None, "torn": 0,
            "assigned": 0, "redispatched_from": 0})

    for f in view.files:
        p = pathlib.Path(f["path"])
        if "worker" not in p.name:
            continue
        r = row(p.parent.name or p.stem)
        r["age_s"] = f["age_s"]
        r["alive"] = f["age_s"] <= stall
        r["seq"] = f.get("seq")
        r["torn"] = f.get("torn_tails", 0)
    for label, v in (gauges.get("gateway.assigned") or {}).items():
        wid = _labels_dict(label).get("worker")
        if wid:
            row(wid)["assigned"] = int(v)
    for label, v in (counters.get("gateway.redispatched") or {}).items():
        wid = _labels_dict(label).get("worker")
        if wid:
            row(wid)["redispatched_from"] = int(v)
    return {
        "workers": workers,
        "redispatch_total": int(sum(
            (counters.get("gateway.redispatched") or {}).values())),
        "worker_lost_total": int(sum(
            (counters.get("gateway.worker_lost") or {}).values())),
        "backlog": (gauges.get("gateway.backlog") or {}).get("", None),
    }


def print_workers(w: dict) -> None:
    print()
    print(f"workers  redispatches={w['redispatch_total']}  "
          f"lost={w['worker_lost_total']}  "
          f"backlog={'n/a' if w.get('backlog') is None else w['backlog']}")
    rows = w.get("workers") or {}
    if not rows:
        print("  (no worker streams found)")
        return
    print(f"{'worker':16s} {'live':>5s} {'age_s':>8s} {'seq':>8s} "
          f"{'assigned':>9s} {'redisp_from':>12s}")
    for wid, r in sorted(rows.items()):
        age = r.get("age_s")
        alive = r.get("alive")
        print(f"{wid:16s} "
              f"{'n/a' if alive is None else ('yes' if alive else 'NO'):>5s} "
              f"{'n/a' if age is None else f'{age:8.1f}':>8s} "
              f"{'n/a' if r.get('seq') is None else r['seq']:>8} "
              f"{r['assigned']:>9d} {r['redispatched_from']:>12d}")


def cost_section(view, cost_mod) -> dict:
    """The ``--cost`` snapshot section: the fleet cost model and
    ledger from the cumulative merge, plus windowed read-side
    queue-wait estimates (bucket-delta service rates)."""
    out = cost_mod.cost_summary(view.cumulative_report)
    out["queue_wait_estimates"] = cost_mod.queue_wait_estimates(view)
    return out


def print_cost(cost: dict) -> None:
    rows = cost.get("model") or []
    print()
    if rows:
        print(f"{'cost model key':44s} {'n':>6s} {'mean(ms)':>9s} "
              f"{'p50(ms)':>9s} {'p95(ms)':>9s}")
        for r in rows:
            print(f"{r['key']:44s} {r['n']:>6d} "
                  f"{r['mean_s'] * 1e3:>9.3f} "
                  f"{r.get('p50_s', 0.0) * 1e3:>9.3f} "
                  f"{r.get('p95_s', 0.0) * 1e3:>9.3f}")
    else:
        print("  (no cost-model samples)")
    ledger = cost.get("chargeback") or {}
    if ledger:
        print()
        print(f"{'tenant':16s} {'device_s':>10s} {'share':>7s} "
              f"{'steps':>8s} {'halo_ex':>9s} {'compile_s':>9s}")
        for tenant, rec in sorted(ledger.items()):
            print(f"{tenant:16s} {rec['device_s']:>10.3f} "
                  f"{rec['device_share']:>7.2%} "
                  f"{rec['member_steps']:>8d} "
                  f"{rec['halo_exchanges']:>9.0f} "
                  f"{rec['compile_s']:>9.3f}")
        cons = cost.get("conservation") or {}
        ratio = cons.get("ratio")
        print(f"conservation: attributed={cons.get('attributed', 0.0):.3f}s "
              f"total={cons.get('total', 0.0):.3f}s "
              f"ratio={'n/a' if ratio is None else f'{ratio:.4f}'} "
              f"{'OK' if cons.get('ok') else 'VIOLATED'}")
    waits = {**(cost.get("predicted_queue_wait_s") or {}),
             **(cost.get("queue_wait_estimates") or {})}
    if waits:
        print()
        print(f"{'tenant':16s} {'predicted_wait_s':>16s}")
        for tenant, w in sorted(waits.items()):
            print(f"{tenant:16s} {w:>16.3f}")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument("sources", nargs="*", default=["."],
                    help="stream dir(s), glob(s) or *.stream.jsonl files")
    ap.add_argument("--window", type=float, default=None,
                    help="sliding window seconds "
                         "(default DCCRG_LIVE_WINDOW_S or 60)")
    ap.add_argument("--metrics", default=",".join(DEFAULT_METRICS),
                    help="comma-separated histogram names to tabulate")
    ap.add_argument("--quantiles", default="0.5,0.95,0.99",
                    help="comma-separated quantile fractions")
    ap.add_argument("--json", default=None,
                    help="write the snapshot JSON to this path ('-' "
                         "for stdout, replacing the console view)")
    ap.add_argument("--prometheus", default=None,
                    help="write a Prometheus text exposition of the "
                         "windowed report to this path ('-' for stdout)")
    ap.add_argument("--alerts", action="store_true",
                    help="evaluate the alert rules (DCCRG_ALERT_RULES "
                         "or the shipped defaults) against each view")
    ap.add_argument("--cost", action="store_true",
                    help="add the cost & capacity section: step-cost "
                         "model, chargeback ledger + conservation, "
                         "predicted queue-waits")
    ap.add_argument("--workers", action="store_true",
                    help="add the gateway fleet section: per-worker "
                         "liveness (heartbeat staleness), assigned "
                         "counts and redispatch events")
    ap.add_argument("--follow", action="store_true",
                    help="refresh in place every --refresh seconds")
    ap.add_argument("--refresh", type=float, default=2.0,
                    help="refresh period for --follow")
    ap.add_argument("--iterations", type=int, default=0,
                    help="with --follow: stop after N refreshes "
                         "(0 = until interrupted)")
    args = ap.parse_args(argv)

    live = _load("live")
    qs = tuple(float(x) for x in args.quantiles.split(",") if x)
    metrics = [m for m in args.metrics.split(",") if m]
    paths: list = []
    for src in args.sources:
        paths.extend(live.discover_streams(src))
    if not paths and not args.follow:
        print("fleet_top: no *.stream.jsonl sources found",
              file=sys.stderr)
        return 2
    # a single directory source keeps discovering new writers per poll
    sources = (args.sources[0]
               if len(args.sources) == 1 and not paths else paths)
    agg = live.FleetAggregator(sources, window_s=args.window)
    cost_mod = _load("cost") if args.cost else None
    engine = None
    if args.alerts:
        alerts_mod = _load("alerts")
        if alerts_mod.alerts_enabled():
            engine = alerts_mod.AlertEngine(alerts_mod.rules_from_env())

    n = 0
    while True:
        agg.poll()
        view = agg.view()
        alert_states = None
        if engine is not None:
            engine.poll(view)
            alert_states = engine.snapshot()
        snap = snapshot(view, metrics, qs)
        if alert_states is not None:
            snap["alerts"] = alert_states
        if cost_mod is not None:
            snap["cost"] = cost_section(view, cost_mod)
        if args.workers:
            snap["workers"] = workers_section(view)
        if args.prometheus:
            text = live.to_prometheus(view.window_report)
            if args.prometheus == "-":
                sys.stdout.write(text)
            else:
                with open(args.prometheus, "w") as f:
                    f.write(text)
        if args.json:
            text = json.dumps(snap, indent=1, default=float)
            if args.json == "-":
                print(text)
            else:
                with open(args.json, "w") as f:
                    f.write(text)
        elif not (args.prometheus == "-"):
            if args.follow and n:
                print()
            print_snapshot(snap, qs, alerts=alert_states)
        n += 1
        if not args.follow or (args.iterations and n >= args.iterations):
            break
        try:
            time.sleep(max(args.refresh, 0.1))
        except KeyboardInterrupt:
            break
    return 0


if __name__ == "__main__":
    sys.exit(main())
