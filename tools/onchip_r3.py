#!/usr/bin/env python
"""One-shot on-chip measurement battery for round 3's new paths.

Run when the TPU tunnel is up:  python tools/onchip_r3.py
Writes results incrementally to tools/onchip_r3.json (so a mid-run
tunnel drop preserves what completed).
"""
import json
import pathlib
import subprocess
import sys

ROOT = pathlib.Path(__file__).resolve().parents[1]
OUT = ROOT / "tools" / "onchip_r3.json"


def record(key, value):
    data = json.loads(OUT.read_text()) if OUT.exists() else {}
    data[key] = value
    OUT.write_text(json.dumps(data, indent=1))
    print(f"[onchip] {key}: recorded", flush=True)


def run_child(code, timeout=1500):
    """Each measurement in its own process: a tunnel drop kills one
    measurement, not the battery."""
    r = subprocess.run([sys.executable, "-c", code], text=True,
                       capture_output=True, timeout=timeout, cwd=str(ROOT))
    line = next((ln for ln in reversed(r.stdout.splitlines())
                 if ln.startswith("{")), None)
    if r.returncode == 0 and line:
        return json.loads(line)
    return {"error": (r.stderr or r.stdout)[-800:]}


PRELUDE = """
import sys, json, time, statistics
sys.path.insert(0, %r)
import jax
import numpy as np
""" % str(ROOT)


def main():
    # 1. flat kernel shape sweep (lane-alignment question)
    code = PRELUDE + """
import tools.flat_kernel_bench as fkb
out = {}
for shape in fkb.SHAPES:
    try:
        out["x".join(map(str, shape))] = round(fkb.bench(*shape) / 1e9, 3)
    except Exception as e:
        out["x".join(map(str, shape))] = str(e)[-150:]
print(json.dumps(out))
"""
    record("flat_kernel_sweep_Bvox_per_s", run_child(code, 2400))

    # 2. GoL fused kernel (bench config)
    code = PRELUDE + """
import bench
print(json.dumps(bench.measure_gol()))
"""
    record("gol", run_child(code))

    # 3. refined advection through the current dispatch (boxed preferred)
    code = PRELUDE + """
import bench
print(json.dumps(bench.measure_refined()))
"""
    record("refined_dispatch", run_child(code))

    # 4. device-side PIC
    code = PRELUDE + """
import bench
print(json.dumps(bench.measure_pic()))
"""
    record("pic", run_child(code))

    # 5. flat Poisson (refined + uniform)
    code = PRELUDE + """
import bench
print(json.dumps(bench.measure_poisson()))
"""
    record("poisson", run_child(code))

    print("[onchip] battery complete:", OUT, flush=True)


if __name__ == "__main__":
    main()
