#!/usr/bin/env python
"""Incremental on-chip measurement battery (round 3).

Run when the TPU tunnel is up:  python tools/onchip_r3.py
Writes results incrementally to tools/onchip_r3.json; keys that already
hold a successful result are skipped, so re-running after a mid-battery
tunnel drop measures only what is still missing.  `--watch` polls the
tunnel (5 min period) and runs the battery each time it comes up, until
every key is recorded or the deadline passes.
"""
import json
import os
import pathlib
import subprocess
import sys
import time

ROOT = pathlib.Path(__file__).resolve().parents[1]
OUT = ROOT / "tools" / "onchip_r3.json"


def _load():
    return json.loads(OUT.read_text()) if OUT.exists() else {}


#: the one key whose value is a {shape: rate-or-error-string} map — its
#: completeness and cross-pass merging are per shape
SWEEP_KEY = "flat_kernel_sweep_Bvox_per_s"


def _ok(value, key=None):
    """A measurement is complete when it is not an error record: no
    "error" key, and — for the sweep, whose values are per-shape rates
    or error strings — no string-valued entries.  Regular measurements
    legitimately contain strings ("path", "device_kind", notes).

    A record whose own "platform" says "cpu" is NOT a measurement: it
    means jax silently initialized on the host after the tunnel dropped
    between the tunnel_up() probe and the child, and the number is a
    CPU rate that must not be persisted as on-chip evidence."""
    if value is None:
        return False
    if isinstance(value, dict):
        if "error" in value:
            return False
        if value.get("platform") == "cpu":
            return False
        if key == SWEEP_KEY:
            return all(not isinstance(v, str) for v in value.values())
    return True


def record(key, value):
    data = _load()
    prev = data.get(key)
    if key != SWEEP_KEY and isinstance(value, dict) and "error" not in value:
        # vintage stamp: bench.py's outage fallback promotes the headline
        # only when the measurement is fresh (same-round), so every
        # successful record carries its wall-clock time.  The sweep map
        # holds only per-shape rates — a string stamp there would trip
        # _ok's string check and the merge logic.
        value.setdefault(
            "measured_at",
            time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()))
    if _ok(prev, key) and not _ok(value, key) and key != SWEEP_KEY:
        # a failed/cpu-fallback child must not clobber persisted on-chip
        # evidence (e.g. a concurrent runner racing the watcher); the
        # sweep's per-shape merge above already preserves its shapes
        print(f"[onchip] {key}: keeping prior record "
              "(new result incomplete)", flush=True)
        return
    if (key == SWEEP_KEY and not _ok(value, key)
            and isinstance(prev, dict) and isinstance(value, dict)):
        # merge sweep passes: a shape measured on an earlier pass
        # survives a later pass's tunnel-drop error string
        merged = {k: v for k, v in prev.items() if not isinstance(v, str)}
        for k, v in value.items():
            if not isinstance(v, str) or k not in merged:
                merged[k] = v
        value = merged
    data[key] = value
    # atomic replace: bench.py's fallback path may read this file at any
    # moment (it is exactly the outage-time evidence), so a truncate+write
    # must never be observable; pid-unique temp name keeps concurrent
    # writers (watch daemon + an ad-hoc run) atomic per writer
    tmp = OUT.with_suffix(f".tmp.{os.getpid()}")
    tmp.write_text(json.dumps(data, indent=1))
    os.replace(tmp, OUT)
    state = "recorded" if _ok(value, key) else "INCOMPLETE"
    print(f"[onchip] {key}: {state}", flush=True)


def done(key):
    return _ok(_load().get(key), key)


def run_child(code, timeout=1500):
    """Each measurement in its own process: a tunnel drop kills one
    measurement, not the battery.  The prelude's atexit hook prints the
    child's phase breakdown as a ``TELEMETRY:`` line — attached to the
    result as ``obs_phases`` so BENCH rounds carry on-chip epoch/halo
    splits per battery key, not just the CPU probe's."""
    try:
        r = subprocess.run([sys.executable, "-c", code], text=True,
                           capture_output=True, timeout=timeout,
                           cwd=str(ROOT))
    except subprocess.TimeoutExpired:
        return {"error": f"timed out after {timeout}s"}
    line = next((ln for ln in reversed(r.stdout.splitlines())
                 if ln.startswith("{")), None)
    if r.returncode == 0 and line:
        out = json.loads(line)
        tel = next((ln for ln in reversed(r.stdout.splitlines())
                    if ln.startswith("TELEMETRY:")), None)
        if tel and isinstance(out, dict) and "error" not in out:
            try:
                out["obs_phases"] = json.loads(tel[len("TELEMETRY:"):])
            except json.JSONDecodeError:
                pass
        return out
    return {"error": (r.stderr or r.stdout)[-800:]}


#: child prelude: import path + a streaming exporter (a killed child
#: leaves its incremental phase evidence in tools/onchip_stream.jsonl)
#: and an atexit phase dump the parent folds into the recorded value
PRELUDE = """
import sys, json, time, statistics
sys.path.insert(0, %r)
import jax
import numpy as np
try:
    import atexit, os
    from dccrg_tpu import obs as _obs
    _obs.stream_to(%r, period=30.0, truncate=True,
                   extra={"source": "onchip_battery"})
    atexit.register(lambda: print(
        "TELEMETRY:" + json.dumps(_obs.metrics.report()["phases"]),
        flush=True))
    # per-child timeline export (origin_unix_s anchors the post-battery
    # fleet merge: tools/trace_report.py --fleet tools/onchip_trace_*.json)
    atexit.register(lambda: _obs.export_chrome_trace(
        %r + "onchip_trace_%%d.json" %% os.getpid()))
except Exception as _e:
    print("battery telemetry unavailable:", _e, file=sys.stderr)
""" % (str(ROOT), str(ROOT / "tools" / "onchip_stream.jsonl"),
       str(ROOT / "tools") + "/")

#: key -> (child code, timeout).  bench.measure_* are the single source
#: of truth for configurations; each runs alone in a child.
#:
#: ORDER MATTERS: the tunnel's up-windows have proven short (2026-08-01
#: it answered long enough for exactly one measurement before dropping
#: mid-`large`), so the quick, high-value measurements run first —
#: headline, then Poisson (the one workload below its CPU baseline on
#: chip, VERDICT-r4 weak #2), then the other per-workload numbers; the
#: long-running `large` streaming config and the sweep go last.
MEASUREMENTS = {
    "headline": ("import bench\nprint(json.dumps(bench.measure_tpu()))", 1500),
    "poisson": ("import bench\nprint(json.dumps(bench.measure_poisson()))",
                1500),
    # the rolled static-offset decomposition of the SAME general
    # operator (ops/rolled_gather.py) — the round-5 fix candidate for
    # the gather path's 0.13x showing
    "poisson_rolled": ("""
import bench
out = bench.measure_poisson(allow_flat=False, use_pallas=False,
                            include_uniform=False, allow_rolled=True)
out["device_kind"] = jax.devices()[0].device_kind
out["platform"] = jax.devices()[0].platform
print(json.dumps(out))
""", 1500),
    "gol": ("import bench\nprint(json.dumps(bench.measure_gol()))", 1500),
    "refined_dispatch": (
        "import bench\nprint(json.dumps(bench.measure_refined()))", 1500),
    # the boxed path pinned, so recalibration measures it directly
    # instead of inferring which path the dispatch ran
    "refined_boxed": (
        "import bench\n"
        "print(json.dumps(bench.measure_refined(force='boxed')))", 1500),
    # the 3-level config, both paths pinned + the dispatch's own choice
    "refined3_ml": (
        "import bench\n"
        "print(json.dumps(bench.measure_refined3(force='ml')))", 1500),
    "refined3_boxed": (
        "import bench\n"
        "print(json.dumps(bench.measure_refined3(force='boxed')))", 1500),
    "pic": ("import bench\nprint(json.dumps(bench.measure_pic()))", 1500),
    # the general gather-table path on the SAME refined config, for the
    # VERDICT-r3 attribution of its 0.13x showing (bench.measure_poisson
    # stays the single source of truth for the configuration)
    "poisson_gather": ("""
import bench
out = bench.measure_poisson(allow_flat=False, use_pallas=False,
                            include_uniform=False, allow_rolled=False)
out["device_kind"] = jax.devices()[0].device_kind
out["platform"] = jax.devices()[0].platform
print(json.dumps(out))
""", 1500),
    "poisson3": ("import bench\nprint(json.dumps(bench.measure_poisson3()))",
                 1500),
    "vlasov": ("import bench\nprint(json.dumps(bench.measure_vlasov()))",
               1500),
    # ISSUE 7: the Pallas async-DMA halo transport vs the collective
    # ring, oracle-verified on chip (the kernels CI only ever runs under
    # the interpreter), and the fused split-phase steps vs their eager
    # forms — the two measurements that turn the measured CPU overlap
    # fractions into accelerator evidence when the tunnel returns
    "halo_pallas_backend": ("""
import bench
out = bench.measure_halo_backends()
print(json.dumps(out))
""", 1500),
    "fused_split_steps": ("""
import bench
out = bench.measure_split_fused()
print(json.dumps(out))
""", 1500),
    # ISSUE 11: the deep-dispatch ensemble sweep on a real accelerator —
    # k steps per host dispatch amortizes a round-trip that is far more
    # expensive against a chip than against the virtual CPU mesh, and the
    # per-member HBM figures become real allocator headroom there
    "deep_dispatch": ("""
import bench
out = bench.measure_deep_dispatch()
print(json.dumps(out))
""", 1500),
    # ISSUE 14: exchange-amortized deep dispatch — the wide-halo g×k
    # sweep; the per-dispatch exchange this amortizes is an ICI
    # collective on a real mesh, so the wide/legacy ratio measured here
    # understates the on-chip margin
    "wide_halo": ("""
import bench
out = bench.measure_wide_halo()
print(json.dumps(out))
""", 1500),
    # ISSUE 17: cost-model-armed vs EMA-only deadline burst — on a real
    # accelerator the per-dispatch cost the model prices includes the
    # host round-trip and ICI exchanges, so informed depth selection
    # has more room to move the miss rate than on the CPU mesh
    "cost_model": ("""
import bench
out = bench.measure_cost_model()
print(json.dumps(out))
""", 1500),
    "large": ("import bench\nprint(json.dumps(bench.measure_large()))", 1500),
    "flat_kernel_sweep_Bvox_per_s": ("""
import tools.flat_kernel_bench as fkb
out = {}
for shape in fkb.SHAPES:
    try:
        out["x".join(map(str, shape))] = round(fkb.bench(*shape) / 1e9, 3)
    except Exception as e:
        out["x".join(map(str, shape))] = str(e)[-150:]
print(json.dumps(out))
""", 2400),
}


def tunnel_up(timeout=120):
    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import sys, jax; "
             "sys.exit(1 if jax.devices()[0].platform == 'cpu' else 0)"],
            timeout=timeout, capture_output=True, text=True)
        return r.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def battery():
    for key, (body, timeout) in MEASUREMENTS.items():
        if done(key):
            print(f"[onchip] {key}: already recorded, skipping", flush=True)
            continue
        value = run_child(PRELUDE + body, timeout)
        if key == SWEEP_KEY and isinstance(value, dict):
            # the sweep's value is a pure {shape: rate} map — a phase
            # table there would read as a shape to _ok and the merge
            value.pop("obs_phases", None)
        record(key, value)
        if not done(key) and not tunnel_up():
            print("[onchip] tunnel dropped; stopping this pass", flush=True)
            return False
    return all(done(k) for k in MEASUREMENTS)


def _recalibrate():
    """Run tools/recalibrate.py --write so a completed battery turns
    into dispatch constants without operator attention (the tunnel may
    drop again before anyone looks).  Never raises: a recalibration
    failure must not take down a watcher whose battery just landed."""
    try:
        r = subprocess.run(
            [sys.executable, str(ROOT / "tools" / "recalibrate.py"),
             "--write"],
            text=True, capture_output=True, timeout=120,
        )
        msg = (r.stdout + r.stderr).strip()[-400:]
    except (subprocess.TimeoutExpired, OSError) as e:
        msg = f"FAILED: {e}"
    print("[onchip] recalibrate:", msg, flush=True)


def _complete(auto_recal: bool):
    """The single battery-completion sequence for every exit site."""
    print("[onchip] battery complete:", OUT, flush=True)
    if auto_recal:
        _recalibrate()


def main():
    auto_recal = "--then-recalibrate" in sys.argv
    if "--watch" in sys.argv:
        i = sys.argv.index("--watch") + 1
        hours = 8.0
        if i < len(sys.argv):
            try:
                hours = float(sys.argv[i])
            except ValueError:
                pass
        deadline = time.time() + hours * 3600
        while time.time() < deadline:
            if all(done(k) for k in MEASUREMENTS):
                _complete(auto_recal)
                return
            if tunnel_up():
                print("[onchip] tunnel up; running battery", flush=True)
                if battery():
                    _complete(auto_recal)
                    return
                if auto_recal:
                    # partial pass: recalibrate from whatever landed —
                    # recalibrate.py refuses to write when the needed
                    # keys (refined_boxed + sweep) are missing, so this
                    # is safe to attempt after every window
                    _recalibrate()
            else:
                print("[onchip] tunnel down; sleeping", flush=True)
            time.sleep(300)
        print("[onchip] watch deadline reached", flush=True)
        return
    if battery():
        _complete(auto_recal)
    elif auto_recal:
        _recalibrate()


if __name__ == "__main__":
    main()
