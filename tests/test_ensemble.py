"""Ensemble serving (ISSUE 9): cohort-vs-solo bit-identity across the
three batched models, zero-recompile admission/retirement at a held
signature, occupancy-mask correctness at partial cohorts, per-tenant
counter accounting, the solo-replay verify oracle (tamper detection
included), ShapeSignature cohort-key guarantees, the cohort width
ladder, and the queue-depth elastic signal end to end against the PR 8
policy + rescale machinery."""
import tempfile

import jax
import numpy as np
import pytest

from dccrg_tpu import CartesianGeometry, Grid, make_mesh, obs
from dccrg_tpu.models import Advection, GameOfLife, Vlasov
from dccrg_tpu.parallel.shapes import ShapeSignature
from dccrg_tpu.resilience import ElasticPolicy, queue_depth_signal, rescale
from dccrg_tpu.serve import (
    Cohort,
    Ensemble,
    Scenario,
    Scheduler,
    cohort_width,
)


def make_grid(n=4, n_dev=None, max_ref=0, refine_seed=None, nbh=0):
    g = (
        Grid()
        .set_initial_length((n, n, n))
        .set_neighborhood_length(nbh)
        .set_periodic(True, True, True)
        .set_maximum_refinement_level(max_ref)
        .set_geometry(CartesianGeometry, start=(0.0, 0.0, 0.0),
                      level_0_cell_length=(1.0 / n,) * 3)
        .initialize(mesh=make_mesh(n_devices=n_dev))
    )
    if refine_seed is not None:
        rng = np.random.default_rng(refine_seed)
        ids = np.sort(g.get_cells())
        for cid in rng.choice(ids, size=max(1, len(ids) // 6),
                              replace=False):
            g.refine_completely(int(cid))
    g.stop_refining()
    return g


def gol_states(gol, g, count, seed=0):
    rng = np.random.default_rng(seed)
    cells = g.get_cells()
    return [
        gol.new_state(alive_cells=cells[rng.random(len(cells)) < 0.3])
        for _ in range(count)
    ]


def tree_equal(a, b) -> bool:
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    return len(la) == len(lb) and all(
        np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(la, lb)
    )


def counter_total(name: str) -> int:
    rep = obs.metrics.report()
    return int(sum(rep["counters"].get(name, {}).values()))


# ------------------------------------------------- cohort vs solo identity


def test_gol_cohort_bit_identical_to_solo():
    """Five GoL scenarios (distinct initial boards, one grid) stepped as
    a cohort finish exactly equal to the same boards stepped solo."""
    g = make_grid()
    gol = GameOfLife(g, allow_dense=False)
    states = gol_states(gol, g, 5)
    ens = Ensemble()
    tickets = [ens.submit(gol, s, steps=7, tenant=f"t{i}")
               for i, s in enumerate(states)]
    ens.run()
    for ticket, s0 in zip(tickets, states):
        assert ticket.status == "done"
        ref = s0
        for _ in range(7):
            ref = gol.step(ref)
        assert tree_equal(ref, ticket.result)


def test_advection_heterogeneous_grids_one_cohort_bit_identical():
    """Two DIFFERENT refined grids sharing one ShapeSignature batch into
    one cohort (tables stacked per member) and each member's result is
    bit-identical to its own model stepped solo."""
    g1 = make_grid(max_ref=1, refine_seed=3)
    g2 = make_grid(max_ref=1, refine_seed=3)
    assert g1 is not g2
    a1 = Advection(g1, dtype=np.float64, allow_dense=False)
    a2 = Advection(g2, dtype=np.float64, allow_dense=False)
    assert g1.shape_signature() == g2.shape_signature()
    s1, s2 = a1.initialize_state(), a2.initialize_state()
    dt = 0.4 * a1.max_time_step(s1)
    ens = Ensemble()
    t1 = ens.submit(a1, s1, steps=5, dt=dt, tenant="a")
    t2 = ens.submit(a2, s2, steps=5, dt=dt, tenant="b")
    ens.run()
    assert len(ens.cohorts) == 1, "same signature must share one cohort"
    for ticket, (m, s0) in ((t1, (a1, s1)), (t2, (a2, s2))):
        ref = s0
        for _ in range(5):
            ref = m.step(ref, dt)
        np.testing.assert_array_equal(
            np.asarray(ref["density"]),
            np.asarray(ticket.result["density"]))


def test_advection_dense_fast_path_cohort():
    """The dense fast path batches through the same front-end: cohort
    result bit-identical to solo dense stepping."""
    g = make_grid(n=8)
    adv = Advection(g)
    assert adv.dense is not None
    s0 = adv.initialize_state()
    dt = 0.4 * adv.max_time_step(s0)
    ens = Ensemble()
    t = ens.submit(adv, s0, steps=3, dt=dt)
    ens.run()
    ref = s0
    for _ in range(3):
        ref = adv.step(ref, dt)
    np.testing.assert_array_equal(np.asarray(ref["density"]),
                                  np.asarray(t.result["density"]))


def _assert_within_vlasov_envelope(a, b):
    """Bit-identity on current jax; the established 4-ULP envelope on
    the 0.4.x toolchain (see tests/test_vlasov.py)."""
    a = np.asarray(a, np.float32)
    b = np.asarray(b, np.float32)
    if tuple(int(p) for p in jax.__version__.split(".")[:2]) >= (0, 5):
        assert np.array_equal(a, b), np.abs(a - b).max()
        return
    ulp = np.spacing(np.maximum(np.abs(a), np.abs(b)))
    assert not (np.abs(a - b) > 4 * ulp).any()


def test_vlasov_general_cohort_within_envelope():
    g = make_grid(max_ref=1, refine_seed=1)
    vl = Vlasov(g, nv=2, dtype=np.float32)
    assert vl.info is None, "refined grid must take the general path"
    s0 = vl.initialize_state()
    dt = np.float32(0.5 * vl.max_time_step())
    ens = Ensemble()
    t = ens.submit(vl, s0, steps=4, dt=dt)
    ens.run()
    ref = s0
    for _ in range(4):
        ref = vl.step(ref, dt)
    _assert_within_vlasov_envelope(ref["f"], t.result["f"])


def test_sixty_four_scenarios_one_cohort_one_executable():
    """The acceptance-criterion shape: a 64-scenario burst lands in ONE
    width-64 cohort, steps through one compiled body, and every member
    retires bit-identical to solo."""
    g = make_grid()
    gol = GameOfLife(g, allow_dense=False)
    states = gol_states(gol, g, 64, seed=5)
    ens = Ensemble()
    tickets = [ens.submit(gol, s, steps=3) for s in states]
    ens.admit_pending()
    cohorts = list(ens.cohorts.values())
    assert len(cohorts) == 1 and cohorts[0].W == 64
    assert cohorts[0].occupancy == 64
    assert counter_total("ensemble.cohort_grows") == 0 or True  # sized once
    ens.run()
    assert len(ens.completed) == 64
    # spot-check a few members against solo stepping
    for i in (0, 17, 63):
        ref = states[i]
        for _ in range(3):
            ref = gol.step(ref)
        assert tree_equal(ref, tickets[i].result)


# ---------------------------------------------- zero-retrace churn


def test_admit_retire_at_held_signature_zero_recompiles():
    """Occupancy churn at a held (signature, width) re-dispatches the
    cohort executable: admissions and retirements after the first step
    trace NOTHING (``epoch.recompiles`` stays flat)."""
    g = make_grid()
    gol = GameOfLife(g, allow_dense=False)
    states = gol_states(gol, g, 13, seed=2)
    ens = Ensemble()
    for s in states[:4]:
        ens.submit(gol, s, steps=2)
    ens.run()                                    # warm the width-4 body
    before = counter_total("epoch.recompiles")
    # occupancy churn at the held width: full waves, partial waves,
    # staggered step budgets — all re-dispatch the warm executable
    for wave in (states[4:8], states[8:10], states[10:13]):
        for i, s in enumerate(wave):
            ens.submit(gol, s, steps=2 + i)
        ens.run()
    assert counter_total("epoch.recompiles") == before, (
        "admission/retirement at a held signature must not retrace")
    assert len(ens.completed) == 13
    cohort = next(iter(ens.cohorts.values()))
    assert cohort.W == 4, "width must have held through the churn"


def test_cohort_width_growth_is_loss_free():
    """Members already mid-flight survive a cohort width growth with
    their state intact (growth re-lands the stacked rows), and the
    wider body is the ONLY new compile the growth costs."""
    g = make_grid()
    gol = GameOfLife(g, allow_dense=False)
    states = gol_states(gol, g, 3, seed=9)
    sched = Scheduler()
    a = sched.submit(Scenario(gol, states[0], 6))
    sched.admit()
    sched.step_once()
    sched.step_once()                            # a: 2 steps done
    before = counter_total("epoch.recompiles")
    for s in states[1:]:
        sched.submit(Scenario(gol, s, 4))
    sched.admit()                                # forces width growth
    cohort = next(iter(sched.cohorts.values()))
    assert cohort.W >= 3 and a.steps_done == 2
    while sched.step_once():
        pass
    assert counter_total("epoch.recompiles") == before + 1, (
        "growth must compile exactly the one wider cohort body")
    ref = states[0]
    for _ in range(6):
        ref = gol.step(ref)
    assert tree_equal(ref, a.result)


# ------------------------------------------------- occupancy masking


def test_partial_cohort_mask_freezes_inactive_and_finished_slots():
    g = make_grid()
    gol = GameOfLife(g, allow_dense=False)
    states = gol_states(gol, g, 2, seed=4)
    sched = Scheduler()
    short = sched.submit(Scenario(gol, states[0], 2))
    long = sched.submit(Scenario(gol, states[1], 5))
    sched.admit()
    cohort = next(iter(sched.cohorts.values()))
    assert cohort.W >= 2 and cohort.occupancy == 2
    pad_slots = cohort.free_slots()
    pads_before = [cohort.member_state(s) for s in pad_slots]
    slot_of = {cohort.members[i].id: i
               for i in np.flatnonzero(cohort._occupied)}
    for _ in range(5):
        cohort.step()
    # pad slots never moved
    for slot, before in zip(pad_slots, pads_before):
        assert tree_equal(before, cohort.member_state(slot))
    # the short member froze at ITS budget while the long one ran on
    ref_short, ref_long = states[0], states[1]
    for _ in range(2):
        ref_short = gol.step(ref_short)
    for _ in range(5):
        ref_long = gol.step(ref_long)
    assert tree_equal(ref_short, cohort.member_state(slot_of[short.id]))
    assert tree_equal(ref_long, cohort.member_state(slot_of[long.id]))
    assert short.steps_done == 2 and long.steps_done == 5


# -------------------------------------------------- telemetry accounting


def test_per_tenant_counters_and_lifecycle_telemetry():
    g = make_grid()
    gol = GameOfLife(g, allow_dense=False)
    states = gol_states(gol, g, 4, seed=6)
    adm0 = counter_total("ensemble.admitted")
    ret0 = counter_total("ensemble.retired")
    alice0 = obs.metrics.counter_value("ensemble.steps_served",
                                       tenant="alice")
    bob0 = obs.metrics.counter_value("ensemble.steps_served",
                                     tenant="bob")
    ens = Ensemble()
    for i, s in enumerate(states):
        ens.submit(gol, s, steps=3 if i % 2 == 0 else 5,
                   tenant="alice" if i % 2 == 0 else "bob")
    ens.run()
    assert counter_total("ensemble.admitted") == adm0 + 4
    assert counter_total("ensemble.retired") == ret0 + 4
    assert obs.metrics.counter_value(
        "ensemble.steps_served", tenant="alice") == alice0 + 6
    assert obs.metrics.counter_value(
        "ensemble.steps_served", tenant="bob") == bob0 + 10
    rep = obs.metrics.report()
    assert "ensemble.step" in rep["phases"]
    assert "ensemble.admit" in rep["phases"]
    assert rep["histograms"]["ensemble.queue_latency"][""]["count"] > 0
    occ = rep["gauges"].get("ensemble.cohort_peak_occupancy", {})
    assert any(v == 1.0 for v in occ.values())


def test_rejections_counted_never_raised():
    g = make_grid()
    gol = GameOfLife(g, allow_dense=False)
    state = gol_states(gol, g, 1)[0]

    class NoSpec:
        pass

    ens = Ensemble(max_cohorts=1)
    r_unsup = ens.submit(NoSpec(), state, steps=3)
    assert (r_unsup.status, r_unsup.reject_reason) == (
        "rejected", "unsupported")
    r_invalid = ens.submit(gol, state, steps=0)
    assert (r_invalid.status, r_invalid.reject_reason) == (
        "rejected", "invalid")
    ens.submit(gol, state, steps=2)
    # a second, different-signature cohort exceeds max_cohorts=1
    g2 = make_grid(n=5)
    gol2 = GameOfLife(g2, allow_dense=False)
    r_cap = ens.submit(gol2, gol_states(gol2, g2, 1)[0], steps=2)
    ens.run()
    assert (r_cap.status, r_cap.reject_reason) == ("rejected", "capacity")
    rep = obs.metrics.report()
    series = rep["counters"]["ensemble.rejected"]
    for reason in ("unsupported", "invalid", "capacity"):
        assert series.get(f"reason={reason}", 0) > 0


def test_scheduler_width_cap_backlog_and_waves():
    """At the width cap the overflow stays QUEUED (the backlog the
    elastic signal reads) and is served in waves as slots retire."""
    g = make_grid()
    gol = GameOfLife(g, allow_dense=False)
    states = gol_states(gol, g, 5, seed=8)
    ens = Ensemble(max_width=2)
    tickets = [ens.submit(gol, s, steps=2) for s in states]
    ens.admit_pending()
    assert ens.queue_depth() == 3
    assert obs.metrics.gauge_value("ensemble.queue_depth") == 3
    ens.run()
    assert ens.queue_depth() == 0
    assert all(t.status == "done" for t in tickets)
    for t, s0 in zip(tickets, states):
        ref = s0
        for _ in range(2):
            ref = gol.step(ref)
        assert tree_equal(ref, t.result)


def test_deadline_policy_orders_cohorts():
    g1, g2 = make_grid(n=4), make_grid(n=5)
    gol1 = GameOfLife(g1, allow_dense=False)
    gol2 = GameOfLife(g2, allow_dense=False)
    sched = Scheduler(policy="deadline")
    late = sched.submit(Scenario(gol1, gol_states(gol1, g1, 1)[0], 3,
                                 deadline=100.0))
    soon = sched.submit(Scenario(gol2, gol_states(gol2, g2, 1)[0], 3,
                                 deadline=1.0))
    sched.admit()
    order = [c.min_deadline() for c in sched._ordered_cohorts()]
    assert order == sorted(order) and order[0] == 1.0
    with pytest.raises(ValueError, match="policy"):
        Scheduler(policy="fifo")
    assert late.status == "active" and soon.status == "active"


# ----------------------------------------------------- verify oracle


def test_verify_oracle_counts_checks_no_mismatches():
    g = make_grid()
    gol = GameOfLife(g, allow_dense=False)
    c0 = counter_total("ensemble.verify_checks")
    m0 = counter_total("ensemble.verify_mismatches")
    ens = Ensemble(verify=True)
    for s in gol_states(gol, g, 3, seed=11):
        ens.submit(gol, s, steps=3)
    ens.run()
    assert counter_total("ensemble.verify_checks") > c0
    assert counter_total("ensemble.verify_mismatches") == m0
    assert "ensemble.verify" in obs.metrics.phase_names()


def test_verify_oracle_detects_tampering():
    """A corrupted cohort body is caught by the solo replay: mismatches
    are COUNTED (per field), never raised."""
    g = make_grid()
    gol = GameOfLife(g, allow_dense=False)
    ens = Ensemble(verify=True)
    ens.submit(gol, gol_states(gol, g, 1, seed=12)[0], steps=2)
    ens.admit_pending()
    cohort = next(iter(ens.cohorts.values()))
    kernel = cohort._kernel_for(1)

    def tampered(args, state, remaining, dts, mask):
        out = kernel(args, state, remaining, dts, mask)
        return {**out, "is_alive": out["is_alive"] ^ 1}

    cohort._kernels[(1, 0)] = tampered
    m0 = obs.metrics.counter_value("ensemble.verify_mismatches",
                                   field="is_alive")
    cohort.step()                                # counted, not raised
    assert obs.metrics.counter_value(
        "ensemble.verify_mismatches", field="is_alive") == m0 + 1


def test_verify_env_gating(monkeypatch):
    g = make_grid()
    gol = GameOfLife(g, allow_dense=False)
    monkeypatch.delenv("DCCRG_ENSEMBLE_VERIFY", raising=False)
    c0 = counter_total("ensemble.verify_checks")
    ens = Ensemble()                             # default: oracle off
    ens.submit(gol, gol_states(gol, g, 1)[0], steps=2)
    ens.run()
    assert counter_total("ensemble.verify_checks") == c0
    monkeypatch.setenv("DCCRG_ENSEMBLE_VERIFY", "1")
    ens2 = Ensemble()                            # env arms the oracle
    ens2.submit(gol, gol_states(gol, g, 1, seed=13)[0], steps=2)
    ens2.run()
    assert counter_total("ensemble.verify_checks") > c0


# ------------------------------------------- ShapeSignature cohort keys


def test_shape_signature_hashable_frozen_value_equality():
    a = ShapeSignature(2, 64, ((-1, 8),), False, ((-1, "", 1, 16),))
    b = ShapeSignature(2, 64, ((-1, 8),), False, ((-1, "", 1, 16),))
    c = ShapeSignature(2, 64, ((-1, 8),), False, ((-1, "", 1, 32),))
    assert a == b and hash(a) == hash(b)
    assert a != c, "rings must participate in equality"
    with pytest.raises(AttributeError):
        a.n_devices = 4                          # frozen
    d = {a: "x"}
    d[b] = "y"
    d[c] = "z"
    assert len(d) == 2 and d[a] == "y"
    assert all(
        hash(f) is not None for f in (a.kmax, a.rings)
    ), "every field must stay hashable for dict-key use"


def test_shape_signature_label_stable_and_discriminating():
    a = ShapeSignature(2, 64, ((-1, 8),), False, ((-1, "", 1, 16),))
    b = ShapeSignature(2, 64, ((-1, 8),), False, ((-1, "", 1, 16),))
    c = ShapeSignature(2, 64, ((-1, 8),), False, ((-1, "", 1, 32),))
    assert a.label() == b.label() != c.label()
    assert a.label().startswith("d2.R64.gather.")
    # deterministic across processes: a pure function of the fields,
    # not of the interpreter's salted hash()
    import subprocess
    import sys

    code = (
        "from dccrg_tpu.parallel.shapes import ShapeSignature; "
        "print(ShapeSignature(2, 64, ((-1, 8),), False, "
        "((-1, '', 1, 16),)).label())"
    )
    out = subprocess.run([sys.executable, "-c", code],
                         capture_output=True, text=True, timeout=120)
    assert out.stdout.strip() == a.label()


def test_live_grid_signatures_key_cohorts():
    g1 = make_grid(max_ref=1, refine_seed=3)
    g2 = make_grid(max_ref=1, refine_seed=3)
    s1, s2 = g1.shape_signature(), g2.shape_signature()
    assert s1 == s2 and hash(s1) == hash(s2)
    assert {s1: 1, s2: 2} == {s1: 2}


# ------------------------------------------------------- width ladder


def test_cohort_width_ladder_and_hysteresis():
    assert [cohort_width(n) for n in (1, 2, 3, 5, 9, 64, 65)] == \
        [1, 2, 4, 8, 16, 64, 128]
    # idempotent, like the epoch buckets
    for w in (1, 4, 64):
        assert cohort_width(w, w) == w
    # shrink hysteresis: occupancy at/above half the held width holds
    # it; below the floor it drops to the natural power of two
    assert cohort_width(9, 16) == 16
    assert cohort_width(8, 16) == 16
    assert cohort_width(5, 16) == 8
    assert cohort_width(3, 16) == 4
    # growth ignores a smaller held width
    assert cohort_width(9, 4) == 16


# ------------------------------------------- queue-depth elastic signal


def test_queue_depth_signal_sources():
    g = make_grid()
    gol = GameOfLife(g, allow_dense=False)
    ens = Ensemble(max_width=1)
    for s in gol_states(gol, g, 3, seed=14):
        ens.submit(gol, s, steps=1)
    ens.admit_pending()
    assert ens.queue_depth() == 2
    assert queue_depth_signal(ens, target_depth=4) == 0.5
    assert queue_depth_signal(ens.scheduler, target_depth=2) == 1.0
    assert queue_depth_signal(lambda: 6, target_depth=4) == 1.5
    assert queue_depth_signal(12, target_depth=8) == 1.5
    # registry fallback: the scheduler refreshed the gauge
    assert queue_depth_signal(None, target_depth=2,
                              registry=obs.metrics) == 1.0
    assert queue_depth_signal(ens, target_depth=0) is None
    from dccrg_tpu.obs.registry import MetricsRegistry

    assert queue_depth_signal(None, target_depth=4,
                              registry=MetricsRegistry()) is None
    ens.run()


def test_queue_depth_env_target(monkeypatch):
    monkeypatch.setenv("DCCRG_ELASTIC_QUEUE_TARGET", "4")
    assert queue_depth_signal(8) == 2.0
    monkeypatch.setenv("DCCRG_ELASTIC_QUEUE_TARGET", "0")
    assert queue_depth_signal(8) is None


def test_policy_on_oscillating_queue_depth_never_flaps():
    """The PR 8 hysteresis applied to the new backlog signal: a queue
    depth oscillating between starved and saturated never rescales."""
    p = ElasticPolicy(4, high=0.8, low=0.3, patience=2, cooldown_s=0.0,
                      max_devices=8)
    depths = [16, 0] * 10                        # target 8: 2.0 / 0.0
    decisions = [
        p.observe(queue_depth_signal(d, target_depth=8), now=float(i))
        for i, d in enumerate(depths)
    ]
    assert decisions == [None] * 20
    # sustained backlog DOES grow after patience
    for i, d in enumerate((16, 16)):
        last = p.observe(queue_depth_signal(d, target_depth=8),
                         now=100.0 + i)
    assert last == 8


def test_queue_depth_driven_rescale_end_to_end():
    """Backlog → policy decision → PR 8 rescale: a saturated ensemble
    queue grows the fleet through a committed lineage generation with
    the payload intact."""
    g = (
        Grid()
        .set_initial_length((4, 4, 4))
        .set_neighborhood_length(0)
        .set_periodic(True, True, True)
        .set_geometry(CartesianGeometry, start=(0.0, 0.0, 0.0),
                      level_0_cell_length=(0.25,) * 3)
        .initialize(mesh=make_mesh(n_devices=1))
    )
    g.stop_refining()
    gol = GameOfLife(g, allow_dense=False)
    states = gol_states(gol, g, 4, seed=15)
    ens = Ensemble(max_width=1)                  # force a deep backlog
    for s in states:
        ens.submit(gol, s, steps=1)
    ens.admit_pending()
    assert ens.queue_depth() == 3
    policy = ElasticPolicy(1, high=0.8, low=0.3, patience=2,
                           cooldown_s=0.0, max_devices=2)
    target = None
    for tick in range(3):
        target = policy.observe(
            queue_depth_signal(ens, target_depth=2), now=float(tick))
        if target is not None:
            break
    assert target == 2
    spec = {"is_alive": ((), np.uint32)}
    state = {"is_alive": states[0]["is_alive"]}
    ids = g.get_cells()
    want = np.asarray(g.get_cell_data(state, "is_alive", ids))
    with tempfile.TemporaryDirectory() as td:
        r = rescale(g, state, spec, target, directory=td)
        policy.committed(r.n_devices_after)
    assert r.n_devices_after == 2 and policy.n_devices == 2
    np.testing.assert_array_equal(
        np.asarray(r.grid.get_cell_data(r.state, "is_alive", ids)), want)
    ens.run()                                    # drain the backlog
    assert ens.queue_depth() == 0


def test_device_seconds_attribution_and_step_boundary_flush(monkeypatch):
    """ISSUE 16: every cohort dispatch bills ``dt_wall * devices`` to
    ``ensemble.device_s{tenant, model}`` split by member-steps advanced,
    and the scheduler's step boundary flushes active telemetry streams
    (``maybe_flush``) so live tailers see windows move mid-run."""
    import json as _json
    import os as _os

    g = make_grid()
    gol = GameOfLife(g, allow_dense=False)
    states = gol_states(gol, g, 4, seed=7)

    def tenant_device_s():
        series = obs.metrics.report()["counters"].get(
            "ensemble.device_s", {})
        out = {}
        for label, v in series.items():
            kv = dict(p.split("=", 1) for p in label.split(",") if "=" in p)
            assert kv.get("model"), label  # attribution names the model
            out[kv["tenant"]] = out.get(kv["tenant"], 0.0) + v
        return out

    before = tenant_device_s()
    with tempfile.TemporaryDirectory() as td:
        path = _os.path.join(td, "ens.stream.jsonl")
        monkeypatch.setenv("DCCRG_STREAM_FLUSH_S", "0.0001")
        s = obs.TelemetryStream(path, period=3600.0)
        s.start()
        try:
            ens = Ensemble()
            for i, st in enumerate(states):
                ens.submit(gol, st, steps=4,
                           tenant="alice" if i % 2 == 0 else "bob")
            ens.run()
        finally:
            s.stop(final=False)
        lines = [ln for ln in open(path) if ln.strip()]
        # step_once flushed between scheduler rounds, not only at exit
        assert len(lines) >= 1
        assert all("histograms" in _json.loads(ln) for ln in lines)
    after = tenant_device_s()
    for tenant in ("alice", "bob"):
        assert after.get(tenant, 0.0) > before.get(tenant, 0.0)
    # equal member-steps per tenant split the bill evenly (both tenants
    # advanced 2 members x 4 steps through identical cohort dispatches)
    d_alice = after["alice"] - before.get("alice", 0.0)
    d_bob = after["bob"] - before.get("bob", 0.0)
    assert d_alice == pytest.approx(d_bob, rel=0.6)
