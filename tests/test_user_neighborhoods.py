"""User neighborhoods, additional item hooks, invariant checker, timers
(reference analogues: tests/user_neighborhood, tests/additional_cell_data,
the DEBUG verification layer)."""
import numpy as np
import pytest

from dccrg_tpu import CartesianGeometry, Grid, make_mesh
from dccrg_tpu.parallel.stencil import StencilTables
from dccrg_tpu.utils import timers, verify_grid, verify_user_data


def make_grid(hood=1, length=(6, 6, 1), max_ref=0):
    n = np.asarray(length)
    return (
        Grid()
        .set_initial_length(length)
        .set_maximum_refinement_level(max_ref)
        .set_neighborhood_length(hood)
        .set_periodic(True, True, False)
        .set_geometry(
            CartesianGeometry, start=(0.0, 0.0, 0.0), level_0_cell_length=tuple(1.0 / n)
        )
        .initialize(mesh=make_mesh())
    )


def test_add_remove_neighborhood():
    g = make_grid(hood=1)
    # face-only sub-neighborhood inside the full 26-cube default
    faces = [(0, 0, -1), (0, -1, 0), (-1, 0, 0), (1, 0, 0), (0, 1, 0), (0, 0, 1)]
    # z offsets leave the 6x6x1 non-periodic z grid -> keep xy faces
    assert g.add_neighborhood(7, [(1, 0, 0), (-1, 0, 0), (0, 1, 0), (0, -1, 0)])
    assert 7 in g.epoch.hoods
    ids, offs = g.get_neighbors_of(8, hood_id=7)
    assert len(ids) == 4
    # user hood must be inside the default
    assert not g.add_neighborhood(8, [(2, 0, 0)])
    # duplicate id rejected
    assert not g.add_neighborhood(7, [(1, 0, 0)])
    # smaller hood -> fewer cells exchanged
    assert g.epoch.hoods[7].pair_counts.sum() < g.epoch.hoods[None].pair_counts.sum()
    assert g.remove_neighborhood(7)
    assert 7 not in g.epoch.hoods
    assert not g.remove_neighborhood(7)


def test_user_hood_exchange_and_states_stay_valid():
    g = make_grid(hood=1)
    state = g.new_state({"v": ((), np.float64)})
    cells = g.get_cells()
    state = g.set_cell_data(state, "v", cells, cells.astype(np.float64))
    g.add_neighborhood(3, [(1, 0, 0), (-1, 0, 0)])
    # the pre-existing state still matches the layout and exchanges fine
    state = g.update_copies_of_remote_neighbors(state, hood_id=3)
    verify_grid(g)


def test_cell_and_neighbor_item_hooks():
    g = make_grid(hood=0)
    tables = StencilTables(
        g,
        cell_items={
            "center": lambda grid, ids: grid.geometry.get_center(ids),
            "is_edge": lambda grid, ids: (
                grid.mapping.get_indices(ids)[:, 0] == 0
            ),
        },
        neighbor_items={
            "nbr_is_local": lambda grid, src, nbr, off: (
                grid.get_owner(nbr) == grid.get_owner(src)
            ),
            "offset_norm": lambda grid, src, nbr, off: np.abs(off).sum(axis=1),
        },
    )
    D, R = g.n_devices, g.epoch.R
    assert np.asarray(tables.center).shape == (D, R, 3)
    assert np.asarray(tables.nbr_is_local).shape == np.asarray(tables.nbr_rows).shape
    # spot check: cell 1's center
    pos = int(g.leaves.position(np.uint64(1)))
    d, r = g.leaves.owner[pos], g.epoch.row_of[pos]
    np.testing.assert_allclose(
        np.asarray(tables.center)[d, r], g.geometry.get_center(np.uint64(1))
    )
    # offsets of face neighbors are one cell apart
    valid = np.asarray(tables.nbr_valid)
    norms = np.asarray(tables.offset_norm)
    assert (norms[valid] == 1).all()


def test_verify_grid_passes_and_catches_corruption():
    g = make_grid(hood=1, max_ref=1)
    g.refine_completely(8)
    g.stop_refining()
    verify_grid(g)
    # corrupt the directory -> must be caught
    g.leaves.owner[0] = 99
    with pytest.raises(AssertionError):
        verify_grid(g)


def test_verify_user_data():
    g = make_grid(hood=1)
    spec = {"v": ((), np.float64)}
    state = g.new_state(spec)
    cells = g.get_cells()
    state = g.set_cell_data(state, "v", cells, np.arange(len(cells), dtype=np.float64))
    verify_user_data(g, state, spec)


def test_timers_record_phases():
    timers.reset()
    make_grid()
    rep = timers.report()
    # the epoch rebuild phase, recorded via the obs registry the timers
    # shim now views (renamed from the pre-obs "grid.rebuild_epoch")
    assert "epoch.build" in rep
    assert rep["epoch.build"]["count"] >= 1
    assert rep["epoch.build"]["total_s"] > 0
