"""Split-phase halo (communication/computation overlap) tests — the
reference's defining scaling pattern (dccrg.hpp:5010-5367; canonical use
examples/game_of_life.cpp:124-138): start the ghost transfer, compute
inner cells while it is in flight, wait, compute outer cells."""
import numpy as np
import pytest

from dccrg_tpu import CartesianGeometry, Grid, make_mesh
from dccrg_tpu.models import GameOfLife


def make_grid(length=(10, 10, 1), n_dev=8, method="RCB", max_ref=0):
    g = (
        Grid()
        .set_initial_length(length)
        .set_maximum_refinement_level(max_ref)
        .set_neighborhood_length(1)
        .set_load_balancing_method(method)
        .initialize(mesh=make_mesh(n_devices=n_dev))
    )
    return g


GLIDER = [35, 36, 37, 27, 16]


def test_split_phase_api_matches_blocking_exchange():
    """start + wait(handle) must leave ghost rows exactly as the blocking
    refresh does."""
    g = make_grid()
    state = g.new_state({"v": ((), np.float64)})
    cells = g.get_cells()
    state = g.set_cell_data(state, "v", cells, np.sin(cells.astype(np.float64)))
    blocking = g.update_copies_of_remote_neighbors(state)
    handle = g.start_remote_neighbor_copy_updates(state)
    merged = g.wait_remote_neighbor_copy_updates(state, handle)
    np.testing.assert_array_equal(
        np.asarray(blocking["v"]), np.asarray(merged["v"])
    )


def test_inner_compute_unaffected_by_transfer():
    """Inner cells (no remote neighbors) gather only local rows, so their
    results computed BEFORE the merge equal the blocking step's."""
    g = make_grid()
    gol_b = GameOfLife(g)
    gol_o = GameOfLife(g, overlap=True)
    state = gol_b.new_state(alive_cells=GLIDER)
    sb = gol_b.step(state)
    so = gol_o.step(state)
    hood = g.epoch.hoods[None]
    inner = np.asarray(hood.inner_mask)
    np.testing.assert_array_equal(
        np.asarray(sb["is_alive"])[inner], np.asarray(so["is_alive"])[inner]
    )
    np.testing.assert_array_equal(
        np.asarray(sb["live_neighbor_count"])[inner],
        np.asarray(so["live_neighbor_count"])[inner],
    )


@pytest.mark.parametrize("n_dev", [1, 8])
def test_overlap_step_identical_physics(n_dev):
    g = make_grid(n_dev=n_dev)
    gol_b = GameOfLife(g)
    gol_o = GameOfLife(g, overlap=True)
    sb = gol_b.new_state(alive_cells=GLIDER)
    so = gol_o.new_state(alive_cells=GLIDER)
    for _ in range(8):
        sb = gol_b.step(sb)
        so = gol_o.step(so)
        assert set(gol_b.alive_cells(sb).tolist()) == set(
            gol_o.alive_cells(so).tolist()
        )
        # all local rows identical, counts included
        local = np.asarray(g.epoch.local_mask)
        np.testing.assert_array_equal(
            np.asarray(sb["is_alive"])[local],
            np.asarray(so["is_alive"])[local],
        )
        np.testing.assert_array_equal(
            np.asarray(sb["live_neighbor_count"])[local],
            np.asarray(so["live_neighbor_count"])[local],
        )


def test_overlap_on_refined_grid():
    """Inner/outer split must respect AMR neighbor structure too."""
    g = make_grid(length=(8, 8, 1), max_ref=1)
    g.refine_completely(1)
    g.refine_completely(28)
    g.stop_refining()
    g.balance_load()
    gol_b = GameOfLife(g)
    gol_o = GameOfLife(g, overlap=True)
    rng = np.random.default_rng(3)
    cells = g.get_cells()
    alive0 = cells[rng.random(len(cells)) < 0.4]
    sb = gol_b.new_state(alive_cells=alive0)
    so = gol_o.new_state(alive_cells=alive0)
    for _ in range(5):
        sb = gol_b.step(sb)
        so = gol_o.step(so)
    assert set(gol_b.alive_cells(sb).tolist()) == set(
        gol_o.alive_cells(so).tolist()
    )


def test_overlap_covers_every_local_cell():
    """Compacted inner + outer row sets partition the local rows."""
    from dccrg_tpu.parallel.stencil import compact_rows

    g = make_grid(length=(6, 6, 6))
    hood = g.epoch.hoods[None]
    scratch = g.epoch.R - 1
    for d in range(g.n_devices):
        inner = set(np.flatnonzero(np.asarray(hood.inner_mask)[d]).tolist())
        outer = set(np.flatnonzero(np.asarray(hood.outer_mask)[d]).tolist())
        local = set(np.flatnonzero(np.asarray(g.epoch.local_mask)[d]).tolist())
        assert inner | outer == local
        assert not (inner & outer)
    rows = compact_rows(np.asarray(hood.inner_mask), scratch)
    for d in range(g.n_devices):
        got = set(rows[d].tolist()) - {scratch}
        assert got == set(np.flatnonzero(np.asarray(hood.inner_mask)[d]).tolist())


def test_collective_independent_of_inner_compute():
    """The overlap property itself, checked on the step's dataflow graph:
    inside the jitted split-phase step, the ghost collectives (the ring's
    ppermute steps) must not depend on any result of the inner-cell
    compute, and the inner-cell results must not depend on any
    collective — that mutual independence is exactly what lets a parallel
    runtime (TPU async collectives, XLA latency-hiding scheduler) run
    them concurrently."""
    import jax

    g = make_grid(length=(8, 8, 8))
    gol = GameOfLife(g, overlap=True)
    state = gol.new_state(alive_cells=GLIDER)
    jaxpr = jax.make_jaxpr(gol._step)(state)

    # collect equations of the (single) inner shard_map body
    def find_eqns(jpr, out):
        for eqn in jpr.eqns:
            out.append(eqn)
            for v in eqn.params.values():
                for vv in v if isinstance(v, (list, tuple)) else (v,):
                    if hasattr(vv, "jaxpr"):      # ClosedJaxpr
                        vv = vv.jaxpr
                    if hasattr(vv, "eqns"):       # open Jaxpr
                        find_eqns(vv, out)

    eqns = []
    find_eqns(jaxpr.jaxpr, eqns)
    colls = [
        e for e in eqns
        if "ppermute" in str(e.primitive) or "all_to_all" in str(e.primitive)
    ]
    assert colls, "expected at least one ghost collective in the step"

    # ancestors of a var: all vars transitively feeding it (a jaxpr
    # Literal has .val and no producer; skip it)
    producers = {}
    for e in eqns:
        for ov in e.outvars:
            producers[id(ov)] = e

    def ancestors(vs):
        seen = set()
        stack = [v for v in vs if not hasattr(v, "val")]
        while stack:
            v = stack.pop()
            if id(v) in seen:
                continue
            seen.add(id(v))
            e = producers.get(id(v))
            if e is not None:
                stack.extend(iv for iv in e.invars if not hasattr(iv, "val"))
        return seen

    coll_ancestors = set()
    for c in colls:
        coll_ancestors |= ancestors(c.invars)
    coll_out_ids = {id(v) for c in colls for v in c.outvars}

    # "inner compute" = the integer-sum reductions NOT downstream of any
    # collective; at least one reduction (the inner count) must be fully
    # independent of all of them in both directions
    reduces = [
        e for e in eqns if str(e.primitive) in ("reduce_sum", "reduce_and", "add_any")
        and e not in colls
    ]
    independent = []
    for e in reduces:
        anc = ancestors(e.invars)
        if not (anc & coll_out_ids):           # doesn't read a collective
            out_ids = {id(v) for v in e.outvars}
            if not (out_ids & coll_ancestors):  # no collective reads it
                independent.append(e)
    assert independent, (
        "no reduction is dataflow-independent of the collectives — the "
        "split-phase step lost its overlap structure"
    )


def test_stale_split_phase_convention_raises():
    """The pre-handle calling convention (passing the start() result where
    a state belongs) must fail loudly, not exchange garbage."""
    g = make_grid()
    state = g.new_state({"v": ((), np.float64)})
    handle = g.start_remote_neighbor_copy_updates(state)
    with pytest.raises(TypeError, match="HaloHandle"):
        g.wait_remote_neighbor_copy_updates(handle)       # old pattern
    with pytest.raises(TypeError, match="HaloHandle"):
        g.wait_remote_neighbor_copy_updates(state, state)  # swapped args
