"""Elastic fleet (ISSUE 8): supervised rescale bit-identity, policy
hysteresis/cooldown (no flap under oscillating load), watchdog stall
detection + escalation ladder, injected ``device.lost`` / ``step.hang``
handling, and the fresh-process persistent-cache warm start asserting
``epoch.recompiles == 0`` on a held ShapeSignature."""
import json
import os
import subprocess
import sys
import tempfile
import textwrap

import numpy as np
import pytest

from dccrg_tpu import CartesianGeometry, Grid, make_mesh, obs
from dccrg_tpu.models import Advection, GameOfLife
from dccrg_tpu.resilience import (
    CheckpointLineage,
    DeviceLostError,
    ElasticPolicy,
    EscalationLadder,
    HeartbeatMonitor,
    Supervisor,
    available_devices,
    plane,
    rescale,
    step_latency_signal,
    utilization_signal,
)
from dccrg_tpu.resilience import inject

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _disarm_after():
    yield
    plane.disarm()


def make_adv_grid(n_dev, n=4, seed=0):
    g = (
        Grid()
        .set_initial_length((n, n, n))
        .set_neighborhood_length(0)
        .set_periodic(True, True, True)
        .set_maximum_refinement_level(1)
        .set_geometry(CartesianGeometry, start=(0.0, 0.0, 0.0),
                      level_0_cell_length=(1.0 / n,) * 3)
        .initialize(mesh=make_mesh(n_devices=n_dev))
    )
    rng = np.random.default_rng(seed)
    ids = np.sort(g.get_cells())
    for cid in rng.choice(ids, size=max(1, len(ids) // 5), replace=False):
        g.refine_completely(int(cid))
    g.stop_refining()
    return g, rng


ADV_SPEC = {k: ((), np.float64) for k in ("density", "vx", "vy", "vz")}


def land_advection(g, spec_state):
    """Rebuild model + full state from a (grid, spec-field state) pair —
    the same landing path the soak child uses."""
    ids = np.sort(g.get_cells())
    adv = Advection(g)
    s = adv.initialize_state()
    for f in ADV_SPEC:
        s = adv.set_cell_data(s, f, ids, g.get_cell_data(spec_state, f, ids))
    s = g.update_copies_of_remote_neighbors(s)
    return adv, s


# ------------------------------------------------------- rescale mechanism


def test_rescale_gol_bit_identity_1_to_8():
    """GoL stepped across rescales 1 -> 8 -> 1 must finish exactly equal
    to the fixed-mesh run (checkpoint round trip exact, GoL exact on any
    count)."""
    rng = np.random.default_rng(7)
    runs = {}
    for elastic in (False, True):
        g = (Grid().set_initial_length((8, 8, 1)).set_neighborhood_length(1)
             .set_periodic(True, True, False)
             .initialize(mesh=make_mesh(n_devices=1)))
        cells = g.get_cells()
        alive = cells[np.random.default_rng(42).random(len(cells)) < 0.4]
        gol = GameOfLife(g)
        s = gol.new_state(alive_cells=alive)
        with tempfile.TemporaryDirectory() as td:
            for step in range(9):
                if elastic and step in (3, 6):
                    target = 8 if step == 3 else 1
                    r = rescale(g, s, GameOfLife.SPEC, target,
                                directory=td, user_header=b"t")
                    assert r.n_devices_after == target
                    g, s = r.grid, r.state
                    gol = GameOfLife(g)
                s = gol.run(s, 1)
            runs[elastic] = set(gol.alive_cells(s).tolist())
    assert runs[True] == runs[False]


def test_rescale_advection_converges_across_counts():
    """Advection stepped across 1 -> 8 -> 2 rescales matches the
    fixed-mesh run within the documented cross-layout tolerance."""
    finals = {}
    for elastic in (False, True):
        g, rng = make_adv_grid(1)
        ids = np.sort(g.get_cells())
        adv = Advection(g)
        s = adv.initialize_state()
        s = adv.set_cell_data(s, "density", ids,
                              rng.uniform(1, 2, len(ids)))
        for f in ("vx", "vy", "vz"):
            s = adv.set_cell_data(s, f, ids,
                                  rng.uniform(-0.2, 0.2, len(ids)))
        s = g.update_copies_of_remote_neighbors(s)
        dt = 0.3 * adv.max_time_step(s)
        with tempfile.TemporaryDirectory() as td:
            for step in range(6):
                if elastic and step in (2, 4):
                    r = rescale(g, s, ADV_SPEC, 8 if step == 2 else 2,
                                directory=td, user_header=b"t")
                    g = r.grid
                    adv, s = land_advection(g, r.state)
                s = adv.step(s, dt)
        finals[elastic] = np.asarray(
            g.get_cell_data(s, "density", ids), np.float64)
    np.testing.assert_allclose(finals[True], finals[False],
                               rtol=1e-11, atol=0)


def test_rescale_counters_phase_and_result():
    g, rng = make_adv_grid(2)
    spec = {"q": ((), np.float64)}
    s = g.new_state(spec)
    ids = g.get_cells()
    s = g.set_cell_data(s, "q", ids, rng.uniform(0, 1, len(ids)))
    up0 = obs.metrics.counter_value("elastic.rescales", direction="up")
    down0 = obs.metrics.counter_value("elastic.rescales", direction="down")
    with tempfile.TemporaryDirectory() as td:
        r = rescale(g, s, spec, 4, directory=td)
        assert (r.direction, r.n_devices_before, r.n_devices_after) == \
            ("up", 2, 4)
        assert r.commit_s > 0 and r.reland_s > 0
        r2 = rescale(r.grid, r.state, spec, 1, directory=td)
        assert r2.direction == "down" and r2.n_devices_after == 1
        # payload survives both re-landings bit-identically
        np.testing.assert_array_equal(
            np.asarray(r2.grid.get_cell_data(r2.state, "q", ids)),
            np.asarray(g.get_cell_data(s, "q", ids)))
    assert obs.metrics.counter_value("elastic.rescales",
                                     direction="up") == up0 + 1
    assert obs.metrics.counter_value("elastic.rescales",
                                     direction="down") == down0 + 1
    assert obs.metrics.gauge_value("elastic.n_devices") == 1
    assert "elastic.rescale" in obs.metrics.phase_names()


def test_rescale_rejects_bad_targets():
    g, rng = make_adv_grid(1)
    spec = {"q": ((), np.float64)}
    s = g.new_state(spec)
    with tempfile.TemporaryDirectory() as td:
        with pytest.raises(ValueError, match="lineage"):
            rescale(g, s, spec, 2)
        with pytest.raises(ValueError, match="devices"):
            rescale(g, s, spec, 0, directory=td)
        with pytest.raises(DeviceLostError, match="visible"):
            rescale(g, s, spec, available_devices() + 1, directory=td)


def test_rescaled_grids_share_signature_and_executables():
    """Two re-landings of the same lineage generation at the same count
    build equal ShapeSignatures (rings included) — the satellite claim
    that the signature alone predicts executable-cache behavior."""
    g, rng = make_adv_grid(2)
    spec = {"q": ((), np.float64)}
    s = g.new_state(spec)
    with tempfile.TemporaryDirectory() as td:
        lineage = CheckpointLineage(td, keep=2)
        lineage.commit(g, s, spec)
        grids = []
        for _ in range(2):
            g2, s2, _h, _gen = lineage.latest_valid(spec, n_devices=4)
            s2 = g2.update_copies_of_remote_neighbors(s2)  # build halos
            grids.append(g2)
    sig_a, sig_b = (gr.shape_signature() for gr in grids)
    assert sig_a == sig_b
    assert sig_a.rings, "ring hints missing from the grid signature"


# ---------------------------------------------------------------- policy


def test_policy_oscillating_load_never_flaps():
    p = ElasticPolicy(4, high=0.8, low=0.3, patience=2, cooldown_s=0.0,
                      max_devices=8)
    decisions = [p.observe(load, now=float(i))
                 for i, load in enumerate([0.95, 0.05] * 10)]
    assert decisions == [None] * 20


def test_policy_patience_then_grow_and_clamp():
    p = ElasticPolicy(4, high=0.8, low=0.3, patience=3, cooldown_s=0.0,
                      max_devices=8)
    assert p.observe(0.9, now=0.0) is None
    assert p.observe(0.9, now=1.0) is None
    assert p.observe(0.9, now=2.0) == 8
    p.committed(8, now=2.0)
    # at max: sustained high load cannot grow further
    for i in range(5):
        assert p.observe(0.99, now=3.0 + i) is None


def test_policy_shrink_with_floor():
    p = ElasticPolicy(4, min_devices=2, high=0.8, low=0.3, patience=2,
                      cooldown_s=0.0, max_devices=8)
    assert p.observe(0.1, now=0.0) is None
    assert p.observe(0.1, now=1.0) == 2
    p.committed(2, now=1.0)
    assert p.observe(0.1, now=2.0) is None  # floor: patience restarts
    assert p.observe(0.1, now=3.0) is None  # 2 == min_devices


def test_policy_cooldown_blocks_then_releases():
    p = ElasticPolicy(2, high=0.8, low=0.3, patience=1, cooldown_s=10.0,
                      max_devices=8)
    assert p.observe(0.9, now=0.0) == 4
    p.committed(4, now=0.0)
    assert p.observe(0.9, now=5.0) is None       # inside cooldown
    assert p.observe(0.9, now=10.5) == 8         # released
    # in-between load resets streaks (hysteresis band)
    p2 = ElasticPolicy(4, high=0.8, low=0.3, patience=2, cooldown_s=0.0)
    assert p2.observe(0.9, now=0.0) is None
    assert p2.observe(0.5, now=1.0) is None
    assert p2.observe(0.9, now=2.0) is None      # streak restarted


def test_policy_env_knobs(monkeypatch):
    monkeypatch.setenv("DCCRG_ELASTIC_HIGH", "0.6")
    monkeypatch.setenv("DCCRG_ELASTIC_LOW", "0.2")
    monkeypatch.setenv("DCCRG_ELASTIC_PATIENCE", "1")
    monkeypatch.setenv("DCCRG_ELASTIC_COOLDOWN", "0")
    p = ElasticPolicy(2, max_devices=8)
    assert (p.high, p.low, p.patience, p.cooldown_s) == (0.6, 0.2, 1, 0.0)
    assert p.observe(0.7, now=0.0) == 4
    with pytest.raises(ValueError, match="low < high"):
        ElasticPolicy(2, high=0.3, low=0.5)


def test_signals_from_registry():
    from dccrg_tpu.obs.registry import MetricsRegistry

    reg = MetricsRegistry(enabled=True)
    assert utilization_signal(reg) is None
    reg.gauge("hbm.bytes_in_use", 750, device=0)
    reg.gauge("hbm.bytes_limit", 1000, device=0)
    reg.gauge("hbm.bytes_in_use", 100, device=1)
    reg.gauge("hbm.bytes_limit", 1000, device=1)
    assert utilization_signal(reg) == pytest.approx(0.75)
    assert step_latency_signal(0.5, registry=reg) is None
    reg.phase_add("halo.exchange", 1.0)
    assert step_latency_signal(0.5, registry=reg) == pytest.approx(2.0)


# -------------------------------------------------------------- watchdog


def _stream(path, registry=None):
    return obs.TelemetryStream(path, period=3600.0, registry=registry,
                               truncate=True)


def test_heartbeat_monitor_detects_silence(tmp_path):
    hb = str(tmp_path / "hb.jsonl")
    mon = HeartbeatMonitor(hb, stall_after_s=5.0, now=0.0)
    assert mon.poll(now=1.0) == ("waiting", None)
    assert mon.poll(now=6.0) == ("stalled", "no-heartbeat")
    s = _stream(hb)
    s.write_snapshot(step=0)
    mon = HeartbeatMonitor(hb, stall_after_s=5.0, now=0.0)
    assert mon.poll(now=1.0) == ("ok", None)
    assert mon.poll(now=4.0) == ("ok", None)
    assert mon.poll(now=7.0) == ("stalled", "no-heartbeat")


def test_heartbeat_monitor_detects_frozen_progress(tmp_path):
    """Lines keep arriving (the stream ticker survived) but the step
    marker and counters are frozen — the step.hang shape."""
    from dccrg_tpu.obs.registry import MetricsRegistry

    reg = MetricsRegistry(enabled=True)
    hb = str(tmp_path / "hb.jsonl")
    s = _stream(hb, registry=reg)
    reg.inc("work.done")
    s.write_snapshot(step=0)
    mon = HeartbeatMonitor(hb, stall_after_s=3.0, now=0.0)
    assert mon.poll(now=0.5) == ("ok", None)
    reg.inc("work.done")
    s.write_snapshot(step=1)
    assert mon.poll(now=2.0) == ("ok", None)
    for now in (4.0, 6.0):
        s.write_snapshot(step=1)           # beats WITHOUT progress
        status = mon.poll(now=now)
    assert status == ("stalled", "no-progress")
    # progress resumes -> healthy again
    reg.inc("work.done")
    s.write_snapshot(step=2)
    assert mon.poll(now=7.0) == ("ok", None)


def test_heartbeat_monitor_tolerates_torn_tail(tmp_path):
    hb = tmp_path / "hb.jsonl"
    s = _stream(str(hb))
    s.write_snapshot(step=0)
    with open(hb, "a") as f:
        f.write('{"seq": 1, "truncated')   # killed mid-write
    mon = HeartbeatMonitor(str(hb), stall_after_s=5.0, now=0.0)
    assert mon.poll(now=1.0) == ("ok", None)
    assert mon.beats == 1


def test_escalation_ladder_order_counters_and_reset():
    warn0 = obs.metrics.counter_value("supervisor.warnings",
                                      reason="unit")
    deg0 = obs.metrics.counter_value("elastic.degraded")
    lad = EscalationLadder()
    assert [lad.escalate("unit") for _ in range(4)] == \
        ["warn", "rescale_down", "restart", "restart"]
    assert obs.metrics.counter_value("supervisor.warnings",
                                     reason="unit") == warn0 + 1
    assert obs.metrics.counter_value("elastic.degraded") == deg0 + 1
    assert obs.metrics.counter_value("supervisor.escalations",
                                     action="restart") >= 2
    lad.reset()
    assert lad.escalate("unit") == "warn"
    # patience absorbs strikes per rung
    lad2 = EscalationLadder(patience=2)
    assert [lad2.escalate("x") for _ in range(4)] == \
        ["warn", "warn", "rescale_down", "rescale_down"]
    # a dead child enters at the degraded rung
    lad3 = EscalationLadder()
    assert lad3.escalate("child-dead", minimum="rescale_down") == \
        "rescale_down"


def test_supervisor_escalates_and_recovers(tmp_path):
    hb = str(tmp_path / "hb.jsonl")
    s = _stream(hb)
    s.write_snapshot(step=0)
    sup = Supervisor(HeartbeatMonitor(hb, stall_after_s=2.0, now=0.0))
    assert sup.poll(now=0.5)["action"] is None
    acts = [sup.poll(now=10.0 + i)["action"] for i in range(3)]
    assert acts == ["warn", "rescale_down", "restart"]
    # a fresh beat resets the ladder
    s.write_snapshot(step=1)
    assert sup.poll(now=13.5)["action"] is None
    assert sup.poll(now=20.0)["action"] == "warn"
    assert "supervisor.poll" in obs.metrics.phase_names()


def test_supervisor_dead_child_goes_degraded(tmp_path):
    hb = str(tmp_path / "hb.jsonl")
    _stream(hb).write_snapshot(step=0)
    sup = Supervisor(HeartbeatMonitor(hb, stall_after_s=30.0, now=0.0),
                     child_alive=lambda: False)
    out = sup.poll(now=1.0)
    assert (out["status"], out["action"]) == ("dead", "rescale_down")
    assert sup.poll(now=2.0)["action"] == "restart"


# ------------------------------------------------------------ fault sites


def test_device_lost_site_raises_and_counts():
    before = obs.metrics.counter_value("resilience.injected",
                                       site="device.lost",
                                       where="discovery")
    plane.arm("device.lost", prob=1.0, seed=0, count=1)
    with pytest.raises(DeviceLostError):
        available_devices()
    assert available_devices() >= 1   # budget spent: back to normal
    assert obs.metrics.counter_value(
        "resilience.injected", site="device.lost", where="discovery"
    ) == before + 1


def test_device_lost_aborts_rescale():
    g, rng = make_adv_grid(1)
    spec = {"q": ((), np.float64)}
    s = g.new_state(spec)
    plane.arm("device.lost", prob=1.0, seed=0, count=1)
    with tempfile.TemporaryDirectory() as td:
        with pytest.raises(DeviceLostError):
            rescale(g, s, spec, 2, directory=td)
        plane.disarm()
        r = rescale(g, s, spec, 2, directory=td)   # plane clear: works
        assert r.n_devices_after == 2


def test_step_hang_site_sleeps_and_counts():
    import time

    assert not inject.maybe_hang("step.hang", seconds=0.01)
    plane.arm("step.hang", prob=1.0, seed=0, count=1)
    t0 = time.perf_counter()
    assert inject.maybe_hang("step.hang", seconds=0.05)
    assert time.perf_counter() - t0 >= 0.05
    assert not inject.maybe_hang("step.hang", seconds=0.05)  # budget spent


# ------------------------------------------- persistent-cache warm start


WARM_CHILD = textwrap.dedent("""\
    import sys, os, json
    lineage_dir, nd, out = sys.argv[1], int(sys.argv[2]), sys.argv[3]
    import jax
    jax.config.update('jax_platforms', 'cpu')
    os.environ['XLA_FLAGS'] = (os.environ.get('XLA_FLAGS', '')
        + ' --xla_force_host_platform_device_count=8').strip()
    jax.config.update('jax_enable_x64', True)
    import numpy as np
    sys.path.insert(0, %r)
    from dccrg_tpu import Grid, obs
    from dccrg_tpu.models import Advection
    from dccrg_tpu.parallel.exec_cache import (persistent_cache_counts,
                                               persistent_cache_dir)
    from dccrg_tpu.resilience import CheckpointLineage

    SPEC = {k: ((), np.float64) for k in ('density', 'vx', 'vy', 'vz')}
    lineage = CheckpointLineage(lineage_dir, keep=2)
    g, s2, hdr, gen = lineage.latest_valid(SPEC, n_devices=nd)
    ids = np.sort(g.get_cells())
    adv = Advection(g)
    s = adv.initialize_state()
    for f in SPEC:
        s = adv.set_cell_data(s, f, ids, g.get_cell_data(s2, f, ids))
    s = g.update_copies_of_remote_neighbors(s)
    dt = 0.25 * adv.max_time_step(s)
    s = adv.step(s, dt)
    # first churn cycle: rebuild + re-land + step, the warm-start claim
    lvl = g.mapping.get_refinement_level(ids)
    cand = ids[lvl < g.mapping.max_refinement_level]
    g.refine_completely(int(cand[len(cand) // 2]))
    g.stop_refining()
    s = g.remap_state(s)
    s = g.update_copies_of_remote_neighbors(s)
    adv = Advection(g)
    s = adv.step(s, dt)
    jax.block_until_ready(s['density'])
    rep = obs.metrics.report()
    json.dump({
        'signature': repr(g.shape_signature()),
        'cache_dir': persistent_cache_dir(),
        'recompiles': sum(
            rep['counters'].get('epoch.recompiles', {}).values()),
        'warm_compiles': sum(
            rep['counters'].get('epoch.warm_compiles', {}).values()),
        'persistent_cache': persistent_cache_counts(),
    }, open(out, 'w'))
""" % ROOT)


def test_fresh_process_warm_start_zero_recompiles(tmp_path):
    """The zero-cold-start proof: two fresh processes resume the same
    lineage under a shared ``DCCRG_COMPILE_CACHE_DIR`` and run one churn
    cycle; the second must land on the first's ShapeSignature with
    ``epoch.recompiles == 0`` — every compile a persistent-cache hit."""
    g, rng = make_adv_grid(2, seed=3)
    adv = Advection(g)
    s = adv.initialize_state()
    ids = np.sort(g.get_cells())
    s = adv.set_cell_data(s, "density", ids, rng.uniform(1, 2, len(ids)))
    s = g.update_copies_of_remote_neighbors(s)
    lineage_dir = str(tmp_path / "lineage")
    CheckpointLineage(lineage_dir, keep=2).commit(g, s, ADV_SPEC)

    env = dict(os.environ)
    env["DCCRG_COMPILE_CACHE_DIR"] = str(tmp_path / "cache")
    env["JAX_PLATFORMS"] = "cpu"
    reports = []
    for i in range(2):
        out = str(tmp_path / f"proof_{i}.json")
        r = subprocess.run(
            [sys.executable, "-c", WARM_CHILD, lineage_dir, "2", out],
            env=env, capture_output=True, text=True, timeout=300,
            cwd=ROOT,
        )
        assert r.returncode == 0, r.stdout[-1500:] + r.stderr[-1500:]
        with open(out) as f:
            reports.append(json.load(f))
    a, b = reports
    assert b["cache_dir"] == str(tmp_path / "cache")
    assert b["signature"] == a["signature"], (a, b)
    assert b["recompiles"] == 0, b
    assert b["warm_compiles"] > 0, b
    assert b["persistent_cache"]["hits"] > 0, b


# ---------------------------------------------------- signature satellite


def test_ring_signature_canonical_form():
    from dccrg_tpu.parallel.shapes import ring_signature

    assert ring_signature({}) == ()
    assert ring_signature(None) == ()
    hints = {(None, None, 1): 44, (2, "density", 3): 16,
             (None, None, 2): 8}
    assert ring_signature(hints) == (
        (-1, "", 1, 44), (-1, "", 2, 8), (2, "density", 3, 16))


def test_grid_signature_surfaces_ring_hints():
    g, _rng = make_adv_grid(2)
    spec = {"q": ((), np.float64)}
    s = g.new_state(spec)
    sig0 = g.shape_signature()
    g.update_copies_of_remote_neighbors(s)   # builds the halo schedule
    sig1 = g.shape_signature()
    assert sig1.rings, "halo build left no ring hints in the signature"
    assert sig1._replace(rings=()) == sig0._replace(rings=())
    # held hints are sticky: a second identical exchange changes nothing
    g.update_copies_of_remote_neighbors(s)
    assert g.shape_signature() == sig1


def test_check_telemetry_artifact_routing(tmp_path):
    """Bench byproducts route to tools/ only for the repo-root
    telemetry.json; everything else stays beside --out."""
    sys.path.insert(0, os.path.join(ROOT, "tools"))
    try:
        import check_telemetry
    finally:
        sys.path.pop(0)
    root_out = os.path.join(ROOT, "telemetry.json")
    assert check_telemetry.artifact_path(root_out, ".stream.jsonl") == \
        os.path.join(ROOT, "tools", "telemetry.json.stream.jsonl")
    tmp_out = str(tmp_path / "t.json")
    assert check_telemetry.artifact_path(tmp_out, ".trace.json") == \
        str(tmp_path / "t.json.trace.json")
    assert check_telemetry.artifact_path(
        root_out, ".x", artifact_dir=str(tmp_path)
    ) == str(tmp_path / "telemetry.json.x")
