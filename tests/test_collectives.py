"""Multi-controller metadata collectives (the reference's MPI support
layer, dccrg_mpi_support.hpp) — degenerate single-process behavior plus
the real multi-controller wire path exercised through a substituted
transport (SURVEY.md §2.4 seam)."""
import numpy as np
import pytest

from dccrg_tpu import Grid, make_mesh
from dccrg_tpu.utils import collectives


def test_single_process_degenerate():
    assert collectives.process_count() == 1
    vals = np.array([3, 1, 2], dtype=np.uint64)
    parts = collectives.allgather_u64(vals)
    assert len(parts) == 1
    np.testing.assert_array_equal(parts[0], vals)
    np.testing.assert_array_equal(
        collectives.union_u64({5, 2, 9}), np.array([2, 5, 9], dtype=np.uint64)
    )
    assert collectives.all_reduce([1.0, 2.0, 3.0]) == 6.0


class _FakeTransport:
    """Simulates P processes: process_allgather returns this process's
    array stacked with pre-baked peer arrays."""

    def __init__(self, monkeypatch, peer_payloads):
        self.peers = peer_payloads          # list of dicts: shape -> array
        monkeypatch.setattr(
            collectives, "process_count", lambda: 1 + len(peer_payloads)
        )
        monkeypatch.setattr(collectives, "_process_allgather", self)
        self.calls = 0

    def __call__(self, x):
        # first call per collective gathers lengths, second gathers padded
        # payloads; peers answer from their scripted sequences
        rows = [np.asarray(x)]
        for peer in self.peers:
            rows.append(np.asarray(peer.pop(0)))
        self.calls += 1
        return np.stack(rows)


def test_allgather_u64_wire_format(monkeypatch):
    """Variable-length gather = length gather + padded payload gather."""
    peer = [
        np.array([2], dtype=np.int64),                # peer's length
        np.array([7, 8, 0], dtype=np.uint64),         # peer's padded payload
    ]
    _FakeTransport(monkeypatch, [peer])
    parts = collectives.allgather_u64(np.array([1, 2, 3], dtype=np.uint64))
    assert len(parts) == 2
    np.testing.assert_array_equal(parts[0], [1, 2, 3])
    np.testing.assert_array_equal(parts[1], [7, 8])   # trimmed to length 2


def test_union_and_allreduce_across_processes(monkeypatch):
    peer = [
        np.array([2], dtype=np.int64),
        np.array([5, 2], dtype=np.uint64),
    ]
    _FakeTransport(monkeypatch, [peer])
    np.testing.assert_array_equal(
        collectives.union_u64(np.array([2, 9], dtype=np.uint64)), [2, 5, 9]
    )
    _FakeTransport(monkeypatch, [[np.asarray(10.0)]])
    assert collectives.all_reduce([1.0, 2.0]) == 13.0  # 3 local + 10 remote


def test_stop_refining_merges_remote_requests(monkeypatch):
    """End-to-end through the grid: a refine request queued by a (mocked)
    remote controller is committed locally — every process runs the
    deterministic commit pipeline on the union of requests, keeping the
    replicated leaf directory identical everywhere."""
    g = (
        Grid()
        .set_initial_length((4, 4, 1))
        .set_maximum_refinement_level(1)
        .set_neighborhood_length(1)
        .initialize(mesh=make_mesh(n_devices=2))
    )
    g.refine_completely(1)                 # local request: cell 1
    # remote controller requested cell 16; all four queues (to_refine,
    # to_unrefine, not_to_refine, not_to_unrefine) travel in ONE
    # lengths-vector + padded-payload collective pair
    peer = [
        np.array([1, 0, 0, 0], dtype=np.int64),   # peer queue lengths
        np.array([16], dtype=np.uint64),          # concatenated payload
    ]
    _FakeTransport(monkeypatch, [peer])
    new_cells = g.stop_refining()
    # both cells are gone from the leaf set (refined into children)
    assert not g.leaves.exists(np.uint64(1))
    assert not g.leaves.exists(np.uint64(16))
    assert len(new_cells) == 16            # two cells x 8 children


def test_sync_adaptation_identity_single_process():
    from dccrg_tpu.utils.collectives import sync_adaptation

    g = (
        Grid()
        .set_initial_length((4, 4, 1))
        .set_maximum_refinement_level(1)
        .set_neighborhood_length(1)
        .initialize(mesh=make_mesh(n_devices=2))
    )
    g.refine_completely(3)
    before = set(g.amr.to_refine)
    sync_adaptation(g.amr)
    assert g.amr.to_refine == before


def test_balance_load_merges_remote_pins_and_weights(monkeypatch):
    """A pin and a weight registered by a (mocked) remote controller are
    honored by the local balance_load — partition inputs reach agreement
    before the partitioner runs (update_pin_requests, dccrg.hpp:8297-8340)."""
    g = (
        Grid()
        .set_initial_length((4, 4, 1))
        .set_neighborhood_length(1)
        .initialize(mesh=make_mesh(n_devices=2))
    )
    g.pin(3, 0)                            # local pin: cell 3 -> device 0
    # remote controller pinned cell 7 -> device 1 and weighted cell 5 by 9.0
    w = np.asarray(9.0, dtype=np.float64).view(np.uint64)
    peer = [
        np.array([1, 1, 1, 1], dtype=np.int64),   # pins(2 arrays), weights(2)
        np.array([7, 1, 5, int(w)], dtype=np.uint64),
    ]
    _FakeTransport(monkeypatch, [peer])
    g.balance_load()
    assert g.get_owner([3])[0] == 0
    assert g.get_owner([7])[0] == 1
    # the merged view is transient: this controller's own dicts stay
    # local, so a later unpin here cannot be resurrected by stale copies
    # inherited from peers (reference: all_pin_requests is a gather-side
    # temporary, dccrg.hpp:8297-8340)
    assert g.pin_requests == {3: 0}
    assert g.cell_weights == {}


# ------------------------------------------------- p2p transport unit

def _make_transport(rank):
    """A _P2PTransport wired by hand (no process_allgather): listener
    bound, address book patched in afterwards by the caller."""
    import socket

    from dccrg_tpu.utils.collectives import _P2PTransport

    t = _P2PTransport.__new__(_P2PTransport)
    t.rank = rank
    t.token = 0x5EC0DE              # same job token for all test peers
    t.sent_to = {}
    t.received_from = {}
    t._pair_seq = {}
    t._pending = {}
    t._listener = socket.socket()
    t._listener.bind(("127.0.0.1", 0))
    t._listener.listen(128)
    return t


def test_p2p_exchange_pair_and_payload_sizes():
    """Symmetric exchange between two in-process transports, from 8-byte
    scalars to megabyte payloads (the threaded sends must not deadlock
    on payloads past the kernel socket buffers)."""
    import threading

    a, b = _make_transport(0), _make_transport(1)
    book = [("127.0.0.1", t._listener.getsockname()[1]) for t in (a, b)]
    a.addrs = b.addrs = book

    try:
        for size in (8, 1 << 21):
            pa, pb = b"A" * size, b"B" * size
            out = {}

            def run(t, payload, key):
                out[key] = t.exchange(payload, [1 - t.rank])

            th = threading.Thread(target=run, args=(b, pb, "b"))
            th.start()
            run(a, pa, "a")
            th.join(timeout=60)
            assert out["a"] == {1: pb} and out["b"] == {0: pa}
        assert a.sent_to[1] == 8 + (1 << 21)
        assert a.received_from[1] == 8 + (1 << 21)
    finally:
        a._listener.close()
        b._listener.close()


def test_p2p_stash_absorbs_mismatched_peer_sets():
    """Three transports; 1 and 2 run a pair exchange while 0 goes
    straight to the clique: 0's early connect to 2 must be stashed and
    consumed when 2 reaches the clique (not rejected)."""
    import threading
    import time

    ts = [_make_transport(r) for r in range(3)]
    book = [("127.0.0.1", t._listener.getsockname()[1]) for t in ts]
    for t in ts:
        t.addrs = book

    results = {}

    def run0():
        results[0] = ts[0].exchange(b"zero0000", [1, 2])

    def run1():
        # let rank 0's clique connect land in the backlogs FIRST, so
        # the stash branch is exercised deterministically, not by
        # thread-scheduling luck
        time.sleep(0.3)
        results["pair1"] = ts[1].exchange(b"pair1111", [2])
        results[1] = ts[1].exchange(b"one11111", [0, 2])

    def run2():
        time.sleep(0.3)
        results["pair2"] = ts[2].exchange(b"pair2222", [1])
        results[2] = ts[2].exchange(b"two22222", [0, 1])

    threads = [threading.Thread(target=f) for f in (run0, run1, run2)]
    try:
        for th in threads:
            th.start()
        for th in threads:
            th.join(timeout=60)
    finally:
        for t in ts:
            t._listener.close()
    assert results["pair1"] == {2: b"pair2222"}
    assert results["pair2"] == {1: b"pair1111"}
    assert results[0] == {1: b"one11111", 2: b"two22222"}
    assert results[1] == {0: b"zero0000", 2: b"two22222"}
    assert results[2] == {0: b"zero0000", 1: b"one11111"}


def test_p2p_rejects_wrong_job_token():
    """A message whose header carries a different job token must never be
    consumed as a peer contribution (ADVICE r4: unauthenticated listener);
    the exchange completes with the legitimate peer regardless."""
    import socket
    import struct
    import threading

    import pytest

    a, b = _make_transport(0), _make_transport(1)
    book = [("127.0.0.1", t._listener.getsockname()[1]) for t in (a, b)]
    a.addrs = b.addrs = book

    intruder_done = threading.Event()

    def intrude():
        # claims to be rank 0 but with a wrong token
        s = socket.create_connection(book[1], timeout=10)
        hdr = struct.pack(a._HEADER, 0, 1, 0xBAD, len(b"evil1234"))
        s.sendall(hdr + b"evil1234")
        s.close()
        intruder_done.set()

    out = {}

    def run(t, payload, key):
        out[key] = t.exchange(payload, [1 - t.rank])

    threading.Thread(target=intrude).start()
    assert intruder_done.wait(10)
    th = threading.Thread(target=run, args=(b, b"beta5678", "b"))
    try:
        with pytest.warns(UserWarning, match="bad job token"):
            th.start()
            run(a, b"alph1234", "a")
            th.join(timeout=60)
    finally:
        a._listener.close()
        b._listener.close()
    assert out["a"] == {1: b"beta5678"}
    assert out["b"] == {0: b"alph1234"}
