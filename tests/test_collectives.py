"""Multi-controller metadata collectives (the reference's MPI support
layer, dccrg_mpi_support.hpp) — degenerate single-process behavior plus
the real multi-controller wire path exercised through a substituted
transport (SURVEY.md §2.4 seam)."""
import numpy as np
import pytest

from dccrg_tpu import Grid, make_mesh
from dccrg_tpu.utils import collectives


def test_single_process_degenerate():
    assert collectives.process_count() == 1
    vals = np.array([3, 1, 2], dtype=np.uint64)
    parts = collectives.allgather_u64(vals)
    assert len(parts) == 1
    np.testing.assert_array_equal(parts[0], vals)
    np.testing.assert_array_equal(
        collectives.union_u64({5, 2, 9}), np.array([2, 5, 9], dtype=np.uint64)
    )
    assert collectives.all_reduce([1.0, 2.0, 3.0]) == 6.0


class _FakeTransport:
    """Simulates P processes: process_allgather returns this process's
    array stacked with pre-baked peer arrays."""

    def __init__(self, monkeypatch, peer_payloads):
        self.peers = peer_payloads          # list of dicts: shape -> array
        monkeypatch.setattr(
            collectives, "process_count", lambda: 1 + len(peer_payloads)
        )
        monkeypatch.setattr(collectives, "_process_allgather", self)
        self.calls = 0

    def __call__(self, x):
        # first call per collective gathers lengths, second gathers padded
        # payloads; peers answer from their scripted sequences
        rows = [np.asarray(x)]
        for peer in self.peers:
            rows.append(np.asarray(peer.pop(0)))
        self.calls += 1
        return np.stack(rows)


def test_allgather_u64_wire_format(monkeypatch):
    """Variable-length gather = length gather + padded payload gather."""
    peer = [
        np.array([2], dtype=np.int64),                # peer's length
        np.array([7, 8, 0], dtype=np.uint64),         # peer's padded payload
    ]
    _FakeTransport(monkeypatch, [peer])
    parts = collectives.allgather_u64(np.array([1, 2, 3], dtype=np.uint64))
    assert len(parts) == 2
    np.testing.assert_array_equal(parts[0], [1, 2, 3])
    np.testing.assert_array_equal(parts[1], [7, 8])   # trimmed to length 2


def test_union_and_allreduce_across_processes(monkeypatch):
    peer = [
        np.array([2], dtype=np.int64),
        np.array([5, 2], dtype=np.uint64),
    ]
    _FakeTransport(monkeypatch, [peer])
    np.testing.assert_array_equal(
        collectives.union_u64(np.array([2, 9], dtype=np.uint64)), [2, 5, 9]
    )
    _FakeTransport(monkeypatch, [[np.asarray(10.0)]])
    assert collectives.all_reduce([1.0, 2.0]) == 13.0  # 3 local + 10 remote


def test_stop_refining_merges_remote_requests(monkeypatch):
    """End-to-end through the grid: a refine request queued by a (mocked)
    remote controller is committed locally — every process runs the
    deterministic commit pipeline on the union of requests, keeping the
    replicated leaf directory identical everywhere."""
    g = (
        Grid()
        .set_initial_length((4, 4, 1))
        .set_maximum_refinement_level(1)
        .set_neighborhood_length(1)
        .initialize(mesh=make_mesh(n_devices=2))
    )
    g.refine_completely(1)                 # local request: cell 1
    # remote controller requested cell 16; all four queues (to_refine,
    # to_unrefine, not_to_refine, not_to_unrefine) travel in ONE
    # lengths-vector + padded-payload collective pair
    peer = [
        np.array([1, 0, 0, 0], dtype=np.int64),   # peer queue lengths
        np.array([16], dtype=np.uint64),          # concatenated payload
    ]
    _FakeTransport(monkeypatch, [peer])
    new_cells = g.stop_refining()
    # both cells are gone from the leaf set (refined into children)
    assert not g.leaves.exists(np.uint64(1))
    assert not g.leaves.exists(np.uint64(16))
    assert len(new_cells) == 16            # two cells x 8 children


def test_sync_adaptation_identity_single_process():
    from dccrg_tpu.utils.collectives import sync_adaptation

    g = (
        Grid()
        .set_initial_length((4, 4, 1))
        .set_maximum_refinement_level(1)
        .set_neighborhood_length(1)
        .initialize(mesh=make_mesh(n_devices=2))
    )
    g.refine_completely(3)
    before = set(g.amr.to_refine)
    sync_adaptation(g.amr)
    assert g.amr.to_refine == before


def test_balance_load_merges_remote_pins_and_weights(monkeypatch):
    """A pin and a weight registered by a (mocked) remote controller are
    honored by the local balance_load — partition inputs reach agreement
    before the partitioner runs (update_pin_requests, dccrg.hpp:8297-8340)."""
    g = (
        Grid()
        .set_initial_length((4, 4, 1))
        .set_neighborhood_length(1)
        .initialize(mesh=make_mesh(n_devices=2))
    )
    g.pin(3, 0)                            # local pin: cell 3 -> device 0
    # remote controller pinned cell 7 -> device 1 and weighted cell 5 by 9.0
    w = np.asarray(9.0, dtype=np.float64).view(np.uint64)
    peer = [
        np.array([1, 1, 1, 1], dtype=np.int64),   # pins(2 arrays), weights(2)
        np.array([7, 1, 5, int(w)], dtype=np.uint64),
    ]
    _FakeTransport(monkeypatch, [peer])
    g.balance_load()
    assert g.get_owner([3])[0] == 0
    assert g.get_owner([7])[0] == 1
    # the merged view is transient: this controller's own dicts stay
    # local, so a later unpin here cannot be resurrected by stale copies
    # inherited from peers (reference: all_pin_requests is a gather-side
    # temporary, dccrg.hpp:8297-8340)
    assert g.pin_requests == {3: 0}
    assert g.cell_weights == {}
