"""Telemetry subsystem tests: registry semantics, zero-overhead disabled
mode, nested/re-entrant phases, JSON export round-trip, the timers
back-compat shim, instrumented-seam coverage, and a ``Grid.report()``
smoke test on a refined game-of-life run (ISSUE 1 satellite).

ISSUE 2 layers: the streaming JSONL exporter, the begin/end event
timeline + Chrome trace export, per-device HBM gauges, fused-kernel
reconciliation counters, and the ``obs.profile_trace`` materialization
gate (previously only exercised manually via TensorBoard/xprof)."""
import json
import os
import sys
import threading
import time

import numpy as np
import pytest

from dccrg_tpu import obs
from dccrg_tpu.obs.registry import MetricsRegistry

HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.dirname(HERE)


# ---------------------------------------------------------------- registry


def test_counter_semantics():
    reg = MetricsRegistry()
    reg.inc("c")
    reg.inc("c", 2)
    reg.inc("c", 5, device=1)
    reg.inc("c", np.int64(3), device=1)
    rep = reg.report()["counters"]["c"]
    assert rep[""] == 3
    assert rep["device=1"] == 8
    assert isinstance(rep["device=1"], int)  # numpy scalars unwrapped
    assert reg.counter_value("c", device=1) == 8
    assert reg.counter_value("never") == 0


def test_inc_many_and_batch():
    reg = MetricsRegistry()
    reg.inc_many([("a", 1), ("b", 2, {"k": "v"}), ("a", 3)])
    reg.inc_batch([(("a", ()), 10), (("b", (("k", "v"),)), 20)])
    rep = reg.report()["counters"]
    assert rep["a"][""] == 14
    assert rep["b"]["k=v"] == 22


def test_gauge_latest_value_wins():
    reg = MetricsRegistry()
    reg.gauge("g", 1.5)
    reg.gauge("g", 2.5)
    reg.gauge("g", 7, hood="default")
    rep = reg.report()["gauges"]["g"]
    assert rep[""] == 2.5
    assert rep["hood=default"] == 7
    assert reg.gauge_value("g") == 2.5
    assert reg.gauge_value("missing", default=-1) == -1


def test_histogram_semantics():
    reg = MetricsRegistry()
    for v in (0.5, 1.0, 3.0, 3.0, 0.0):
        reg.observe("h", v)
    rep = reg.report()["histograms"]["h"][""]
    assert rep["count"] == 5
    assert rep["sum"] == pytest.approx(7.5)
    assert rep["mean"] == pytest.approx(1.5)
    assert rep["min"] == 0.0
    assert rep["max"] == 3.0
    # power-of-two buckets: 0.5 -> le=0.5, 1.0 -> le=1.0, 3.0 x2 -> le=4.0,
    # 0.0 -> the non-positive bucket "0"
    assert rep["buckets"] == {"0": 1, "0.5": 1, "1.0": 1, "4.0": 2}


def test_disabled_mode_records_no_keys():
    reg = MetricsRegistry(enabled=False)
    reg.inc("c")
    reg.inc_many([("a", 1)])
    reg.inc_batch([(("a", ()), 1)])
    reg.gauge("g", 1)
    reg.observe("h", 1.0)
    reg.phase_add("p", 0.1)
    with reg.phase("p2"):
        pass
    rep = reg.report()
    assert rep == {"phases": {}, "counters": {}, "gauges": {},
                   "histograms": {}}


def test_nested_phase_counts_outer_span_once():
    """The pre-obs PhaseTimers double-counted a nested phase("x") inside
    phase("x"); the registry must count the outermost wall span once."""
    reg = MetricsRegistry()
    with reg.phase("x"):
        time.sleep(0.05)
        with reg.phase("x"):
            time.sleep(0.05)
    rep = reg.report()["phases"]["x"]
    assert rep["count"] == 1
    # double-counting would give >= 0.15 (outer 0.1 + inner 0.05)
    assert 0.09 <= rep["total_s"] < 0.14
    # distinct names still nest freely
    with reg.phase("outer"):
        with reg.phase("inner"):
            pass
    phases = reg.report()["phases"]
    assert phases["outer"]["count"] == 1
    assert phases["inner"]["count"] == 1


def test_registry_thread_safety():
    reg = MetricsRegistry()

    def work():
        for _ in range(1000):
            reg.inc("t")
            with reg.phase("tp"):
                pass

    threads = [threading.Thread(target=work) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    rep = reg.report()
    assert rep["counters"]["t"][""] == 8000
    assert rep["phases"]["tp"]["count"] == 8000


def test_export_json_round_trip(tmp_path):
    reg = MetricsRegistry()
    reg.inc("halo.bytes_moved", 1024, hood="default")
    reg.gauge("epoch.n_cells", 72)
    reg.observe("lat", 0.25)
    with reg.phase("epoch.build"):
        pass
    out = tmp_path / "telemetry.json"
    written = obs.export_json(str(out), registry=reg,
                              extra={"workload": "unit"})
    loaded = json.loads(out.read_text())
    assert loaded == json.loads(json.dumps(written))
    assert loaded["workload"] == "unit"
    assert loaded["counters"]["halo.bytes_moved"]["hood=default"] == 1024
    assert "epoch.build" in loaded["phases"]


# ------------------------------------------------------------ timers shim


def test_phase_timers_shim_over_obs():
    from dccrg_tpu.utils.timers import PhaseTimers, timers

    # independent instance: old API shape
    pt = PhaseTimers()
    with pt.phase("a"):
        pass
    rep = pt.report()
    assert rep["a"]["count"] == 1
    assert set(rep["a"]) == {"total_s", "count", "mean_s"}
    assert pt.total["a"] >= 0.0
    assert pt.count["a"] == 1
    pt.reset()
    assert pt.report() == {}
    # nested same-name: fixed (no double count)
    with pt.phase("n"):
        time.sleep(0.02)
        with pt.phase("n"):
            time.sleep(0.02)
    assert pt.report()["n"]["count"] == 1
    # the process-wide `timers` is a view over obs.metrics
    assert timers._registry is obs.metrics
    prev = timers.enabled
    try:
        with timers.phase("shim.phase"):
            pass
        assert "shim.phase" in obs.metrics.report()["phases"]
    finally:
        timers.enabled = prev


# ------------------------------------------------- instrumented seams


def _small_grid(max_ref=1, hood=1, length=(8, 8, 1)):
    from dccrg_tpu import Grid, make_mesh

    return (
        Grid()
        .set_initial_length(length)
        .set_maximum_refinement_level(max_ref)
        .set_neighborhood_length(hood)
        .set_load_balancing_method("RCB")
        .initialize(mesh=make_mesh())
    )


def test_halo_exchange_telemetry_counters():
    obs.metrics.reset()
    obs.enable()
    g = _small_grid(max_ref=0)
    spec = {"rho": ((), np.float64)}
    st = g.new_state(spec)
    m = g.telemetry
    assert m.counter_value("halo.cells_moved") == 0
    st = g.update_copies_of_remote_neighbors(st)
    pair_counts = g.epoch.hoods[None].pair_counts
    expected_cells = int(pair_counts.sum())
    assert expected_cells > 0  # 8-device board really exchanges
    assert m.counter_value("halo.cells_moved") == expected_cells
    assert m.counter_value("halo.bytes_moved") == expected_cells * 8
    # per-device counters match the schedule tables, send total == recv
    send = [int(m.counter_value("halo.send_cells", device=d, hood="default"))
            for d in range(g.n_devices)]
    recv = [int(m.counter_value("halo.recv_cells", device=d, hood="default"))
            for d in range(g.n_devices)]
    assert send == [int(v) for v in pair_counts.sum(axis=1)]
    assert recv == [int(v) for v in pair_counts.sum(axis=0)]
    assert sum(send) == sum(recv) == expected_cells
    # wire bytes >= useful bytes (ring padding), phase recorded
    assert (m.counter_value("halo.wire_bytes")
            >= m.counter_value("halo.bytes_moved"))
    assert "halo.exchange" in m.report()["phases"]


def test_halo_split_phase_telemetry():
    obs.metrics.reset()
    obs.enable()
    g = _small_grid(max_ref=0)
    st = g.new_state({"rho": ((), np.float64)})
    handle = g.start_remote_neighbor_copy_updates(st)
    st = g.wait_remote_neighbor_copy_updates(st, handle)
    m = obs.metrics
    assert m.counter_value("halo.exchanges", kind="split",
                           hood="default") == 1
    assert m.report()["phases"]["halo.exchange"]["count"] == 1


def test_disabled_telemetry_records_nothing_on_grid_paths():
    obs.metrics.reset()
    obs.disable()
    try:
        g = _small_grid()
        st = g.new_state({"rho": ((), np.float64)})
        st = g.update_copies_of_remote_neighbors(st)
        g.refine_completely(int(g.get_cells()[0]))
        g.stop_refining()
        g.balance_load()
        rep = obs.metrics.report()
        assert rep == {"phases": {}, "counters": {}, "gauges": {},
                       "histograms": {}}
    finally:
        obs.enable()


def test_grid_report_smoke_refined_game_of_life():
    """Grid.report() on a refined game-of-life run: every structural
    seam the run exercises shows up in one snapshot."""
    from dccrg_tpu.models import GameOfLife

    obs.metrics.reset()
    obs.enable()
    g = _small_grid(max_ref=1, hood=1)
    for cid in g.get_cells()[:4]:
        g.refine_completely(int(cid))
    g.stop_refining()
    g.balance_load()
    gol = GameOfLife(g)
    state = gol.new_state(alive_cells=[12, 13, 14])
    for _ in range(3):
        state = gol.step(state)
    # one explicit host-level ghost refresh ticks the halo seam even
    # when the model's own step fuses its exchange into jit
    gol_state_field = next(iter(state))
    g.update_copies_of_remote_neighbors({gol_state_field: state[gol_state_field]})

    rep = g.report()
    for phase in ("epoch.build", "amr.refine", "loadbalance.migrate",
                  "halo.exchange"):
        assert phase in rep["phases"], phase
        assert rep["phases"][phase]["count"] >= 1
    assert rep["counters"]["amr.cells_refined"][""] == 4
    assert rep["grid"]["n_cells"] == len(g.get_cells())
    assert rep["grid"]["n_devices"] == g.n_devices
    assert rep["grid"]["max_refinement_level"] == 1
    # the accessor is the process-wide registry
    assert g.telemetry is obs.metrics


def test_checkpoint_telemetry(tmp_path):
    obs.metrics.reset()
    obs.enable()
    g = _small_grid(max_ref=0, hood=1, length=(4, 4, 2))
    spec = {"rho": ((), np.float64)}
    st = g.new_state(spec)
    st = g.set_cell_data(st, "rho", g.get_cells(),
                         np.arange(1.0, len(g.get_cells()) + 1))
    path = str(tmp_path / "t.dc")
    g.save_grid_data(st, path, spec)
    m = obs.metrics
    assert m.report()["phases"]["checkpoint.write"]["count"] == 1
    n = len(g.get_cells())
    assert m.counter_value("checkpoint.bytes_written") == n * 8 + n * 16
    from dccrg_tpu.grid import Grid

    g2, st2, _ = Grid.load_grid_data(path, spec)
    assert m.report()["phases"]["checkpoint.read"]["count"] >= 1
    assert m.counter_value("checkpoint.bytes_read") == n * 8
    assert m.counter_value("checkpoint.cells_read") == n


def test_amr_induced_refines_counter():
    """A single refine on a 2-level grid forces 2:1 induction around it
    after the first pass; the repair counter must see the induced set."""
    obs.metrics.reset()
    obs.enable()
    g = _small_grid(max_ref=2, hood=1, length=(8, 8, 1))
    g.refine_completely(int(g.get_cells()[0]))
    g.stop_refining()
    base = obs.metrics.counter_value("amr.induced_refines")
    # refine a level-1 cell twice-removed from its coarse neighbors:
    # committing it drags coarser neighbors along (2:1 repairs)
    lvl = g.mapping.get_refinement_level(g.get_cells())
    fine = g.get_cells()[lvl == 1][0]
    g.refine_completely(int(fine))
    g.stop_refining()
    assert obs.metrics.counter_value("amr.induced_refines") > base
    assert obs.metrics.counter_value("amr.commits") == 2


def test_halo_counters_survive_schedule_retirement():
    """Halo telemetry is buffered per schedule; an epoch rebuild drops
    the schedule (grid._halo_cache cleared) and GC must flush — not
    lose — the pending counts."""
    import gc

    obs.metrics.reset()
    obs.enable()
    g = _small_grid(max_ref=1)
    st = g.new_state({"rho": ((), np.float64)})
    st = g.update_copies_of_remote_neighbors(st)
    moved = int(g.epoch.hoods[None].pair_counts.sum())
    # structural change retires the schedule before any report flushed it
    g.refine_completely(int(g.get_cells()[0]))
    g.stop_refining()
    gc.collect()
    assert obs.metrics.counter_value("halo.cells_moved") == moved


# ------------------------------------------------------- event timeline


def test_timeline_records_registry_phases():
    from dccrg_tpu.obs.events import EventTimeline

    reg = MetricsRegistry()
    tl = EventTimeline(enabled=True)
    reg.timeline = tl
    with reg.phase("outer"):
        with reg.phase("inner"):
            time.sleep(0.005)
    reg.phase_add("halo.exchange", 0.002)
    assert len(tl) == 3
    names = {e["name"] for e in tl.chrome_trace()["traceEvents"]}
    assert names == {"outer", "inner", "halo.exchange"}
    # a disabled registry records nothing into the timeline either
    reg.enabled = False
    with reg.phase("off"):
        pass
    reg.phase_add("off2", 0.001)
    assert len(tl) == 3


def test_timeline_chrome_trace_pairs_and_nesting():
    from dccrg_tpu.obs.events import EventTimeline

    tl = EventTimeline(enabled=True)
    with tl.span("outer", kind="test"):
        with tl.span("inner"):
            time.sleep(0.002)
    trace = tl.chrome_trace()
    evs = trace["traceEvents"]
    # matched B/E pairs in stack order: B outer, B inner, E inner, E outer
    assert [(e["ph"], e["name"]) for e in evs] == [
        ("B", "outer"), ("B", "inner"), ("E", "inner"), ("E", "outer"),
    ]
    ts = [e["ts"] for e in evs]
    assert ts == sorted(ts) and ts[0] >= 0
    assert evs[0]["args"] == {"kind": "test"}
    assert trace["otherData"]["dropped_events"] == 0


def test_timeline_bounded_and_disabled():
    from dccrg_tpu.obs.events import EventTimeline

    tl = EventTimeline(enabled=True, max_events=3)
    for i in range(5):
        tl.add(f"e{i}", float(i), 0.5)
    assert len(tl) == 3
    assert tl.summary()["dropped"] == 2
    tl.clear()
    assert len(tl) == 0 and tl.summary()["dropped"] == 0
    tl.enabled = False
    with tl.span("nope"):
        pass
    tl.add("nope2", 0.0, 1.0)
    assert len(tl) == 0


def test_export_chrome_trace_file_validates(tmp_path):
    """Export -> file -> the check_telemetry schema validator."""
    from dccrg_tpu import obs
    from dccrg_tpu.obs.events import EventTimeline

    sys.path.insert(0, os.path.join(ROOT, "tools"))
    try:
        import check_telemetry
    finally:
        sys.path.pop(0)
    tl = EventTimeline(enabled=True)
    with tl.span("epoch.build"):
        with tl.span("epoch.hood_build"):
            pass
    with tl.span("halo.exchange"):
        pass
    path = tmp_path / "trace.json"
    obs.export_chrome_trace(str(path), tl)
    data = json.loads(path.read_text())
    assert len(data["traceEvents"]) == 6
    assert check_telemetry.validate_chrome_trace(str(path)) == []


# ------------------------------------------------------ streaming export


def test_stream_snapshots_schema_and_final(tmp_path):
    from dccrg_tpu import obs

    reg = MetricsRegistry()
    reg.inc("c", 5)
    path = tmp_path / "s.jsonl"
    with obs.TelemetryStream(str(path), period=3600.0, registry=reg,
                             extra={"workload": "unit"}) as s:
        s.write_snapshot(tag="a")
        reg.inc("c", 2)
        s.write_snapshot()
    lines = [json.loads(l) for l in path.read_text().splitlines()]
    # 2 explicit + 1 final (context exit)
    assert len(lines) == 3
    assert [l["seq"] for l in lines] == [0, 1, 2]
    assert all(a["ts"] <= b["ts"] for a, b in zip(lines, lines[1:]))
    assert lines[0]["tag"] == "a" and lines[0]["workload"] == "unit"
    assert lines[0]["counters"]["c"][""] == 5
    assert lines[1]["counters"]["c"][""] == 7
    assert lines[-1]["final"] is True


def test_stream_periodic_ticker(tmp_path):
    """The daemon ticker really appends between explicit calls — the
    hung-run evidence path."""
    from dccrg_tpu import obs

    reg = MetricsRegistry()
    path = tmp_path / "tick.jsonl"
    s = obs.TelemetryStream(str(path), period=0.05, registry=reg)
    s.start()
    deadline = time.time() + 5.0
    while time.time() < deadline:
        if path.exists() and len(path.read_text().splitlines()) >= 2:
            break
        time.sleep(0.02)
    s.stop(final=False)
    lines = [json.loads(l) for l in path.read_text().splitlines()]
    assert len(lines) >= 2
    assert [l["seq"] for l in lines] == list(range(len(lines)))


def test_stream_validator_rejects_bad_streams(tmp_path):
    sys.path.insert(0, os.path.join(ROOT, "tools"))
    try:
        import check_telemetry
    finally:
        sys.path.pop(0)
    ok = {"seq": 0, "ts": 1.0, "phases": {}, "counters": {"c": {"": 1}},
          "gauges": {}, "histograms": {}}
    good = tmp_path / "good.jsonl"
    good.write_text(
        json.dumps(ok) + "\n"
        + json.dumps({**ok, "seq": 1, "ts": 2.0,
                      "counters": {"c": {"": 3}}}) + "\n"
        # killed mid-write: trailing partial line is tolerated
        + '{"seq": 2, "ts": 3.0, "pha'
    )
    assert check_telemetry.validate_stream(str(good)) == []
    bad_seq = tmp_path / "bad_seq.jsonl"
    bad_seq.write_text(json.dumps(ok) + "\n" + json.dumps(ok) + "\n")
    assert any("seq" in f
               for f in check_telemetry.validate_stream(str(bad_seq)))
    bad_ts = tmp_path / "bad_ts.jsonl"
    bad_ts.write_text(
        json.dumps({**ok, "ts": 9.0}) + "\n"
        + json.dumps({**ok, "seq": 1, "ts": 2.0}) + "\n"
    )
    assert any("ts" in f
               for f in check_telemetry.validate_stream(str(bad_ts)))
    bad_ctr = tmp_path / "bad_ctr.jsonl"
    bad_ctr.write_text(
        json.dumps(ok) + "\n"
        + json.dumps({**ok, "seq": 1, "ts": 2.0,
                      "counters": {"c": {"": 0}}}) + "\n"
    )
    assert any("decreased" in f
               for f in check_telemetry.validate_stream(str(bad_ctr)))
    missing = tmp_path / "missing.jsonl"
    missing.write_text('{"seq": 0, "ts": 1.0}\n')
    assert any("missing keys" in f
               for f in check_telemetry.validate_stream(str(missing)))


def test_trace_validator_rejects_bad_traces(tmp_path):
    sys.path.insert(0, os.path.join(ROOT, "tools"))
    try:
        import check_telemetry
    finally:
        sys.path.pop(0)

    def write(events):
        p = tmp_path / "t.json"
        p.write_text(json.dumps({"traceEvents": events}))
        return str(p)

    b = {"name": "x", "ph": "B", "pid": 1, "tid": 0, "ts": 1.0}
    e = {"name": "x", "ph": "E", "pid": 1, "tid": 0, "ts": 2.0}
    assert check_telemetry.validate_chrome_trace(write([b, e])) == []
    # unmatched begin
    assert any("unmatched" in f for f in
               check_telemetry.validate_chrome_trace(write([b])))
    # E closing the wrong name
    assert any("closes" in f for f in check_telemetry.validate_chrome_trace(
        write([b, {**e, "name": "y"}])))
    # backwards in-thread timestamp
    assert any("backwards" in f
               for f in check_telemetry.validate_chrome_trace(
                   write([{**b, "ts": 5.0}, {**e, "ts": 1.0}])))
    # bare E with empty stack
    assert any("empty stack" in f
               for f in check_telemetry.validate_chrome_trace(write([e])))


# ------------------------------------------------------------ HBM gauges


def test_sample_hbm_records_per_device_gauges():
    from dccrg_tpu import obs

    class FakeDev:
        def __init__(self, i, stats):
            self.id = i
            self._stats = stats

        def memory_stats(self):
            return self._stats

    reg = MetricsRegistry()
    out = obs.sample_hbm(registry=reg, devices=[
        FakeDev(0, {"bytes_in_use": 100, "bytes_limit": 1000}),
        FakeDev(1, None),                      # CPU-style backend
        FakeDev(2, {"bytes_in_use": 300, "peak_bytes_in_use": 400}),
    ])
    assert out == {0: {"bytes_in_use": 100, "bytes_limit": 1000},
                   2: {"bytes_in_use": 300, "peak_bytes_in_use": 400}}
    assert reg.gauge_value("hbm.bytes_in_use", device=0) == 100
    assert reg.gauge_value("hbm.bytes_in_use", device=2) == 300
    assert reg.gauge_value("hbm.peak_bytes_in_use", device=2) == 400
    # disabled registry records nothing
    reg2 = MetricsRegistry(enabled=False)
    assert obs.sample_hbm(registry=reg2, devices=[
        FakeDev(0, {"bytes_in_use": 1})]) == {}
    assert reg2.report()["gauges"] == {}
    # the real backend path must never raise, whatever it reports
    obs.sample_hbm(registry=reg)


# -------------------------------------------- fused-run reconciliation


def test_fused_run_reconciliation_counters():
    """Whole-run dispatches (ghost traffic inside jit) must reconcile
    steps x schedule bytes into fused.* once per run() call."""
    from dccrg_tpu import obs
    from dccrg_tpu.models import GameOfLife

    obs.metrics.reset()
    obs.enable()
    g = _small_grid(max_ref=0, hood=1, length=(8, 8, 1))
    gol = GameOfLife(g)
    st = gol.new_state(alive_cells=[12, 13, 14])
    gol.run(st, 7)
    m = obs.metrics
    path = "fused" if gol._fused_run is not None else "dense"
    assert m.counter_value("fused.runs", model="game_of_life",
                           path=path) == 1
    assert m.counter_value("fused.steps", model="game_of_life",
                           path=path) == 7
    expected = 7 * g.halo(None).bytes_moved({"is_alive": st["is_alive"]})
    assert m.counter_value("fused.halo_bytes_equiv", model="game_of_life",
                           path=path) == expected
    assert expected > 0  # the 8-device board really has a schedule


def test_fused_run_reconciliation_vlasov_and_advection():
    from dccrg_tpu import CartesianGeometry, Grid, make_mesh, obs
    from dccrg_tpu.models import Advection, Vlasov

    obs.metrics.reset()
    obs.enable()
    n = 8
    g = (
        Grid()
        .set_initial_length((n, n, n))
        .set_neighborhood_length(0)
        .set_periodic(True, True, True)
        .set_geometry(
            CartesianGeometry,
            start=(0.0, 0.0, 0.0),
            level_0_cell_length=(1.0 / n,) * 3,
        )
        .initialize(mesh=make_mesh())
    )
    v = Vlasov(g, nv=2, dtype=np.float32, use_pallas=False)
    assert v.info is not None
    s = v.initialize_state()
    v.run(s, 3, np.float32(0.2 * v.max_time_step()))
    m = obs.metrics
    assert m.counter_value("fused.steps", model="vlasov", path="xla") == 3
    # dense slab layout on >1 device: 2 ring planes per device per step
    expected = 3 * g.n_devices * 2 * v.info.ny * v.info.nx * v.B * 4
    assert m.counter_value("fused.halo_bytes_equiv", model="vlasov",
                           path="xla") == expected

    adv = Advection(g, dtype=np.float32, use_pallas=False)
    sa = adv.initialize_state()
    adv.run(sa, 4, np.float32(0.2 * adv.max_time_step(sa)))
    runs = m.report()["counters"].get("fused.runs", {})
    adv_series = {k: v for k, v in runs.items() if "model=advection" in k}
    assert sum(adv_series.values()) == 1, adv_series
    steps = m.report()["counters"]["fused.steps"]
    assert sum(v for k, v in steps.items() if "model=advection" in k) == 4


def test_fused_reconciliation_disabled_records_nothing():
    from dccrg_tpu import obs
    from dccrg_tpu.models import GameOfLife

    obs.metrics.reset()
    obs.disable()
    try:
        g = _small_grid(max_ref=0, hood=1, length=(8, 8, 1))
        gol = GameOfLife(g)
        gol.run(gol.new_state(alive_cells=[12]), 3)
        assert obs.metrics.report()["counters"] == {}
    finally:
        obs.enable()


# ------------------------------------------------------- profiler trace


def test_profile_trace_materializes_trace_dir(tmp_path):
    """obs.profile_trace must actually leave a trace on disk (previously
    only exercised manually via TensorBoard/xprof) — and restore the
    annotation flag after."""
    import jax
    import jax.numpy as jnp

    from dccrg_tpu import obs

    log_dir = tmp_path / "trace"
    prev = obs.metrics.annotate
    with obs.profile_trace(str(log_dir)):
        assert obs.metrics.annotate is True
        jax.block_until_ready(jnp.ones((16, 16)) @ jnp.ones((16, 16)))
        with obs.metrics.phase("trace.probe"):
            pass
    assert obs.metrics.annotate is prev
    files = [p for p in log_dir.rglob("*") if p.is_file()]
    assert files, "profiler trace directory did not materialize"


# --------------------------------------------------------------- CI gate


def test_check_telemetry_tool(tmp_path):
    """The CI gate runs as a plain (not slow) pytest: phase/counter
    completeness, export round-trip, and the overhead ceiling (with
    headroom over the standalone 5% for CI timing noise)."""
    sys.path.insert(0, os.path.join(ROOT, "tools"))
    try:
        import check_telemetry
    finally:
        sys.path.pop(0)
    failures = check_telemetry.run_check(
        str(tmp_path / "telemetry.json"), steps=10, reps=3, threshold=1.5,
    )
    assert failures == []
    data = json.loads((tmp_path / "telemetry.json").read_text())
    for phase in check_telemetry.REQUIRED_PHASES:
        assert phase in data["phases"]
