"""Query/getter surface tests (reference tests/get_cells, constructors,
mpi_support analogues)."""
import numpy as np
import pytest

from dccrg_tpu import Grid, make_mesh
from dccrg_tpu.grid import (
    HAS_LOCAL_NEIGHBOR_OF,
    HAS_LOCAL_NEIGHBOR_TO,
    HAS_REMOTE_NEIGHBOR_OF,
    HAS_REMOTE_NEIGHBOR_TO,
)
from dccrg_tpu.utils.collectives import (
    all_gather,
    all_reduce,
    halo_peers,
    some_reduce,
)


@pytest.fixture
def grid():
    return (
        Grid()
        .set_initial_length((8, 8, 1))
        .set_neighborhood_length(1)
        .initialize(mesh=make_mesh())
    )


def test_criteria_bitmask(grid):
    for d in range(grid.n_devices):
        local = set(grid.local_cells(d).tolist())
        inner = set(grid.inner_cells(d).tolist())
        outer = set(grid.outer_cells(d).tolist())
        with_remote = set(
            grid.get_cells_by_criteria(
                d, HAS_REMOTE_NEIGHBOR_OF | HAS_REMOTE_NEIGHBOR_TO
            ).tolist()
        )
        assert with_remote == outer
        with_local = set(
            grid.get_cells_by_criteria(
                d, HAS_LOCAL_NEIGHBOR_OF | HAS_LOCAL_NEIGHBOR_TO
            ).tolist()
        )
        assert with_local <= local
        # every cell in this grid has some neighbor
        assert not len(grid.get_cells_by_criteria(d, 0))


def test_exact_match(grid):
    d = 0
    # cells matching exactly local-of+local-to and nothing else = inner
    bits = HAS_LOCAL_NEIGHBOR_OF | HAS_LOCAL_NEIGHBOR_TO
    exact = set(grid.get_cells_by_criteria(d, bits, exact_match=True).tolist())
    assert exact == set(grid.inner_cells(d).tolist())


def test_getters(grid):
    assert grid.get_maximum_refinement_level() == 0
    assert grid.get_neighborhood_length() == 1
    assert grid.get_load_balancing_method() == "RCB"
    assert grid.get_periodicity() == (False, False, False)
    assert grid.get_total_cells() == 64
    assert sum(grid.get_local_cell_count(d) for d in range(8)) == 64
    assert grid.get_ghost_cell_count(0) > 0
    grid.set_partitioning_option("IMBALANCE_TOL", "1.05")
    assert grid.get_partitioning_options() == {"IMBALANCE_TOL": "1.05"}


def test_copy_structure(grid):
    g2 = grid.copy_structure()
    np.testing.assert_array_equal(g2.get_cells(), grid.get_cells())
    assert g2.epoch is grid.epoch
    # second payload aligned with the same decomposition
    s1 = grid.new_state({"a": ((), np.float64)})
    s2 = g2.new_state({"b": ((2,), np.int32)})
    assert np.asarray(s2["b"]).shape[:2] == np.asarray(s1["a"]).shape[:2]
    # mutating the copy (rebalance) does not disturb the original
    g2.pin(1, 7)
    g2.balance_load()
    assert int(g2.get_owner(np.uint64(1))) == 7
    assert int(grid.get_owner(np.uint64(1))) == 0
    np.testing.assert_array_equal(g2.get_cells(), grid.get_cells())


def test_collectives(grid):
    vals = np.arange(grid.n_devices, dtype=float)
    assert all_gather(vals) == vals.tolist()
    assert all_reduce(vals) == vals.sum()
    assert all_reduce(vals, op=np.minimum) == 0.0
    peers = halo_peers(grid, 3)
    assert 2 in peers and 4 in peers
    # neighbor-only reduce covers the device and its peers only
    got = some_reduce(grid, vals, 3, op=np.add)
    expect = vals[np.unique(np.concatenate([[3], peers]))].sum()
    assert got == expect
    assert got < vals.sum()
