"""Flat inflated two-level AMR kernel (ops/flat_amr.py) vs the boxed
per-level path: same physics to f32 rounding, exact mass conservation,
working open boundaries."""
import jax.numpy as jnp
import numpy as np
import pytest

from dccrg_tpu import CartesianGeometry, Grid, make_mesh
from dccrg_tpu.models import Advection


def make(periodic=(True, True, True), n=8):
    g = (
        Grid()
        .set_initial_length((n, n, n))
        .set_neighborhood_length(0)
        .set_periodic(*periodic)
        .set_maximum_refinement_level(1)
        .set_geometry(
            CartesianGeometry,
            start=(0.0, 0.0, 0.0),
            level_0_cell_length=(1.0 / n,) * 3,
        )
        .initialize(mesh=make_mesh(n_devices=1))
    )
    ids = g.get_cells()
    c = g.geometry.get_center(ids)
    r = np.linalg.norm(c - 0.45, axis=1)
    for cid in ids[r < 0.28]:
        g.refine_completely(int(cid))
    g.stop_refining()
    return g


def seeded_state(adv, g):
    s0 = adv.initialize_state()
    ids = g.get_cells()
    cen = g.geometry.get_center(ids)
    vz = 0.3 * np.sin(2 * np.pi * cen[:, 2])
    vy = 0.2 + 0.1 * np.cos(2 * np.pi * cen[:, 1])
    s0 = adv.set_cell_data(s0, "vz", ids, vz.astype(np.float32))
    s0 = adv.set_cell_data(s0, "vy", ids, vy.astype(np.float32))
    return s0, ids


def lvl_mass(g, ids, rho):
    lvl = g.mapping.get_refinement_level(ids)
    return float(np.sum(np.asarray(rho, np.float64) * (1.0 / 8.0) ** lvl))


@pytest.mark.parametrize(
    "periodic", [(True, True, True), (True, False, True)]
)
def test_flat_matches_boxed(periodic):
    g = make(periodic)
    flat = Advection(g, dtype=np.float32, use_pallas="interpret")
    boxed = Advection(g, dtype=np.float32, use_pallas=False)
    assert flat._flat_run is not None
    assert getattr(boxed, "_flat_run", None) is None  # gated on use_pallas
    s0, ids = seeded_state(flat, g)
    dt = np.float32(0.3 * flat.max_time_step(s0))

    a = flat.run(s0, 7, dt)  # dispatches to the flat kernel
    b = boxed.run(s0, 7, dt)
    ra = np.asarray(flat.get_cell_data(a, "density", ids), np.float64)
    rb = np.asarray(boxed.get_cell_data(b, "density", ids), np.float64)
    err = np.abs(ra - rb).max() / np.abs(rb).max()
    assert err < 2e-6, err

    m0 = lvl_mass(g, ids, flat.get_cell_data(s0, "density", ids))
    ma = lvl_mass(g, ids, ra)
    assert ma == pytest.approx(m0, rel=1e-6)


def test_flat_open_boundary_differs_from_periodic():
    """The weight-zeroed wrap faces really turn the boundary off."""

    def run(periodic):
        g = make(periodic)
        adv = Advection(g, dtype=np.float32, use_pallas="interpret")
        s0, ids = seeded_state(adv, g)
        dt = np.float32(0.3 * adv.max_time_step(s0))
        out = adv.run(s0, 7, dt)
        return np.asarray(adv.get_cell_data(out, "density", ids))

    ra = run((True, True, True))
    rb = run((True, False, True))
    assert np.abs(ra - rb).max() > 1e-4


def test_flat_gating():
    """f64, uniform grids, and multi-device stay off the flat path."""
    g = make()
    assert getattr(Advection(g), "_flat_run", None) is None  # f64 default

    n = 8
    gu = (
        Grid()
        .set_initial_length((n, n, n))
        .set_neighborhood_length(0)
        .set_periodic(True, True, True)
        .set_geometry(
            CartesianGeometry,
            start=(0.0, 0.0, 0.0),
            level_0_cell_length=(1.0 / n,) * 3,
        )
        .initialize(mesh=make_mesh(n_devices=1))
    )
    adv = Advection(gu, dtype=np.float32, use_pallas="interpret")
    assert adv.dense is not None  # uniform grids take the dense path


@pytest.mark.parametrize("n_dev", [2, 4])
@pytest.mark.parametrize(
    "periodic", [(True, True, True), (True, False, True)]
)
def test_flat_sharded_matches_boxed(n_dev, periodic):
    """The multi-device flat path (z-slab-sharded voxel domain, two
    ppermuted planes per step, collective-free coarse pool) matches the
    boxed path and conserves mass; use_pallas=False opts out to the boxed
    numerics."""
    n = 8
    g = (
        Grid()
        .set_initial_length((n, n, n))
        .set_neighborhood_length(0)
        .set_periodic(*periodic)
        .set_maximum_refinement_level(1)
        .set_geometry(
            CartesianGeometry,
            start=(0.0, 0.0, 0.0),
            level_0_cell_length=(1.0 / n,) * 3,
        )
        .initialize(mesh=make_mesh(n_devices=n_dev))
    )
    ids = g.get_cells()
    c = g.geometry.get_center(ids)
    r = np.linalg.norm(c - 0.45, axis=1)
    for cid in ids[r < 0.28]:
        g.refine_completely(int(cid))
    g.stop_refining()

    flat = Advection(g, dtype=np.float32)
    boxed = Advection(g, dtype=np.float32, use_pallas=False)
    assert flat._flat_run is not None  # engages without Pallas
    assert getattr(boxed, "_flat_run", None) is None  # opt-out honored
    s0, ids = seeded_state(flat, g)
    dt = np.float32(0.3 * flat.max_time_step(s0))
    a = flat.run(s0, 7, dt)
    b = boxed.run(s0, 7, dt)
    ra = np.asarray(flat.get_cell_data(a, "density", ids), np.float64)
    rb = np.asarray(boxed.get_cell_data(b, "density", ids), np.float64)
    assert np.abs(ra - rb).max() / np.abs(rb).max() < 2e-6
    m0 = lvl_mass(g, ids, flat.get_cell_data(s0, "density", ids))
    assert lvl_mass(g, ids, ra) == pytest.approx(m0, rel=1e-6)


def test_flat_sharded_device_count_invariant():
    """1-device (interpret kernel) and 4-device (sharded XLA) flat runs
    agree on the same grid and inputs."""

    def run(n_dev):
        n = 8
        g = (
            Grid()
            .set_initial_length((n, n, n))
            .set_neighborhood_length(0)
            .set_periodic(True, True, True)
            .set_maximum_refinement_level(1)
            .set_geometry(
                CartesianGeometry,
                start=(0.0, 0.0, 0.0),
                level_0_cell_length=(1.0 / n,) * 3,
            )
            .initialize(mesh=make_mesh(n_devices=n_dev))
        )
        ids = g.get_cells()
        c = g.geometry.get_center(ids)
        r = np.linalg.norm(c - 0.45, axis=1)
        for cid in ids[r < 0.28]:
            g.refine_completely(int(cid))
        g.stop_refining()
        adv = Advection(
            g, dtype=np.float32,
            use_pallas="interpret" if n_dev == 1 else True,
        )
        assert adv._flat_run is not None
        s0, ids = seeded_state(adv, g)
        dt = np.float32(0.3 * adv.max_time_step(s0))
        out = adv.run(s0, 7, dt)
        return np.asarray(adv.get_cell_data(out, "density", ids))

    r1 = run(1)
    r4 = run(4)
    np.testing.assert_allclose(r1, r4, rtol=2e-7, atol=1e-9)


def test_flat_run_feeds_adaptation_cycle():
    """A flat-path run's state drives check_for_adaptation/adapt_grid
    without conversion (the run returns the row layout), and the new
    model rebuilds its fast paths for the adapted grid."""
    g = make()
    adv = Advection(g, dtype=np.float32, use_pallas="interpret")
    assert adv._flat_run is not None
    s0, ids = seeded_state(adv, g)
    dt = np.float32(0.3 * adv.max_time_step(s0))
    state = adv.run(s0, 5, dt)
    m0 = lvl_mass(g, ids, adv.get_cell_data(state, "density", ids))

    adv.check_for_adaptation(state)
    adv2, state2, _new, _removed = adv.adapt_grid(state)
    ids2 = adv2.grid.get_cells()
    m1 = lvl_mass(adv2.grid, ids2, adv2.get_cell_data(state2, "density", ids2))
    assert m1 == pytest.approx(m0, rel=1e-5)
    # the new model runs (flat rebuilt if the grid still qualifies,
    # boxed otherwise)
    out = adv2.run(state2, 3, np.float32(0.3 * adv2.max_time_step(state2)))
    m2 = lvl_mass(adv2.grid, ids2, adv2.get_cell_data(out, "density", ids2))
    assert m2 == pytest.approx(m1, rel=1e-5)


def test_pad_lane_extent():
    from dccrg_tpu.ops.flat_amr import pad_lane_extent

    assert pad_lane_extent(128) == 128      # aligned: untouched
    assert pad_lane_extent(256) == 256
    assert pad_lane_extent(96) == 128       # the refined-bench extent
    assert pad_lane_extent(200) == 256
    assert pad_lane_extent(16) == 16        # pad would cost > max_factor
    assert pad_lane_extent(126) == 128      # needs 2 halo columns -> 256?
    # 126 + 2 = 128 exactly: fits the next multiple
    assert pad_lane_extent(127) == 256 or pad_lane_extent(127) == 127


@pytest.mark.parametrize("nx_extra", [2, 6])
@pytest.mark.parametrize(
    "periodic", [(True, True, True), (False, True, True)]
)
def test_flat_padded_kernel_bit_identical(periodic, nx_extra):
    """The lane-padded kernel (explicit wrap-halo columns) reproduces the
    unpadded kernel bit for bit: same operand values reach every flux."""
    from dccrg_tpu.ops.flat_amr import (
        build_flat_amr_tables,
        compute_flat_weights,
        make_flat_amr_run,
    )

    g = make(periodic)
    t = build_flat_amr_tables(g)
    assert t is not None
    nz1, ny1, nx1 = t["shape"]
    adv = Advection(g, dtype=np.float32, use_pallas="interpret")
    s0, ids = seeded_state(adv, g)
    rows = t["rows"]

    def field(name):
        return jnp.asarray(s0[name][0])[rows].reshape(nz1, ny1, nx1)

    V = field("density").astype(jnp.float32)
    (wpx, wnx), (wpy, wny), (wpz, wnz) = compute_flat_weights(
        t, field("vx"), field("vy"), field("vz")
    )
    leaf = t["leaf_fine"]
    updf = jnp.asarray(leaf.astype(np.float64) / t["vol_f"], jnp.float32)
    updc = jnp.asarray((~leaf).astype(np.float64) / t["vol_c"], jnp.float32)
    dt = np.float32(0.3 * adv.max_time_step(s0))

    k0 = make_flat_amr_run(nz1, ny1, nx1, interpret=True)
    kp = make_flat_amr_run(nz1, ny1, nx1, nx_pad=nx1 + nx_extra,
                           interpret=True)
    for steps in (4, 7):  # even + odd (ping-pong final copy)
        a = np.asarray(k0(V, wpx, wnx, wpy, wny, wpz, wnz,
                          updf, updc, dt, steps))
        b = np.asarray(kp(V, wpx, wnx, wpy, wny, wpz, wnz,
                          updf, updc, dt, steps))
        assert np.array_equal(a, b), np.abs(a - b).max()
