"""Neighbor-engine tests against a brute-force geometric oracle
(reference analogues: tests/get_neighbors_, tests/get_face_neighbors)."""
import numpy as np
import pytest

from dccrg_tpu.core import Mapping, Topology
from dccrg_tpu.core.neighborhood import default_neighborhood
from dccrg_tpu.core.neighbors import LeafSet, find_all_neighbors, invert_neighbors


def oracle_neighbors(mapping, topology, leaves, hood, cell):
    """Brute force: for each slot, scan all leaves for coverage of the slot
    region, mirroring find_neighbors_of semantics."""
    lvl = int(mapping.get_refinement_level(np.uint64(cell)))
    idx = mapping.get_indices(np.uint64(cell)).astype(np.int64)
    s = int(mapping.get_cell_length_in_indices(np.uint64(cell)))
    L = np.asarray(mapping.length_in_indices, dtype=np.int64)

    all_idx = mapping.get_indices(leaves).astype(np.int64)
    all_len = mapping.get_cell_length_in_indices(leaves).astype(np.int64)

    out = []
    for h in hood:
        t = idx + np.asarray(h) * s
        ok = True
        for d in range(3):
            if (t[d] < 0 or t[d] >= L[d]) and not topology.periodic[d]:
                ok = False
        if not ok:
            continue
        t_mod = np.mod(t, L)
        # leaves overlapping region [t_mod, t_mod + s - 1]
        hits = np.nonzero(
            ((all_idx <= t_mod) & (t_mod < all_idx + all_len[:, None])).all(axis=1)
            | (
                (t_mod <= all_idx) & (all_idx < t_mod + s)
            ).all(axis=1)
        )[0]
        found = []
        for j in hits:
            nlvl = int(mapping.get_refinement_level(np.uint64(leaves[j])))
            if nlvl >= lvl:  # same or finer: leaf inside slot
                if ((t_mod <= all_idx[j]) & (all_idx[j] < t_mod + s)).all():
                    found.append(j)
            else:  # coarser: slot inside leaf
                if ((all_idx[j] <= t_mod) & (t_mod < all_idx[j] + all_len[j])).all():
                    found.append(j)
        for j in sorted(found, key=lambda j: int(leaves[j])):
            # offset: neighbor corner - cell corner, unwrapped to slot direction
            corner = all_idx[j]
            off = np.asarray(h) * s + (
                np.mod(corner - t_mod, L) if True else corner - t_mod
            )
            # wrap the within-slot/within-coarse displacement to signed form
            within = corner - t_mod
            within = np.mod(within + L // 2, L) - L // 2
            off = np.asarray(h) * s + within
            out.append((int(leaves[j]), tuple(int(v) for v in off)))
    return out


def entries_of(lists, i):
    ids, offs = lists.row(i)
    return [(int(c), tuple(int(v) for v in o)) for c, o in zip(ids, offs)]


def make_leafset(mapping, refine_cells=()):
    """Leaf set = all level-0 cells, with given cells replaced by children."""
    cells = set(range(1, int(np.prod(mapping.length)) + 1))
    for c in refine_cells:
        cells.remove(c)
        for ch in mapping.get_all_children(np.uint64(c)):
            cells.add(int(ch))
    arr = np.array(sorted(cells), dtype=np.uint64)
    return LeafSet(cells=arr, owner=np.zeros(len(arr), dtype=np.int32))


@pytest.mark.parametrize("periodic", [(False,) * 3, (True,) * 3, (True, False, True)])
@pytest.mark.parametrize("hood_len", [0, 1, 2])
def test_uniform_grid_vs_oracle(periodic, hood_len):
    m = Mapping(length=(4, 3, 2), max_refinement_level=0)
    t = Topology(periodic=periodic)
    leaves = make_leafset(m)
    hood = default_neighborhood(hood_len)
    lists = find_all_neighbors(m, t, leaves, hood)
    for i in range(len(leaves)):
        got = entries_of(lists, i)
        want = oracle_neighbors(m, t, leaves.cells, hood, int(leaves.cells[i]))
        assert sorted(got) == sorted(want), f"cell {leaves.cells[i]}"


@pytest.mark.parametrize("periodic", [(False,) * 3, (True,) * 3])
@pytest.mark.parametrize("hood_len", [0, 1])
def test_refined_grid_vs_oracle(periodic, hood_len):
    m = Mapping(length=(3, 3, 3), max_refinement_level=2)
    t = Topology(periodic=periodic)
    # refine the center cell (id 14) - its children abut every level-0 face
    leaves = make_leafset(m, refine_cells=[14])
    hood = default_neighborhood(hood_len)
    lists = find_all_neighbors(m, t, leaves, hood)
    for i in range(len(leaves)):
        got = entries_of(lists, i)
        want = oracle_neighbors(m, t, leaves.cells, hood, int(leaves.cells[i]))
        assert sorted(got) == sorted(want), f"cell {leaves.cells[i]}"


def test_refined_neighbor_expansion_order():
    """A slot covered by finer cells yields all 8 siblings x-fastest."""
    m = Mapping(length=(2, 1, 1), max_refinement_level=1)
    leaves = make_leafset(m, refine_cells=[2])
    t = Topology()
    hood = default_neighborhood(0)
    lists = find_all_neighbors(m, t, leaves, hood)
    # cell 1 (level 0) has +x slot covered by cell 2's children
    i = int(leaves.position(np.uint64(1)))
    ids, offs = lists.row(i)
    children = m.get_all_children(np.uint64(2))
    sel = [(int(c), tuple(map(int, o))) for c, o in zip(ids, offs) if int(c) in set(children.tolist())]
    assert [c for c, _ in sel] == [int(c) for c in children]
    # offsets: +x slot at x=2 (s=2, half=1): {2,3} x {0,1} x {0,1}
    assert sel[0][1] == (2, 0, 0)
    assert sel[1][1] == (3, 0, 0)
    assert sel[4][1] == (2, 0, 1)


def test_coarse_neighbor_appears_once_per_slot():
    m = Mapping(length=(2, 2, 1), max_refinement_level=1)
    leaves = make_leafset(m, refine_cells=[1])
    t = Topology()
    hood = default_neighborhood(1)
    lists = find_all_neighbors(m, t, leaves, hood)
    # a child of cell 1 adjacent to coarse cell 2 sees it via several slots
    ch = m.get_all_children(np.uint64(1))
    i = int(leaves.position(ch[1]))  # child at +x side
    ids, _ = lists.row(i)
    assert (ids == 2).sum() >= 2


def test_periodic_self_neighbor():
    """Length-1 periodic dimension: a cell wraps to itself."""
    m = Mapping(length=(1, 1, 1), max_refinement_level=0)
    t = Topology(periodic=(True, True, True))
    leaves = make_leafset(m)
    lists = find_all_neighbors(m, t, leaves, default_neighborhood(0))
    ids, offs = lists.row(0)
    assert (ids == 1).all() and len(ids) == 6


def test_invert_neighbors_symmetric_on_uniform():
    m = Mapping(length=(3, 3, 1), max_refinement_level=0)
    t = Topology()
    leaves = make_leafset(m)
    lists = find_all_neighbors(m, t, leaves, default_neighborhood(1))
    start, src = invert_neighbors(len(leaves), lists)
    # uniform grid: neighbors_to == neighbors_of set
    for j in range(len(leaves)):
        to_set = set(src[start[j] : start[j + 1]].tolist())
        of_set = set(lists.nbr_pos[lists.start[j] : lists.start[j + 1]].tolist())
        assert to_set == of_set
