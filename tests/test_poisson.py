"""Poisson solver tests vs serial oracles — the reference validates its
parallel solver against a serial implementation
(tests/poisson/reference_poisson_solve.hpp); here the oracles are an
analytic periodic solution and an independently-built dense matrix."""
import numpy as np
import pytest

from dccrg_tpu import CartesianGeometry, Grid, make_mesh
from dccrg_tpu.models.poisson import Poisson


def make_grid(length, max_ref=0, periodic=(True, True, True), cell_len=None, n_dev=None):
    n = np.asarray(length)
    cell_len = cell_len or tuple(1.0 / n)
    return (
        Grid()
        .set_initial_length(length)
        .set_maximum_refinement_level(max_ref)
        .set_neighborhood_length(0)
        .set_periodic(*periodic)
        .set_geometry(CartesianGeometry, start=(0.0, 0.0, 0.0), level_0_cell_length=cell_len)
        .initialize(mesh=make_mesh(n_devices=n_dev))
    )


def dense_matrix_oracle(grid):
    """Independent construction of the system matrix from the reference's
    factor formulas (poisson_solve.hpp:691-822), cell by cell."""
    cells = grid.get_cells()
    pos = {int(c): i for i, c in enumerate(cells)}
    n = len(cells)
    A = np.zeros((n, n))
    lengths = grid.geometry.get_length(cells)
    for i, c in enumerate(cells):
        half = lengths[i] / 2
        offs = {+1: 2 * half[0], -1: -2 * half[0], +2: 2 * half[1],
                -2: -2 * half[1], +3: 2 * half[2], -3: -2 * half[2]}
        present = set()
        fn = grid.get_face_neighbors_of(int(c))
        for nid, d in fn:
            j = pos[int(nid)]
            nh = lengths[j] / 2
            ax = abs(d) - 1
            off = half[ax] + nh[ax]
            offs[d] = off if d > 0 else -off
            present.add(d)
        total = {1: offs[1] - offs[-1], 2: offs[2] - offs[-2], 3: offs[3] - offs[-3]}
        f = {}
        for d in (+1, +2, +3):
            f[d] = 2.0 / (offs[d] * total[d]) if d in present else 0.0
        for d in (-1, -2, -3):
            f[d] = -2.0 / (offs[d] * total[-d]) if d in present else 0.0
        A[i, i] = -sum(f.values())
        for nid, d in fn:
            j = pos[int(nid)]
            m = f[d]
            if lengths[j][0] < lengths[i][0]:  # finer neighbor
                m /= 4.0
            A[i, j] += m
    return A


def test_periodic_1d_analytic():
    n = 32
    g = make_grid((n, 1, 1))
    p = Poisson(g)
    x = g.geometry.get_center(g.get_cells())[:, 0]
    k = 2 * np.pi
    rhs = np.sin(k * x)
    state = p.initialize_state(rhs)
    state, res, it = p.solve(state, max_iterations=2000, stop_residual=1e-12)
    sol = g.get_cell_data(state, "solution", g.get_cells())
    expect = -np.sin(k * x) / k**2
    sol = sol - sol.mean() + expect.mean()
    # second-order accurate on a 32-cell grid
    np.testing.assert_allclose(sol, expect, atol=2e-3)
    assert res < 1e-10


def test_matches_dense_oracle_uniform():
    g = make_grid((6, 6, 1), periodic=(True, True, False))
    p = Poisson(g)
    rng = np.random.default_rng(9)
    rhs = rng.standard_normal(36)
    rhs -= rhs.mean()
    state = p.initialize_state(rhs)
    state, res, it = p.solve(state, max_iterations=500, stop_residual=1e-13)
    sol = g.get_cell_data(state, "solution", g.get_cells())

    A = dense_matrix_oracle(g)
    want, *_ = np.linalg.lstsq(A, rhs, rcond=None)
    np.testing.assert_allclose(sol - sol.mean(), want - want.mean(), atol=1e-8)


def test_refined_operator_matches_oracle():
    """On AMR grids the reference's discretization is non-normal and its
    system can be inconsistent; BiCG then only semi-converges (which the
    reference handles by keeping the min-residual solution,
    poisson_solve.hpp:246-250).  So the oracle check is on the OPERATOR:
    A·v and Aᵀ·v must match the independently built dense matrix exactly."""
    g = make_grid((4, 4, 1), max_ref=1, periodic=(True, True, False))
    g.refine_completely(6)
    g.refine_completely(11)
    g.stop_refining()
    p = Poisson(g)
    cells = g.get_cells()
    pos = g.leaves.position(cells)
    dev, row = g.epoch.global_rows(pos)
    A = dense_matrix_oracle(g)
    rng = np.random.default_rng(2)
    for _ in range(3):
        v = rng.standard_normal(len(cells))
        st = g.new_state(p.spec)
        st = g.set_cell_data(st, "solution", cells, v)
        Ax, _ = p._apply(st["solution"], p._mult_tables()[0])
        np.testing.assert_allclose(np.asarray(Ax)[dev, row], A @ v, atol=1e-12)
        ATx, _ = p._apply(st["solution"], p._mult_tables()[1])
        np.testing.assert_allclose(np.asarray(ATx)[dev, row], A.T @ v, atol=1e-12)


def test_refined_solve_reaches_attainable_residual():
    """The best residual our solver reports must be close to the true
    attainable minimum (lstsq residual) on a refined grid."""
    g = make_grid((4, 4, 1), max_ref=1, periodic=(True, True, False))
    g.refine_completely(6)
    g.refine_completely(11)
    g.stop_refining()
    p = Poisson(g)
    cells = g.get_cells()
    rng = np.random.default_rng(1)
    rhs = rng.standard_normal(len(cells))
    vol = np.prod(g.geometry.get_length(cells), axis=-1)
    rhs -= (rhs * vol).sum() / vol.sum()
    state = p.initialize_state(rhs)
    state, res, it = p.solve(
        state, max_iterations=2000, stop_residual=1e-13,
        stop_after_residual_increase=1e6,
    )

    # BiCG on this singular non-normal system semi-converges then breaks
    # down (dot_r -> 0), as the reference's identical algorithm does; the
    # guarantee is a substantial reduction and an honest best-residual
    # report, not full convergence (the reference tests count failures
    # rather than require them to be zero).
    assert res <= 0.2 * np.linalg.norm(rhs)
    assert p.residual(state) == pytest.approx(res, rel=1e-6, abs=1e-12)


def test_residual_reported():
    g = make_grid((8, 8, 1), periodic=(True, True, False))
    p = Poisson(g)
    rhs = np.zeros(64)
    rhs[0], rhs[-1] = 1.0, -1.0
    state = p.initialize_state(rhs)
    state, res, it = p.solve(state, max_iterations=300, stop_residual=1e-12)
    assert res <= 1e-10
    assert p.residual(state) == pytest.approx(res, rel=1e-3, abs=1e-12)


def test_device_count_invariance():
    sols = []
    for n_dev in (1, 8):
        g = make_grid((8, 4, 1), periodic=(True, True, False), n_dev=n_dev)
        p = Poisson(g)
        x = g.geometry.get_center(g.get_cells())[:, 0]
        state = p.initialize_state(np.cos(2 * np.pi * x))
        state, res, it = p.solve(state, max_iterations=500, stop_residual=1e-13)
        sol = g.get_cell_data(state, "solution", g.get_cells())
        sols.append(sol - sol.mean())
    np.testing.assert_allclose(sols[0], sols[1], atol=1e-10)


def test_skip_cells_embedded_1d():
    """Reference poisson1d_skip_cells.cpp: a 1-D problem embedded in a
    wider grid, with every off-line cell skipped, must solve identically
    to the genuinely 1-D grid (skipped neighbors act as missing)."""
    g1 = make_grid((8, 1, 1), periodic=(True, False, False))
    x1 = g1.geometry.get_center(g1.get_cells())[:, 0]
    rhs_of = lambda x: np.sin(2 * np.pi * x)
    p1 = Poisson(g1)
    s1 = p1.initialize_state(rhs_of(x1) - rhs_of(x1).mean())
    s1, res1, _ = p1.solve(s1, max_iterations=500, stop_residual=1e-13)
    sol1 = g1.get_cell_data(s1, "solution", g1.get_cells())

    g3 = make_grid((8, 3, 1), periodic=(True, False, False),
                   cell_len=(1 / 8, 1.0, 1.0))
    cells = g3.get_cells()
    cy = g3.geometry.get_center(cells)[:, 1]
    line = cells[np.isclose(cy, 1.5)]
    skip = cells[~np.isclose(cy, 1.5)]
    p3 = Poisson(g3, solve_cells=line, skip_cells=skip)
    x3 = g3.geometry.get_center(cells)[:, 0]
    s3 = p3.initialize_state(rhs_of(x3) - rhs_of(x3).mean())
    s3, res3, _ = p3.solve(s3, max_iterations=500, stop_residual=1e-13)
    sol3 = g3.get_cell_data(s3, "solution", line)
    order = np.argsort(g3.geometry.get_center(line)[:, 0])
    np.testing.assert_allclose(
        sol3[order] - sol3.mean(), sol1 - sol1.mean(), atol=1e-9
    )
    # skipped cells are never written
    np.testing.assert_array_equal(g3.get_cell_data(s3, "solution", skip), 0.0)


def test_boundary_cells_dirichlet_1d():
    """Reference poisson1d_boundary.cpp: end cells act as fixed Dirichlet
    data — used by the solver, never updated."""
    n = 32
    L = 2 * np.pi
    g = make_grid((n, 1, 1), periodic=(False, False, False),
                  cell_len=(L / n, 1.0, 1.0))
    cells = g.get_cells()
    x = g.geometry.get_center(cells)[:, 0]
    interior = cells[1:-1]
    bnd = cells[[0, -1]]
    exact = -np.sin(x)
    p = Poisson(g, solve_cells=interior)
    state = p.initialize_state(np.sin(x))
    state = g.set_cell_data(state, "solution", bnd, exact[[0, -1]])
    state, res, _ = p.solve(state, max_iterations=2000, stop_residual=1e-13)
    sol = g.get_cell_data(state, "solution", cells)
    np.testing.assert_array_equal(sol[[0, -1]], exact[[0, -1]])
    np.testing.assert_allclose(sol[1:-1], exact[1:-1], atol=5e-3)


def test_boundary_and_skip_match_dense_oracle():
    """Role-aware dense oracle: the solved block must equal the direct
    solution of A_ss x = rhs_s - A_sb u_b with skip neighbors removed."""
    g = make_grid((6, 4, 1), periodic=(False, False, False),
                  cell_len=(1 / 6, 1 / 4, 1.0))
    cells = g.get_cells()
    centers = g.geometry.get_center(cells)
    skip = cells[(centers[:, 0] > 5 / 6) & (centers[:, 1] > 3 / 4)]
    bnd = cells[centers[:, 0] < 1 / 6]
    sset, bset = set(skip.tolist()), set(bnd.tolist())
    solve = np.array([c for c in cells if int(c) not in sset and int(c) not in bset],
                     dtype=np.uint64)
    p = Poisson(g, solve_cells=solve, skip_cells=skip)

    rng = np.random.default_rng(4)
    rhs = rng.standard_normal(len(cells))
    ub = rng.standard_normal(len(bnd))
    state = p.initialize_state(rhs)
    state = g.set_cell_data(state, "solution", bnd, ub)
    state, res, _ = p.solve(state, max_iterations=1000, stop_residual=1e-13)
    sol = g.get_cell_data(state, "solution", cells)

    # oracle with the same role rules
    pos = {int(c): i for i, c in enumerate(cells)}
    n = len(cells)
    A = np.zeros((n, n))
    lengths = g.geometry.get_length(cells)
    for i, c in enumerate(cells):
        if int(c) in sset:
            continue
        half = lengths[i] / 2
        offs = {+1: 2 * half[0], -1: -2 * half[0], +2: 2 * half[1],
                -2: -2 * half[1], +3: 2 * half[2], -3: -2 * half[2]}
        present = set()
        fn = [(nid, d) for nid, d in g.get_face_neighbors_of(int(c))
              if int(nid) not in sset
              and not (int(c) in bset and int(nid) in bset)]
        for nid, d in fn:
            j = pos[int(nid)]
            nh = lengths[j] / 2
            ax = abs(d) - 1
            off = half[ax] + nh[ax]
            offs[d] = off if d > 0 else -off
            present.add(d)
        total = {1: offs[1] - offs[-1], 2: offs[2] - offs[-2], 3: offs[3] - offs[-3]}
        f = {}
        for d in (+1, +2, +3):
            f[d] = 2.0 / (offs[d] * total[d]) if d in present else 0.0
        for d in (-1, -2, -3):
            f[d] = -2.0 / (offs[d] * total[-d]) if d in present else 0.0
        A[i, i] = -sum(f.values())
        for nid, d in fn:
            A[i, pos[int(nid)]] += f[d]

    si = np.array([pos[int(c)] for c in solve])
    bi = np.array([pos[int(c)] for c in bnd])
    b_eff = rhs[si] - A[np.ix_(si, bi)] @ ub
    want = np.linalg.solve(A[np.ix_(si, si)], b_eff)
    np.testing.assert_allclose(sol[si], want, atol=1e-8)


def test_flat_path_matches_gather_refined():
    """The dense flat-voxel operator (ops/flat_poisson.py) reproduces the
    gather-table solve on a refined single-device grid."""
    g = make_grid((8, 8, 8), max_ref=1, n_dev=1)
    ids = g.get_cells()
    c = g.geometry.get_center(ids)
    r = np.linalg.norm(c - 0.45, axis=1)
    for cid in ids[r < 0.3]:
        g.refine_completely(int(cid))
    g.stop_refining()
    ids = g.get_cells()
    c = g.geometry.get_center(ids)
    rhs = np.sin(2 * np.pi * c[:, 0]) * np.cos(2 * np.pi * c[:, 1])

    p_flat = Poisson(g)
    assert p_flat._flat is not None, "flat path must engage"
    p_gather = Poisson(g, allow_flat=False, allow_rolled=False)
    assert p_gather._flat is None

    s0 = p_flat.initialize_state(rhs)
    out_f, res_f, it_f = p_flat.solve(s0, max_iterations=200,
                                      stop_residual=1e-10)
    out_g, res_g, it_g = p_gather.solve(s0, max_iterations=200,
                                        stop_residual=1e-10)
    assert abs(it_f - it_g) <= 1
    sf = np.asarray(g.get_cell_data(out_f, "solution", ids))
    sg = np.asarray(g.get_cell_data(out_g, "solution", ids))
    np.testing.assert_allclose(sf, sg, rtol=1e-10, atol=1e-12)


@pytest.mark.parametrize("n_dev", [1, 8])
def test_flat_path_three_levels_matches_gather(n_dev):
    """VERDICT-r4 item 3: the flat operator now covers 3+ leaf levels
    (per-voxel sub-face weights 1/4^(vl-level), reshape-pyramid block
    sums).  The matvec must equal the gather path to f64 roundoff —
    the sharp operator-identity test — and the solve to BiCG rounding
    accumulation; the whole-solve Pallas kernel stays gated to 2
    levels."""
    g = make_grid((8, 8, 8), max_ref=2, n_dev=n_dev)
    for rad in (0.3, 0.2):
        ids = g.get_cells()
        c = g.geometry.get_center(ids)
        r = np.linalg.norm(c - 0.5, axis=1)
        lv = g.mapping.get_refinement_level(ids)
        for cid in ids[(r < rad) & (lv == lv.max())]:
            g.refine_completely(int(cid))
        g.stop_refining()
    assert g.mapping.get_refinement_level(g.get_cells()).max() == 2
    ids = np.sort(g.leaves.cells)
    c = g.geometry.get_center(ids)
    rhs = np.sin(2 * np.pi * c[:, 0]) * np.cos(2 * np.pi * c[:, 1])
    rhs -= rhs.mean()

    p_flat = Poisson(g)
    assert p_flat._flat is not None, "flat path must engage at 3 levels"
    assert p_flat._flat_tables["vl"] == 2
    assert p_flat._solve_fast is None
    p_gather = Poisson(g, allow_flat=False, use_pallas=False, allow_rolled=False)

    # operator identity on a random vector, forward and transpose
    rng = np.random.default_rng(1)
    v = rng.standard_normal(len(ids))
    sv = g.set_cell_data(g.new_state({"x": ((), np.float64)}), "x", ids, v)
    fwd, rev, vox, wb, _masks = p_flat._flat
    mf, mr = p_gather._mult_tables()
    for mult, fl in ((mf, fwd), (mr, rev)):
        a_g, _ = p_gather._apply(sv["x"], mult)
        a_f = wb(fl(vox(sv["x"])))
        ag = np.asarray(g.get_cell_data({"x": a_g}, "x", ids))
        af = np.asarray(g.get_cell_data({"x": a_f}, "x", ids))
        np.testing.assert_allclose(af, ag, rtol=1e-13, atol=1e-13)

    # solve-level agreement (dot association differs -> BiCG rounding)
    s0 = p_flat.initialize_state(rhs)
    out_f, _rf, it_f = p_flat.solve(s0, max_iterations=40,
                                    stop_residual=0.0,
                                    stop_after_residual_increase=np.inf)
    out_g, _rg, it_g = p_gather.solve(s0, max_iterations=40,
                                      stop_residual=0.0,
                                      stop_after_residual_increase=np.inf)
    assert it_f == it_g
    sf = np.asarray(g.get_cell_data(out_f, "solution", ids))
    sg = np.asarray(g.get_cell_data(out_g, "solution", ids))
    np.testing.assert_allclose(sf, sg, rtol=1e-6, atol=1e-8)


def test_flat_path_matches_gather_uniform_with_roles():
    """Flat path on a uniform grid with skip and boundary cells: the cell
    role rules (poisson_solve.hpp:896-965) survive the flat folding."""
    g = make_grid((6, 6, 6), periodic=(False, False, False), n_dev=1)
    cells = g.get_cells()
    ctr = g.geometry.get_center(cells)
    # skip a small ball, boundary = domain faces, solve the rest
    skip = cells[np.linalg.norm(ctr - 0.5, axis=1) < 0.17]
    on_face = (
        (ctr < 1.0 / 6).any(axis=1) | (ctr > 5.0 / 6).any(axis=1)
    )
    bnd = cells[on_face & ~np.isin(cells, skip)]
    solve = cells[~on_face & ~np.isin(cells, skip)]
    rng = np.random.default_rng(3)
    rhs = rng.standard_normal(len(cells))

    kw = dict(solve_cells=solve, skip_cells=skip)
    p_flat = Poisson(g, **kw)
    assert p_flat._flat is not None
    p_gather = Poisson(g, allow_flat=False, allow_rolled=False, **kw)

    s0 = p_flat.grid.new_state(p_flat.spec)
    s0 = g.set_cell_data(s0, "rhs", cells, rhs)
    ub = rng.standard_normal(len(bnd))
    s0 = g.set_cell_data(s0, "solution", bnd, ub)

    out_f, _, it_f = p_flat.solve(s0, max_iterations=150,
                                  stop_residual=1e-12)
    out_g, _, it_g = p_gather.solve(s0, max_iterations=150,
                                    stop_residual=1e-12)
    assert abs(it_f - it_g) <= 1
    sf = np.asarray(g.get_cell_data(out_f, "solution", cells))
    sg = np.asarray(g.get_cell_data(out_g, "solution", cells))
    np.testing.assert_allclose(sf, sg, rtol=1e-9, atol=1e-11)


def test_flat_path_periodic_self_coupling():
    """A cell whose periodic neighbor is itself (domain one leaf wide
    along an axis) must keep the self-coupling the reference's factors
    produce — the flat path folds it through the wrap faces."""
    g = make_grid((8, 1, 1), cell_len=(1.0 / 8, 1.0, 1.0), n_dev=1)
    cells = g.get_cells()
    c = g.geometry.get_center(cells)
    rhs = np.sin(2 * np.pi * c[:, 0])

    p_flat = Poisson(g)
    assert p_flat._flat is not None
    p_gather = Poisson(g, allow_flat=False, allow_rolled=False)

    s0 = p_flat.initialize_state(rhs)
    out_f, _, _ = p_flat.solve(s0, max_iterations=100, stop_residual=1e-13)
    out_g, _, _ = p_gather.solve(s0, max_iterations=100, stop_residual=1e-13)
    sf = np.asarray(g.get_cell_data(out_f, "solution", cells))
    sg = np.asarray(g.get_cell_data(out_g, "solution", cells))
    np.testing.assert_allclose(sf, sg, rtol=1e-9, atol=1e-12)

    # and with a coarse leaf spanning a full periodic voxel axis
    g2 = make_grid((8, 2, 1), max_ref=1,
                   cell_len=(1.0 / 8, 0.5, 1.0), n_dev=1)
    ids = g2.get_cells()
    for cid in ids[: 4]:
        g2.refine_completely(int(cid))
    g2.stop_refining()
    ids = g2.get_cells()
    c2 = g2.geometry.get_center(ids)
    rhs2 = np.sin(2 * np.pi * c2[:, 0]) + 0.3 * np.cos(2 * np.pi * c2[:, 1])

    q_flat = Poisson(g2)
    assert q_flat._flat is not None
    q_gather = Poisson(g2, allow_flat=False, allow_rolled=False)
    s2 = q_flat.initialize_state(rhs2)
    o_f, _, _ = q_flat.solve(s2, max_iterations=200, stop_residual=1e-13)
    o_g, _, _ = q_gather.solve(s2, max_iterations=200, stop_residual=1e-13)
    vf = np.asarray(g2.get_cell_data(o_f, "solution", ids))
    vg = np.asarray(g2.get_cell_data(o_g, "solution", ids))
    np.testing.assert_allclose(vf, vg, rtol=1e-9, atol=1e-12)


def test_flat_path_multi_device_invariant():
    """The z-slab-sharded flat operator engages on multi-device meshes
    (ownership = voxel slab partition) and matches the single-device
    solve."""
    def solve(nd):
        g = make_grid((8, 8, 8), max_ref=1, n_dev=nd)
        ids = g.get_cells()
        c = g.geometry.get_center(ids)
        for cid in ids[np.linalg.norm(c - 0.45, axis=1) < 0.3]:
            g.refine_completely(int(cid))
        g.stop_refining()
        ids = g.get_cells()
        c = g.geometry.get_center(ids)
        rhs = np.sin(2 * np.pi * c[:, 0]) * np.cos(2 * np.pi * c[:, 1])
        p = Poisson(g)
        assert p._flat is not None, f"flat path must engage at D={nd}"
        s = p.initialize_state(rhs)
        out, _, it = p.solve(s, max_iterations=100, stop_residual=1e-11)
        return np.asarray(g.get_cell_data(out, "solution", ids)), it

    s1, i1 = solve(1)
    s4, i4 = solve(4)
    assert abs(i1 - i4) <= 1
    np.testing.assert_allclose(s1, s4, rtol=1e-11, atol=1e-14)


@pytest.mark.parametrize("refine", [False, True])
def test_fused_bicg_matches_xla_flat(refine):
    """The whole-solve fused BiCG kernel (ops/poisson_kernel.py, interpret
    mode) reproduces the XLA flat-path solve: same iterations, same
    residual path, solutions equal to f32 rounding."""
    n = 12
    g = make_grid((n, n, n), max_ref=1 if refine else 0, n_dev=1)
    if refine:
        ids = g.get_cells()
        c = g.geometry.get_center(ids)
        for cid in ids[np.linalg.norm(c - 0.5, axis=1) < 0.3]:
            g.refine_completely(int(cid))
        g.stop_refining()
    ids = g.get_cells()
    c = g.geometry.get_center(ids)
    rhs = np.sin(2 * np.pi * c[:, 0]) * np.cos(2 * np.pi * c[:, 1])

    fast = Poisson(g, dtype=np.float32, use_pallas="interpret")
    slow = Poisson(g, dtype=np.float32, use_pallas=False)
    assert fast._solve_fast is not None, "fused solve must engage"
    assert slow._solve_fast is None
    s0 = fast.initialize_state(rhs)
    out_f, res_f, it_f = fast.solve(s0, max_iterations=60,
                                    stop_residual=1e-5)
    # the fallback policy silently swaps in the XLA solver if the kernel
    # raises — assert the fast path actually executed, or the comparison
    # below is XLA vs XLA
    assert fast._solve_fast is not None, "fused solve must have run"
    out_s, res_s, it_s = slow.solve(s0, max_iterations=60,
                                    stop_residual=1e-5)
    # the fused kernel's dot reductions associate differently from XLA's,
    # so a near-threshold stopping decision may flip by one iteration on
    # real hardware
    assert abs(it_f - it_s) <= 1
    sf = np.asarray(g.get_cell_data(out_f, "solution", ids))
    ss = np.asarray(g.get_cell_data(out_s, "solution", ids))
    if it_f == it_s:
        assert res_f == pytest.approx(res_s, rel=1e-5)
        np.testing.assert_allclose(sf, ss, rtol=1e-5, atol=1e-7)
    else:
        # one trajectory took an extra step past the threshold: both
        # must have converged, and to the same field at the tolerance
        assert res_f <= 1e-5 and res_s <= 1e-5
        np.testing.assert_allclose(sf, ss, rtol=1e-3, atol=1e-6)


def test_fused_bicg_gating():
    """f64, multi-device, and no-flat grids stay off the fused solve."""
    g = make_grid((8, 8, 8), n_dev=1)
    assert Poisson(g)._solve_fast is None                  # f64 default
    assert Poisson(g, dtype=np.float32,
                   use_pallas=False)._solve_fast is None   # opt-out
    g2 = make_grid((8, 8, 8), n_dev=4)
    assert Poisson(g2, dtype=np.float32,
                   use_pallas="interpret")._solve_fast is None  # multi-dev


def test_solve_restarts_recover_breakdown():
    """BiCG breakdown recovery: the seed-529 soak configuration (random
    skip cells + mixed periodicity + AMR) stops its flat trajectory at
    ~1e-5 by the semi-convergence rule; solve(restarts=4) rebuilds the
    Krylov space from the best solution and reaches the target, matching
    the reference's re-invoke driver usage."""
    rng = np.random.default_rng(529)
    n = int(rng.choice([4, 6, 8]))
    n_dev = int(rng.choice([1, 2, 4]))
    periodic = tuple(bool(b) for b in rng.integers(0, 2, 3))
    maxref = int(rng.integers(0, 2))
    g = make_grid((n, n, n), periodic=periodic, max_ref=maxref,
                  n_dev=n_dev)
    ids = g.get_cells()
    k = max(1, int(0.2 * len(ids)))
    for cid in rng.choice(ids, size=k, replace=False):
        g.refine_completely(int(cid))
    g.stop_refining()
    cells = g.get_cells()
    rhs = rng.standard_normal(len(cells))
    rng.integers(0, 3)  # mode draw (=1 for this seed)
    kw = {"skip_cells": rng.choice(cells, size=len(cells) // 8 + 1,
                                   replace=False)}
    p = Poisson(g, **kw)
    assert p._flat is not None
    s0 = g.new_state(p.spec)
    s0 = g.set_cell_data(s0, "rhs", cells, rhs - rhs.mean())
    _, res1, it1 = p.solve(s0, max_iterations=60, stop_residual=1e-11)
    assert res1 > 1e-7, "config no longer reproduces the breakdown"
    out, res, it = p.solve(s0, max_iterations=60, stop_residual=1e-11,
                           restarts=4)
    assert res <= 1e-9, (res, it)
    assert it > it1
