"""The perf-regression gate (tools/telemetry_diff.py): verdict logic on
synthetic phase tables (deterministic — no timing in CI), input-shape
loaders, CLI exit codes, and the allowlist knob."""
import json
import os
import sys

import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.dirname(HERE)


@pytest.fixture(scope="module")
def diff():
    sys.path.insert(0, os.path.join(ROOT, "tools"))
    try:
        import telemetry_diff
    finally:
        sys.path.pop(0)
    return telemetry_diff


def _phases(**means):
    """Phase table with count=10 and the given per-phase means."""
    return {
        name: {"total_s": m * 10, "count": 10, "mean_s": m}
        for name, m in means.items()
    }


BASE = _phases(**{
    "halo.exchange": 0.010,
    "epoch.build": 0.020,
    "amr.refine": 0.005,
})


def test_identical_rounds_pass(diff):
    v = diff.compare(BASE, BASE)
    assert v["verdict"] == "PASS"
    assert v["failures"] == []
    assert all(r["status"] in ("ok", "below-noise-floor")
               for r in v["rows"])


def test_regression_fails_and_names_the_phase(diff):
    cur = _phases(**{
        "halo.exchange": 0.020,      # 2.0x: regression
        "epoch.build": 0.021,        # 1.05x: inside threshold
        "amr.refine": 0.005,
    })
    v = diff.compare(cur, BASE, threshold=0.35)
    assert v["verdict"] == "FAIL"
    assert len(v["failures"]) == 1
    assert "halo.exchange" in v["failures"][0]
    by_phase = {r["phase"]: r for r in v["rows"]}
    assert by_phase["halo.exchange"]["status"] == "REGRESSED"
    assert by_phase["halo.exchange"]["ratio"] == pytest.approx(2.0)
    assert by_phase["epoch.build"]["status"] == "ok"


def test_allowlist_knob_suppresses_failure(diff):
    cur = _phases(**{
        "halo.exchange": 0.030,
        "epoch.build": 0.020,
        "amr.refine": 0.005,
    })
    v = diff.compare(cur, BASE, allow=("halo.exchange",))
    assert v["verdict"] == "PASS"
    statuses = {r["phase"]: r["status"] for r in v["rows"]}
    assert statuses["halo.exchange"] == "allowed-regression"


def test_missing_gated_phase_is_coverage_loss(diff):
    cur = _phases(**{"halo.exchange": 0.010, "epoch.build": 0.020})
    v = diff.compare(cur, BASE)
    assert v["verdict"] == "FAIL"
    assert any("amr.refine" in f and "missing" in f for f in v["failures"])
    # ... unless allowlisted
    assert diff.compare(cur, BASE, allow=("amr.refine",))["verdict"] == "PASS"


def test_noise_floor_skips_tiny_phases(diff):
    base = _phases(**{"checkpoint.write": 0.00005})
    cur = _phases(**{"checkpoint.write": 0.00050})  # 10x, but microseconds
    v = diff.compare(cur, base, min_total=1e-3)
    assert v["verdict"] == "PASS"
    assert v["rows"][0]["status"] == "below-noise-floor"


def test_new_and_ungated_phases_inform_only(diff):
    cur = {**BASE, **_phases(**{"brand.new_phase": 5.0})}
    v = diff.compare(cur, BASE)
    assert v["verdict"] == "PASS"
    assert {r["phase"]: r["status"] for r in v["rows"]}[
        "brand.new_phase"] == "new"
    # a phase outside the gated set regresses without failing
    cur2 = dict(BASE)
    cur2 = {**cur2, **_phases(**{"halo.exchange": 0.010,
                                 "epoch.build": 0.020,
                                 "amr.refine": 0.100})}
    v2 = diff.compare(cur2, BASE, phases=("halo.exchange",))
    assert v2["verdict"] == "PASS"
    assert {r["phase"]: r["status"] for r in v2["rows"]}[
        "amr.refine"] == "ungated"


# ----------------------------------------------------------- input shapes


def test_load_phases_all_shapes(diff, tmp_path):
    # telemetry.json shape
    t = tmp_path / "telemetry.json"
    t.write_text(json.dumps({"phases": BASE, "counters": {}}))
    assert diff.load_phases(str(t)) == BASE
    # bench-record shape
    b = tmp_path / "BENCH_DETAIL.json"
    b.write_text(json.dumps(
        {"metric": "x", "detail": {"telemetry": {"phases": BASE}}}))
    assert diff.load_phases(str(b)) == BASE
    # streaming JSONL: the LAST complete snapshot wins, a trailing
    # truncated line (killed mid-write) is skipped
    s = tmp_path / "stream.jsonl"
    early = {"seq": 0, "ts": 1.0, "phases": _phases(**{"halo.exchange": 1.0})}
    late = {"seq": 1, "ts": 2.0, "phases": BASE}
    s.write_text(json.dumps(early) + "\n" + json.dumps(late)
                 + "\n" + '{"seq": 2, "trunc')
    assert diff.load_phases(str(s)) == BASE
    # shape with no phases anywhere
    n = tmp_path / "nothing.json"
    n.write_text(json.dumps({"metric": "x"}))
    with pytest.raises(ValueError):
        diff.load_phases(str(n))


def test_cli_verdict_and_exit_codes(diff, tmp_path):
    base_f = tmp_path / "base.json"
    base_f.write_text(json.dumps({"phases": BASE}))
    cur_pass = tmp_path / "cur_pass.json"
    cur_pass.write_text(json.dumps({"phases": BASE}))
    cur_fail = tmp_path / "cur_fail.json"
    cur_fail.write_text(json.dumps(
        {"phases": _phases(**{"halo.exchange": 0.050,
                              "epoch.build": 0.020,
                              "amr.refine": 0.005})}))
    out = tmp_path / "verdict.json"
    hist = ["--history", str(tmp_path / "history.jsonl")]
    assert diff.main(["--current", str(cur_pass), "--baseline", str(base_f),
                      "--json", str(out)] + hist) == 0
    assert json.loads(out.read_text())["verdict"] == "PASS"
    assert diff.main(["--current", str(cur_fail), "--baseline", str(base_f),
                      "--json", str(out)] + hist) == 1
    rec = json.loads(out.read_text())
    assert rec["verdict"] == "FAIL"
    assert any("halo.exchange" in f for f in rec["failures"])
    # the allowlist flag flips it back to PASS
    assert diff.main(["--current", str(cur_fail), "--baseline", str(base_f),
                      "--allow", "halo.exchange"] + hist) == 0
    # unreadable input is a distinct exit code (2), not a crash
    assert diff.main(["--current", str(tmp_path / "absent.json"),
                      "--baseline", str(base_f)] + hist) == 2


def test_gate_on_repo_telemetry_round_trip(diff, tmp_path):
    """The real repo telemetry.json diffed against itself must PASS —
    the shape the per-round bench gate exercises."""
    tel = os.path.join(ROOT, "telemetry.json")
    if not os.path.exists(tel):
        pytest.skip("no telemetry.json in repo root")
    assert diff.main(["--current", tel, "--baseline", tel,
                      "--no-history"]) == 0


# ------------------------------------------------- history + drift gate


def test_drift_gate_catches_slow_creep(diff):
    """+12% per round stays inside a 35% step threshold forever; the
    cumulative check against the oldest retained round fails it."""
    rounds = [_phases(**{"epoch.delta_build": 0.010 * (1.12 ** i),
                         "halo.exchange": 0.010})
              for i in range(8)]
    # every consecutive pair passes the step gate
    for a, b in zip(rounds, rounds[1:]):
        assert diff.compare(b, a, threshold=0.35)["verdict"] == "PASS"
    v = diff.check_drift(rounds[-1], rounds[0], threshold=0.75)
    assert v["verdict"] == "FAIL"
    assert any("epoch.delta_build" in f and "drift" in f
               for f in v["failures"])
    statuses = {r["phase"]: r["status"] for r in v["rows"]}
    assert statuses["epoch.delta_build"] == "DRIFT"
    assert statuses["halo.exchange"] == "ok"
    # a missing phase is the step gate's business, not drift's
    v2 = diff.check_drift(_phases(**{"halo.exchange": 0.010}), rounds[0])
    assert v2["verdict"] == "PASS"


def test_history_file_rolls_and_feeds_drift(diff, tmp_path):
    hist = tmp_path / "history.jsonl"
    base_f = tmp_path / "base.json"
    base_f.write_text(json.dumps({"phases": BASE}))
    # 12 rounds with slow creep in one phase; keep window of 5
    for i in range(12):
        cur = tmp_path / f"cur{i}.json"
        cur.write_text(json.dumps({"phases": _phases(**{
            "halo.exchange": 0.010,
            "epoch.build": 0.020 * (1.10 ** i),
            "amr.refine": 0.005,
        })}))
        rc = diff.main(["--current", str(cur), "--baseline", str(base_f),
                        "--history", str(hist), "--history-keep", "5",
                        "--allow", "epoch.build"])
        assert rc == 0  # creeping phase allowlisted: gate stays green
    history = diff.load_history(str(hist))
    assert len(history) == 5  # rolled to the retained window
    assert history[-1]["source"].endswith("cur11.json")
    # without the allowlist the drift over the window (1.1^4 = 1.46x
    # at default 1.75x) still passes, but a steeper creep fails
    steep = tmp_path / "steep.json"
    steep.write_text(json.dumps({"phases": _phases(**{
        "halo.exchange": 0.010,
        "epoch.build": 0.200,
        "amr.refine": 0.005,
    })}))
    rc = diff.main(["--current", str(steep), "--baseline", str(steep),
                    "--history", str(hist)])
    assert rc == 1  # cumulative drift vs oldest retained round


def test_gauge_floor_gate(diff, tmp_path):
    """ISSUE 6: overlap.fraction is gated as a FLOOR — a drop below
    (1 - threshold) x baseline fails; rises, vacuous sides and
    zero-baseline values never do; a labeled series vanishing is a
    coverage loss."""
    base = {"overlap.fraction": {"phase=halo": 0.6}}
    assert diff.compare_gauges(
        {"overlap.fraction": {"phase=halo": 0.55}}, base
    )["verdict"] == "PASS"
    assert diff.compare_gauges(
        {"overlap.fraction": {"phase=halo": 0.9}}, base
    )["verdict"] == "PASS"
    bad = diff.compare_gauges(
        {"overlap.fraction": {"phase=halo": 0.2}}, base, threshold=0.35
    )
    assert bad["verdict"] == "FAIL"
    assert "0.2" in bad["failures"][0]
    missing = diff.compare_gauges({"overlap.fraction": {}}, base)
    assert missing["verdict"] == "FAIL"
    assert "coverage loss" in missing["failures"][0]
    assert diff.compare_gauges(None, base)["verdict"] == "PASS"
    assert diff.compare_gauges({}, None)["verdict"] == "PASS"
    assert diff.compare_gauges(
        {}, {"overlap.fraction": {"phase=halo": 0}}
    )["verdict"] == "FAIL"  # label present with value 0 still must exist


def test_load_gauges_shapes(diff, tmp_path):
    tel = tmp_path / "telemetry.json"
    tel.write_text(json.dumps({
        "phases": {}, "counters": {},
        "gauges": {"overlap.fraction": {"phase=halo": 0.5}},
    }))
    assert diff.load_gauges(str(tel)) == {
        "overlap.fraction": {"phase=halo": 0.5}
    }
    stream = tmp_path / "s.jsonl"
    stream.write_text(
        json.dumps({"gauges": {"g": {"": 1}}}) + "\n"
        + json.dumps({"gauges": {"g": {"": 2}}}) + "\n"
    )
    assert diff.load_gauges(str(stream)) == {"g": {"": 2}}  # last line wins
    nothing = tmp_path / "n.json"
    nothing.write_text(json.dumps({"phases": {}}))
    assert diff.load_gauges(str(nothing)) is None
