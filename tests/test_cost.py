"""Cost & capacity plane (ISSUE 17): the online step-cost model (exact
key stats, fallback chain, cross-process merge exactness), the
chargeback ledger's conservation invariant against a real serving
round, the capacity tracker / predicted queue-wait math, the
model-priced ``select_k`` (and its ``DCCRG_COST_MODEL=0`` byte-identity
escape hatch), admission estimates, and the two-tenant burst
calibration the CI probe also gates."""
import importlib.util
import json
import os
import subprocess
import sys

import numpy as np
import pytest

from dccrg_tpu import CartesianGeometry, Grid, make_mesh, obs
from dccrg_tpu.models import Advection
from dccrg_tpu.obs import cost, slo
from dccrg_tpu.serve import Ensemble

HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.dirname(HERE)
COST_PY = os.path.join(ROOT, "dccrg_tpu", "obs", "cost.py")


def make_grid(n=4):
    g = (
        Grid()
        .set_initial_length((n, n, n))
        .set_neighborhood_length(0)
        .set_periodic(True, True, True)
        .set_geometry(CartesianGeometry, start=(0.0, 0.0, 0.0),
                      level_0_cell_length=(1.0 / n,) * 3)
        .initialize(mesh=make_mesh())
    )
    g.stop_refining()
    return g


def make_adv(n=4):
    g = make_grid(n)
    adv = Advection(g, dtype=np.float32, allow_dense=False)
    dt = np.float32(0.4 * adv.max_time_step(adv.initialize_state()))
    return adv, dt


def detached_model() -> cost.StepCostModel:
    """A StepCostModel with no registry attached (pure local store)."""
    m = cost.StepCostModel(registry=False)
    m._registry = None
    return m


@pytest.fixture(autouse=True)
def _fresh_cost_state():
    """Each test starts with an empty process-wide model/tracker and a
    reset registry (the module-level singletons are process-wide)."""
    obs.metrics.reset()
    obs.enable()
    cost.model.reset()
    cost.tracker.reset()
    yield
    cost.model.reset()
    cost.tracker.reset()


# ------------------------------------------------------ model statistics


def test_predict_exact_matches_brute_force():
    """Exact-level estimates reproduce numpy's mean/std exactly and the
    quantiles within one histogram bucket."""
    m = detached_model()
    rng = np.random.default_rng(3)
    vals = rng.lognormal(-6.0, 0.8, size=500)
    for v in vals:
        m.observe("adv", "sigA", 4, 2, 8, float(v))
    est = m.predict("adv", sig="sigA", k=4, g=2, w=8)
    assert est is not None and est.level == "exact"
    assert est.n == len(vals)
    assert est.mean == pytest.approx(float(np.mean(vals)))
    assert est.std == pytest.approx(float(np.std(vals)), rel=1e-6)
    rel = 2.0 ** (1 / cost.COST_RESOLUTION) - 1 + 0.05
    for q, got in ((0.5, est.p50), (0.95, est.p95)):
        true = float(np.quantile(vals, q))
        assert got == pytest.approx(true, rel=rel)
    assert est.q_value == est.p95  # default DCCRG_COST_QUANTILE=0.95


def test_fallback_chain_exact_model_global():
    """predict walks exact -> same-model -> global, labels the level,
    and returns None only on an empty model."""
    m = detached_model()
    assert m.predict("anything") is None
    for v in (0.010, 0.011, 0.012):
        m.observe("adv", "sigA", 4, 2, 8, v)
    exact = m.predict("adv", sig="sigA", k=4, g=2, w=8)
    assert exact.level == "exact" and exact.n == 3
    # same model, different compiled-body key: model-level merge
    other_key = m.predict("adv", sig="sigB", k=1, g=0, w=4)
    assert other_key.level == "model" and other_key.n == 3
    # novel model kind: global merge over everything observed
    novel = m.predict("no-such-kind")
    assert novel.level == "global" and novel.n == 3
    assert novel.mean == pytest.approx(0.011)


def test_export_ingest_equals_pooled():
    """Ingesting two models' exports equals one model observing the
    pooled samples — count, mean, std and quantiles all agree (the
    invariant fleet aggregation rests on)."""
    a, b, pooled = (detached_model() for _ in range(3))
    rng = np.random.default_rng(11)
    for i, v in enumerate(rng.lognormal(-5.5, 0.6, size=400)):
        (a if i % 2 else b).observe("adv", "s", 2, 0, 4, float(v))
        pooled.observe("adv", "s", 2, 0, 4, float(v))
    merged = detached_model()
    merged.ingest(a.export())
    merged.ingest(b.export())
    em, ep = (mm.predict("adv", sig="s", k=2, g=0, w=4)
              for mm in (merged, pooled))
    assert em.level == ep.level == "exact"
    assert em.n == ep.n == 400
    assert em.mean == pytest.approx(ep.mean)
    assert em.std == pytest.approx(ep.std)
    assert em.p95 == pytest.approx(ep.p95)


def test_merge_across_processes_file_loaded():
    """The cross-process form: a subprocess file-loads cost.py (no
    package, no jax) and prints its export; ingesting it here equals
    having observed those samples locally."""
    code = (
        "import importlib.util, json\n"
        "spec = importlib.util.spec_from_file_location('c', %r)\n"
        "c = importlib.util.module_from_spec(spec)\n"
        "spec.loader.exec_module(c)\n"
        "m = c.StepCostModel(registry=False)\n"
        "m._registry = None\n"
        "for i in range(40):\n"
        "    m.observe('adv', 's', 4, 4, 8, 0.001 * (i + 1))\n"
        "print(json.dumps(m.export()))\n" % COST_PY
    )
    out = subprocess.run([sys.executable, "-c", code], check=True,
                         capture_output=True, text=True)
    remote = json.loads(out.stdout.strip().splitlines()[-1])
    local = detached_model()
    for i in range(40):
        local.observe("adv", "s", 4, 4, 8, 0.001 * (i + 1))
    fleet = cost.StepCostModel.from_reports([remote, local.export()])
    est = fleet.predict("adv", sig="s", k=4, g=4, w=8)
    solo = local.predict("adv", sig="s", k=4, g=4, w=8)
    assert est.n == 2 * solo.n
    assert est.mean == pytest.approx(solo.mean)
    assert est.p95 == pytest.approx(solo.p95)


def test_observe_mirrors_into_registry_and_env_off_records_nothing(
        monkeypatch):
    """The dual store: every observation lands in the shared registry's
    cost.step_s series; with DCCRG_COST_MODEL=0 the serving write seam
    (record_dispatch) records nothing anywhere."""
    cost.model.observe("adv", "s", 1, 0, 2, 0.004)
    rep = obs.metrics.report()
    series = rep["histograms"].get(cost.COST_HISTOGRAM)
    assert series and sum(h["count"] for h in series.values()) == 1
    label = cost.key_label("adv", "s", 1, 0, 2)
    assert cost.model.series()[label]["count"] == 1
    kv = cost.parse_label(label)
    assert kv == {"model": "adv", "sig": "s", "k": "1", "g": "0", "w": "2"}


# ------------------------------------------------------------- capacity


def test_tracker_rates_and_window_eviction():
    t = cost.ServiceRateTracker(window_s=10.0)
    t.note({"a": 6, "b": 2}, busy_s=2.0, now=100.0)
    t.note({"a": 2}, busy_s=2.0, now=102.0)
    assert t.rate(now=103.0) == pytest.approx(10 / 4)
    assert t.rate("a", now=103.0) == pytest.approx(8 / 4)
    assert t.rate("b", now=103.0) == pytest.approx(2 / 4)
    assert t.rate("cold", now=103.0) == 0.0
    # the first record ages out of the window: totals follow
    assert t.rate("a", now=111.0) == pytest.approx(2 / 2)
    assert t.rate("b", now=111.0) == 0.0
    # fully idle window
    assert t.rate(now=130.0) == 0.0


def test_predicted_wait_warm_and_cold_tenants():
    """A warm tenant's wait is its backlog over its own rate; a cold
    tenant borrows the fleet rate scaled by backlog share, which equals
    the full FIFO drain time of everything queued."""
    rates = lambda t: {"warm": 4.0, None: 10.0}.get(t, 0.0)  # noqa: E731
    waits = cost.predicted_wait({"warm": 20, "cold": 5, "idle": 0},
                                rates=rates)
    assert waits["warm"] == pytest.approx(20 / 4.0)
    # cold: (fleet_rate * 5/25) drains its 5 steps in 25/10 s
    assert waits["cold"] == pytest.approx(25 / 10.0)
    assert waits["idle"] == 0.0
    # no resolvable rate anywhere: tenants are omitted, not invented
    assert cost.predicted_wait({"x": 3}, rates=lambda t: 0.0) == {}


# ----------------------------------------- serving round: conservation


def test_chargeback_conservation_on_real_round():
    """A real mixed-tenant serving round: per-tenant device-seconds sum
    to the recorded wall x mesh total within one bucket, and every
    submitting tenant appears in the ledger."""
    adv, dt = make_adv()
    ens = Ensemble(steps_per_dispatch=2)
    for i in range(4):
        ens.submit(adv, adv.initialize_state(), steps=4, dt=dt,
                   tenant=f"t{i % 2}")
    ens.run()
    rep = obs.metrics.report()
    cons = cost.conservation(rep)
    assert cons["ok"], cons
    ledger = cost.chargeback(rep)
    assert {"t0", "t1"} <= set(ledger)
    for t in ("t0", "t1"):
        assert ledger[t]["device_s"] > 0
        assert ledger[t]["member_steps"] == 8
    shares = [ledger[t]["device_share"] for t in sorted(ledger)]
    assert sum(shares) == pytest.approx(1.0)


def test_serving_round_trains_model_and_tracker():
    """One ensemble round leaves exact-level samples at the stepped
    compiled-body key and a positive fleet service rate."""
    adv, dt = make_adv()
    ens = Ensemble(steps_per_dispatch=2)
    ens.submit(adv, adv.initialize_state(), steps=4, dt=dt, tenant="m")
    ens.run()
    keys = cost.model.keys()
    assert keys, "no cost samples after a served round"
    kv = cost.parse_label(keys[0])
    est = cost.model.predict(kv["model"], sig=kv["sig"], k=kv["k"],
                             g=kv["g"], w=kv["w"])
    assert est is not None and est.level == "exact" and est.n >= 1
    assert cost.tracker.rate() > 0
    assert cost.tracker.rate("m") > 0


# ------------------------------------------------- select_k consumers


def test_select_k_prices_slack_from_model_quantile():
    """Once the exact key has DCCRG_COST_MIN_SAMPLES samples, select_k
    divides deadline slack by the model's q_value instead of the EMA:
    poisoning the key with huge samples forces depth 1."""
    adv, dt = make_adv()
    ens = Ensemble(steps_per_dispatch=4)
    import time as _time

    ens.submit(adv, adv.initialize_state(), steps=8, dt=dt,
               deadline=_time.perf_counter() + 30.0)
    ens.admit_pending()
    cohort = next(iter(ens.scheduler.cohorts.values()))
    k0 = ens.scheduler.select_k(cohort)
    assert k0 == 4  # EMA empty, generous slack: configured depth
    # 100s/step at the cohort's exact compiled-body key: 30s of slack
    # now affords zero whole steps -> clamped to the floor of 1
    g = cohort._wide_g(4)
    for _ in range(cost.min_samples()):
        cost.model.observe(cohort.spec.kind, cohort.sig_label, 4, g,
                           cohort.W, 100.0)
    assert ens.scheduler.select_k(cohort) == 1


def test_select_k_ignores_model_below_min_samples_and_when_off(
        monkeypatch):
    adv, dt = make_adv()
    ens = Ensemble(steps_per_dispatch=4)
    import time as _time

    ens.submit(adv, adv.initialize_state(), steps=8, dt=dt,
               deadline=_time.perf_counter() + 30.0)
    ens.admit_pending()
    cohort = next(iter(ens.scheduler.cohorts.values()))
    g = cohort._wide_g(4)
    below = max(cost.min_samples() - 1, 1)
    for _ in range(below):
        cost.model.observe(cohort.spec.kind, cohort.sig_label, 4, g,
                           cohort.W, 100.0)
    assert ens.scheduler.select_k(cohort) == 4  # still the EMA path
    for _ in range(cost.min_samples()):
        cost.model.observe(cohort.spec.kind, cohort.sig_label, 4, g,
                           cohort.W, 100.0)
    assert ens.scheduler.select_k(cohort) == 1  # model engages
    monkeypatch.setenv("DCCRG_COST_MODEL", "0")
    assert ens.scheduler.select_k(cohort) == 4  # kill switch restores


def test_results_byte_identical_with_model_on_and_off(monkeypatch):
    """The escape hatch's real guarantee: whatever depths the model
    prices, served results stay bit-identical to the EMA-only
    scheduler's (depth changes batching, never arithmetic)."""
    finals = {}
    import time as _time

    for setting in ("1", "0"):
        monkeypatch.setenv("DCCRG_COST_MODEL", setting)
        cost.model.reset()
        cost.tracker.reset()
        adv, dt = make_adv()
        ens = Ensemble(steps_per_dispatch=2)
        tickets = [
            ens.submit(adv, adv.initialize_state(), steps=4, dt=dt,
                       tenant=f"t{i}",
                       deadline=_time.perf_counter() + 60.0)
            for i in range(2)
        ]
        ens.run()
        finals[setting] = [
            {k: np.asarray(v).tobytes()
             for k, v in sorted(t.result.items())}
            for t in tickets
        ]
    assert finals["1"] == finals["0"]


# ------------------------------------------- admission + calibration


def test_admission_estimates_counted_never_raised():
    """Every submit counts a verdict; a cold model says unknown, a
    poisoned model says late for an impossible deadline — and nothing
    is ever refused (the scenario still runs to completion)."""
    adv, dt = make_adv()
    ens = Ensemble(steps_per_dispatch=2)
    import time as _time

    ens.submit(adv, adv.initialize_state(), steps=2, dt=dt)

    def verdicts():
        rep = obs.metrics.report()
        series = rep["counters"].get("ensemble.admission_estimates", {})
        return {cost.parse_label(lb)["verdict"]: int(v)
                for lb, v in series.items()}

    assert verdicts().get("unknown", 0) == 1  # no deadline, cold model
    for _ in range(cost.min_samples()):
        cost.model.observe(adv.batch_step_spec().kind, "s", 1, 0, 1,
                           100.0)
    t = ens.submit(adv, adv.initialize_state(), steps=2, dt=dt,
                   deadline=_time.perf_counter() + 0.001)
    assert verdicts().get("late", 0) == 1
    ens.run()
    assert t.result is not None  # advice never blocked admission


def test_burst_calibration_within_bucket():
    """The acceptance claim the CI probe also gates: submit-time
    predicted queue-waits for a two-tenant burst into a width-capped
    fleet bracket the measured per-tenant p95 within one
    CALIBRATION_BUCKET.

    Wall-clock-calibrated on an oversubscribed host, so it borrows the
    ``_overhead_probe`` discipline: collect garbage first (a GC pause
    landing inside the burst but not the training wave skews the rate
    the prediction was priced from) and confirm a failed measurement
    with ONE re-measure under fresh tenant labels — a real
    miscalibration fails both attempts."""
    import gc

    def measure(tag):
        adv, dt = make_adv()
        burst = Ensemble(steps_per_dispatch=4, max_width=4)
        for _ in range(4):
            burst.submit(adv, adv.initialize_state(), steps=8, dt=dt,
                         tenant="warm")
        burst.run()                  # compiles the (W=4, k=4) body
        cost.tracker.reset()         # drop compile-inflated timings
        for _ in range(4):
            burst.submit(adv, adv.initialize_state(), steps=8, dt=dt,
                         tenant="warm")
        burst.run()                  # clean wave trains the rate window
        for i in range(16):
            burst.submit(adv, adv.initialize_state(), steps=8, dt=dt,
                         tenant=f"{tag}{i % 2}")
        predicted = {
            cost.parse_label(lb).get("tenant"): float(v)
            for lb, v in (obs.metrics.report()["gauges"]
                          .get("cost.predicted_queue_wait_s") or {}).items()
        }
        burst.run()
        waits = obs.metrics.report()["histograms"]["ensemble.queue_wait_s"]
        rows = []
        for tenant in (f"{tag}0", f"{tag}1"):
            pred = predicted.get(tenant)
            assert pred and pred > 0, f"no submit-time prediction: {tenant}"
            measured = slo.quantile(waits[f"tenant={tenant}"], 0.95)
            assert measured and measured > 0
            rows.append((tenant, pred, measured, pred / measured))
        return rows

    lo, hi = 1.0 / cost.CALIBRATION_BUCKET, cost.CALIBRATION_BUCKET
    gc.collect()
    rows = measure("b")
    if not all(lo <= r[3] <= hi for r in rows):
        gc.collect()
        # fresh labels: the queue-wait histograms are cumulative, so a
        # retry under "b*" would mix both attempts' samples
        rows = measure("c")
    for tenant, pred, measured, ratio in rows:
        assert lo <= ratio <= hi, (
            f"{tenant}: predicted {pred:.4f}s vs measured p95 "
            f"{measured:.4f}s (ratio {ratio:.2f}), confirmed twice")
