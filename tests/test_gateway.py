"""Fleet gateway (ISSUE 19): journal torn-tail replay (the WAL cut at
every record boundary AND mid-record must replay to exactly the clean
prefix with the tear counted, mirroring ``test_checkpoint_hardening``'s
cut-at-every-section sweep), CRC corruption, snapshot compaction,
enforced admission (queue bound, predicted-late, and the
``DCCRG_GATEWAY_ADMISSION=0`` A/B), exactly-once retirement under
duplicate retire reports, worker-loss redispatch from the journaled
watermark, gateway-crash recovery, and the armed cost plane's
``select_k`` queue-wait slack charge (ROADMAP item 3 follow-on (b))
with its byte-identity escape hatch."""
import json
import os
import time
import zlib

import numpy as np
import pytest

from dccrg_tpu import CartesianGeometry, Grid, make_mesh, obs
from dccrg_tpu.models import Advection
from dccrg_tpu.obs import cost
from dccrg_tpu.serve import Ensemble, Gateway, SubmissionJournal, WorkerHandle
from dccrg_tpu.serve.gateway import _append_jsonl, _canon


@pytest.fixture(autouse=True)
def _fresh_state():
    obs.metrics.reset()
    obs.enable()
    cost.model.reset()
    cost.tracker.reset()
    yield
    cost.model.reset()
    cost.tracker.reset()


def counter_total(name: str) -> int:
    rep = obs.metrics.report()
    return int(sum(rep["counters"].get(name, {}).values()))


# ------------------------------------------------------------- journal


#: a representative event tape covering every record type the gateway
#: journals (the cut sweep walks its byte stream)
EVENTS = [
    ("accepted", {"sid": "s0", "model": "gol", "seed": 0, "steps": 8,
                  "tenant": "a"}),
    ("assigned", {"sid": "s0", "worker": "w0"}),
    ("accepted", {"sid": "s1", "model": "advection", "seed": 1,
                  "steps": 6, "tenant": "b"}),
    ("watermark", {"sid": "s0", "step": 4, "park": "/tmp/p0"}),
    ("rejected", {"sid": "s2", "tenant": "a", "reason": "queue-full"}),
    ("redispatched", {"sid": "s0", "worker": "w1", "from_worker": "w0",
                      "step": 4}),
    ("retired", {"sid": "s0", "worker": "w1"}),
]


def _state_of(j: SubmissionJournal):
    return (dict(j.accepted), dict(j.assigned),
            {k: dict(v) for k, v in j.watermark.items()},
            set(j.retired), dict(j.rejected))


def _write_tape(path: str):
    """Append EVENTS, snapshotting the expected state after each record
    (tracked independently of replay, so the sweep's oracle is not the
    code under test)."""
    j = SubmissionJournal(path)
    expected = [_state_of(j)]
    for ev, fields in EVENTS:
        j.append(ev, **fields)
        expected.append(_state_of(j))
    j.close()
    return expected


def test_journal_replay_cut_at_every_boundary_and_midrecord(tmp_path):
    """The WAL cut at any byte: replay reconstructs exactly the state
    of the longest clean record prefix; a partial trailing record is a
    counted tear (``gateway.journal_torn``), never an exception."""
    path = str(tmp_path / "wal.jsonl")
    expected = _write_tape(path)
    raw = open(path, "rb").read()
    # record boundaries: byte offsets just after each newline
    bounds = [0]
    for i, b in enumerate(raw):
        if b == ord("\n"):
            bounds.append(i + 1)
    assert len(bounds) == len(EVENTS) + 1
    cut_path = str(tmp_path / "cut.jsonl")
    for n_rec, off in enumerate(bounds):
        # clean cut AT the boundary: exact prefix, no tear
        open(cut_path, "wb").write(raw[:off])
        jc = SubmissionJournal(cut_path)
        assert _state_of(jc) == expected[n_rec], f"boundary {n_rec}"
        assert jc.torn == 0, f"boundary {n_rec} counted a phantom tear"
        jc.close()
        os.unlink(cut_path)
        if n_rec == len(EVENTS):
            continue
        # torn cut mid-record: previous prefix + one counted tear
        mid = off + max(1, (bounds[n_rec + 1] - off) // 2)
        open(cut_path, "wb").write(raw[:mid])
        jc = SubmissionJournal(cut_path)
        assert _state_of(jc) == expected[n_rec], f"mid-record {n_rec}"
        assert jc.torn == 1, f"mid-record {n_rec} tear not counted"
        jc.close()
        os.unlink(cut_path)


def test_journal_crc_mismatch_ends_the_prefix(tmp_path):
    """A bit-flipped record (newline intact, CRC wrong) ends the
    authoritative prefix: later records are discarded, the tear is
    counted — a torn-then-reused disk region must not resurrect."""
    path = str(tmp_path / "wal.jsonl")
    expected = _write_tape(path)
    lines = open(path, "rb").read().splitlines(keepends=True)
    victim = 3
    rec = json.loads(lines[victim])
    rec["step"] = 999          # payload no longer matches the CRC
    lines[victim] = json.dumps(rec).encode() + b"\n"
    open(path, "wb").write(b"".join(lines))
    before = counter_total("gateway.journal_torn")
    j = SubmissionJournal(path)
    assert _state_of(j) == expected[victim]
    assert j.torn == 1
    assert counter_total("gateway.journal_torn") == before + 1
    j.close()


def test_journal_checkpoint_compacts_and_replays(tmp_path):
    """Snapshot + truncate, then more WAL records: a reopen replays
    snapshot state plus the suffix, and counts the replay."""
    path = str(tmp_path / "wal.jsonl")
    j = SubmissionJournal(path)
    for ev, fields in EVENTS[:4]:
        j.append(ev, **fields)
    j.checkpoint()
    assert os.path.getsize(path) == 0     # WAL compacted into snapshot
    for ev, fields in EVENTS[4:]:
        j.append(ev, **fields)
    full = _state_of(j)
    j.close()
    before = counter_total("gateway.journal_replays")
    j2 = SubmissionJournal(path)
    assert _state_of(j2) == full
    assert counter_total("gateway.journal_replays") == before + 1
    j2.close()
    # snapshot CRC is over canonical bytes: corrupting it is a tear,
    # and the WAL suffix still replays
    snap_path = path + SubmissionJournal.SNAPSHOT_SUFFIX
    snap = json.load(open(snap_path))
    snap["state"]["retired"] = ["forged"]
    json.dump(snap, open(snap_path, "w"))
    j3 = SubmissionJournal(path)
    assert j3.torn == 1
    assert "forged" not in j3.retired
    j3.close()


def test_journal_append_is_canonical_and_crc_stable(tmp_path):
    """Records are canonical JSON with a CRC over the sorted-key
    payload — byte-stable across processes, so replays re-verify."""
    path = str(tmp_path / "wal.jsonl")
    j = SubmissionJournal(path)
    j.append("accepted", sid="x", steps=3, tenant="t")
    j.close()
    rec = json.loads(open(path).read().strip())
    payload = {k: v for k, v in rec.items() if k != "crc"}
    assert rec["crc"] == zlib.crc32(_canon(payload))


# ------------------------------------------------- gateway (fake fleet)


class FakeProc:
    """A worker process stub: alive until killed/terminated."""

    def __init__(self):
        self.rc = None

    def poll(self):
        return self.rc

    def kill(self):
        self.rc = -9

    def wait(self, timeout=None):
        return self.rc

    def terminate(self):
        self.rc = 0


def fake_worker(tmp_path, wid: str) -> WorkerHandle:
    w = WorkerHandle(wid, str(tmp_path / wid), n_devices=1,
                     spawn=FakeProc)
    w.start()
    return w


def test_admission_queue_bound_and_predicted_late(tmp_path, monkeypatch):
    """The ENFORCED edge: a full queue rejects with ``queue-full``; an
    armed rate window rejects a submission whose predicted wait blows
    its own deadline budget with ``predicted-late``; decisions are
    journaled (idempotent under replay) and counted by reason."""
    monkeypatch.setenv("DCCRG_GATEWAY_QUEUE_MAX", "2")
    w = fake_worker(tmp_path, "w0")
    # rate seam: 1 member-step per second for everyone
    gw = Gateway(str(tmp_path / "j.jsonl"), [w], rates=lambda t: 1.0)
    ok, r = gw.submit({"sid": "a0", "model": "gol", "steps": 5,
                       "tenant": "burst"})
    assert ok and r is None
    # 5 queued + 10 own steps at 1 step/s = 15 s wait > 3 s budget
    ok, r = gw.submit({"sid": "a1", "model": "gol", "steps": 10,
                       "tenant": "burst", "deadline_s": 3.0})
    assert (ok, r) == (False, "predicted-late")
    # generous budget passes the same arithmetic
    ok, r = gw.submit({"sid": "a2", "model": "gol", "steps": 10,
                       "tenant": "burst", "deadline_s": 60.0})
    assert ok
    # the queue bound is absolute — even an instant scenario bounces
    ok, r = gw.submit({"sid": "a3", "model": "gol", "steps": 1,
                       "tenant": "vip"})
    assert (ok, r) == (False, "queue-full")
    rep = obs.metrics.report()["counters"].get("gateway.rejected", {})
    assert rep.get("reason=predicted-late") == 1
    assert rep.get("reason=queue-full") == 1
    # journaled decisions replay without re-deciding (or re-counting)
    assert gw.submit({"sid": "a1", "model": "gol", "steps": 10,
                      "tenant": "burst"}) == (False, "predicted-late")
    assert gw.submit({"sid": "a0", "model": "gol", "steps": 5,
                      "tenant": "burst"}) == (True, None)
    gw.close()


def test_admission_off_is_the_ab_baseline(tmp_path, monkeypatch):
    """``DCCRG_GATEWAY_ADMISSION=0``: predicted-late never fires (the
    starvation A/B's baseline arm); only the hard queue bound holds."""
    monkeypatch.setenv("DCCRG_GATEWAY_ADMISSION", "0")
    w = fake_worker(tmp_path, "w0")
    gw = Gateway(str(tmp_path / "j.jsonl"), [w], rates=lambda t: 1.0)
    ok, r = gw.submit({"sid": "a0", "model": "gol", "steps": 10 ** 6,
                       "tenant": "burst", "deadline_s": 0.001})
    assert ok and r is None
    gw.close()


def test_exactly_once_retirement_dedupes_zombie_reports(tmp_path):
    """At-least-once stepping, exactly-once retirement: duplicate
    retire reports (a redispatched member's original worker coming
    back as a zombie) are counted, not double-retired."""
    w = fake_worker(tmp_path, "w0")
    gw = Gateway(str(tmp_path / "j.jsonl"), [w])
    gw.submit({"sid": "s0", "model": "gol", "steps": 4, "tenant": "t",
               "deadline_s": 60.0})
    gw.tick(restart_lost=False)
    assert gw.journal.assigned == {"s0": "w0"}
    for _ in range(3):
        _append_jsonl(w.outbox, {"ev": "retired", "sid": "s0",
                                 "step": 4, "result": "/r0"})
    gw.poll_outboxes()
    assert gw.journal.retired == {"s0"}
    assert counter_total("gateway.retired") == 1
    assert counter_total("gateway.retire_duplicates") == 2
    assert counter_total("gateway.deadline_ok") == 1
    gw.close()


def test_worker_loss_redispatches_from_watermark(tmp_path):
    """A dead worker's in-flight scenarios move to a survivor with the
    journaled watermark (step + park path) in the new assignment; the
    loss and each move are counted."""
    w0 = fake_worker(tmp_path, "w0")
    w1 = fake_worker(tmp_path, "w1")
    gw = Gateway(str(tmp_path / "j.jsonl"), [w0, w1])
    gw.submit({"sid": "s0", "model": "gol", "steps": 10, "tenant": "t"})
    gw.submit({"sid": "s1", "model": "gol", "steps": 10, "tenant": "t"})
    gw.tick(restart_lost=False)
    assert sorted(gw.journal.assigned.values()) == ["w0", "w1"]
    (sid0,) = gw.journal.in_flight("w0")
    _append_jsonl(w0.outbox, {"ev": "watermark", "sid": sid0,
                              "step": 6, "park": "/park0",
                              "busy_s": 0.5})
    gw.poll_outboxes()
    w0.proc.rc = -9                      # SIGKILL
    gw.tick(restart_lost=False)
    assert w0.lost
    assert gw.journal.assigned[sid0] == "w1"
    assert gw.redispatches == [{"sid": sid0, "from": "w0", "to": "w1",
                                "step": 6}]
    # the survivor's inbox carries the resume point
    recs = [json.loads(ln) for ln in open(w1.inbox)]
    moved = [r for r in recs if r["sid"] == sid0]
    assert moved and moved[-1]["resume_step"] == 6
    assert moved[-1]["park"] == "/park0"
    assert counter_total("gateway.worker_lost") == 1
    assert counter_total("gateway.redispatched") == 1
    gw.close()


def test_signature_affinity_routes_to_the_warm_worker(tmp_path):
    """A worker's ``started`` report binds its signature label; later
    same-signature submissions route to it while load allows."""
    w0 = fake_worker(tmp_path, "w0")
    w1 = fake_worker(tmp_path, "w1")
    gw = Gateway(str(tmp_path / "j.jsonl"), [w0, w1])
    gw.submit({"sid": "s0", "model": "gol", "steps": 4, "tenant": "t"})
    gw.tick(restart_lost=False)
    owner = gw.journal.assigned["s0"]
    _append_jsonl(gw.workers[owner].outbox,
                  {"ev": "started", "sid": "s0", "sig": "SIG-A",
                   "step": 0})
    gw.poll_outboxes()
    gw.submit({"sid": "s1", "model": "gol", "steps": 4, "tenant": "t",
               "sig": "SIG-A"})
    gw.tick(restart_lost=False)
    assert gw.journal.assigned["s1"] == owner
    gw.close()


def test_gateway_crash_recovery_reroutes_unretired(tmp_path):
    """A fresh gateway incarnation over the same journal: accepted and
    retired survive replay, every unretired assignment returns to the
    backlog and re-routes to the fresh workers from its watermark."""
    w0 = fake_worker(tmp_path, "w0")
    gw = Gateway(str(tmp_path / "j.jsonl"), [w0])
    gw.submit({"sid": "s0", "model": "gol", "steps": 10, "tenant": "t"})
    gw.submit({"sid": "s1", "model": "gol", "steps": 4, "tenant": "t"})
    gw.tick(restart_lost=False)
    _append_jsonl(w0.outbox, {"ev": "watermark", "sid": "s0", "step": 8,
                              "park": "/park0", "busy_s": 0.1})
    _append_jsonl(w0.outbox, {"ev": "retired", "sid": "s1", "step": 4,
                              "result": "/r1"})
    gw.poll_outboxes()
    gw.journal.close()                   # simulated SIGKILL (no drain)

    w0b = fake_worker(tmp_path, "w0")    # fresh incarnation, same wid
    gw2 = Gateway(str(tmp_path / "j.jsonl"), [w0b])
    assert set(gw2.journal.accepted) == {"s0", "s1"}
    assert gw2.journal.retired == {"s1"}
    assert gw2.journal.assigned == {}    # stale assignments dropped
    assert gw2.journal.backlog() == ["s0"]
    gw2.tick(restart_lost=False)
    assert gw2.journal.assigned == {"s0": "w0"}
    recs = [json.loads(ln) for ln in open(w0b.inbox)]
    assert recs[-1]["sid"] == "s0" and recs[-1]["resume_step"] == 8
    gw2.close()


def test_drain_handback_returns_parked_work_to_backlog(tmp_path):
    """A draining worker's ``handback`` unassigns the scenario and
    preserves its park watermark for the next routing pass."""
    w0 = fake_worker(tmp_path, "w0")
    gw = Gateway(str(tmp_path / "j.jsonl"), [w0])
    gw.submit({"sid": "s0", "model": "gol", "steps": 10, "tenant": "t"})
    gw.tick(restart_lost=False)
    _append_jsonl(w0.outbox, {"ev": "handback", "sid": "s0", "step": 6,
                              "park": "/park0"})
    gw.poll_outboxes()
    assert gw.journal.backlog() == ["s0"]
    assert gw.journal.watermark["s0"] == {"step": 6, "park": "/park0"}
    gw.close()


# ------------------------------- select_k queue-wait charge (item 3 b)


def make_adv(n=4):
    g = (
        Grid()
        .set_initial_length((n, n, n))
        .set_neighborhood_length(0)
        .set_periodic(True, True, True)
        .set_geometry(CartesianGeometry, start=(0.0, 0.0, 0.0),
                      level_0_cell_length=(1.0 / n,) * 3)
        .initialize(mesh=make_mesh())
    )
    g.stop_refining()
    adv = Advection(g, dtype=np.float32, allow_dense=False)
    dt = np.float32(0.4 * adv.max_time_step(adv.initialize_state()))
    return adv, dt


def test_select_k_charges_predicted_wait_when_armed(monkeypatch):
    """ROADMAP item 3 follow-on (b): with the cost model armed, the
    deadline-slack clamp additionally charges the earliest-deadline
    tenant's predicted queue wait; a backlog that eats the slack forces
    depth 1, and ``DCCRG_COST_MODEL=0`` restores the EMA path."""
    monkeypatch.setenv("DCCRG_COST_MIN_SAMPLES", "1")
    adv, dt = make_adv()
    ens = Ensemble(steps_per_dispatch=4)
    ens.submit(adv, adv.initialize_state(), steps=8, dt=dt, tenant="dl",
               deadline=time.perf_counter() + 30.0)
    ens.admit_pending()
    cohort = next(iter(ens.scheduler.cohorts.values()))
    g = cohort._wide_g(4)
    cost.model.observe(cohort.spec.kind, cohort.sig_label, 4, g,
                       cohort.W, 1.0)
    # armed, no backlog: 30 s slack / 1 s/step affords full depth
    assert ens.scheduler.select_k(cohort) == 4
    # 1000 backlogged member-steps at a measured 10 steps/s: 100 s of
    # predicted wait eats the whole slack
    ens.submit(adv, adv.initialize_state(), steps=1000, dt=dt,
               tenant="dl")
    cost.tracker.note({"dl": 10}, 1.0)
    assert ens.scheduler.select_k(cohort) == 1
    # the kill switch restores the EMA-only path byte-for-byte
    monkeypatch.setenv("DCCRG_COST_MODEL", "0")
    assert ens.scheduler.select_k(cohort) == 4


def test_results_byte_identical_with_queue_wait_charge(monkeypatch):
    """The satellite's asserted guarantee: an armed queue-wait charge
    changes only dispatch depth, never served bytes — results with the
    cost plane on (min_samples=1, live tracker) equal the EMA run's."""
    finals = {}
    for setting in ("1", "0"):
        monkeypatch.setenv("DCCRG_COST_MODEL", setting)
        monkeypatch.setenv("DCCRG_COST_MIN_SAMPLES", "1")
        cost.model.reset()
        cost.tracker.reset()
        adv, dt = make_adv()
        ens = Ensemble(steps_per_dispatch=2)
        tickets = [
            ens.submit(adv, adv.initialize_state(), steps=4, dt=dt,
                       tenant=f"t{i}",
                       deadline=time.perf_counter() + 60.0)
            for i in range(3)
        ]
        ens.run()
        finals[setting] = [
            {k: np.asarray(v).tobytes()
             for k, v in sorted(t.result.items())}
            for t in tickets
        ]
    assert finals["1"] == finals["0"]
