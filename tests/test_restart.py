"""Checkpoint/restart round-trip (reference tests/restart: a restarted run
must match the uninterrupted one; files reload with any process count)."""
import numpy as np
import pytest

from dccrg_tpu import CartesianGeometry, Grid, make_mesh
from dccrg_tpu.models import Advection, GameOfLife


def test_save_load_structure_and_data(tmp_path):
    g = (
        Grid()
        .set_initial_length((4, 4, 2))
        .set_maximum_refinement_level(1)
        .set_neighborhood_length(1)
        .set_periodic(True, False, False)
        .set_geometry(
            CartesianGeometry, start=(1.0, 2.0, 3.0), level_0_cell_length=(0.5, 0.5, 2.0)
        )
        .initialize(mesh=make_mesh())
    )
    g.refine_completely(1)
    g.refine_completely(30)
    g.stop_refining()
    spec = {"a": ((), np.float64), "b": ((3,), np.float32)}
    state = g.new_state(spec)
    cells = g.get_cells()
    rng = np.random.default_rng(5)
    av = rng.standard_normal(len(cells))
    bv = rng.standard_normal((len(cells), 3)).astype(np.float32)
    state = g.set_cell_data(state, "a", cells, av)
    state = g.set_cell_data(state, "b", cells, bv)

    path = tmp_path / "ckpt.dc"
    g.save_grid_data(state, str(path), spec, user_header=b"hello-restart")

    for n_dev in (8, 3, 1):
        g2, s2, hdr = Grid.load_grid_data(str(path), spec, mesh=make_mesh(n_devices=n_dev))
        assert hdr == b"hello-restart"
        np.testing.assert_array_equal(g2.get_cells(), cells)
        assert g2.mapping == g.mapping
        assert g2.topology == g.topology
        np.testing.assert_allclose(
            g2.geometry.get_center(cells), g.geometry.get_center(cells)
        )
        np.testing.assert_array_equal(g2.get_cell_data(s2, "a", cells), av)
        np.testing.assert_array_equal(g2.get_cell_data(s2, "b", cells), bv)


def test_restarted_gol_matches_uninterrupted(tmp_path):
    def build():
        g = (
            Grid()
            .set_initial_length((10, 10, 1))
            .set_neighborhood_length(1)
            .initialize(mesh=make_mesh())
        )
        return g, GameOfLife(g)

    alive0 = [54, 55, 56, 12, 13, 22, 77]
    g1, gol1 = build()
    s1 = gol1.new_state(alive_cells=alive0)
    s1 = gol1.run(s1, 10)
    want = set(gol1.alive_cells(s1).tolist())

    g2, gol2 = build()
    s2 = gol2.new_state(alive_cells=alive0)
    s2 = gol2.run(s2, 4)
    path = tmp_path / "gol.dc"
    g2.save_grid_data(s2, str(path), GameOfLife.SPEC)

    g3, s3, _ = Grid.load_grid_data(str(path), GameOfLife.SPEC, mesh=make_mesh(n_devices=3))
    gol3 = GameOfLife(g3)
    s3 = gol3.run(s3, 6)
    assert set(gol3.alive_cells(s3).tolist()) == want


def test_vtk_writer(tmp_path):
    g = (
        Grid()
        .set_initial_length((2, 2, 1))
        .set_maximum_refinement_level(1)
        .initialize(mesh=make_mesh())
    )
    g.refine_completely(1)
    g.stop_refining()
    n = len(g.get_cells())
    rho = np.arange(n)
    # ASCII: eyeball-readable, all sections present
    path = tmp_path / "grid.vtk"
    g.write_vtk_file(str(path), scalars={"rho": rho}, binary=False)
    text = path.read_text()
    assert "UNSTRUCTURED_GRID" in text
    assert f"CELLS {n} {9*n}" in text
    assert "SCALARS rho" in text
    # BINARY (default): same structure, payload decodes to the same data
    pb = tmp_path / "grid_bin.vtk"
    g.write_vtk_file(str(pb), scalars={"rho": rho})
    raw = pb.read_bytes()
    assert b"BINARY" in raw and f"CELLS {n} {9*n}".encode() in raw
    pts_off = raw.index(b"float\n") + len(b"float\n")
    pts = np.frombuffer(raw[pts_off:pts_off + 8 * n * 3 * 4], ">f4")
    mins = g.geometry.get_min(g.get_cells())
    np.testing.assert_allclose(pts.reshape(n, 8, 3)[:, 0], mins, rtol=1e-6)
    sc_off = raw.index(b"LOOKUP_TABLE default\n") + len(
        b"LOOKUP_TABLE default\n"
    )
    got = np.frombuffer(raw[sc_off:sc_off + 4 * n], ">f4")
    np.testing.assert_allclose(got, rho.astype(np.float32))


def test_variable_size_payload_roundtrip(tmp_path):
    """Ragged fields store only count[i] rows per cell (reference:
    variable cell data in files, tests/restart/IO.hpp)."""
    from dccrg_tpu.models import Particles

    g = (
        Grid()
        .set_initial_length((4, 4, 1))
        .set_neighborhood_length(1)
        .set_periodic(True, True, False)
        .set_geometry(
            CartesianGeometry, start=(0.0, 0.0, 0.0),
            level_0_cell_length=(0.25, 0.25, 1.0),
        )
        .initialize(mesh=make_mesh(n_devices=4))
    )
    p = Particles(g, max_particles_per_cell=8)
    rng = np.random.default_rng(11)
    pts = rng.uniform(0.01, 0.99, size=(37, 3)) * [1.0, 1.0, 1.0]
    state = p.new_state(pts)
    spec, ragged = p.spec(), {"particles": "number_of_particles"}

    path = tmp_path / "ragged.dc"
    g.save_grid_data(state, str(path), spec, ragged=ragged)

    # a ragged file must be smaller than the padded-full one
    path_full = tmp_path / "full.dc"
    g.save_grid_data(state, str(path_full), spec)
    assert path.stat().st_size < path_full.stat().st_size

    for n_dev in (2, 8):
        g2, s2, _ = Grid.load_grid_data(
            str(path), spec, ragged=ragged, mesh=make_mesh(n_devices=n_dev)
        )
        p2 = Particles(g2, max_particles_per_cell=8)
        got = np.sort(p2.positions(s2).view("f8,f8,f8"), axis=0)
        want = np.sort(p.positions(state).view("f8,f8,f8"), axis=0)
        np.testing.assert_array_equal(got, want)
        for c in g.get_cells():
            np.testing.assert_array_equal(
                np.sort(p2.particles_of(s2, c), axis=0),
                np.sort(p.particles_of(state, c), axis=0),
            )


def test_chunked_loading(tmp_path):
    """start_/continue_/finish_loading_grid_data parity
    (dccrg.hpp:2085-2368): payloads arrive over repeated calls."""
    g = (
        Grid()
        .set_initial_length((6, 6, 1))
        .set_neighborhood_length(1)
        .initialize(mesh=make_mesh())
    )
    spec = {"v": ((2,), np.float64)}
    cells = g.get_cells()
    vals = np.arange(2 * len(cells), dtype=np.float64).reshape(len(cells), 2)
    state = g.set_cell_data(g.new_state(spec), "v", cells, vals)
    path = tmp_path / "chunk.dc"
    g.save_grid_data(state, str(path), spec, user_header=b"chunked")

    loader = Grid.start_loading_grid_data(str(path), spec, mesh=make_mesh(n_devices=3))
    n_calls = 0
    while loader.continue_loading_grid_data(max_cells=7):
        n_calls += 1
    g2, s2, hdr = loader.finish_loading_grid_data()
    assert n_calls >= 5  # 36 cells / 7 per chunk
    assert hdr == b"chunked"
    np.testing.assert_array_equal(g2.get_cell_data(s2, "v", cells), vals)


@pytest.mark.parametrize("seed", [3, 11])
def test_fuzz_checkpoint_roundtrip_random_grids(seed):
    """Randomized checkpoint round trip: random multi-level AMR grid and
    payloads, saved at one device count and reloaded at another, must
    reproduce structure and payloads bitwise and advect in lockstep with
    the original (to f64 cross-layout fusion tolerance)."""
    import os
    import tempfile

    from dccrg_tpu.models import Advection

    rng = np.random.default_rng(seed)
    n = int(rng.choice([4, 6]))
    nd_a = int(rng.choice([1, 2, 4]))
    nd_b = int(rng.choice([1, 3, 8]))
    periodic = tuple(bool(b) for b in rng.integers(0, 2, 3))
    max_lvl = int(rng.choice([1, 2]))
    g = (
        Grid()
        .set_initial_length((n, n, n))
        .set_neighborhood_length(0)
        .set_periodic(*periodic)
        .set_maximum_refinement_level(max_lvl)
        .set_geometry(
            CartesianGeometry,
            start=(0.0, 0.0, 0.0),
            level_0_cell_length=(1.0 / n,) * 3,
        )
        .initialize(mesh=make_mesh(n_devices=nd_a))
    )
    for _ in range(max_lvl):
        ids = g.get_cells()
        for cid in rng.choice(ids, size=max(1, len(ids) // 5),
                              replace=False):
            g.refine_completely(int(cid))
        g.stop_refining()
    ids = g.get_cells()
    adv = Advection(g)
    s = adv.initialize_state()
    s = adv.set_cell_data(s, "density", ids, rng.uniform(1, 2, len(ids)))
    for f in ("vx", "vy", "vz"):
        s = adv.set_cell_data(s, f, ids, rng.uniform(-0.2, 0.2, len(ids)))
    s = g.update_copies_of_remote_neighbors(s)
    spec = {k: adv.spec[k] for k in ("density", "vx", "vy", "vz")}
    with tempfile.TemporaryDirectory() as tmp:
        path = os.path.join(tmp, "f.dc")
        g.save_grid_data(s, path, spec)
        g2, s2, _ = Grid.load_grid_data(path, spec, n_devices=nd_b)
    np.testing.assert_array_equal(g2.get_cells(), ids)
    for f in spec:
        np.testing.assert_array_equal(
            g2.get_cell_data(s2, f, ids), g.get_cell_data(s, f, ids)
        )
    adv2 = Advection(g2)
    full2 = adv2.initialize_state()
    for f in spec:
        full2 = adv2.set_cell_data(full2, f, ids, g2.get_cell_data(s2, f, ids))
    full2 = g2.update_copies_of_remote_neighbors(full2)
    dt = 0.3 * adv.max_time_step(s)
    a, b = s, full2
    for _ in range(2):
        a = adv.step(a, dt)
        b = adv2.step(b, dt)
    np.testing.assert_allclose(
        np.asarray(adv.get_cell_data(a, "density", ids)),
        np.asarray(adv2.get_cell_data(b, "density", ids)),
        rtol=1e-13, atol=0,
    )


def test_leaf_set_initialize_validates():
    """Direct leaf-set construction (the loader's path) rejects corrupt
    sets: duplicates, holes, and 2:1 violations all raise."""
    from dccrg_tpu import Grid, make_mesh

    def fresh():
        return (
            Grid()
            .set_initial_length((4, 4, 4))
            .set_maximum_refinement_level(2)
            .set_neighborhood_length(1)
        )

    base = np.arange(1, 65, dtype=np.uint64)

    # valid: one cell refined one level
    g0 = fresh().initialize(mesh=make_mesh(n_devices=1))
    kids = g0.mapping.get_all_children(np.uint64(1))
    ok = np.concatenate([base[1:], kids]).astype(np.uint64)
    g = fresh().initialize(mesh=make_mesh(n_devices=1), leaf_set=ok)
    assert len(g.get_cells()) == 63 + 8

    with pytest.raises(ValueError, match="duplicate"):
        fresh().initialize(
            mesh=make_mesh(n_devices=1),
            leaf_set=np.concatenate([base, base[:1]]),
        )
    with pytest.raises(ValueError, match="tile"):
        fresh().initialize(mesh=make_mesh(n_devices=1), leaf_set=base[1:])
    # 2:1 violation: a level-2 family island inside level-0 neighbors
    grandkids = np.concatenate(
        [g0.mapping.get_all_children(k) for k in kids]
    ).astype(np.uint64)
    bad = np.concatenate([base[1:], grandkids])
    with pytest.raises(ValueError, match="2:1|consistent"):
        fresh().initialize(mesh=make_mesh(n_devices=1), leaf_set=bad)
    # compensating overlap+hole: cell 1 AND its children present (one
    # extra level-0 volume) while cell 2 is absent (one missing) — the
    # integer volume sum matches, only the ancestor screen catches it
    overlap = np.concatenate([base[0:1], base[2:], kids]).astype(np.uint64)
    with pytest.raises(ValueError, match="ancestor"):
        fresh().initialize(mesh=make_mesh(n_devices=1), leaf_set=overlap)
    # deep inconsistency that passes both the volume sum and the
    # ancestor screen: cell 2's slot holds 7 children plus the 8
    # grandchildren of the missing child — caught only by the neighbor
    # engine, which must still surface it as the documented ValueError
    kids2 = g0.mapping.get_all_children(np.uint64(2))
    gkids = g0.mapping.get_all_children(kids2[0])
    deep = np.concatenate([base[:1], base[2:], kids2[1:], gkids])
    with pytest.raises(ValueError, match="consistent"):
        fresh().initialize(
            mesh=make_mesh(n_devices=1), leaf_set=deep.astype(np.uint64)
        )
