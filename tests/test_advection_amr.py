"""Advection with dynamic AMR — the reference's tests/advection 2d.cpp flow:
initialize, pre-adapt around the hump, then step/adapt, checking mass
conservation and 2:1 balance throughout."""
import numpy as np
import pytest

from dccrg_tpu import CartesianGeometry, Grid, make_mesh
from dccrg_tpu.models import Advection

from test_amr import check_two_to_one


def make(n=10, max_ref=2, n_dev=None):
    g = (
        Grid()
        .set_initial_length((n, n, 1))
        .set_maximum_refinement_level(max_ref)
        .set_neighborhood_length(0)
        .set_periodic(True, True, False)
        .set_geometry(
            CartesianGeometry,
            start=(0.0, 0.0, 0.0),
            level_0_cell_length=(1.0 / n, 1.0 / n, 1.0 / n),
        )
        .initialize(mesh=make_mesh(n_devices=n_dev))
    )
    return g, Advection(g, allow_dense=False)


def test_initial_adaptation_refines_hump_edge():
    g, adv = make()
    state = adv.initialize_state()
    n0 = len(g.get_cells())
    state = adv.check_for_adaptation(state)
    adv, state, new_cells, removed = adv.adapt_grid(state)
    assert len(new_cells) > 0
    assert len(g.get_cells()) > n0
    check_two_to_one(g)
    # refined cells cluster near the hump edge (x in [0.1, 0.4])
    centers = g.geometry.get_center(new_cells)
    assert (np.abs(centers[:, 0] - 0.25) < 0.3).all()


def test_amr_run_conserves_mass():
    g, adv = make(n=8, max_ref=1)
    state = adv.initialize_state()
    # pre-adaptation rounds like 2d.cpp:267-289
    for _ in range(1):
        state = adv.check_for_adaptation(state)
        adv, state, _, _ = adv.adapt_grid(state)
    m0 = adv.total_mass(state)
    dt = 0.25 * adv.max_time_step(state)
    for step in range(6):
        state = adv.step(state, dt)
        state = adv.check_for_adaptation(state)
        adv, state, _, _ = adv.adapt_grid(state)
        check_two_to_one(g)
    # unrefinement averaging loses no mass; refinement inheritance neither
    assert adv.total_mass(state) == pytest.approx(m0, rel=1e-10)
    # density field stays sane
    rho = adv.get_cell_data(state, "density", g.get_cells())
    assert (rho >= -1e-12).all()
    assert rho.max() <= 0.51


def test_amr_structure_device_count_invariant():
    structs = []
    for n_dev in (1, 8):
        g, adv = make(n=8, max_ref=1, n_dev=n_dev)
        state = adv.initialize_state()
        state = adv.check_for_adaptation(state)
        adv, state, _, _ = adv.adapt_grid(state)
        dt = 0.25 * adv.max_time_step(state)
        state = adv.step(state, dt)
        state = adv.check_for_adaptation(state)
        adv, state, _, _ = adv.adapt_grid(state)
        structs.append(g.get_cells())
    np.testing.assert_array_equal(structs[0], structs[1])


@pytest.mark.parametrize(
    "periodic", [(True, True, False), (False, False, False)]
)
def test_dense_max_diff_matches_general_path(periodic):
    """The dense-layout AMR indicator (shifted slices + slab ring) computes
    exactly the general gather path's values — the fast path can feed
    check_for_adaptation without a rebuild (adapter.hpp:71-110 runs on the
    solver's own data).  Both periodic and open x/y exercise the
    boundary-face masks against the general path."""
    def build(dense):
        g = (
            Grid()
            .set_initial_length((8, 8, 8))
            .set_maximum_refinement_level(1)
            .set_neighborhood_length(0)
            .set_periodic(*periodic)
            .set_geometry(
                CartesianGeometry,
                start=(0.0, 0.0, 0.0),
                level_0_cell_length=(0.125, 0.125, 0.125),
            )
            .initialize(mesh=make_mesh(n_devices=8))
        )
        return g, Advection(g, allow_dense=dense)

    gd, advd = build(True)
    gg, advg = build(False)
    assert advd.dense is not None and advg.dense is None
    sd = advd.initialize_state()
    sg = advg.initialize_state()
    sd = advd.compute_max_diff(sd, 0.25)
    sg = advg.compute_max_diff(sg, 0.25)
    cells = gd.get_cells()
    np.testing.assert_allclose(
        advd.get_cell_data(sd, "max_diff", cells),
        advg.get_cell_data(sg, "max_diff", cells),
        rtol=1e-12, atol=1e-14,
    )


def test_dense_path_drives_amr_to_first_refine():
    """AMR driver runs on the dense fast path until the first refinement
    commits; adapt_grid converts the z-slab state to the row layout and
    hands over to the general path with mass intact."""
    g = (
        Grid()
        .set_initial_length((10, 10, 1))
        .set_maximum_refinement_level(2)
        .set_neighborhood_length(0)
        .set_periodic(True, True, False)
        .set_geometry(
            CartesianGeometry,
            start=(0.0, 0.0, 0.0),
            level_0_cell_length=(0.1, 0.1, 0.1),
        )
        .initialize(mesh=make_mesh(n_devices=1))
    )
    adv = Advection(g)
    assert adv.dense is not None
    state = adv.initialize_state()
    dt = 0.25 * adv.max_time_step(state)
    state = adv.run(state, 3, dt)
    m0 = adv.total_mass(state)
    state = adv.check_for_adaptation(state)
    adv2, state, new_cells, removed = adv.adapt_grid(state)
    assert len(new_cells) > 0
    assert adv2.dense is None
    check_two_to_one(g)
    assert adv2.total_mass(state) == pytest.approx(m0, rel=1e-10)
    # and the handed-over state keeps stepping
    state = adv2.step(state, 0.25 * adv2.max_time_step(state))
    assert adv2.total_mass(state) == pytest.approx(m0, rel=1e-10)


def test_noop_adapt_keeps_dense_path():
    """An adapt cycle that queues nothing must not degrade the model off
    the dense fast path."""
    g = (
        Grid()
        .set_initial_length((8, 8, 8))
        .set_maximum_refinement_level(1)
        .set_neighborhood_length(0)
        .set_periodic(True, True, True)
        .set_geometry(
            CartesianGeometry,
            start=(0.0, 0.0, 0.0),
            level_0_cell_length=(0.125, 0.125, 0.125),
        )
        .initialize(mesh=make_mesh(n_devices=8))
    )
    adv = Advection(g)
    assert adv.dense is not None
    state = adv.initialize_state()
    m0 = adv.total_mass(state)
    # no check_for_adaptation: queues are empty
    adv2, state, new_cells, removed = adv.adapt_grid(state)
    assert len(new_cells) == 0 and len(removed) == 0
    # no structural change: the SAME model (tables, compiled kernels) is
    # returned — no rebuild, no recompile
    assert adv2 is adv
    assert adv2.dense is not None
    assert adv2.total_mass(state) == pytest.approx(m0, rel=1e-12)
    state = adv2.step(state, 0.25 * adv2.max_time_step(state))
    assert adv2.total_mass(state) == pytest.approx(m0, rel=1e-10)
