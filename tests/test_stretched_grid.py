"""End-to-end workloads on a stretched Cartesian geometry (the reference
exercises stretched grids in tests/poisson and tests/geometry)."""
import numpy as np

from dccrg_tpu import Grid, StretchedCartesianGeometry, make_mesh
from dccrg_tpu.models.poisson import Poisson


def make_stretched(nx=12, n_dev=None):
    # geometrically stretched x, uniform y/z
    bounds_x = np.cumsum(np.concatenate([[0.0], 1.06 ** np.arange(nx)]))
    bounds_x /= bounds_x[-1]
    return (
        Grid()
        .set_initial_length((nx, 6, 1))
        .set_neighborhood_length(0)
        .set_periodic(False, True, False)
        .set_geometry(
            StretchedCartesianGeometry,
            coordinates=(
                bounds_x,
                np.linspace(0.0, 1.0, 7),
                np.array([0.0, 1.0]),
            ),
        )
        .initialize(mesh=make_mesh(n_devices=n_dev))
    )


def test_grid_on_stretched_geometry():
    g = make_stretched()
    cells = g.get_cells()
    lengths = g.geometry.get_length(cells)
    # x lengths grow monotonically along x
    idx = g.mapping.get_indices(cells)
    order = np.argsort(idx[:, 0])
    lx = lengths[order][:, 0]
    row = lx[idx[order, 1] == 0][: 12]
    assert (np.diff(row) > 0).all()
    # coordinate queries invert correctly
    centers = g.geometry.get_center(cells)
    got = g.get_existing_cell(centers)
    np.testing.assert_array_equal(got, cells)


def test_poisson_on_stretched_grid():
    """The variable-spacing factors (poisson_solve.hpp:691-822 semantics)
    must reproduce an analytic solution on a stretched grid."""
    g = make_stretched(nx=24)
    p = Poisson(g)
    cells = g.get_cells()
    x = g.geometry.get_center(cells)[:, 0]
    # the discrete operator is the plain Laplacian (A.u ~ u''), so for
    # u = cos(pi x) (zero-flux at the Neumann walls x=0,1):
    rhs = -np.pi**2 * np.cos(np.pi * x)
    state = p.initialize_state(rhs)
    state, res, it = p.solve(state, max_iterations=3000, stop_residual=1e-12)
    sol = g.get_cell_data(state, "solution", cells)
    expect = np.cos(np.pi * x)
    sol = sol - sol.mean() + expect.mean()
    # second order in the local spacing; stretched 24-cell grid
    np.testing.assert_allclose(sol, expect, atol=5e-2)
    # the discrete Neumann system is slightly inconsistent on a stretched
    # grid (non-self-adjoint factors), leaving a small residual floor
    assert res < 0.05 * np.linalg.norm(rhs)


def test_halo_exchange_on_stretched(tmp_path):
    g = make_stretched()
    spec = {"v": ((), np.float64)}
    state = g.new_state(spec)
    cells = g.get_cells()
    state = g.set_cell_data(state, "v", cells, cells.astype(np.float64))
    from dccrg_tpu.utils import verify_user_data

    verify_user_data(g, state, spec)
    # checkpoint round-trip keeps the stretched geometry
    g.save_grid_data(state, str(tmp_path / "s.dc"), spec)
    g2, s2, _ = Grid.load_grid_data(str(tmp_path / "s.dc"), spec, n_devices=3)
    np.testing.assert_allclose(
        g2.geometry.get_center(cells), g.geometry.get_center(cells)
    )
    np.testing.assert_array_equal(g2.get_cell_data(s2, "v", cells), cells.astype(np.float64))


def test_advection_on_stretched_geometry():
    """A uniform-level stretched grid must NOT take the dense fast path
    (its metric factors assume one cell size); the general path runs with
    per-cell geometry and conserves mass, device-count invariant."""
    from dccrg_tpu.models import Advection

    n = 8
    xs = np.cumsum(np.r_[0, 1.1 ** np.arange(n)])
    xs /= xs[-1]

    def run(n_dev):
        g = (
            Grid()
            .set_initial_length((n, n, n))
            .set_neighborhood_length(0)
            .set_periodic(True, True, True)
            .set_geometry(
                StretchedCartesianGeometry,
                coordinates=(xs, np.linspace(0, 1, n + 1),
                             np.linspace(0, 1, n + 1)),
            )
            .initialize(mesh=make_mesh(n_devices=n_dev))
        )
        adv = Advection(g, dtype=np.float64)
        assert adv.dense is None, "dense path must not engage on stretched"
        s = adv.initialize_state()
        ids = g.get_cells()
        vol = np.prod(g.geometry.get_length(ids), axis=1)
        dt = np.float64(0.4 * adv.max_time_step(s))
        m0 = float((np.asarray(g.get_cell_data(s, "density", ids)) * vol).sum())
        out = adv.run(s, 20, dt)
        dens = np.asarray(g.get_cell_data(out, "density", ids))
        m1 = float((dens * vol).sum())
        assert abs(m1 - m0) <= 1e-12 * max(m0, 1.0)
        return dens

    d1 = run(1)
    d4 = run(4)
    np.testing.assert_allclose(d1, d4, rtol=0, atol=1e-13)
