"""Deep dispatch (ISSUE 11): k-steps-per-call cohort bodies, buffer
donation and broadcast-shared tables.

The contracts under test: a depth-k dispatch is bit-identical to k solo
steps (through the solo-replay oracle, including members retiring
mid-k-block and heterogeneous-grid cohorts); occupancy churn at a held
(signature, width, k) retraces nothing and changing ONLY k compiles
exactly one new body; donating the stacked state never corrupts a
member the oracle replays; the scheduler's k selection clamps to
per-member remaining budgets and to deadline slack; the per-member HBM
gauge measures the shared-table win and the ``telemetry_diff`` ceiling
gate watches it; ``request.step`` spans and ``ensemble.steps_served``
stay exact when one dispatch advances k steps."""
import numpy as np
import pytest

import jax

from dccrg_tpu import CartesianGeometry, Grid, make_mesh, obs
from dccrg_tpu.models import Advection, GameOfLife
from dccrg_tpu.obs.events import timeline
from dccrg_tpu.parallel.exec_cache import (
    BatchStepSpec,
    cohort_key,
    default_steps_per_dispatch,
    max_steps_per_dispatch,
)
from dccrg_tpu.parallel.halo import interior_steps_per_exchange
from dccrg_tpu.serve import Ensemble, Scenario, Scheduler


def make_grid(n=4, n_dev=None, max_ref=0, refine_seed=None):
    g = (
        Grid()
        .set_initial_length((n, n, n))
        .set_neighborhood_length(0)
        .set_periodic(True, True, True)
        .set_maximum_refinement_level(max_ref)
        .set_geometry(CartesianGeometry, start=(0.0, 0.0, 0.0),
                      level_0_cell_length=(1.0 / n,) * 3)
        .initialize(mesh=make_mesh(n_devices=n_dev))
    )
    if refine_seed is not None:
        rng = np.random.default_rng(refine_seed)
        ids = np.sort(g.get_cells())
        for cid in rng.choice(ids, size=max(1, len(ids) // 6),
                              replace=False):
            g.refine_completely(int(cid))
    g.stop_refining()
    return g


def gol_states(gol, g, count, seed=0):
    rng = np.random.default_rng(seed)
    cells = g.get_cells()
    return [
        gol.new_state(alive_cells=cells[rng.random(len(cells)) < 0.3])
        for _ in range(count)
    ]


def tree_equal(a, b) -> bool:
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    return len(la) == len(lb) and all(
        np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(la, lb)
    )


def counter_total(name: str) -> int:
    rep = obs.metrics.report()
    return int(sum(rep["counters"].get(name, {}).values()))


# --------------------------------------------- k-step bit-identity


def test_k4_gol_bit_identical_incl_mid_k_retirement():
    """A depth-4 dispatch equals 4 solo steps for every member — and a
    member whose budget is NOT a multiple of k freezes mid-k-block at
    exactly its budget (here 6 = 4 + 2 inside the second block)."""
    g = make_grid()
    gol = GameOfLife(g, allow_dense=False)
    states = gol_states(gol, g, 4, seed=1)
    c0 = counter_total("ensemble.verify_checks")
    m0 = counter_total("ensemble.verify_mismatches")
    ens = Ensemble(verify=True, steps_per_dispatch=4)
    budgets = [6, 8, 8, 8]                  # member 0 retires mid-block
    tickets = [ens.submit(gol, s, steps=b)
               for s, b in zip(states, budgets)]
    ens.run()
    for t, s0, b in zip(tickets, states, budgets):
        assert t.status == "done" and t.steps_done == b
        ref = s0
        for _ in range(b):
            ref = gol.step(ref)
        assert tree_equal(ref, t.result)
    assert counter_total("ensemble.verify_checks") > c0
    assert counter_total("ensemble.verify_mismatches") == m0


def test_advection_f64_heterogeneous_cohort_k_steps_bit_identical():
    """Two refined grids sharing one signature batch into ONE depth-k
    cohort; each member's result is bit-identical to its own model
    stepped solo, with one member's budget landing mid-block."""
    g1 = make_grid(max_ref=1, refine_seed=3)
    g2 = make_grid(max_ref=1, refine_seed=3)
    a1 = Advection(g1, dtype=np.float64, allow_dense=False)
    a2 = Advection(g2, dtype=np.float64, allow_dense=False)
    assert g1.shape_signature() == g2.shape_signature()
    s1, s2 = a1.initialize_state(), a2.initialize_state()
    dt = 0.4 * a1.max_time_step(s1)
    ens = Ensemble(verify=True, steps_per_dispatch=3)
    t1 = ens.submit(a1, s1, steps=5, dt=dt, tenant="a")  # 3 + 2
    t2 = ens.submit(a2, s2, steps=6, dt=dt, tenant="b")  # 3 + 3
    ens.run()
    assert len(ens.cohorts) == 1
    for ticket, (m, s0, steps) in ((t1, (a1, s1, 5)), (t2, (a2, s2, 6))):
        ref = s0
        for _ in range(steps):
            ref = m.step(ref, dt)
        np.testing.assert_array_equal(
            np.asarray(ref["density"]),
            np.asarray(ticket.result["density"]))
    assert counter_total("ensemble.verify_mismatches") == 0


# --------------------------------------------- compile accounting


def test_zero_retrace_churn_at_held_signature_width_and_k():
    g = make_grid()
    gol = GameOfLife(g, allow_dense=False)
    states = gol_states(gol, g, 12, seed=2)
    ens = Ensemble(steps_per_dispatch=4)
    for s in states[:4]:
        ens.submit(gol, s, steps=8)
    ens.run()                               # warm the (W=4, k=4) body
    before = counter_total("epoch.recompiles")
    for wave in (states[4:8], states[8:10], states[10:12]):
        for i, s in enumerate(wave):
            ens.submit(gol, s, steps=4 * (i + 1))
        ens.run()
    assert counter_total("epoch.recompiles") == before, (
        "churn at a held (signature, width, k) must not retrace")
    assert len(ens.completed) == 12


def test_changing_only_k_compiles_exactly_one_body():
    g = make_grid()
    gol = GameOfLife(g, allow_dense=False)
    states = gol_states(gol, g, 2, seed=3)
    sched = Scheduler()
    for s in states:
        sched.submit(Scenario(gol, s, 64))
    sched.admit()
    cohort = next(iter(sched.cohorts.values()))
    cohort.step(1)                          # warm k=1
    before = counter_total("epoch.recompiles")
    cohort.step(4)                          # NEW body: exactly one trace
    assert counter_total("epoch.recompiles") == before + 1
    cohort.step(4)                          # held k: re-dispatch
    cohort.step(1)                          # k=1 body still cached
    assert counter_total("epoch.recompiles") == before + 1
    # the cache key really carries k (plus layout flags)
    spec = cohort.spec
    assert cohort_key(spec, cohort.W, 1) != cohort_key(spec, cohort.W, 4)
    assert (cohort_key(spec, cohort.W, 4, shared_args=True)
            != cohort_key(spec, cohort.W, 4, shared_args=False))


# ------------------------------------------------------- donation


def test_donation_does_not_corrupt_oracle_replayed_member():
    """With donation armed (the default), the oracle's pre-dispatch
    member snapshot must survive the aliasing dispatch: replays stay
    clean and results stay bit-identical across many dispatches."""
    g = make_grid()
    gol = GameOfLife(g, allow_dense=False)
    states = gol_states(gol, g, 3, seed=4)
    m0 = counter_total("ensemble.verify_mismatches")
    ens = Ensemble(verify=True, steps_per_dispatch=2)
    tickets = [ens.submit(gol, s, steps=8) for s in states]
    ens.run()
    cohort = next(iter(ens.cohorts.values()))
    assert cohort._donate is True          # donation is the default
    for t, s0 in zip(tickets, states):
        ref = s0
        for _ in range(8):
            ref = gol.step(ref)
        assert tree_equal(ref, t.result)
    assert counter_total("ensemble.verify_mismatches") == m0


def test_donation_env_gate(monkeypatch):
    from dccrg_tpu.serve import donation_enabled

    monkeypatch.delenv("DCCRG_ENSEMBLE_DONATE", raising=False)
    assert donation_enabled()
    monkeypatch.setenv("DCCRG_ENSEMBLE_DONATE", "0")
    assert not donation_enabled()
    g = make_grid()
    gol = GameOfLife(g, allow_dense=False)
    ens = Ensemble()
    t = ens.submit(gol, gol_states(gol, g, 1, seed=5)[0], steps=2)
    ens.run()
    cohort = next(iter(ens.cohorts.values()))
    assert cohort._donate is False
    ref = gol.step(gol.step(gol_states(gol, g, 1, seed=5)[0]))
    assert t.status == "done"


# ------------------------------------------------------ k selection


def test_select_k_clamps_to_remaining_steps():
    g = make_grid()
    gol = GameOfLife(g, allow_dense=False)
    states = gol_states(gol, g, 2, seed=6)
    sched = Scheduler(steps_per_dispatch=16)
    sched.submit(Scenario(gol, states[0], 3))
    sched.submit(Scenario(gol, states[1], 5))
    sched.admit()
    cohort = next(iter(sched.cohorts.values()))
    # deepest usable step: the LONGEST remaining budget (the shorter
    # member freezes mid-block via the in-kernel clamp)
    assert sched.select_k(cohort) == 5
    while sched.step_once():
        pass
    assert all(s.steps_done == s.steps
               for s in (sched.completed[0], sched.completed[1]))


def test_select_k_deadline_slack_and_cap(monkeypatch):
    # pin the EMA pricing path: with the fleet cost model armed the
    # slack clamp would price from OTHER cohorts' pooled samples even
    # before this cohort has an EMA (the model-driven path is covered
    # by tests/test_cost.py)
    monkeypatch.setenv("DCCRG_COST_MODEL", "0")
    g = make_grid()
    gol = GameOfLife(g, allow_dense=False)
    state = gol_states(gol, g, 1, seed=7)[0]
    sched = Scheduler(steps_per_dispatch=16)
    sched.submit(Scenario(gol, state, 64, deadline=1002.0))
    sched.admit()
    cohort = next(iter(sched.cohorts.values()))
    # no EMA yet: slack cannot be priced, remaining is the only clamp
    assert sched.select_k(cohort, now=1000.0) == 16
    cohort.step_s_ema = 1.0
    # 2 s of slack at 1 s/step affords a depth-2 block, not 16
    assert sched.select_k(cohort, now=1000.0) == 2
    # past-deadline member: retire visibility ASAP, depth 1
    assert sched.select_k(cohort, now=1003.0) == 1
    # the env cap bounds everything
    monkeypatch.setenv("DCCRG_ENSEMBLE_K_MAX", "8")
    cohort.step_s_ema = None
    assert sched.select_k(cohort, now=1000.0) == 8
    assert max_steps_per_dispatch() == 8


def test_spec_default_k_rides_env(monkeypatch):
    monkeypatch.delenv("DCCRG_ENSEMBLE_K", raising=False)
    assert default_steps_per_dispatch() == 1
    monkeypatch.setenv("DCCRG_ENSEMBLE_K", "4")
    assert default_steps_per_dispatch() == 4
    g = make_grid()
    gol = GameOfLife(g, allow_dense=False)
    spec = gol.batch_step_spec()
    assert spec.steps_per_dispatch == 4
    # the spec default reaches the cohort when no override is given
    sched = Scheduler()
    sched.submit(Scenario(gol, gol_states(gol, g, 1, seed=8)[0], 8))
    sched.admit()
    cohort = next(iter(sched.cohorts.values()))
    assert cohort.k == 4 and sched.select_k(cohort) == 4
    monkeypatch.setenv("DCCRG_ENSEMBLE_K", "not-a-number")
    assert default_steps_per_dispatch() == 1


# --------------------------------------- shared tables + HBM gauge


def test_shared_tables_measured_lower_than_stacked_equiv():
    """Members of one model instance share ONE broadcast table copy:
    the measured per-member bytes sit far below the stacked-tables
    equivalent, and the gauge lands for telemetry_diff to ceiling-gate."""
    g = make_grid()
    gol = GameOfLife(g, allow_dense=False)
    ens = Ensemble()
    for s in gol_states(gol, g, 4, seed=9):
        ens.submit(gol, s, steps=2)
    ens.admit_pending()
    cohort = next(iter(ens.cohorts.values()))
    assert cohort.shared_args
    measured = cohort.member_hbm_bytes()
    stacked = cohort.member_hbm_bytes_stacked_tables()
    assert 0 < measured < stacked
    gauge = obs.metrics.gauge_value("ensemble.hbm_bytes_per_member",
                                    model="gol")
    assert gauge == measured
    ens.run()


def test_promotion_to_stacked_is_loss_free_and_counted():
    """A cohort promoted to per-member stacked tables keeps every
    member's results bit-identical (one new body compile, counted),
    and the per-member bytes rise — the regression direction the
    ceiling gate watches."""
    g = make_grid()
    gol = GameOfLife(g, allow_dense=False)
    states = gol_states(gol, g, 3, seed=10)
    ens = Ensemble(verify=True)
    tickets = [ens.submit(gol, s, steps=6) for s in states]
    ens.admit_pending()
    cohort = next(iter(ens.cohorts.values()))
    ens.step()                               # shared-mode dispatches
    before_bytes = cohort.member_hbm_bytes()
    p0 = counter_total("ensemble.cohort_promotions")
    r0 = counter_total("epoch.recompiles")
    cohort.promote_to_stacked()
    assert not cohort.shared_args
    assert counter_total("ensemble.cohort_promotions") == p0 + 1
    ens.run()                                # stacked-mode dispatches
    assert counter_total("epoch.recompiles") == r0 + 1, (
        "promotion must cost exactly the one stacked body")
    assert cohort.member_hbm_bytes() > before_bytes
    for t, s0 in zip(tickets, states):
        ref = s0
        for _ in range(6):
            ref = gol.step(ref)
        assert tree_equal(ref, t.result)
    assert counter_total("ensemble.verify_mismatches") == 0


def test_mismatched_tables_promote_on_admit():
    """A joiner whose runtime tables differ by CONTENT flips the cohort
    out of shared mode at admission (the content key), and both members
    still step to their own solo results."""
    g = make_grid()
    gol = GameOfLife(g, allow_dense=False)
    states = gol_states(gol, g, 2, seed=11)
    sched = Scheduler()
    a = sched.submit(Scenario(gol, states[0], 3))
    sched.admit()
    cohort = next(iter(sched.cohorts.values()))
    assert cohort.shared_args
    b = Scenario(gol, states[1], 3)
    sched.submit(b)
    # perturb ONE table copy into content-inequality: a fresh tuple of
    # recreated arrays keeps identity-miss + content-hit on all leaves
    # except the first, which gets a same-shape different value
    leaves = [np.asarray(x).copy()
              for x in jax.tree_util.tree_leaves(b.spec.args)]
    treedef = jax.tree_util.tree_structure(b.spec.args)
    leaves[0] = leaves[0] ^ 1 if leaves[0].dtype.kind in "iu" \
        else leaves[0] + 1
    b.spec = b.spec._replace(
        args=jax.tree_util.tree_unflatten(treedef, leaves))
    sched.admit()
    # admission grew the width-1 cohort (fresh object) and THEN the
    # mismatched joiner promoted it out of shared mode
    cohort = next(iter(sched.cohorts.values()))
    assert not cohort.shared_args
    assert cohort.occupancy == 2
    while sched.step_once():
        pass
    # member a is untouched by the promotion: solo-identical
    ref = states[0]
    for _ in range(3):
        ref = gol.step(ref)
    assert tree_equal(ref, a.result)
    # member b's result equals ITS member program on ITS (perturbed)
    # tables — the stacked cohort really used the per-member copy
    solo = states[1]
    for _ in range(3):
        solo = b.spec.call(b.spec.args, solo, np.float32(0))
    assert tree_equal(solo, b.result)


# ------------------------------------------- k-aware SLO accounting


def test_request_step_span_and_steps_served_are_k_aware():
    g = make_grid()
    gol = GameOfLife(g, allow_dense=False)
    states = gol_states(gol, g, 2, seed=12)
    t0 = obs.metrics.counter_value("ensemble.steps_served",
                                   tenant="kaware")
    ens = Ensemble(steps_per_dispatch=4)
    for s in states:
        ens.submit(gol, s, steps=8, tenant="kaware")
    ens.run()
    assert obs.metrics.counter_value(
        "ensemble.steps_served", tenant="kaware") == t0 + 16
    spans = [s for s in timeline.spans()
             if s["name"] == "request.step" and s["args"]
             and s["args"].get("steps_per_dispatch") == 4]
    assert spans, "depth-4 dispatches must leave k-aware step spans"
    last = spans[-1]
    assert last["args"]["member_steps"] == 8      # 2 members x k=4
    assert last["args"]["members"] == 2
    k_gauge = obs.metrics.gauge_value("ensemble.steps_per_dispatch",
                                      model="gol")
    assert k_gauge == 4


# -------------------------------------------------- halo depth budget


def test_interior_steps_per_exchange_budget():
    # ghost depth g, stencil radius r -> floor(g / r), floored at 1
    assert interior_steps_per_exchange(0) == 1
    assert interior_steps_per_exchange(1) == 1
    assert interior_steps_per_exchange(4) == 4
    assert interior_steps_per_exchange(4, stencil_radius=2) == 2
    assert interior_steps_per_exchange(5, stencil_radius=2) == 2
    g = make_grid()
    ex = g.halo(None)
    assert ex.ring_distances == tuple(ex.ring_ks)


# --------------------------------------------- telemetry ceiling gate


def test_telemetry_diff_hbm_ceiling_gate():
    import importlib.util
    import pathlib

    spec = importlib.util.spec_from_file_location(
        "telemetry_diff",
        pathlib.Path(__file__).resolve().parents[1]
        / "tools" / "telemetry_diff.py",
    )
    td = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(td)
    assert "ensemble.hbm_bytes_per_member" in td.GATED_GAUGES_MAX
    base = {"ensemble.hbm_bytes_per_member": {"model=gol": 1000}}
    ok = {"ensemble.hbm_bytes_per_member": {"model=gol": 1100}}
    bad = {"ensemble.hbm_bytes_per_member": {"model=gol": 2000}}
    lower = {"ensemble.hbm_bytes_per_member": {"model=gol": 10}}
    gate = td.compare_gauges(ok, base, threshold=0.35,
                             gauges=td.GATED_GAUGES_MAX, mode="max")
    assert gate["verdict"] == "PASS"
    gate = td.compare_gauges(bad, base, threshold=0.35,
                             gauges=td.GATED_GAUGES_MAX, mode="max")
    assert gate["verdict"] == "FAIL"
    # an IMPROVEMENT (bytes falling) must pass the ceiling...
    gate = td.compare_gauges(lower, base, threshold=0.35,
                             gauges=td.GATED_GAUGES_MAX, mode="max")
    assert gate["verdict"] == "PASS"
    # ...and a vanished series is still a coverage loss
    gate = td.compare_gauges({}, base, threshold=0.35,
                             gauges=td.GATED_GAUGES_MAX, mode="max")
    assert gate["verdict"] == "FAIL"
    with pytest.raises(ValueError, match="mode"):
        td.compare_gauges(ok, base, mode="sideways")
