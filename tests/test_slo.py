"""The request-level SLO plane (ISSUE 10): quantile estimation over
exported log-bucket histograms, cross-registry/-process merges, request
lifecycle spans and deadline accounting in the serving front-end, the
flight-recorder black box, and the telemetry_diff p99 ceiling gate."""
import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from dccrg_tpu import CartesianGeometry, Grid, make_mesh, obs
from dccrg_tpu.obs import slo
from dccrg_tpu.obs.flightrec import (
    FlightRecorder,
    recorder as flight_recorder,
    validate_flightrec,
)
from dccrg_tpu.obs.registry import MetricsRegistry

HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.dirname(HERE)


# ------------------------------------------------------------ quantiles


def test_quantile_single_value_is_exact():
    reg = MetricsRegistry()
    for _ in range(10):
        reg.observe("lat", 0.125)
    h = reg.report()["histograms"]["lat"][""]
    for q in (0.0, 0.5, 0.95, 0.99, 1.0):
        assert slo.quantile(h, q) == pytest.approx(0.125)


def test_quantile_known_values_fine_resolution():
    """At the SLO resolution (8 buckets/octave, ~9% edges) quantile
    estimates of a smooth sample set sit within one bucket of truth."""
    reg = MetricsRegistry()
    reg.set_histogram_resolution("lat", slo.SLO_RESOLUTION)
    rng = np.random.default_rng(0)
    vals = np.sort(rng.lognormal(-3.0, 1.0, size=4000))
    for v in vals:
        reg.observe("lat", float(v))
    h = reg.report()["histograms"]["lat"][""]
    for q in (0.5, 0.9, 0.95, 0.99):
        est = slo.quantile(h, q)
        true = float(vals[int(q * (len(vals) - 1))])
        assert est == pytest.approx(true, rel=2.0 ** (1 / 8) - 1 + 0.02)


def test_quantile_ordering_and_envelope():
    reg = MetricsRegistry()
    for v in (0.001, 0.002, 0.01, 0.05, 0.9, 3.0):
        reg.observe("lat", v)
    h = reg.report()["histograms"]["lat"][""]
    p50, p95, p99 = (slo.quantile(h, q) for q in (0.5, 0.95, 0.99))
    assert p50 <= p95 <= p99
    assert h["min"] <= p50 and p99 <= h["max"]


def test_quantile_empty_and_json_roundtrip():
    assert slo.quantile({}, 0.5) is None
    assert slo.quantile({"count": 0}, 0.5) is None
    reg = MetricsRegistry()
    reg.observe("lat", 0.25)
    reg.observe("lat", 1.0)
    # the post-hoc path: through JSON exactly as telemetry.json stores it
    h = json.loads(json.dumps(reg.report()))["histograms"]["lat"][""]
    assert 0.25 <= slo.quantile(h, 0.5) <= 1.0


def test_merge_equals_pooled_observation():
    """Merging two registries' exports is EXACT: same result as one
    registry observing the pooled samples (equal values -> equal bucket
    keys on both sides)."""
    a, b, pooled = (MetricsRegistry() for _ in range(3))
    for r in (a, b, pooled):
        r.set_histogram_resolution("lat", slo.SLO_RESOLUTION)
    rng = np.random.default_rng(1)
    for i, v in enumerate(rng.lognormal(-2, 0.7, size=300)):
        (a if i % 2 else b).observe("lat", float(v))
        pooled.observe("lat", float(v))
    ha = a.report()["histograms"]["lat"][""]
    hb = b.report()["histograms"]["lat"][""]
    hp = pooled.report()["histograms"]["lat"][""]
    m = slo.merge(ha, hb)
    assert m["count"] == hp["count"]
    assert m["buckets"] == hp["buckets"]
    assert m["min"] == hp["min"] and m["max"] == hp["max"]
    assert slo.quantile(m, 0.99) == pytest.approx(
        slo.quantile(hp, 0.99))


def test_merge_across_processes():
    """The cross-process form: a subprocess exports its registry as
    JSON (registry.py file-loaded — no package, no jax), merged here
    label by label via merge_series."""
    code = (
        "import importlib.util, json, sys\n"
        "spec = importlib.util.spec_from_file_location('reg', %r)\n"
        "reg = importlib.util.module_from_spec(spec)\n"
        "spec.loader.exec_module(reg)\n"
        "r = reg.MetricsRegistry()\n"
        "r.set_histogram_resolution('ensemble.e2e_s', %d)\n"
        "for i in range(50):\n"
        "    r.observe('ensemble.e2e_s', 0.01 * (i + 1), tenant='a')\n"
        "print(json.dumps(r.report()))\n"
        % (os.path.join(ROOT, "dccrg_tpu", "obs", "registry.py"),
           slo.SLO_RESOLUTION)
    )
    out = subprocess.run([sys.executable, "-c", code], check=True,
                         capture_output=True, text=True)
    remote = json.loads(out.stdout.strip().splitlines()[-1])
    local = MetricsRegistry()
    local.set_histogram_resolution("ensemble.e2e_s", slo.SLO_RESOLUTION)
    for i in range(50):
        local.observe("ensemble.e2e_s", 0.01 * (i + 1), tenant="a")
    merged = slo.merge_series([remote, local.report()], "ensemble.e2e_s")
    assert merged["tenant=a"]["count"] == 100
    # identical sample sets in both processes: the merged quantile is
    # the single-process quantile
    solo = slo.quantile(local.report()["histograms"]
                        ["ensemble.e2e_s"]["tenant=a"], 0.95)
    assert slo.quantile(merged["tenant=a"], 0.95) == pytest.approx(solo)


def test_observe_duration_phase_hook():
    """Existing phase timers feed the histogram plane with no new call
    sites; DCCRG_PHASE_HIST=0 (per-registry flag) opts out."""
    reg = MetricsRegistry()
    assert reg.duration_histograms  # default on
    with reg.phase("work"):
        pass
    reg.phase_add("hot", 0.002)
    hists = reg.report()["histograms"]["phase.duration_s"]
    assert hists["phase=work"]["count"] == 1
    assert hists["phase=hot"]["count"] == 1

    off = MetricsRegistry()
    off.duration_histograms = False
    with off.phase("work"):
        pass
    assert "phase.duration_s" not in off.report()["histograms"]


def test_phase_hist_env_gate():
    code = (
        "import importlib.util, os\n"
        "os.environ['DCCRG_PHASE_HIST'] = '0'\n"
        "spec = importlib.util.spec_from_file_location('reg', %r)\n"
        "reg = importlib.util.module_from_spec(spec)\n"
        "spec.loader.exec_module(reg)\n"
        "assert not reg.MetricsRegistry().duration_histograms\n"
        "print('GATED-OK')\n"
        % os.path.join(ROOT, "dccrg_tpu", "obs", "registry.py")
    )
    out = subprocess.run([sys.executable, "-c", code], check=True,
                         capture_output=True, text=True)
    assert "GATED-OK" in out.stdout


# ----------------------------------------------------- serving lifecycle


def _gol_ensemble():
    from dccrg_tpu.models import GameOfLife
    from dccrg_tpu.serve import Ensemble

    n = 4
    g = (
        Grid()
        .set_initial_length((n, n, n))
        .set_neighborhood_length(0)
        .set_periodic(True, True, True)
        .set_geometry(CartesianGeometry, start=(0.0, 0.0, 0.0),
                      level_0_cell_length=(1.0 / n,) * 3)
        .initialize(mesh=make_mesh())
    )
    g.stop_refining()
    gol = GameOfLife(g, allow_dense=False)
    cells = g.get_cells()
    rng = np.random.default_rng(7)
    mk = lambda: gol.new_state(
        alive_cells=cells[rng.random(len(cells)) < 0.3]
    )
    return Ensemble(), gol, mk


def test_request_lifecycle_spans_and_histograms():
    obs.metrics.reset()
    obs.timeline.clear()
    ens, gol, mk = _gol_ensemble()
    t = ens.submit(gol, mk(), steps=3, tenant="alice")
    ens.submit(gol, mk(), steps=2, tenant="bob")
    ens.run()
    assert t.status == "done"
    assert t.retired_at is not None
    assert t.retired_at >= t.admitted_at >= t.submitted_at

    rep = obs.metrics.report()
    hists = rep["histograms"]
    assert hists["ensemble.queue_wait_s"]["tenant=alice"]["count"] == 1
    assert hists["ensemble.e2e_s"]["tenant=bob"]["count"] == 1
    svc = hists["ensemble.service_s"]
    assert any("tenant=alice" in label and "model=" in label
               for label in svc)

    spans = obs.timeline.spans()
    by_name = {}
    for s in spans:
        by_name.setdefault(s["name"], []).append(s)
    for wanted in ("request.queued", "request.admit", "request.step",
                   "request.retire", "request.e2e"):
        assert wanted in by_name, f"missing lifecycle span {wanted}"
    # the e2e span carries the request id and covers submit -> retire
    e2e = [s for s in by_name["request.e2e"]
           if s["args"] and s["args"].get("request") == t.id]
    assert len(e2e) == 1
    assert e2e[0]["begin"] == pytest.approx(t.submitted_at)
    assert e2e[0]["dur"] == pytest.approx(t.retired_at - t.submitted_at)
    # step spans name their member requests
    assert any(t.id in (s["args"] or {}).get("requests", [])
               for s in by_name["request.step"])


def test_deadline_miss_counted_at_retire():
    obs.metrics.reset()
    ens, gol, mk = _gol_ensemble()
    now = time.perf_counter()
    ens.submit(gol, mk(), steps=2, tenant="late", deadline=now - 5.0)
    ens.submit(gol, mk(), steps=2, tenant="fine", deadline=now + 3600.0)
    ens.submit(gol, mk(), steps=2, tenant="none")
    ens.run()
    counters = obs.metrics.report()["counters"]
    assert counters["ensemble.deadline_miss"] == {"tenant=late": 1}
    assert counters["ensemble.slo_violations"] == {"class=deadline": 1}
    rates = slo.deadline_miss_rates(obs.metrics.report())
    assert rates["late"] == {"missed": 1, "completed": 1, "rate": 1.0}
    assert rates["fine"]["missed"] == 0


# ------------------------------------------------------ flight recorder


def test_flightrec_ring_bound():
    fr = FlightRecorder(cap=16, enabled=True)
    for i in range(50):
        fr.add_span(f"s{i}", float(i), 0.001)
        fr.note("tick", i=i)
    rec = fr.record()
    assert len(rec["spans"]) == 16
    assert len(rec["events"]) == 16
    assert rec["dropped"] == {"spans": 34, "events": 34}
    # the ring keeps the NEWEST spans — the postmortem window
    assert rec["spans"][-1]["name"] == "s49"
    assert rec["spans"][0]["name"] == "s34"


def test_flightrec_cap_env(monkeypatch):
    monkeypatch.setenv("DCCRG_FLIGHTREC_CAP", "32")
    assert FlightRecorder().cap == 32
    monkeypatch.setenv("DCCRG_FLIGHTREC_CAP", "bogus")
    assert FlightRecorder().cap == 512


def test_flightrec_env_disable(monkeypatch, tmp_path):
    monkeypatch.setenv("DCCRG_FLIGHTREC", "0")
    fr = FlightRecorder()
    assert not fr.enabled
    fr.add_span("s", 0.0, 1.0)
    fr.note("k")
    fr.begin_request("r")
    assert len(fr) == 0 and fr.in_flight() == []
    assert fr.dump(path=str(tmp_path / "d.json")) is None
    assert not (tmp_path / "d.json").exists()


def test_flightrec_dump_schema_and_inflight(tmp_path):
    fr = FlightRecorder(cap=64, enabled=True)
    fr.add_span("halo.exchange", time.perf_counter(), 0.004,
                {"ring": 1})
    fr.begin_request(17, tenant="alice", status="active")
    fr.note("request.admit", request=17)
    path = fr.dump(path=str(tmp_path / "pm.json"), reason="unit-test")
    assert validate_flightrec(path) == []
    rec = json.loads((tmp_path / "pm.json").read_text())
    assert rec["schema"] == "dccrg.flightrec.v1"
    assert rec["reason"] == "unit-test"
    assert [r["id"] for r in rec["in_flight"]] == ["17"]
    assert rec["snapshot"].keys() >= {"phases", "counters", "gauges",
                                      "histograms"}
    # tampering is detected
    rec["spans"] = [{"name": 3}]
    (tmp_path / "pm.json").write_text(json.dumps(rec))
    assert validate_flightrec(str(tmp_path / "pm.json"))


def test_flightrec_unarmed_dump_is_noop():
    fr = FlightRecorder(enabled=True)
    assert fr.dump(reason="nowhere") is None


def test_flightrec_mark_unit_tracks_one(tmp_path):
    fr = FlightRecorder(enabled=True)
    fr.arm(str(tmp_path), period=1000.0)  # no autodump interference
    fr.mark_unit("gol/0", phase="gol", step=0)
    fr.mark_unit("gol/1", phase="gol", step=1)
    assert [r["id"] for r in fr.in_flight()] == ["gol/1"]
    fr.disarm()


def test_flightrec_checkpoint_atomic_and_named(tmp_path):
    fr = FlightRecorder(enabled=True)
    fr.arm(str(tmp_path), period=0.0, autodump=True)
    fr.mark_unit("adv/3", phase="adv", step=3)
    files = [p for p in os.listdir(tmp_path)
             if p.startswith("flightrec_") and p.endswith(".json")]
    assert files, "autodump checkpoint never landed"
    newest = os.path.join(tmp_path, files[0])
    assert validate_flightrec(newest) == []
    rec = json.loads(open(newest).read())
    assert [r["id"] for r in rec["in_flight"]] == ["adv/3"]
    assert not [p for p in os.listdir(tmp_path) if p.endswith(".tmp")]
    fr.disarm()


def test_escalation_dumps_once_per_incident(tmp_path):
    from dccrg_tpu.resilience import EscalationLadder

    prev = flight_recorder.armed_dir
    try:
        flight_recorder.arm(str(tmp_path), autodump=False)
        ladder = EscalationLadder()
        actions = [ladder.escalate("stall") for _ in range(3)]
        assert actions == ["warn", "rescale_down", "restart"]
        dumps = [p for p in os.listdir(tmp_path)
                 if p.startswith("flightrec_")]
        assert len(dumps) == 1, dumps
        assert validate_flightrec(os.path.join(tmp_path, dumps[0])) == []
        assert ladder.last_dump == os.path.join(tmp_path, dumps[0])
        rec = json.loads(open(ladder.last_dump).read())
        assert rec["reason"].startswith("escalation:stall")
        # a healthy reset re-arms the black box for the NEXT incident
        ladder.reset()
        ladder.escalate("stall-again")
        dumps = [p for p in os.listdir(tmp_path)
                 if p.startswith("flightrec_")]
        assert len(dumps) == 2
    finally:
        if prev is not None:
            flight_recorder.arm(prev)
        else:
            flight_recorder.disarm()


def test_verify_mismatch_dumps_black_box(tmp_path):
    """A tampered cohort row must trip the oracle AND leave a
    postmortem (one per cohort, not one per step)."""
    import jax

    obs.metrics.reset()
    prev = flight_recorder.armed_dir
    try:
        flight_recorder.arm(str(tmp_path), autodump=False)
        ens, gol, mk = _gol_ensemble()
        ens.scheduler.verify = True
        ens.submit(gol, mk(), steps=4, tenant="alice")
        ens.admit_pending()
        (cohort,) = ens.cohorts.values()
        cohort._verify_on = True
        ens.step()
        # corrupt the cohort BODY: its output diverges from the solo
        # member program, which is exactly what the oracle audits
        kernel = cohort._kernel_for(1)
        cohort._kernels[(1, 0)] = lambda args, state, remaining, dts, mask: (
            jax.tree_util.tree_map(
                lambda S: S + S.dtype.type(1),
                kernel(args, state, remaining, dts, mask),
            )
        )
        ens.step()
        ens.step()
        mism = sum(obs.metrics.report()["counters"]
                   .get("ensemble.verify_mismatches", {}).values())
        assert mism > 0
        dumps = [p for p in os.listdir(tmp_path)
                 if p.startswith("flightrec_")]
        assert len(dumps) == 1
        rec = json.loads(open(os.path.join(tmp_path, dumps[0])).read())
        assert rec["reason"] == "ensemble.verify_mismatch"
    finally:
        if prev is not None:
            flight_recorder.arm(prev)
        else:
            flight_recorder.disarm()


# ----------------------------------------------------- diff gate + CLI


@pytest.fixture(scope="module")
def diff():
    sys.path.insert(0, os.path.join(ROOT, "tools"))
    try:
        import telemetry_diff
    finally:
        sys.path.pop(0)
    return telemetry_diff


def _latency_report(scale: float) -> dict:
    reg = MetricsRegistry()
    reg.set_histogram_resolution("ensemble.e2e_s", slo.SLO_RESOLUTION)
    rng = np.random.default_rng(3)
    for v in rng.lognormal(-2, 0.5, size=400):
        reg.observe("ensemble.e2e_s", scale * float(v), tenant="a")
    return reg.report()


def test_diff_p99_ceiling_gate(diff, tmp_path):
    base = _latency_report(1.0)
    ok = _latency_report(1.0)
    bad = _latency_report(3.0)
    assert diff.compare_quantiles(
        ok["histograms"], base["histograms"])["verdict"] == "PASS"
    v = diff.compare_quantiles(bad["histograms"], base["histograms"])
    assert v["verdict"] == "FAIL"
    assert "p99" in v["failures"][0]
    # vacuous without both sides
    assert diff.compare_quantiles(
        None, base["histograms"])["verdict"] == "PASS"
    # end to end through the CLI entry point: an injected p99
    # regression fails the round
    bpath, cpath = tmp_path / "base.json", tmp_path / "cur.json"
    bpath.write_text(json.dumps(base))
    cpath.write_text(json.dumps(bad))
    rc = diff.main(["--current", str(cpath), "--baseline", str(bpath),
                    "--no-history"])
    assert rc == 1
    cpath.write_text(json.dumps(ok))
    assert diff.main(["--current", str(cpath), "--baseline", str(bpath),
                      "--no-history"]) == 0


def test_slo_report_cli_offline(tmp_path):
    """The acceptance criterion: per-tenant p50/p95/p99 and miss rates
    from exported histograms alone — no live process."""
    reg = MetricsRegistry()
    for name in ("ensemble.queue_wait_s", "ensemble.e2e_s",
                 "ensemble.service_s"):
        reg.set_histogram_resolution(name, slo.SLO_RESOLUTION)
    rng = np.random.default_rng(5)
    for tenant in ("alice", "bob"):
        for v in rng.lognormal(-3, 0.6, size=60):
            reg.observe("ensemble.queue_wait_s", float(v), tenant=tenant)
            reg.observe("ensemble.e2e_s", 3 * float(v), tenant=tenant)
    reg.inc("ensemble.deadline_miss", 3, tenant="alice")
    tel = tmp_path / "telemetry.json"
    tel.write_text(json.dumps(reg.report()))
    out_json = tmp_path / "slo.json"
    r = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "slo_report.py"),
         str(tel), "--json", str(out_json)],
        capture_output=True, text=True, timeout=120,
    )
    assert r.returncode == 0, r.stderr
    assert "tenant=alice" in r.stdout and "p99" in r.stdout
    rep = json.loads(out_json.read_text())
    rows = {(row["metric"], row["labels"]): row for row in rep["latency"]}
    row = rows[("ensemble.e2e_s", "tenant=alice")]
    assert row["p50"] <= row["p95"] <= row["p99"]
    assert rep["deadline_miss_rates"]["alice"]["missed"] == 3
    assert rep["deadline_miss_rates"]["alice"]["rate"] == pytest.approx(
        3 / 60)


def test_slo_report_drilldown(tmp_path):
    """Slowest-request drill-down: request.e2e spans cross-referenced
    to overlapping kernel spans from other (device) pids."""
    sys.path.insert(0, os.path.join(ROOT, "tools"))
    try:
        import slo_report
    finally:
        sys.path.pop(0)
    trace = {"traceEvents": [
        {"name": "request.e2e", "ph": "B", "pid": 1, "tid": 0, "ts": 0.0,
         "args": {"request": 5, "tenant": "alice"}},
        {"name": "request.e2e", "ph": "E", "pid": 1, "tid": 0,
         "ts": 9000.0},
        {"name": "jit_gol_step", "ph": "X", "pid": 2, "tid": 0,
         "ts": 1000.0, "dur": 7000.0},
        {"name": "unrelated_kernel", "ph": "X", "pid": 2, "tid": 0,
         "ts": 20000.0, "dur": 500.0},
    ]}
    slow = slo_report.slowest_requests(trace, top=3)
    assert len(slow) == 1
    assert slow[0]["request"] == 5
    names = [k["name"] for k in slow[0]["kernels"]]
    assert names == ["jit_gol_step"]


def test_check_telemetry_required_sets_cover_slo():
    sys.path.insert(0, os.path.join(ROOT, "tools"))
    try:
        import check_telemetry as ct
    finally:
        sys.path.pop(0)
    assert "ensemble.deadline_miss" in ct.REQUIRED_NONZERO_COUNTERS
    assert "flightrec.dumps" in ct.REQUIRED_NONZERO_COUNTERS
    assert "flightrec.dump" in ct.REQUIRED_PHASES
    assert set(ct.REQUIRED_HISTOGRAMS) >= {
        "ensemble.queue_wait_s", "ensemble.e2e_s", "phase.duration_s",
    }
