"""Shape-stable epochs (ISSUE 5): bucketed table shapes + the
persistent executable cache.

The contract under test:

(a) randomized AMR+LB churn compiles each model kernel at most once per
    (kernel, shape signature) — a rebuild that lands on a signature the
    cache has seen re-dispatches existing executables, zero retraces;
(b) bucketed results are bit-identical to a forced-unbucketed run (the
    padding invariants absorb the bucket margin);
(c) hysteresis — a grid oscillating around a ladder boundary never
    flaps between shapes, and shapes only shrink when utilization drops
    well below the held value;
(d) the executable cache is a bounded LRU under adversarial signature
    churn.
"""
import jax
import numpy as np
import pytest

from dccrg_tpu import CartesianGeometry, Grid, make_mesh, obs
from dccrg_tpu.models import Advection, GameOfLife
from dccrg_tpu.parallel.exec_cache import ExecutableCache, trace_counts
from dccrg_tpu.parallel.epoch_delta import TablePool
from dccrg_tpu.parallel.shapes import (
    bucket_k,
    bucket_rows,
    epoch_shape_hints,
    signature_of,
)


def make_grid(n=8, n_dev=8, max_lvl=2, hood=1, periodic=(True, True, True)):
    return (
        Grid()
        .set_initial_length((n, n, n))
        .set_neighborhood_length(hood)
        .set_periodic(*periodic)
        .set_maximum_refinement_level(max_lvl)
        .set_load_balancing_method("RCB")
        .set_geometry(
            CartesianGeometry,
            start=(0.0, 0.0, 0.0),
            level_0_cell_length=(1.0 / n,) * 3,
        )
        .initialize(mesh=make_mesh(n_devices=n_dev))
    )


def churn_step(g, rng, round_i, max_lvl=2, target=None):
    """One randomized mutation, yielding after EACH structural change
    (so callers can remap payloads): a volume-balanced AMR storm (whole
    unrefine families so the shrink side really commits), then a
    lightly-pinned repartition every other round.  The pin set is small
    and deterministic per parity so ownership — hence pair counts —
    oscillates within the hysteresis margin instead of re-rolling the
    whole partition every LB round (real load balancing converges; it
    does not jump to a random partition each call)."""
    ids = g.get_cells()
    lvl = g.mapping.get_refinement_level(ids)
    # cell-count controller: unrefine requests are routinely vetoed
    # (2:1 repair, induced refinement), so an uncontrolled storm grows
    # the grid monotonically and every round would legitimately cross a
    # bucket — real AMR tracks a feature at roughly constant resolution
    grow = target is None or len(ids) <= target
    coarse = ids[lvl < max_lvl]
    if grow and len(coarse):
        pick = rng.choice(len(coarse), size=min(4, len(coarse)),
                          replace=False)
        g.refine_completely_many(coarse[pick])
    fine = ids[lvl >= 1]
    if len(fine):
        parents = np.unique(g.mapping.get_parent(fine))
        sibs = g.mapping.get_all_children(parents)
        whole = np.isin(sibs, fine).all(axis=1)
        fams = sibs[whole]
        if len(fams):
            n_unref = 4 if grow else 12
            fpick = rng.choice(len(fams), size=min(n_unref, len(fams)),
                               replace=False)
            g.unrefine_completely_many(fams[fpick].reshape(-1))
    g.stop_refining()
    yield "amr"
    if round_i % 2 == 1 and g.n_devices > 1:
        cells = g.get_cells()
        for j in range(4):
            g.pin(int(cells[j * 7]), int((j + round_i // 2)
                                         % g.n_devices))
        g.balance_load()
        g.unpin_all_cells()
        yield "lb"


# ------------------------------------------------- (a) one compile per sig


@pytest.mark.parametrize("n_dev,seed,rounds", [(1, 0, 10), (8, 3, 20)])
def test_at_most_one_compile_per_kernel_signature(n_dev, seed, rounds):
    """Across a whole randomized AMR+LB churn run, each model kernel is
    traced at most once per distinct (ring structure, shape signature)
    — the executable cache absorbs every repeat."""
    rng = np.random.default_rng(seed)
    g = make_grid(n_dev=n_dev)
    ids = g.get_cells()
    ctr = g.geometry.get_center(ids)
    g.refine_completely_many(ids[np.linalg.norm(ctr - 0.5, axis=1) < 0.3])
    g.stop_refining()

    base = trace_counts()  # process-global: other tests' traces excluded
    seen_sigs = set()
    target = len(g.get_cells())
    for round_i in range(rounds):
        for _ in churn_step(g, rng, round_i, target=target):
            pass
        adv = Advection(g, dtype=np.float32, allow_dense=False)
        state = adv.initialize_state()
        dt = np.float32(0.2 * adv.max_time_step(state))
        state = adv.step(state, dt)
        state = adv.compute_max_diff(state, 0.25)
        jax.block_until_ready(state["density"])
        # the full compiled-schedule identity: epoch shapes + ring
        # structure + the (bucketed, hysteresis-held) ring step sizes
        seen_sigs.add((g.shape_signature(), adv._exchange.structure_key,
                       tuple(adv._exchange.ring_sizes)))

    counts = trace_counts()
    for kernel in ("advection.step", "advection.max_diff"):
        traced = counts.get(kernel, 0) - base.get(kernel, 0)
        assert traced <= len(seen_sigs), (
            f"{kernel} traced {traced}x for "
            f"{len(seen_sigs)} distinct signatures"
        )
    # the churn must actually repeat signatures for the bound to bite
    assert len(seen_sigs) < rounds


def test_repeat_signature_costs_zero_retraces():
    """The probe contract: a second structural commit that keeps the
    shape signature compiles nothing anywhere (total recompiles flat)."""
    g = make_grid(n_dev=8)
    ids = g.get_cells()
    ctr = g.geometry.get_center(ids)
    g.refine_completely_many(ids[np.linalg.norm(ctr - 0.5, axis=1) < 0.3])
    g.stop_refining()

    def cycle(i):
        cells = g.get_cells()
        lvl = g.mapping.get_refinement_level(cells)
        cand = cells[lvl < 2]
        g.refine_completely(int(cand[(i * 13) % len(cand)]))
        g.stop_refining()
        m = GameOfLife(g, allow_dense=False)
        st = m.new_state(g.get_cells()[::3])
        st = m.step(st)
        jax.block_until_ready(st["is_alive"])

    cycle(0)
    sig = g.shape_signature()
    before = sum(trace_counts().values())
    cycle(1)
    assert g.shape_signature() == sig, "hysteresis failed to hold shapes"
    assert sum(trace_counts().values()) == before, (
        "same-signature rebuild recompiled a kernel"
    )


# ----------------------------------------------------- (b) bit-identity


def _advect_churn(n_dev, seed, steps=3):
    rng = np.random.default_rng(seed)
    g = make_grid(n_dev=n_dev)
    ids = g.get_cells()
    ctr = g.geometry.get_center(ids)
    g.refine_completely_many(ids[np.linalg.norm(ctr - 0.5, axis=1) < 0.3])
    g.stop_refining()
    adv = Advection(g, dtype=np.float64, allow_dense=False)
    state = adv.initialize_state()
    dt = 0.2 * adv.max_time_step(state)
    for round_i in range(3):
        for _change in churn_step(g, rng, round_i):
            # carry the payload across EACH structural change, then
            # rebuild the model against the new structure
            state = g.remap_state(state)
        adv = Advection(g, dtype=np.float64, allow_dense=False)
        cells = g.get_cells()
        centers = g.geometry.get_center(cells)
        state = g.set_cell_data(state, "vx", cells, -centers[:, 1] + 0.5)
        state = g.set_cell_data(state, "vy", cells, centers[:, 0] - 0.5)
        state = g.set_cell_data(state, "vz", cells,
                                np.zeros(len(cells)))
        state = adv._exchange(state)
        for _ in range(steps):
            state = adv.step(state, dt)
    cells = np.sort(g.get_cells())
    return np.asarray(g.get_cell_data(state, "density", cells))


@pytest.mark.parametrize("n_dev", [1, 8])
def test_bucketed_bit_identical_to_unbucketed(n_dev, monkeypatch):
    rho_bucketed = _advect_churn(n_dev, seed=7)
    monkeypatch.setenv("DCCRG_EPOCH_BUCKETS", "0")
    rho_exact = _advect_churn(n_dev, seed=7)
    np.testing.assert_array_equal(rho_bucketed, rho_exact)


# ------------------------------------------------------- (c) hysteresis


def test_bucket_ladders():
    for n in (1, 2, 7, 8, 9, 100, 1000, 12345):
        assert bucket_rows(n) >= n
        assert bucket_k(n) >= n
        # deterministic and idempotent against the own choice
        assert bucket_rows(n) == bucket_rows(n)
        assert bucket_rows(n, bucket_rows(n)) == bucket_rows(n)
        assert bucket_k(n, bucket_k(n)) == bucket_k(n)
    # monotone
    assert bucket_rows(100) <= bucket_rows(130)
    assert bucket_k(8) <= bucket_k(27)


def test_bucket_hysteresis_no_flap():
    """A value oscillating around a ladder boundary keeps one shape:
    growth moves up, small shrink holds, only a deep drop releases."""
    b = bucket_rows(100)
    up = bucket_rows(b + 1, b)       # crossed the boundary: grow
    assert up > b
    assert bucket_rows(b, up) == up        # back at boundary: hold
    assert bucket_rows(int(0.8 * up), up) == up  # mild shrink: hold
    released = bucket_rows(int(0.3 * up), up)    # deep drop: release
    assert released < up


def test_bucket_disabled_is_exact(monkeypatch):
    monkeypatch.setenv("DCCRG_EPOCH_BUCKETS", "0")
    for n in (1, 9, 100, 12345):
        assert bucket_rows(n) == n
        assert bucket_k(n) == n
        assert bucket_rows(n, 10 * n) == n


def test_grid_signature_does_not_flap():
    """Refine/unrefine the same family back and forth: after the first
    cycle the signature must stay put (no shape oscillation)."""
    g = make_grid(n_dev=1, max_lvl=1)
    ids = g.get_cells()
    g.refine_completely(int(ids[0]))
    g.stop_refining()
    sigs = []
    for _ in range(4):
        child = g.get_cells()[g.mapping.get_refinement_level(
            g.get_cells()) == 1][0]
        g.unrefine_completely(int(child))
        g.stop_refining()
        g.refine_completely(int(g.get_cells()[0]))
        g.stop_refining()
        sigs.append(g.shape_signature())
    assert len(set(sigs)) == 1, f"signature flapped: {sigs}"


def test_shape_hints_reproduce_epoch():
    """epoch_shape_hints + bucket idempotence: a fresh build handed the
    live epoch's shapes reproduces R and every Kmax exactly."""
    from dccrg_tpu.parallel.epoch import build_epoch

    g = make_grid(n_dev=8)
    g.refine_completely(1)
    g.stop_refining()
    hints = epoch_shape_hints(g.epoch)
    rebuilt = build_epoch(
        g.mapping, g.topology, g.leaves, g.n_devices, g.neighborhoods,
        uniform_geometry=g._uniform_geometry(), shape_hints=hints,
    )
    assert rebuilt.R == g.epoch.R
    assert signature_of(rebuilt) == signature_of(g.epoch)


# ------------------------------------------------------------ (d) LRU


def test_executable_cache_bounded_lru():
    cache = ExecutableCache(maxsize=4)
    ev0 = obs.metrics.counter_value("epoch.cache_evictions") or 0
    for i in range(10):
        cache.get(("k", i), lambda i=i: i)
    assert len(cache) == 4
    assert (obs.metrics.counter_value("epoch.cache_evictions") or 0) \
        >= ev0 + 6
    # most-recent entries survive; LRU is gone
    assert ("k", 9) in cache and ("k", 0) not in cache
    # a hit refreshes recency
    assert cache.get(("k", 6), lambda: "rebuilt") == 6
    cache.get(("k", 99), lambda: 99)
    assert ("k", 6) in cache


def test_executable_cache_hit_returns_same_object():
    cache = ExecutableCache(maxsize=8)
    built = []
    fn = cache.get(("a",), lambda: built.append(1) or object())
    fn2 = cache.get(("a",), lambda: built.append(1) or object())
    assert fn is fn2 and len(built) == 1


def test_table_pool_roundtrip():
    pool = TablePool()
    tabs = (
        np.zeros((2, 8, 4), np.int32), np.zeros((2, 8, 4), bool),
        np.zeros((2, 8, 4, 3), np.int32), np.zeros((2, 8, 4), np.int32),
        np.zeros((2, 8, 4), np.int32),
    )
    pool.put(tabs)
    assert pool.take(2, 8, 8) is None          # shape mismatch
    got = pool.take(2, 8, 4)
    assert got is tabs
    assert pool.take(2, 8, 4) is None          # handed out once


def test_grid_reuses_pooled_tables():
    """Successive delta rebuilds at a held signature recycle the retired
    epoch's gather-table buffers (epoch.table_pool_reuse moves)."""
    g = make_grid(n_dev=1)
    ids = g.get_cells()
    ctr = g.geometry.get_center(ids)
    g.refine_completely_many(ids[np.linalg.norm(ctr - 0.5, axis=1) < 0.3])
    g.stop_refining()
    before = obs.metrics.counter_value("epoch.table_pool_reuse") or 0
    for i in range(3):
        cells = g.get_cells()
        lvl = g.mapping.get_refinement_level(cells)
        g.refine_completely(int(cells[lvl < 2][i]))
        g.stop_refining()
    assert (obs.metrics.counter_value("epoch.table_pool_reuse") or 0) \
        > before
