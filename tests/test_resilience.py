"""Fault-injection plane + checkpoint lineage (ISSUE 4): every
detection/recovery path must actually fire under injected faults, and a
SIGKILLed run must resume bit-identically through the lineage manager."""
import glob
import os
import signal
import socket
import subprocess
import sys

import numpy as np
import pytest

from dccrg_tpu import CartesianGeometry, Grid, make_mesh, obs
from dccrg_tpu.io.checkpoint import CheckpointError
from dccrg_tpu.resilience import CheckpointLineage, FaultPlane, plane
from dccrg_tpu.resilience.manager import MANIFEST_NAME

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _disarm_after():
    yield
    plane.disarm()


# ------------------------------------------------------------ inject plane


def test_plane_unarmed_never_fires():
    p = FaultPlane()
    assert not p.fires("nope")
    assert p.fired("nope") == 0


def test_plane_determinism_and_budgets():
    p = FaultPlane()
    p.arm("x", prob=0.5, seed=42)
    pattern1 = [p.fires("x") for _ in range(50)]
    p.arm("x", prob=0.5, seed=42)  # re-arm resets RNG + budget
    pattern2 = [p.fires("x") for _ in range(50)]
    assert pattern1 == pattern2
    assert any(pattern1) and not all(pattern1)
    # count budget
    p.arm("y", prob=1.0, seed=0, count=3)
    assert [p.fires("y") for _ in range(5)] == [True, True, True,
                                               False, False]
    # 'after' skips evaluations before the site becomes eligible
    p.arm("z", prob=1.0, seed=0, count=1, after=2)
    assert [p.fires("z") for _ in range(4)] == [False, False, True, False]
    with pytest.raises(ValueError, match="probability"):
        p.arm("w", prob=1.5)


def test_plane_env_spec_parsing():
    p = FaultPlane()
    p.load_env("a:0.25:7:3:2, b , c:1.0")
    rep = p.report()
    assert rep["a"] == {"prob": 0.25, "fired": 0, "remaining": 3,
                       "after": 2}
    assert rep["b"]["prob"] == 1.0 and rep["b"]["remaining"] is None
    assert set(rep) == {"a", "b", "c"}


def test_plane_firings_counted_in_registry():
    before = obs.metrics.counter_value("resilience.injected",
                                       site="unit.test")
    plane.arm("unit.test", prob=1.0, seed=0, count=2)
    assert plane.fires("unit.test") and plane.fires("unit.test")
    assert not plane.fires("unit.test")
    assert obs.metrics.counter_value(
        "resilience.injected", site="unit.test"
    ) == before + 2


# --------------------------------------------------------------- p2p retry


def test_recv_retry_counts_and_recovers():
    from dccrg_tpu.utils.collectives import _P2PTransport

    a, b = socket.socketpair()
    try:
        b.sendall(b"resilient!")
        plane.arm("p2p.recv", prob=1.0, seed=0, count=3)
        before = obs.metrics.counter_value("p2p.retries", peer="9")
        got = _P2PTransport._recvn(a, 10, peer=9)
        assert got == b"resilient!"
        assert obs.metrics.counter_value("p2p.retries", peer="9") \
            == before + 3
    finally:
        a.close()
        b.close()


def test_retry_budget_exhaustion_aborts_cleanly():
    from dccrg_tpu.utils.collectives import retrying

    calls = []

    def flaky():
        calls.append(1)
        raise ConnectionResetError("always down")

    with pytest.raises(RuntimeError, match="retry budget of 2 exhausted"):
        retrying(flaky, "connect", peer=4, budget=2, base=0.001)
    assert len(calls) == 3  # initial + 2 retries
    # the diagnostic names the op, the peer, and the env knob
    try:
        retrying(flaky, "connect", peer=4, budget=0, base=0.001)
    except RuntimeError as e:
        assert "connect" in str(e) and "peer 4" in str(e)
        assert "DCCRG_P2P_RETRIES" in str(e)


def test_timeouts_are_not_retried():
    from dccrg_tpu.utils.collectives import retrying

    calls = []

    def slow():
        calls.append(1)
        raise socket.timeout("too slow")

    with pytest.raises(socket.timeout):
        retrying(slow, "recv", budget=5, base=0.001)
    assert len(calls) == 1


# ------------------------------------------------------------- halo.nan


def test_halo_nan_storm_detected_by_verify_finite():
    from dccrg_tpu.utils.verify import verify_finite

    g = (
        Grid()
        .set_initial_length((4, 4, 1))
        .set_neighborhood_length(1)
        .set_periodic(True, True, False)
        .initialize(mesh=make_mesh(n_devices=2))
    )
    spec = {"q": ((), np.float64)}
    cells = g.get_cells()
    state = g.set_cell_data(g.new_state(spec), "q", cells,
                            np.arange(len(cells), dtype=float))
    verify_finite(g, state, spec)  # clean state passes

    plane.arm("halo.nan", prob=1.0, seed=5, count=1)
    before = obs.metrics.counter_value("resilience.injected",
                                       site="halo.nan")
    stormed = g.update_copies_of_remote_neighbors(state)
    assert obs.metrics.counter_value(
        "resilience.injected", site="halo.nan"
    ) == before + 1
    with pytest.raises(AssertionError, match="non-finite"):
        verify_finite(g, stormed, spec)
    # disarmed exchanges are clean again
    plane.disarm("halo.nan")
    refreshed = g.update_copies_of_remote_neighbors(state)
    verify_finite(g, refreshed, spec)


# ------------------------------------------------------------ lineage


SPEC = {"v": ((), np.float64)}


def _small_grid(n_devices=2):
    g = (
        Grid()
        .set_initial_length((4, 4, 1))
        .set_neighborhood_length(1)
        .initialize(mesh=make_mesh(n_devices=n_devices))
    )
    cells = g.get_cells()
    state = g.set_cell_data(g.new_state(SPEC), "v", cells,
                            np.arange(len(cells), dtype=float))
    return g, state, cells


def test_lineage_commit_rotate_resume(tmp_path):
    g, state, cells = _small_grid()
    d = str(tmp_path / "lin")
    lin = CheckpointLineage(d, keep=3)
    for i in range(5):
        state = g.set_cell_data(state, "v", cells,
                                np.full(len(cells), float(i)))
        gen = lin.commit(g, state, SPEC, user_header=str(i).encode())
        assert gen == i + 1
    gens = [e["gen"] for e in lin.generations()]
    assert gens == [3, 4, 5]  # keep=3 rotated the oldest out
    assert len(glob.glob(os.path.join(d, "gen-*.dc"))) == 3
    g2, s2, hdr, gen = Grid.resume_latest(d, SPEC, n_devices=1)
    assert (gen, hdr) == (5, b"4")
    np.testing.assert_array_equal(
        g2.get_cell_data(s2, "v", cells), np.full(len(cells), 4.0)
    )


def test_lineage_scans_past_torn_and_corrupt_generations(tmp_path):
    g, state, cells = _small_grid()
    d = str(tmp_path / "lin")
    lin = CheckpointLineage(d, keep=4)
    for i in range(4):
        lin.commit(g, state, SPEC, user_header=str(i).encode())
    files = sorted(glob.glob(os.path.join(d, "gen-*.dc")))
    # newest torn mid-payload, second-newest bit-flipped
    with open(files[-1], "r+b") as f:
        f.truncate(os.path.getsize(files[-1]) - 11)
    with open(files[-2], "r+b") as f:
        f.seek(os.path.getsize(files[-2]) - 5)
        b = f.read(1)
        f.seek(-1, 1)
        f.write(bytes([b[0] ^ 0x01]))
    before = obs.metrics.counter_value("lineage.generations_skipped",
                                       reason="size")
    g2, s2, hdr, gen = Grid.resume_latest(d, SPEC, n_devices=2)
    assert (gen, hdr) == (2, b"1")
    # both bad generations were skipped with file-level evidence (the
    # manifest records size + whole-file CRC of what was committed)
    skipped = obs.metrics.counter_value("lineage.generations_skipped",
                                        reason="size") + \
        obs.metrics.counter_value("lineage.generations_skipped",
                                  reason="file_crc")
    assert skipped >= 2


def test_lineage_torn_manifest_falls_back_to_scan(tmp_path):
    g, state, cells = _small_grid()
    d = str(tmp_path / "lin")
    lin = CheckpointLineage(d, keep=3)
    for i in range(3):
        lin.commit(g, state, SPEC, user_header=str(i).encode())
    with open(os.path.join(d, MANIFEST_NAME), "r+b") as f:
        f.truncate(17)
    before = obs.metrics.counter_value("lineage.manifest_torn")
    g2, s2, hdr, gen = Grid.resume_latest(d, SPEC, n_devices=1)
    assert (gen, hdr) == (3, b"2")
    assert obs.metrics.counter_value("lineage.manifest_torn") > before
    # and a later commit re-adopts the scanned generations + heals the
    # manifest
    ng = lin.commit(g, state, SPEC, user_header=b"healed")
    assert ng == 4
    entries, healthy = lin._read_manifest()
    assert healthy and [e["gen"] for e in entries] == [2, 3, 4]


def test_lineage_rejects_torn_commit_and_keeps_previous(tmp_path):
    g, state, cells = _small_grid()
    d = str(tmp_path / "lin")
    lin = CheckpointLineage(d, keep=2)
    lin.commit(g, state, SPEC, user_header=b"good")
    plane.arm("checkpoint.torn_write", prob=1.0, seed=1, count=1)
    with pytest.raises(CheckpointError, match="lineage"):
        lin.commit(g, state, SPEC, user_header=b"torn")
    plane.disarm("checkpoint.torn_write")
    assert obs.metrics.counter_value("resilience.injected",
                                     site="checkpoint.torn_write") >= 1
    g2, s2, hdr, gen = Grid.resume_latest(d, SPEC, n_devices=1)
    assert (gen, hdr) == (1, b"good")
    # the torn stray neither occupies a keep slot nor survives the next
    # successful rotation
    lin.commit(g, state, SPEC, user_header=b"after")
    lin.commit(g, state, SPEC, user_header=b"after2")
    g2, s2, hdr, gen = Grid.resume_latest(d, SPEC, n_devices=1)
    assert hdr == b"after2"


def test_lineage_skips_bitflipped_generation_via_payload_crc(tmp_path):
    """The acceptance-criteria chain: a generation written with a
    flipped bit is detected by CRC, skipped by the scan, and salvage
    recovers every intact cell — all visible in telemetry."""
    g, state, cells = _small_grid()
    d = str(tmp_path / "lin")
    lin = CheckpointLineage(d, keep=3)
    clean = lin.commit(g, state, SPEC, user_header=b"clean")
    plane.arm("checkpoint.bit_flip", prob=1.0, seed=2, count=1)
    flipped = lin.commit(g, state, SPEC, user_header=b"flipped")
    plane.disarm("checkpoint.bit_flip")

    crc_before = obs.metrics.counter_value("checkpoint.crc_failures",
                                           section="payload")
    g2, s2, hdr, gen = Grid.resume_latest(d, SPEC, n_devices=2)
    assert (gen, hdr) == (clean, b"clean")
    assert obs.metrics.counter_value(
        "checkpoint.crc_failures", section="payload"
    ) > crc_before
    assert obs.metrics.counter_value(
        "lineage.generations_skipped", reason="payload"
    ) >= 1

    # salvage of the flipped generation recovers all intact cells
    g3, s3, hdr3, gen3, lost = lin.salvage_latest(SPEC, n_devices=1)
    assert gen3 == flipped and hdr3 == b"flipped"
    assert len(lost) == 1
    keep = ~np.isin(cells, lost)
    np.testing.assert_array_equal(
        np.asarray(g3.get_cell_data(s3, "v", cells[keep])),
        np.asarray(g.get_cell_data(state, "v", cells[keep])),
    )


def test_lineage_empty_directory_raises(tmp_path):
    with pytest.raises(CheckpointError, match="no valid generation"):
        CheckpointLineage(str(tmp_path / "empty")).latest_valid(
            SPEC, n_devices=1
        )


# ----------------------------------- leaf-set validation (satellite 2)


def _leafset_grid():
    return (
        Grid()
        .set_initial_length((4, 4, 4))
        .set_maximum_refinement_level(2)
        .set_neighborhood_length(1)
    )


def test_leaf_set_non_tiling_names_corrupt_checkpoint():
    base = np.arange(1, 65, dtype=np.uint64)
    with pytest.raises(
        ValueError,
        match=r"leaf_set does not tile the domain \(corrupt checkpoint\?\)",
    ):
        _leafset_grid().initialize(mesh=make_mesh(n_devices=1),
                                   leaf_set=base[1:])


def test_leaf_set_overlap_names_corrupt_checkpoint():
    base = np.arange(1, 65, dtype=np.uint64)
    g0 = _leafset_grid().initialize(mesh=make_mesh(n_devices=1))
    kids = g0.mapping.get_all_children(np.uint64(1))
    overlap = np.concatenate([base[0:1], base[2:], kids]).astype(np.uint64)
    with pytest.raises(
        ValueError,
        match=r"cell and its ancestor\s+\(corrupt checkpoint\?\)",
    ):
        _leafset_grid().initialize(mesh=make_mesh(n_devices=1),
                                   leaf_set=overlap)


def test_leaf_set_two_to_one_violation_raises():
    """A level-2 family island inside level-0 neighbors violates 2:1;
    the neighbor engine rejects it during the build and the loader
    contract turns that into the documented ValueError."""
    base = np.arange(1, 65, dtype=np.uint64)
    g0 = _leafset_grid().initialize(mesh=make_mesh(n_devices=1))
    kids = g0.mapping.get_all_children(np.uint64(1))
    grandkids = np.concatenate(
        [g0.mapping.get_all_children(k) for k in kids]
    ).astype(np.uint64)
    bad = np.concatenate([base[1:], grandkids]).astype(np.uint64)
    with pytest.raises(ValueError, match="consistent 2:1|2:1 balance"):
        _leafset_grid().initialize(mesh=make_mesh(n_devices=1),
                                   leaf_set=bad)


def test_two_to_one_post_build_oracle_message():
    """grid.py's defensive post-build balance check (the last line of
    the loader's validation) raises the documented message when the
    epoch's neighbor tables carry a >2x length ratio — exercised
    directly, since any set reachable through initialize is rejected
    earlier by the neighbor engine."""
    g = _leafset_grid().initialize(mesh=make_mesh(n_devices=1))
    hood = g.epoch.hoods[None]
    orig = hood.nbr_len
    try:
        fake = orig.copy()
        valid = np.argwhere(hood.nbr_valid)
        i = tuple(valid[0])
        fake[i] = fake[i] * 4  # fake a two-level jump
        hood.nbr_len = fake
        with pytest.raises(
            ValueError,
            match=r"violates 2:1 balance \(corrupt checkpoint\?\)",
        ):
            g._validate_two_to_one()
    finally:
        hood.nbr_len = orig


# ------------------------------------------------- crash smoke (CI speed)


CRASH_SMOKE_CHILD = r"""
import sys
wd, kill_spec = sys.argv[1], sys.argv[2]
import os
os.environ["DCCRG_FAULT"] = kill_spec
import jax
jax.config.update('jax_platforms', 'cpu')
import numpy as np
sys.path.insert(0, {root!r})
from dccrg_tpu import Grid, make_mesh
from dccrg_tpu.io.checkpoint import CheckpointError
from dccrg_tpu.models import GameOfLife
from dccrg_tpu.resilience.manager import CheckpointLineage

g = (Grid().set_initial_length((6, 6, 1)).set_neighborhood_length(1)
     .set_periodic(True, True, False)
     .initialize(mesh=make_mesh(n_devices=1)))
cells = g.get_cells()
alive0 = cells[np.random.default_rng(0).random(len(cells)) < 0.4]
lineage = CheckpointLineage(os.path.join(wd, 'gol'), keep=3)
gol = GameOfLife(g)
s = gol.new_state(alive_cells=alive0)
step = 0
while step < 8:
    s = gol.run(s, 1)
    step += 1
    lineage.commit(g, s, GameOfLife.SPEC, user_header=str(step).encode())
print('CHILD_COMPLETED', flush=True)
"""


@pytest.mark.parametrize("resume_devices", [1])
def test_crash_sigkill_resume_bit_identical(tmp_path, resume_devices):
    """CI-speed crash smoke (ISSUE 4 satellite): one SIGKILL/resume
    cycle through the lineage manager — the child dies at its SECOND
    commit via the sigkill.post_commit injection site, this process
    resumes from latest_valid() and the continued run's final state is
    bit-identical to the uninterrupted one."""
    from dccrg_tpu.models import GameOfLife

    # uninterrupted oracle, in process
    g = (
        Grid()
        .set_initial_length((6, 6, 1))
        .set_neighborhood_length(1)
        .set_periodic(True, True, False)
        .initialize(mesh=make_mesh(n_devices=1))
    )
    cells = g.get_cells()
    alive0 = cells[np.random.default_rng(0).random(len(cells)) < 0.4]
    gol = GameOfLife(g)
    ref = gol.run(gol.new_state(alive_cells=alive0), 8)
    want_alive = set(gol.alive_cells(ref).tolist())

    # the child SIGKILLs itself right after its second commit
    wd = str(tmp_path)
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    env.pop("DCCRG_FAULT", None)
    r = subprocess.run(
        [sys.executable, "-c", CRASH_SMOKE_CHILD.format(root=ROOT),
         wd, "sigkill.post_commit:1:0:1:1"],
        capture_output=True, text=True, env=env, timeout=240,
    )
    assert r.returncode == -signal.SIGKILL, (r.returncode, r.stdout,
                                             r.stderr)
    assert "CHILD_COMPLETED" not in r.stdout

    # resume from the lineage and finish the run
    g2, s2, hdr, gen = Grid.resume_latest(
        os.path.join(wd, "gol"), GameOfLife.SPEC,
        n_devices=resume_devices,
    )
    step = int(hdr)
    assert step == 2 and gen == 2  # died exactly at the second commit
    gol2 = GameOfLife(g2)
    s2 = gol2.run(s2, 8 - step)
    assert set(gol2.alive_cells(s2).tolist()) == want_alive
    # bit-identical full state, not just the alive set
    for field in GameOfLife.SPEC:
        np.testing.assert_array_equal(
            np.asarray(g2.get_cell_data(s2, field, cells)),
            np.asarray(g.get_cell_data(ref, field, cells)),
            err_msg=field,
        )
