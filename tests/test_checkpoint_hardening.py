"""Hardened checkpoint format (ISSUE 4b): CRC-carrying v2 envelope,
typed CheckpointError on every torn/garbage read path, per-cell salvage,
and v1 back-compatibility."""
import os
import struct
import zlib

import numpy as np
import pytest

from dccrg_tpu import CartesianGeometry, Grid, make_mesh
from dccrg_tpu.io.checkpoint import (
    CHECKPOINT_VERSION,
    CheckpointError,
    V2_MAGIC,
    quick_validate,
)


SPEC = {"a": ((), np.float64), "b": ((3,), np.float32)}


def _grid_and_state(n_devices=2, seed=7):
    g = (
        Grid()
        .set_initial_length((4, 4, 2))
        .set_maximum_refinement_level(1)
        .set_neighborhood_length(1)
        .set_periodic(True, False, False)
        .set_geometry(
            CartesianGeometry, start=(0.0, 0.0, 0.0),
            level_0_cell_length=(0.25, 0.25, 0.5),
        )
        .initialize(mesh=make_mesh(n_devices=n_devices))
    )
    g.refine_completely(1)
    g.stop_refining()
    cells = g.get_cells()
    rng = np.random.default_rng(seed)
    state = g.new_state(SPEC)
    av = rng.standard_normal(len(cells))
    bv = rng.standard_normal((len(cells), 3)).astype(np.float32)
    state = g.set_cell_data(state, "a", cells, av)
    state = g.set_cell_data(state, "b", cells, bv)
    return g, state, cells, av, bv


def _sections_of(raw: bytes):
    """Byte extents of each v2 section: [(name, start, end), ...]."""
    assert raw[:8] == V2_MAGIC
    (hlen,) = struct.unpack("<Q", raw[8:16])
    (n_cells,) = struct.unpack("<Q", raw[16 + hlen - 8 : 16 + hlen])
    head_end = 16 + hlen + 4
    tlen = n_cells * 20 + 8
    table_end = head_end + tlen + 4
    return [
        ("magic", 0, 8),
        ("header_len", 8, 16),
        ("header", 16, 16 + hlen),
        ("header_crc", 16 + hlen, head_end),
        ("cell_table", head_end, head_end + tlen),
        ("table_crc", head_end + tlen, table_end),
        ("payload", table_end, len(raw)),
    ]


def test_v2_is_default_and_roundtrips(tmp_path):
    g, state, cells, av, bv = _grid_and_state()
    path = str(tmp_path / "v2.dc")
    g.save_grid_data(state, path, SPEC, user_header=b"v2-header")
    raw = open(path, "rb").read()
    assert raw[:8] == V2_MAGIC
    assert CHECKPOINT_VERSION == 2
    assert quick_validate(path) == 2
    for n_dev in (1, 3, 8):
        g2, s2, hdr = Grid.load_grid_data(path, SPEC, n_devices=n_dev)
        assert hdr == b"v2-header"
        np.testing.assert_array_equal(g2.get_cells(), cells)
        np.testing.assert_array_equal(g2.get_cell_data(s2, "a", cells), av)
        np.testing.assert_array_equal(g2.get_cell_data(s2, "b", cells), bv)


def test_v1_files_still_load(tmp_path):
    g, state, cells, av, bv = _grid_and_state()
    path = str(tmp_path / "v1.dc")
    g.save_grid_data(state, path, SPEC, user_header=b"old", version=1)
    raw = open(path, "rb").read()
    assert raw[:8] != V2_MAGIC
    assert quick_validate(path) == 1
    g2, s2, hdr = Grid.load_grid_data(path, SPEC, n_devices=3)
    assert hdr == b"old"
    np.testing.assert_array_equal(g2.get_cell_data(s2, "a", cells), av)
    np.testing.assert_array_equal(g2.get_cell_data(s2, "b", cells), bv)


@pytest.mark.parametrize("version", [1, 2])
def test_truncation_raises_typed_error_at_every_cut(tmp_path, version):
    """A file cut ANYWHERE must raise CheckpointError naming a section —
    never a bare struct.error/EOFError (satellite 1).  Cuts sweep every
    section boundary plus points inside each section."""
    g, state, cells, av, bv = _grid_and_state(n_devices=1)
    path = str(tmp_path / "full.dc")
    g.save_grid_data(state, path, SPEC, version=version)
    raw = open(path, "rb").read()
    cuts = set()
    if version == 2:
        for name, start, end in _sections_of(raw):
            cuts.update((start, (start + end) // 2, max(start, end - 1)))
    cuts.update(range(0, len(raw), max(1, len(raw) // 40)))
    cuts.discard(len(raw))
    cut_path = str(tmp_path / "cut.dc")
    for cut in sorted(cuts):
        with open(cut_path, "wb") as f:
            f.write(raw[:cut])
        with pytest.raises(CheckpointError) as ei:
            Grid.load_grid_data(cut_path, SPEC, n_devices=1)
        assert ei.value.section, cut
        # and the chunked triple surfaces the same typed error
        with pytest.raises(CheckpointError):
            loader = Grid.start_loading_grid_data(cut_path, SPEC,
                                                  n_devices=1)
            while loader.continue_loading_grid_data(max_cells=3):
                pass
            loader.finish_loading_grid_data()


def test_bit_flip_detected_per_section(tmp_path):
    """One flipped bit in any section is detected by the CRC for that
    section and reported with its name."""
    from dccrg_tpu import obs

    g, state, cells, av, bv = _grid_and_state(n_devices=1)
    path = str(tmp_path / "clean.dc")
    g.save_grid_data(state, path, SPEC)
    raw = open(path, "rb").read()
    sections = dict(
        (name, (start, end)) for name, start, end in _sections_of(raw)
    )
    flip_path = str(tmp_path / "flipped.dc")
    for name, want_sections in (
        ("header", {"header"}),
        ("cell_table", {"cell_table"}),
        ("payload", {"payload"}),
    ):
        start, end = sections[name]
        flipped = bytearray(raw)
        flipped[(start + end) // 2] ^= 0x20
        with open(flip_path, "wb") as f:
            f.write(bytes(flipped))
        before = obs.metrics.counter_value(
            "checkpoint.crc_failures", section=name
        )
        with pytest.raises(CheckpointError) as ei:
            Grid.load_grid_data(flip_path, SPEC, n_devices=1)
        assert ei.value.section in want_sections
        after = obs.metrics.counter_value(
            "checkpoint.crc_failures", section=name
        )
        assert after > before, f"CRC failure for {name} not counted"


def test_salvage_recovers_every_intact_cell(tmp_path):
    from dccrg_tpu import obs

    g, state, cells, av, bv = _grid_and_state(n_devices=2)
    path = str(tmp_path / "clean.dc")
    g.save_grid_data(state, path, SPEC)
    raw = bytearray(open(path, "rb").read())
    payload_start = _sections_of(bytes(raw))[-1][1]
    bpc = 8 + 3 * 4  # fixed layout of SPEC
    # corrupt the payloads of three scattered cells
    victims = [1, len(cells) // 2, len(cells) - 1]
    for v in victims:
        raw[payload_start + v * bpc + 3] ^= 0xFF
    bad_path = str(tmp_path / "bad.dc")
    open(bad_path, "wb").write(bytes(raw))

    with pytest.raises(CheckpointError, match="payload"):
        Grid.load_grid_data(bad_path, SPEC, n_devices=1)

    before_lost = obs.metrics.counter_value("checkpoint.cells_lost")
    g2, s2, hdr, lost = Grid.load_grid_data(
        bad_path, SPEC, n_devices=3, on_error="salvage"
    )
    np.testing.assert_array_equal(lost, cells[np.asarray(victims)])
    keep = ~np.isin(cells, lost)
    np.testing.assert_array_equal(
        g2.get_cell_data(s2, "a", cells[keep]), av[keep]
    )
    np.testing.assert_array_equal(
        g2.get_cell_data(s2, "b", cells[keep]), bv[keep]
    )
    # lost cells fall back to the new_state fill (zeros), not garbage
    np.testing.assert_array_equal(
        np.asarray(g2.get_cell_data(s2, "a", lost)), np.zeros(len(lost))
    )
    assert obs.metrics.counter_value("checkpoint.cells_lost") \
        == before_lost + len(victims)


def test_salvage_of_truncated_file_recovers_prefix(tmp_path):
    g, state, cells, av, bv = _grid_and_state(n_devices=1)
    path = str(tmp_path / "clean.dc")
    g.save_grid_data(state, path, SPEC)
    raw = open(path, "rb").read()
    payload_start = _sections_of(raw)[-1][1]
    bpc = 8 + 3 * 4
    keep_cells = len(cells) // 3
    cut = payload_start + keep_cells * bpc + bpc // 2  # mid-cell tear
    cut_path = str(tmp_path / "torn.dc")
    open(cut_path, "wb").write(raw[:cut])

    with pytest.raises(CheckpointError, match="truncated"):
        Grid.load_grid_data(cut_path, SPEC, n_devices=1)
    g2, s2, hdr, lost = Grid.load_grid_data(
        cut_path, SPEC, n_devices=1, on_error="salvage"
    )
    np.testing.assert_array_equal(lost, cells[keep_cells:])
    np.testing.assert_array_equal(
        g2.get_cell_data(s2, "a", cells[:keep_cells]), av[:keep_cells]
    )


def test_salvage_ragged_payloads(tmp_path):
    """Per-cell CRC integrity composes with variable-size payloads: a
    corrupt ragged cell is lost alone, every other cell's particles
    survive bit-exactly."""
    from dccrg_tpu.models import Particles

    g = (
        Grid()
        .set_initial_length((4, 4, 1))
        .set_neighborhood_length(1)
        .set_periodic(True, True, False)
        .set_geometry(
            CartesianGeometry, start=(0.0, 0.0, 0.0),
            level_0_cell_length=(0.25, 0.25, 1.0),
        )
        .initialize(mesh=make_mesh(n_devices=4))
    )
    p = Particles(g, max_particles_per_cell=8)
    rng = np.random.default_rng(3)
    state = p.new_state(rng.uniform(0.01, 0.99, size=(41, 3)))
    spec, ragged = p.spec(), {"particles": "number_of_particles"}
    path = str(tmp_path / "ragged.dc")
    g.save_grid_data(state, path, spec, ragged=ragged)

    raw = bytearray(open(path, "rb").read())
    cells = g.get_cells()
    # find a victim cell that actually carries particles, and flip a
    # byte inside its payload chunk (chunk extents from the table)
    secs = dict((n, (s, e)) for n, s, e in _sections_of(bytes(raw)))
    t0, t1 = secs["cell_table"]
    n = len(cells)
    table = np.frombuffer(bytes(raw[t0:t0 + n * 16]), "<u8").reshape(n, 2)
    counts = np.asarray(
        g.get_cell_data(state, "number_of_particles", table[:, 0]),
        np.int64,
    )
    victim = int(np.flatnonzero(counts > 0)[0])
    pstart = secs["payload"][0]
    raw[pstart + int(table[victim, 1]) + 10] ^= 0x40
    bad = str(tmp_path / "ragged_bad.dc")
    open(bad, "wb").write(bytes(raw))

    g2, s2, hdr, lost = Grid.load_grid_data(
        bad, spec, ragged=ragged, n_devices=2, on_error="salvage"
    )
    np.testing.assert_array_equal(lost, table[victim : victim + 1, 0])
    p2 = Particles(g2, max_particles_per_cell=8)
    for c in cells:
        if c == lost[0]:
            assert len(p2.particles_of(s2, int(c))) == 0
        else:
            np.testing.assert_array_equal(
                np.sort(p2.particles_of(s2, int(c)), axis=0),
                np.sort(p.particles_of(state, int(c)), axis=0),
            )


def test_quick_validate_failures(tmp_path):
    g, state, cells, av, bv = _grid_and_state(n_devices=1)
    path = str(tmp_path / "c.dc")
    g.save_grid_data(state, path, SPEC)
    raw = open(path, "rb").read()
    bad = str(tmp_path / "bad.dc")
    # torn payload
    open(bad, "wb").write(raw[:-7])
    with pytest.raises(CheckpointError, match="payload"):
        quick_validate(bad)
    # flipped header byte
    secs = dict((n, (s, e)) for n, s, e in _sections_of(raw))
    flipped = bytearray(raw)
    flipped[(secs["header"][0] + secs["header"][1]) // 2] ^= 1
    open(bad, "wb").write(bytes(flipped))
    with pytest.raises(CheckpointError, match="header"):
        quick_validate(bad)
    # quick_validate does NOT read the payload: a payload flip passes
    flipped = bytearray(raw)
    flipped[-3] ^= 1
    open(bad, "wb").write(bytes(flipped))
    assert quick_validate(bad) == 2


def test_on_error_rejects_unknown_policy(tmp_path):
    g, state, cells, av, bv = _grid_and_state(n_devices=1)
    path = str(tmp_path / "c.dc")
    g.save_grid_data(state, path, SPEC)
    with pytest.raises(ValueError, match="on_error"):
        Grid.load_grid_data(path, SPEC, n_devices=1, on_error="ignore")
    with pytest.raises(ValueError, match="version"):
        g.save_grid_data(state, path, SPEC, version=3)


def test_checkpoint_error_is_value_error():
    err = CheckpointError("payload", "boom", path="/x")
    assert isinstance(err, ValueError)
    assert err.section == "payload"
    assert "payload" in str(err) and "/x" in str(err)
