"""Grid construction + halo exchange tests on the virtual 8-device CPU mesh.

Mirrors the reference's init/proc_bdy_cells/mpi_support test dirs: grid
invariants must be device-count-invariant and ghost copies bit-identical to
their source rows.
"""
import numpy as np
import pytest
import jax

from dccrg_tpu import Grid, make_mesh


def make_grid(length=(8, 8, 1), hood=1, periodic=(False, False, False), n_dev=None):
    g = (
        Grid()
        .set_initial_length(length)
        .set_periodic(*periodic)
        .set_neighborhood_length(hood)
    )
    return g.initialize(mesh=make_mesh(n_devices=n_dev))


def test_initialize_partitions_all_cells():
    g = make_grid()
    assert g.n_devices == 8
    all_local = np.concatenate([g.local_cells(d) for d in range(8)])
    np.testing.assert_array_equal(np.sort(all_local), g.get_cells())
    # block striping: contiguous id ranges
    for d in range(7):
        if len(g.local_cells(d)) and len(g.local_cells(d + 1)):
            assert g.local_cells(d).max() < g.local_cells(d + 1).min()


def test_owner_directory():
    g = make_grid()
    for d in range(8):
        assert (g.get_owner(g.local_cells(d)) == d).all()
    assert int(g.get_owner(np.uint64(0))) == -1


def test_inner_outer_partition():
    g = make_grid(length=(8, 8, 1))
    for d in range(8):
        inner = set(g.inner_cells(d).tolist())
        outer = set(g.outer_cells(d).tolist())
        local = set(g.local_cells(d).tolist())
        assert inner | outer == local
        assert not (inner & outer)
        # inner cells have no remote neighbors
        for c in inner:
            ids, _ = g.get_neighbors_of(c)
            assert (g.get_owner(ids) == d).all()
        for c in outer:
            ids, _ = g.get_neighbors_of(c)
            to = g.get_owner(g.get_neighbors_to(c))
            assert (g.get_owner(ids) != d).any() or (to != d).any()


def test_halo_exchange_bit_identical():
    g = make_grid(length=(8, 8, 1))
    spec = {"v": ((), np.float64)}
    state = g.new_state(spec)
    # value = cell id as float (exactly representable)
    cells = g.get_cells()
    state = g.set_cell_data(state, "v", cells, cells.astype(np.float64))
    state = g.update_copies_of_remote_neighbors(state)
    # every ghost row must hold exactly its cell's id
    host = np.asarray(state["v"])
    for d in range(8):
        ghosts = g.remote_cells(d)
        rows = g.epoch.rows_on_device(d, g.leaves.position(ghosts))
        np.testing.assert_array_equal(host[d, rows], ghosts.astype(np.float64))


def test_halo_exchange_multi_field_and_vector():
    g = make_grid(length=(4, 4, 4), hood=0)
    spec = {"rho": ((), np.float32), "mom": ((3,), np.float64)}
    state = g.new_state(spec)
    cells = g.get_cells()
    rng = np.random.default_rng(3)
    rho = rng.standard_normal(len(cells)).astype(np.float32)
    mom = rng.standard_normal((len(cells), 3))
    state = g.set_cell_data(state, "rho", cells, rho)
    state = g.set_cell_data(state, "mom", cells, mom)
    state = g.update_copies_of_remote_neighbors(state)
    for d in range(8):
        ghosts = g.remote_cells(d)
        if not len(ghosts):
            continue
        got_rho = np.asarray(state["rho"])[d][
            g.epoch.rows_on_device(d, g.leaves.position(ghosts))
        ]
        want_rho = rho[g.leaves.position(ghosts)]
        np.testing.assert_array_equal(got_rho, want_rho)
        got_mom = np.asarray(state["mom"])[d][
            g.epoch.rows_on_device(d, g.leaves.position(ghosts))
        ]
        np.testing.assert_array_equal(got_mom, mom[g.leaves.position(ghosts)])


def test_set_get_cell_data_roundtrip():
    g = make_grid(length=(4, 4, 1))
    state = g.new_state({"x": ((), np.int32)})
    cells = g.get_cells()
    vals = np.arange(len(cells), dtype=np.int32)
    state = g.set_cell_data(state, "x", cells, vals)
    np.testing.assert_array_equal(g.get_cell_data(state, "x", cells), vals)


def test_send_receive_counts_symmetric():
    g = make_grid(length=(8, 8, 1))
    h = g.epoch.hoods[None]
    # what i sends to j equals what j receives from i by construction;
    # with a symmetric neighborhood the relation is symmetric too
    np.testing.assert_array_equal(h.pair_counts, h.pair_counts.T)
    total_send = sum(g.get_number_of_update_send_cells(d) for d in range(8))
    total_recv = sum(g.get_number_of_update_receive_cells(d) for d in range(8))
    assert total_send == total_recv == int(h.pair_counts.sum())


def test_ring_schedule_wire_bytes_scale_with_actual_pairs(monkeypatch):
    """VERDICT-r4 weak 5: the general halo must not be a padded
    worst-pair x D^2 all_to_all.  The ring schedule only runs the
    distances some pair actually communicates over, each sized by its
    own max pair count, so on a slab-partitioned grid the wire traffic
    tracks the real send lists (reference neighbor-only messaging,
    dccrg.hpp:10564-11070).  Buckets off: the exact-schedule property is
    what's under test (the bucketed margin is asserted separately
    below)."""
    monkeypatch.setenv("DCCRG_EPOCH_BUCKETS", "0")
    g = make_grid(length=(8, 8, 8), hood=1)
    h = g.epoch.hoods[None]
    halo = g.halo(None)
    D = g.n_devices
    pc = np.asarray(h.pair_counts)
    dd = np.arange(D)
    # the schedule covers exactly the distances with traffic
    active = {k for k in range(1, D) if pc[dd, (dd + k) % D].max() > 0}
    assert set(halo.ring_ks) == active
    # wire rows = sum over active distances of D * that distance's max
    want_wire = sum(int(pc[dd, (dd + k) % D].max()) * D for k in active)
    assert halo.wire_cells == want_wire
    # a z-ordered 8x8x8 grid on 8 devices is slab-like: nearest-distance
    # traffic dominates, so the ring moves far less than the padded
    # all_to_all equivalent (D * D * global max) and stays within 2x of
    # the useful payload
    padded_equiv = D * D * int(pc.max())
    assert halo.wire_cells < padded_equiv
    assert halo.wire_cells <= 2 * halo.cells_moved
    state = g.new_state({"v": ((), np.float64)})
    assert halo.wire_bytes(state) == halo.wire_cells * 8
    assert halo.bytes_moved(state) == halo.cells_moved * 8


def test_ring_schedule_bucketed_margin():
    """With shape buckets on (the default), each ring step pads up the
    geometric ladder: wire rows stay within one bucket step of the exact
    schedule and far below the padded all_to_all equivalent."""
    from dccrg_tpu.parallel.shapes import bucket_pairs

    g = make_grid(length=(8, 8, 8), hood=1)
    h = g.epoch.hoods[None]
    halo = g.halo(None)
    D = g.n_devices
    pc = np.asarray(h.pair_counts)
    dd = np.arange(D)
    active = {k for k in range(1, D) if pc[dd, (dd + k) % D].max() > 0}
    assert set(halo.ring_ks) == active
    want_wire = sum(
        bucket_pairs(int(pc[dd, (dd + k) % D].max())) * D for k in active
    )
    assert halo.wire_cells == want_wire
    assert halo.wire_cells < D * D * int(pc.max())


def test_face_neighbors():
    g = make_grid(length=(3, 3, 3), hood=1)
    # center cell 14: 6 face neighbors
    fn = g.get_face_neighbors_of(14)
    dirs = sorted(d for _, d in fn)
    assert dirs == [-3, -2, -1, 1, 2, 3]
    ids = {int(c) for c, _ in fn}
    assert ids == {13, 15, 11, 17, 5, 23}


def test_device_count_invariance():
    """Same grid on 2 vs 8 devices: same global data after halo + stencil."""
    results = {}
    for n_dev in (2, 8):
        g = make_grid(length=(6, 6, 1), n_dev=n_dev)
        state = g.new_state({"v": ((), np.float64)})
        cells = g.get_cells()
        state = g.set_cell_data(state, "v", cells, np.sin(cells.astype(np.float64)))
        state = g.update_copies_of_remote_neighbors(state)
        # neighbor sums via host gather (uses ghost values on each device)
        h = g.epoch.hoods[None]
        host = np.asarray(state["v"])
        sums = np.zeros(len(cells))
        for d in range(g.n_devices):
            rows = np.flatnonzero(g.epoch.local_mask[d])
            nbr = host[d][h.nbr_rows[d, rows]]
            nbr = np.where(h.nbr_valid[d, rows], nbr, 0.0)
            pos = g.leaves.position(g.epoch.cell_ids[d, rows])
            sums[pos] = nbr.sum(axis=1)
        results[n_dev] = sums
    np.testing.assert_array_equal(results[2], results[8])
