"""Halo backend tests (ISSUE 7): the Pallas async-DMA ring bodies
(``parallel/halo_dma.py``, run under the interpreter on this CPU suite)
must be bit-identical to the collective ``ppermute`` path — which stays
the always-available oracle (``DCCRG_HALO_VERIFY=1``) — and the fused
split-phase advection/vlasov steps must reproduce their eager
counterparts while riding the executable cache with zero retraces on a
seen shape signature."""
import jax
import numpy as np
import pytest

from dccrg_tpu import CartesianGeometry, Grid, make_mesh, obs
from dccrg_tpu.models import Advection, GameOfLife, Vlasov
from dccrg_tpu.parallel import halo_dma


def make_grid(n_dev=8, length=(10, 10, 1), max_ref=0, hood_len=1,
              refine_ball=None, periodic=False, geometry=False):
    g = Grid().set_initial_length(length)
    g.set_maximum_refinement_level(max_ref)
    g.set_neighborhood_length(hood_len)
    g.set_periodic(periodic, periodic, periodic)
    g.set_load_balancing_method("RCB")
    if geometry or refine_ball is not None:
        g.set_geometry(
            CartesianGeometry, start=(0.0, 0.0, 0.0),
            level_0_cell_length=tuple(1.0 / n for n in length),
        )
    g.initialize(mesh=make_mesh(n_devices=n_dev))
    if refine_ball is not None:
        ids = g.get_cells()
        ctr = g.geometry.get_center(ids)
        g.refine_completely_many(
            ids[np.linalg.norm(ctr - 0.5, axis=1) < refine_ball]
        )
        g.stop_refining()
        g.balance_load()
    return g


def rand_state(g, spec, seed=0):
    rng = np.random.default_rng(seed)
    state = g.new_state(spec)
    cells = g.get_cells()
    for name, (shape, dtype) in spec.items():
        if np.issubdtype(dtype, np.floating):
            vals = rng.normal(size=(len(cells),) + shape).astype(dtype)
        else:
            vals = rng.integers(0, 7, size=(len(cells),) + shape
                                ).astype(dtype)
        state = g.set_cell_data(state, name, cells, vals)
    return state


def assert_states_bitwise(a, b):
    for name in a:
        assert (np.asarray(a[name]).tobytes()
                == np.asarray(b[name]).tobytes()), name


def assert_ulp_close(a, b, n_ulp):
    a, b = np.asarray(a), np.asarray(b)
    ulp = np.spacing(np.maximum(np.abs(a), np.abs(b)))
    bad = np.abs(a - b) > n_ulp * ulp
    assert not bad.any(), (
        f"{int(bad.sum())} elements beyond {n_ulp} ULP; max diff "
        f"{np.abs(a - b).max()}"
    )


# ------------------------------------------------------ backend selection


def test_backend_resolution(monkeypatch):
    monkeypatch.delenv("DCCRG_HALO_BACKEND", raising=False)
    # auto on a CPU suite: the collective path stays the default
    assert halo_dma.resolve_backend() == "collective"
    monkeypatch.setenv("DCCRG_HALO_BACKEND", "pallas")
    assert halo_dma.resolve_backend() == "pallas"
    monkeypatch.setenv("DCCRG_HALO_BACKEND", "collective")
    assert halo_dma.resolve_backend() == "collective"
    monkeypatch.setenv("DCCRG_HALO_BACKEND", "auto")
    assert halo_dma.resolve_backend() == "collective"


def test_invalid_backend_env_raises(monkeypatch):
    monkeypatch.setenv("DCCRG_HALO_BACKEND", "quantum")
    with pytest.raises(ValueError, match="DCCRG_HALO_BACKEND"):
        halo_dma.resolve_backend()


def test_backend_enters_structure_key(monkeypatch):
    # the backend is resolved when the schedule is CONSTRUCTED (the
    # first halo() call), so snapshot each key under its own env
    monkeypatch.setenv("DCCRG_HALO_BACKEND", "collective")
    g1 = make_grid()
    k1 = g1.halo().structure_key
    monkeypatch.setenv("DCCRG_HALO_BACKEND", "pallas")
    g2 = make_grid()
    k2 = g2.halo().structure_key
    assert k1[-1] == "collective" and k2[-1] == "pallas"
    assert k1[:-1] == k2[:-1]


# ------------------------------------------------- DMA body bit-identity


@pytest.mark.parametrize("n_dev", [1, 8])
@pytest.mark.parametrize(
    "spec",
    [
        {"v": ((), np.float64)},
        {"rho": ((), np.float32), "mom": ((3,), np.float32)},
        {"alive": ((), np.uint32)},
    ],
    ids=["f64-scalar", "f32-multifield", "u32"],
)
def test_pallas_exchange_bit_identical(monkeypatch, n_dev, spec):
    """The interpreted DMA ring body leaves ghost rows byte-for-byte
    equal to the collective path, per dtype and trailing shape, on one
    ring distance and on the refined multi-ring schedule."""
    monkeypatch.setenv("DCCRG_HALO_BACKEND", "pallas")
    gp = make_grid(n_dev=n_dev, length=(8, 8, 8), max_ref=1,
                   refine_ball=0.3, periodic=True)
    assert gp.halo().backend == "pallas"
    if n_dev > 1:
        assert len(gp.halo().ring_ks) >= 2, "want a multi-ring schedule"
    monkeypatch.setenv("DCCRG_HALO_BACKEND", "collective")
    gc = make_grid(n_dev=n_dev, length=(8, 8, 8), max_ref=1,
                   refine_ball=0.3, periodic=True)
    sp = rand_state(gp, spec)
    sc = rand_state(gc, spec)
    assert_states_bitwise(
        gp.update_copies_of_remote_neighbors(sp),
        gc.update_copies_of_remote_neighbors(sc),
    )


def test_pallas_split_matches_blocking(monkeypatch):
    monkeypatch.setenv("DCCRG_HALO_BACKEND", "pallas")
    g = make_grid()
    state = rand_state(g, {"v": ((), np.float64)})
    blocking = g.update_copies_of_remote_neighbors(state)
    handle = g.start_remote_neighbor_copy_updates(state)
    merged = g.wait_remote_neighbor_copy_updates(state, handle)
    assert_states_bitwise(blocking, merged)


# ------------------------------------------------------- verify oracle


def test_verify_counts_and_detects_mismatch(monkeypatch):
    monkeypatch.setenv("DCCRG_HALO_BACKEND", "pallas")
    monkeypatch.setenv("DCCRG_HALO_VERIFY", "1")
    obs.enable()
    g = make_grid()
    ex = g.halo()
    state = rand_state(g, {"v": ((), np.float64)})
    checks0 = obs.metrics.counter_value("halo.verify_checks")
    out = g.update_copies_of_remote_neighbors(state)
    assert obs.metrics.counter_value("halo.verify_checks") == checks0 + 1
    assert obs.metrics.counter_value("halo.verify_mismatches",
                                     field="v") == 0
    # a corrupted payload must be detected AND counted, not raised
    tampered = {"v": np.asarray(out["v"]).copy()}
    tampered["v"][0, 0] += 1.0
    assert ex._verify_oracle(state, tampered) == 1
    assert obs.metrics.counter_value("halo.verify_mismatches",
                                     field="v") == 1
    # the clean result verifies to zero mismatches
    assert ex._verify_oracle(state, out) == 0


def test_verify_env_gates_the_check(monkeypatch):
    monkeypatch.setenv("DCCRG_HALO_BACKEND", "pallas")
    monkeypatch.delenv("DCCRG_HALO_VERIFY", raising=False)
    obs.enable()
    g = make_grid()
    state = rand_state(g, {"v": ((), np.float64)})
    checks0 = obs.metrics.counter_value("halo.verify_checks")
    g.update_copies_of_remote_neighbors(state)
    assert obs.metrics.counter_value("halo.verify_checks") == checks0


def test_verify_noop_on_collective_backend(monkeypatch):
    """The oracle IS the collective path: verifying it against itself
    would double every exchange for nothing, so the gate stays off."""
    monkeypatch.setenv("DCCRG_HALO_BACKEND", "collective")
    monkeypatch.setenv("DCCRG_HALO_VERIFY", "1")
    obs.enable()
    g = make_grid()
    state = rand_state(g, {"v": ((), np.float64)})
    checks0 = obs.metrics.counter_value("halo.verify_checks")
    g.update_copies_of_remote_neighbors(state)
    assert obs.metrics.counter_value("halo.verify_checks") == checks0


# --------------------------------------------- fused split-phase steps


@pytest.mark.parametrize("n_dev", [1, 8])
@pytest.mark.parametrize("backend", ["collective", "pallas"])
def test_split_advection_bit_identical(monkeypatch, n_dev, backend):
    """The fused start → interior → finish → boundary advection step is
    bit-identical to the eager step; the whole-run fori_loop form stays
    within 2 ULP (XLA instruction selection varies with the row-set
    shapes inside the loop — the residual class the module docstring
    already licenses across device counts)."""
    monkeypatch.setenv("DCCRG_HALO_BACKEND", backend)
    g = make_grid(n_dev=n_dev, length=(8, 8, 8), max_ref=1,
                  refine_ball=0.3, periodic=True)
    eager = Advection(g, dtype=np.float64, allow_dense=False)
    fused = Advection(g, dtype=np.float64, allow_dense=False,
                      overlap=True)
    se = eager.initialize_state()
    sf = fused.initialize_state()
    dt = 0.4 * eager.max_time_step(se)
    for _ in range(4):
        se = eager.step(se, dt)
        sf = fused.step(sf, dt)
        assert_states_bitwise({"density": se["density"]},
                              {"density": sf["density"]})
    re = eager.run(se, 3, dt)
    rf = fused.run(sf, 3, dt)
    assert_ulp_close(re["density"], rf["density"], 2)


@pytest.mark.parametrize("n_dev", [1, 8])
@pytest.mark.parametrize("periodic", [True, False],
                         ids=["periodic", "open"])
def test_split_vlasov_matches_eager(monkeypatch, n_dev, periodic):
    """The fused vlasov step matches the eager general step — bitwise
    here (the split form reorders nothing), with the repo's 4-ULP
    envelope as the licensed bound on jax 0.4.x (the acceptance
    criterion's tolerance, matching the fused-kernel tests)."""
    monkeypatch.setenv("DCCRG_HALO_BACKEND", "pallas")
    g = make_grid(n_dev=n_dev, length=(8, 8, 8), max_ref=1,
                  refine_ball=0.3, periodic=periodic)
    eager = Vlasov(g, nv=3, dtype=np.float32)
    fused = Vlasov(g, nv=3, dtype=np.float32, overlap=True)
    assert eager.info is None and fused.info is None
    se = eager.initialize_state()
    sf = fused.initialize_state()
    dt = np.float32(0.5 * eager.max_time_step())
    for _ in range(3):
        se = eager.step(se, dt)
        sf = fused.step(sf, dt)
        assert_ulp_close(se["f"], sf["f"], 4)
    assert np.asarray(se["f"]).tobytes() == np.asarray(sf["f"]).tobytes()
    re = eager.run(se, 3, dt)
    rf = fused.run(sf, 3, dt)
    assert_ulp_close(re["f"], rf["f"], 4)


def test_split_vlasov_forces_row_layout(monkeypatch):
    """overlap=True pins the general row layout even on a slab grid —
    the split form exists to overlap the gather-path halo seam."""
    monkeypatch.delenv("DCCRG_HALO_BACKEND", raising=False)
    g = make_grid(n_dev=8, length=(4, 4, 8), periodic=True,
                  geometry=True)
    assert Vlasov(g, nv=2).info is not None
    vl = Vlasov(g, nv=2, overlap=True)
    assert vl.info is None
    state = vl.initialize_state()
    m0 = vl.total_mass(state)
    state = vl.run(state, 4, 0.5 * vl.max_time_step())
    assert abs(vl.total_mass(state) - m0) < 1e-6


def test_gol_overlap_rides_pallas_backend(monkeypatch):
    monkeypatch.setenv("DCCRG_HALO_BACKEND", "pallas")
    g = make_grid()
    glider = [35, 36, 37, 27, 16]
    gol_b = GameOfLife(g)
    gol_o = GameOfLife(g, overlap=True)
    sb = gol_b.new_state(alive_cells=glider)
    so = gol_o.new_state(alive_cells=glider)
    for _ in range(6):
        sb = gol_b.step(sb)
        so = gol_o.step(so)
    assert set(gol_b.alive_cells(sb).tolist()) == set(
        gol_o.alive_cells(so).tolist()
    )


# --------------------------------------------------- zero-retrace churn


def test_zero_retrace_churn_split_and_dma(monkeypatch):
    """A structural commit landing on a seen shape signature must
    re-dispatch every ISSUE 7 kernel — the DMA halo bodies and the
    fused split-phase steps — with ZERO retraces (the shape-stable
    epoch contract of PR 5, extended to the new bodies)."""
    from dccrg_tpu.parallel.exec_cache import trace_counts

    monkeypatch.setenv("DCCRG_HALO_BACKEND", "pallas")
    # the check_telemetry churn probe's proven recipe: on the 8^3
    # refined-ball grid a one-cell commit stays inside every held
    # bucket (R, Kmax, ring sizes, split widths); a smaller grid can
    # legitimately outgrow a ring bucket and retrace
    g = make_grid(n_dev=8, length=(8, 8, 8), max_ref=1, hood_len=0,
                  refine_ball=0.3, periodic=True)

    def cycle(i):
        cells = g.get_cells()
        lvl = g.mapping.get_refinement_level(cells)
        cand = cells[lvl < 1]
        g.refine_completely(int(cand[(i * 13) % len(cand)]))
        g.stop_refining()
        adv = Advection(g, dtype=np.float32, allow_dense=False,
                        overlap=True)
        vl = Vlasov(g, nv=2, dtype=np.float32, overlap=True)
        sa = adv.initialize_state()
        sv = vl.initialize_state()
        sa = adv.step(sa, np.float32(0.25 * adv.max_time_step(sa)))
        sv = vl.step(sv, np.float32(0.25 * vl.max_time_step()))
        jax.block_until_ready((sa["density"], sv["f"]))

    cycle(0)
    sig = g.shape_signature()
    counts0 = dict(trace_counts())
    # the new bodies actually traced at least once in cycle 0
    for label in ("halo.dma.body", "advection.split_step",
                  "vlasov.split_step"):
        assert counts0.get(label, 0) >= 1, label
    cycle(1)
    assert g.shape_signature() == sig, (
        "one-cell commit flipped the shape signature — bucket "
        "hysteresis broke"
    )
    changed = {
        k: v - counts0.get(k, 0)
        for k, v in trace_counts().items() if v != counts0.get(k, 0)
    }
    assert not changed, f"second same-signature cycle retraced {changed}"
