"""Load balancing tests (reference analogues: tests/load_balancing,
pinned_cells, hierarchical_test)."""
import numpy as np
import pytest

from dccrg_tpu import CartesianGeometry, Grid, make_mesh
from dccrg_tpu.models import GameOfLife


def make_grid(method="RCB", length=(8, 8, 1), n_dev=None, hood=1):
    return (
        Grid()
        .set_initial_length(length)
        .set_neighborhood_length(hood)
        .set_load_balancing_method(method)
        .set_geometry(
            CartesianGeometry, start=(0.0, 0.0, 0.0), level_0_cell_length=(1.0, 1.0, 1.0)
        )
        .initialize(mesh=make_mesh(n_devices=n_dev))
    )


@pytest.mark.parametrize("method", ["RCB", "HSFC", "BLOCK", "GRAPH"])
def test_balance_produces_even_partition(method):
    g = make_grid(method)
    g.balance_load()
    counts = np.bincount(g.get_owner(g.get_cells()), minlength=8)
    assert counts.sum() == 64
    assert counts.max() - counts.min() <= 2


def test_rib_beats_rcb_on_oblique_distribution():
    """RIB is a real inertial bisection, not an RCB alias: on a weighted
    cloud elongated along the xy diagonal (largest *variance*) but with a
    wider z *extent*, RCB cuts z while RIB cuts the diagonal, giving
    measurably lower within-part weighted scatter (reference: Zoltan RIB
    as a distinct LB_METHOD, dccrg.hpp:7715-7733)."""
    from dccrg_tpu.parallel.loadbalance import rcb_partition, rib_partition

    rng = np.random.default_rng(7)
    n = 4000
    t = rng.uniform(-5, 5, n)
    centers = np.stack([
        t / np.sqrt(2) + rng.normal(0, 0.2, n),
        t / np.sqrt(2) + rng.normal(0, 0.2, n),
        rng.uniform(-4, 4, n),
    ], axis=1)
    w = rng.uniform(0.5, 2.0, n)

    def scatter(owner, k):
        s = 0.0
        for p in range(k):
            m = owner == p
            wp, c = w[m], centers[m]
            mu = (wp[:, None] * c).sum(0) / wp.sum()
            s += (wp[:, None] * (c - mu) ** 2).sum()
        return s

    for k in (2, 8):
        rcb = rcb_partition(centers, k, w)
        rib = rib_partition(centers, k, w)
        assert scatter(rib, k) < scatter(rcb, k)
        loads = np.bincount(rib, weights=w, minlength=k)
        assert loads.max() <= 1.05 * loads.sum() / k
        assert loads.min() > 0


def test_rib_balances_through_grid():
    """RIB routes through balance_load distinctly from RCB and balances
    cell counts on a uniform grid."""
    from dccrg_tpu.parallel.loadbalance import compute_partition

    g = make_grid("RIB", length=(8, 8, 8))
    g.balance_load()
    counts = np.bincount(g.get_owner(g.get_cells()), minlength=8)
    assert counts.sum() == 512
    assert counts.max() - counts.min() <= 2
    # with weights concentrated on an oblique band the two geometric
    # methods must produce different partitions (RIB is not an alias)
    c = g.geometry.get_center(g.get_cells())
    d = np.abs(c[:, 0] - c[:, 1]) / np.sqrt(2)
    wts = np.where(d < 1.0, 100.0, 1.0)
    assert not np.array_equal(
        compute_partition("RIB", g, 8, wts),
        compute_partition("RCB", g, 8, wts),
    )


def test_none_keeps_partition():
    g = make_grid("NONE")
    before = g.get_owner(g.get_cells())
    g.balance_load()
    np.testing.assert_array_equal(g.get_owner(g.get_cells()), before)


def test_weights_skew_partition():
    g = make_grid("BLOCK", length=(16, 1, 1))
    # make the first 4 cells very heavy: they should spread over devices
    for c in range(1, 5):
        g.set_cell_weight(c, 100.0)
    g.balance_load()
    owners = g.get_owner(np.arange(1, 5, dtype=np.uint64))
    assert len(set(owners.tolist())) >= 3


def test_pinning_overrides_partitioner():
    g = make_grid("RCB")
    assert g.pin(1, 7)
    assert g.pin(64, 0)
    g.balance_load()
    assert int(g.get_owner(np.uint64(1))) == 7
    assert int(g.get_owner(np.uint64(64))) == 0
    # unpin and rebalance: partitioner decides again
    g.unpin(1)
    g.unpin_all_cells()
    g.balance_load()


def test_balance_load_preserves_data():
    g = make_grid("RCB")
    state = g.new_state({"v": ((), np.float64)})
    cells = g.get_cells()
    vals = np.sin(cells.astype(np.float64))
    state = g.set_cell_data(state, "v", cells, vals)
    g.balance_load()
    state = g.remap_state(state)
    np.testing.assert_array_equal(g.get_cell_data(state, "v", cells), vals)


def test_gol_correct_after_balance():
    """The reference's pinned/RCB GoL tests: physics must be identical
    before and after repartitioning."""
    g1 = make_grid("BLOCK", length=(10, 10, 1))
    gol1 = GameOfLife(g1)
    s1 = gol1.new_state(alive_cells=[54, 55, 56, 12, 13, 22])
    s1 = gol1.run(s1, 5)
    final1 = set(gol1.alive_cells(s1).tolist())

    g2 = make_grid("RCB", length=(10, 10, 1))
    gol2 = GameOfLife(g2)
    s2 = gol2.new_state(alive_cells=[54, 55, 56, 12, 13, 22])
    s2 = gol2.run(s2, 2)
    g2.balance_load()
    s2 = g2.remap_state(s2)
    gol2 = GameOfLife(g2)  # tables rebind to the new epoch
    s2 = gol2.run(s2, 3)
    assert set(gol2.alive_cells(s2).tolist()) == final1


def test_hierarchical_partitioning():
    g = make_grid("RCB")
    g.add_partitioning_level(4)  # 2 groups of 4 devices
    g.balance_load()
    owners = g.get_owner(g.get_cells())
    counts = np.bincount(owners, minlength=8)
    assert counts.sum() == 64
    assert counts.max() - counts.min() <= 4
    # group structure: cells of devices 0-3 form one spatial half
    centers = g.geometry.get_center(g.get_cells())
    grp = owners // 4
    # the two groups should split space reasonably (not interleaved): check
    # that each group's bounding box is smaller than the full domain in at
    # least one dimension
    for gi in (0, 1):
        ext = centers[grp == gi].max(axis=0) - centers[grp == gi].min(axis=0)
        full = centers.max(axis=0) - centers.min(axis=0)
        assert (ext < full - 1e-9).any()


def _refined_cube(method, n=8, levels=1, n_dev=8):
    g = (
        Grid()
        .set_initial_length((n, n, n))
        .set_maximum_refinement_level(levels)
        .set_neighborhood_length(1)
        .set_load_balancing_method(method)
        .initialize(mesh=make_mesh(n_devices=n_dev))
    )
    # refine one corner region to make the adjacency irregular
    for c in range(1, n * n + 1):
        g.refine_completely(c)
    g.stop_refining()
    return g


def test_graph_beats_hilbert_edge_cut():
    """The honest GRAPH partitioner must measurably reduce the halo edge
    cut below its own HILBERT seed (reference Zoltan GRAPH via callbacks,
    dccrg.hpp:11807-12142)."""
    from dccrg_tpu.parallel.graph import edge_cut, grid_adjacency
    from dccrg_tpu.parallel.loadbalance import compute_partition

    g = _refined_cube("HILBERT")
    start, nbr = grid_adjacency(g)
    hil = compute_partition("HILBERT", g, 8, None)
    gra = compute_partition("GRAPH", g, 8, None)
    cut_h = edge_cut(hil, start, nbr)
    cut_g = edge_cut(gra, start, nbr)
    assert cut_g < cut_h
    # and the load cap held: max part weight <= 1.1 * average
    counts = np.bincount(gra, minlength=8)
    assert counts.max() <= 1.1 * counts.sum() / 8 + 1e-9
    assert counts.min() >= 1


def test_hypergraph_reduces_comm_volume():
    from dccrg_tpu.parallel.graph import comm_volume, grid_adjacency
    from dccrg_tpu.parallel.loadbalance import compute_partition

    g = _refined_cube("HILBERT")
    start, nbr = grid_adjacency(g)
    hil = compute_partition("HILBERT", g, 8, None)
    hyp = compute_partition("HYPERGRAPH", g, 8, None)
    assert comm_volume(hyp, start, nbr) < comm_volume(hil, start, nbr)


def test_graph_balance_load_end_to_end():
    """balance_load under GRAPH keeps physics identical and reduces the
    total ghost surface vs the HILBERT striping."""
    gh = _refined_cube("HILBERT")
    gh.balance_load()
    gg = _refined_cube("GRAPH")
    gg.balance_load()
    np.testing.assert_array_equal(gh.get_cells(), gg.get_cells())
    ghosts_h = sum(gh.get_ghost_cell_count(d) for d in range(8))
    ghosts_g = sum(gg.get_ghost_cell_count(d) for d in range(8))
    assert ghosts_g <= ghosts_h


def test_imbalance_tol_option_honored():
    """IMBALANCE_TOL measurably changes a partition: skewed weights under
    BLOCK violate the cap with plain proportional cuts; setting the option
    triggers the min-max-load repair (reference records these as Zoltan
    params, dccrg.hpp:5537-5564)."""
    from dccrg_tpu.parallel.loadbalance import compute_partition

    g = make_grid("BLOCK", length=(9, 1, 1), n_dev=3)
    w = np.array([4.0, 4, 4, 3, 3, 3, 3, 3, 3])
    plain = compute_partition("BLOCK", g, 3, w)
    repaired = compute_partition("BLOCK", g, 3, w, {"IMBALANCE_TOL": 1.05})
    assert not np.array_equal(plain, repaired)
    loads_plain = np.bincount(plain, weights=w, minlength=3)
    loads_rep = np.bincount(repaired, weights=w, minlength=3)
    # proportional midpoint cuts give a 13-weight part; the min-max repair
    # finds the optimal contiguous partition (max 12)
    assert loads_plain.max() == 13.0
    assert loads_rep.max() == 12.0
    # and the option is honored through grid.balance_load
    g.set_partitioning_option("IMBALANCE_TOL", 1.05)
    for c, wc in enumerate(w, start=1):
        g.set_cell_weight(c, float(wc))
    g.balance_load()
    owners = g.get_owner(g.get_cells())
    assert np.bincount(owners, weights=w, minlength=3).max() == 12.0


def test_imbalance_repair_never_worse_and_nonempty():
    """The min-max repair is only kept when it strictly lowers the max
    load, and the nonempty variant never leaves an idle part when there
    are at least as many cells as parts (fuzzed)."""
    from dccrg_tpu.parallel.partition import weighted_blocks

    rng = np.random.default_rng(0)
    for _ in range(300):
        n = int(rng.integers(6, 40))
        n_parts = int(rng.integers(2, 9))
        w = rng.integers(1, 10, n).astype(float)
        order = np.arange(n)
        plain = weighted_blocks(order, w, n_parts)
        rep = weighted_blocks(order, w, n_parts, 1.0)
        max_plain = np.bincount(plain, weights=w, minlength=n_parts).max()
        max_rep = np.bincount(rep, weights=w, minlength=n_parts).max()
        assert max_rep <= max_plain
        ne = weighted_blocks(order, w, n_parts, 1.0, nonempty=True)
        if n >= n_parts:
            assert (np.bincount(ne, minlength=n_parts) > 0).all()


def test_graph_seed_carries_imbalance_tol():
    """On a line grid no boundary move improves the cut, so GRAPH returns
    its seed — the seed itself must already respect IMBALANCE_TOL."""
    from dccrg_tpu.parallel.loadbalance import compute_partition

    g = make_grid("GRAPH", length=(9, 1, 1), n_dev=3)
    w = np.array([4.0, 4, 4, 3, 3, 3, 3, 3, 3])
    part = compute_partition("GRAPH", g, 3, w, {"IMBALANCE_TOL": 1.05})
    assert np.bincount(part, weights=w, minlength=3).max() == 12.0


def test_multilevel_hierarchical_partitioning():
    """Three-level HIER (2 groups of 4, pairs of 2, single devices):
    cell counts must balance at every level of the hierarchy."""
    g = _refined_cube("RCB")
    g.add_partitioning_level(4)
    g.add_partitioning_level(2)
    g.balance_load()
    owners = g.get_owner(g.get_cells())
    n = len(owners)
    for level_size, n_groups in ((4, 2), (2, 4), (1, 8)):
        counts = np.bincount(owners // level_size, minlength=n_groups)
        assert counts.sum() == n
        # every group at every level holds its proportional share +-25%
        share = n / n_groups
        assert counts.max() <= 1.25 * share
        assert counts.min() >= 0.75 * share


def test_hierarchical_nondivisible_devices():
    """A partitioning level that does not divide the device count forms a
    remainder group — no device may be left idle."""
    g = make_grid("RCB", length=(8, 8, 8), n_dev=6)
    g.add_partitioning_level(4)  # groups of 4 + remainder group of 2
    g.balance_load()
    counts = np.bincount(g.get_owner(g.get_cells()), minlength=6)
    assert counts.sum() == 512
    assert counts.min() > 0
    share = 512 / 6
    assert counts.max() <= 1.25 * share and counts.min() >= 0.75 * share


def test_graph_refines_tiny_parts():
    """With fewer than 1/(tol-1) cells per part the load cap is tighter
    than the seed's own max load; refinement must still be able to trade
    equal-load moves for cut improvements."""
    from dccrg_tpu.parallel.graph import edge_cut, grid_adjacency
    from dccrg_tpu.parallel.loadbalance import compute_partition

    g = make_grid("GRAPH", length=(5, 4, 1), n_dev=8)
    start, nbr = grid_adjacency(g)
    hil = compute_partition("HILBERT", g, 8, None)
    gra = compute_partition("GRAPH", g, 8, None)
    assert edge_cut(gra, start, nbr) < edge_cut(hil, start, nbr)
    counts = np.bincount(gra, minlength=8)
    assert counts.min() >= 1
    # balance no worse than the seed's own spread
    assert counts.max() <= np.bincount(hil, minlength=8).max()


def test_balance_after_refinement_with_weights():
    g = (
        Grid()
        .set_initial_length((4, 4, 1))
        .set_maximum_refinement_level(1)
        .set_neighborhood_length(1)
        .set_load_balancing_method("HSFC")
        .initialize(mesh=make_mesh())
    )
    g.refine_completely(1)
    g.refine_completely(16)
    g.stop_refining()
    g.balance_load()
    counts = np.bincount(g.get_owner(g.get_cells()), minlength=8)
    assert counts.sum() == len(g.get_cells())
    assert counts.max() - counts.min() <= 2


def test_hilbert_curve_properties():
    """The Hilbert key is a bijection onto 0..n^3-1 whose consecutive
    cells are face-adjacent — the locality property Morton lacks (and why
    the reference links sfc++, dccrg.hpp:56-58)."""
    from dccrg_tpu.parallel.partition import _hilbert_key

    for nbits in (1, 2, 3):
        n = 1 << nbits
        g = np.stack(
            np.meshgrid(*[np.arange(n)] * 3, indexing="ij"), -1
        ).reshape(-1, 3)
        key = _hilbert_key(g, nbits)
        assert len(np.unique(key)) == len(key)
        assert int(key.max()) == len(key) - 1
        path = g[np.argsort(key)]
        steps = np.abs(np.diff(path.astype(int), axis=0)).sum(axis=1)
        assert (steps == 1).all()


def test_hilbert_partition_balanced_and_smaller_surface():
    """HILBERT striping balances counts and its ghost surface is no worse
    than MORTON's on a uniform cube."""
    from dccrg_tpu.utils.verify import verify_grid

    def build(method):
        return (
            Grid()
            .set_initial_length((8, 8, 8))
            .set_neighborhood_length(1)
            .set_load_balancing_method(method)
            .initialize(mesh=make_mesh(n_devices=8))
        )

    gh = build("HILBERT")
    counts = [gh.get_local_cell_count(d) for d in range(8)]
    assert max(counts) - min(counts) <= 1
    gm = build("MORTON")
    ghosts_h = sum(gh.get_ghost_cell_count(d) for d in range(8))
    ghosts_m = sum(gm.get_ghost_cell_count(d) for d in range(8))
    assert ghosts_h <= ghosts_m
    # same leaf set either way, and rebalancing under HSFC keeps it
    np.testing.assert_array_equal(gh.get_cells(), gm.get_cells())
    gh.refine_completely(1)
    gh.stop_refining()
    gh.balance_load()
    verify_grid(gh)


def test_three_phase_balance_load_chunked():
    """The real split balance_load: initialize stages the new partition
    without touching the live grid, continue migrates payload chunks
    (repeatable), finish commits and returns the migrated state —
    equivalent to the one-shot balance_load + remap_state."""
    from dccrg_tpu import CartesianGeometry

    def build():
        g = (
            Grid()
            .set_initial_length((8, 8, 8))
            .set_neighborhood_length(1)
            .set_load_balancing_method("GRAPH")
            .set_geometry(
                CartesianGeometry,
                start=(0.0, 0.0, 0.0),
                level_0_cell_length=(1.0 / 8,) * 3,
            )
            .initialize(mesh=make_mesh(n_devices=4))
        )
        state = g.new_state({"rho": ((), np.float64)})
        cells = g.get_cells()
        state = g.set_cell_data(
            state, "rho", cells, np.sin(cells.astype(np.float64))
        )
        return g, state, cells

    # reference result: one-shot
    g1, s1, cells = build()
    g1.balance_load()
    s1 = g1.remap_state(s1)
    want_owner = g1.leaves.owner.copy()
    want = g1.get_cell_data(s1, "rho", cells)

    # three-phase with small chunks
    g2, s2, _ = build()
    old_owner = g2.leaves.owner.copy()
    g2.initialize_balance_load()
    # live grid untouched while staged
    np.testing.assert_array_equal(g2.leaves.owner, old_owner)
    n_chunks = 0
    while g2.continue_balance_load(s2, max_cells=100):
        n_chunks += 1
    assert n_chunks >= 5  # 512 cells / 100 per chunk
    out = g2.finish_balance_load()
    assert isinstance(out, dict)
    np.testing.assert_array_equal(g2.leaves.owner, want_owner)
    np.testing.assert_array_equal(g2.get_cell_data(out, "rho", cells), want)

    # remap_state still works for payloads not carried through the phases
    s2b = g2.remap_state(s2)
    np.testing.assert_array_equal(g2.get_cell_data(s2b, "rho", cells), want)


def test_three_phase_finish_drains_remaining():
    """finish_balance_load drains unmigrated chunks from the passed
    state; a partial migration with no state to finish from is an
    error (the staged copy would silently be incomplete)."""
    from dccrg_tpu import CartesianGeometry

    g = (
        Grid()
        .set_initial_length((6, 6, 6))
        .set_neighborhood_length(1)
        .set_load_balancing_method("GRAPH")
        .set_geometry(
            CartesianGeometry,
            start=(0.0, 0.0, 0.0),
            level_0_cell_length=(1.0 / 6,) * 3,
        )
        .initialize(mesh=make_mesh(n_devices=4))
    )
    state = g.new_state({"rho": ((), np.float64)})
    cells = g.get_cells()
    vals = np.cos(cells.astype(np.float64))
    state = g.set_cell_data(state, "rho", cells, vals)
    g.initialize_balance_load()
    g.continue_balance_load(state, max_cells=10)   # one partial chunk
    with pytest.raises(RuntimeError, match="partial"):
        g.finish_balance_load()
    out = g.finish_balance_load(state)
    np.testing.assert_array_equal(g.get_cell_data(out, "rho", cells), vals)

    # guards: structural mutators are refused while a balance is staged
    g.initialize_balance_load()
    with pytest.raises(RuntimeError, match="in progress"):
        g.balance_load()
    with pytest.raises(RuntimeError, match="in progress"):
        g.stop_refining()
    g.finish_balance_load()


# ---------------------------------------------------- per-level options


def _record_partitions(monkeypatch):
    """Wrap compute_partition to record (method, n_parts, options)."""
    from dccrg_tpu.parallel import loadbalance

    calls = []
    orig = loadbalance.compute_partition

    def recording(method, grid, n_parts, weights, options=None, adjacency=None):
        calls.append((method.upper(), n_parts,
                      {str(k).upper(): v for k, v in (options or {}).items()}))
        return orig(method, grid, n_parts, weights, options, adjacency)

    monkeypatch.setattr(loadbalance, "compute_partition", recording)
    return calls


def test_per_level_methods_and_options(monkeypatch):
    """Reference parity (dccrg.hpp:5650-5706): each hierarchy level runs
    under its own method and options — DCN level GRAPH with tol 1.05,
    ICI level HILBERT with tol 1.2."""
    g = make_grid("RCB", length=(8, 8, 8))
    g.add_partitioning_level(4)   # level 0: 2 groups of 4 (DCN)
    g.add_partitioning_level(1)   # level 1: single devices (ICI)
    g.add_partitioning_option(0, "LB_METHOD", "GRAPH")
    g.add_partitioning_option(0, "IMBALANCE_TOL", 1.05)
    g.add_partitioning_option(1, "LB_METHOD", "HILBERT")
    g.add_partitioning_option(1, "IMBALANCE_TOL", 1.2)

    calls = _record_partitions(monkeypatch)
    g.balance_load()

    # level 0 splits all 8 devices under GRAPH/1.05; level 1 splits each
    # 4-device group under HILBERT/1.2
    assert [(m, n) for m, n, _ in calls] == [
        ("GRAPH", 8), ("HILBERT", 4), ("HILBERT", 4)
    ]
    assert calls[0][2]["IMBALANCE_TOL"] == 1.05
    assert all(c[2]["IMBALANCE_TOL"] == 1.2 for c in calls[1:])

    counts = np.bincount(g.get_owner(g.get_cells()), minlength=8)
    assert counts.sum() == 512
    assert counts.min() > 0
    assert counts.max() <= 1.2 * 512 / 8


def test_partitioning_level_defaults(monkeypatch):
    """A fresh level carries the reference's default options
    (LB_METHOD=HYPERGRAPH, PHG_CUT_OBJECTIVE=CONNECTIVITY,
    dccrg.hpp:5600-5605) — the group split runs HYPERGRAPH even when the
    grid's global method is RCB."""
    g = make_grid("RCB", length=(8, 8, 1))
    g.add_partitioning_level(4)
    assert g.get_partitioning_options(0) == {
        "LB_METHOD": "HYPERGRAPH",
        "PHG_CUT_OBJECTIVE": "CONNECTIVITY",
    }
    calls = _record_partitions(monkeypatch)
    g.balance_load()
    assert calls[0][0] == "HYPERGRAPH"
    # fall-through within each group uses the grid's global method
    assert {c[0] for c in calls[1:]} == {"RCB"}


def test_partitioning_level_and_option_removal():
    """remove_partitioning_level/option edit the hierarchy in place;
    out-of-range indices are no-ops (dccrg.hpp:5610-5744)."""
    g = make_grid("RCB")
    g.add_partitioning_level(4)
    g.add_partitioning_level(2)
    g.add_partitioning_option(1, "IMBALANCE_TOL", 1.3)
    assert g.get_partitioning_options(1)["IMBALANCE_TOL"] == 1.3

    g.remove_partitioning_option(1, "PHG_CUT_OBJECTIVE")
    assert "PHG_CUT_OBJECTIVE" not in g.get_partitioning_options(1)
    g.remove_partitioning_option(1, "NOT_THERE")       # no-op
    g.remove_partitioning_option(7, "IMBALANCE_TOL")   # no-op

    g.remove_partitioning_level(0)
    # former level 1 shifted down, its options intact
    assert g._hier_levels == [2]
    assert g.get_partitioning_options(0)["IMBALANCE_TOL"] == 1.3
    g.remove_partitioning_level(5)                     # no-op
    assert g._hier_levels == [2]

    with pytest.raises(ValueError, match="at least 1"):
        g.add_partitioning_level(0)
    g.add_partitioning_option(9, "IMBALANCE_TOL", 1.1)  # no-op, no raise
    assert g.get_partitioning_options(9) == {}


def test_reserved_options_raise():
    """Zoltan parameters the reference reserves for dccrg itself raise
    from both option APIs (dccrg.hpp:7716-7723)."""
    g = make_grid("RCB")
    g.add_partitioning_level(4)
    with pytest.raises(ValueError, match="reserved"):
        g.set_partitioning_option("RETURN_LISTS", "ALL")
    with pytest.raises(ValueError, match="reserved"):
        g.add_partitioning_option(0, "AUTO_MIGRATE", "1")


def test_unknown_option_warns():
    """Unrecognized option names warn when set (global or per-level);
    documented-inert Zoltan knobs do not."""
    import warnings as _w

    g = make_grid("RCB")
    g.add_partitioning_level(4)
    with pytest.warns(UserWarning, match="SOME_BOGUS_KNOB"):
        g.set_partitioning_option("SOME_BOGUS_KNOB", "7")
    with pytest.warns(UserWarning, match="OTHER_BOGUS_KNOB"):
        g.add_partitioning_option(0, "OTHER_BOGUS_KNOB", "x")
    with _w.catch_warnings():
        _w.simplefilter("error")
        g.set_partitioning_option("RCB_RECTILINEAR_BLOCKS", "1")  # inert
        g.balance_load()


def test_global_lb_method_override_on_fallthrough(monkeypatch):
    """A global LB_METHOD=GRAPH option must also steer the hierarchy's
    exhausted-levels fall-through (the adjacency pre-build gate resolves
    the override, so graph_partition gets a real adjacency)."""
    g = make_grid("RCB", length=(8, 8, 8))
    g.set_partitioning_option("LB_METHOD", "GRAPH")
    g.add_partitioning_level(4)
    g.add_partitioning_option(0, "LB_METHOD", "HILBERT")
    calls = _record_partitions(monkeypatch)
    g.balance_load()
    assert [(m, n) for m, n, _ in calls] == [("HILBERT", 8), ("GRAPH", 4),
                                             ("GRAPH", 4)]
    counts = np.bincount(g.get_owner(g.get_cells()), minlength=8)
    assert counts.sum() == 512 and counts.min() > 0
