"""Kernel fallback-policy unit tests (``utils/fallback.py``).

The policy: a compile/lowering rejection permanently disables the fast
path; a transient runtime fault falls back for the call only, with a
consecutive-fall cap so a deterministic-but-unrecognized failure cannot
pay a failed fast-path attempt on every step forever.
"""
import pytest

from dccrg_tpu.utils.fallback import _MAX_TRANSIENT_FALLS, fallback_call


class Kernel:
    def __init__(self):
        self.disabled = False

    def disable(self):
        self.disabled = True


def test_permanent_marker_disables_on_second_consecutive_hit():
    """A substring marker can coincidentally appear in a transient
    error's text, so a marker-classified error must recur on the next
    call before the fast path is disabled for good (ADVICE r4)."""
    k = Kernel()

    def fast():
        raise RuntimeError("Mosaic failed to compile: unsupported op")

    assert fallback_call("k", fast, lambda: 1, k.disable) == 1
    assert not k.disabled  # first hit: could be a transient coincidence
    assert fallback_call("k", fast, lambda: 1, k.disable) == 1
    assert k.disabled      # it recurred: deterministic rejection


def test_single_marker_hit_then_success_keeps_the_fast_path():
    k = Kernel()
    state = {"fail": True}

    def fast():
        if state["fail"]:
            raise RuntimeError("RPC cancelled while lowering in flight")
        return 42

    assert fallback_call("k", fast, lambda: 1, k.disable) == 1
    state["fail"] = False
    assert fallback_call("k", fast, lambda: 1, k.disable) == 42
    state["fail"] = True
    assert fallback_call("k", fast, lambda: 1, k.disable) == 1
    assert not k.disabled  # hits were not consecutive: no disable


def test_not_implemented_disables_immediately():
    k = Kernel()

    def fast():
        raise NotImplementedError("no lowering rule")

    assert fallback_call("k", fast, lambda: 1, k.disable) == 1
    assert k.disabled


def test_transient_fault_does_not_disable():
    k = Kernel()
    attempts = []

    def fast():
        attempts.append(1)
        raise RuntimeError("RESOURCE_EXHAUSTED: out of memory")

    assert fallback_call("k", fast, lambda: 1, k.disable) == 1
    assert not k.disabled  # one-off fault: the kernel gets another chance


def test_consecutive_transient_falls_hit_the_cap():
    k = Kernel()
    attempts = []

    def fast():
        attempts.append(1)
        raise RuntimeError("RESOURCE_EXHAUSTED: out of memory")

    for _ in range(_MAX_TRANSIENT_FALLS + 2):
        if k.disabled:
            break
        assert fallback_call("k", fast, lambda: 1, k.disable) == 1
    assert k.disabled
    assert len(attempts) == _MAX_TRANSIENT_FALLS


def test_fast_success_resets_the_fall_count():
    k = Kernel()
    state = {"fail": True}

    def fast():
        if state["fail"]:
            raise RuntimeError("transient blip")
        return 42

    # fail (cap-1) times, succeed, then fail (cap-1) times again: the
    # reset means the cap is never reached
    for _ in range(_MAX_TRANSIENT_FALLS - 1):
        assert fallback_call("k", fast, lambda: 1, k.disable) == 1
    state["fail"] = False
    assert fallback_call("k", fast, lambda: 1, k.disable) == 42
    state["fail"] = True
    for _ in range(_MAX_TRANSIENT_FALLS - 1):
        assert fallback_call("k", fast, lambda: 1, k.disable) == 1
    assert not k.disabled


def test_both_paths_failing_propagates_the_fast_error():
    k = Kernel()

    def fast():
        raise RuntimeError("Mosaic rejects this")

    def slow():
        raise ValueError("bad caller input")

    with pytest.raises(RuntimeError, match="Mosaic"):
        fallback_call("k", fast, slow, k.disable)
    assert not k.disabled  # the input was bad, not the kernel
