"""Rolled static-offset matvec (ops/rolled_gather.py) and its Poisson
integration.

The general gather-path operator has static structure, so it decomposes
into dense roll terms + a small exception COO (the flat voxel path's
roll trick generalized to any static sparsity).  The decomposition must
be exactly the same operator: these tests compare it entry-for-entry
against brute force and against the gather-table ``_apply`` oracle on
refined grids (mirroring the reference's solver-vs-direct checks,
``tests/poisson/poisson1d.cpp`` style).
"""
import jax
import numpy as np
import pytest

from dccrg_tpu import CartesianGeometry, Grid, make_mesh
from dccrg_tpu.models import Poisson
from dccrg_tpu.ops.rolled_gather import build_rolled_matvec, make_rolled_apply

jax.config.update("jax_enable_x64", True)


def _brute(nbr, mult, scaling, x):
    return scaling * x + (mult * x[nbr]).sum(-1)


@pytest.mark.parametrize("seed", range(6))
def test_matvec_matches_brute_force(seed):
    rng = np.random.default_rng(seed)
    R = int(rng.integers(8, 400))
    K = int(rng.integers(1, 9))
    nbr = rng.integers(0, R, (R, K))
    mult = rng.standard_normal((R, K))
    mult[rng.random((R, K)) < 0.4] = 0.0
    # concentrate most entries on a short offset head (the leaf-order
    # structure the decomposition exploits), keep a random tail
    for k in range(K):
        o = int(rng.integers(-4, 5))
        rows = np.arange(R)
        tgt = rows + o
        ok = (rng.random(R) < 0.8) & (tgt >= 0) & (tgt < R)
        nbr[ok, k] = tgt[ok]
    scaling = rng.standard_normal(R)
    x = rng.standard_normal(R)
    ref = _brute(nbr, mult, scaling, x)

    t = build_rolled_matvec(nbr, mult, scaling, max_exc_frac=1.0)
    assert t is not None
    y = np.asarray(make_rolled_apply(t, np.float64)(x))
    assert np.abs(y - ref).max() < 1e-13 * max(1.0, np.abs(ref).max())

    # exception-heavy split of the same operator is still the operator
    t2 = build_rolled_matvec(nbr, mult, scaling, max_terms=2,
                             max_exc_frac=1.0)
    y2 = np.asarray(make_rolled_apply(t2, np.float64)(x))
    assert np.abs(y2 - ref).max() < 1e-13 * max(1.0, np.abs(ref).max())


def test_build_refusals_and_degenerate():
    rng = np.random.default_rng(7)
    R, K = 256, 6
    scaling = rng.standard_normal(R)
    # scattered indices, tight exception budget: refuse
    nbr = rng.integers(0, R, (R, K))
    assert build_rolled_matvec(nbr, np.ones((R, K)), scaling,
                               max_exc_frac=0.01) is None
    # pure-diagonal system: zero terms, zero exceptions
    t = build_rolled_matvec(nbr, np.zeros((R, K)), scaling)
    x = rng.standard_normal(R)
    assert np.allclose(np.asarray(make_rolled_apply(t, np.float64)(x)),
                       scaling * x)
    assert t["offsets"] == [] and t["exc_r"].size == 0


def _refined_grid(n=8, n_devices=1, maxref=1, periodic=(True, True, True)):
    g = (Grid().set_initial_length((n, n, n)).set_neighborhood_length(0)
         .set_periodic(*periodic).set_maximum_refinement_level(maxref)
         .set_geometry(CartesianGeometry, start=(0.0, 0.0, 0.0),
                       level_0_cell_length=(1.0 / n,) * 3)
         .initialize(mesh=make_mesh(n_devices=n_devices)))
    ids = g.get_cells()
    c = g.geometry.get_center(ids)
    r = np.linalg.norm(c - 0.5, axis=1)
    for cid in ids[r < 0.3]:
        g.refine_completely(int(cid))
    g.stop_refining()
    return g


@pytest.mark.parametrize("periodic", [(True, True, True),
                                      (False, True, False)])
def test_rolled_matches_gather_operator_on_grid(periodic):
    g = _refined_grid(periodic=periodic)
    ids = g.get_cells()
    pr = Poisson(g, allow_flat=False, allow_rolled=True)
    pg = Poisson(g, allow_flat=False, allow_rolled=False)
    assert pr._rolled is not None and pg._rolled is None

    rng = np.random.default_rng(3)
    mf, mr = pg._mult_tables()
    for _ in range(3):
        v = rng.standard_normal(len(ids))
        s = g.new_state(pg.spec)
        x = g.set_cell_data(s, "solution", ids, v)["solution"]
        for mult, rolled in ((mf, pr._rolled[0]), (mr, pr._rolled[1])):
            a_g = np.asarray(pg._apply(x, mult)[0])
            a_r = np.asarray(rolled(x))
            assert np.abs(a_g - a_r).max() < 1e-12 * max(
                1.0, np.abs(a_g).max())


def test_rolled_solver_tracks_gather_solver():
    g = _refined_grid()
    ids = g.get_cells()
    c = g.geometry.get_center(ids)
    rhs = np.sin(2 * np.pi * c[:, 0]) * np.cos(2 * np.pi * c[:, 1])
    rhs -= rhs.mean()
    pr = Poisson(g, allow_flat=False, allow_rolled=True)
    pg = Poisson(g, allow_flat=False, allow_rolled=False)
    st = pr.initialize_state(rhs)
    sol_r, res_r, it_r = pr.solve(st, max_iterations=100,
                                  stop_residual=1e-8)
    sol_g, res_g, it_g = pg.solve(st, max_iterations=100,
                                  stop_residual=1e-8)
    # the operators differ in fp association, so a residual landing
    # within an ulp of a stopping rule can split the trajectories by
    # one iteration (same ±1 convention as the flat-vs-gather tests)
    assert abs(int(it_r) - int(it_g)) <= 1
    # both solutions judged under the SAME independent gather residual
    rr = float(pg.residual(sol_r))
    rg = float(pg.residual(sol_g))
    assert rr <= 10.0 * rg + 1e-9 and rg <= 10.0 * rr + 1e-9
    if int(it_r) == int(it_g):
        assert float(res_r) == pytest.approx(float(res_g), rel=1e-8)
        d = np.abs(np.asarray(sol_r["solution"])
                   - np.asarray(sol_g["solution"])).max()
        assert d < 1e-8
    # the independent residual() diagnostic still runs the raw gather
    assert float(pr.residual(sol_r)) == pytest.approx(float(res_r),
                                                      rel=1e-6)


def test_rolled_respects_cell_roles():
    g = _refined_grid()
    ids = g.get_cells()
    rng = np.random.default_rng(11)
    skip = rng.choice(ids, size=len(ids) // 8, replace=False)
    pr = Poisson(g, allow_flat=False, allow_rolled=True, skip_cells=skip)
    pg = Poisson(g, allow_flat=False, allow_rolled=False, skip_cells=skip)
    assert pr._rolled is not None
    rhs = rng.standard_normal(len(ids))
    st = pr.initialize_state(rhs)
    sol_r, res_r, it_r = pr.solve(st, max_iterations=50,
                                  stop_residual=1e-8)
    sol_g, res_g, it_g = pg.solve(st, max_iterations=50,
                                  stop_residual=1e-8)
    assert abs(int(it_r) - int(it_g)) <= 1  # fp-association tolerance
    rr = float(pg.residual(sol_r))
    rg = float(pg.residual(sol_g))
    assert rr <= 10.0 * rg + 1e-9 and rg <= 10.0 * rr + 1e-9


@pytest.mark.parametrize("n_devices", [2, 4])
def test_rolled_matches_gather_on_multi_device(n_devices):
    """Sharded meshes: per-device roll spaces with a union offset set
    must still be the gather operator entry-for-entry (ghosts refreshed
    by the same halo exchange on both paths)."""
    g = _refined_grid(n_devices=n_devices)
    ids = g.get_cells()
    pr = Poisson(g, allow_flat=False, allow_rolled=True)
    pg = Poisson(g, allow_flat=False, allow_rolled=False)
    assert pr._rolled is not None

    rng = np.random.default_rng(5)
    mf, mr = pg._mult_tables()
    for _ in range(2):
        v = rng.standard_normal(len(ids))
        s = g.new_state(pg.spec)
        x = g.set_cell_data(s, "solution", ids, v)["solution"]
        for mult, rolled in ((mf, pr._rolled[0]), (mr, pr._rolled[1])):
            a_g = np.asarray(pg._apply(x, mult)[0])
            a_r = np.asarray(rolled(x))
            # compare on real rows only: scratch/pad rows are outside
            # the operator's contract
            mask = np.asarray(pg.tables.local_mask)
            da = np.abs(np.where(mask, a_g - a_r, 0.0)).max()
            assert da < 1e-12 * max(1.0, np.abs(a_g).max())

    # and the solver end-to-end
    c = g.geometry.get_center(ids)
    rhs = np.sin(2 * np.pi * c[:, 0]) * np.cos(2 * np.pi * c[:, 1])
    rhs -= rhs.mean()
    st = pr.initialize_state(rhs)
    sol_r, res_r, it_r = pr.solve(st, max_iterations=60,
                                  stop_residual=1e-8)
    sol_g, res_g, it_g = pg.solve(st, max_iterations=60,
                                  stop_residual=1e-8)
    assert abs(int(it_r) - int(it_g)) <= 1
    rr = float(pg.residual(sol_r))
    rg = float(pg.residual(sol_g))
    assert rr <= 10.0 * rg + 1e-9 and rg <= 10.0 * rr + 1e-9


def test_rolled_engages_on_stretched_geometry():
    """The real beneficiary: the flat voxel layout always refuses
    stretched geometry, so before the rolled operator these grids paid
    the raw gather (reference supports Poisson on any geometry via the
    same factor cache, poisson_solve.hpp:716-745)."""
    from dccrg_tpu.geometry.stretched import StretchedCartesianGeometry

    n = 10
    coords = [np.cumsum(np.concatenate([[0.0],
                                        np.linspace(0.5, 1.5, n)]))
              for _ in range(3)]
    g = (Grid().set_initial_length((n, n, n)).set_neighborhood_length(0)
         .set_periodic(False, False, False).set_maximum_refinement_level(1)
         .set_geometry(StretchedCartesianGeometry, coordinates=coords)
         .initialize(mesh=make_mesh(n_devices=1)))
    ids = g.get_cells()
    for cid in ids[:40]:
        g.refine_completely(int(cid))
    g.stop_refining()
    ids = g.get_cells()

    pr = Poisson(g, allow_rolled=True)
    pg = Poisson(g, allow_rolled=False)
    assert pr._flat is None and pr._rolled is not None

    rng = np.random.default_rng(0)
    mf, mr = pg._mult_tables()
    v = rng.standard_normal(len(ids))
    x = g.set_cell_data(g.new_state(pg.spec), "solution", ids,
                        v)["solution"]
    for mult, rolled in ((mf, pr._rolled[0]), (mr, pr._rolled[1])):
        a_g = np.asarray(pg._apply(x, mult)[0])
        a_r = np.asarray(rolled(x))
        assert np.abs(a_g - a_r).max() < 1e-12 * max(1.0,
                                                     np.abs(a_g).max())
