"""Particle workload tests (reference tests/particles: constant-vx drift,
cell-to-cell handoff, migration across device boundaries, variable-size
payload exchange)."""
import numpy as np
import pytest

from dccrg_tpu import CartesianGeometry, Grid, make_mesh
from dccrg_tpu.models.particles import Particles


def make_grid(length=(8, 8, 1), periodic=(True, True, False), max_ref=0, n_dev=None):
    n = np.asarray(length)
    return (
        Grid()
        .set_initial_length(length)
        .set_maximum_refinement_level(max_ref)
        .set_neighborhood_length(1)
        .set_periodic(*periodic)
        .set_geometry(
            CartesianGeometry,
            start=(0.0, 0.0, 0.0),
            level_0_cell_length=tuple(1.0 / n),
        )
        .initialize(mesh=make_mesh(n_devices=n_dev))
    )


def test_bucketing():
    g = make_grid()
    p = Particles(g)
    pts = np.array([[0.05, 0.05, 0.5], [0.55, 0.55, 0.5], [0.95, 0.05, 0.5]])
    state = p.new_state(pts)
    assert p.count(state) == 3
    # each particle sits in its containing cell
    for pt in pts:
        cell = int(g.get_existing_cell(pt[None])[0])
        got = p.particles_of(state, cell)
        assert any(np.allclose(row, pt) for row in got)


def test_drift_and_handoff():
    g = make_grid()
    p = Particles(g)
    state = p.new_state(np.array([[0.05, 0.5, 0.5]]))
    # drift along +x across the whole domain; count conserved, position
    # advances, wraps periodically
    for i in range(20):
        state = p.step(state, velocity=(0.1, 0.0, 0.0), dt=1.0)
        assert p.count(state) == 1
    pos = p.positions(state)[0]
    assert pos[0] == pytest.approx((0.05 + 2.0) % 1.0, abs=1e-12)
    cell = int(g.get_existing_cell(pos[None])[0])
    assert len(p.particles_of(state, cell)) == 1


def test_migration_across_devices():
    g = make_grid(n_dev=8)
    p = Particles(g)
    rng = np.random.default_rng(4)
    pts = np.column_stack([
        rng.random(50), rng.random(50), np.full(50, 0.5)
    ])
    state = p.new_state(pts)
    owners0 = set()
    for _ in range(10):
        state = p.step(state, velocity=(0.07, 0.013, 0.0), dt=1.0)
        assert p.count(state) == 50
    # particles ended up distributed over several devices' cells
    final = p.positions(state)
    cells = g.get_existing_cell(final)
    assert len(set(g.get_owner(cells).tolist())) > 1
    np.testing.assert_allclose(
        np.sort(final[:, 0]),
        np.sort((pts[:, 0] + 0.7) % 1.0),
        atol=1e-12,
    )


def test_remap_after_balance_and_refine():
    g = make_grid(length=(4, 4, 1), max_ref=1)
    p = Particles(g)
    pts = np.array([[0.1, 0.1, 0.5], [0.6, 0.6, 0.5], [0.9, 0.9, 0.5]])
    state = p.new_state(pts)

    g.refine_completely(1)
    g.stop_refining()
    state = p.remap(state)
    assert p.count(state) == 3
    # the particle at (0.1, 0.1) now lives in a refined child
    c = int(g.get_existing_cell(np.array([[0.1, 0.1, 0.5]]))[0])
    assert g.get_refinement_level(c) == 1
    assert len(p.particles_of(state, c)) == 1

    g.balance_load()
    state = p.remap(state)
    assert p.count(state) == 3
    np.testing.assert_allclose(
        np.sort(p.positions(state), axis=0), np.sort(pts, axis=0)
    )


def test_capacity_guard():
    g = make_grid(length=(2, 2, 1))
    p = Particles(g, max_particles_per_cell=4)
    pts = np.tile(np.array([[0.1, 0.1, 0.5]]), (5, 1))
    with pytest.raises(ValueError, match="capacity"):
        p.new_state(pts)


def test_nonperiodic_escape_drops_on_device_path():
    """A particle crossing a non-periodic boundary is removed, as the
    reference's handoff does when get_existing_cell finds no cell
    (tests/particles/simple.cpp:74-92); the device path counts the drop
    in the state's overflow scalar."""
    g = make_grid(periodic=(False, False, False))
    p = Particles(g)
    assert p._dev_rebucket is not None
    state = p.new_state(np.array([[0.95, 0.5, 0.5]]))
    for _ in range(3):
        state = p.step(state, velocity=(0.1, 0.0, 0.0), dt=1.0)
    assert p.count(state) == 0
    assert int(state["overflow"]) == 1


def test_nonperiodic_escape_raises_on_host_path():
    """The host path keeps its stricter contract: an escape through a
    non-periodic boundary raises instead of silently dropping."""
    g = make_grid(periodic=(False, False, False))
    p = Particles(g)
    p._dev_rebucket = None  # force host orchestration
    state = p.new_state(np.array([[0.95, 0.5, 0.5]]))
    with pytest.raises(ValueError, match="non-periodic"):
        for _ in range(3):
            state = p.step(state, velocity=(0.1, 0.0, 0.0), dt=1.0)


def test_per_cell_velocity_field():
    """velocity_field builds a [D, R, 3] per-cell field (the reference's
    per-cell velocity data, tests/particles/simple.cpp:52-97); particles
    in different cells move with their own cell's velocity."""
    g = make_grid(n_dev=8)
    p = Particles(g)
    # +x drift in the left half of the domain, +y drift in the right half
    vel = p.velocity_field(
        lambda c: np.where(
            c[:, :1] < 0.5,
            np.array([[0.1, 0.0, 0.0]]),
            np.array([[0.0, 0.1, 0.0]]),
        )
    )
    pts = np.array([[0.1, 0.3, 0.5], [0.8, 0.3, 0.5]])
    state = p.new_state(pts)
    state = p.step(state, velocity=vel, dt=1.0)
    got = p.positions(state)
    got = got[np.argsort(got[:, 0])]
    np.testing.assert_allclose(got[0], [0.2, 0.3, 0.5], atol=1e-12)
    np.testing.assert_allclose(got[1], [0.8, 0.4, 0.5], atol=1e-12)


def test_scatter_matches_loop_reference():
    """The vectorized bucketing fills slots exactly like per-particle
    appends in input order."""
    g = make_grid(n_dev=8)
    p = Particles(g, max_particles_per_cell=8)
    rng = np.random.default_rng(7)
    pts = np.column_stack(
        [rng.random(200), rng.random(200), np.full(200, 0.5)]
    )
    state = p.new_state(pts)
    assert p.count(state) == 200
    pos = np.asarray(state["particles"])
    cnt = np.asarray(state["number_of_particles"])
    # reference slow path
    import numpy as _np

    cells = g.get_existing_cell(pts)
    lpos = g.leaves.position(cells)
    dev = g.leaves.owner[lpos]
    row = g.epoch.row_of[lpos]
    exp_pos = _np.zeros_like(pos)
    exp_cnt = _np.zeros_like(cnt)
    for d, r, pt in zip(dev, row, pts):
        exp_pos[d, r, exp_cnt[d, r]] = pt
        exp_cnt[d, r] += 1
    _np.testing.assert_array_equal(cnt, exp_cnt)
    _np.testing.assert_allclose(pos, exp_pos)


@pytest.mark.parametrize("seed", [2, 7])
def test_fuzz_particles_random_grids(seed):
    """Randomized PIC: random (possibly refined) grid and device count;
    particle count conserved through pushes with migration, buckets stay
    position-consistent, and the machinery survives AMR and a load
    balance."""
    rng = np.random.default_rng(seed)
    n = int(rng.choice([4, 6, 8]))
    n_dev = int(rng.choice([1, 2, 4, 8]))
    g = (
        Grid()
        .set_initial_length((n, n, n))
        .set_neighborhood_length(1)
        .set_periodic(True, True, True)
        .set_maximum_refinement_level(1)
        .set_geometry(
            CartesianGeometry,
            start=(0.0, 0.0, 0.0),
            level_0_cell_length=(1.0 / n,) * 3,
        )
        .initialize(mesh=make_mesh(n_devices=n_dev))
    )
    if rng.random() < 0.5:
        ids = g.get_cells()
        for cid in rng.choice(ids, size=len(ids) // 6 + 1, replace=False):
            g.refine_completely(int(cid))
        g.stop_refining()
    npart = int(rng.integers(200, 1500))
    m = Particles(g, max_particles_per_cell=256)
    state = m.new_state(rng.random((npart, 3)))
    vel = m.velocity_field(lambda c: 0.2 * (c - 0.5))
    for turn in range(4):
        state = m.step(state, velocity=vel, dt=0.1)
        assert m.count(state) == npart
    ids = g.get_cells()
    for cell in rng.choice(ids, size=min(30, len(ids)), replace=False):
        pts = m.particles_of(state, int(cell))
        if len(pts):
            lo = g.geometry.get_min(np.asarray([cell], np.uint64))[0]
            hi = g.geometry.get_max(np.asarray([cell], np.uint64))[0]
            assert ((pts >= lo - 1e-12) & (pts <= hi + 1e-12)).all()
    for cid in rng.choice(ids, size=3, replace=False):
        g.refine_completely(int(cid))
    g.stop_refining()
    state = m.remap(state)
    g.balance_load()
    state = m.remap(state)
    state = m.step(
        state, velocity=m.velocity_field(lambda c: 0.2 * (c - 0.5)), dt=0.1
    )
    assert m.count(state) == npart


def test_device_rebucket_matches_host():
    """The device-side sort re-bucket (uniform fully-periodic grids) is
    bit-identical to the host path, across device counts, including the
    one-dispatch run() loop."""
    def build(nd):
        return make_grid((8, 8, 4), periodic=(True, True, True), n_dev=nd)

    rng = np.random.default_rng(7)
    pts = rng.uniform(0, 1, size=(500, 3))
    vel = (0.09, -0.04, 0.13)

    results = {}
    for nd in (1, 4):
        g = build(nd)
        pc = Particles(g, max_particles_per_cell=32)
        assert pc._dev_rebucket is not None
        s = pc.new_state(pts)
        s = pc.run(s, 25, velocity=vel, dt=0.5)
        assert pc.count(s) == 500
        assert int(np.asarray(s["overflow"])) == 0
        results[nd] = np.sort(pc.positions(s), axis=0)

    g = build(1)
    pc = Particles(g, max_particles_per_cell=32)
    pc._dev_rebucket = None          # force the host mechanism
    s = pc.new_state(pts)
    for _ in range(25):
        s = pc.step(s, velocity=vel, dt=0.5)
    host = np.sort(pc.positions(s), axis=0)
    for r in results.values():
        np.testing.assert_array_equal(r, host)


def test_device_rebucket_overflow_counter():
    """Cell-capacity overflow on the device path drops the excess and
    counts it (the host path raises instead)."""
    g = make_grid((4, 4, 4), periodic=(True, True, True), n_dev=1)
    pc = Particles(g, max_particles_per_cell=2)
    with pytest.raises(ValueError):
        pc.new_state(np.full((5, 3), 0.6))  # host scatter rejects
    # the device path instead drops and counts: converge particles from
    # several cells into one via a contracting velocity field
    g2 = make_grid((4, 1, 1), periodic=(True, True, True), n_dev=1)
    pc2 = Particles(g2, max_particles_per_cell=2)
    assert pc2._dev_rebucket is not None
    spread = np.column_stack([
        np.array([0.05, 0.3, 0.55, 0.8, 0.1, 0.35]),
        np.full(6, 0.5), np.full(6, 0.5),
    ])
    s2 = pc2.new_state(spread)
    vel = pc2.velocity_field(lambda c: np.column_stack([
        0.5 - c[:, 0], np.zeros(len(c)), np.zeros(len(c))]))
    s2 = pc2.run(s2, 8, velocity=vel, dt=1.0)
    dropped = int(np.asarray(s2["overflow"]))
    kept = pc2.count(s2)
    assert dropped > 0
    assert kept + dropped == 6


def test_device_rebucket_counts_beyond_halo_loss():
    """A particle that out-runs the ghost halo in one step (displacement
    > 1 cell across a device boundary) cannot be handed off — the device
    path drops it but must account for it in ``overflow``."""
    g = make_grid((4, 4, 4), periodic=(True, True, True), n_dev=4)
    pc = Particles(g, max_particles_per_cell=8)
    assert pc._dev_rebucket is not None
    pts = np.array([[0.5, 0.5, 0.125]])   # z-cell 0 on device 0
    s = pc.new_state(pts)
    # jump 2 z-cells in one step: lands on device 2, never ghosted here
    s = pc.run(s, 1, velocity=(0.0, 0.0, 0.5), dt=1.0)
    assert pc.count(s) == 0
    assert int(np.asarray(s["overflow"])) == 1


def test_device_rebucket_on_refined_grid():
    """The generalized device re-bucket keys on the epoch's leaf tables,
    so an AMR grid stays on device (reference particles under refinement,
    tests/particles/simple.cpp:52-97) — bit-identical to the host path."""
    def build(nd):
        g = make_grid((4, 4, 2), periodic=(True, True, True), max_ref=2,
                      n_dev=nd)
        for c in (1, 2, 7, 12):
            g.refine_completely(c)
        g.stop_refining()
        kid = int(g.mapping.get_all_children(np.uint64(1))[0])
        g.refine_completely(kid)
        g.stop_refining()
        return g

    rng = np.random.default_rng(11)
    pts = rng.uniform(0, 1, size=(300, 3))
    vel = (0.05, -0.03, 0.04)

    results = {}
    for nd in (1, 4):
        g = build(nd)
        pc = Particles(g, max_particles_per_cell=64)
        assert pc._dev_rebucket is not None, "AMR grid must stay on device"
        s = pc.new_state(pts)
        s = pc.run(s, 10, velocity=vel, dt=0.5)
        assert pc.count(s) == 300
        assert int(np.asarray(s["overflow"])) == 0
        results[nd] = np.sort(pc.positions(s), axis=0)

    g = build(1)
    pc = Particles(g, max_particles_per_cell=64)
    pc._dev_rebucket = None          # force the host mechanism
    s = pc.new_state(pts)
    for _ in range(10):
        s = pc.step(s, velocity=vel, dt=0.5)
    host = np.sort(pc.positions(s), axis=0)
    for nd, r in results.items():
        np.testing.assert_array_equal(r, host, err_msg=f"n_dev={nd}")


def test_device_rebucket_after_balance_load():
    """Post-balance_load ownership (arbitrary, non-block-striped) stays
    on the device path: remap() rebuilds the row tables and the run()
    loop keeps matching the host path (reference runs particles under
    balance_load as a matter of course, simple.cpp:285-294)."""
    def run_one(host_path):
        g = make_grid((8, 8, 2), periodic=(True, True, True), n_dev=4)
        pc = Particles(g, max_particles_per_cell=32)
        rng = np.random.default_rng(23)
        pts = rng.uniform(0, 1, size=(200, 3))
        s = pc.new_state(pts)
        s = pc.run(s, 5, velocity=(0.07, 0.05, 0.0), dt=0.5)
        # scatter ownership away from block striping
        for cell in g.get_cells()[::3]:
            g.pin(int(cell), int(cell) % 4)
        g.balance_load()
        s = pc.remap(s)   # re-buckets into the new layout itself
        if host_path:
            pc._dev_rebucket = None
        else:
            assert pc._dev_rebucket is not None, \
                "pinned/scattered ownership must stay on device"
        if pc._dev_rebucket is not None:
            s = pc.run(s, 10, velocity=(0.07, 0.05, 0.0), dt=0.5)
        else:
            for _ in range(10):
                s = pc.step(s, velocity=(0.07, 0.05, 0.0), dt=0.5)
        assert pc.count(s) == 200
        return np.sort(pc.positions(s), axis=0)

    dev = run_one(host_path=False)
    host = run_one(host_path=True)
    np.testing.assert_array_equal(dev, host)


def test_exact_upper_edge_matches_host():
    """The domain is closed ([start, end]): a particle exactly on the
    upper edge belongs to the last cell on BOTH re-bucket paths,
    periodic or not (a plain mod would fold end onto start on the
    device path and diverge from the host bucket)."""
    for periodic in ((True, True, True), (False, False, False)):
        g = make_grid((4, 4, 4), periodic=periodic, n_dev=1)
        pc = Particles(g)
        assert pc._dev_rebucket is not None
        pt = np.array([[1.0, 0.5, 0.5]])
        s = pc.new_state(pt)           # host scatter accepts the edge
        host_cell = int(g.get_existing_cell(pt)[0])
        s = pc.rebucket(s)             # device path must agree
        assert pc.count(s) == 1, periodic
        assert int(np.asarray(s["overflow"])) == 0, periodic
        got = pc.particles_of(s, host_cell)
        assert len(got) == 1 and np.allclose(got[0], pt[0]), periodic
