"""Device-timeline merge tests (ISSUE 6): the xplane wire decoder
against hand-encoded protos, clock alignment on synthetic skewed
timelines, the merged-trace overlap/attribution math on constructed
evidence, the cross-process fleet merge, the timeline context/truncation
satellites, and one end-to-end profiled advection round whose merged
trace must validate with a measured overlap fraction."""
import json
import os
import struct
import sys

import numpy as np
import pytest

from dccrg_tpu import obs
from dccrg_tpu.obs import xplane as xp
from dccrg_tpu.obs.events import EventTimeline
from dccrg_tpu.obs.merge import (
    DEVICE_PID_BASE,
    ClockAlignment,
    MergedTrace,
    build_merged,
    merge_chrome_traces,
    validate_merged_trace,
    _intersect,
    _measure,
    _union,
)
from dccrg_tpu.obs.registry import MetricsRegistry

HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.dirname(HERE)


# ------------------------------------------------- proto wire encoding
# A miniature protobuf ENCODER for the XSpace subset — the test builds
# real wire bytes by hand so the decoder is checked against the format,
# not against itself.


def _enc_varint(v: int) -> bytes:
    out = bytearray()
    v &= (1 << 64) - 1
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _enc_field(num: int, wire: int, payload) -> bytes:
    tag = _enc_varint((num << 3) | wire)
    if wire == 0:
        return tag + _enc_varint(payload)
    if wire == 2:
        return tag + _enc_varint(len(payload)) + payload
    if wire == 1:
        return tag + payload
    raise ValueError(wire)


def _enc_str(num: int, s: str) -> bytes:
    return _enc_field(num, 2, s.encode())


def _enc_map_entry(num: int, key: int, msg: bytes) -> bytes:
    entry = _enc_field(1, 0, key) + _enc_field(2, 2, msg)
    return _enc_field(num, 2, entry)


def _enc_stat(metadata_id: int, *, ref=None, s=None, i64=None) -> bytes:
    out = _enc_field(1, 0, metadata_id)
    if ref is not None:
        out += _enc_field(7, 0, ref)
    if s is not None:
        out += _enc_str(5, s)
    if i64 is not None:
        out += _enc_field(4, 0, i64)
    return out


def _enc_event(metadata_id: int, offset_ps: int, dur_ps: int,
               stats=()) -> bytes:
    out = (_enc_field(1, 0, metadata_id) + _enc_field(2, 0, offset_ps)
           + _enc_field(3, 0, dur_ps))
    for st in stats:
        out += _enc_field(4, 2, st)
    return out


def _enc_line(line_id: int, name: str, timestamp_ns: int,
              events=()) -> bytes:
    out = (_enc_field(1, 0, line_id) + _enc_str(2, name)
           + _enc_field(3, 0, timestamp_ns))
    for ev in events:
        out += _enc_field(4, 2, ev)
    return out


def _named(mid: int, name: str) -> bytes:
    return _enc_field(1, 0, mid) + _enc_str(2, name)


def _make_xspace(tmp_path, device_plane=True):
    """One hand-encoded capture: a host plane with a python line
    (markers incl. two clock-sync beacons) and, optionally, a device
    plane with two kernel events carrying hlo_module stats."""
    # host plane: stat/event metadata + python line
    ev_meta = (
        _enc_map_entry(4, 1, _named(1, f"{xp.CLOCK_SYNC_TAG}:1000000"))
        + _enc_map_entry(4, 2, _named(2, f"{xp.CLOCK_SYNC_TAG}:3000000"))
        + _enc_map_entry(4, 3, _named(3, "my_phase"))
        + _enc_map_entry(4, 4, _named(4, "$frame ignored"))
    )
    # beacons at xplane 1.5ms/3.5ms for embedded perf 1ms/3ms:
    # offset = 0.5 ms
    line = _enc_line(7, "python", 1_000_000, events=[
        _enc_event(1, 500_000_000, 1000),      # 1.5e6 ns
        _enc_event(2, 2_500_000_000, 1000),    # 3.5e6 ns
        _enc_event(3, 600_000_000, 400_000_000),  # my_phase 400 µs
        _enc_event(4, 0, 1_000_000),           # python frame: skipped
    ])
    host_plane = _enc_str(2, "/host:CPU") + ev_meta + _enc_field(3, 2, line)
    space = _enc_field(1, 2, host_plane)
    if device_plane:
        smd = (_enc_map_entry(5, 10, _named(10, "hlo_module"))
               + _enc_map_entry(5, 11, _named(11, "jit_test_kernel")))
        emd = (_enc_map_entry(4, 1, _named(1, "fusion.1"))
               + _enc_map_entry(4, 2, _named(2, "no-module-op")))
        k1 = _enc_event(1, 100_000_000, 50_000_000,   # 50 µs
                        stats=[_enc_stat(10, ref=11)])
        k2 = _enc_event(1, 700_000_000, 100_000_000,  # 100 µs
                        stats=[_enc_stat(10, s="jit_other")])
        k3 = _enc_event(2, 900_000_000, 1_000_000)  # no hlo_module: skip
        dev_line = _enc_line(1, "XLA Ops", 2_000_000,
                             events=[k1, k2, k3])
        dev_plane = (_enc_str(2, "/device:TPU:3") + emd + smd
                     + _enc_field(3, 2, dev_line))
        space += _enc_field(1, 2, dev_plane)
    d = tmp_path / "plugins" / "profile" / "run1"
    d.mkdir(parents=True)
    (d / "host.xplane.pb").write_bytes(space)
    return str(tmp_path)


# ------------------------------------------------------------- decoder


def test_xplane_decoder_against_hand_encoded_proto(tmp_path):
    log_dir = _make_xspace(tmp_path)
    files = xp.find_xplane_files(log_dir)
    assert len(files) == 1
    planes = xp.parse_xplane(files[0])
    assert [p["name"] for p in planes] == ["/host:CPU", "/device:TPU:3"]
    host = planes[0]
    assert host["lines"][0]["name"] == "python"
    assert host["lines"][0]["timestamp_ns"] == 1_000_000
    evs = host["lines"][0]["events"]
    assert evs[0]["start_ns"] == pytest.approx(1_500_000)
    assert evs[2]["name"] == "my_phase"
    assert evs[2]["dur_ns"] == pytest.approx(400_000)
    dev = planes[1]
    k1 = dev["lines"][0]["events"][0]
    # ref-valued stats deref through the stat-metadata table
    assert k1["stats"]["hlo_module"] == "jit_test_kernel"
    assert k1["start_ns"] == pytest.approx(2_000_000 + 100_000)
    assert k1["dur_ns"] == pytest.approx(50_000)


def test_xplane_ingest_classification(tmp_path):
    ing = xp.ingest(_make_xspace(tmp_path))
    assert ing.has_device_evidence
    assert len(ing.exec_lines) == 1
    line = ing.exec_lines[0]
    assert line.kind == "device"
    assert line.device_id == 3       # parsed from /device:TPU:3
    # only hlo_module-bearing events become kernel spans
    assert [s.module for s in line.spans] == ["jit_test_kernel",
                                              "jit_other"]
    assert line.busy_ns() == pytest.approx(150_000)
    # python-tracer frames ($-prefixed) are dropped, annotations kept
    names = [m.name for m in ing.markers]
    assert "my_phase" in names
    assert not any(n.startswith("$") for n in names)
    syncs = xp.clock_syncs(ing)
    assert syncs == [(1_000_000, pytest.approx(1_500_000)),
                     (3_000_000, pytest.approx(3_500_000))]


def test_xplane_ingest_graceful_paths(tmp_path, monkeypatch):
    # no files at all
    ing = xp.ingest(str(tmp_path))
    assert ing.paths == [] and not ing.has_device_evidence
    # opt-out drops everything even when files exist
    _make_xspace(tmp_path)
    monkeypatch.setenv("DCCRG_XPLANE", "0")
    assert not xp.xplane_enabled()
    ing = xp.ingest(str(tmp_path))
    assert ing.paths == [] and not ing.has_device_evidence
    monkeypatch.setenv("DCCRG_XPLANE", "1")
    # host-only capture (no device plane, no runtime lines): valid
    # ingest, no evidence — the documented deviceless no-op
    host_only = tmp_path / "hostonly"
    host_only.mkdir()
    _make_xspace(host_only, device_plane=False)
    ing = xp.ingest(str(host_only))
    assert ing.paths and not ing.has_device_evidence
    assert xp.clock_syncs(ing)   # beacons still recoverable


def test_varint_signed64_roundtrip():
    from dccrg_tpu.obs.xplane import _signed64, _varint

    for v in (0, 1, 127, 128, 300, 2 ** 32, 2 ** 63 - 1):
        buf = _enc_varint(v)
        got, pos = _varint(buf, 0)
        assert (got, pos) == (v, len(buf))
        assert _signed64(got) == v
    # negative int64s are 10-byte varints in two's complement
    buf = _enc_varint(-5 & ((1 << 64) - 1))
    got, _ = _varint(buf, 0)
    assert _signed64(got) == -5


# ----------------------------------------------------- clock alignment


def test_clock_alignment_synthetic_skew():
    # xplane clock = perf clock + 123456789 ns, beacons jittered a few µs
    true_offset = 123_456_789
    rng = np.random.default_rng(0)
    pairs = []
    for i in range(7):
        perf_ns = 1_000_000 * (i + 1)
        jitter = int(rng.integers(0, 5_000))
        pairs.append((perf_ns, perf_ns + true_offset + jitter))
    al = ClockAlignment.from_syncs(pairs)
    assert abs(al.offset_ns - true_offset) <= 5_000
    assert al.n_syncs == 7 and al.spread_ns <= 5_000
    # a descheduled outlier beacon must not drag the median
    pairs.append((8_000_000, 8_000_000 + true_offset + 50_000_000))
    al2 = ClockAlignment.from_syncs(pairs)
    assert abs(al2.offset_ns - true_offset) <= 5_000
    # the mapping inverts the skew
    assert al.to_perf_s(2_000_000 + al.offset_ns) == pytest.approx(2e-3)
    assert ClockAlignment.from_syncs([]) is None


def test_interval_algebra():
    assert _union([(3, 5), (1, 2), (4, 7), (9, 9)]) == [(1, 2), (3, 7)]
    assert _intersect([(1, 5)], [(2, 3), (4, 8)]) == [(2, 3), (4, 5)]
    assert _measure([(1, 2), (3, 7)]) == 5


# ------------------------------------------------- merged trace (unit)


def _synthetic_merged(overlap_ms=2.0, with_timeline_spans=True):
    """Constructed evidence with a KNOWN overlap fraction: host halo
    window [10ms, 16ms] (start span [10,11], exchange span [15,16]),
    one device running interior compute [12ms, 12+overlap_ms] and a
    collective [11.2ms, 11.5ms]."""
    tl = EventTimeline(enabled=True)
    t0 = tl.origin_perf
    if with_timeline_spans:
        tl.add("halo.start", t0 + 10e-3, 1e-3)
        tl.add("halo.exchange", t0 + 15e-3, 1e-3)
        tl.add("epoch.build", t0 + 1e-3, 2e-3)
    # xplane clock: perf_ns + K
    K = 5_000_000_000
    align = ClockAlignment(K, 3, 100.0)

    def x(ms):
        return t0 * 1e9 + ms * 1e6 + K

    spans = [
        # edge spans pin the device-evidence window to [9, 17] ms so the
        # whole halo window sits inside the profiled clip
        xp.KernelSpan("pad", "jit_pad", x(9.0), 0.1e6),
        xp.KernelSpan("fusion.7", "jit_model_step", x(12.0),
                      overlap_ms * 1e6),
        xp.KernelSpan("ppermute", "jit_halo_body", x(11.2), 0.3e6),
        xp.KernelSpan("pad", "jit_pad", x(16.9), 0.1e6),
    ]
    ing = xp.XIngest(["synthetic"],
                     [xp.ExecLine(0, "XLA Ops", "device", spans)],
                     [], ["/device:TPU:0"])
    labels = {"jit_model_step": "model.step", "jit_halo_body": "halo.body",
              "jit_pad": "pad.op"}
    return build_merged(ingest=ing, timeline=tl, alignment=align,
                        kernel_labels=labels), tl


def test_merged_overlap_fraction_known_value():
    merged, _tl = _synthetic_merged(overlap_ms=2.0)
    s = merged.summary()
    assert s["aligned"] and s["device_evidence"]
    ov = s["overlap"]["halo"]
    # in-flight window = [10, 16] ms = 6 ms; compute inside = 2 ms
    assert ov["inflight_s"] == pytest.approx(6e-3, rel=1e-6)
    assert ov["overlap_s"] == pytest.approx(2e-3, rel=1e-6)
    assert ov["fraction"] == pytest.approx(2 / 6, abs=1e-6)
    assert ov["device_collective_s"] == pytest.approx(0.3e-3, rel=1e-6)
    # kernel attribution keyed by traced_jit labels
    assert s["kernels"]["model.step"]["count"] == 1
    assert s["kernels"]["model.step"]["time_us"] == pytest.approx(2000)
    assert s["kernels"]["halo.body"]["module"] == "jit_halo_body"


def test_merged_gauges_recorded_from_evidence():
    merged, _tl = _synthetic_merged()
    reg = MetricsRegistry()
    s = merged.record_gauges(reg)
    rep = reg.report()
    assert rep["gauges"]["overlap.fraction"]["phase=halo"] == \
        pytest.approx(s["overlap"]["halo"]["fraction"])
    assert "device=0" in rep["gauges"]["device.busy_fraction"]
    assert rep["counters"]["device.kernel_time_us"]["kernel=model.step"] \
        == 2000
    # no evidence -> no gauges (the deviceless no-op)
    tl = EventTimeline(enabled=True)
    empty = build_merged(ingest=xp.XIngest([], [], [], []), timeline=tl,
                         kernel_labels={})
    reg2 = MetricsRegistry()
    s2 = empty.record_gauges(reg2)
    assert not s2["device_evidence"]
    assert reg2.report()["gauges"] == {}


def test_merged_chrome_trace_validates():
    merged, _tl = _synthetic_merged()
    trace = merged.to_chrome()
    assert validate_merged_trace(trace) == []
    evs = trace["traceEvents"]
    # one pid per device, distinct from the host pid
    dev_pids = {e["pid"] for e in evs if e.get("ph") == "X"}
    assert dev_pids == {DEVICE_PID_BASE + 0}
    assert os.getpid() not in dev_pids
    # async b/e pair spans host dispatch -> device completion for the
    # collective span
    bs = [e for e in evs if e.get("ph") == "b"]
    es = [e for e in evs if e.get("ph") == "e"]
    assert len(bs) == 1 and len(es) == 1
    assert bs[0]["id"] == es[0]["id"]
    assert bs[0]["ts"] == pytest.approx(10_000, abs=1)  # halo.start begin
    assert es[0]["ts"] >= bs[0]["ts"]
    # B/E host events still matched and monotonic per tid
    host_ts = [e["ts"] for e in evs
               if e.get("ph") in ("B", "E") and e["pid"] == os.getpid()
               and e["tid"] == 0]
    assert host_ts == sorted(host_ts)


def test_merged_export_compaction(tmp_path):
    merged, _tl = _synthetic_merged()
    path = tmp_path / "m.json"
    merged.export(str(path), max_spans_per_device=1)
    data = json.loads(path.read_text())
    assert data["otherData"]["device_spans_dropped"] == {"0": 3}
    xs = [e for e in data["traceEvents"] if e.get("ph") == "X"]
    assert len(xs) == 1 and xs[0]["name"] == "model.step"  # longest kept
    assert validate_merged_trace(str(path)) == []


def test_validate_merged_trace_catches_breakage():
    merged, _tl = _synthetic_merged()
    trace = merged.to_chrome()
    bad = json.loads(json.dumps(trace))
    # unmatched async begin
    bad["traceEvents"] = [e for e in bad["traceEvents"]
                          if e.get("ph") != "e"]
    assert any("never ended" in f for f in validate_merged_trace(bad))
    bad2 = json.loads(json.dumps(trace))
    for e in bad2["traceEvents"]:
        if e.get("ph") == "X":
            e["dur"] = -5
            break
    assert any("negative dur" in f for f in validate_merged_trace(bad2))


# --------------------------------------------------------- fleet merge


def test_fleet_merge_shifts_onto_shared_epoch_zero(tmp_path):
    def one_proc(origin, name):
        tl = EventTimeline(enabled=True)
        tl.rebase(0.0, origin)
        tl.add("halo.exchange", 1e-3, 1e-3)
        tr = tl.chrome_trace()
        p = tmp_path / name
        p.write_text(json.dumps(tr))
        return str(p)

    p1 = one_proc(100.0, "a.trace.json")
    p2 = one_proc(100.5, "b.trace.json")   # started 500 ms later
    fleet = merge_chrome_traces([p1, p2],
                                out_path=str(tmp_path / "fleet.json"))
    assert fleet["otherData"]["origin_unix_s"] == 100.0
    assert validate_merged_trace(fleet) == []
    spans = [e for e in fleet["traceEvents"] if e.get("ph") == "B"]
    assert len(spans) == 2
    ts = sorted(e["ts"] for e in spans)
    # second process's identical span lands 500 ms later on the shared
    # epoch-zero
    assert ts[1] - ts[0] == pytest.approx(500_000, abs=1)
    # pids renumbered per process — no collision even though both
    # processes exported the same os pid
    assert len({e["pid"] for e in spans}) == 2
    # a source without the anchor is rejected loudly
    (tmp_path / "bad.json").write_text(json.dumps({"traceEvents": []}))
    with pytest.raises(ValueError, match="origin_unix_s"):
        merge_chrome_traces([str(tmp_path / "bad.json")])


# ---------------------------------------- timeline satellites (ISSUE 6)


def test_timeline_context_args_layering():
    tl = EventTimeline(enabled=True)
    with tl.context(grid_id=7):
        with tl.span("outer"):
            pass
        with tl.context(step=3):
            with tl.span("inner", extra="x"):
                pass
    with tl.span("outside"):
        pass
    spans = {s["name"]: s["args"] for s in tl.spans()}
    assert spans["outer"] == {"grid_id": 7}
    assert spans["inner"] == {"grid_id": 7, "step": 3, "extra": "x"}
    assert spans["outside"] is None


def test_timeline_drop_counter_and_truncation_marker():
    obs.metrics.reset()
    obs.enable()
    tl = EventTimeline(enabled=True, max_events=2)
    for i in range(5):
        tl.add(f"e{i}", float(i), 0.5)
    assert tl.summary()["dropped"] == 3
    assert tl.summary()["max_events"] == 2
    assert obs.metrics.counter_value("timeline.dropped") == 3
    trace = tl.chrome_trace()
    markers = [e for e in trace["traceEvents"]
               if e.get("name") == "timeline.truncated"]
    assert len(markers) == 1
    assert markers[0]["ph"] == "i"
    assert markers[0]["args"]["dropped_events"] == 3
    # a truncated timeline still validates (instant events are legal)
    assert validate_merged_trace(trace) == []


def test_concurrent_grids_separable_by_grid_id():
    from test_obs import _small_grid

    obs.metrics.reset()
    obs.enable()
    obs.timeline.clear()
    obs.enable_timeline()
    g1 = _small_grid(max_ref=0, length=(4, 4, 1))
    g2 = _small_grid(max_ref=0, length=(4, 4, 1))
    assert g1.grid_id != g2.grid_id
    st1 = g1.new_state({"rho": ((), np.float64)})
    st2 = g2.new_state({"rho": ((), np.float64)})
    obs.timeline.clear()
    g1.update_copies_of_remote_neighbors(st1)
    g2.update_copies_of_remote_neighbors(st2)
    halo_args = [s["args"] for s in obs.timeline.spans()
                 if s["name"] == "halo.exchange"]
    assert {a["grid_id"] for a in halo_args} == {g1.grid_id, g2.grid_id}
    assert g1.report()["grid"]["grid_id"] == g1.grid_id


# ------------------------------------------------------- end to end


def test_profiled_round_merges_and_measures(tmp_path):
    """The acceptance path: a tiny profiled split-phase advection round
    must produce a schema-valid merged trace (matched B/E pairs, one
    pid per device, monotonic ts), nonzero device-busy time, an
    overlap fraction in [0, 1], and kernel attribution intersecting the
    ``epoch.recompiles`` key set — or, on a backend whose capture has
    no execution lines, the documented graceful no-op."""
    sys.path.insert(0, os.path.join(ROOT, "tools"))
    try:
        import check_telemetry as ct
    finally:
        sys.path.pop(0)
    obs.metrics.reset()
    obs.enable()
    obs.timeline.clear()
    obs.enable_timeline()
    from test_obs import _small_grid

    import jax

    from dccrg_tpu.models import Advection

    g = _small_grid(max_ref=0, hood=0, length=(8, 8, 1))
    adv = Advection(g, dtype=np.float32, allow_dense=False)
    state = adv.initialize_state()
    dt = np.float32(0.4 * adv.max_time_step(state))
    state = ct.drive_split(g, adv, state, dt, 1)      # warm compiles
    log_dir = tmp_path / "profile"
    with obs.profile_trace(str(log_dir)):
        state = ct.drive_split(g, adv, state, dt, 3)
    merged_path = tmp_path / "merged.json"
    merged, summary = obs.merge_profile(str(log_dir),
                                        out_path=str(merged_path))
    if not summary["device_evidence"]:
        pytest.skip("backend emitted no execution lines (documented "
                    "deviceless no-op)")
    assert summary["aligned"]
    assert summary["alignment"]["n_syncs"] >= 2
    # nonzero device-busy time, fractions in [0, 1]
    assert summary["devices"]
    for rec in summary["devices"].values():
        assert rec["busy_s"] > 0
        assert 0.0 <= rec["fraction"] <= 1.0
    frac = summary["overlap"]["halo"]["fraction"]
    assert frac is not None and 0.0 <= frac <= 1.0
    # attribution closes the loop with the recompile counters
    rep = obs.metrics.report()
    attributed = set(rep["counters"].get("device.kernel_time_us", {}))
    compiled = set(rep["counters"].get("epoch.recompiles", {}))
    assert attributed & compiled
    # merged trace file validates; exactly one pid per device
    assert validate_merged_trace(str(merged_path)) == []
    trace = json.loads(merged_path.read_text())
    xs_pids = {e["pid"] for e in trace["traceEvents"]
               if e.get("ph") == "X"}
    assert len(xs_pids) == len(summary["devices"])
    jax.block_until_ready(state["density"])
