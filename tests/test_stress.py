"""Randomized AMR / load-balance stress test with full invariant
verification after every mutating operation — the analogue of the
reference's DEBUG-build workflow, where every test also runs as a
``*_debug.exe`` with ``is_consistent``/``verify_neighbors``/
``verify_remote_neighbor_info`` enabled after each mutating collective
(``dccrg.hpp:12264-12850``, SURVEY §4).

A seeded random sequence of refine/unrefine requests (with vetoes),
commits, and repartitions runs on the 8-device mesh; ``verify_grid`` and
ghost bit-identity (``verify_user_data``) are checked after every commit,
and mass is conserved through every ``remap_state``.
"""
import numpy as np
import pytest

from dccrg_tpu import CartesianGeometry, Grid, make_mesh
from dccrg_tpu.utils.verify import verify_grid, verify_user_data

SPEC = {"density": ((), np.float64)}


def make_grid(n=8, max_lvl=2, n_dev=8, method="RCB"):
    return (
        Grid()
        .set_initial_length((n, n, n))
        .set_neighborhood_length(1)
        .set_periodic(True, False, True)
        .set_maximum_refinement_level(max_lvl)
        .set_load_balancing_method(method)
        .set_geometry(
            CartesianGeometry,
            start=(0.0, 0.0, 0.0),
            level_0_cell_length=(1.0 / n,) * 3,
        )
        .initialize(mesh=make_mesh(n_devices=n_dev))
    )


def total_mass(grid, state):
    """Mass = sum over leaves of density * cell volume (level-weighted so
    refine/unrefine policies that preserve mass can be checked)."""
    ids = grid.get_cells()
    rho = grid.get_cell_data(state, "density", ids)
    lvl = grid.mapping.get_refinement_level(ids)
    vol = (1.0 / 8.0) ** lvl  # relative to a level-0 cell
    return float(np.sum(rho * vol))


@pytest.mark.parametrize("seed,method", [(0, "HILBERT"), (7, "GRAPH")])
def test_random_amr_lb_sequence_keeps_invariants(seed, method):
    rng = np.random.default_rng(seed)
    g = make_grid(method=method)
    state = g.new_state(SPEC, fill=0.0)
    ids = g.get_cells()
    state = g.set_cell_data(
        state, "density", ids, rng.uniform(1.0, 2.0, len(ids))
    )
    mass = total_mass(g, state)

    for round_i in range(6):
        ids = g.get_cells()
        # --- random refine/unrefine/veto requests
        for cid in rng.choice(ids, size=min(12, len(ids)), replace=False):
            op = rng.integers(4)
            if op == 0:
                g.refine_completely(int(cid))
            elif op == 1:
                g.unrefine_completely(int(cid))
            elif op == 2:
                g.dont_refine(int(cid))
            else:
                g.dont_unrefine(int(cid))
        new_cells = g.stop_refining()
        removed = g.get_removed_cells()
        # children inherit parent density, a new parent takes the mean of
        # its children — both exactly conserve level-weighted mass
        state = g.remap_state(state)
        verify_grid(g)
        verify_user_data(g, state, SPEC)
        assert total_mass(g, state) == pytest.approx(mass, rel=1e-12), (
            round_i, len(new_cells), len(removed)
        )

        # --- repartition with the grid's configured method
        if round_i % 2 == 1:
            g.balance_load()
            state = g.remap_state(state)
            verify_grid(g)
            verify_user_data(g, state, SPEC)
            assert total_mass(g, state) == pytest.approx(mass, rel=1e-12)

    # the sequence actually refined something: leaves above level 0 exist
    # (or the leaf count moved), so the invariant checks exercised a
    # genuinely adapted grid
    final = g.get_cells()
    final_lvls = g.mapping.get_refinement_level(final)
    assert final_lvls.max() > 0 or len(final) != 8**3


def test_stress_device_count_invariance():
    """The same seeded mutation sequence on 1 and 8 devices must produce
    identical leaf sets and identical cell data — the reference's
    'tests work with any number of processes' property (tests/README:5-7)."""

    def run(n_dev):
        rng = np.random.default_rng(3)
        g = make_grid(n_dev=n_dev)
        state = g.new_state(SPEC, fill=0.0)
        ids = g.get_cells()
        state = g.set_cell_data(
            state, "density", ids, rng.uniform(1.0, 2.0, len(ids))
        )
        for _ in range(4):
            ids = g.get_cells()
            for cid in rng.choice(ids, size=min(10, len(ids)), replace=False):
                op = rng.integers(3)
                if op == 0:
                    g.refine_completely(int(cid))
                elif op == 1:
                    g.unrefine_completely(int(cid))
                else:
                    g.dont_refine(int(cid))
            g.stop_refining()
            state = g.remap_state(state)
            g.balance_load()
            state = g.remap_state(state)
        ids = g.get_cells()
        return ids, np.asarray(g.get_cell_data(state, "density", ids))

    ids1, rho1 = run(1)
    ids8, rho8 = run(8)
    np.testing.assert_array_equal(ids1, ids8)
    np.testing.assert_allclose(rho1, rho8, rtol=0, atol=0)
