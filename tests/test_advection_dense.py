"""Dense fast path vs general gather path: same physics, same results."""
import numpy as np
import pytest

from dccrg_tpu import CartesianGeometry, Grid, make_mesh
from dccrg_tpu.models import Advection


def make(n=8, nz=8, periodic=(True, True, True), allow_dense=True, n_dev=None):
    g = (
        Grid()
        .set_initial_length((n, n, nz))
        .set_neighborhood_length(0)
        .set_periodic(*periodic)
        .set_geometry(
            CartesianGeometry,
            start=(0.0, 0.0, 0.0),
            level_0_cell_length=(1.0 / n, 1.0 / n, 1.0 / nz),
        )
        .initialize(mesh=make_mesh(n_devices=n_dev))
    )
    return g, Advection(g, allow_dense=allow_dense)


def test_dense_detected():
    g, adv = make()
    assert adv.dense is not None
    assert adv.dense.nz_local == 1
    g2, adv2 = make(nz=4)  # 4 planes over 8 devices -> not slab-aligned
    assert adv2.dense is None


@pytest.mark.parametrize("periodic", [(True, True, True), (True, False, False)])
def test_dense_matches_general(periodic):
    g1, dense = make(periodic=periodic)
    g2, general = make(periodic=periodic, allow_dense=False)
    assert dense.dense is not None and general.dense is None

    s1 = dense.initialize_state()
    s2 = general.initialize_state()
    cells = g1.get_cells()
    # seed a z-velocity so all six faces carry flux
    vz = 0.3 * np.sin(2 * np.pi * g1.geometry.get_center(cells)[:, 2])
    s1 = dense.set_cell_data(s1, "vz", cells, vz)
    s2 = general.set_cell_data(s2, "vz", cells, vz)
    s2 = g2.update_copies_of_remote_neighbors(s2)

    np.testing.assert_allclose(
        dense.get_cell_data(s1, "density", cells),
        general.get_cell_data(s2, "density", cells),
        rtol=0, atol=0,
    )
    dt = 0.4 * min(dense.max_time_step(s1), general.max_time_step(s2))
    for _ in range(8):
        s1 = dense.step(s1, dt)
        s2 = general.step(s2, dt)
    np.testing.assert_allclose(
        dense.get_cell_data(s1, "density", cells),
        general.get_cell_data(s2, "density", cells),
        rtol=1e-13, atol=1e-16,
    )


def test_dense_mass_conservation():
    g, adv = make()
    state = adv.initialize_state()
    m0 = adv.total_mass(state)
    dt = 0.4 * adv.max_time_step(state)
    for _ in range(20):
        state = adv.step(state, dt)
    assert adv.total_mass(state) == pytest.approx(m0, rel=1e-12)


def test_dense_single_device():
    g, adv = make(n_dev=1)
    assert adv.dense is not None
    state = adv.initialize_state()
    dt = 0.4 * adv.max_time_step(state)
    m0 = adv.total_mass(state)
    for _ in range(5):
        state = adv.step(state, dt)
    assert adv.total_mass(state) == pytest.approx(m0, rel=1e-12)


@pytest.mark.parametrize("periodic", [(True, True, True), (True, True, False)])
def test_pallas_integration_interpret(periodic):
    """The full Advection Pallas wiring (blocked per-step kernel in
    step(), fused whole-block kernel in run(), mask reshapes, device-dim
    handling) runs via the Pallas interpreter on CPU and matches the XLA
    dense path."""
    g, _ = make(periodic=periodic, n_dev=1)
    pal = Advection(g, dtype=np.float32, use_pallas="interpret")
    xla = Advection(g, dtype=np.float32, use_pallas=False)
    assert pal._fused_run is not None and xla._fused_run is None

    s0 = pal.initialize_state()
    cells = g.get_cells()
    vz = 0.3 * np.sin(2 * np.pi * g.geometry.get_center(cells)[:, 2])
    s0 = pal.set_cell_data(s0, "vz", cells, vz.astype(np.float32))
    dt = np.float32(0.4 * pal.max_time_step(s0))

    a = pal.step(s0, dt)
    b = xla.step(s0, dt)
    np.testing.assert_allclose(
        np.asarray(a["density"]), np.asarray(b["density"]), rtol=2e-7, atol=1e-9
    )

    a = pal.run(s0, 5, dt)
    b = s0
    for _ in range(5):
        b = xla.step(b, dt)
    np.testing.assert_allclose(
        np.asarray(a["density"]), np.asarray(b["density"]), rtol=1e-6, atol=1e-9
    )


def test_plane_kernel_interpret():
    """The fallback plane kernel (make_flux_update) still engages and
    matches XLA when no block size divides nzl (odd z extent) — the
    blocked kernel cannot be built there."""
    from dccrg_tpu.ops.dense_advection import pick_step_block

    g, _ = make(nz=7, n_dev=1)
    assert pick_step_block(7, 8, 8) == 0
    pal = Advection(g, dtype=np.float32, use_pallas="interpret")
    xla = Advection(g, dtype=np.float32, use_pallas=False)
    assert pal._dense_run is None  # blocked path did not engage

    s0 = pal.initialize_state()
    cells = g.get_cells()
    vz = 0.3 * np.sin(2 * np.pi * g.geometry.get_center(cells)[:, 2])
    s0 = pal.set_cell_data(s0, "vz", cells, vz.astype(np.float32))
    dt = np.float32(0.4 * pal.max_time_step(s0))
    a = pal.step(s0, dt)
    b = xla.step(s0, dt)
    np.testing.assert_allclose(
        np.asarray(a["density"]), np.asarray(b["density"]), rtol=2e-7, atol=1e-9
    )


@pytest.mark.parametrize("periodic", [(True, True, True), (True, True, False)])
@pytest.mark.parametrize("nz,n_dev", [(32, 1), (32, 4)])
def test_blocked_kernel_interpret(periodic, nz, n_dev):
    """The blocked per-step kernel (multi-plane z-blocks, halo stacks
    spliced in VMEM) matches the XLA dense path — with several blocks per
    device (m>1, interior strided-slice halo rows) and across devices
    (ppermute-received edge rows)."""
    from dccrg_tpu.ops.dense_advection import pick_step_block

    g, _ = make(nz=nz, periodic=periodic, n_dev=n_dev)
    pal = Advection(g, dtype=np.float32, use_pallas="interpret")
    xla = Advection(g, dtype=np.float32, use_pallas=False)
    nzl = nz // n_dev
    assert pick_step_block(nzl, 8, 8) >= 2  # blocked path engages
    assert pal._dense_run is not None

    s0 = pal.initialize_state()
    cells = g.get_cells()
    vz = 0.3 * np.sin(2 * np.pi * g.geometry.get_center(cells)[:, 2])
    s0 = pal.set_cell_data(s0, "vz", cells, vz.astype(np.float32))
    dt = np.float32(0.4 * pal.max_time_step(s0))

    a = pal.step(s0, dt)
    b = xla.step(s0, dt)
    np.testing.assert_allclose(
        np.asarray(a["density"]), np.asarray(b["density"]), rtol=2e-7, atol=1e-9
    )

    # the hoisted multi-step run matches stepping (called directly: on one
    # device run() would prefer the whole-block fused kernel)
    import jax.numpy as jnp

    a = pal._dense_run(s0, jnp.asarray(5, jnp.int32), dt)
    b = s0
    for _ in range(5):
        b = xla.step(b, dt)
    np.testing.assert_allclose(
        np.asarray(a["density"]), np.asarray(b["density"]), rtol=1e-6, atol=1e-9
    )


@pytest.mark.parametrize("periodic", [(True, True, True), (True, True, False)])
@pytest.mark.parametrize("steps", [4, 7])
def test_fused_run_kernel_matches_steps(periodic, steps):
    """The whole-block multi-step kernel (interpret mode on CPU) advances
    exactly like `steps` sequential XLA dense steps (f32)."""
    import jax.numpy as jnp

    from dccrg_tpu.ops.dense_advection import make_fused_run

    n, nz = 8, 8
    g, adv = make(n=n, nz=nz, periodic=periodic, n_dev=1)
    adv32 = Advection(g, dtype=np.float32)
    assert adv32.dense is not None and adv32.dense.n_devices == 1
    state = adv32.initialize_state()
    cells = g.get_cells()
    vz = 0.3 * np.sin(2 * np.pi * g.geometry.get_center(cells)[:, 2])
    state = adv32.set_cell_data(state, "vz", cells, vz.astype(np.float32))
    dt = np.float32(0.4 * adv32.max_time_step(state))

    l0 = g.geometry.get_level_0_cell_length()
    area = np.array([l0[1] * l0[2], l0[0] * l0[2], l0[0] * l0[1]])
    fused = make_fused_run(nz, n, n, area, 1.0 / float(l0.prod()), interpret=True)

    mask_x = np.ones(n, np.float32)
    mask_y = np.ones(n, np.float32)
    zface_up = np.ones(nz, np.float32)
    if not periodic[2]:
        zface_up[-1] = 0.0
    zface_dn = np.roll(zface_up, 1)
    got = fused(
        state["density"][0], state["vx"][0], state["vy"][0], state["vz"][0],
        jnp.asarray(mask_x).reshape(1, 1, n),
        jnp.asarray(mask_y).reshape(1, n, 1),
        jnp.asarray(zface_up).reshape(nz, 1, 1),
        jnp.asarray(zface_dn).reshape(nz, 1, 1),
        dt, steps,
    )

    ref = state
    for _ in range(steps):
        ref = adv32.step(ref, dt)
    # on real TPU the fused run is bit-identical to stepping; interpret
    # mode (XLA CPU) applies FMA contraction differently per path, so
    # allow ~1 ulp here
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(ref["density"][0]), rtol=2e-7, atol=1e-9
    )
