"""Dense fast path vs general gather path: same physics, same results."""
import numpy as np
import pytest

from dccrg_tpu import CartesianGeometry, Grid, make_mesh
from dccrg_tpu.models import Advection


def make(n=8, nz=8, periodic=(True, True, True), allow_dense=True, n_dev=None):
    g = (
        Grid()
        .set_initial_length((n, n, nz))
        .set_neighborhood_length(0)
        .set_periodic(*periodic)
        .set_geometry(
            CartesianGeometry,
            start=(0.0, 0.0, 0.0),
            level_0_cell_length=(1.0 / n, 1.0 / n, 1.0 / nz),
        )
        .initialize(mesh=make_mesh(n_devices=n_dev))
    )
    return g, Advection(g, allow_dense=allow_dense)


def test_dense_detected():
    g, adv = make()
    assert adv.dense is not None
    assert adv.dense.nz_local == 1
    g2, adv2 = make(nz=4)  # 4 planes over 8 devices -> not slab-aligned
    assert adv2.dense is None


@pytest.mark.parametrize("periodic", [(True, True, True), (True, False, False)])
def test_dense_matches_general(periodic):
    g1, dense = make(periodic=periodic)
    g2, general = make(periodic=periodic, allow_dense=False)
    assert dense.dense is not None and general.dense is None

    s1 = dense.initialize_state()
    s2 = general.initialize_state()
    cells = g1.get_cells()
    # seed a z-velocity so all six faces carry flux
    vz = 0.3 * np.sin(2 * np.pi * g1.geometry.get_center(cells)[:, 2])
    s1 = dense.set_cell_data(s1, "vz", cells, vz)
    s2 = general.set_cell_data(s2, "vz", cells, vz)
    s2 = g2.update_copies_of_remote_neighbors(s2)

    np.testing.assert_allclose(
        dense.get_cell_data(s1, "density", cells),
        general.get_cell_data(s2, "density", cells),
        rtol=0, atol=0,
    )
    dt = 0.4 * min(dense.max_time_step(s1), general.max_time_step(s2))
    for _ in range(8):
        s1 = dense.step(s1, dt)
        s2 = general.step(s2, dt)
    np.testing.assert_allclose(
        dense.get_cell_data(s1, "density", cells),
        general.get_cell_data(s2, "density", cells),
        rtol=1e-13, atol=1e-16,
    )


def test_dense_mass_conservation():
    g, adv = make()
    state = adv.initialize_state()
    m0 = adv.total_mass(state)
    dt = 0.4 * adv.max_time_step(state)
    for _ in range(20):
        state = adv.step(state, dt)
    assert adv.total_mass(state) == pytest.approx(m0, rel=1e-12)


def test_dense_single_device():
    g, adv = make(n_dev=1)
    assert adv.dense is not None
    state = adv.initialize_state()
    dt = 0.4 * adv.max_time_step(state)
    m0 = adv.total_mass(state)
    for _ in range(5):
        state = adv.step(state, dt)
    assert adv.total_mass(state) == pytest.approx(m0, rel=1e-12)
