"""Vlasov (velocity-block-per-cell) stretch workload tests."""
import numpy as np
import pytest

from dccrg_tpu import CartesianGeometry, Grid, make_mesh
from dccrg_tpu.models.vlasov import Vlasov


def make(n=8, nz=8, n_dev=None, periodic=(True, True, True)):
    return (
        Grid()
        .set_initial_length((n, n, nz))
        .set_neighborhood_length(0)
        .set_periodic(*periodic)
        .set_geometry(
            CartesianGeometry,
            start=(0.0, 0.0, 0.0),
            level_0_cell_length=(1.0 / n, 1.0 / n, 1.0 / nz),
        )
        .initialize(mesh=make_mesh(n_devices=n_dev))
    )


def test_general_path_converges_to_dense_on_uniform_grid():
    """A uniform grid whose partition is NOT slab-aligned (RCB) takes
    the general row-layout path.  The dense layout dimension-splits the
    update while the general path prices all faces unsplit (inheriting
    the oracle-validated advection face machinery), so the two differ by
    the O(dt) splitting error — the same evolved time must agree better
    as dt halves, and exactly in mass."""
    def evolve(dt_frac, steps):
        g_d = make(n=4, nz=8, n_dev=8)
        vl_d = Vlasov(g_d, nv=3, dtype=np.float64)
        assert vl_d.info is not None
        g_g = (
            Grid()
            .set_initial_length((4, 4, 8))
            .set_neighborhood_length(0)
            .set_periodic(True, True, True)
            .set_load_balancing_method("RCB")
            .set_geometry(
                CartesianGeometry,
                start=(0.0, 0.0, 0.0),
                level_0_cell_length=(0.25, 0.25, 0.125),
            )
            .initialize(mesh=make_mesh(n_devices=8))
        )
        g_g.balance_load()
        vl_g = Vlasov(g_g, nv=3, dtype=np.float64)
        assert vl_g.info is None, "RCB partition must take the general path"
        dt = dt_frac * vl_d.max_time_step()
        s_d = vl_d.run(vl_d.initialize_state(), steps, dt)
        s_g = vl_g.run(vl_g.initialize_state(), steps, dt)
        assert vl_g.total_mass(s_g) == pytest.approx(
            vl_d.total_mass(s_d), rel=1e-12
        )
        cells = np.sort(g_g.leaves.cells)
        f_g = np.asarray(g_g.get_cell_data(s_g, "f", cells), np.float64)
        f_d_grid = np.asarray(s_d["f"], np.float64).reshape(
            8, 4, 4, vl_d.B
        )
        lin = (cells - 1).astype(np.int64)
        f_d = f_d_grid[lin // 16, (lin // 4) % 4, lin % 4]
        return np.abs(f_g - f_d).max() / np.abs(f_d).max()

    err_coarse = evolve(0.4, 4)    # same evolved time: 4 x 0.4 CFL
    err_fine = evolve(0.2, 8)      # ... as 8 x 0.2 CFL
    assert err_coarse < 0.05, err_coarse
    assert err_fine < 0.62 * err_coarse, (err_fine, err_coarse)


def _refined_grid(n_dev=8):
    g = (
        Grid()
        .set_initial_length((6, 6, 6))
        .set_neighborhood_length(0)
        .set_periodic(True, True, True)
        .set_maximum_refinement_level(1)
        .set_geometry(
            CartesianGeometry,
            start=(0.0, 0.0, 0.0),
            level_0_cell_length=(1.0 / 6,) * 3,
        )
        .initialize(mesh=make_mesh(n_devices=n_dev))
    )
    ids = g.get_cells()
    c = g.geometry.get_center(ids)
    r = np.linalg.norm(c - 0.5, axis=1)
    for cid in ids[r < 0.3]:
        g.refine_completely(int(cid))
    g.stop_refining()
    return g


def test_refined_grid_per_bin_matches_advection():
    """The AMR Vlasov path vs the oracle it is built to equal: each
    velocity bin advects with a spatially-constant velocity, which is
    exactly the (validated) general advection step with constant
    velocity fields — per bin, the two must agree to f64 roundoff on a
    refined grid."""
    from dccrg_tpu.models import Advection

    g = _refined_grid()
    ids = np.sort(g.leaves.cells)
    vl = Vlasov(g, nv=2, dtype=np.float64)
    assert vl.info is None
    s = vl.initialize_state()
    dt = 0.3 * vl.max_time_step()
    steps = 5
    out = vl.run(s, steps, dt)
    f0 = np.asarray(g.get_cell_data(s, "f", ids), np.float64)
    fT = np.asarray(g.get_cell_data(out, "f", ids), np.float64)

    adv = Advection(g, dtype=np.float64, use_pallas=False,
                    allow_boxed=False)
    for b in (0, 3, 7):
        sa = adv.initialize_state()
        sa = adv.set_cell_data(sa, "density", ids, f0[:, b])
        for d, name in enumerate(("vx", "vy", "vz")):
            sa = adv.set_cell_data(
                sa, name, ids, np.full(len(ids), vl.v_bins[b, d])
            )
        sa = g.update_copies_of_remote_neighbors(sa)
        for _ in range(steps):
            sa = adv.step(sa, dt)
        want = np.asarray(g.get_cell_data(sa, "density", ids), np.float64)
        np.testing.assert_allclose(fT[:, b], want, rtol=1e-12, atol=1e-15)


def test_refined_open_boundaries_outflow():
    """Open boundaries on the general/AMR path are vacuum-inflow /
    free-outflow like the dense path — not silent zero-flux walls:
    phase-space density must LEAVE the box monotonically."""
    g = (
        Grid()
        .set_initial_length((6, 6, 6))
        .set_neighborhood_length(0)
        .set_periodic(False, False, False)
        .set_maximum_refinement_level(1)
        .set_geometry(
            CartesianGeometry,
            start=(0.0, 0.0, 0.0),
            level_0_cell_length=(1.0 / 6,) * 3,
        )
        .initialize(mesh=make_mesh(n_devices=8))
    )
    ids = g.get_cells()
    c = g.geometry.get_center(ids)
    r = np.linalg.norm(c - 0.5, axis=1)
    for cid in ids[r < 0.3]:
        g.refine_completely(int(cid))
    g.stop_refining()
    vl = Vlasov(g, nv=3, dtype=np.float64)
    assert vl.info is None
    s = vl.initialize_state()
    dt = 0.5 * vl.max_time_step()
    masses = [vl.total_mass(s)]
    for _ in range(6):
        s = vl.run(s, 10, dt)
        masses.append(vl.total_mass(s))
    assert all(m1 < m0 for m0, m1 in zip(masses, masses[1:])), masses
    assert masses[-1] < 0.9 * masses[0], "mass must actually drain"
    assert (np.asarray(s["f"]) >= -1e-12).all()


def test_general_cfl_bound_is_unsplit_and_stable():
    """max_time_step on the general path uses the unsplit donor-cell
    bound (sum over dimensions), tighter than the split dense bound —
    and running AT that bound stays stable."""
    g = _refined_grid(1)
    vl = Vlasov(g, nv=3, dtype=np.float64)
    lmin = float(g.geometry.get_length(g.get_cells()).min())
    vmax = float(np.abs(vl.v_bins).max())
    split_bound = lmin / vmax
    dt_max = vl.max_time_step()
    assert dt_max < split_bound  # strictly tighter (3 active dims)
    s = vl.initialize_state()
    m0 = vl.total_mass(s)
    s = vl.run(s, 30, 0.99 * dt_max)
    f = np.asarray(s["f"], np.float64)
    assert np.isfinite(f).all()
    assert (f >= -1e-10).all(), "negative density = instability"
    assert vl.total_mass(s) == pytest.approx(m0, rel=1e-12)


def test_refined_grid_mass_conserved_and_device_invariant():
    outs = {}
    for n_dev in (1, 8):
        g = _refined_grid(n_dev)
        vl = Vlasov(g, nv=3, dtype=np.float64)
        s = vl.initialize_state()
        m0 = vl.total_mass(s)
        dt = 0.3 * vl.max_time_step()
        s = vl.run(s, 10, dt)
        assert vl.total_mass(s) == pytest.approx(m0, rel=1e-12)
        ids = np.sort(g.leaves.cells)
        outs[n_dev] = np.asarray(g.get_cell_data(s, "f", ids), np.float64)
    np.testing.assert_allclose(outs[1], outs[8], rtol=1e-12, atol=1e-15)


def test_mass_conservation():
    g = make()
    vl = Vlasov(g, nv=4, dtype=np.float64)
    state = vl.initialize_state()
    m0 = vl.total_mass(state)
    dt = 0.3 * vl.max_time_step()
    state = vl.run(state, 20, dt)
    assert vl.total_mass(state) == pytest.approx(m0, rel=1e-12)
    f = np.asarray(state["f"])
    assert (f >= -1e-12).all()


def test_single_bin_translates():
    """With all mass in one velocity bin, the density hump translates
    rigidly at that bin's velocity."""
    g = make(n=16, nz=8, n_dev=8)
    vl = Vlasov(g, nv=2, v_max=0.5, dtype=np.float64)
    state = vl.initialize_state()
    # put all mass in the bin with velocity (+0.25, +0.25, +0.25)
    vbin = np.argmin(np.abs(vl.v_bins - 0.25).sum(axis=1))
    f = np.array(state["f"])
    dens = f.sum(-1)
    f[:] = 0
    f[..., vbin] = dens
    import jax, jax.numpy as jnp
    from dccrg_tpu.parallel.mesh import shard_spec

    state = {"f": jax.device_put(jnp.asarray(f), shard_spec(g.mesh, 5))}
    peak0 = _density_peak(g, vl, state)
    dt = 0.25 * vl.max_time_step()
    steps = int(round(0.4 / dt))
    state = vl.run(state, steps, dt)
    peak1 = _density_peak(g, vl, state)
    expect = peak0 + 0.25 * steps * dt
    # upwind diffusion smears the hump; the peak still tracks the bin
    # velocity to within a cell or two
    np.testing.assert_allclose(peak1, expect, atol=0.15)
    # and mass stays exact
    assert vl.total_mass(state) == pytest.approx(
        float(dens.sum() * np.prod(g.geometry.get_level_0_cell_length())), rel=1e-12
    )


def _density_peak(g, vl, state):
    dens = vl.density(state)
    info = vl.info
    cells = g.get_cells()
    centers = g.geometry.get_center(cells)
    lin = (cells - np.uint64(1)).astype(np.int64)
    x = lin % info.nx
    y = (lin // info.nx) % info.ny
    z = lin // (info.nx * info.ny)
    w = dens[z // info.nz_local, z % info.nz_local, y, x]
    return centers[np.argmax(w)]


def test_open_boundaries_outflow():
    """Non-periodic dimensions are vacuum-inflow/free-outflow: mass leaves
    the box monotonically and never goes negative (grid.topology is
    honored, not assumed periodic)."""
    g = make(periodic=(False, False, False))
    vl = Vlasov(g, nv=4, dtype=np.float64)
    state = vl.initialize_state()
    dt = 0.3 * vl.max_time_step()
    masses = [vl.total_mass(state)]
    for _ in range(5):
        state = vl.run(state, 5, dt)
        masses.append(vl.total_mass(state))
    assert all(m1 < m0 for m0, m1 in zip(masses, masses[1:]))
    assert (np.asarray(state["f"]) >= -1e-12).all()


def test_mixed_periodicity_device_invariance():
    """Open-z boundary rides the slab ring with the wrap plane zeroed on
    the edge devices only — result must not depend on the device count."""
    res = []
    for n_dev in (1, 8):
        g = make(n_dev=n_dev, periodic=(True, True, False))
        vl = Vlasov(g, nv=3, dtype=np.float64)
        state = vl.initialize_state()
        dt = 0.3 * vl.max_time_step()
        state = vl.run(state, 10, dt)
        res.append(vl.density(state).reshape(-1, vl.info.ny, vl.info.nx))
    np.testing.assert_allclose(res[0], res[1], rtol=1e-12, atol=1e-15)


def test_device_count_invariance():
    res = []
    for n_dev in (1, 8):
        g = make(n_dev=n_dev)
        vl = Vlasov(g, nv=3, dtype=np.float64)
        state = vl.initialize_state()
        dt = 0.3 * vl.max_time_step()
        state = vl.run(state, 10, dt)
        res.append(vl.density(state).reshape(-1, vl.info.ny, vl.info.nx))
    np.testing.assert_allclose(res[0], res[1], rtol=1e-12, atol=1e-15)


def _jax_rounds_bit_identical() -> bool:
    """jax >= 0.5 Pallas interpret mode reproduces the XLA body bit for
    bit; the 0.4.x interpreter lowers a few ops through different
    float32 association and lands within a few ULP instead (ROADMAP jax
    version pin item)."""
    import jax

    return tuple(int(p) for p in jax.__version__.split(".")[:2]) >= (0, 5)


def _assert_fused_matches(a, b):
    """Bit-identity on current jax; a tight ULP envelope on old jax.

    The tolerance is deliberately ULP-denominated (4 ULP of the larger
    magnitude, elementwise) rather than a relative epsilon: the only
    licensed difference is final-rounding association, and anything
    beyond a few ULP is a real kernel bug that a rtol would hide on
    small values."""
    a = np.asarray(a, np.float32)
    b = np.asarray(b, np.float32)
    if _jax_rounds_bit_identical():
        assert np.array_equal(a, b), np.abs(a - b).max()
        return
    ulp = np.spacing(np.maximum(np.abs(a), np.abs(b)))
    bad = np.abs(a - b) > 4 * ulp
    assert not bad.any(), (
        f"{int(bad.sum())} elements beyond 4 ULP; max diff "
        f"{np.abs(a - b).max()} at magnitude "
        f"{np.maximum(np.abs(a), np.abs(b))[bad].max()}"
    )


@pytest.mark.parametrize("n_dev,nz", [(1, 8), (2, 8), (1, 16), (2, 32)])
@pytest.mark.parametrize(
    "periodic",
    [(True, True, True), (True, False, False), (False, False, False)],
)
def test_fused_step_matches_xla(n_dev, nz, periodic):
    """The blocked fused kernel (one HBM pass, halo planes re-split in
    VMEM) matches the XLA three-split body — bit-identical on current
    jax, within 4 ULP under the 0.4.x Pallas interpreter — including
    multi-block devices (nzl > block: interior strided halo rows and the
    cross-block zi splice) and open boundaries on every axis."""
    g = make(n=8, nz=nz, n_dev=n_dev, periodic=periodic)
    fast = Vlasov(g, nv=4, dtype=np.float32, use_pallas="interpret")
    slow = Vlasov(g, nv=4, dtype=np.float32, use_pallas=False)
    assert fast._fused_block > 0
    nzl = nz // (n_dev or 1)
    if nz >= 16:
        assert nzl > fast._fused_block, "must exercise the m>1 path"
    assert slow._fused_block == 0
    s = fast.initialize_state()
    dt = np.float32(0.4 * fast.max_time_step())
    a = np.asarray(fast.run(s, 5, dt)["f"])
    b = np.asarray(slow.run(s, 5, dt)["f"])
    _assert_fused_matches(a, b)
