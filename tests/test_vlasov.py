"""Vlasov (velocity-block-per-cell) stretch workload tests."""
import numpy as np
import pytest

from dccrg_tpu import CartesianGeometry, Grid, make_mesh
from dccrg_tpu.models.vlasov import Vlasov


def make(n=8, nz=8, n_dev=None, periodic=(True, True, True)):
    return (
        Grid()
        .set_initial_length((n, n, nz))
        .set_neighborhood_length(0)
        .set_periodic(*periodic)
        .set_geometry(
            CartesianGeometry,
            start=(0.0, 0.0, 0.0),
            level_0_cell_length=(1.0 / n, 1.0 / n, 1.0 / nz),
        )
        .initialize(mesh=make_mesh(n_devices=n_dev))
    )


def test_requires_dense():
    g = (
        Grid().set_initial_length((3, 3, 3)).set_neighborhood_length(0)
        .initialize(mesh=make_mesh(n_devices=8))
    )
    with pytest.raises(ValueError, match="dense"):
        Vlasov(g)


def test_mass_conservation():
    g = make()
    vl = Vlasov(g, nv=4, dtype=np.float64)
    state = vl.initialize_state()
    m0 = vl.total_mass(state)
    dt = 0.3 * vl.max_time_step()
    state = vl.run(state, 20, dt)
    assert vl.total_mass(state) == pytest.approx(m0, rel=1e-12)
    f = np.asarray(state["f"])
    assert (f >= -1e-12).all()


def test_single_bin_translates():
    """With all mass in one velocity bin, the density hump translates
    rigidly at that bin's velocity."""
    g = make(n=16, nz=8, n_dev=8)
    vl = Vlasov(g, nv=2, v_max=0.5, dtype=np.float64)
    state = vl.initialize_state()
    # put all mass in the bin with velocity (+0.25, +0.25, +0.25)
    vbin = np.argmin(np.abs(vl.v_bins - 0.25).sum(axis=1))
    f = np.array(state["f"])
    dens = f.sum(-1)
    f[:] = 0
    f[..., vbin] = dens
    import jax, jax.numpy as jnp
    from dccrg_tpu.parallel.mesh import shard_spec

    state = {"f": jax.device_put(jnp.asarray(f), shard_spec(g.mesh, 5))}
    peak0 = _density_peak(g, vl, state)
    dt = 0.25 * vl.max_time_step()
    steps = int(round(0.4 / dt))
    state = vl.run(state, steps, dt)
    peak1 = _density_peak(g, vl, state)
    expect = peak0 + 0.25 * steps * dt
    # upwind diffusion smears the hump; the peak still tracks the bin
    # velocity to within a cell or two
    np.testing.assert_allclose(peak1, expect, atol=0.15)
    # and mass stays exact
    assert vl.total_mass(state) == pytest.approx(
        float(dens.sum() * np.prod(g.geometry.get_level_0_cell_length())), rel=1e-12
    )


def _density_peak(g, vl, state):
    dens = vl.density(state)
    info = vl.info
    cells = g.get_cells()
    centers = g.geometry.get_center(cells)
    lin = (cells - np.uint64(1)).astype(np.int64)
    x = lin % info.nx
    y = (lin // info.nx) % info.ny
    z = lin // (info.nx * info.ny)
    w = dens[z // info.nz_local, z % info.nz_local, y, x]
    return centers[np.argmax(w)]


def test_open_boundaries_outflow():
    """Non-periodic dimensions are vacuum-inflow/free-outflow: mass leaves
    the box monotonically and never goes negative (grid.topology is
    honored, not assumed periodic)."""
    g = make(periodic=(False, False, False))
    vl = Vlasov(g, nv=4, dtype=np.float64)
    state = vl.initialize_state()
    dt = 0.3 * vl.max_time_step()
    masses = [vl.total_mass(state)]
    for _ in range(5):
        state = vl.run(state, 5, dt)
        masses.append(vl.total_mass(state))
    assert all(m1 < m0 for m0, m1 in zip(masses, masses[1:]))
    assert (np.asarray(state["f"]) >= -1e-12).all()


def test_mixed_periodicity_device_invariance():
    """Open-z boundary rides the slab ring with the wrap plane zeroed on
    the edge devices only — result must not depend on the device count."""
    res = []
    for n_dev in (1, 8):
        g = make(n_dev=n_dev, periodic=(True, True, False))
        vl = Vlasov(g, nv=3, dtype=np.float64)
        state = vl.initialize_state()
        dt = 0.3 * vl.max_time_step()
        state = vl.run(state, 10, dt)
        res.append(vl.density(state).reshape(-1, vl.info.ny, vl.info.nx))
    np.testing.assert_allclose(res[0], res[1], rtol=1e-12, atol=1e-15)


def test_device_count_invariance():
    res = []
    for n_dev in (1, 8):
        g = make(n_dev=n_dev)
        vl = Vlasov(g, nv=3, dtype=np.float64)
        state = vl.initialize_state()
        dt = 0.3 * vl.max_time_step()
        state = vl.run(state, 10, dt)
        res.append(vl.density(state).reshape(-1, vl.info.ny, vl.info.nx))
    np.testing.assert_allclose(res[0], res[1], rtol=1e-12, atol=1e-15)


@pytest.mark.parametrize("n_dev,nz", [(1, 8), (2, 8), (1, 16), (2, 32)])
@pytest.mark.parametrize(
    "periodic",
    [(True, True, True), (True, False, False), (False, False, False)],
)
def test_fused_step_matches_xla(n_dev, nz, periodic):
    """The blocked fused kernel (one HBM pass, halo planes re-split in
    VMEM) is bit-identical to the XLA three-split body — including
    multi-block devices (nzl > block: interior strided halo rows and the
    cross-block zi splice) and open boundaries on every axis."""
    g = make(n=8, nz=nz, n_dev=n_dev, periodic=periodic)
    fast = Vlasov(g, nv=4, dtype=np.float32, use_pallas="interpret")
    slow = Vlasov(g, nv=4, dtype=np.float32, use_pallas=False)
    assert fast._fused_block > 0
    nzl = nz // (n_dev or 1)
    if nz >= 16:
        assert nzl > fast._fused_block, "must exercise the m>1 path"
    assert slow._fused_block == 0
    s = fast.initialize_state()
    dt = np.float32(0.4 * fast.max_time_step())
    a = np.asarray(fast.run(s, 5, dt)["f"])
    b = np.asarray(slow.run(s, 5, dt)["f"])
    assert np.array_equal(a, b), np.abs(a - b).max()
