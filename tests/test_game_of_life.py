"""Game-of-life end-to-end tests, mirroring the reference's blinker
verification (examples/simple_game_of_life.cpp:122-158) and the
device-count-invariance expectation of its test suite."""
import numpy as np
import pytest

from dccrg_tpu import Grid, make_mesh
from dccrg_tpu.models import GameOfLife


def make_gol(n_dev=None):
    g = (
        Grid()
        .set_initial_length((10, 10, 1))
        .set_maximum_refinement_level(0)
        .set_neighborhood_length(1)
        .set_load_balancing_method("RCB")
        .initialize(mesh=make_mesh(n_devices=n_dev))
    )
    return g, GameOfLife(g)


def test_blinker_oscillates():
    grid, gol = make_gol()
    # blinker at cells 54, 55, 56 (a horizontal row in the 10x10 grid)
    state = gol.new_state(alive_cells=[54, 55, 56])
    for turn in range(1, 21):
        state = gol.step(state)
        alive = set(gol.alive_cells(state).tolist())
        assert 55 in alive, f"turn {turn}"
        if turn % 2 == 1:  # after odd number of steps: vertical
            assert alive == {45, 55, 65}, f"turn {turn}"
        else:  # back to horizontal
            assert alive == {54, 55, 56}, f"turn {turn}"


def test_block_still_life():
    grid, gol = make_gol()
    block = [44, 45, 54, 55]
    state = gol.new_state(alive_cells=block)
    state = gol.run(state, 5)
    assert set(gol.alive_cells(state).tolist()) == set(block)


def test_glider_moves():
    grid, gol = make_gol()
    # glider in the upper-left corner: cells (x,y): (1,0),(2,1),(0,2),(1,2),(2,2)
    ids = [1 + 1 + 0 * 10, 1 + 2 + 1 * 10, 1 + 0 + 2 * 10, 1 + 1 + 2 * 10, 1 + 2 + 2 * 10]
    state = gol.new_state(alive_cells=ids)
    state = gol.run(state, 4)
    # after 4 steps a glider translates by (1, 1)
    expect = {i + 1 + 1 * 10 for i in ids}
    assert set(gol.alive_cells(state).tolist()) == expect


def test_device_count_invariance():
    """Rank-count-invariant results, the reference suite's core property."""
    finals = []
    rng = np.random.default_rng(11)
    alive0 = (rng.random(100) < 0.35).nonzero()[0] + 1
    for n_dev in (1, 3, 8):
        grid, gol = make_gol(n_dev=n_dev)
        state = gol.new_state(alive_cells=alive0.astype(np.uint64))
        state = gol.run(state, 10)
        finals.append(frozenset(gol.alive_cells(state).tolist()))
    assert finals[0] == finals[1] == finals[2]


def test_periodic_gol_wraps():
    g = (
        Grid()
        .set_initial_length((8, 8, 1))
        .set_periodic(True, True, False)
        .set_neighborhood_length(1)
        .initialize(mesh=make_mesh())
    )
    gol = GameOfLife(g)
    # blinker crossing the x boundary: row y=3, cells x = 7, 0, 1
    ids = [1 + 7 + 3 * 8, 1 + 0 + 3 * 8, 1 + 1 + 3 * 8]
    state = gol.new_state(alive_cells=ids)
    state = gol.step(state)
    alive = set(gol.alive_cells(state).tolist())
    # vertical blinker at x=0: y = 2,3,4
    assert alive == {1 + 0 + 2 * 8, 1 + 0 + 3 * 8, 1 + 0 + 4 * 8}
    state = gol.step(state)
    assert set(gol.alive_cells(state).tolist()) == set(ids)


@pytest.mark.parametrize(
    "n_dev,use_pallas", [(1, "interpret"), (1, False), (2, True), (5, True)]
)
@pytest.mark.parametrize(
    "periodic", [(False, False, False), (True, True, False)]
)
def test_dense2d_matches_general(n_dev, use_pallas, periodic):
    """The dense y-slab fast path (whole-run device loop, 8-neighbor
    count as shifted bands) produces identical alive sets and neighbor
    counts to the general gather path, at any device count — including
    the single-device fused Pallas kernel via the interpreter and the
    XLA dense loop it falls back to."""
    g = (
        Grid()
        .set_initial_length((10, 10, 1))
        .set_maximum_refinement_level(0)
        .set_neighborhood_length(1)
        .set_periodic(*periodic)
        .initialize(mesh=make_mesh(n_devices=n_dev))
    )
    rng = np.random.default_rng(0)
    cells = g.get_cells()
    alive0 = cells[rng.random(len(cells)) < 0.35]
    fast = GameOfLife(g, use_pallas=use_pallas)
    slow = GameOfLife(g, allow_dense=False)
    assert fast._dense_run is not None
    assert slow._dense_run is None
    s = fast.run(fast.new_state(alive_cells=alive0), 13)
    r = slow.run(slow.new_state(alive_cells=alive0), 13)
    assert set(fast.alive_cells(s).tolist()) == set(slow.alive_cells(r).tolist())
    np.testing.assert_array_equal(
        g.get_cell_data(s, "live_neighbor_count", cells),
        g.get_cell_data(r, "live_neighbor_count", cells),
    )


def test_gol_padded_kernel_bit_identical():
    """Tile-padding (explicit wrap-halo rows/columns) reproduces the
    unpadded fused kernel bit for bit on both axes, all periodicities."""
    import jax.numpy as jnp

    from dccrg_tpu.ops.gol_kernel import make_gol_run

    rng = np.random.default_rng(3)
    ny, nx = 12, 20
    a = jnp.asarray((rng.random((ny, nx)) < 0.35).astype(np.float32))
    for px, py in [(True, True), (False, False), (True, False)]:
        k0 = make_gol_run(ny, nx, px, py, interpret=True)
        for ny_pad, nx_pad in [(16, None), (None, 24), (16, 24)]:
            kp = make_gol_run(ny, nx, px, py, ny_pad=ny_pad, nx_pad=nx_pad,
                              interpret=True)
            for turns in (4, 7):
                o0, c0 = k0(a, turns)
                op, cp = kp(a, turns)
                assert np.array_equal(np.asarray(o0), np.asarray(op)), (
                    px, py, ny_pad, nx_pad, turns)
                assert np.array_equal(np.asarray(c0), np.asarray(cp))


def test_gol_model_y_padding_engages():
    """A 30x12 board pads y 12->16 through the model dispatch and still
    matches the general gather path exactly."""
    g = (
        Grid()
        .set_initial_length((30, 12, 1))
        .set_neighborhood_length(1)
        .set_periodic(True, True, False)
        .initialize(mesh=make_mesh(n_devices=1))
    )
    rng = np.random.default_rng(1)
    cells = g.get_cells()
    alive0 = cells[rng.random(len(cells)) < 0.35]
    fast = GameOfLife(g, use_pallas="interpret")
    slow = GameOfLife(g, allow_dense=False)
    assert fast._dense_run is not None
    s = fast.run(fast.new_state(alive_cells=alive0), 9)
    r = slow.run(slow.new_state(alive_cells=alive0), 9)
    assert set(fast.alive_cells(s).tolist()) == set(
        slow.alive_cells(r).tolist())
