"""Bench outage-fallback promotion guards.

When the TPU tunnel is down at bench time, bench.py promotes the
incremental battery's persisted headline (tools/onchip_r3.json) into the
record's headline value ONLY when the measurement is trustworthy:
TPU-platform, stamped inside the current round's window, numerically
positive.  These tests drive `_emit_fallback` / `_round_start` and the
battery's own `record` guards directly — the mirror of the reference's
measurement protocol, where a benchmark log always states what was
actually measured (reference tests/scalability/run_tests.py's sweep
logs never substitute an old rate for a missing run).
"""
import contextlib
import io
import json
import time

import pytest


@pytest.fixture()
def sandbox(tmp_path, monkeypatch):
    """bench with ROOT pointed at a tmp dir and the slow evidence
    collectors stubbed (they are irrelevant to the promotion logic)."""
    import bench

    (tmp_path / "tools").mkdir()
    monkeypatch.setattr(bench, "ROOT", tmp_path)
    monkeypatch.setattr(bench, "measure_multidev_cpu",
                        lambda: {"stub": True})
    monkeypatch.setattr(bench, "measure_scalability", lambda: {"stub": True})
    monkeypatch.setattr(bench, "measure_cpu_baseline", lambda: 6.5e7)
    # the shape-stability churn, halo-overlap, elastic and ensemble
    # probes spawn real jax children — stubbed out like the other slow
    # evidence collectors
    monkeypatch.setattr(bench, "_attach_epoch_churn", lambda record: None)
    monkeypatch.setattr(bench, "_attach_halo_overlap", lambda record: None)
    monkeypatch.setattr(bench, "_attach_elastic", lambda record: None)
    monkeypatch.setattr(bench, "_attach_ensemble", lambda record: None)
    return bench, tmp_path


def _run_fallback(bench, tmp_path):
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        bench._emit_fallback({"probe": "test"})
    line = buf.getvalue().strip().splitlines()[-1]
    compact = json.loads(line)
    detail = json.loads(
        (tmp_path / "BENCH_DETAIL.json").read_text())["detail"]
    assert len(line) < 1000  # driver tail-capture guarantee
    return compact, detail


def _iso(epoch):
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime(epoch))


def _write_battery(tmp_path, headline):
    (tmp_path / "tools" / "onchip_r3.json").write_text(
        json.dumps({"headline": headline}))


def test_fresh_tpu_headline_promoted(sandbox):
    bench, tmp_path = sandbox
    _write_battery(tmp_path, {
        "updates_per_s_per_chip": 5.2e10, "platform": "tpu",
        "measured_at": _iso(time.time() - 600)})
    compact, detail = _run_fallback(bench, tmp_path)
    assert compact["value"] == 5.2e10
    assert compact["vs_baseline"] == pytest.approx(5.2e10 / 6.5e7, rel=1e-3)
    assert "on-chip battery measurement" in detail["value_source"]
    assert "battery measurement" in detail["error"]


def test_missing_battery_keeps_error_record(sandbox):
    bench, tmp_path = sandbox
    compact, detail = _run_fallback(bench, tmp_path)
    assert compact["value"] == -1.0 and compact["vs_baseline"] == -1.0
    assert detail["value_source"] is None
    assert "no accelerator number" in detail["error"]
    assert "no battery" in detail["last_measured_this_round"]["vintage"]


def test_cpu_platform_record_never_promoted_or_attached(sandbox):
    bench, tmp_path = sandbox
    (tmp_path / "tools" / "onchip_r3.json").write_text(json.dumps({
        "headline": {"updates_per_s_per_chip": 5.2e10, "platform": "cpu",
                     "measured_at": _iso(time.time())},
        "gol": {"updates_per_s": 1e9, "platform": "tpu",
                "measured_at": _iso(time.time())},
    }))
    compact, detail = _run_fallback(bench, tmp_path)
    assert compact["value"] == -1.0
    battery = detail["onchip_battery"]
    assert "headline" not in battery  # host fallback is not evidence
    assert "gol" in battery  # real measurements still attach


def test_round_window_beats_fixed_24h_cap(sandbox):
    bench, tmp_path = sandbox
    now = time.time()
    round_start = now - 30 * 3600  # rounds can run past 24h
    (tmp_path / "PROGRESS.jsonl").write_text(
        json.dumps({"ts": round_start + 100, "round": 5, "wall_s": 100})
        + "\n"
        + json.dumps({"ts": round_start + 20 * 3600, "round": 5,
                      "wall_s": 200}) + "\n")
    assert bench._round_start() == pytest.approx(round_start, abs=1.0)

    # 25h old but inside the 30h round: promoted
    _write_battery(tmp_path, {
        "updates_per_s_per_chip": 5.2e10, "platform": "tpu",
        "measured_at": _iso(now - 25 * 3600)})
    compact, _ = _run_fallback(bench, tmp_path)
    assert compact["value"] == 5.2e10

    # before the round began: stale, rejected
    _write_battery(tmp_path, {
        "updates_per_s_per_chip": 5.2e10, "platform": "tpu",
        "measured_at": _iso(round_start - 2 * 3600)})
    compact, detail = _run_fallback(bench, tmp_path)
    assert compact["value"] == -1.0
    assert detail["value_source"] is None


def test_no_progress_file_falls_back_to_24h(sandbox):
    bench, tmp_path = sandbox
    assert bench._round_start() is None
    _write_battery(tmp_path, {
        "updates_per_s_per_chip": 5.2e10, "platform": "tpu",
        "measured_at": _iso(time.time() - 3600)})
    compact, _ = _run_fallback(bench, tmp_path)
    assert compact["value"] == 5.2e10
    _write_battery(tmp_path, {
        "updates_per_s_per_chip": 5.2e10, "platform": "tpu",
        "measured_at": _iso(time.time() - 30 * 3600)})
    compact, _ = _run_fallback(bench, tmp_path)
    assert compact["value"] == -1.0


def test_partial_record_recovered_on_mid_bench_timeout(sandbox, monkeypatch):
    """A tunnel drop mid-real-bench hangs the child until the parent's
    timeout; the child's cumulative record lines mean the parent must
    report the live numbers measured before the hang, not the outage
    fallback."""
    import subprocess
    bench, tmp_path = sandbox

    partial = json.dumps({
        "metric": "3d_advection_cell_updates_per_sec_per_chip",
        "value": 5.3e10, "unit": "cell-updates/s/chip",
        "vs_baseline": 810.0,
        "detail": {"partial": {"measured": ["headline", "poisson"],
                               "missing": ["large"]}},
    })
    # the hang cut the NEXT record mid-print: the truncated line must
    # not shadow the complete one above it
    truncated = partial[: len(partial) // 2]

    calls = []

    def fake_run(*a, **k):
        calls.append(a)
        # call 1: the telemetry probe; call 2: the tunnel probe — both
        # report success so the real child (call 3) runs
        if len(calls) <= 2:
            class R:
                returncode = 0
                stderr = ""
            return R()
        raise subprocess.TimeoutExpired(
            cmd="bench --_real", timeout=1,
            output=("warmup noise\n" + partial + "\n"
                    + truncated).encode(),
            stderr=b"tunnel hung")

    monkeypatch.setattr(bench.subprocess, "run", fake_run)
    monkeypatch.setattr(bench.sys, "argv", ["bench.py"])
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        bench.main()
    # telemetry probe + tunnel probe + real child + cost probe (the
    # cost attach also times out here; its failure never blocks the
    # recovered record)
    assert len(calls) == 4
    line = buf.getvalue().strip().splitlines()[-1]
    d = json.loads(line)
    assert d["value"] == 5.3e10 and d["vs_baseline"] == 810.0
    # the compact line must not read as a complete battery
    assert d["detail"]["partial_missing"] == ["large"]
    assert d["detail"]["recovered"] is True
    det = json.loads((tmp_path / "BENCH_DETAIL.json").read_text())["detail"]
    assert det["partial"]["missing"] == ["large"]
    assert "recovery_diagnostics" in det


def test_build_real_record_partial_flag(sandbox):
    bench, tmp_path = sandbox
    tpu = {"updates_per_s_per_chip": 5.2e10, "platform": "tpu",
           "device_kind": "TPU v5 lite", "n_devices": 1, "halo_GBps": 0.0,
           "best_updates_per_s_per_chip": 5.4e10, "times": [0.1]}
    rec = bench._build_real_record(tpu, {}, partial=True)
    assert rec["detail"]["partial"]["measured"] == ["headline"]
    assert "poisson" in rec["detail"]["partial"]["missing"]
    rec = bench._build_real_record(tpu, {}, partial=False)
    assert "partial" not in rec["detail"]
    assert rec["value"] == 5.2e10 and rec["vs_baseline"] > 0
    json.dumps(rec)  # must be serializable


def test_battery_record_guards(tmp_path, monkeypatch):
    """onchip_r3.record: a failed or host-fallback child never clobbers
    persisted on-chip evidence; the sweep map stays stamp-free so its
    per-shape completeness/merge logic keeps working."""
    import pathlib
    monkeypatch.syspath_prepend(
        str(pathlib.Path(__file__).resolve().parents[1] / "tools"))
    import onchip_r3

    monkeypatch.setattr(onchip_r3, "OUT", tmp_path / "battery.json")
    (tmp_path / "battery.json").write_text("{}")
    onchip_r3.record("headline", {"updates_per_s_per_chip": 5e10,
                                  "platform": "tpu"})
    saved = json.loads((tmp_path / "battery.json").read_text())["headline"]
    assert "measured_at" in saved  # vintage stamp applied

    for bad in ({"error": "timed out"},
                {"updates_per_s_per_chip": 1e3, "platform": "cpu"}):
        onchip_r3.record("headline", bad)
        saved = json.loads(
            (tmp_path / "battery.json").read_text())["headline"]
        assert saved["updates_per_s_per_chip"] == 5e10

    key = onchip_r3.SWEEP_KEY
    onchip_r3.record(key, {"96x96x96": 8.1})
    sweep = json.loads((tmp_path / "battery.json").read_text())[key]
    assert "measured_at" not in sweep
    assert onchip_r3.done(key)
    # partial later pass: measured shapes survive error strings
    onchip_r3.record(key, {"96x96x96": "tunnel dropped",
                           "128x128x128": 9.2})
    sweep = json.loads((tmp_path / "battery.json").read_text())[key]
    assert sweep["96x96x96"] == 8.1 and sweep["128x128x128"] == 9.2
