"""Boxed (per-level dense) AMR advection path vs the general gather path.

The boxed layout (``parallel/boxed.py``) must reproduce the general path's
update exactly up to floating-point association order: same face set, same
upwind choices, same v_face interpolation (reference semantics
``tests/advection/solve.hpp:129-260``).  In f64 the two paths agree to
~1e-13 over tens of steps; mass conservation is exact to roundoff.
"""
import numpy as np
import pytest

from dccrg_tpu import CartesianGeometry, Grid, make_mesh
from dccrg_tpu.geometry.stretched import StretchedCartesianGeometry
from dccrg_tpu.models import Advection


def _grid(n=8, maxref=1, periodic=(True, True, True), n_devices=1,
          refine_center=(0.3, 0.5, 0.5), radii=(0.25,)):
    g = (
        Grid()
        .set_initial_length((n, n, n))
        .set_neighborhood_length(0)
        .set_periodic(*periodic)
        .set_maximum_refinement_level(maxref)
        .set_geometry(
            CartesianGeometry,
            start=(0.0, 0.0, 0.0),
            level_0_cell_length=(1.0 / n, 1.0 / n, 1.0 / n),
        )
        .initialize(mesh=make_mesh(n_devices=n_devices))
    )
    for r_ref in radii:
        ids = g.get_cells()
        c = g.geometry.get_center(ids)
        r = np.linalg.norm(c - np.asarray(refine_center), axis=1)
        for cid in ids[r < r_ref]:
            g.refine_completely(int(cid))
        g.stop_refining()
    return g


def _compare(g, steps=8):
    adv = Advection(g, dtype=np.float64, allow_dense=False)
    assert adv.boxed is not None
    state = adv.initialize_state()
    dt = np.float64(0.4 * adv.max_time_step(state))
    flat = state
    for _ in range(steps):
        flat = adv._step(flat, dt)
    boxed = adv._boxed_run(state, steps, dt)
    local = np.asarray(adv.tables.local_mask)
    a = np.asarray(flat["density"])[local]
    b = np.asarray(boxed["density"])[local]
    np.testing.assert_allclose(b, a, rtol=1e-12, atol=1e-13)
    assert np.isclose(adv.total_mass(boxed), adv.total_mass(state), rtol=1e-12)
    return adv


def test_boxed_matches_flat_full_3d_velocity():
    # the stock rotating hump has vz == 0; exercise the z-axis kernel path
    # (axis map, z areas, z face masks, z cross-level faces) with a fully
    # 3-D divergence-free-ish velocity field
    g = _grid(n=8, maxref=1)
    adv = Advection(g, dtype=np.float64, allow_dense=False)
    assert adv.boxed is not None
    state = adv.initialize_state()
    cells = g.get_cells()
    c = g.geometry.get_center(cells)
    state = g.set_cell_data(state, "vx", cells, np.sin(2 * np.pi * c[:, 2]) + 0.1)
    state = g.set_cell_data(state, "vy", cells, np.cos(2 * np.pi * c[:, 0]) - 0.2)
    state = g.set_cell_data(state, "vz", cells, np.sin(2 * np.pi * c[:, 1]) + 0.3)
    state = adv._exchange(state)
    dt = np.float64(0.4 * adv.max_time_step(state))
    flat = state
    for _ in range(8):
        flat = adv._step(flat, dt)
    boxed = adv._boxed_run(state, 8, dt)
    local = np.asarray(adv.tables.local_mask)
    np.testing.assert_allclose(
        np.asarray(boxed["density"])[local],
        np.asarray(flat["density"])[local],
        rtol=1e-12,
        atol=1e-13,
    )
    assert np.isclose(adv.total_mass(boxed), adv.total_mass(state), rtol=1e-12)


def test_boxed_matches_flat_refined_periodic():
    adv = _compare(_grid(n=8, maxref=1))
    assert len(adv.boxed.pairs) == 1  # one adjacent level pair (1 | 0)


def test_boxed_matches_flat_wrap_corner():
    # refined region spanning the periodic corner: cross-level faces wrap
    # in every axis, exercising the wrapped upsample window and the
    # wrapped pooled-plane adds
    _compare(_grid(n=8, maxref=1, refine_center=(0.0, 0.0, 0.0), radii=(0.3,)),
             steps=12)


def test_boxed_matches_flat_wrap_high_edge():
    # refined region at the HIGH domain corner: the last pooled row wraps
    # to coarse coordinate 0, outside pool_route's main in-domain block,
    # so it must be routed by its own single-row segment
    _compare(_grid(n=8, maxref=1, refine_center=(1.0, 1.0, 1.0), radii=(0.3,)),
             steps=12)


def test_boxed_matches_flat_refined_nonperiodic():
    _compare(_grid(n=8, maxref=1, periodic=(False, False, False)))


def test_boxed_matches_flat_two_levels():
    adv = _compare(_grid(n=8, maxref=2, radii=(0.3, 0.15)))
    levels = sorted(adv.boxed.boxes)
    assert levels == [0, 1, 2]
    assert sorted((p.fine_level, p.coarse_level) for p in adv.boxed.pairs) == [
        (1, 0),
        (2, 1),
    ]


def test_boxed_uniform_single_level():
    # uniform but refinable grid: one box covering the whole domain,
    # no interface groups, pure dense rolls
    g = _grid(n=6, maxref=1, radii=())
    adv = _compare(g)
    assert len(adv.boxed.pairs) == 0
    assert list(adv.boxed.boxes) == [0]


def test_boxed_run_equals_repeated_boxed_runs():
    g = _grid(n=8, maxref=1)
    adv = Advection(g, dtype=np.float64, allow_dense=False)
    state = adv.initialize_state()
    dt = np.float64(0.4 * adv.max_time_step(state))
    once = adv._boxed_run(state, 6, dt)
    twice = adv._boxed_run(adv._boxed_run(state, 3, dt), 3, dt)
    np.testing.assert_allclose(
        np.asarray(once["density"]), np.asarray(twice["density"]),
        rtol=1e-13, atol=1e-15,
    )


@pytest.mark.parametrize("n_devices", [2, 4, 8])
def test_boxed_multi_device_matches_flat(n_devices):
    # the z-slab boxed layout engages on any device count dividing nz;
    # every device prices the faces registered in its padded slab (cut and
    # periodic-seam faces included) and the result matches the general
    # gather path
    adv = _compare(_grid(n=8, maxref=1, n_devices=n_devices), steps=8)
    assert adv.boxed.n_devices == n_devices


def test_boxed_multi_device_wrap_corner():
    # refined region spanning the periodic corner across device cuts
    _compare(
        _grid(n=8, maxref=1, n_devices=4, refine_center=(0.0, 0.0, 0.0),
              radii=(0.3,)),
        steps=12,
    )


def test_boxed_multi_device_two_levels():
    adv = _compare(_grid(n=8, maxref=2, n_devices=2, radii=(0.3, 0.15)),
                   steps=8)
    assert sorted(adv.boxed.boxes) == [0, 1, 2]


def test_boxed_multi_device_matches_single_device():
    # same grid, 1 vs 4 devices: the boxed update is association-order
    # identical, so results agree to the last ulp
    outs = []
    for nd in (1, 4):
        g = _grid(n=8, maxref=1, n_devices=nd)
        adv = Advection(g, dtype=np.float64, allow_dense=False)
        assert adv.boxed is not None
        state = adv.initialize_state()
        out = adv._boxed_run(state, 10, np.float64(0.02))
        ids = np.sort(g.get_cells())
        outs.append(np.asarray(g.get_cell_data(out, "density", ids)))
    np.testing.assert_allclose(outs[0], outs[1], rtol=1e-14, atol=1e-16)


def test_boxed_disabled_non_slab_partition():
    # a non-z-slab ownership (RCB repartition) falls back to the gather path
    n = 8
    g = (
        Grid()
        .set_initial_length((n, n, n))
        .set_neighborhood_length(0)
        .set_periodic(True, True, True)
        .set_maximum_refinement_level(1)
        .set_load_balancing_method("RCB")
        .set_geometry(
            CartesianGeometry,
            start=(0.0, 0.0, 0.0),
            level_0_cell_length=(1.0 / n, 1.0 / n, 1.0 / n),
        )
        .initialize(mesh=make_mesh(n_devices=2))
    )
    ids = g.get_cells()
    c = g.geometry.get_center(ids)
    r = np.linalg.norm(c - np.array([0.3, 0.5, 0.5]), axis=1)
    for cid in ids[r < 0.25]:
        g.refine_completely(int(cid))
    g.stop_refining()
    g.balance_load()
    adv = Advection(g, dtype=np.float64, allow_dense=False)
    assert adv.boxed is None
    # ZSLAB rebalancing restores the slab ownership and the fast path
    g._lb_method = "ZSLAB"
    g.balance_load()
    adv = Advection(g, dtype=np.float64, allow_dense=False)
    assert adv.boxed is not None and adv.boxed.n_devices == 2


def test_boxed_disabled_stretched_geometry():
    n = 6
    g = (
        Grid()
        .set_initial_length((n, n, n))
        .set_neighborhood_length(0)
        .set_periodic(True, True, True)
        .set_geometry(
            StretchedCartesianGeometry,
            coordinates=[np.linspace(0.0, 1.0, n + 1) ** 1.3] * 3,
        )
        .initialize(mesh=make_mesh(n_devices=1))
    )
    adv = Advection(g, dtype=np.float64, allow_dense=False)
    assert adv.boxed is None


def test_boxed_used_by_run():
    g = _grid(n=8, maxref=1)
    adv = Advection(g, dtype=np.float64, allow_dense=False)
    state = adv.initialize_state()
    dt = np.float64(0.4 * adv.max_time_step(state))
    out_run = adv.run(state, 5, dt)
    out_boxed = adv._boxed_run(state, 5, dt)
    np.testing.assert_array_equal(
        np.asarray(out_run["density"]), np.asarray(out_boxed["density"])
    )


def test_boxed_refinement_across_periodic_seam():
    """Regression: a refined region CROSSING periodic boundaries (fine
    box covering the wrapped axes) must not price phantom cross-level
    fluxes — the z-ring wrap pad of the cross-face masks used to copy
    interior registrations onto the far ring row, which local mode's
    pooled wrap segments delivered into the opposite coarse plane."""
    import jax.numpy as jnp

    def dist_periodic(c, p):
        d = np.abs(c - p)
        d = np.minimum(d, 1 - d)
        return np.linalg.norm(d, axis=1)

    n = 8
    g = (
        Grid()
        .set_initial_length((n, n, n))
        .set_neighborhood_length(0)
        .set_periodic(True, True, True)
        .set_maximum_refinement_level(1)
        .set_geometry(
            CartesianGeometry,
            start=(0.0, 0.0, 0.0),
            level_0_cell_length=(1.0 / n,) * 3,
        )
        .initialize(mesh=make_mesh(n_devices=1))
    )
    ids = g.get_cells()
    c = g.geometry.get_center(ids)
    r = dist_periodic(c, np.zeros(3))     # ball at the corner: wraps all axes
    for cid in ids[r < 0.28]:
        g.refine_completely(int(cid))
    g.stop_refining()
    ids = g.get_cells()

    adv = Advection(g, dtype=np.float32, use_pallas=False)
    s0 = adv.initialize_state()
    rng = np.random.default_rng(0)
    cen = g.geometry.get_center(ids)
    s0 = adv.set_cell_data(
        s0, "density", ids, rng.uniform(1, 2, len(ids)).astype(np.float32)
    )
    s0 = adv.set_cell_data(
        s0, "vz", ids, (0.3 * np.sin(2 * np.pi * cen[:, 2])).astype(np.float32)
    )
    dt = np.float32(0.3 * adv.max_time_step(s0))
    b = adv._boxed_run(s0, jnp.asarray(3, jnp.int32), dt)
    st = s0
    for _ in range(3):
        st = adv.step(st, dt)
    np.testing.assert_allclose(
        np.asarray(adv.get_cell_data(b, "density", ids)),
        np.asarray(adv.get_cell_data(st, "density", ids)),
        rtol=3e-6, atol=1e-7,
    )


@pytest.mark.parametrize("n_dev", [2, 4])
def test_boxed_slab_refinement_across_periodic_seam(n_dev):
    """Slab mode prices wrap-adjacent refinement correctly too: a
    corner-centered refined ball (crossing every periodic boundary,
    including the z seam between the wrap-adjacent slabs) matches the
    general gather path.  Velocity ghosts must be refreshed after
    set_cell_data for the general path — the reference's own usage
    pattern (examples update copies after initialization)."""
    import jax.numpy as jnp

    def dist_periodic(c, p):
        d = np.abs(c - p)
        d = np.minimum(d, 1 - d)
        return np.linalg.norm(d, axis=1)

    n = 8
    g = (
        Grid()
        .set_initial_length((n, n, n))
        .set_neighborhood_length(0)
        .set_periodic(True, True, True)
        .set_maximum_refinement_level(1)
        .set_geometry(
            CartesianGeometry,
            start=(0.0, 0.0, 0.0),
            level_0_cell_length=(1.0 / n,) * 3,
        )
        .initialize(mesh=make_mesh(n_devices=n_dev))
    )
    ids = g.get_cells()
    c = g.geometry.get_center(ids)
    r = dist_periodic(c, np.zeros(3))
    for cid in ids[r < 0.28]:
        g.refine_completely(int(cid))
    g.stop_refining()
    ids = g.get_cells()

    adv = Advection(g, dtype=np.float32, use_pallas=False)
    assert adv.boxed is not None
    s0 = adv.initialize_state()
    rng = np.random.default_rng(0)
    cen = g.geometry.get_center(ids)
    s0 = adv.set_cell_data(
        s0, "density", ids, rng.uniform(1, 2, len(ids)).astype(np.float32)
    )
    s0 = adv.set_cell_data(
        s0, "vz", ids, (0.3 * np.sin(2 * np.pi * cen[:, 2])).astype(np.float32)
    )
    s0 = g.update_copies_of_remote_neighbors(s0)
    dt = np.float32(0.3 * adv.max_time_step(s0))
    b = adv._boxed_run(s0, jnp.asarray(3, jnp.int32), dt)
    st = s0
    for _ in range(3):
        st = adv.step(st, dt)
    np.testing.assert_allclose(
        np.asarray(adv.get_cell_data(b, "density", ids)),
        np.asarray(adv.get_cell_data(st, "density", ids)),
        rtol=3e-6, atol=1e-7,
    )


@pytest.mark.parametrize("seed", [1, 4, 7, 11])
def test_fuzz_paths_agree(seed):
    """Differential check on random refined grids (random periodicity,
    device count, velocities, scattered refinement): the boxed and flat
    paths must match the general gather path.  Seed 4 is the regression
    for the slab-mode wrap-seam cross-face rings (mode-dependent ring
    padding)."""
    import jax.numpy as jnp

    from dccrg_tpu.models import Advection as Adv

    rng = np.random.default_rng(seed)
    n = int(rng.choice([4, 6, 8]))
    n_dev = int(rng.choice([1, 2, 4]))
    periodic = tuple(bool(b) for b in rng.integers(0, 2, 3))
    g = (
        Grid()
        .set_initial_length((n, n, n))
        .set_neighborhood_length(0)
        .set_periodic(*periodic)
        .set_maximum_refinement_level(1)
        .set_geometry(
            CartesianGeometry,
            start=(0.0, 0.0, 0.0),
            level_0_cell_length=(1.0 / n,) * 3,
        )
        .initialize(mesh=make_mesh(n_devices=n_dev))
    )
    ids = g.get_cells()
    for cid in rng.choice(ids, size=max(1, int(0.3 * len(ids))),
                          replace=False):
        g.refine_completely(int(cid))
    g.stop_refining()
    ids = g.get_cells()
    if g.mapping.get_refinement_level(ids).max() == 0:
        pytest.skip("all refinement requests vetoed")

    adv = Adv(g, dtype=np.float32, use_pallas=False)
    flat = Adv(g, dtype=np.float32,
               use_pallas="interpret" if n_dev == 1 else True)
    s0 = adv.initialize_state()
    s0 = adv.set_cell_data(
        s0, "density", ids, rng.uniform(1, 2, len(ids)).astype(np.float32)
    )
    for f in ("vx", "vy", "vz"):
        s0 = adv.set_cell_data(
            s0, f, ids, rng.uniform(-0.3, 0.3, len(ids)).astype(np.float32)
        )
    s0 = g.update_copies_of_remote_neighbors(s0)
    dt = np.float32(0.3 * adv.max_time_step(s0))
    st = s0
    for _ in range(3):
        st = adv.step(st, dt)
    ref = np.asarray(adv.get_cell_data(st, "density", ids), np.float64)
    scale = np.abs(ref).max()
    if getattr(adv, "_boxed_run", None) is not None:
        b = adv._boxed_run(s0, jnp.asarray(3, jnp.int32), dt)
        rb = np.asarray(adv.get_cell_data(b, "density", ids), np.float64)
        assert np.abs(rb - ref).max() / scale < 5e-6
    if getattr(flat, "_flat_run", None) is not None:
        a = flat.run(s0, 3, dt)
        ra = np.asarray(flat.get_cell_data(a, "density", ids), np.float64)
        assert np.abs(ra - ref).max() / scale < 5e-6


@pytest.mark.parametrize("seed", [0, 5])
def test_fuzz_three_level_boxed(seed):
    """Three-level grids (two cross-level pairs in the boxed layout):
    random scattered refinement must match the general gather path."""
    import jax.numpy as jnp

    rng = np.random.default_rng(seed)
    n = int(rng.choice([4, 6]))
    n_dev = int(rng.choice([1, 2, 4]))
    periodic = tuple(bool(b) for b in rng.integers(0, 2, 3))
    g = (
        Grid()
        .set_initial_length((n, n, n))
        .set_neighborhood_length(0)
        .set_periodic(*periodic)
        .set_maximum_refinement_level(2)
        .set_geometry(
            CartesianGeometry,
            start=(0.0, 0.0, 0.0),
            level_0_cell_length=(1.0 / n,) * 3,
        )
        .initialize(mesh=make_mesh(n_devices=n_dev))
    )
    for frac in (0.3, 0.2):
        ids = g.get_cells()
        for cid in rng.choice(ids, size=max(1, int(frac * len(ids))),
                              replace=False):
            g.refine_completely(int(cid))
        g.stop_refining()
    ids = g.get_cells()
    if g.mapping.get_refinement_level(ids).max() < 2:
        pytest.skip("refinement did not reach level 2")
    adv = Advection(g, dtype=np.float32, use_pallas=False)
    if getattr(adv, "_boxed_run", None) is None:
        pytest.skip("boxed layout ineligible for this pattern")
    s0 = adv.initialize_state()
    s0 = adv.set_cell_data(
        s0, "density", ids, rng.uniform(1, 2, len(ids)).astype(np.float32)
    )
    for f in ("vx", "vy", "vz"):
        s0 = adv.set_cell_data(
            s0, f, ids, rng.uniform(-0.3, 0.3, len(ids)).astype(np.float32)
        )
    s0 = g.update_copies_of_remote_neighbors(s0)
    dt = np.float32(0.3 * adv.max_time_step(s0))
    st = s0
    for _ in range(3):
        st = adv.step(st, dt)
    ref = np.asarray(adv.get_cell_data(st, "density", ids), np.float64)
    b = adv._boxed_run(s0, jnp.asarray(3, jnp.int32), dt)
    rb = np.asarray(adv.get_cell_data(b, "density", ids), np.float64)
    assert np.abs(rb - ref).max() / np.abs(ref).max() < 5e-6
