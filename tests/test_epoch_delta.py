"""Incremental epoch rebuild (``parallel/epoch_delta.py``).

The contract under test is the strongest one available: after every
AMR commit / repartition in a randomized churn sequence, the live
(delta-patched) epoch must be **table-for-table identical** to a fresh
``build_epoch`` of the same (leaves, owner) snapshot — on 1- and
8-device meshes, with user neighborhoods registered mid-sequence, on
both the native and the pure-numpy paths.  Plus: the fast path must
actually engage (``epoch.delta_builds > 0``), and every documented
fallback reason must be triggerable.
"""
import numpy as np
import pytest

from dccrg_tpu import CartesianGeometry, Grid, make_mesh, obs
from dccrg_tpu.parallel.epoch import build_epoch
from dccrg_tpu.parallel.epoch_delta import (
    FALLBACK_REASONS,
    build_epoch_delta,
)
from dccrg_tpu.utils.verify import compare_epochs, verify_grid


def make_grid(n=8, max_lvl=2, n_dev=8, method="RCB", hood=1,
              periodic=(True, False, True)):
    return (
        Grid()
        .set_initial_length((n, n, n))
        .set_neighborhood_length(hood)
        .set_periodic(*periodic)
        .set_maximum_refinement_level(max_lvl)
        .set_load_balancing_method(method)
        .set_geometry(
            CartesianGeometry,
            start=(0.0, 0.0, 0.0),
            level_0_cell_length=(1.0 / n,) * 3,
        )
        .initialize(mesh=make_mesh(n_devices=n_dev))
    )


def oracle(g):
    # the oracle takes the live epoch's shapes as hints: the bucket
    # choice is idempotent against its own result (parallel/shapes.py),
    # so the fresh build reproduces the grid-managed epoch exactly —
    # hysteresis included — while any table corruption still trips the
    # comparison
    from dccrg_tpu.parallel.shapes import epoch_shape_hints

    return build_epoch(
        g.mapping, g.topology, g.leaves, g.n_devices, g.neighborhoods,
        uniform_geometry=g._uniform_geometry(),
        shape_hints=epoch_shape_hints(g.epoch),
    )


def churn_step(g, rng, round_i):
    """One randomized mutation: AMR request storm + commit, then a
    repartition every other round (pins shuffle ownership so the LB
    delta path sees real migrations)."""
    ids = g.get_cells()
    for cid in rng.choice(ids, size=min(10, len(ids)), replace=False):
        op = rng.integers(4)
        if op == 0:
            g.refine_completely(int(cid))
        elif op == 1:
            g.unrefine_completely(int(cid))
        elif op == 2:
            g.dont_refine(int(cid))
        else:
            g.dont_unrefine(int(cid))
    before = set(g.get_cells().tolist())
    g.stop_refining()
    after = set(g.get_cells().tolist())
    # the exposed AMR touched set is exactly the leaf-set symmetric diff
    delta = g.get_last_adaptation_delta()
    assert set(delta.added.tolist()) == after - before
    assert set(delta.removed.tolist()) == before - after
    yield "amr"
    if round_i % 2 == 1:
        for cid in rng.choice(g.get_cells(), size=5, replace=False):
            g.pin(int(cid), int(rng.integers(g.n_devices)))
        g.balance_load()
        g.unpin_all_cells()
        yield "lb"


@pytest.mark.parametrize("n_dev,seed", [(1, 0), (8, 1), (8, 5)])
def test_churn_identical_to_full_build(n_dev, seed):
    rng = np.random.default_rng(seed)
    g = make_grid(n_dev=n_dev)
    for round_i in range(6):
        if round_i == 3:
            # a user neighborhood mid-sequence: its registration is a
            # full rebuild, every later commit patches BOTH hoods
            assert g.add_neighborhood(7, [(1, 0, 0), (0, -1, 0)])
        for _ in churn_step(g, rng, round_i):
            compare_epochs(g.epoch, oracle(g))
            verify_grid(g)
    assert (obs.metrics.counter_value("epoch.delta_builds") or 0) > 0


def test_numpy_path_identical_to_full_build(monkeypatch):
    """The pure-numpy delta (CSR splice + inverse patch + run-copy table
    patch) against the pure-numpy full build."""
    import dccrg_tpu.native as native

    monkeypatch.setattr(native, "native_find_neighbors",
                        lambda *a, **k: None)
    monkeypatch.setattr(native, "native_invert_and_pairs",
                        lambda *a, **k: None)
    monkeypatch.setattr(native, "native_sort_unique_u64",
                        lambda *a, **k: None)
    monkeypatch.setattr(native, "native_fill_tables",
                        lambda *a, **k: False)
    monkeypatch.setattr(native, "native_delta_patch_tables",
                        lambda *a, **k: False)
    rng = np.random.default_rng(2)
    g = make_grid(n_dev=8)
    for round_i in range(4):
        for _ in churn_step(g, rng, round_i):
            compare_epochs(g.epoch, oracle(g))
            verify_grid(g)


def test_delta_fast_path_engages():
    """A small clustered storm on a refined grid must take the delta
    path (the counter moves and the phase records a span)."""
    g = make_grid(n_dev=8)
    ids = g.get_cells()
    ctr = g.geometry.get_center(ids)
    r = np.linalg.norm(ctr - 0.5, axis=1)
    g.refine_completely_many(ids[r < 0.3])
    g.stop_refining()  # large change: may fall back, not asserted
    before = obs.metrics.counter_value("epoch.delta_builds") or 0
    phase_before = (obs.metrics.report()["phases"]
                    .get("epoch.delta_build", {}).get("count", 0))
    g.refine_completely(int(g.get_cells()[0]))
    g.stop_refining()
    assert (obs.metrics.counter_value("epoch.delta_builds") or 0) > before
    assert (obs.metrics.report()["phases"]["epoch.delta_build"]["count"]
            > phase_before)
    compare_epochs(g.epoch, oracle(g))


def _fallbacks(reason):
    return obs.metrics.counter_value(
        "epoch.delta_fallbacks", reason=reason
    ) or 0


def test_fallback_fraction():
    g = make_grid(n_dev=8, max_lvl=1)
    g.refine_completely(1)
    g.stop_refining()  # leave the dense-eligible uniform grid first
    before = _fallbacks("fraction")
    g.refine_completely_many(g.get_cells())  # touches everything
    g.stop_refining()
    assert _fallbacks("fraction") > before
    compare_epochs(g.epoch, oracle(g))


def test_fallback_r_growth(monkeypatch):
    monkeypatch.setenv("DCCRG_EPOCH_DELTA_MAX_R_GROWTH", "1.0")
    # buckets off: with the geometric ladder + hysteresis a one-cell
    # refinement is absorbed by the held row budget and R never grows
    monkeypatch.setenv("DCCRG_EPOCH_BUCKETS", "0")
    g = make_grid(n_dev=8)
    g.refine_completely(1)
    g.stop_refining()
    before = _fallbacks("r_growth")
    # a tiny storm: closure is small, but R must grow on the refined
    # device -> with growth capped at 1.0x the delta path must decline
    g.refine_completely(int(g.get_cells()[10]))
    g.stop_refining()
    assert _fallbacks("r_growth") > before
    compare_epochs(g.epoch, oracle(g))


def test_fallback_dense_flip():
    g = make_grid(n_dev=8, max_lvl=1)
    assert g.epoch.dense is not None  # uniform level-0 block partition
    before = _fallbacks("dense_flip")
    g.refine_completely(1)
    g.stop_refining()
    assert _fallbacks("dense_flip") > before
    assert g.epoch.dense is None
    compare_epochs(g.epoch, oracle(g))


def test_fallback_device_count_and_hoods_changed():
    g = make_grid(n_dev=8)
    g.refine_completely(1)
    g.stop_refining()
    before = _fallbacks("device_count")
    assert build_epoch_delta(
        g.epoch, g.leaves, g.n_devices + 1, g.neighborhoods,
        uniform_geometry=g._uniform_geometry(),
    ) is None
    assert _fallbacks("device_count") > before
    before = _fallbacks("hoods_changed")
    hoods = dict(g.neighborhoods)
    hoods[3] = np.array([[1, 0, 0]], dtype=np.int64)
    assert build_epoch_delta(
        g.epoch, g.leaves, g.n_devices, hoods,
        uniform_geometry=g._uniform_geometry(),
    ) is None
    assert _fallbacks("hoods_changed") > before
    assert set(FALLBACK_REASONS) >= {
        "fraction", "r_growth", "dense_flip", "device_count",
        "hoods_changed",
    }


def test_delta_disabled_by_env(monkeypatch):
    monkeypatch.setenv("DCCRG_EPOCH_DELTA", "0")
    g = make_grid(n_dev=1)
    g.refine_completely(1)
    g.stop_refining()
    assert build_epoch_delta(
        g.epoch, g.leaves, g.n_devices, g.neighborhoods,
        uniform_geometry=g._uniform_geometry(),
    ) is None
    compare_epochs(g.epoch, oracle(g))


def test_epoch_verify_env_cross_checks(monkeypatch):
    """DCCRG_EPOCH_VERIFY=1: every incremental epoch self-checks against
    a fresh full build (and verify_grid re-checks it)."""
    monkeypatch.setenv("DCCRG_EPOCH_VERIFY", "1")
    rng = np.random.default_rng(3)
    g = make_grid(n_dev=8)
    for round_i in range(3):
        for _ in churn_step(g, rng, round_i):
            verify_grid(g)


def test_prev_epoch_is_slim_and_releasable():
    """After a structural change only the slim carry is retained (no
    hood tables), remap_state stays repeatable for several payloads, and
    release_prev_epoch drops the carry."""
    g = make_grid(n_dev=8)
    s1 = g.new_state({"a": ((), np.float64)}, fill=1.0)
    s2 = g.new_state({"b": ((), np.float32)}, fill=2.0)
    g.refine_completely(1)
    g.stop_refining()
    carry = g._prev_epoch
    assert carry is not None and not hasattr(carry, "hoods")
    assert not hasattr(carry, "cell_ids")  # row tables not retained
    s1 = g.remap_state(s1)
    s2 = g.remap_state(s2)  # second payload still remaps
    ids = g.get_cells()
    assert np.allclose(g.get_cell_data(s1, "a", ids), 1.0)
    assert np.allclose(g.get_cell_data(s2, "b", ids), 2.0)
    g.release_prev_epoch()
    assert g._prev_epoch is None
    assert g.remap_state(s1) is s1  # identity until the next change
