"""Geometry tests: Cartesian vs Stretched consistency, periodic wrapping,
coordinate->cell queries (reference tests/geometry analogues)."""
import numpy as np
import pytest

from dccrg_tpu.core import ERROR_CELL, Mapping, Topology
from dccrg_tpu.geometry import (
    CartesianGeometry,
    NoGeometry,
    StretchedCartesianGeometry,
    geometry_from_id,
)


@pytest.fixture
def mapping():
    return Mapping(length=(4, 3, 2), max_refinement_level=2)


def test_cartesian_box(mapping):
    g = CartesianGeometry(
        mapping=mapping, start=(-1.0, 0.0, 2.0), level_0_cell_length=(0.5, 1.0, 2.0)
    )
    np.testing.assert_allclose(g.get_start(), [-1.0, 0.0, 2.0])
    np.testing.assert_allclose(g.get_end(), [-1.0 + 4 * 0.5, 3.0, 2.0 + 2 * 2.0])


def test_cartesian_center_length(mapping):
    g = CartesianGeometry(mapping=mapping, level_0_cell_length=(1.0, 1.0, 1.0))
    cells = np.arange(1, int(mapping.last_cell) + 1, dtype=np.uint64)
    lvl = mapping.get_refinement_level(cells)
    lens = g.get_length(cells)
    np.testing.assert_allclose(lens, (1.0 / 2**lvl)[:, None] * np.ones(3))
    centers = g.get_center(cells)
    mins, maxs = g.get_min(cells), g.get_max(cells)
    np.testing.assert_allclose(centers, 0.5 * (mins + maxs))
    # cell 1 is the level-0 cell at origin corner
    np.testing.assert_allclose(g.get_center(np.uint64(1)), [0.5, 0.5, 0.5])

    # invalid -> NaN
    assert np.isnan(g.get_center(np.uint64(0))).all()


def test_coord_to_cell_roundtrip(mapping):
    g = CartesianGeometry(mapping=mapping, start=(0.5, -2.0, 0.0),
                          level_0_cell_length=(2.0, 0.25, 1.5))
    cells = np.arange(1, int(mapping.last_cell) + 1, dtype=np.uint64)
    lvl = mapping.get_refinement_level(cells)
    centers = g.get_center(cells)
    got = np.empty_like(cells)
    for i, (c, l) in enumerate(zip(centers, lvl)):
        got[i] = g.get_cell(int(l), c)
    np.testing.assert_array_equal(got, cells)


def test_periodic_wrapping():
    m = Mapping(length=(4, 4, 4))
    g = CartesianGeometry(
        mapping=m, topology=Topology(periodic=(True, False, False)),
        level_0_cell_length=(1.0, 1.0, 1.0),
    )
    r = g.get_real_coordinate(np.array([-0.5, -0.5, 2.0]))
    assert r[0] == pytest.approx(3.5)
    assert np.isnan(r[1])
    assert r[2] == 2.0
    # wrapped coordinate lands in the right cell
    assert int(g.get_cell(0, np.array([4.5, 1.0, 1.0]))) == int(
        g.get_cell(0, np.array([0.5, 1.0, 1.0]))
    )
    # outside non-periodic -> ERROR_CELL
    assert int(g.get_cell(0, np.array([1.0, 9.0, 1.0]))) == int(ERROR_CELL)


def test_stretched_matches_cartesian_when_uniform(mapping):
    uniform = StretchedCartesianGeometry(
        mapping=mapping,
        coordinates=(
            np.arange(5) * 2.0 + 1.0,
            np.arange(4) * 0.5,
            np.arange(3) * 1.0,
        ),
    )
    cart = CartesianGeometry(
        mapping=mapping, start=(1.0, 0.0, 0.0), level_0_cell_length=(2.0, 0.5, 1.0)
    )
    cells = np.arange(1, int(mapping.last_cell) + 1, dtype=np.uint64)
    np.testing.assert_allclose(uniform.get_center(cells), cart.get_center(cells))
    np.testing.assert_allclose(uniform.get_length(cells), cart.get_length(cells))
    np.testing.assert_allclose(uniform.get_min(cells), cart.get_min(cells))
    coords = cart.get_center(cells)
    lvls = mapping.get_refinement_level(cells)
    for c, l, cell in zip(coords[:50], lvls[:50], cells[:50]):
        assert int(uniform.get_cell(int(l), c)) == int(cell)


def test_stretched_nonuniform():
    m = Mapping(length=(3, 1, 1), max_refinement_level=1)
    g = StretchedCartesianGeometry(
        mapping=m,
        coordinates=(np.array([0.0, 1.0, 10.0, 100.0]), np.array([0.0, 1.0]),
                     np.array([0.0, 1.0])),
    )
    # level-0 cells have widths 1, 9, 90
    lvl0 = np.array([1, 2, 3], dtype=np.uint64)
    np.testing.assert_allclose(g.get_length(lvl0)[:, 0], [1.0, 9.0, 90.0])
    # children split the parent in half in physical space
    ch = m.get_all_children(np.uint64(2))
    np.testing.assert_allclose(g.get_min(ch[:1])[0, 0], 1.0)
    np.testing.assert_allclose(g.get_length(ch)[:, 0], 4.5)
    # coordinate lookup
    assert int(g.get_cell(0, np.array([50.0, 0.5, 0.5]))) == 3
    assert int(g.get_cell(1, np.array([3.0, 0.2, 0.2]))) == int(ch[0])


def test_no_geometry(mapping):
    g = NoGeometry(mapping)
    np.testing.assert_allclose(g.get_start(), [0, 0, 0])
    np.testing.assert_allclose(g.get_end(), [4, 3, 2])
    assert g.geometry_id == 0


def test_geometry_file_roundtrip(mapping):
    top = Topology(periodic=(True, True, False))
    g = CartesianGeometry(
        mapping=mapping, topology=top, start=(1.0, 2.0, 3.0),
        level_0_cell_length=(0.1, 0.2, 0.3),
    )
    cls = geometry_from_id(g.geometry_id)
    g2, n = cls.params_from_file_bytes(g.params_to_file_bytes(), mapping, top)
    assert n == 48
    np.testing.assert_allclose(g2.get_start(), g.get_start())
    np.testing.assert_allclose(g2.get_end(), g.get_end())

    s = StretchedCartesianGeometry(
        mapping=mapping,
        coordinates=(np.array([0.0, 1, 2, 4, 8.0]), np.array([0.0, 1, 3, 6.0]),
                     np.array([0.0, 2, 5.0])),
    )
    s2, _ = StretchedCartesianGeometry.params_from_file_bytes(
        s.params_to_file_bytes(), mapping, top
    )
    for a, b in zip(s2.coordinates, s.coordinates):
        np.testing.assert_allclose(a, b)
