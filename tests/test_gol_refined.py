"""Game of life on statically refined grids (reference
tests/game_of_life/refined2d.cpp, unrefined2d.cpp: life on AMR'd grids
with patterns placed away from refinement boundaries) and with the
reference's hierarchical/pinned variants combined."""
import numpy as np

from dccrg_tpu import Grid, make_mesh
from dccrg_tpu.models import GameOfLife


def make_refined(refine_at, n_dev=None):
    g = (
        Grid()
        .set_initial_length((10, 10, 1))
        .set_maximum_refinement_level(1)
        .set_neighborhood_length(1)
        .initialize(mesh=make_mesh(n_devices=n_dev))
    )
    for c in refine_at:
        g.refine_completely(c)
    g.stop_refining()
    return g


def test_blinker_away_from_refinement():
    """Refine a corner; a blinker far from it behaves exactly as on the
    uniform grid (the refined2d test's design)."""
    g = make_refined([1])  # refine corner cell 1
    gol = GameOfLife(g)
    state = gol.new_state(alive_cells=[54, 55, 56])
    for turn in range(1, 11):
        state = gol.step(state)
        alive = set(gol.alive_cells(state).tolist())
        expect = {45, 55, 65} if turn % 2 == 1 else {54, 55, 56}
        assert alive == expect, f"turn {turn}"


def test_refined_structure_consistent_after_life():
    g = make_refined([1, 34, 67])
    gol = GameOfLife(g)
    rng = np.random.default_rng(2)
    cells = g.get_cells()
    state = gol.new_state(alive_cells=cells[rng.random(len(cells)) < 0.3])
    state = gol.run(state, 5)
    # counts stay within neighbor-count bounds; no NaN/garbage
    counts = g.get_cell_data(state, "live_neighbor_count", cells)
    h = g.epoch.hoods[None]
    max_entries = np.diff(h.lists.start).max()
    assert counts.max() <= max_entries
    from dccrg_tpu.utils import verify_grid

    verify_grid(g)


def test_refined_gol_device_invariance():
    finals = []
    for n_dev in (1, 8):
        g = make_refined([1, 55], n_dev=n_dev)
        gol = GameOfLife(g)
        cells = g.get_cells()
        rng = np.random.default_rng(7)
        alive0 = cells[rng.random(len(cells)) < 0.3]
        state = gol.new_state(alive_cells=alive0)
        state = gol.run(state, 8)
        finals.append(frozenset(gol.alive_cells(state).tolist()))
    assert finals[0] == finals[1]


def test_unrefined_gol():
    """Refine then unrefine back (unrefined2d analogue): behavior must
    match the never-refined grid."""
    g = make_refined([28])
    children = g.mapping.get_all_children(np.uint64(28))
    g.unrefine_completely(int(children[0]))
    g.stop_refining()
    assert len(g.get_cells()) == 100
    gol = GameOfLife(g)
    state = gol.new_state(alive_cells=[54, 55, 56])
    state = gol.run(state, 4)
    assert set(gol.alive_cells(state).tolist()) == {54, 55, 56}
