"""Per-cell dynamic payload selection at the exchange seam — the
reference's ``get_mpi_datatype(cell_id, sender, receiver, receiving,
neighborhood_id)`` (``dccrg_get_cell_datatype.hpp:48-125``), where a
cell can vary its transferred content per exchange and neighborhood.
Here the policy is a host callback evaluated at schedule-compile time;
ghost copies of unselected cells keep their previous values, exactly
like data a reference cell leaves out of its returned datatype."""
import numpy as np
import pytest

from dccrg_tpu import Grid, make_mesh


def make_grid(hood=1, length=(8, 8, 1)):
    return (
        Grid()
        .set_initial_length(length)
        .set_neighborhood_length(hood)
        .initialize(mesh=make_mesh(n_devices=8))
    )


def even_cells_only(field, cell_ids, sender, receiver, hood_id):
    """rho travels only for even cell ids; aux always travels."""
    if field == "rho":
        return np.asarray(cell_ids, np.uint64) % 2 == 0
    return np.ones(len(cell_ids), bool)


def _ghost_map(g):
    """{(device, row): cell_id} for every ghost row."""
    out = {}
    ep = g.epoch
    for d in range(g.n_devices):
        for k, pos in enumerate(ep.ghost_pos[d]):
            out[(d, int(ep.n_local[d] + k))] = int(ep.leaves.cells[pos])
    return out


def _states(g):
    spec = {"rho": ((), np.float64), "aux": ((), np.float64)}
    st = g.new_state(spec, fill=-1.0)
    cells = g.get_cells()
    st = g.set_cell_data(st, "rho", cells, cells.astype(np.float64))
    st = g.set_cell_data(st, "aux", cells, 100.0 + cells.astype(np.float64))
    return st


def test_policy_gates_per_cell_per_field():
    g = make_grid()
    st = _states(g)
    full = g.halo(None)(st)
    sel = g.halo(None, cell_datatype=even_cells_only)(st)
    rho_f, rho_s = np.asarray(full["rho"]), np.asarray(sel["rho"])
    aux_f, aux_s = np.asarray(full["aux"]), np.asarray(sel["aux"])
    checked_even = checked_odd = 0
    for (d, row), cid in _ghost_map(g).items():
        # aux always transfers: identical to the full exchange
        assert aux_s[d, row] == aux_f[d, row] == 100.0 + cid
        if cid % 2 == 0:
            assert rho_s[d, row] == rho_f[d, row] == cid
            checked_even += 1
        else:
            # unselected: the ghost keeps its pre-exchange fill value
            assert rho_s[d, row] == -1.0
            checked_odd += 1
    assert checked_even and checked_odd


def test_policy_reduces_wire_bytes():
    g = make_grid()
    st = _states(g)
    full = g.halo(None)
    sel = g.halo(None, cell_datatype=even_cells_only)
    assert sel.bytes_moved(st) < full.bytes_moved(st)
    assert sel.wire_bytes(st) <= full.wire_bytes(st)
    # aux moves everywhere, rho only from even cells
    only_aux = {"aux": st["aux"]}
    assert sel.bytes_moved(only_aux) == full.bytes_moved(only_aux)


def test_split_phase_matches_blocking_under_policy():
    g = make_grid()
    st = _states(g)
    h = g.halo(None, cell_datatype=even_cells_only)
    blocking = h(st)
    handle = h.start(st)
    merged = h.finish(st, handle)
    for f in ("rho", "aux"):
        np.testing.assert_array_equal(
            np.asarray(blocking[f]), np.asarray(merged[f])
        )


def test_grid_level_policy_and_epoch_rebuild():
    """set_cell_datatype installs the policy for the default halo()
    route; an epoch rebuild (balance_load) recompiles the schedule
    against the new send lists with the same policy."""
    g = make_grid()
    g.set_cell_datatype(even_cells_only)
    st = _states(g)
    out = g.update_copies_of_remote_neighbors(st)
    gm = _ghost_map(g)
    odd = [(d, r) for (d, r), cid in gm.items() if cid % 2 == 1]
    assert odd
    assert all(np.asarray(out["rho"])[d, r] == -1.0 for d, r in odd)

    g.balance_load()
    st2 = _states(g)
    out2 = g.update_copies_of_remote_neighbors(st2)
    gm2 = _ghost_map(g)
    for (d, r), cid in gm2.items():
        want = -1.0 if cid % 2 == 1 else float(cid)
        assert np.asarray(out2["rho"])[d, r] == want

    g.set_cell_datatype(None)
    out3 = g.update_copies_of_remote_neighbors(_states(g))
    assert all(
        np.asarray(out3["rho"])[d, r] == cid
        for (d, r), cid in _ghost_map(g).items()
    )


def test_policy_sees_neighborhood_and_pair():
    """The policy receives (sender, receiver, hood_id) — a policy keyed
    on the neighborhood produces different schedules per hood, the
    reference's neighborhood_id-dependent datatype."""
    g = make_grid()
    assert g.add_neighborhood(7, [(0, 1, 0)])
    seen = set()

    def spy(field, cell_ids, sender, receiver, hood_id):
        seen.add((sender, receiver, hood_id))
        return (np.ones(len(cell_ids), bool) if hood_id == 7
                else np.zeros(len(cell_ids), bool))

    st = _states(g)
    out_default = g.halo(None, cell_datatype=spy)(st)
    out_hood7 = g.halo(7, cell_datatype=spy)(st)
    assert any(h == 7 for (_s, _r, h) in seen)
    assert any(h is None for (_s, _r, h) in seen)
    assert all(s != r for (s, r, _h) in seen)
    # default hood: everything masked out -> ghosts untouched
    gm = _ghost_map(g)
    assert all(
        np.asarray(out_default["rho"])[d, r] == -1.0 for d, r in gm
    )
    # hood 7: its (sparser) ghost set fully refreshed
    assert np.asarray(out_hood7["rho"]).max() > 0


def test_bad_mask_shape_raises():
    g = make_grid()
    st = _states(g)

    def bad(field, cell_ids, sender, receiver, hood_id):
        return np.ones(3, bool)

    with pytest.raises(ValueError, match="mask"):
        g.halo(None, cell_datatype=bad)(st)
