"""Live fleet telemetry (ISSUE 16): the stream tailer (byte-offset
resume, torn-tail re-join, seq-gap counting), windowed bucket-delta
views over multiple per-process streams, the exact-merge == pooled
property on live data, the Prometheus exposition round-trip, the
alerting plane's for_s/hysteresis no-flap state machine with its
one-dump-per-incident flight-recorder discipline, and the supervisor's
alert signal source."""
import json
import math
import os
import subprocess
import sys
import time

import pytest

from dccrg_tpu.obs import alerts, live, slo
from dccrg_tpu.obs import stream as obs_stream
from dccrg_tpu.obs.flightrec import FlightRecorder, validate_flightrec
from dccrg_tpu.obs.registry import MetricsRegistry

HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.dirname(HERE)


def _write_lines(path, snaps_and_ts, extra=None):
    """Append ``(snapshot, ts)`` stream lines; a snapshot is either a
    ``report()`` dict captured at observe time or a registry (snapshot
    taken NOW — only sound when every line may share the final state)."""
    with open(path, "a") as f:
        for seq, (snap, ts) in enumerate(snaps_and_ts):
            if not isinstance(snap, dict):
                snap = snap.report()
            rec = {"seq": seq, "ts": ts, **(extra or {}), **snap}
            f.write(json.dumps(rec, default=float) + "\n")


def _slo_registry():
    reg = MetricsRegistry(enabled=True)
    reg.set_histogram_resolution("ensemble.e2e_s", slo.SLO_RESOLUTION)
    return reg


# ------------------------------------------------------------- tailer


def test_tailer_byte_offset_resume(tmp_path):
    """Each poll reads only appended bytes; already-read records are
    never re-delivered."""
    p = tmp_path / "a.stream.jsonl"
    reg = _slo_registry()
    _write_lines(p, [(reg, 1.0), (reg, 2.0)])
    t = live.StreamTailer(str(p))
    first = t.poll()
    assert [r["seq"] for r in first] == [0, 1]
    assert t.poll() == []
    with open(p, "a") as f:
        f.write(json.dumps({"seq": 2, "ts": 3.0, **reg.report()},
                           default=float) + "\n")
    assert [r["seq"] for r in t.poll()] == [2]
    assert t.records_read == 3
    assert t.seq_gaps == 0 and t.torn_tails == 0 and t.bad_lines == 0


def test_tailer_torn_tail_resumes_cleanly(tmp_path):
    """Regression (ISSUE 16 satellite): a line cut mid-write is held
    back, COUNTED, and delivered intact once the remainder lands."""
    p = tmp_path / "a.stream.jsonl"
    reg = _slo_registry()
    full = json.dumps({"seq": 0, "ts": 1.0, **reg.report()},
                      default=float) + "\n"
    cut = len(full) // 2
    with open(p, "w") as f:
        f.write(full[:cut])  # torn: the writer died mid-line ... or not
    t = live.StreamTailer(str(p))
    assert t.poll() == []  # fragment withheld, not mis-parsed
    assert t.torn_tails == 1
    with open(p, "a") as f:
        f.write(full[cut:])  # the writer completes the line
    recs = t.poll()
    assert len(recs) == 1 and recs[0]["seq"] == 0
    assert t.bad_lines == 0  # the re-joined line parsed exactly once
    assert t.records_read == 1


def test_tailer_counts_seq_gaps(tmp_path):
    p = tmp_path / "a.stream.jsonl"
    reg = _slo_registry()
    with open(p, "w") as f:
        for seq in (0, 1, 4, 5, 9):  # gaps: 2-3 (2 lines), 6-8 (3)
            f.write(json.dumps({"seq": seq, "ts": float(seq),
                                **reg.report()}, default=float) + "\n")
    t = live.StreamTailer(str(p))
    assert len(t.poll()) == 5
    assert t.seq_gaps == 5


def test_tailer_counts_into_registry(tmp_path):
    p = tmp_path / "a.stream.jsonl"
    reg = _slo_registry()
    with open(p, "w") as f:
        for seq in (0, 3):
            f.write(json.dumps({"seq": seq, "ts": float(seq),
                                **reg.report()}, default=float) + "\n")
        f.write("{not json}\n")
        f.write('{"seq": 4, "ts"')  # torn tail
    counter_reg = MetricsRegistry(enabled=True)
    t = live.StreamTailer(str(p), registry=counter_reg)
    t.poll()
    counters = counter_reg.report()["counters"]
    label = "path=a.stream.jsonl"
    assert counters["stream.seq_gaps"][label] == 2
    assert counters["stream.bad_lines"][label] == 1
    assert counters["stream.torn_tails"][label] == 1


def test_validate_stream_counts_gaps_and_torn_tail(tmp_path):
    """``check_telemetry.validate_stream`` tolerates-but-counts the
    same anomalies the tailer does."""
    sys.path.insert(0, os.path.join(ROOT, "tools"))
    try:
        from check_telemetry import validate_stream
    finally:
        sys.path.pop(0)
    p = tmp_path / "a.stream.jsonl"
    reg = _slo_registry()
    with open(p, "w") as f:
        for seq in (0, 1, 5):
            f.write(json.dumps({"seq": seq, "ts": float(seq),
                                **reg.report()}, default=float) + "\n")
        f.write('{"seq": 6, "ts": 6.0, "cut mid-')  # torn final line
    counts: dict = {}
    failures = validate_stream(str(p), counts)
    assert failures == []
    assert counts["lines"] == 3
    assert counts["seq_gaps"] == 3
    assert counts["torn_tail"] == 1


# ---------------------------------------------------- windowed views


def _brute_quantile(samples, q):
    """Sample quantile with the same rank convention slo.quantile uses
    (value at ceil(q*n) in the sorted order)."""
    s = sorted(samples)
    rank = q * len(s)
    idx = max(int(math.ceil(rank)) - 1, 0)
    return s[min(idx, len(s) - 1)]


def test_windowed_quantile_matches_bruteforce(tmp_path):
    """Known-value check: the bucket-delta windowed p50/p95/p99 lands
    within one log-bucket of the brute-force quantile over exactly the
    in-window samples.  Values span one octave so every sub-bucket is
    occupied and the one-bucket bound is tight (sparse buckets would
    legitimately widen the interpolation interval)."""
    p = tmp_path / "a.stream.jsonl"
    reg = _slo_registry()
    rows = []
    samples = []
    t0 = 1000.0
    for j in range(120):
        v = 0.010 * (1.0 + ((j * 37) % 100) / 100.0)  # [0.010, 0.020)
        reg.observe("ensemble.e2e_s", v, tenant="t0")
        samples.append((t0 + j, v))
        rows.append((reg.report(), t0 + j))  # cumulative-at-this-line
    _write_lines(p, rows)

    window = 50.0
    agg = live.FleetAggregator([str(p)], window_s=window)
    now = t0 + 119.5
    agg.poll(now=now)
    view = agg.view(now=now)
    # the window edge snapshot is the newest line with ts <= now-50
    # (ts = t0+69); in-window samples are those observed on later lines
    in_window = [v for ts, v in samples if ts > now - window]
    assert view.histogram("ensemble.e2e_s")["count"] == len(in_window)
    bucket = 2.0 ** (1.0 / slo.SLO_RESOLUTION)
    for q in (0.5, 0.95, 0.99):
        est = view.quantile("ensemble.e2e_s", q)
        true = _brute_quantile(in_window, q)
        assert true / bucket <= est <= true * bucket * (1 + 1e-9), (
            q, est, true)


def test_windowed_counters_and_rates(tmp_path):
    p = tmp_path / "a.stream.jsonl"
    reg = MetricsRegistry(enabled=True)
    rows = []
    for j in range(10):
        reg.inc("ensemble.steps_served", 2, tenant="t0")
        rows.append((reg.report(), 100.0 + j))
    _write_lines(p, rows)
    agg = live.FleetAggregator([str(p)], window_s=4.0)
    agg.poll(now=109.5)
    view = agg.view(now=109.5)
    # edge = line at ts 105 (newest <= 105.5): lines 106..109 in window
    assert view.counter("ensemble.steps_served") == 8
    assert view.rate("ensemble.steps_served") == pytest.approx(2.0)
    # the full cumulative total is still visible
    assert view.counter("ensemble.steps_served", windowed=False) == 20


def test_two_live_streams_merge_equals_pooled(tmp_path):
    """The acceptance criterion: live windowed quantiles over two
    concurrently-written streams match the post-hoc pooled
    ``obs/slo.py`` merge to within one bucket (and counts exactly)."""
    regs = [_slo_registry(), _slo_registry()]
    paths = [tmp_path / f"w{i}.stream.jsonl" for i in (0, 1)]
    pooled_reg = _slo_registry()
    t0 = 500.0
    for i, (reg, p) in enumerate(zip(regs, paths)):
        rows = []
        for j in range(25):
            v = 0.001 * (1.0 + ((j * 7 + i * 3) % 50))
            reg.observe("ensemble.e2e_s", v, tenant=f"t{i}")
            pooled_reg.observe("ensemble.e2e_s", v, tenant=f"t{i}")
            reg.inc("ensemble.steps_served", 1, tenant=f"t{i}")
            if j % 5 == 0:
                reg.inc("ensemble.deadline_miss", 1, tenant=f"t{i}")
            rows.append((reg, t0 + j))
        _write_lines(p, rows)

    agg = live.FleetAggregator([str(q) for q in paths], window_s=3600.0)
    agg.poll(now=t0 + 30)
    view = agg.view(now=t0 + 30)
    assert view.counter("ensemble.steps_served") == 50
    assert view.counter("ensemble.deadline_miss") == 10

    pooled_all = slo.merge(
        *pooled_reg.report()["histograms"]["ensemble.e2e_s"].values())
    live_h = view.histogram("ensemble.e2e_s")
    assert live_h["count"] == pooled_all["count"] == 50
    assert live_h["buckets"] == pooled_all["buckets"]
    for q in (0.5, 0.95, 0.99):
        assert view.quantile("ensemble.e2e_s", q) == pytest.approx(
            slo.quantile(pooled_all, q))
    # per-tenant windowed miss rates carry the slo semantics
    rates = view.miss_rates()
    assert rates["t0"]["completed"] == 25 and rates["t0"]["missed"] == 5
    assert rates["t0"]["rate"] == pytest.approx(0.2)


def test_aggregator_discovers_new_writers(tmp_path):
    reg = _slo_registry()
    a = tmp_path / "a.stream.jsonl"
    _write_lines(a, [(reg, 1.0)])
    agg = live.FleetAggregator(str(tmp_path), window_s=3600.0)
    agg.poll(now=2.0)
    assert agg.view(now=2.0).health["files"] == 1
    b = tmp_path / "b.stream.jsonl"
    _write_lines(b, [(reg, 2.0)])
    agg.poll(now=3.0)
    assert agg.view(now=3.0).health["files"] == 2


# ------------------------------------------------------- exposition


def test_prometheus_exposition_round_trip():
    reg = _slo_registry()
    for v in (0.001, 0.004, 0.032, 0.5):
        reg.observe("ensemble.e2e_s", v, tenant="acme")
    reg.inc("ensemble.steps_served", 7, tenant="acme")
    reg.inc("alerts.fired", 2, rule="queue-depth")
    reg.gauge("ensemble.queue_depth", 3.5)
    rep = reg.report()
    text = live.to_prometheus(rep)
    # exposition shape: TYPE lines, cumulative le buckets, +Inf == count
    assert "# TYPE dccrg_ensemble_e2e_s histogram" in text
    assert 'le="+Inf"' in text
    back = live.parse_prometheus(text)
    assert back["counters"]["ensemble.steps_served"]["tenant=acme"] == 7
    assert back["counters"]["alerts.fired"]["rule=queue-depth"] == 2
    assert back["gauges"]["ensemble.queue_depth"][""] == 3.5
    h = rep["histograms"]["ensemble.e2e_s"]["tenant=acme"]
    b = back["histograms"]["ensemble.e2e_s"]["tenant=acme"]
    assert b["count"] == h["count"]
    assert b["sum"] == pytest.approx(h["sum"])
    assert b["buckets"] == {k: int(n) for k, n in h["buckets"].items()}
    # quantiles survive the round trip bucket-exactly
    for q in (0.5, 0.99):
        assert slo.quantile({**b, "min": h["min"], "max": h["max"]}, q) \
            == pytest.approx(slo.quantile(h, q))


# ------------------------------------------------------------ alerts


class _View:
    """Minimal FleetView protocol stub driving one scripted value."""

    def __init__(self, v):
        self.v = v

    def gauge_values(self, name):
        return {} if self.v is None else {"": self.v}

    def rate(self, name, labels=None):
        return self.v

    def quantile(self, name, q, labels=None):
        return self.v

    def miss_rates(self):
        if self.v is None:
            return {}
        return {"t0": {"rate": self.v, "missed": 1, "completed": 2}}


def _engine(rules):
    return alerts.AlertEngine(rules, registry=False, flight_recorder=False)


def test_alert_oscillation_never_flaps():
    """A series oscillating between the fire and clear thresholds fires
    exactly once and NEVER clears: hysteresis provably prevents flap."""
    rule = alerts.AlertRule("osc", "g", source="gauge", kind="ceiling",
                            threshold=0.5, clear=0.2, for_s=0.0)
    eng = _engine([rule])
    transitions = []
    for i, v in enumerate([0.6, 0.3] * 25):
        transitions += eng.poll(_View(v), now=float(i))
    assert [t["event"] for t in transitions] == ["fired"]
    st = eng.state("osc")
    assert st["fires"] == 1 and st["clears"] == 0
    assert eng.firing() == ["osc"]
    # only a full hysteresis crossing clears — then a new incident may fire
    eng.poll(_View(0.1), now=1000.0)
    assert eng.state("osc")["clears"] == 1
    assert eng.firing() == []
    eng.poll(_View(0.9), now=1001.0)
    assert eng.state("osc")["fires"] == 2


def test_alert_for_s_suppresses_transients():
    rule = alerts.AlertRule("slow", "g", source="gauge", kind="ceiling",
                            threshold=0.5, clear=0.2, for_s=2.5)
    eng = _engine([rule])
    # oscillation faster than for_s: pending always lapses, never fires
    for i, v in enumerate([0.6, 0.3] * 10):
        eng.poll(_View(v), now=float(i))
    assert eng.state("slow")["fires"] == 0
    # sustained breach fires once for_s is exceeded
    fired = []
    for i in range(5):
        fired += eng.poll(_View(0.7), now=100.0 + i)
    assert [t["event"] for t in fired] == ["fired"]


def test_alert_floor_kind_and_no_data_holds_state():
    rule = alerts.AlertRule("low", "overlap.fraction", source="gauge",
                            kind="floor", threshold=0.1, clear=0.15)
    eng = _engine([rule])
    eng.poll(_View(0.05), now=0.0)
    assert eng.firing() == ["low"]
    eng.poll(_View(None), now=1.0)  # no data: state held, no clear
    assert eng.firing() == ["low"]
    eng.poll(_View(0.12), now=2.0)  # above threshold but below clear
    assert eng.firing() == ["low"]
    eng.poll(_View(0.2), now=3.0)
    assert eng.firing() == []


def test_alert_one_dump_per_incident(tmp_path):
    """The ladder discipline on the alert plane: an incident dumps the
    armed flight recorder exactly once however long it persists; a new
    incident after a clear dumps again."""
    fr = FlightRecorder(enabled=True, registry=MetricsRegistry())
    fr.arm(str(tmp_path), autodump=False)
    rule = alerts.AlertRule("burst", "g", source="gauge", kind="ceiling",
                            threshold=0.5, clear=0.2, for_s=0.0)
    eng = alerts.AlertEngine([rule], registry=False, flight_recorder=fr)
    for i in range(5):  # persisting breach: one incident
        eng.poll(_View(0.9), now=float(i))
    dumps = sorted(f for f in os.listdir(tmp_path)
                   if f.startswith("flightrec_") and f.endswith(".json"))
    assert len(dumps) == 1
    full = os.path.join(str(tmp_path), dumps[0])
    assert validate_flightrec(full) == []
    rec = json.load(open(full))
    assert "alert:burst" in rec["reason"]
    assert any(ev.get("kind") == "alert.fired"
               and ev.get("rule") == "burst"
               for ev in rec["events"])
    assert eng.state("burst")["dump"] == full
    # clear, then a second incident -> a second dump
    eng.poll(_View(0.1), now=100.0)
    eng.poll(_View(0.9), now=101.0)
    dumps = [f for f in os.listdir(tmp_path)
             if f.startswith("flightrec_") and f.endswith(".json")]
    assert len(dumps) == 2


def test_alert_counters_and_default_rules():
    reg = MetricsRegistry(enabled=True)
    rule = alerts.AlertRule("r", "g", source="gauge", kind="ceiling",
                            threshold=0.5, clear=0.2)
    eng = alerts.AlertEngine([rule], registry=reg, flight_recorder=False)
    eng.poll(_View(0.9), now=0.0)
    eng.poll(_View(0.1), now=1.0)
    counters = reg.report()["counters"]
    assert counters["alerts.fired"]["rule=r"] == 1
    assert counters["alerts.cleared"]["rule=r"] == 1
    # the alerts.evaluate phase is recorded (telemetry_diff allows it)
    assert "alerts.evaluate" in reg.report()["phases"]
    names = {r.name for r in alerts.default_rules()}
    assert names == {"deadline-miss-rate", "queue-depth",
                     "halo-exchanges-per-step", "overlap-fraction",
                     "worker-lost"}


def test_load_rules_and_env(tmp_path, monkeypatch):
    p = tmp_path / "rules.json"
    p.write_text(json.dumps({"rules": [
        {"name": "custom", "metric": "ensemble.queue_depth",
         "source": "gauge", "kind": "ceiling", "threshold": 9.0,
         "clear": 4.0, "for_s": 1.5},
    ]}))
    rules = alerts.load_rules(str(p))
    assert len(rules) == 1 and rules[0].name == "custom"
    assert rules[0].clear == 4.0 and rules[0].for_s == 1.5
    monkeypatch.setenv("DCCRG_ALERT_RULES", str(p))
    assert [r.name for r in alerts.rules_from_env()] == ["custom"]
    monkeypatch.setenv("DCCRG_ALERTS", "0")
    assert not alerts.alerts_enabled()
    monkeypatch.delenv("DCCRG_ALERTS")
    assert alerts.alerts_enabled()


def test_supervisor_takes_alert_signal(tmp_path):
    """A live child whose alert rules are firing climbs the ladder even
    while its heartbeat beats; a cleared engine lets it reset."""
    from dccrg_tpu.resilience.supervisor import (
        EscalationLadder,
        HeartbeatMonitor,
        Supervisor,
    )

    hb = tmp_path / "hb.jsonl"
    hb.write_text(json.dumps({"step": 1}) + "\n")
    mon = HeartbeatMonitor(str(hb), stall_after_s=1e6)

    class Engine:
        def __init__(self):
            self.rules = []

        def firing(self):
            return list(self.rules)

    eng = Engine()
    sup = Supervisor(mon, ladder=EscalationLadder(), alerts=eng)
    assert sup.poll(now=0.0)["action"] is None
    eng.rules = ["deadline-miss-rate"]
    out = sup.poll(now=1.0)
    assert out["status"] == "degraded"
    assert out["reason"] == "alert:deadline-miss-rate"
    assert out["action"] == "warn"
    out = sup.poll(now=2.0)
    assert out["action"] == "rescale_down"  # the ladder climbed
    eng.rules = []
    assert sup.poll(now=3.0)["action"] is None  # healthy again: reset
    out = sup.poll(now=4.0)
    eng.rules = ["queue-depth"]
    assert sup.poll(now=5.0)["action"] == "warn"  # back at rung one


# ------------------------------------------- stream flush + attribution


def test_maybe_flush_writes_at_step_boundaries(tmp_path, monkeypatch):
    def our_lines():
        return [ln for ln in p.read_text().splitlines() if ln] \
            if p.exists() else []

    monkeypatch.setenv("DCCRG_STREAM_FLUSH_S", "0.0")
    reg = MetricsRegistry(enabled=True)
    p = tmp_path / "s.stream.jsonl"
    s = obs_stream.TelemetryStream(str(p), period=3600.0, registry=reg)
    s.start()
    try:
        assert obs_stream.maybe_flush() == 0  # knob 0 disables the seam
        assert our_lines() == []
        monkeypatch.setenv("DCCRG_STREAM_FLUSH_S", "0.0001")
        time.sleep(0.002)
        assert obs_stream.maybe_flush() >= 1
        assert len(our_lines()) == 1
        time.sleep(0.002)
        obs_stream.maybe_flush()
        assert len(our_lines()) == 2
    finally:
        s.stop(final=False)
    obs_stream.maybe_flush()  # stopped streams drop out of the seam
    lines = our_lines()
    assert len(lines) == 2
    assert json.loads(lines[1])["seq"] == 1


def test_fleet_top_cli_json(tmp_path):
    """The console runs jax-free on a synthetic stream dir and reports
    the windowed snapshot."""
    reg = _slo_registry()
    rows = []
    now = time.time()
    for j in range(8):
        reg.observe("ensemble.e2e_s", 0.002 * (1 + j % 5), tenant="acme")
        reg.inc("ensemble.steps_served", 1, tenant="acme")
        rows.append((reg, now - 8 + j))
    _write_lines(tmp_path / "a.stream.jsonl", rows)
    out = tmp_path / "snap.json"
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "fleet_top.py"),
         str(tmp_path), "--json", str(out), "--window", "3600"],
        capture_output=True, text=True, env=env, timeout=120,
    )
    assert proc.returncode == 0, proc.stderr
    snap = json.loads(out.read_text())
    assert snap["health"]["files"] == 1
    assert snap["latency"][0]["count"] == 8
    assert snap["rates"]["ensemble.steps_served"]["tenant=acme"] > 0


def test_slo_report_live_mode(tmp_path):
    reg = _slo_registry()
    rows = []
    now = time.time()
    for j in range(6):
        reg.observe("ensemble.e2e_s", 0.003, tenant="acme")
        if j % 2 == 0:
            reg.inc("ensemble.deadline_miss", 1, tenant="acme")
        rows.append((reg, now - 6 + j))
    _write_lines(tmp_path / "a.stream.jsonl", rows)
    out = tmp_path / "live.json"
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "slo_report.py"),
         "--live", str(tmp_path), "--window", "3600",
         "--json", str(out)],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stderr
    rep = json.loads(out.read_text())
    assert rep["window_s"] == 3600.0
    assert rep["latency"][0]["count"] == 6
    assert rep["deadline_miss_rates"]["acme"]["missed"] == 3
    assert "ensemble.e2e_s" in proc.stdout


def test_live_module_loads_without_jax(tmp_path):
    """The stdlib-only contract, end to end: file-loading live.py and
    alerts.py in a fresh interpreter must not pull in jax."""
    code = (
        "import importlib.util, sys\n"
        f"for name in ('live', 'alerts'):\n"
        f"    path = {os.path.join(ROOT, 'dccrg_tpu', 'obs')!r}"
        " + '/' + name + '.py'\n"
        "    spec = importlib.util.spec_from_file_location(name, path)\n"
        "    mod = importlib.util.module_from_spec(spec)\n"
        "    spec.loader.exec_module(mod)\n"
        "assert 'jax' not in sys.modules, 'jax leaked into the loader'\n"
    )
    proc = subprocess.run([sys.executable, "-c", code],
                          capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr
