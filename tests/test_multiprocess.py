"""REAL multi-controller SPMD tests: N coordinated OS processes
(parametrized: 2 procs x 4 devices and 3 procs x 2 devices — the
reference suite's odd-rank-count shape), one global mesh, gloo
collectives across the process boundary (``jax.distributed``).

This is the deployment shape the reference reaches with one MPI rank per
node: replicated metadata + rank-spanning data exchange.  The reference
tests the same property with ``mpiexec -n 3`` on localhost
(reference tests/README:5-7); here the fixture is coordinated JAX
processes on localhost.

The workers run game of life (halo exchange over the wire), AMR with
*different* refine requests per controller (agreement through
``sync_adaptation``), ghost bit-identity, and ``balance_load`` with
per-controller pins (agreement through ``sync_partition_inputs``).  The
driver asserts every controller reports identical results and that
they match a single-process oracle run in this process.
"""
import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
WORKER = os.path.join(HERE, "multiproc_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


#: worker-output signatures of a jaxlib whose CPU backend cannot run
#: cross-process collectives at all — an environment gap, not a bug in
#: this package, so the suite SKIPS with the reason instead of erroring
#: (ROADMAP jax version pin item; jaxlib 0.4.x raises the first one)
_NO_MULTIPROC_MARKERS = (
    "Multiprocess computations aren't implemented on the CPU backend",
    "multi-process computations are not supported",
    "cross-host collectives are not implemented",
)


def _skip_if_backend_lacks_collectives(worker_output: str) -> None:
    for marker in _NO_MULTIPROC_MARKERS:
        if marker in worker_output:
            pytest.skip(
                "this jaxlib's CPU backend lacks multiprocess "
                f"collectives ({marker!r}); pin the image's jax forward "
                "to run the multi-controller suite"
            )


def _run_workers(nproc: int, dpp: int = 4, timeout: float = 420.0):
    port = _free_port()
    procs, logs = [], []
    for pid in range(nproc):
        env = dict(os.environ)
        # each worker is a clean CPU-only controller with dpp local devices;
        # never let the TPU plugin register (its client dial would
        # serialize the workers on the real-chip tunnel)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={dpp}"
        procs.append(
            subprocess.Popen(
                [sys.executable, "-u", WORKER, str(pid), str(nproc), str(port), str(dpp)],
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
                env=env,
            )
        )
    results = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            logs.append(out)
            if p.returncode != 0:
                _skip_if_backend_lacks_collectives(out)
            assert p.returncode == 0, f"worker failed:\n{out[-4000:]}"
            lines = [l for l in out.splitlines() if l.startswith("RESULT ")]
            assert lines, f"no RESULT line:\n{out[-4000:]}"
            results.append(json.loads(lines[-1][len("RESULT "):]))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    return results


# 2 controllers x 4 devices, and the reference suite's odd-rank-count
# shape (mpiexec -n 3, tests/README:5-7): 3 controllers x 2 devices
@pytest.fixture(scope="module", params=[(2, 4), (3, 2)],
                ids=["2proc_x4dev", "3proc_x2dev"])
def multi_proc_results(request):
    return _run_workers(*request.param)


def test_controllers_agree(multi_proc_results):
    """Every controller must report the identical world state."""
    first = multi_proc_results[0]
    for other in multi_proc_results[1:]:
        assert other == first


def test_matches_single_controller_oracle(multi_proc_results):
    """The multi-process run must equal a single-process run of the same
    scenario — the reference's rank-count-invariance property, across a
    real process boundary."""
    res = multi_proc_results[0]
    assert res["n_devices"] == {2: 8, 3: 6}[res["nproc"]]

    from dccrg_tpu import Grid, make_mesh
    from dccrg_tpu.models import GameOfLife

    grid = (
        Grid()
        .set_initial_length((10, 10, 1))
        .set_maximum_refinement_level(0)
        .set_neighborhood_length(1)
        .set_load_balancing_method("RCB")
        .initialize(mesh=make_mesh())
    )
    gol = GameOfLife(grid)
    state = gol.new_state(alive_cells=[54, 55, 56])
    for turn in range(4):
        state = gol.step(state)
        alive = sorted(int(c) for c in gol.alive_cells(state))
        assert res["blinker"][turn] == alive

    # AMR oracle: the union of every controller's request
    # (controller p refined cell 3 + p)
    g2 = (
        Grid()
        .set_initial_length((4, 4, 2))
        .set_maximum_refinement_level(2)
        .set_neighborhood_length(1)
        .initialize(mesh=make_mesh())
    )
    st = g2.new_state({"rho": ((), np.float64)})
    cells = g2.get_cells()
    st = g2.set_cell_data(st, "rho", cells, np.arange(1.0, len(cells) + 1))
    for c in range(3, 3 + res["nproc"]):
        assert g2.refine_completely(c)
    g2.stop_refining()
    st = g2.remap_state(st, policy={"rho": {"refine": "inherit"}})
    import hashlib

    ids = np.sort(g2.leaves.cells)
    ids_hash = hashlib.sha256(np.ascontiguousarray(ids).tobytes()).hexdigest()[:16]
    assert res["amr"]["n_leaves"] == len(ids)
    assert res["amr"]["ids_hash"] == ids_hash
    mass1 = float(
        (np.asarray(st["rho"]) * g2.epoch.local_mask).sum()
    )
    assert res["amr"]["mass1"] == pytest.approx(mass1)


def test_pins_honored_across_controllers(multi_proc_results):
    """Controller 0's pin and controller 1's pin must BOTH land — proof
    that sync_partition_inputs really merged the request sets."""
    res = multi_proc_results[0]
    assert res["pins"]["first_owner"] == res["n_devices"] - 1
    assert res["pins"]["last_owner"] == 0
    assert res["ghost"] == "ok"


def test_flat_poisson_across_controllers(multi_proc_results):
    """The gather-free flat Poisson solve over the process-spanning mesh
    (z-roll collective permutes + cross-controller BiCG dots) must equal
    a single-process run on an identically-sized mesh."""
    res = multi_proc_results[0]["poisson_flat"]
    D = res["n_devices"]

    from dccrg_tpu import CartesianGeometry, Grid, make_mesh
    from dccrg_tpu.models import Poisson

    n = D  # grid edge = device count: z-slabs divide evenly
    g = (
        Grid()
        .set_initial_length((n, n, n))
        .set_neighborhood_length(0)
        .set_periodic(True, True, True)
        .set_geometry(
            CartesianGeometry,
            start=(0.0, 0.0, 0.0),
            level_0_cell_length=(1.0 / n,) * 3,
        )
        .initialize(mesh=make_mesh(n_devices=D))
    )
    cells = np.sort(g.leaves.cells)
    cen = g.geometry.get_center(cells)
    rhs = np.sin(2 * np.pi * cen[:, 0]) * np.cos(2 * np.pi * cen[:, 1])
    p = Poisson(g)
    assert p._flat is not None
    s = p.initialize_state(rhs)
    out, r, it = p.solve(s, max_iterations=25, stop_residual=0.0,
                         stop_after_residual_increase=float("inf"))
    assert res["iterations"] == it
    sol = np.asarray(g.get_cell_data(out, "solution", cells), np.float64)
    # gloo cross-process dots vs XLA in-process dots may round
    # differently; 25 BiCG iterations compound it — loose but meaningful
    np.testing.assert_allclose(np.asarray(res["solution"]), sol,
                               rtol=1e-7, atol=1e-10)
    assert res["residual"] == pytest.approx(r, rel=1e-6)


def test_some_reduce_point_to_point(multi_proc_results):
    """The point-to-point Some_Reduce (reference
    dccrg_mpi_support.hpp:282-377): the clique exchange sums every
    process's value, and the device-level reduce over device 0's halo
    peer group matches a single-process oracle.  The workers themselves
    assert the transport touched ONLY the named peers."""
    res = multi_proc_results[0]
    D = res["n_devices"]
    nproc = res["nproc"]
    assert res["some_reduce"]["clique"] == sum(10 ** p for p in range(nproc))

    from dccrg_tpu import Grid, make_mesh
    from dccrg_tpu.utils.collectives import some_reduce

    grid = (
        Grid()
        .set_initial_length((10, 10, 1))
        .set_maximum_refinement_level(0)
        .set_neighborhood_length(1)
        .set_load_balancing_method("RCB")
        .initialize(mesh=make_mesh(n_devices=D))
    )
    counts = np.asarray(
        [grid.get_local_cell_count(d) for d in range(D)], np.uint64
    )
    assert res["some_reduce"]["device0"] == int(some_reduce(grid, counts, 0))


def test_host_mutator_agreement_enforced(multi_proc_results):
    """VERDICT-r4 missing 4: user-neighborhood registration and builder
    settings are hash-compared over the collectives seam, not just
    documented.  The workers deliberately diverge (different offsets in
    add_neighborhood, different initial lengths in initialize) and every
    controller must observe the raise; the agreeing registration that
    follows must succeed."""
    for res in multi_proc_results:
        assert res["agreement"] == {
            "neighborhood": "raised",
            "initialize": "raised",
        }


def test_particles_across_controllers(multi_proc_results):
    """The particle device re-bucket (shard_map sort + psum loss
    accounting) spanning real controller processes must match a
    single-process run on an identically-sized mesh bit-for-bit."""
    import hashlib

    res = multi_proc_results[0]
    D = res["n_devices"]
    assert res["particles"]["count"] == 120

    from dccrg_tpu import CartesianGeometry, Grid, make_mesh
    from dccrg_tpu.models import Particles

    g = (
        Grid()
        .set_initial_length((4, 4, D))
        .set_neighborhood_length(1)
        .set_periodic(True, True, True)
        .set_maximum_refinement_level(1)
        .set_geometry(
            CartesianGeometry,
            start=(0.0, 0.0, 0.0),
            level_0_cell_length=(0.25, 0.25, 1.0 / D),
        )
        .initialize(mesh=make_mesh(n_devices=D))
    )
    assert g.refine_completely(int(g.get_cells()[0]))
    g.stop_refining()
    assert g.mapping.get_refinement_level(g.leaves.cells).max() == 1
    pic = Particles(g, max_particles_per_cell=64)
    rng = np.random.default_rng(42)
    pts = rng.uniform(0.0, 1.0, size=(120, 3))
    s = pic.new_state(pts)
    s = pic.run(s, 5, velocity=(0.03, 0.02, 0.11), dt=0.5)
    assert pic.count(s) == 120
    oracle = hashlib.sha256(
        np.ascontiguousarray(np.sort(pic.positions(s), axis=0).round(12))
        .tobytes()
    ).hexdigest()[:16]
    assert res["particles"]["pos_hash"] == oracle
