"""Worker process for the REAL multi-controller test.

Launched by ``tests/test_multiprocess.py`` as N separate OS processes,
each a JAX controller of its own block of CPU devices in one global
mesh (``jax.distributed.initialize`` + gloo CPU collectives).  This is
the deployment shape the reference reaches with one MPI rank per node
(``dccrg.hpp:7622-7687``): every controller holds the replicated leaf
directory, device collectives span the process boundary, and host
metadata reaches agreement through ``utils/collectives.py``.

Each scenario prints nothing; the end result is one ``RESULT {json}``
line the driver compares across processes and against a single-process
oracle.  Any cross-controller divergence shows up as a hash mismatch.
"""
import hashlib
import json
import os
import sys


def _hash(arr) -> str:
    import numpy as np

    return hashlib.sha256(np.ascontiguousarray(arr).tobytes()).hexdigest()[:16]


def main() -> None:
    pid, nproc, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
    dpp = int(sys.argv[4]) if len(sys.argv) > 4 else 4  # devices/process
    os.environ.setdefault("GLOO_SOCKET_IFNAME", "lo")
    import jax

    jax.config.update("jax_enable_x64", True)
    jax.distributed.initialize(
        coordinator_address=f"localhost:{port}",
        num_processes=nproc,
        process_id=pid,
    )
    import numpy as np

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    from dccrg_tpu import Grid, make_mesh
    from dccrg_tpu.models import GameOfLife
    from dccrg_tpu.utils.collectives import fetch, process_count
    from dccrg_tpu.utils.verify import verify_grid, verify_user_data

    assert process_count() == nproc
    assert len(jax.devices()) == dpp * nproc
    res = {"nproc": nproc, "n_devices": len(jax.devices())}

    # ---- scenario 1: game of life across the process boundary --------
    # (reference: examples/simple_game_of_life.cpp blinker assertion)
    grid = (
        Grid()
        .set_initial_length((10, 10, 1))
        .set_maximum_refinement_level(0)
        .set_neighborhood_length(1)
        .set_load_balancing_method("RCB")
        .initialize(mesh=make_mesh())
    )
    gol = GameOfLife(grid)
    state = gol.new_state(alive_cells=[54, 55, 56])
    blinker = []
    for _ in range(4):
        state = gol.step(state)
        blinker.append(sorted(int(c) for c in gol.alive_cells(state)))
    res["blinker"] = blinker

    # ---- scenario 2: AMR with per-controller disjoint requests -------
    # Each controller queues a different refine; stop_refining unions the
    # queues through the collectives seam before the deterministic commit
    # (the reference's cross-rank request exchange, dccrg.hpp:3461-3485).
    g2 = (
        Grid()
        .set_initial_length((4, 4, 2))
        .set_maximum_refinement_level(2)
        .set_neighborhood_length(1)
        .initialize(mesh=make_mesh())
    )
    spec = {"rho": ((), np.float64)}
    st2 = g2.new_state(spec)
    cells = g2.get_cells()
    st2 = g2.set_cell_data(st2, "rho", cells, np.arange(1.0, len(cells) + 1))
    mass0 = float(fetch(st2["rho"]).sum())
    # controller p refines cell (3 + p): different requests per process
    assert g2.refine_completely(3 + pid)
    g2.stop_refining()
    st2 = g2.remap_state(st2, policy={"rho": {"refine": "inherit"}})
    verify_grid(g2)
    ids = np.sort(g2.leaves.cells)
    # children inherit the parent value, so total over leaves grows by
    # 7x the refined parents' values — recompute expected on every
    # controller identically instead of asserting a magic number
    res["amr"] = {
        "n_leaves": int(len(ids)),
        "ids_hash": _hash(ids),
        "mass0": mass0,
        "mass1": float(
            (fetch(st2["rho"]) * g2.epoch.local_mask).sum()
        ),
    }

    # ---- scenario 3: ghost bit-identity over the wire ----------------
    rng = np.random.default_rng(7)
    st3 = g2.new_state(spec)
    st3 = g2.set_cell_data(
        st3, "rho", g2.get_cells(), rng.random(len(g2.get_cells()))
    )
    verify_user_data(g2, st3, spec)
    res["ghost"] = "ok"

    # ---- scenario 3b: per-device halo telemetry ----------------------
    # One explicit exchange; the obs counters' deltas must match the
    # epoch's pair tables on EVERY controller (the replicated-schedule
    # invariant), total send == total recv (every shipped cell lands),
    # and the recorded numbers go into the RESULT dict so the driver's
    # cross-rank equality check proves the telemetry itself is
    # symmetric across ranks — not just the final field values.
    from dccrg_tpu import obs

    D2 = g2.n_devices

    def dev_counters(name):
        return [
            int(obs.metrics.counter_value(name, device=d, hood="default"))
            for d in range(D2)
        ]

    send0, recv0 = dev_counters("halo.send_cells"), dev_counters("halo.recv_cells")
    bytes0 = int(obs.metrics.counter_value("halo.bytes_moved"))
    st3 = g2.update_copies_of_remote_neighbors(st3)
    dsend = [a - b for a, b in zip(dev_counters("halo.send_cells"), send0)]
    drecv = [a - b for a, b in zip(dev_counters("halo.recv_cells"), recv0)]
    dbytes = int(obs.metrics.counter_value("halo.bytes_moved")) - bytes0
    pair_counts = g2.epoch.hoods[None].pair_counts
    assert dsend == [int(v) for v in pair_counts.sum(axis=1)], dsend
    assert drecv == [int(v) for v in pair_counts.sum(axis=0)], drecv
    assert sum(dsend) == sum(drecv)
    assert dbytes == sum(dsend) * 8  # one f64 per cell
    res["telemetry"] = {
        "halo_send_cells": dsend,
        "halo_recv_cells": drecv,
        "halo_bytes_moved": dbytes,
    }

    # ---- scenario 4: balance_load with per-controller pins -----------
    # controller 0 pins the first leaf to the last device; every other
    # controller pins the last leaf to device 0 (identical duplicates —
    # merge-safe); sync_partition_inputs must merge the requests so all
    # controllers compute the same partition.
    first, last = int(ids[0]), int(ids[-1])
    if pid == 0:
        assert g2.pin(first, g2.n_devices - 1)
    else:
        assert g2.pin(last, 0)
    g2.balance_load()
    st2 = g2.remap_state(st2)
    verify_grid(g2)
    owners = g2.leaves.owner
    pos_first = int(g2.leaves.position(np.uint64(first)))
    pos_last = int(g2.leaves.position(np.uint64(last)))
    res["pins"] = {
        "owners_hash": _hash(np.asarray(owners, dtype=np.int64)),
        "first_owner": int(owners[pos_first]),
        "last_owner": int(owners[pos_last]),
        "mass2": float(
            (fetch(st2["rho"]) * g2.epoch.local_mask).sum()
        ),
    }

    # ---- scenario 5: checkpoint fan-in + reload across controllers --
    # save runs its collective readbacks on every controller but only
    # process 0 writes the file; both controllers then reload it and
    # must see the same grid + payloads as the live state.
    import tempfile

    ckpt = os.path.join(tempfile.gettempdir(), f"mp_ckpt_{port}.dc")
    from dccrg_tpu.io.checkpoint import load_grid_data, save_grid_data
    from dccrg_tpu.utils.collectives import barrier

    if pid == 0 and os.path.exists(ckpt):
        os.unlink(ckpt)  # a stale file must not mask a save regression
    save_grid_data(g2, st2, ckpt, spec, user_header=b"mp-test")
    g3, st3b, hdr = load_grid_data(ckpt, spec)
    assert hdr == b"mp-test"
    assert np.array_equal(np.sort(g3.leaves.cells), np.sort(g2.leaves.cells))
    live = g2.get_cell_data(st2, "rho", np.sort(g2.leaves.cells))
    reloaded = g3.get_cell_data(st3b, "rho", np.sort(g2.leaves.cells))
    assert np.array_equal(live, reloaded), "checkpoint round trip differs"
    res["ckpt"] = {"rho_hash": _hash(reloaded),
                   "file_exists": os.path.exists(ckpt)}
    barrier("ckpt_asserts_done")  # peers finish reading before cleanup
    if pid == 0:
        os.unlink(ckpt)

    # ---- scenario 6: flat sharded Poisson solve across controllers --
    # the gather-free voxel BiCG (ops/flat_poisson.py) with the voxel
    # arrays z-slab sharded over the PROCESS-SPANNING mesh: the matvec's
    # z-rolls become collective permutes over the wire and the BiCG dots
    # reduce across controllers.
    from dccrg_tpu import CartesianGeometry
    from dccrg_tpu.models import Poisson

    D = dpp * nproc
    n = D  # grid edge = device count: z-slabs divide evenly
    gp = (
        Grid()
        .set_initial_length((n, n, n))
        .set_neighborhood_length(0)
        .set_periodic(True, True, True)
        .set_geometry(
            CartesianGeometry,
            start=(0.0, 0.0, 0.0),
            level_0_cell_length=(1.0 / n,) * 3,
        )
        .initialize(mesh=make_mesh())
    )
    cells = np.sort(gp.leaves.cells)
    cen = gp.geometry.get_center(cells)
    rhs = np.sin(2 * np.pi * cen[:, 0]) * np.cos(2 * np.pi * cen[:, 1])
    pp = Poisson(gp)
    assert pp._flat is not None, "flat sharded path must engage"
    assert pp._flat_tables["n_devices"] == D
    sp = pp.initialize_state(rhs)
    op, rp, itp = pp.solve(sp, max_iterations=25, stop_residual=0.0,
                           stop_after_residual_increase=float("inf"))
    sol = np.asarray(gp.get_cell_data(op, "solution", cells), np.float64)
    res["poisson_flat"] = {
        "n_devices": D,
        "iterations": int(itp),
        "residual": float(rp),
        "solution": [float(v) for v in sol],
    }

    # ---- scenario 7: point-to-point Some_Reduce ----------------------
    # (reference dccrg_mpi_support.hpp:282-377: Isend/Irecv value
    # exchange among an explicit neighbor-process set — transport
    # parity, not just value parity)
    from dccrg_tpu.utils.collectives import (
        _P2PTransport, some_reduce, some_reduce_p2p,
    )

    # bootstrap is a global collective (the address-book allgather) —
    # reach it on every process before any neighbor-only exchange
    transport = _P2PTransport.get()

    # a strict PAIR exchange: processes 0 and 1 exchange; everyone else
    # stays out entirely — the transport must touch only the named peer
    pair_peer = {0: 1, 1: 0}.get(pid)
    if pair_peer is not None:
        v = some_reduce_p2p(np.uint64(5 + pid), [pair_peer])
        assert int(v) == (5 + pid) + (5 + pair_peer), v
        assert set(transport.sent_to) == {pair_peer}, transport.sent_to
        assert set(transport.received_from) == {pair_peer}
    else:
        v = some_reduce_p2p(np.uint64(7), [])     # empty set: identity
        assert int(v) == 7
        assert not transport.sent_to and not transport.received_from

    # the reference's symmetric clique: every process exchanges with all
    # others; each gets the full sum
    full = some_reduce_p2p(np.uint64(10 ** pid),
                           [p for p in range(nproc) if p != pid])
    assert int(full) == sum(10 ** p for p in range(nproc)), full

    # mismatched peer sets across consecutive exchanges: 1 and 2 run a
    # pair while 0 skips straight to the next clique — 0's early connect
    # must be stashed by the acceptor, not rejected (nproc >= 3 only)
    if nproc >= 3:
        if pid in (1, 2):
            v = some_reduce_p2p(np.uint64(pid), [3 - pid])
            assert int(v) == 3, v
        skew = some_reduce_p2p(np.uint64(pid),
                               [p for p in range(nproc) if p != pid])
        assert int(skew) == sum(range(nproc)), skew

    # payload far beyond kernel socket buffers: the threaded sends keep
    # a fully-connected clique deadlock-free
    big = np.full(200_000, float(pid + 1), np.float64)   # 1.6 MB
    big_sum = some_reduce_p2p(big, [p for p in range(nproc) if p != pid])
    assert big_sum.shape == big.shape
    assert float(big_sum[0]) == sum(range(1, nproc + 1))
    assert np.all(big_sum == big_sum[0])

    # device-level Some_Reduce on the gol grid: member processes carry
    # partials over the wire, the rest compute from replicated metadata
    n_dev = len(jax.devices())
    counts = np.asarray(
        [grid.get_local_cell_count(d) for d in range(n_dev)], np.uint64
    )
    sr = some_reduce(grid, counts, 0)
    res["some_reduce"] = {"device0": int(sr), "clique": int(full)}

    # ---- scenario 8: particles across the process boundary ----------
    # the device re-bucket's shard_map (per-device sort + psum loss
    # accounting) spans the controller processes; a refined grid engages
    # the generalized row-table path (reference particle migration
    # between ranks, tests/particles/simple.cpp:285-294)
    from dccrg_tpu import CartesianGeometry
    from dccrg_tpu.models import Particles

    gp2 = (
        Grid()
        .set_initial_length((4, 4, dpp * nproc))
        .set_neighborhood_length(1)
        .set_periodic(True, True, True)
        .set_maximum_refinement_level(1)
        .set_geometry(
            CartesianGeometry,
            start=(0.0, 0.0, 0.0),
            level_0_cell_length=(0.25, 0.25, 1.0 / (dpp * nproc)),
        )
        .initialize(mesh=make_mesh())
    )
    assert gp2.refine_completely(int(gp2.get_cells()[0]))
    gp2.stop_refining()
    assert gp2.mapping.get_refinement_level(gp2.leaves.cells).max() == 1
    pic = Particles(gp2, max_particles_per_cell=64)
    assert pic._dev_rebucket is not None, "device re-bucket must engage"
    rng = np.random.default_rng(42)   # same seed on every controller
    pts = rng.uniform(0.0, 1.0, size=(120, 3))
    sp2 = pic.new_state(pts)
    sp2 = pic.run(sp2, 5, velocity=(0.03, 0.02, 0.11), dt=0.5)
    assert pic.count(sp2) == 120, "particle conservation across processes"
    assert int(np.asarray(fetch(sp2["overflow"]))) == 0
    res["particles"] = {
        "count": pic.count(sp2),
        "pos_hash": _hash(np.sort(pic.positions(sp2), axis=0).round(12)),
    }

    # ---- scenario 9: enforced agreement for host mutators ------------
    # user-neighborhood registration and builder settings are checked
    # (hash-compared over the collectives seam), not just documented:
    # a deliberately diverging registration must raise on EVERY
    # controller, leaving no mutation behind; an agreeing one succeeds.
    try:
        grid.add_neighborhood(99, [(0, 0, 1)] if pid == 0 else [(0, 1, 0)])
        agreement_nbhood = "missed"
    except RuntimeError as e:
        agreement_nbhood = "raised" if "disagree" in str(e) else f"wrong:{e}"
    assert 99 not in grid.neighborhoods, "diverging hood must not register"
    assert grid.add_neighborhood(5, [(0, 1, 0)]), "agreeing hood must land"
    assert grid.remove_neighborhood(5)
    try:
        (Grid()
         .set_initial_length((4 + pid, 4, 1))     # diverging builder input
         .set_neighborhood_length(1)
         .initialize(mesh=make_mesh()))
        agreement_init = "missed"
    except RuntimeError as e:
        agreement_init = "raised" if "disagree" in str(e) else f"wrong:{e}"
    res["agreement"] = {"neighborhood": agreement_nbhood,
                       "initialize": agreement_init}

    print("RESULT " + json.dumps(res), flush=True)


if __name__ == "__main__":
    main()
