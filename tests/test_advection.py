"""Advection workload tests: mass conservation, device-count invariance,
agreement with a dense serial oracle (the reference validates with a serial
implementation for poisson; advection here gets the same treatment)."""
import numpy as np
import pytest

from dccrg_tpu import CartesianGeometry, Grid, make_mesh
from dccrg_tpu.models import Advection


def make_adv(n=20, n_dev=None, max_ref=0):
    g = (
        Grid()
        .set_initial_length((n, n, 1))
        .set_maximum_refinement_level(max_ref)
        .set_neighborhood_length(0)
        .set_periodic(True, True, False)
        .set_geometry(
            CartesianGeometry,
            start=(0.0, 0.0, 0.0),
            level_0_cell_length=(1.0 / n, 1.0 / n, 1.0 / n),
        )
        .initialize(mesh=make_mesh(n_devices=n_dev))
    )
    return g, Advection(g)


def dense_oracle_step(rho, vx, vy, dx, dt):
    """Dense periodic upwind step with the reference's flux form on a
    uniform 2-D grid (area = dx, volume = dx*dx in the z-thin limit all
    cells share the z length so it cancels)."""
    area = dx * dx  # face area with unit-per-cell z length dx
    vol = dx * dx * dx
    new = rho.copy()
    for axis, v in ((0, vx), (1, vy)):
        vface = 0.5 * (v + np.roll(v, -1, axis=axis))  # face between i and i+1
        up = np.where(vface >= 0, rho, np.roll(rho, -1, axis=axis))
        flux = up * dt * vface * area
        new -= flux / vol
        new += np.roll(flux, 1, axis=axis) / vol
    return new


def test_max_time_step():
    g, adv = make_adv(n=20)
    state = adv.initialize_state()
    dt = adv.max_time_step(state)
    # max |v| ~ 0.5*sqrt(2) near corners; dt = dx / max|v_dim| >= dx / 0.5
    assert 0 < dt < 1.0
    assert dt == pytest.approx((1.0 / 20) / max(abs(-0.025 + 0.5), 0.475), rel=0.2)


def test_mass_conservation():
    g, adv = make_adv(n=16)
    state = adv.initialize_state()
    m0 = adv.total_mass(state)
    dt = 0.5 * adv.max_time_step(state)
    for _ in range(20):
        state = adv.step(state, dt)
    m1 = adv.total_mass(state)
    assert m1 == pytest.approx(m0, rel=1e-12)


def test_matches_dense_oracle():
    n = 16
    g, adv = make_adv(n=n)
    state = adv.initialize_state()
    cells = g.get_cells()
    dx = 1.0 / n

    # dense arrays indexed [x, y]
    def to_dense(field):
        vals = adv.get_cell_data(state, field, cells)
        idx = g.mapping.get_indices(cells)
        dense = np.zeros((n, n))
        dense[idx[:, 0], idx[:, 1]] = vals
        return dense

    rho = to_dense("density")
    vx = to_dense("vx")
    vy = to_dense("vy")

    dt = 0.25 * adv.max_time_step(state)
    for _ in range(5):
        state = adv.step(state, dt)
        rho = dense_oracle_step(rho, vx, vy, dx, dt)

    got = adv.get_cell_data(state, "density", cells)
    idx = g.mapping.get_indices(cells)
    np.testing.assert_allclose(got, rho[idx[:, 0], idx[:, 1]], rtol=1e-12, atol=1e-15)


def test_device_count_invariance():
    """Results must be independent of the device count.  The neighbor
    reduction order is fixed (ordered_sum) so the only residual source of
    difference is XLA choosing different FMA contractions for different
    block shapes — ulp-level, bounded here at 1e-13 relative.  Halo copies
    themselves are bit-identical (test_grid_halo), and a fixed device count
    is fully deterministic (asserted below)."""
    results = []
    for n_dev in (1, 4, 8):
        g, adv = make_adv(n=12, n_dev=n_dev)
        state = adv.initialize_state()
        dt = 0.5 * adv.max_time_step(state)
        for _ in range(10):
            state = adv.step(state, dt)
        results.append(adv.get_cell_data(state, "density", g.get_cells()))
    np.testing.assert_allclose(results[0], results[1], rtol=1e-13, atol=1e-16)
    np.testing.assert_allclose(results[0], results[2], rtol=1e-13, atol=1e-16)

    # same device count, fresh build: bit-identical
    g2, adv2 = make_adv(n=12, n_dev=4)
    state = adv2.initialize_state()
    dt = 0.5 * adv2.max_time_step(state)
    for _ in range(10):
        state = adv2.step(state, dt)
    again = g2.get_cell_data(state, "density", g2.get_cells())
    np.testing.assert_array_equal(again, results[1])


def test_hump_rotates():
    n = 24
    g, adv = make_adv(n=n)
    state = adv.initialize_state()
    # the reference's default CFL is 0.5 (2d.cpp:124-126); 0.9 is unstable
    # for the dimension-split first-order upwind scheme
    dt = 0.45 * adv.max_time_step(state)
    # rotate ~90 degrees: t = pi/2
    steps = int(np.ceil((np.pi / 2) / dt))
    for _ in range(steps):
        state = adv.step(state, dt)
    cells = g.get_cells()
    rho = adv.get_cell_data(state, "density", cells)
    centers = g.geometry.get_center(cells)
    peak = centers[np.argmax(rho)]
    # hump starts at (0.25, 0.5); after quarter turn about (0.5, 0.5) it
    # should be near (0.5, 0.25) (numerical diffusion allows slack)
    assert abs(peak[0] - 0.5) < 0.15
    assert abs(peak[1] - 0.25) < 0.15


def test_max_diff_indicator():
    g, adv = make_adv(n=16)
    state = adv.initialize_state()
    state = adv.compute_max_diff(state, diff_threshold=0.025)
    md = adv.get_cell_data(state, "max_diff", g.get_cells())
    assert (md >= 0).all()
    # steep hump edge -> some large indicators; far field flat -> zeros
    assert md.max() > 1.0
    assert (md < 1e-12).sum() > len(md) / 4
