"""Multi-level (3+ level) flat AMR advection — the VERDICT-r4 extension
of the flat fast path beyond levels {0, 1} (reference AMR allows 21
levels, ``dccrg_mapping.hpp:316-329``).  The multi-level form inflates
every leaf onto finest-level voxels and runs the whole multi-step loop
as rolls/multiplies/adds with a hierarchical pool/broadcast for the
coarse updates; these tests pin it against the general gather path
(reference ``solve.hpp`` semantics) in f64."""
import numpy as np
import pytest

from dccrg_tpu import CartesianGeometry, Grid, make_mesh
from dccrg_tpu.models import Advection


def ball_grid(n_dev, n=8, levels=2, periodic=(True, True, True),
              cell_length=None):
    # non-power-of-two default cell lengths: the ml volume tables must
    # carry f64 inverse volumes into an f64 run (f32-quantized tables
    # would pass only for power-of-two cell sizes)
    cl = cell_length if cell_length is not None else (
        0.1, 0.07, 0.13,
    )
    g = (
        Grid()
        .set_initial_length((n, n, n))
        .set_neighborhood_length(0)
        .set_periodic(*periodic)
        .set_maximum_refinement_level(levels)
        .set_geometry(
            CartesianGeometry,
            start=(0.0, 0.0, 0.0),
            level_0_cell_length=cl,
        )
        .initialize(mesh=make_mesh(n_devices=n_dev))
    )
    for rad in (0.3, 0.15):
        ids = g.get_cells()
        c = g.geometry.get_center(ids)
        r = np.linalg.norm(c - 0.5, axis=1)
        lv = g.mapping.get_refinement_level(ids)
        for cid in ids[(r < rad) & (lv == lv.max())]:
            g.refine_completely(int(cid))
        g.stop_refining()
    lv = g.mapping.get_refinement_level(g.get_cells())
    assert lv.max() == 2, "test grid must span 3 levels"
    return g


@pytest.mark.parametrize("n_dev", [1, 8])
def test_ml_flat_matches_general_path(n_dev):
    g = ball_grid(n_dev)
    ids = np.sort(g.leaves.cells)
    adv_ml = Advection(g, dtype=np.float64)
    assert adv_ml._flat_kind == "ml", "3-level grid must engage the ml path"
    adv_gen = Advection(g, dtype=np.float64, use_pallas=False,
                        allow_boxed=False)
    s_ml = adv_ml.initialize_state()
    s = adv_gen.initialize_state()
    dt = 0.3 * adv_gen.max_time_step(s)
    steps = 10
    out = adv_ml._flat_run(s_ml, steps, dt)
    for _ in range(steps):
        s = adv_gen.step(s, dt)
    a = np.asarray(g.get_cell_data(out, "density", ids), np.float64)
    b = np.asarray(g.get_cell_data(s, "density", ids), np.float64)
    err = np.abs(a - b).max() / np.abs(b).max()
    assert err < 1e-11, err
    # mass conservation (periodic domain): exact up to f64 rounding
    vol = np.prod(g.geometry.get_length(ids), axis=-1)
    np.testing.assert_allclose((a * vol).sum(), (b * vol).sum(), rtol=1e-12)


def test_ml_flat_nonperiodic_boundaries():
    g = ball_grid(1, periodic=(False, False, False))
    ids = np.sort(g.leaves.cells)
    adv_ml = Advection(g, dtype=np.float64)
    assert adv_ml._flat_kind == "ml"
    adv_gen = Advection(g, dtype=np.float64, use_pallas=False,
                        allow_boxed=False)
    rng = np.random.default_rng(0)
    s_ml = adv_ml.initialize_state()
    s = adv_gen.initialize_state()
    rho = rng.uniform(1.0, 2.0, len(ids))
    s_ml = adv_ml.set_cell_data(s_ml, "density", ids, rho)
    s = adv_gen.set_cell_data(s, "density", ids, rho)
    s = g.update_copies_of_remote_neighbors(s)
    dt = 0.3 * adv_gen.max_time_step(s)
    steps = 8
    out = adv_ml._flat_run(s_ml, steps, dt)
    for _ in range(steps):
        s = adv_gen.step(s, dt)
    a = np.asarray(g.get_cell_data(out, "density", ids), np.float64)
    b = np.asarray(g.get_cell_data(s, "density", ids), np.float64)
    assert np.abs(a - b).max() / np.abs(b).max() < 1e-11


def test_ml_pallas_kernel_matches_general_path():
    """The VMEM-resident multi-level Pallas kernel (interpret mode on
    CPU) must agree with the general gather path — the hierarchical
    roll-chain capture/broadcast vs the reference semantics."""
    g = ball_grid(1)
    ids = np.sort(g.leaves.cells)
    adv_k = Advection(g, dtype=np.float32, use_pallas="interpret")
    assert adv_k._flat_kind == "ml_pallas_interpret", adv_k._flat_kind
    adv_gen = Advection(g, dtype=np.float32, use_pallas=False,
                        allow_boxed=False)
    s_k = adv_k.initialize_state()
    s = adv_gen.initialize_state()
    dt = np.float32(0.3 * adv_gen.max_time_step(s))
    steps = 6
    out = adv_k._flat_run(s_k, steps, dt)
    for _ in range(steps):
        s = adv_gen.step(s, dt)
    a = np.asarray(g.get_cell_data(out, "density", ids), np.float64)
    b = np.asarray(g.get_cell_data(s, "density", ids), np.float64)
    assert np.abs(a - b).max() / np.abs(b).max() < 5e-6


def test_two_level_grids_keep_the_tuned_paths():
    """Levels {0, 1} must still dispatch to the existing 2-level flat
    forms (Pallas kernel / sharded XLA), not the ml generalization."""
    n = 8
    g = (
        Grid()
        .set_initial_length((n, n, n))
        .set_neighborhood_length(0)
        .set_periodic(True, True, True)
        .set_maximum_refinement_level(1)
        .set_geometry(
            CartesianGeometry,
            start=(0.0, 0.0, 0.0),
            level_0_cell_length=(1.0 / n,) * 3,
        )
        .initialize(mesh=make_mesh(n_devices=8))
    )
    g.refine_completely(1)
    g.stop_refining()
    adv = Advection(g, dtype=np.float32)
    assert adv._flat_kind != "ml"


def test_ml_run_dispatch_and_fallback_shape():
    """run() routes a 3-level grid through the flat ml form (or boxed by
    the cost edge) and produces the same physics as step()-stepping."""
    g = ball_grid(1, n=6)
    ids = np.sort(g.leaves.cells)
    adv = Advection(g, dtype=np.float64)
    s = adv.initialize_state()
    dt = 0.3 * adv.max_time_step(s)
    out = adv.run(s, 6, dt)
    s2 = s
    adv_gen = Advection(g, dtype=np.float64, use_pallas=False,
                        allow_boxed=False)
    for _ in range(6):
        s2 = adv_gen.step(s2, dt)
    a = np.asarray(g.get_cell_data(out, "density", ids), np.float64)
    b = np.asarray(g.get_cell_data(s2, "density", ids), np.float64)
    assert np.abs(a - b).max() / np.abs(b).max() < 5e-11
