"""dccrg-lint: per-rule positive/negative fixtures, baseline round-trip,
the whole-repo CI gate, the registry thread-race stress test (the
dynamic oracle behind LOCK-DISCIPLINE), the stdlib-only subprocess
import probe, and the zero-retrace-under-x64 regression for the
DTYPE-PROMOTE fixes.

The linter is stdlib-only and file-loaded here (not imported through a
package) — exactly the loading contract it polices.
"""
import importlib.util
import json
import pathlib
import re
import subprocess
import sys
import textwrap
import threading

import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent
LINT_PATH = REPO / "tools" / "dccrg_lint.py"


def _load_lint():
    spec = importlib.util.spec_from_file_location("dccrg_lint", LINT_PATH)
    m = importlib.util.module_from_spec(spec)
    sys.modules["dccrg_lint"] = m
    spec.loader.exec_module(m)
    return m


lint = _load_lint()


def run_rules(root, files, rules, baseline=()):
    """Materialize fixture `files` under `root` and lint them."""
    for rel, src in files.items():
        p = root / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    active, suppressed, stale, errors = lint.run_lint(
        root, rules=rules, baseline_entries=list(baseline))
    return active, suppressed, stale, errors


# ------------------------------------------------------- DTYPE-PROMOTE

DTYPE_BAD = """
    import jax.numpy as jnp

    def reduce(x):
        return jnp.sum(x) + jnp.arange(4)[0]
"""
DTYPE_GOOD = """
    import jax.numpy as jnp

    def reduce(x):
        return (jnp.sum(x, dtype=jnp.int32)
                + jnp.arange(4, dtype=jnp.int32)[0])
"""


def test_dtype_promote_fires_and_clears(tmp_path):
    active, _, _, errors = run_rules(
        tmp_path, {"dccrg_tpu/models/fix.py": DTYPE_BAD},
        [lint.DtypePromote])
    assert not errors
    assert sorted(f.site for f in active) == ["reduce:arange#0",
                                              "reduce:sum#0"]
    active, _, _, _ = run_rules(
        tmp_path, {"dccrg_tpu/models/fix.py": DTYPE_GOOD},
        [lint.DtypePromote])
    assert active == []


def test_dtype_promote_ignores_untraced_scope(tmp_path):
    # same violation outside models/parallel/serve stays silent
    active, _, _, _ = run_rules(
        tmp_path, {"dccrg_tpu/utils/free.py": DTYPE_BAD},
        [lint.DtypePromote])
    assert active == []


def test_unpinning_gol_dtype_fails_the_gate(tmp_path):
    """Acceptance check: stripping the PR 9 dtype pins out of the real
    game_of_life.py makes the rule fire on the copy."""
    src = (REPO / "dccrg_tpu/models/game_of_life.py").read_text()
    assert "dtype=jnp.uint32" in src
    unpinned = re.sub(r",\s*dtype=jnp\.uint32", "", src)
    active, _, _, _ = run_rules(
        tmp_path, {"dccrg_tpu/models/game_of_life.py": unpinned},
        [lint.DtypePromote])
    assert any(f.site.endswith(":sum#0") for f in active)


# --------------------------------------------------- CLOSED-OVER-TABLE

CLOSURE_BAD = """
    import jax

    def make(tables, mesh, put_table):
        statics = tuple(put_table(tables[k], mesh) for k in ("a",))

        @jax.jit
        def run_fn(state):
            return state + statics[0]

        return run_fn
"""
CLOSURE_GOOD = """
    import jax

    def make(tables, mesh, put_table):
        statics = tuple(put_table(tables[k], mesh) for k in ("a",))

        @jax.jit
        def run_fn(statics, state):
            return state + statics[0]

        return lambda state: run_fn(statics, state)
"""
SELF_READ_BAD = """
    import jax

    class Model:
        def __init__(self, tables, mesh, put_table):
            self._rows = put_table(tables["rows"], mesh)

        @jax.jit
        def step(self, state):
            return state + self._rows
"""


def test_closed_over_table_fires_and_clears(tmp_path):
    active, _, _, _ = run_rules(
        tmp_path, {"dccrg_tpu/ops/fx.py": CLOSURE_BAD},
        [lint.ClosedOverTable])
    assert [f.site for f in active] == ["make.run_fn:statics"]
    active, _, _, _ = run_rules(
        tmp_path, {"dccrg_tpu/ops/fx.py": CLOSURE_GOOD},
        [lint.ClosedOverTable])
    assert active == []


def test_closed_over_table_self_read(tmp_path):
    active, _, _, _ = run_rules(
        tmp_path, {"dccrg_tpu/ops/fy.py": SELF_READ_BAD},
        [lint.ClosedOverTable])
    assert [f.site for f in active] == ["Model.step:self._rows"]


def test_traced_jit_callsite_resolves_lexically(tmp_path):
    # a module-level function sharing the inner function's name must
    # not be conflated with the jitted one (the gol `step` shape)
    src = """
        import jax

        def build(tables, mesh, put_table):
            tabs = put_table(tables["t"], mesh)

            def step(tabs, state):
                return state + tabs

            fn = jax.jit(step)

            def outer_step(state):
                return fn(tabs, state)   # un-jitted wrapper: fine

            return outer_step

        def step(state):
            return state
    """
    active, _, _, _ = run_rules(
        tmp_path, {"dccrg_tpu/ops/fz.py": src}, [lint.ClosedOverTable])
    assert active == []


# ------------------------------------------------------------ HOST-SYNC

HOT_ENSEMBLE_BAD = """
    import numpy as np

    class Cohort:
        def step(self):
            return np.asarray(self._state)

    class Scheduler:
        def step_once(self):
            pass

        def run(self):
            pass
"""
HOT_HALO_OK = """
    class HaloExchange:
        def __call__(self, state):
            return self._dispatch(state)

        def _dispatch(self, state):
            return state

        def start(self, state):
            return self._start_dispatch(state)

        def _start_dispatch(self, state):
            return state

        def finish(self, state, handle):
            return self._finish_dispatch(state, handle)

        def _finish_dispatch(self, state, handle):
            return state
"""


def test_host_sync_fires_and_clears(tmp_path):
    files = {"dccrg_tpu/serve/ensemble.py": HOT_ENSEMBLE_BAD,
             "dccrg_tpu/parallel/halo.py": HOT_HALO_OK}
    active, _, _, errors = run_rules(tmp_path, files, [lint.HostSync])
    assert not errors
    assert [f.site for f in active] == ["Cohort.step:np.asarray"]
    files["dccrg_tpu/serve/ensemble.py"] = HOT_ENSEMBLE_BAD.replace(
        "np.asarray(self._state)", "self._state")
    active, _, _, _ = run_rules(tmp_path, files, [lint.HostSync])
    assert active == []


# ---------------------------------------------------------- STDLIB-ONLY

def test_stdlib_only_fires_and_clears(tmp_path):
    active, _, _, _ = run_rules(
        tmp_path, {"tools/myreport.py": "import jax\n"},
        [lint.StdlibOnly])
    assert [f.site for f in active] == ["import:jax"]
    # lazy (function-level) import is the sanctioned escape hatch
    active, _, _, _ = run_rules(
        tmp_path,
        {"tools/myreport.py": "import json\n\ndef f():\n    import jax\n"},
        [lint.StdlibOnly])
    assert active == []


def test_stdlib_only_probe_slo_and_report():
    for rel in ("dccrg_tpu/obs/slo.py", "tools/slo_report.py"):
        err = lint.StdlibOnly.probe(REPO, rel)
        assert err is None, f"{rel}: {err}"


# ------------------------------------------------------ TELEMETRY-DRIFT

GATE_STUBS = {
    "tools/check_telemetry.py": """
        REQUIRED_PHASES = ("epoch.build",)
        REQUIRED_NONZERO_COUNTERS = ("halo.bytes_moved",)
        REQUIRED_HISTOGRAMS = ()
    """,
    "tools/telemetry_diff.py": """
        DEFAULT_PHASES = ("epoch.build",)
        GATED_COUNTERS = ()
        DEFAULT_ALLOW = ()
        GATED_GAUGES_MIN = ()
        GATED_GAUGES_MAX = ()
        GATED_QUANTILES = ()
    """,
}
# flush-left: fixture variants append unindented lines, and dedent on
# the concatenation must stay a no-op
RECORDER_OK = """\
from .registry import metrics

def work():
    with metrics.phase("epoch.build"):
        metrics.inc("halo.bytes_moved", 8)
"""


def test_telemetry_drift_aligned_sets_pass(tmp_path):
    files = dict(GATE_STUBS)
    files["dccrg_tpu/obs/code.py"] = RECORDER_OK
    active, _, _, errors = run_rules(tmp_path, files,
                                     [lint.TelemetryDrift])
    assert not errors and active == []


def test_telemetry_drift_recorded_but_never_gated(tmp_path):
    files = dict(GATE_STUBS)
    files["dccrg_tpu/obs/code.py"] = RECORDER_OK + (
        "\n\ndef rogue():\n"
        "    metrics.phase_add(\"rogue.phase\", 0.1)\n")
    active, _, _, _ = run_rules(tmp_path, files, [lint.TelemetryDrift])
    assert [f.site for f in active] == ["recorded:phase:rogue.phase"]


def test_telemetry_drift_gated_but_never_recorded(tmp_path):
    files = dict(GATE_STUBS)
    files["tools/check_telemetry.py"] = GATE_STUBS[
        "tools/check_telemetry.py"].replace(
        '("halo.bytes_moved",)', '("halo.bytes_moved", "ghost.series")')
    files["dccrg_tpu/obs/code.py"] = RECORDER_OK
    active, _, _, _ = run_rules(tmp_path, files, [lint.TelemetryDrift])
    assert [f.site for f in active] == ["gate:counter:ghost.series"]


# ------------------------------------------------------ LOCK-DISCIPLINE

LOCK_BAD = """
    import threading

    class Reg:
        def __init__(self):
            self._lock = threading.Lock()
            self._counters: dict = {}

        def inc(self, key):
            self._counters[key] = self._counters.get(key, 0) + 1
"""
LOCK_GOOD = """
    import threading

    class Reg:
        def __init__(self):
            self._lock = threading.Lock()
            self._counters: dict = {}

        def inc(self, key):
            with self._lock:
                self._counters[key] = self._counters.get(key, 0) + 1
"""


def test_lock_discipline_fires_and_clears(tmp_path):
    active, _, _, _ = run_rules(
        tmp_path, {"dccrg_tpu/obs/reg.py": LOCK_BAD},
        [lint.LockDiscipline])
    assert [f.site for f in active] == ["Reg.inc:_counters"]
    active, _, _, _ = run_rules(
        tmp_path, {"dccrg_tpu/obs/reg.py": LOCK_GOOD},
        [lint.LockDiscipline])
    assert active == []


def test_registry_thread_race_exact_totals():
    """Dynamic oracle for LOCK-DISCIPLINE: N threads hammer one
    registry; every recorded series must land exactly (a lost update
    anywhere under-counts)."""
    from dccrg_tpu.obs.registry import MetricsRegistry

    reg = MetricsRegistry(enabled=True)
    n_threads, n_iter = 8, 2000
    barrier = threading.Barrier(n_threads)

    def hammer(tid):
        barrier.wait()
        for i in range(n_iter):
            reg.inc("race.counter")
            reg.inc("race.labeled", 2, worker=str(tid % 2))
            reg.observe("race.hist", 1.5)
            reg.phase_add("race.phase", 0.001)
            reg.gauge("race.gauge", i)
            if i % 64 == 0:
                # resolution rewrites race against recorders
                reg.set_histogram_resolution("race.other", 2 + (i % 3))

    threads = [threading.Thread(target=hammer, args=(t,))
               for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    rep = reg.report()
    total = n_threads * n_iter
    assert rep["counters"]["race.counter"][""] == total
    assert sum(rep["counters"]["race.labeled"].values()) == 2 * total
    h = rep["histograms"]["race.hist"][""]
    assert h["count"] == total
    assert h["sum"] == pytest.approx(1.5 * total)
    assert sum(h["buckets"].values()) == total
    p = rep["phases"]["race.phase"]
    assert p["count"] == total
    # lost updates would under-count; the float total is rounded by
    # report(), so exactness is asserted on counts and approx on time
    assert p["total_s"] == pytest.approx(total * 0.001, rel=1e-3)


# ------------------------------------------------------------ ENV-DRIFT

def test_env_drift_fires_and_clears(tmp_path):
    files = {
        "dccrg_tpu/knob.py":
            "import os\nV = os.environ.get(\"DCCRG_NEW_KNOB\", \"1\")\n",
        "README.md": "| `DCCRG_GONE` | `0` | stale row |\n",
    }
    active, _, _, _ = run_rules(tmp_path, files, [lint.EnvDrift])
    assert sorted(f.site for f in active) == [
        "dead:DCCRG_GONE", "undocumented:DCCRG_NEW_KNOB"]
    files["README.md"] = "| `DCCRG_NEW_KNOB` | `1` | documented |\n"
    active, _, _, _ = run_rules(tmp_path, files, [lint.EnvDrift])
    assert active == []


# ------------------------------------------------------------- baseline

def test_baseline_suppress_and_expire(tmp_path):
    files = {"dccrg_tpu/obs/reg.py": LOCK_BAD}
    active, _, _, _ = run_rules(tmp_path, files, [lint.LockDiscipline])
    assert len(active) == 1
    entries = [{"rule": f.rule, "path": f.path, "site": f.site,
                "reason": "test"} for f in active]
    # suppressed: the same finding no longer surfaces
    active, suppressed, stale, _ = run_rules(
        tmp_path, files, [lint.LockDiscipline], baseline=entries)
    assert active == [] and len(suppressed) == 1 and stale == []
    # fixed source: the entry goes stale (baselines may only shrink)
    files["dccrg_tpu/obs/reg.py"] = LOCK_GOOD
    active, suppressed, stale, _ = run_rules(
        tmp_path, files, [lint.LockDiscipline], baseline=entries)
    assert active == [] and suppressed == [] and stale == entries


# ----------------------------------------------------------- CI gate

def test_repo_is_lint_clean():
    """The tier-1-visible gate: `dccrg_lint --json` must exit 0 on the
    repo, with a baseline holding only the documented ROADMAP item-4
    closed-over-table entries."""
    r = subprocess.run(
        [sys.executable, str(LINT_PATH), "--json"],
        capture_output=True, text=True, cwd=str(REPO), timeout=300)
    report = json.loads(r.stdout)
    assert r.returncode == 0, json.dumps(report, indent=2)
    assert report["findings"] == []
    assert report["stale_baseline"] == []
    assert report["errors"] == []
    baseline = json.loads((REPO / "tools/lint_baseline.json").read_text())
    rules = {e["rule"] for e in baseline["entries"]}
    assert rules == {"closed-over-table"}
    assert all("ROADMAP item 4" in e["reason"]
               for e in baseline["entries"])


# ------------------------------------- dtype regression (zero retrace)

def test_particles_zero_retrace_after_dtype_pins():
    """The pinned arange/sum sites must not re-key the particle kernels
    under x64 (conftest enables x64 globally): after the first step's
    traces, further dispatches at a held signature retrace nothing."""
    import numpy as np

    from dccrg_tpu import CartesianGeometry, Grid, make_mesh
    from dccrg_tpu.models.particles import Particles
    from dccrg_tpu.parallel.exec_cache import trace_counts

    n = np.asarray((8, 8, 1))
    g = (
        Grid()
        .set_initial_length((8, 8, 1))
        .set_maximum_refinement_level(0)
        .set_neighborhood_length(1)
        .set_periodic(True, True, False)
        .set_geometry(CartesianGeometry, start=(0.0, 0.0, 0.0),
                      level_0_cell_length=tuple(1.0 / n))
        .initialize(mesh=make_mesh(n_devices=None))
    )
    p = Particles(g)
    state = p.new_state(np.array([[0.05, 0.5, 0.5], [0.55, 0.25, 0.5]]))
    # two warmup steps: the first dispatch re-buckets the fresh state,
    # which re-keys once (pre-existing, signature-driven — verified
    # identical before the dtype pins)
    for _ in range(2):
        state = p.step(state, velocity=(0.1, 0.0, 0.0), dt=1.0)
    base = trace_counts()
    for _ in range(3):
        state = p.step(state, velocity=(0.1, 0.0, 0.0), dt=1.0)
    fresh = {k: v - base.get(k, 0) for k, v in trace_counts().items()
             if v != base.get(k, 0)}
    assert not fresh, f"unexpected retrace at held signature: {fresh}"
    assert p.count(state) == 2
