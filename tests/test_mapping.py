"""Property tests for the cell-id algebra against a brute-force oracle.

The oracle below re-derives the reference's id scheme from its definition
(level blocks, x-fastest ordering — reference dccrg_mapping.hpp:153-289)
with plain Python ints, and the vectorized implementation must agree on
every valid id of several small grids (and on invalid inputs' sentinels).
"""
import numpy as np
import pytest

from dccrg_tpu.core import ERROR_CELL, ERROR_INDEX, Mapping


def oracle_level_offset(length, lvl):
    n = length[0] * length[1] * length[2]
    return 1 + sum(n * 8**i for i in range(lvl))


def oracle_refinement_level(length, max_ref, cell):
    if cell == 0:
        return -1
    last = 0
    for lvl in range(max_ref + 1):
        last += length[0] * length[1] * length[2] * 8**lvl
        if cell <= last:
            return lvl
    return -1


def oracle_indices(length, max_ref, cell):
    lvl = oracle_refinement_level(length, max_ref, cell)
    if lvl < 0:
        return (int(ERROR_INDEX),) * 3
    local = cell - oracle_level_offset(length, lvl)
    lx = length[0] * 2**lvl
    ly = length[1] * 2**lvl
    scale = 2 ** (max_ref - lvl)
    return (
        (local % lx) * scale,
        ((local // lx) % ly) * scale,
        (local // (lx * ly)) * scale,
    )


def oracle_cell_from_indices(length, max_ref, ind, lvl):
    nx = length[0] * 2**max_ref
    ny = length[1] * 2**max_ref
    nz = length[2] * 2**max_ref
    if not (0 <= ind[0] < nx and 0 <= ind[1] < ny and 0 <= ind[2] < nz):
        return 0
    if not (0 <= lvl <= max_ref):
        return 0
    scale = 2 ** (max_ref - lvl)
    ix, iy, iz = ind[0] // scale, ind[1] // scale, ind[2] // scale
    lx = length[0] * 2**lvl
    ly = length[1] * 2**lvl
    return oracle_level_offset(length, lvl) + ix + iy * lx + iz * lx * ly


GRIDS = [
    ((1, 1, 1), 0),
    ((1, 1, 1), 2),
    ((3, 2, 1), 1),
    ((2, 3, 4), 2),
    ((5, 1, 7), 1),
]


@pytest.mark.parametrize("length,max_ref", GRIDS)
def test_roundtrip_all_cells(length, max_ref):
    m = Mapping(length=length, max_refinement_level=max_ref)
    n_total = sum(
        length[0] * length[1] * length[2] * 8**l for l in range(max_ref + 1)
    )
    assert int(m.last_cell) == n_total

    cells = np.arange(1, n_total + 1, dtype=np.uint64)
    lvls = m.get_refinement_level(cells)
    inds = m.get_indices(cells)
    back = m.get_cell_from_indices(inds, lvls)
    np.testing.assert_array_equal(back, cells)

    # spot-check levels and indices against the oracle
    rng = np.random.default_rng(42)
    sample = rng.choice(n_total, size=min(200, n_total), replace=False)
    for s in sample:
        cell = int(cells[s])
        assert int(lvls[s]) == oracle_refinement_level(length, max_ref, cell)
        assert tuple(int(v) for v in inds[s]) == oracle_indices(length, max_ref, cell)


@pytest.mark.parametrize("length,max_ref", GRIDS)
def test_cell_from_indices_matches_oracle(length, max_ref):
    m = Mapping(length=length, max_refinement_level=max_ref)
    rng = np.random.default_rng(7)
    nx, ny, nz = m.length_in_indices
    for _ in range(100):
        ind = (rng.integers(0, nx), rng.integers(0, ny), rng.integers(0, nz))
        lvl = int(rng.integers(0, max_ref + 1))
        got = m.get_cell_from_indices(np.array(ind, dtype=np.uint64), lvl)
        assert int(got) == oracle_cell_from_indices(length, max_ref, ind, lvl)


@pytest.mark.parametrize("length,max_ref", GRIDS)
def test_scalar_fast_paths_match_vectorized(length, max_ref):
    """refinement_level_of/siblings_of/parent_of agree with the vectorized
    tree ops for every valid cell id and for invalid ids."""
    m = Mapping(length=length, max_refinement_level=max_ref)
    all_cells = np.arange(1, int(m.last_cell) + 1, dtype=np.uint64)
    lvl_vec = m.get_refinement_level(all_cells)
    sib_vec = m.get_siblings(all_cells)
    par_vec = m.get_parent(all_cells)
    for i, c in enumerate(all_cells.tolist()):
        assert m.refinement_level_of(c) == lvl_vec[i]
        assert m.siblings_of(c) == sib_vec[i].tolist()
        assert m.parent_of(c) == par_vec[i]
    for bad in (0, int(m.last_cell) + 1, 2**63):
        assert m.refinement_level_of(bad) == -1
        assert m.parent_of(bad) == 0
        assert m.siblings_of(bad) == [0] * 8


def test_invalid_inputs_yield_sentinels():
    m = Mapping(length=(2, 2, 2), max_refinement_level=1)
    last = int(m.last_cell)
    bad = np.array([0, last + 1, last + 100], dtype=np.uint64)
    assert (m.get_refinement_level(bad) == -1).all()
    assert (m.get_indices(bad) == ERROR_INDEX).all()
    assert (m.get_parent(bad) == ERROR_CELL).all()
    # out-of-range indices
    nx, ny, nz = m.length_in_indices
    assert int(m.get_cell_from_indices(np.array([nx, 0, 0], dtype=np.uint64), 0)) == 0
    # bad level
    assert int(m.get_cell_from_indices(np.array([0, 0, 0], dtype=np.uint64), 2)) == 0


def test_parent_child_relations():
    m = Mapping(length=(2, 2, 2), max_refinement_level=2)
    cells = np.arange(1, int(m.last_cell) + 1, dtype=np.uint64)
    lvls = m.get_refinement_level(cells)

    # level-0 cells are their own parent
    lvl0 = cells[lvls == 0]
    np.testing.assert_array_equal(m.get_parent(lvl0), lvl0)

    # children of non-max cells: 8 distinct, one level finer, parent maps back
    refinable = cells[lvls < m.max_refinement_level]
    ch = m.get_all_children(refinable)
    assert ch.shape == (len(refinable), 8)
    assert (ch != ERROR_CELL).all()
    assert (m.get_refinement_level(ch) == (m.get_refinement_level(refinable)[:, None] + 1)).all()
    parents = m.get_parent(ch)
    np.testing.assert_array_equal(parents, np.broadcast_to(refinable[:, None], ch.shape))
    # children distinct within a family
    assert all(len(set(row.tolist())) == 8 for row in ch)

    # max-level cells have no children
    at_max = cells[lvls == m.max_refinement_level]
    assert (m.get_all_children(at_max) == ERROR_CELL).all()

    # get_child = first child; at max level returns the cell itself
    first = m.get_child(refinable)
    np.testing.assert_array_equal(first, ch[:, 0])
    np.testing.assert_array_equal(m.get_child(at_max), at_max)

    # siblings: all children of parent, cell is a member
    finer = cells[lvls > 0]
    sib = m.get_siblings(finer)
    assert ((sib == finer[:, None]).sum(axis=1) == 1).all()

    # level-0 siblings: just the cell
    sib0 = m.get_siblings(lvl0)
    np.testing.assert_array_equal(sib0[:, 0], lvl0)
    assert (sib0[:, 1:] == ERROR_CELL).all()

    # level-0 parent
    np.testing.assert_array_equal(
        m.get_refinement_level(m.get_level_0_parent(cells)),
        np.zeros(len(cells), dtype=np.int64),
    )


def test_cell_length_in_indices():
    m = Mapping(length=(2, 1, 1), max_refinement_level=2)
    cells = np.arange(1, int(m.last_cell) + 1, dtype=np.uint64)
    lvls = m.get_refinement_level(cells)
    lens = m.get_cell_length_in_indices(cells)
    np.testing.assert_array_equal(lens, (1 << (2 - lvls)).astype(np.uint64))


def test_scalar_inputs():
    m = Mapping(length=(2, 2, 2), max_refinement_level=1)
    assert int(m.get_refinement_level(np.uint64(1))) == 0
    assert int(m.get_parent(np.uint64(9))) != 0
    assert m.get_all_children(np.uint64(1)).shape == (8,)
    assert m.get_siblings(np.uint64(1)).shape == (8,)


def test_file_roundtrip():
    m = Mapping(length=(3, 4, 5), max_refinement_level=2)
    data = m.to_file_bytes()
    assert len(data) == Mapping.FILE_DATA_SIZE
    m2 = Mapping.from_file_bytes(data)
    assert m2 == m


def test_max_possible_refinement_level():
    # 1x1x1 grid: sum_{l<=21} 8^l = (8^22-1)/7 ~ 1.05e19 fits in uint64,
    # sum_{l<=22} does not -> max possible level is 21 (as in the reference)
    m = Mapping(length=(1, 1, 1))
    assert m.max_possible_refinement_level() == 21
    with pytest.raises(ValueError):
        Mapping(length=(1, 1, 1), max_refinement_level=22)
    # larger grid shrinks the budget
    m2 = Mapping(length=(1000, 1000, 1000))
    assert m2.max_possible_refinement_level() < 12


def test_topology_roundtrip():
    from dccrg_tpu.core import Topology

    t = Topology(periodic=(True, False, True))
    assert t.is_periodic(0) and not t.is_periodic(1) and t.is_periodic(2)
    t2 = Topology.from_file_bytes(t.to_file_bytes())
    assert t2 == t


def test_random_roundtrip_high_refinement_levels():
    """Property test at refinement depths where exhaustive enumeration is
    impossible (level-12 blocks hold ~7e13 ids): random ids round-trip
    through (level, indices) and the parent/child tree stays consistent."""
    m = Mapping(length=(5, 3, 7), max_refinement_level=12)
    rng = np.random.default_rng(1)
    ids = rng.integers(1, int(m.last_cell) + 1, size=20000, dtype=np.uint64)
    lvl = m.get_refinement_level(ids)
    assert (lvl >= 0).all() and (lvl <= 12).all()
    back = m.get_cell_from_indices(m.get_indices(ids), lvl)
    np.testing.assert_array_equal(back, ids)

    refined = ids[lvl > 0]
    parents = m.get_parent(refined)
    assert (m.get_refinement_level(parents) == m.get_refinement_level(refined) - 1).all()
    kids = m.get_all_children(parents)          # (n, 8)
    assert (kids == refined[:, None]).any(axis=1).all()
    # children sit inside the parent's index volume
    pidx = m.get_indices(parents)
    cidx = m.get_indices(refined)
    plen = m.get_cell_length_in_indices(parents)
    assert ((cidx >= pidx) & (cidx < pidx + plen[:, None])).all()
