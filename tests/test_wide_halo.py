"""Exchange-amortized deep dispatch (ISSUE 14): wide-halo cohort bodies
that pay one depth-g exchange per g interior steps.

The contracts under test: a wide-halo dispatch is BIT-IDENTICAL to
exchange-every-step stepping on every owned row at every (g, k) —
including members retiring mid-exchange-block and heterogeneous
same-signature cohorts; hood-0 grids (budget 1) disengage and ride the
unchanged legacy body; occupancy churn at a held (signature, width, k,
g) retraces nothing and changing ONLY g compiles exactly one new body;
``Scheduler.select_k`` clamps scheduled depths to the exchange budget
so a scheduled dispatch pays exactly ONE exchange; the host-side
``halo.exchanges_per_step`` gauge reads ~1/k when wide halos engage;
and the solo ``run()`` donation satellite is env-gated with MEASURED
effectiveness."""
import numpy as np
import pytest

import jax

from dccrg_tpu import CartesianGeometry, Grid, make_mesh, obs
from dccrg_tpu.models import Advection, GameOfLife, Vlasov
from dccrg_tpu.parallel import halo
from dccrg_tpu.parallel.exec_cache import cohort_key
from dccrg_tpu.parallel.wide_halo import get_wide_plan, halo_depth_cap
from dccrg_tpu.serve import Ensemble, Scenario, Scheduler

MOORE = [(i, j, k) for i in (-1, 0, 1) for j in (-1, 0, 1)
         for k in (-1, 0, 1) if (i, j, k) != (0, 0, 0)]
GOL_HOOD = 7


def make_grid(n=6, hood=2, max_ref=0, refine_seed=None):
    g = (
        Grid()
        .set_initial_length((n, n, n))
        .set_neighborhood_length(hood)
        .set_periodic(True, True, True)
        .set_maximum_refinement_level(max_ref)
        .set_geometry(CartesianGeometry, start=(0.0, 0.0, 0.0),
                      level_0_cell_length=(1.0 / n,) * 3)
        .initialize(mesh=make_mesh(n_devices=8))
    )
    if refine_seed is not None:
        rng = np.random.default_rng(refine_seed)
        ids = np.sort(g.get_cells())
        for cid in rng.choice(ids, size=max(1, len(ids) // 6),
                              replace=False):
            g.refine_completely(int(cid))
    g.stop_refining()
    return g


def make_gol(n=6, hood=2):
    g = make_grid(n=n, hood=hood)
    assert g.add_neighborhood(GOL_HOOD, MOORE)
    return g, GameOfLife(g, hood_id=GOL_HOOD, allow_dense=False)


def counter_total(name: str) -> int:
    rep = obs.metrics.report()
    return int(sum(rep["counters"].get(name, {}).values()))


def assert_local_rows_equal(model, solo, got):
    """Byte-compare owned rows (the wide-halo correctness contract);
    ghost replica rows legitimately hold block-stale values."""
    lm = model.batch_step_spec().wide.local_mask
    for name in sorted(solo):
        a, b = np.asarray(solo[name]), np.asarray(got[name])
        if a.shape[:2] == lm.shape:
            a, b = a[lm], b[lm]
        assert a.tobytes() == b.tobytes(), name


# ------------------------------------------------- (g, k) bit-identity


@pytest.mark.parametrize("hood,k", [(2, 1), (2, 2), (2, 4), (3, 3)])
def test_gol_wide_bit_identical_at_g_k(hood, k):
    """Every (ghost depth, dispatch depth) combination serves owned
    rows bit-identical to exchange-every-step solo stepping, with the
    always-on oracle byte-clean."""
    g, gol = make_gol(hood=hood)
    spec = gol.batch_step_spec()
    assert spec.wide is not None and spec.wide.budget >= 2
    rng = np.random.default_rng(11)
    cells = g.get_cells()
    states = [gol.new_state(alive_cells=cells[rng.random(len(cells)) < 0.3])
              for _ in range(3)]
    m0 = counter_total("ensemble.verify_mismatches")
    ens = Ensemble(verify=True, steps_per_dispatch=k)
    tickets = [ens.submit(gol, s, steps=2 * k + 1) for s in states]
    ens.run()
    cohort = next(iter(ens.cohorts.values()))
    assert cohort._wide is not None
    for t, s0 in zip(tickets, states):
        solo = s0
        for _ in range(2 * k + 1):
            solo = gol.step(solo)
        assert_local_rows_equal(gol, solo, t.result)
    assert counter_total("ensemble.verify_mismatches") == m0


@pytest.mark.parametrize("hood,k", [(2, 4), (3, 5)])
def test_advection_wide_bit_identical_at_g_k(hood, k):
    g = make_grid(n=8, hood=hood)
    adv = Advection(g, dtype=np.float64, allow_dense=False)
    spec = adv.batch_step_spec()
    assert spec.wide is not None and spec.wide.budget >= hood
    s0 = adv.initialize_state()
    dt = np.float64(0.4 * adv.max_time_step(s0))
    m0 = counter_total("ensemble.verify_mismatches")
    ens = Ensemble(verify=True, steps_per_dispatch=k)
    t = ens.submit(adv, s0, steps=k + 1, dt=dt)
    ens.run()
    solo = s0
    for _ in range(k + 1):
        solo = adv.step(solo, dt)
    assert_local_rows_equal(adv, solo, t.result)
    assert counter_total("ensemble.verify_mismatches") == m0


def test_vlasov_wide_bit_identical(vl_nv=2):
    g = make_grid(n=6, hood=2)
    vl = Vlasov(g, nv=vl_nv, dtype=np.float32)
    assert vl.info is None, "multi-device grid must take the general path"
    spec = vl.batch_step_spec()
    assert spec.wide is not None and spec.wide.budget >= 2
    s0 = vl.initialize_state()
    dt = np.float32(0.5 * vl.max_time_step())
    m0 = counter_total("ensemble.verify_mismatches")
    ens = Ensemble(verify=True, steps_per_dispatch=4)
    t = ens.submit(vl, s0, steps=5, dt=dt)
    ens.run()
    solo = s0
    for _ in range(5):
        solo = vl.step(solo, dt)
    assert_local_rows_equal(vl, solo, t.result)
    assert counter_total("ensemble.verify_mismatches") == m0


def test_mid_block_retirement_and_direct_deep_step():
    """A direct ``cohort.step(k)`` past the exchange budget runs
    multiple exchange blocks, and a member retiring mid-block stays
    bit-identical to its clamped solo advance."""
    g = make_grid(n=8, hood=2)
    adv = Advection(g, dtype=np.float64, allow_dense=False)
    s0 = adv.initialize_state()
    dt = np.float64(0.4 * adv.max_time_step(s0))
    s1 = jax.tree_util.tree_map(lambda x: np.asarray(x).copy(), s0)
    s1["density"] = s1["density"] * 1.5
    m0 = counter_total("ensemble.verify_mismatches")
    sched = Scheduler(verify=True)
    t5 = sched.submit(Scenario(adv, s0, steps=5, dt=dt))
    t3 = sched.submit(Scenario(adv, s1, steps=3, dt=dt))
    sched.admit()
    cohort = next(iter(sched.cohorts.values()))
    assert cohort._wide is not None and cohort._wide_budget == 2
    served = cohort.step(5)       # ceil(5/2) = 3 exchange blocks
    assert served == 5 + 3
    for slot in cohort.finished_slots():
        sched.completed.append(cohort.retire(int(slot)))
    for t, start, n in ((t5, s0, 5), (t3, s1, 3)):
        solo = start
        for _ in range(n):
            solo = adv.step(solo, dt)
        assert_local_rows_equal(adv, solo, t.result)
    assert counter_total("ensemble.verify_mismatches") == m0


def test_heterogeneous_same_signature_wide_cohort():
    """Two refined grids at one signature with different AMR patterns
    share one wide cohort: admission promotes to the stacked tables,
    the oracle audits each member against ITS OWN local rows, and both
    members retire bit-identical to solo."""
    g1 = make_grid(n=4, hood=2, max_ref=1, refine_seed=1)
    g2 = make_grid(n=4, hood=2, max_ref=1, refine_seed=2)
    a1 = Advection(g1, dtype=np.float64, allow_dense=False)
    a2 = Advection(g2, dtype=np.float64, allow_dense=False)
    assert g1.shape_signature() == g2.shape_signature()
    assert a1.batch_step_spec().wide is not None
    assert a2.batch_step_spec().wide is not None
    s1, s2 = a1.initialize_state(), a2.initialize_state()
    dt = np.float64(0.4 * min(a1.max_time_step(s1), a2.max_time_step(s2)))
    m0 = counter_total("ensemble.verify_mismatches")
    ens = Ensemble(verify=True, steps_per_dispatch=2)
    t1 = ens.submit(a1, s1, steps=4, dt=dt)
    t2 = ens.submit(a2, s2, steps=4, dt=dt)
    ens.run()
    assert len(ens.cohorts) == 1
    cohort = next(iter(ens.cohorts.values()))
    assert cohort._wide is not None
    assert not cohort.shared_args, "different tables must promote"
    for t, a, s0 in ((t1, a1, s1), (t2, a2, s2)):
        solo = s0
        for _ in range(4):
            solo = a.step(solo, dt)
        assert_local_rows_equal(a, solo, t.result)
    assert counter_total("ensemble.verify_mismatches") == m0


# ---------------------------------------------------- (dis)engagement


def test_hood0_grids_disengage():
    """The pre-ISSUE-14 fleet: hood-0 grids have a budget of 1 (one
    exchange funds one step — the legacy body), so no wide spec ships
    and the cohort runs the unchanged per-step path."""
    g = make_grid(hood=0)
    gol = GameOfLife(g, allow_dense=False)
    assert gol.batch_step_spec().wide is None
    cells = g.get_cells()
    s0 = gol.new_state(alive_cells=cells[::3])
    ens = Ensemble(steps_per_dispatch=4)
    ens.submit(gol, s0, steps=4)
    ens.run()
    cohort = next(iter(ens.cohorts.values()))
    assert cohort._wide is None and cohort._wide_g(4) == 0


def test_env_gate_disables_wide(monkeypatch):
    monkeypatch.setenv("DCCRG_ENSEMBLE_WIDE", "0")
    _, gol = make_gol()
    assert gol.batch_step_spec().wide is None


# ----------------------------------------------- compile accounting


def test_zero_retrace_churn_at_held_sig_width_k_g():
    g, gol = make_gol(hood=2)
    rng = np.random.default_rng(3)
    cells = g.get_cells()
    states = [gol.new_state(alive_cells=cells[rng.random(len(cells)) < 0.3])
              for _ in range(12)]
    ens = Ensemble(steps_per_dispatch=2)
    for s in states[:4]:
        ens.submit(gol, s, steps=4)
    ens.run()                             # warm the (W=4, k=2, g=2) body
    before = counter_total("epoch.recompiles")
    for wave in (states[4:8], states[8:10], states[10:12]):
        for i, s in enumerate(wave):
            ens.submit(gol, s, steps=2 * (i + 1))
        ens.run()
    assert counter_total("epoch.recompiles") == before, (
        "churn at a held (signature, width, k, g) must not retrace")
    assert len(ens.completed) == 12


def test_changing_only_g_compiles_exactly_one_body(monkeypatch):
    g = make_grid(n=8, hood=3)
    adv = Advection(g, dtype=np.float64, allow_dense=False)
    spec = adv.batch_step_spec()
    assert spec.wide is not None and spec.wide.budget >= 3
    s0 = adv.initialize_state()
    dt = np.float64(0.4 * adv.max_time_step(s0))
    sched = Scheduler()
    sched.submit(Scenario(adv, s0, steps=64, dt=dt))
    sched.admit()
    cohort = next(iter(sched.cohorts.values()))
    cohort.step(3)                        # warm (k=3, g=3)
    before = counter_total("epoch.recompiles")
    cohort.step(3)                        # held (k, g): re-dispatch
    assert counter_total("epoch.recompiles") == before
    monkeypatch.setenv("DCCRG_HALO_DEPTH", "2")
    assert halo_depth_cap() == 2
    cohort.step(3)                        # same k, g drops to 2: ONE body
    assert counter_total("epoch.recompiles") == before + 1
    monkeypatch.delenv("DCCRG_HALO_DEPTH")
    cohort.step(3)                        # g=3 body still cached
    assert counter_total("epoch.recompiles") == before + 1
    # the cache key really carries g
    assert (cohort_key(spec, cohort.W, 3, wide_g=3)
            != cohort_key(spec, cohort.W, 3, wide_g=2))


# --------------------------------------------------------- scheduling


def test_select_k_clamps_to_exchange_budget():
    """A scheduled wide dispatch pays exactly ONE exchange: select_k
    clamps the configured depth to the cohort's member-min budget."""
    g, gol = make_gol(hood=2)             # budget 2
    cells = g.get_cells()
    s0 = gol.new_state(alive_cells=cells[::2])
    sched = Scheduler(steps_per_dispatch=16)
    sched.submit(Scenario(gol, s0, steps=64))
    sched.admit()
    cohort = next(iter(sched.cohorts.values()))
    assert cohort._wide is not None and cohort._wide_budget == 2
    assert sched.select_k(cohort) == 2
    # remaining-budget clamp still applies on top
    cohort._remaining[:] = np.where(cohort._occupied, 1, 0)
    assert sched.select_k(cohort) == 1


# ---------------------------------------------------------- telemetry


def test_exchanges_per_step_gauge_drops_to_one_over_k():
    halo._amortization.clear()
    g, gol = make_gol(hood=2)
    cells = g.get_cells()
    s0 = gol.new_state(alive_cells=cells[::2])
    sched = Scheduler()
    sched.submit(Scenario(gol, s0, steps=64))
    sched.admit()
    cohort = next(iter(sched.cohorts.values()))
    cohort.step(2)                        # wide: 1 exchange / 2 steps
    rep = obs.metrics.report()
    assert rep["gauges"]["halo.exchanges_per_step"]["model=gol"] == 0.5
    cohort.step(4)                        # 2 exchanges / 4 steps
    rep = obs.metrics.report()
    assert rep["gauges"]["halo.exchanges_per_step"]["model=gol"] == 0.5
    halo._amortization.clear()
    halo.record_dispatch_exchanges("gol", 4, 4)   # legacy body: 1.0
    rep = obs.metrics.report()
    assert rep["gauges"]["halo.exchanges_per_step"]["model=gol"] == 1.0


# ----------------------------------------------------- run() donation


def test_run_donation_env_gated_and_measured(monkeypatch):
    """DCCRG_RUN_DONATE=1 donates the solo ``run()`` state with
    MEASURED effectiveness (the ``is_deleted`` probe feeding
    ``run.donate_effective``); default off, because solo callers may
    legitimately reuse their input state."""
    from dccrg_tpu.parallel.exec_cache import run_donate_enabled

    monkeypatch.delenv("DCCRG_RUN_DONATE", raising=False)
    assert run_donate_enabled() is False
    monkeypatch.setenv("DCCRG_RUN_DONATE", "1")
    assert run_donate_enabled() is True

    g = make_grid(hood=0)
    adv = Advection(g, dtype=np.float64, allow_dense=False)
    s0 = adv.initialize_state()
    dt = np.float64(0.4 * adv.max_time_step(s0))
    # a donated input buffer must never be read after the call:
    # snapshot the state the solo replay starts from
    s0_copy = jax.tree_util.tree_map(lambda x: np.asarray(x).copy(), s0)
    out = adv.run(s0, 3, dt)
    solo = s0_copy
    for _ in range(3):
        solo = adv.step(solo, dt)
    np.testing.assert_array_equal(np.asarray(solo["density"]),
                                  np.asarray(out["density"]))
    rep = obs.metrics.report()
    assert "model=advection" in rep["gauges"].get("run.donate_effective",
                                                  {})

    g2 = make_grid(hood=0)
    vl = Vlasov(g2, nv=2, dtype=np.float32)
    sv = vl.initialize_state()
    dtv = np.float32(0.5 * vl.max_time_step())
    sv_copy = jax.tree_util.tree_map(lambda x: np.asarray(x).copy(), sv)
    out2 = vl.run(sv, 3, dtv)
    solo = sv_copy
    for _ in range(3):
        solo = vl.step(solo, dtv)
    np.testing.assert_array_equal(np.asarray(solo["f"]),
                                  np.asarray(out2["f"]))
    rep = obs.metrics.report()
    assert "model=vlasov" in rep["gauges"].get("run.donate_effective", {})


# --------------------------------------------------------- wide plans


def test_wide_plan_budget_matches_hood_depth():
    """A depth-g default hood funds g face-stencil steps; the Moore
    sub-hood (whole-neighborhood relevance) funds g radius-1 steps."""
    g = make_grid(n=8, hood=2)
    assert get_wide_plan(g, None, relevance="face").budget == 2
    g2, gol = make_gol(n=8, hood=2)
    assert get_wide_plan(g2, GOL_HOOD, relevance="all").budget == 2
