"""Native C++ neighbor kernel vs the numpy reference implementation."""
import numpy as np
import pytest

from dccrg_tpu.core import Mapping, Topology
from dccrg_tpu.core.neighborhood import default_neighborhood
from dccrg_tpu.core.neighbors import LeafSet, find_all_neighbors
from dccrg_tpu.native import native_available, native_find_neighbors

from test_neighbors import make_leafset

pytestmark = pytest.mark.skipif(
    not native_available(), reason="native kernels not built"
)


@pytest.mark.parametrize("periodic", [(False,) * 3, (True, False, True)])
@pytest.mark.parametrize("hood_len", [0, 1, 2])
@pytest.mark.parametrize("refine", [[], [14], [1, 14, 27]])
def test_native_matches_numpy(periodic, hood_len, refine):
    m = Mapping(length=(3, 3, 3), max_refinement_level=2)
    t = Topology(periodic=periodic)
    leaves = make_leafset(m, refine_cells=refine)
    hood = default_neighborhood(hood_len)

    nat = native_find_neighbors(m, t, leaves.cells, hood, leaves.cells, True)
    assert nat is not None
    start, nbr_cell, nbr_pos, offset, slot = nat

    import os

    os.environ["DCCRG_TPU_NATIVE"] = "0"
    try:
        import dccrg_tpu.native as native_mod

        native_mod._tried, native_mod._lib = True, None
        ref = find_all_neighbors(m, t, leaves, hood)
    finally:
        del os.environ["DCCRG_TPU_NATIVE"]
        native_mod._tried = False

    np.testing.assert_array_equal(start, ref.start)
    np.testing.assert_array_equal(nbr_cell, ref.nbr_cell)
    np.testing.assert_array_equal(nbr_pos, ref.nbr_pos)
    np.testing.assert_array_equal(offset, ref.offset)
    np.testing.assert_array_equal(slot, ref.slot)


def test_native_strict_error():
    m = Mapping(length=(2, 1, 1), max_refinement_level=2)
    t = Topology()
    # broken leaf set: cell 1 missing entirely
    leaves = LeafSet(
        cells=np.array([2], dtype=np.uint64), owner=np.zeros(1, dtype=np.int32)
    )
    with pytest.raises(RuntimeError, match="no neighbor leaf|not an existing leaf"):
        find_all_neighbors(m, t, leaves, default_neighborhood(0))


@pytest.mark.parametrize("periodic", [(True, False, True), (True, True, True)])
def test_native_epoch_matches_numpy(periodic):
    """The fused C++ epoch pass (hood_invert_and_pairs + hood_fill_tables
    + uniform-grid position fast path) builds a bit-identical HoodState to
    the pure-numpy reference path, on a refined multi-device grid."""
    import os

    import numpy as np

    from dccrg_tpu import CartesianGeometry, Grid, make_mesh

    def build():
        n = 12
        g = (
            Grid()
            .set_initial_length((n, n, n))
            .set_neighborhood_length(1)
            .set_periodic(*periodic)
            .set_maximum_refinement_level(1)
            .set_geometry(
                CartesianGeometry,
                start=(0.0, 0.0, 0.0),
                level_0_cell_length=(1.0 / n,) * 3,
            )
            .initialize(mesh=make_mesh(n_devices=4))
        )
        ids = g.get_cells()
        c = g.geometry.get_center(ids)
        r = np.linalg.norm(c - 0.5, axis=1)
        for cid in ids[r < 0.25]:
            g.refine_completely(int(cid))
        g.stop_refining()
        return g

    import dccrg_tpu.native as native_mod

    g_nat = build()
    os.environ["DCCRG_TPU_NATIVE"] = "0"
    try:
        native_mod._tried, native_mod._lib = True, None
        g_ref = build()
    finally:
        del os.environ["DCCRG_TPU_NATIVE"]
        native_mod._tried = False

    h_nat = g_nat.epoch.hoods[None]
    h_ref = g_ref.epoch.hoods[None]
    for f in (
        "to_start", "to_src", "send_rows", "recv_rows", "pair_counts",
        "inner_mask", "outer_mask", "nbr_rows", "nbr_valid", "nbr_offset",
        "nbr_len", "nbr_slot",
    ):
        np.testing.assert_array_equal(
            getattr(h_nat, f), getattr(h_ref, f), err_msg=f
        )


def test_native_sort_unique_matches_numpy():
    from dccrg_tpu.native import native_available, native_sort_unique_u64

    rng = np.random.default_rng(7)
    keys = rng.integers(0, 1 << 48, size=100_000, dtype=np.uint64)
    keys = np.concatenate([keys, keys[:5000]])  # force duplicates
    want = np.unique(keys)
    if native_available():
        got = native_sort_unique_u64(keys.copy())
        np.testing.assert_array_equal(got, want)


def test_setops_helpers():
    from dccrg_tpu.utils.setops import counts_to_start, csr_take, unique_pairs

    a = np.array([3, 1, 3, 1, 0, 3])
    b = np.array([2, 0, 2, 5, 1, 0])
    ua, ub = unique_pairs(a, b, 8)
    want = np.unique(np.stack([a, b], axis=1), axis=0)
    np.testing.assert_array_equal(np.stack([ua, ub], axis=1), want)

    start = counts_to_start(np.array([0, 0, 2, 2, 2]), 4)
    np.testing.assert_array_equal(start, [0, 2, 2, 5, 5])

    data = np.arange(10) * 10
    start = np.array([0, 3, 3, 7, 10])
    got = csr_take(start, data, np.array([2, 0, 3]))
    np.testing.assert_array_equal(got, [30, 40, 50, 60, 0, 10, 20, 70, 80, 90])
