"""Test configuration: force an 8-device virtual CPU mesh so multi-device
sharding paths run on any host, mirroring the reference's
"mpiexec -n N on localhost" testing model (reference tests/README:5-7).

The benchmark (bench.py) runs on the real TPU; tests always run on the
virtual CPU mesh for device-count-invariant assertions.  jax may already be
imported by a pytest plugin, so the platform is set via jax.config (backends
initialize lazily) rather than environment variables.
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    # older jax: the XLA_FLAGS fallback above is the only control; it was
    # set before any backend initialized, so the 8-device mesh still forms
    pass
# the reference is double-precision throughout; tests assert in f64
jax.config.update("jax_enable_x64", True)
