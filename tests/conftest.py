"""Test configuration: force an 8-device virtual CPU mesh so multi-device
sharding paths run on any host, mirroring the reference's
"mpiexec -n N on localhost" testing model (reference tests/README:5-7)."""
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
