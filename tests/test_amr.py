"""AMR pipeline tests (reference analogues: tests/refine, the 2:1 balance
DEBUG invariants, and the adapter's refine/unrefine interplay)."""
import numpy as np
import pytest

from dccrg_tpu import Grid, make_mesh


def make_grid(length=(4, 4, 4), max_ref=2, hood=1, periodic=(False,) * 3, n_dev=None):
    return (
        Grid()
        .set_initial_length(length)
        .set_maximum_refinement_level(max_ref)
        .set_neighborhood_length(hood)
        .set_periodic(*periodic)
        .initialize(mesh=make_mesh(n_devices=n_dev))
    )


def check_two_to_one(grid):
    """No neighbor pair differs by more than one refinement level; also the
    epoch rebuild runs the strict neighbor search, so reaching here means
    every slot resolved."""
    h = grid.epoch.hoods[None]
    lvl = grid.mapping.get_refinement_level(grid.leaves.cells)
    src = np.repeat(np.arange(len(lvl)), np.diff(h.lists.start))
    diff = np.abs(lvl[src] - lvl[h.lists.nbr_pos])
    assert diff.max() <= 1 if len(diff) else True


def test_refine_one_cell():
    g = make_grid()
    n0 = len(g.get_cells())
    assert g.refine_completely(1)
    new_cells = g.stop_refining()
    assert len(new_cells) == 8
    np.testing.assert_array_equal(
        new_cells, g.mapping.get_all_children(np.uint64(1))
    )
    cells = g.get_cells()
    assert len(cells) == n0 - 1 + 8
    assert 1 not in cells
    check_two_to_one(g)
    # children live on the refined cell's device
    assert (g.get_owner(new_cells) == 0).all()


def test_refine_induces_2to1_balance():
    g = make_grid(length=(8, 1, 1), max_ref=2, hood=1)
    # refine cell 1 twice: second round must induce refinement of neighbors
    g.refine_completely(1)
    g.stop_refining()
    check_two_to_one(g)
    child = int(g.mapping.get_all_children(np.uint64(1))[0])
    g.refine_completely(child)
    new_cells = g.stop_refining()
    check_two_to_one(g)
    # cell 2 (level-0 neighbor of cell 1's children) must have been refined
    assert 2 not in g.get_cells()
    assert len(new_cells) > 8


def test_dont_refine_veto():
    g = make_grid()
    g.refine_completely(1)
    g.dont_refine(1)
    new_cells = g.stop_refining()
    assert len(new_cells) == 0
    assert 1 in g.get_cells()


def test_dont_refine_propagates_to_finer():
    """A veto on a coarse cell also vetoes finer neighbors whose refinement
    would force the vetoed cell to refine (override_refines fixed point)."""
    g = make_grid(length=(8, 1, 1), max_ref=2, hood=1)
    g.refine_completely(1)
    g.stop_refining()
    child = int(g.mapping.get_all_children(np.uint64(1))[0])
    # cell 2 is a coarser neighbor of cell 1's children; vetoing cell 2 and
    # refining a child of 1 would need 2 to refine -> child refine cancelled
    g.dont_refine(2)
    g.refine_completely(child)
    new_cells = g.stop_refining()
    assert len(new_cells) == 0
    assert child in g.get_cells()


def test_unrefine_roundtrip():
    g = make_grid()
    n0 = len(g.get_cells())
    g.refine_completely(5)
    children = g.stop_refining()
    assert g.unrefine_completely(int(children[0]))
    g.stop_refining()
    removed = g.get_removed_cells()
    np.testing.assert_array_equal(np.sort(removed), np.sort(children))
    assert len(g.get_cells()) == n0
    assert 5 in g.get_cells()
    check_two_to_one(g)


def test_dont_unrefine_veto():
    """dont_unrefine cancels a pending family unrefine and blocks later
    requests for any sibling (dccrg.hpp:2679-2784 semantics)."""
    g = make_grid()
    g.refine_completely(5)
    children = g.stop_refining()
    # veto recorded after the request: cancels it
    g.unrefine_completely(int(children[0]))
    assert g.dont_unrefine(int(children[1]))
    g.stop_refining()
    assert len(g.get_removed_cells()) == 0
    assert set(children.tolist()) <= set(g.get_cells().tolist())
    # veto recorded before the request: request becomes a no-op
    g.dont_unrefine(int(children[2]))
    g.unrefine_completely(int(children[3]))
    g.stop_refining()
    assert len(g.get_removed_cells()) == 0
    assert set(children.tolist()) <= set(g.get_cells().tolist())
    # level-0 cells can never unrefine: dont_unrefine is a trivial success
    assert g.dont_unrefine(2)
    # unknown cell: refused
    assert not g.dont_unrefine(10**9)


def test_dont_unrefine_at_coordinates():
    g = make_grid()
    g.refine_completely(1)
    children = g.stop_refining()
    center = g.geometry.get_center(children[:1])[0]
    assert g.dont_unrefine_at(center)
    g.unrefine_completely(int(children[0]))
    g.stop_refining()
    assert len(g.get_removed_cells()) == 0


def test_unrefine_blocked_by_sibling_refine():
    g = make_grid()
    g.refine_completely(5)
    children = g.stop_refining()
    g.refine_completely(int(children[1]))
    g.unrefine_completely(int(children[0]))  # same family: no-op
    g.stop_refining()
    assert 5 not in g.get_cells()
    assert int(children[1]) not in g.get_cells()  # it was refined
    check_two_to_one(g)


def test_unrefine_blocked_by_finer_neighbor():
    g = make_grid(length=(8, 1, 1), max_ref=2, hood=1)
    g.refine_completely(1)
    g.stop_refining()
    child = int(g.mapping.get_all_children(np.uint64(1))[0])
    g.refine_completely(child)
    g.stop_refining()  # induces refinement of cell 2 as well
    check_two_to_one(g)
    # the family of cell 1's children now has grandchildren next to it;
    # unrefining the other children of 1 would put a level-0... actually
    # request unrefine of a child of 2's family whose neighbor is 2 levels
    # finer - must be cancelled or refused
    cells = g.get_cells()
    lvl = g.mapping.get_refinement_level(cells)
    n_before = len(cells)
    for c in cells[lvl == 1]:
        g.unrefine_completely(int(c))
    g.stop_refining()
    check_two_to_one(g)


def test_remap_state_policies():
    g = make_grid(length=(2, 2, 1), max_ref=1, hood=1)
    state = g.new_state({"rho": ((), np.float64), "cnt": ((), np.int32)})
    cells = g.get_cells()
    state = g.set_cell_data(state, "rho", cells, np.array([1.0, 2.0, 3.0, 4.0]))
    state = g.set_cell_data(state, "cnt", cells, np.arange(4, dtype=np.int32))

    g.refine_completely(1)
    children = g.stop_refining()
    state = g.remap_state(state)
    # children inherit parent's value; survivors keep theirs
    np.testing.assert_array_equal(
        g.get_cell_data(state, "rho", children), np.ones(8)
    )
    np.testing.assert_array_equal(
        g.get_cell_data(state, "rho", np.array([2, 3, 4], dtype=np.uint64)),
        [2.0, 3.0, 4.0],
    )

    # modify children then unrefine: parent = mean
    state = g.set_cell_data(state, "rho", children, np.arange(8, dtype=np.float64))
    g.unrefine_completely(int(children[0]))
    g.stop_refining()
    state = g.remap_state(state, policy={"rho": {"unrefine": "mean"}})
    assert float(g.get_cell_data(state, "rho", np.array([1], np.uint64))[0]) == pytest.approx(3.5)


def test_device_count_invariant_structure():
    """The committed structure must not depend on the device count."""
    results = []
    for n_dev in (1, 8):
        g = make_grid(length=(4, 4, 1), max_ref=2, n_dev=n_dev)
        g.refine_completely(1)
        g.refine_completely(6)
        g.stop_refining()
        g.refine_completely(int(g.mapping.get_all_children(np.uint64(1))[0]))
        g.stop_refining()
        results.append(g.get_cells())
    np.testing.assert_array_equal(results[0], results[1])


def test_refine_at_coordinates():
    g = (
        Grid()
        .set_initial_length((4, 4, 1))
        .set_maximum_refinement_level(1)
        .set_geometry(None, start=(0.0, 0.0, 0.0), level_0_cell_length=(0.25, 0.25, 1.0))
        .initialize(mesh=make_mesh())
    )
    assert g.refine_completely_at((0.1, 0.1, 0.5))
    new_cells = g.stop_refining()
    assert len(new_cells) == 8
    assert 1 not in g.get_cells()


@pytest.mark.parametrize("seed", [0, 7, 29, 42])
@pytest.mark.parametrize("pending", [False, True])
def test_bulk_requests_match_scalar(seed, pending):
    """The vectorized bulk request APIs (refine/unrefine/dont_* _many)
    produce the identical final queue state and per-cell returns as the
    scalar per-cell calls in order — including pre-seeded queues and
    vetoes (where some bulk forms fall back to the scalar loop) and the
    scalar loop's per-sibling check ordering."""
    from dccrg_tpu import CartesianGeometry

    def build():
        rng = np.random.default_rng(seed)
        n = 6
        g = (
            Grid()
            .set_initial_length((n, n, n))
            .set_neighborhood_length(0)
            .set_periodic(*[bool(b) for b in rng.integers(0, 2, 3)])
            .set_maximum_refinement_level(2)
            .set_geometry(
                CartesianGeometry,
                start=(0.0, 0.0, 0.0),
                level_0_cell_length=(1.0 / n,) * 3,
            )
            .initialize(mesh=make_mesh(n_devices=int(rng.choice([1, 2, 4]))))
        )
        for frac in (0.4, 0.15):
            ids = g.get_cells()
            for cid in rng.choice(ids, size=max(1, int(frac * len(ids))),
                                  replace=False):
                g.refine_completely(int(cid))
            g.stop_refining()
        return g, rng

    def snap(g):
        return (frozenset(g.amr.to_refine), frozenset(g.amr.to_unrefine),
                frozenset(g.amr.not_to_refine),
                frozenset(g.amr.not_to_unrefine))

    for api, many in (
        ("refine_completely", "refine_completely_many"),
        ("unrefine_completely", "unrefine_completely_many"),
        ("dont_unrefine", "dont_unrefine_many"),
        ("dont_refine", "dont_refine_many"),
    ):
        g1, rng1 = build()
        g2, _ = build()
        if pending:
            ids = g1.get_cells()
            for c in rng1.choice(ids, size=5, replace=False):
                g1.refine_completely(int(c))
            for c in rng1.choice(ids, size=5, replace=False):
                g1.dont_unrefine(int(c))
            for c in rng1.choice(ids, size=3, replace=False):
                g1.dont_refine(int(c))
        g2.amr.to_refine = set(g1.amr.to_refine)
        g2.amr.to_unrefine = set(g1.amr.to_unrefine)
        g2.amr.not_to_refine = set(g1.amr.not_to_refine)
        g2.amr.not_to_unrefine = set(g1.amr.not_to_unrefine)
        ids = g1.get_cells()
        storm = rng1.choice(ids, size=min(len(ids), 120), replace=True)
        storm = np.concatenate([storm, [np.uint64(999999999)]])
        rs = np.array([getattr(g1, api)(int(c)) for c in storm])
        rb = getattr(g2, many)(storm)
        np.testing.assert_array_equal(rs, rb, err_msg=api)
        assert snap(g1) == snap(g2), api
